"""Headline benchmark: effective gradient-exchange speedup vs dense.

North star (BASELINE.md): ResNet-50 + topk(1%) + bloom-index on TPU,
>= 3x the effective gradient-exchange bandwidth of the dense baseline.

On a single chip the collective itself can't be timed, so the bench measures
what the codec controls — bytes on the wire and codec wall time — and folds
them through the bandwidth model the paper itself uses for its simulated-FL
numbers (Table 4):

    T_dense      = dense_bytes / BW
    T_compressed = payload_bytes / BW + t_encode + t_decode
    speedup      = T_dense / T_compressed

with BW = 1.25e10 B/s — the reference's own 100 Gbps cluster network
(paper App. F.1), i.e. the cross-host regime where gradient compression
pays (the paper's other regimes are 100 Mbps FL links; intra-pod ICI is so
fast that no codec can win there, which is also true of NCCL on NVLink).
The gradient is the full 25.6M-element ResNet-50 gradient vector; config =
the paper's headline DeepReduce-both: topk 1% + bloom (fpr 1e-3, leftmost)
+ polyfit values.

Timing note: axon's `block_until_ready` returns before execution completes,
so synchronization is done by reading one scalar of the output back to host.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is speedup / 3.0 (>= 1.0 means the >=3x target is met).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

NETWORK_BANDWIDTH = 1.25e10  # bytes/s = 100 Gbps, the reference's cluster net
TARGET_SPEEDUP = 3.0  # BASELINE.md north star


def main() -> None:
    quick = "--quick" in sys.argv

    import jax
    import jax.numpy as jnp

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    d = 1_000_000 if quick else 25_557_032  # ResNet-50 param count (BASELINE.md)
    cfg = DeepReduceConfig(
        compressor="topk",
        compress_ratio=0.01,
        deepreduce="both",
        index="bloom",
        value="polyfit",
        fpr=0.001,
        policy="leftmost",
    )
    codec = TensorCodec((d,), cfg, name="resnet50_grad")

    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32) * (rng.random(d) ** 4))
    key = jax.random.PRNGKey(0)

    encode = jax.jit(lambda t, s: codec.encode(t, step=s, key=key))
    decode = jax.jit(lambda p, s: codec.decode(p, step=s))

    def sync(out):
        """Force completion: axon's block_until_ready is a no-op, so read one
        scalar of every output leaf's first element back to host."""
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf.reshape(-1)[0])
        return out

    payload = sync(encode(g, 0))
    sync(decode(payload, 0))

    def timeit(fn, *args, iters=3 if quick else 10):
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            sync(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    t_enc = timeit(encode, g, 1)
    t_dec = timeit(decode, payload, 1)

    stats = codec.wire_stats(payload)
    payload_bytes = float(stats.total_bits) / 8.0
    dense_bytes = d * 4.0

    t_dense = dense_bytes / NETWORK_BANDWIDTH
    t_comp = payload_bytes / NETWORK_BANDWIDTH + t_enc + t_dec
    speedup = t_dense / t_comp

    result = {
        "metric": "resnet50_grad_exchange_effective_speedup_vs_dense",
        "value": round(speedup, 4),
        "unit": "x",
        "vs_baseline": round(speedup / TARGET_SPEEDUP, 4),
        "detail": {
            "d": d,
            "k": codec.k,
            "rel_volume": round(float(stats.rel_volume()), 6),
            "idx_rel_volume": round(float(stats.idx_rel_volume()), 6),
            "val_rel_volume": round(float(stats.val_rel_volume()), 6),
            "t_encode_s": round(t_enc, 5),
            "t_decode_s": round(t_dec, 5),
            "network_bandwidth_Bps": NETWORK_BANDWIDTH,
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
