"""Headline benchmark: the reference's own end-to-end Table-4 experiment.

The paper's headline efficiency claim (BASELINE.md, paper Table 4): on the
StackOverflow LSTM (4.05M params) over a 100 Mbps link, DRQSGD-BF-P0's
end-to-end gradient exchange is **7.8x faster than the dense baseline**
(and 2.2x faster than Top-r). This bench reproduces that experiment's
arithmetic with our codecs running on real TPU silicon:

    T(config) = payload_bytes / BW + t_encode + t_decode      (per worker)
    speedup   = T(dense) / T(config),    BW = 12.5 MB/s (100 Mbps)

Configs measured:
  - dense           — no compression (payload = 4d bytes, no codec)
  - topr            — Top-r 10% raw sparse (the paper's Top-r column)
  - drqsgd_delta    — topk 10% + delta-bitpack indices + QSGD values
                      (our best: the FastPFor-role codec, O(k) both sides)
  - drqsgd_bloom    — topk 10% + blocked-bloom indices (P0) + QSGD values
                      (the paper's DRQSGD-BF-P0 shape)

Headline value = speedup(best config) vs dense; vs_baseline divides by the
paper's 7.8x, so vs_baseline >= 1.0 means beating the reference's own
number. ResNet-50-scale (25.6M) timings ride in `detail`.

Timing note: axon's `block_until_ready` returns before execution completes,
so synchronization reads one scalar of an output leaf back to host; the
~50-70ms axon dispatch overhead is measured and subtracted via a no-op
baseline.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BW_100MBPS = 12.5e6  # bytes/s
PAPER_E2E_SPEEDUP = 7.8  # DRQSGD-BF-P0 vs baseline, paper Table 4
LSTM_D = 4_053_428  # StackOverflow LSTM param count (BASELINE.md)
RESNET50_D = 25_557_032


def _progress(msg: str) -> None:
    """Stage progress to stderr (stdout stays the single JSON line)."""
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def _sync(x):
    import jax

    for leaf in jax.tree_util.tree_leaves(x):
        if getattr(leaf, "size", 0):
            np.asarray(leaf.reshape(-1)[0])
            return x
    return x


def _timeit(fn, *args, iters=5):
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def measure_config(d, ratio, cfg_kwargs, overhead, iters):
    import jax
    import jax.numpy as jnp

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=ratio, approx_topk=True, **cfg_kwargs
    )
    codec = TensorCodec((d,), cfg, name="bench")
    rng = np.random.default_rng(0)
    g = jnp.asarray((rng.normal(size=d) * rng.random(d) ** 2).astype(np.float32))
    key = jax.random.PRNGKey(0)
    encode = jax.jit(lambda t, s: codec.encode(t, step=s, key=key))
    decode = jax.jit(lambda p, s: codec.decode(p, step=s))
    _progress(f"d={d} {cfg_kwargs.get('index') or 'topr'}: compiling encode")
    payload = _sync(encode(g, 0))
    _progress(f"d={d}: compiling decode")
    _sync(decode(payload, 0))
    _progress(f"d={d}: timing ({iters} iters)")
    t_enc = max(_timeit(encode, g, 1, iters=iters) - overhead, 0.0)
    t_dec = max(_timeit(decode, payload, 1, iters=iters) - overhead, 0.0)
    _progress(f"d={d}: done enc={t_enc:.4f}s dec={t_dec:.4f}s")
    stats = codec.wire_stats(payload)
    return {
        "payload_bytes": float(stats.total_bits) / 8.0,
        "rel_volume": float(stats.rel_volume()),
        "t_encode_s": t_enc,
        "t_decode_s": t_dec,
    }


def exchange_time(m, bw):
    return m["payload_bytes"] / bw + m["t_encode_s"] + m["t_decode_s"]


def _tpu_alive(timeout_s: float = 180.0) -> bool:
    """True if a trivial device round-trip completes within `timeout_s`,
    probed in a SUBPROCESS so a wedged axon tunnel (connection hang inside
    jax.devices()) can't poison this process's jax backend state."""
    import subprocess

    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "v = jax.jit(lambda t: t * 2.0)(jnp.zeros((8,), jnp.float32));"
        "np.asarray(v[:1])"
    )
    try:
        return subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _resnet50_images_per_sec(overhead: float, batch: int = 32) -> dict:
    """Full training-step throughput, dense vs topk-1%-compressed, on the
    single available chip (mesh of 1; the codec + exchange cost is real,
    the collective degenerates)."""
    import jax
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.models import ResNet50
    from deepreduce_tpu.train import Trainer

    rng = np.random.default_rng(0)
    images = rng.normal(size=(batch, 224, 224, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, batch).astype(np.int32)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    out = {}
    for name, cfg in {
        "dense": DeepReduceConfig(
            compressor="none", deepreduce=None, memory="none", communicator="allreduce"
        ),
        "topk1_bloom": DeepReduceConfig(
            compressor="topk", compress_ratio=0.01, approx_topk=True,
            memory="residual", deepreduce="index", index="bloom",
            fpr=0.001, bloom_blocked=True,
        ),
    }.items():
        _progress(f"resnet50 {name}: compiling step")
        trainer = Trainer(ResNet50(num_classes=1000), cfg, optax.sgd(0.1), mesh)
        state = trainer.init_state(jax.random.PRNGKey(0), (images, labels))
        step = lambda s, i: trainer.step(s, (images, labels), jax.random.PRNGKey(i))
        state, _, _ = step(state, 0)
        _sync(state.params)
        best = float("inf")
        for i in range(3):
            t0 = time.perf_counter()
            state, loss, _ = step(state, i + 1)
            _sync(state.params)
            best = min(best, time.perf_counter() - t0)
        out[name] = round(batch / max(best - overhead, 1e-9), 2)
        _progress(f"resnet50 {name}: {out[name]} img/s")
    out["compression_overhead_pct"] = round(
        100.0 * (out["dense"] / max(out["topk1_bloom"], 1e-9) - 1.0), 1
    )
    return out


def main() -> None:
    quick = "--quick" in sys.argv
    iters = 3 if quick else 7

    degraded = not _tpu_alive()
    if degraded:
        _progress("device backend unresponsive after 180s; benching on CPU fallback")
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")

    import jax
    import jax.numpy as jnp

    d = LSTM_D if not quick else 500_000
    ratio = 0.10  # the paper's Top-r 10% LSTM setting (Table 2)

    # dispatch overhead: a trivial jitted op, same sync path
    probe = jax.jit(lambda v: v[:8] * 2.0)
    z = jnp.zeros((1024,), jnp.float32)
    _sync(probe(z))
    overhead = _timeit(probe, z, iters=iters)

    configs = {
        "topr": dict(deepreduce=None, memory="none"),
        "drqsgd_delta": dict(
            deepreduce="both", index="integer", value="qsgd", policy="p0", memory="none"
        ),
        "drqsgd_bloom": dict(
            deepreduce="both",
            index="bloom",
            value="qsgd",
            policy="p0",
            fpr=0.02,
            bloom_blocked=True,
            memory="none",
        ),
    }
    measured = {
        name: measure_config(d, ratio, kw, overhead, iters) for name, kw in configs.items()
    }
    dense = {"payload_bytes": 4.0 * d, "rel_volume": 1.0, "t_encode_s": 0.0, "t_decode_s": 0.0}

    t_dense = exchange_time(dense, BW_100MBPS)
    speedups = {n: t_dense / exchange_time(m, BW_100MBPS) for n, m in measured.items()}
    best_name = max(speedups, key=speedups.get)
    best = speedups[best_name]

    detail = {
        "model": "stackoverflow_lstm" if not quick else "quick",
        "d": d,
        "ratio": ratio,
        "bw_bytes_per_s": BW_100MBPS,
        "t_dense_s": round(t_dense, 4),
        "dispatch_overhead_s": round(overhead, 4),
        "best_config": best_name,
        "speedup_vs_topr": round(
            exchange_time(measured["topr"], BW_100MBPS)
            / exchange_time(measured[best_name], BW_100MBPS),
            3,
        ),
        "platform": jax.devices()[0].platform,
        "degraded_to_cpu": degraded,  # true = probe failed, NOT a TPU result
        "configs": {
            n: {
                "rel_volume": round(m["rel_volume"], 5),
                "t_encode_s": round(m["t_encode_s"], 4),
                "t_decode_s": round(m["t_decode_s"], 4),
                "e2e_speedup_vs_dense": round(speedups[n], 3),
            }
            for n, m in measured.items()
        },
    }
    if not quick:
        # ResNet-50-scale codec timings (the BASELINE.json north-star size)
        r50 = measure_config(
            RESNET50_D,
            0.01,
            dict(deepreduce="both", index="integer", value="qsgd", policy="p0", memory="none"),
            overhead,
            3,
        )
        detail["resnet50_drqsgd_delta"] = {
            "rel_volume": round(r50["rel_volume"], 5),
            "t_encode_s": round(r50["t_encode_s"], 4),
            "t_decode_s": round(r50["t_decode_s"], 4),
            # effective gradient-exchange bandwidth: dense bytes made
            # exchangeable per second of codec work (the BASELINE.md
            # north-star framing)
            "effective_exchange_GBps": round(
                4.0 * RESNET50_D / max(r50["t_encode_s"] + r50["t_decode_s"], 1e-9) / 1e9,
                2,
            ),
        }

    if "--resnet50" in sys.argv:
        # ResNet-50 images/sec at topk 1% (BASELINE.md north-star metric):
        # full fwd+bwd+compressed-exchange step on the available chip.
        # Opt-in — the fwd/bwd compile is minutes through a cold tunnel.
        detail["resnet50_images_per_sec"] = _resnet50_images_per_sec(overhead)

    print(
        json.dumps(
            {
                "metric": "lstm_e2e_grad_exchange_speedup_vs_dense_100mbps",
                "value": round(best, 3),
                "unit": "x",
                "vs_baseline": round(best / PAPER_E2E_SPEEDUP, 4),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
