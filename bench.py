"""Headline benchmark: the reference's own end-to-end Table-4 experiment.

The paper's headline efficiency claim (BASELINE.md, paper Table 4): on the
StackOverflow LSTM (4.05M params) over a 100 Mbps link, DRQSGD-BF-P0's
end-to-end gradient exchange is **7.8x faster than the dense baseline**
(and 2.2x faster than Top-r). This bench reproduces that experiment's
arithmetic with our codecs running on real TPU silicon:

    T(config) = payload_bytes / BW + t_encode + t_decode      (per worker)
    speedup   = T(dense) / T(config),    BW = 12.5 MB/s (100 Mbps)

Configs measured:
  - dense           — no compression (payload = 4d bytes, no codec)
  - topr            — Top-r 10% raw sparse (the paper's Top-r column)
  - drqsgd_delta    — topk 10% + delta-bitpack indices + QSGD values
                      (our best: the FastPFor-role codec, O(k) both sides)
  - drqsgd_bloom    — topk 10% + blocked-bloom indices (P0) + QSGD values
                      (the paper's DRQSGD-BF-P0 shape)
  - drqsgd_bloom_sampled — same wire, sortless sampled-threshold sparsifier
  - drqsgd_bloom_direct  — same wire, sparsifier-free fused encode
                      (bloom.encode_dense_direct: no top-k anywhere)

Headline value = speedup(best config) vs dense; vs_baseline divides by the
paper's 7.8x, so vs_baseline >= 1.0 means beating the reference's own
number. ResNet-50-scale (25.6M) timings ride in `detail`.

Timing note: axon's `block_until_ready` returns before execution completes,
so synchronization reads one scalar of an output leaf back to host. All
timings are AMORTIZED: `reps` async dispatches are enqueued, every output is
synced once at the end, and wall time is divided by `reps` — the only
reliable method through the device tunnel, whose 50-70ms per-dispatch
overhead swamps (and whose early-returning sync can zero out) single-call
timings. The residual per-dispatch enqueue cost is genuine pipeline cost and
is reported, not subtracted, so no recorded time can clamp to 0.0.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PAPER_E2E_SPEEDUP = 7.8  # DRQSGD-BF-P0 vs baseline, paper Table 4
LSTM_D = 4_053_428  # StackOverflow LSTM param count (BASELINE.md)
RESNET50_D = 25_557_032


def _progress(msg: str) -> None:
    """Stage progress to stderr (stdout stays the single JSON line)."""
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def _span(name: str):
    """Lazy span handle — bench defers jax-touching imports until the
    platform is pinned, so the telemetry import happens per call (cheap:
    module lookup after the first)."""
    from deepreduce_tpu.telemetry import spans

    return spans.span(name)


DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024  # 4 MB fusion buckets (Horovod-scale)


def _bucket_bytes_arg() -> int:
    """`--bucket-bytes N`: bucket budget for the bucketed-exchange arm.
    Raw-sys.argv style like --quick/--trace-out; the value is routed into
    the config through `from_params(strict=True)` so a bad knob fails
    loudly in the subprocess."""
    if "--bucket-bytes" in sys.argv:
        i = sys.argv.index("--bucket-bytes")
        if i + 1 < len(sys.argv):
            return int(sys.argv[i + 1])
    return DEFAULT_BUCKET_BYTES


def lstm_leafy_shapes() -> dict:
    """name -> flat size: the StackOverflow LSTM census as the LEAFY pytree
    the paper actually trains (Table 2) — per-gate kernel/recurrent/bias
    plus per-gate layernorm leaves instead of one fused (d,) blob. ~4.05M
    params across 22 leaves, most of them tiny: the shape where per-leaf
    codec overhead is O(leaves) and the bucketed exchange should win."""
    shapes = {"embedding": 10_004 * 96}
    for gate in ("i", "f", "g", "o"):
        shapes[f"lstm/kernel_{gate}"] = 96 * 670
        shapes[f"lstm/recurrent_{gate}"] = 670 * 670
        shapes[f"lstm/bias_{gate}"] = 670
        shapes[f"lstm/ln_scale_{gate}"] = 670
        shapes[f"lstm/ln_bias_{gate}"] = 670
    shapes["proj/kernel"] = 670 * 96
    shapes["proj/bias"] = 96
    shapes["output/kernel"] = 96 * 10_004
    shapes["output/bias"] = 10_004
    return shapes


def _trace_out_path():
    """`--trace-out PATH`: save a Chrome trace of the bench phases there.
    Raw-sys.argv style like --quick/--decode-sweep, and forwarded verbatim
    to the TPU child process (which is the one that records and writes)."""
    if "--trace-out" in sys.argv:
        i = sys.argv.index("--trace-out")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return None


def _maybe_save_trace() -> None:
    path = _trace_out_path()
    if path is None:
        return
    from deepreduce_tpu.telemetry import spans

    spans.get_tracer().save(path)
    _progress(f"telemetry trace -> {path}")


def _sync(x):
    import jax

    for leaf in jax.tree_util.tree_leaves(x):
        if getattr(leaf, "size", 0):
            np.asarray(leaf.reshape(-1)[0])
            return x
    return x


def _timeit(fn, *args, iters=4, reps=10):
    """Amortized timing: `reps` async dispatches, one sync pass over all
    outputs, wall/reps; best of `iters`. Floored at 1us so a measurement can
    never record as exactly 0.0 (which through the tunnel means "below
    dispatch noise", not "free")."""
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(reps)]
        for o in outs:
            _sync(o)
        best = min(best, (time.perf_counter() - t0) / reps)
    return max(best, 1e-6)


def _last_json_line(text: str):
    """Last stdout line that parses as a JSON object — stray trailing output
    (e.g. a library printing at interpreter exit) must not replace the
    record."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def measure_config(d, ratio, cfg_kwargs, iters):
    import jax
    import jax.numpy as jnp

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    # the measured-best knob set (approx_topk, mod-blocked bloom, fused,
    # pallas) ships as a named preset; every config here runs under it
    cfg = DeepReduceConfig.tpu_defaults(
        compress_ratio=ratio, **{"compressor": "topk", **cfg_kwargs}
    )
    codec = TensorCodec((d,), cfg, name="bench")
    rng = np.random.default_rng(0)
    g = jnp.asarray((rng.normal(size=d) * rng.random(d) ** 2).astype(np.float32))
    key = jax.random.PRNGKey(0)
    encode = jax.jit(lambda t, s: codec.encode(t, step=s, key=key))
    decode = jax.jit(lambda p, s: codec.decode(p, step=s))
    label = cfg_kwargs.get("index") or "topr"
    _progress(f"d={d} {label}: compiling encode")
    with _span(f"bench/compile/d{d}/{label}"):
        payload = _sync(encode(g, 0))
        _progress(f"d={d}: compiling decode")
        _sync(decode(payload, 0))
    _progress(f"d={d}: timing ({iters} iters, amortized)")
    with _span(f"bench/time/d{d}/{label}"):
        t_enc = _timeit(encode, g, 1, iters=iters)
        t_dec = _timeit(decode, payload, 1, iters=iters)
    _progress(f"d={d}: done enc={t_enc:.4f}s dec={t_dec:.4f}s")
    stats = codec.wire_stats(payload)
    return {
        "payload_bytes": float(stats.total_bits) / 8.0,
        "rel_volume": float(stats.rel_volume()),
        "t_encode_s": t_enc,
        "t_decode_s": t_dec,
    }


def _costmodel():
    """deepreduce_tpu.costmodel — the extracted step-time model. BW_100MBPS,
    `exchange_time` and the dense baseline row used to live inline here;
    they now have one home shared with the rs_mode='auto' selector.
    Imported lazily (the package __init__ pulls in jax, which bench defers
    until the platform is pinned)."""
    from deepreduce_tpu import costmodel

    return costmodel


def _provenance(modeled, measured, profile=None) -> dict:
    """Honesty stamp on every committed record: which detail fields are
    cost-model arithmetic and which came off a clock. A reader (or the
    `telemetry compare --profile` re-pricer) must be able to tell a modeled
    claim — re-derivable from static constants or a fitted profile — from a
    measurement that only a re-run can reproduce. When the record's modeled
    numbers came from a fitted MachineProfile, `profile_sha256` pins WHICH
    profile (its content hash) so `telemetry profiles` drift reports can be
    matched back to the exact fit that priced the claim."""
    out = {"modeled": sorted(modeled), "measured": sorted(measured)}
    if profile is not None:
        out["profile_sha256"] = profile.content_hash()
    return out


def _latest_midround_record() -> str:
    """Newest committed BENCH_TPU_MIDROUND_*.json, or '' if none exist."""
    import pathlib

    here = pathlib.Path(__file__).parent
    names = sorted(p.name for p in here.glob("BENCH_TPU_MIDROUND_*.json"))
    return names[-1] if names else ""


def _tpu_alive(timeout_s: float = 180.0) -> bool:
    """Subprocess device probe (shared helper; a wedged axon tunnel hangs
    inside jax.devices() and must never poison this process's backend)."""
    from deepreduce_tpu.utils import device_responsive

    return device_responsive(timeout_s=timeout_s)


_PEAK_FLOPS_BF16 = {
    # by device_kind substring; conservative denominator for MFU (models run
    # f32, which is slower than bf16 peak on every TPU generation)
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
}


def _chip_peak_flops() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_FLOPS_BF16.items():
        if sub in kind:
            return peak
    return 197e12


def throughput_models() -> dict:
    """name -> (model ctor kwargs applied, image hw, num classes, bench
    batch) — shared with benchmarks/model_throughput_probe.py so a batch
    sweep measures exactly the model specs this bench records. bf16 compute
    dtype (params/grads stay f32, so the codec path is byte-identical): the
    MXU-native choice."""
    import jax.numpy as jnp

    from deepreduce_tpu.models import ResNet20, ResNet50

    return {
        "resnet50": (ResNet50(num_classes=1000, dtype=jnp.bfloat16), 224, 1000, 128),
        "resnet20": (ResNet20(num_classes=10, dtype=jnp.bfloat16), 32, 10, 1024),
    }


def throughput_cfgs() -> dict:
    """The two model-throughput arms (dense baseline, flagship topk-1%
    bloom) — shared with benchmarks/model_throughput_probe.py so the batch
    sweep measures exactly the configs this bench records."""
    from deepreduce_tpu.config import DeepReduceConfig

    return {
        "dense": DeepReduceConfig(
            compressor="none", deepreduce=None, memory="none", communicator="allreduce"
        ),
        "topk1_bloom": DeepReduceConfig.tpu_defaults(
            compressor="topk", compress_ratio=0.01, memory="residual",
            deepreduce="index", index="bloom", fpr=0.001,
        ),
    }


def time_chained_steps(step, state, *, reps: int = 5, rounds: int = 2):
    """Amortized train-step timing: chain `reps` async step dispatches
    (each depends on the previous state but none blocks the host), sync
    once, divide — per-dispatch tunnel overhead amortizes away. Returns
    (best seconds/step, final state)."""
    best = float("inf")
    for i in range(rounds):
        t0 = time.perf_counter()
        for r in range(reps):
            state, _loss, _ = step(state, 1 + i * reps + r)
        _sync(state.params)
        best = min(best, (time.perf_counter() - t0) / reps)
    return max(best, 1e-9), state


def _model_throughput() -> dict:
    """Full training-step throughput (fwd+bwd+codec+exchange), dense vs
    topk-1% bloom under the tpu_defaults preset, on the single available
    chip (mesh of 1; codec + exchange cost is real, the collective
    degenerates). Reports images/sec, step time, and MFU from the compiled
    step's own XLA flops estimate — the BASELINE.json north-star metric."""
    import jax
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.train import Trainer

    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    peak = _chip_peak_flops()
    cfgs = throughput_cfgs()
    out = {}
    for mname, (model, hw, nclass, batch) in throughput_models().items():
        ishape = (batch, hw, hw, 3)
        # device-resident batch: a host numpy batch would re-cross the
        # tunnel every step and the transfer, not the chip, would be timed
        images = jnp.asarray(rng.normal(size=ishape).astype(np.float32))
        labels = jnp.asarray(rng.integers(0, nclass, batch).astype(np.int32))
        res = {}
        for cname, cfg in cfgs.items():
            _progress(f"{mname} {cname}: compiling step")
            trainer = Trainer(model, cfg, optax.sgd(0.1), mesh)
            state = trainer.init_state(jax.random.PRNGKey(0), (images, labels))
            step = lambda s, i: trainer.step(s, (images, labels), jax.random.PRNGKey(i))
            state, _, _ = step(state, 0)
            _sync(state.params)
            t_step, state = time_chained_steps(step, state)
            entry = {
                "images_per_sec": round(batch / t_step, 2),
                "step_time_s": round(t_step, 4),
            }
            flops = _step_flops(trainer, state, images, labels)
            if flops:
                entry["flops_per_step"] = flops
                entry["mfu_vs_bf16_peak"] = round(flops / t_step / peak, 4)
            res[cname] = entry
            _progress(f"{mname} {cname}: {entry['images_per_sec']} img/s")
        res["compression_overhead_pct"] = round(
            100.0
            * (
                res["dense"]["images_per_sec"]
                / max(res["topk1_bloom"]["images_per_sec"], 1e-9)
                - 1.0
            ),
            1,
        )
        out[mname] = res
    return out


def _step_flops(trainer, state, images, labels) -> float:
    """XLA's own flops estimate for the compiled train step (0.0 if the
    backend doesn't expose cost analysis)."""
    import dataclasses

    import jax

    try:
        state_nores = dataclasses.replace(state, residuals=None)
        lowered = trainer._step_fn.lower(
            state_nores, state.residuals, (images, labels), jax.random.PRNGKey(0)
        )
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:
        return 0.0


def _measured_exchange(degraded: bool) -> dict:
    """OBSERVED fused-exchange throughput, next to the analytic Table-4
    model: (a) the 1-device self-gather on the real chip — compress +
    all_gather(1) + decode-loop + aggregate, the full per-worker codepath;
    (b) the genuine 8-way all_gather + 8-payload decode loop on the
    virtual CPU mesh. Both run in timeout-guarded subprocesses (the
    exchange program's cold compile can wedge a flaky device tunnel — it
    must never hang the whole bench). GBps figures are dense-equivalent
    bytes made exchangeable per second of wall time (the BASELINE.md
    north-star framing)."""
    out = {}
    if not degraded:
        tpu = _exchange_subprocess(LSTM_D, workers=1, pin_cpu=False, timeout=900)
        if tpu:
            out["tpu_1chip_selfgather"] = tpu
    cpu8 = _exchange_subprocess(LSTM_D, workers=8, pin_cpu=True, timeout=600)
    if cpu8:
        out["cpu8_mesh"] = cpu8
    return out


def _exchange_subprocess(
    d: int, workers: int, pin_cpu: bool, timeout: int, decode_strategy: str = "loop"
) -> dict:
    import os
    import subprocess

    from deepreduce_tpu.utils import host_device_count_flags

    # env vars alone do NOT pin the platform here: the axon sitecustomize
    # calls jax.config.update("jax_platforms", "axon") at interpreter start,
    # which beats JAX_PLATFORMS — the subprocess must re-pin in-process
    # (force_platform) or it dials the device tunnel anyway.
    pin = f"force_platform('cpu', device_count={workers})" if pin_cpu else "pass"
    code = f"""
import json, time, numpy as np
from deepreduce_tpu.utils import force_platform
{pin}
import jax, jax.numpy as jnp
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.utils import enable_compile_cache
enable_compile_cache()
d, nw = {d}, {workers}
def sync(x):
    for leaf in jax.tree_util.tree_leaves(x):
        if getattr(leaf, "size", 0):
            np.asarray(leaf.reshape(-1)[0]); return x
    return x
def timeit(fn, *args, iters=4, reps=6):
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(reps)]
        for o in outs:
            sync(o)
        best = min(best, (time.perf_counter() - t0) / reps)
    return max(best, 1e-6)
cfg = DeepReduceConfig.tpu_defaults(
    compressor="topk", compress_ratio=0.10, deepreduce="both",
    index="bloom", value="qsgd", policy="p0", fpr=0.02, memory="none",
    decode_strategy={decode_strategy!r})
grads = {{"g": jnp.asarray(np.random.default_rng(0).normal(size=d), jnp.float32)}}
ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=nw)
mesh = Mesh(np.array(jax.devices()[:nw]), ("data",))
def spmd(g):
    agg, _, wire = ex.exchange(g, None, step=jnp.zeros((), jnp.int32),
                               key=jax.random.PRNGKey(0))
    return agg, wire
fn = jax.jit(shard_map(spmd, mesh=mesh, in_specs=(P(),), out_specs=(P(), P()),
                       check_vma=False))
agg, wire = fn(grads)
sync(agg)
t = timeit(fn, grads)
payload = float(np.asarray(wire.total_bits)) / 8.0
print(json.dumps({{
    "workers": nw, "decode_strategy": {decode_strategy!r},
    "t_step_s": round(t, 4),
    "payload_bytes_per_worker": payload,
    # static per-worker ICI bytes incl. the ring's explicit (W-1)/W hops
    "wire_bytes_per_worker": ex.payload_bytes(grads),
    "observed_gathered_GBps": round(nw * payload / t / 1e9, 3),
    "dense_equiv_GBps": round(4.0 * d / t / 1e9, 3),
}}))
"""
    env = dict(os.environ)
    label = f"{workers}-CPU mesh" if pin_cpu else "1-chip self-gather"
    if decode_strategy != "loop":
        label += f" [{decode_strategy}]"
    if pin_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = host_device_count_flags(
            env.get("XLA_FLAGS", ""), workers
        )
    try:
        _progress(f"measured exchange: {label} subprocess")
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, text=True,
            capture_output=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode == 0:
            rec = _last_json_line(proc.stdout)
            if rec is not None:
                return rec
            _progress(f"{label} produced no JSON record")
        else:
            _progress(f"{label} failed rc={proc.returncode}: {proc.stderr[-300:]}")
    except Exception as e:  # noqa: BLE001 — bench must not die on a probe
        _progress(f"{label} skipped: {e}")
    return {}


def _bucketed_subprocess(
    bucket_bytes: int, workers: int = 8, timeout: int = 900
) -> dict:
    """The `drqsgd_bloom_bucketed` arm: the flagship bloom+qsgd exchange on
    the LEAFY LSTM census (lstm_leafy_shapes — 22 leaves, most tiny),
    per-tensor fused vs bucketed at `bucket_bytes`, on the virtual 8-way
    CPU mesh in a timeout-guarded subprocess. The per-tensor arm pays one
    codec per leaf; the bucketed arm pays one per bucket — the
    O(leaves)→O(buckets) encode win, measured. Configs are built through
    `from_params(strict=True)` so a misspelled knob fails loudly."""
    import os
    import subprocess

    from deepreduce_tpu.utils import host_device_count_flags

    shapes = lstm_leafy_shapes()
    code = f"""
import json, time, numpy as np
from deepreduce_tpu.utils import force_platform
force_platform('cpu', device_count={workers})
import jax, jax.numpy as jnp
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import from_params
from deepreduce_tpu.utils import enable_compile_cache
enable_compile_cache()
shapes, nw = {shapes!r}, {workers}
def sync(x):
    for leaf in jax.tree_util.tree_leaves(x):
        if getattr(leaf, "size", 0):
            np.asarray(leaf.reshape(-1)[0]); return x
    return x
def timeit(fn, *args, iters=4, reps=6):
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(reps)]
        for o in outs:
            sync(o)
        best = min(best, (time.perf_counter() - t0) / reps)
    return max(best, 1e-6)
base = dict(compressor="topk", compress_ratio=0.10, deepreduce="both",
            index="bloom", value="qsgd", policy="p0", fpr=0.02,
            memory="none", approx_topk=True, bloom_blocked="mod",
            fused=True, use_pallas=True)
rng = np.random.default_rng(0)
grads = {{n: jnp.asarray(rng.normal(size=s), jnp.float32)
          for n, s in shapes.items()}}
mesh = Mesh(np.array(jax.devices()[:nw]), ("data",))
out = {{}}
for arm, extra in (("drqsgd_bloom_pertensor", {{}}),
                   ("drqsgd_bloom_bucketed", {{"bucket_bytes": {bucket_bytes}}})):
    cfg = from_params({{**base, **extra}}, strict=True)
    ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=nw)
    def spmd(g, _ex=ex):
        agg, _, wire = _ex.exchange(g, None, step=jnp.zeros((), jnp.int32),
                                    key=jax.random.PRNGKey(0))
        return agg, wire
    fn = jax.jit(shard_map(spmd, mesh=mesh, in_specs=(P(),),
                           out_specs=(P(), P()), check_vma=False))
    agg, wire = fn(grads)
    sync(agg)
    t = timeit(fn, grads)
    out[arm] = {{"t_step_s": round(t, 4),
                 "num_buckets": ex.num_buckets,
                 "wire_bytes_per_worker": ex.payload_bytes(grads)}}
pt = out["drqsgd_bloom_pertensor"]["t_step_s"]
bk = out["drqsgd_bloom_bucketed"]["t_step_s"]
print(json.dumps({{
    "leaves": len(shapes), "d": int(sum(shapes.values())), "workers": nw,
    "bucket_bytes": {bucket_bytes}, "arms": out,
    "bucketed_speedup_vs_pertensor": round(pt / bk, 3)}}))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = host_device_count_flags(env.get("XLA_FLAGS", ""), workers)
    try:
        _progress(f"bucketed exchange: {workers}-CPU mesh subprocess")
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, text=True,
            capture_output=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode == 0:
            rec = _last_json_line(proc.stdout)
            if rec is not None:
                return rec
            _progress("bucketed exchange produced no JSON record")
        else:
            _progress(
                f"bucketed exchange failed rc={proc.returncode}: "
                f"{proc.stderr[-300:]}"
            )
    except Exception as e:  # noqa: BLE001 — bench must not die on a probe
        _progress(f"bucketed exchange skipped: {e}")
    return {}


def _overlap_model(rec: dict, iters: int = 7) -> dict:
    """Model the r15 streaming schedule (cfg.stream_exchange) against the
    r09 pipelined bucket schedule on the 100 Mbps planning link, from one
    measured flagship codec row at the leafy-LSTM size.

    The streaming model is `costmodel.overlapped_step_time`: backward
    compute hides allgather wire, leaving encode + exposed wire + decode.
    The curve sweeps compute_time as fractions of the allgather time and
    reports `costmodel.overlap_fraction` at each point; the committed
    headline is the full-overlap regime (compute_time >= wire, the DDP
    overlap premise the streaming schedule targets), where
    t_enc + W*t_dec <= t_enc + max(wire, W*t_dec) — the r09 pipelined
    model — holds unconditionally."""
    cm = _costmodel()
    shapes = lstm_leafy_shapes()
    d = int(sum(shapes.values()))
    ratio = 0.10
    m = measure_config(
        d, ratio,
        dict(deepreduce="both", index="bloom", value="qsgd", policy="p0",
             fpr=0.02, memory="none"),
        iters,
    )
    W = int(rec.get("workers", 8) or 8)
    wire = cm.allgather_time(m["payload_bytes"], W, cm.BW_100MBPS)
    t_pipelined = cm.hier_dcn_time(
        "bucketed", d, W, ratio, cm.BW_100MBPS, measurement=m
    )
    curve = []
    for frac in (0.0, 0.25, 0.5, 1.0):
        ct = frac * wire
        curve.append(
            {
                "compute_time_s": round(ct, 4),
                "t_streaming_s": round(
                    cm.overlapped_step_time(m, W, compute_time=ct), 4
                ),
                "overlap_fraction": round(
                    cm.overlap_fraction(m, W, compute_time=ct), 4
                ),
            }
        )
    t_stream = cm.overlapped_step_time(m, W, compute_time=wire)
    return {
        "d": d,
        "workers": W,
        "ratio": ratio,
        "bw_bytes_per_s": cm.BW_100MBPS,
        "measurement": {k: round(float(v), 6) for k, v in m.items()},
        "t_allgather_s": round(wire, 4),
        "t_serialized_s": round(cm.fused_step_time(m, W), 4),
        "t_pipelined_r09_s": round(t_pipelined, 4),
        "t_streaming_full_overlap_s": round(t_stream, 4),
        "streaming_le_pipelined": bool(t_stream <= t_pipelined),
        "curve": curve,
    }


def decode_strategy_sweep(d: int = LSTM_D, workers: int = 8) -> dict:
    """The fused-exchange decode-strategy sweep arm: the SAME flagship
    bloom+qsgd exchange measured under all three cfg.decode_strategy values
    (loop / vmap / ring) on the virtual CPU mesh — so the loop-vs-batched-
    vs-overlapped comparison is recorded even while the TPU tunnel is down.
    CPU relative timings say nothing absolute about ICI overlap, but they
    do expose the serial-decode tax the loop pays and the ring's kernel
    count; the on-silicon sweep reuses this arm unchanged."""
    out = {}
    for strategy in ("loop", "vmap", "ring"):
        rec = _exchange_subprocess(
            d, workers=workers, pin_cpu=True, timeout=900,
            decode_strategy=strategy,
        )
        if rec:
            out[strategy] = rec
    return out


def rs_sweep(quick: bool = False, workers: int = 8) -> dict:
    """The in-collective reduction sweep arm (`--rs-sweep`): every sparse_rs
    rs_mode runs for real on the virtual CPU mesh to measure its per-step
    compute, then gets priced at W in {8, 16} with the W-aware ring cost
    model next to the fused drqsgd_bloom_* rows.

    Compute measurement: one spmd step over the W-way mesh, amortized wall
    time divided by W — the host timeshares the W shard programs on its
    cores, so wall/W approximates ONE worker's compute (collectives on the
    shared-memory mesh are memcpys, folded in as a small overestimate).
    The fused rows come from `measure_config` (one encode + one decode,
    single device) and are then modeled with `fused_step_time`, which
    charges the W-fold receive volume and W decodes the gather-then-decode
    design actually pays. W=16 reuses the W=8-measured compute terms: the
    per-worker shards only shrink with W, so the reuse is conservative for
    the in-collective routes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepreduce_tpu import sparse_rs
    from deepreduce_tpu.utils import enable_compile_cache
    from deepreduce_tpu.utils.compat import shard_map

    enable_compile_cache()
    cm = _costmodel()
    d = LSTM_D if not quick else 500_000
    ratio = 0.10  # the paper's Top-r 10% LSTM setting, same as the headline
    W = workers
    mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
    rng = np.random.default_rng(0)
    g = jnp.asarray(
        (rng.normal(size=(W, d)) * rng.random((W, d)) ** 2).astype(np.float32)
    )
    key = jax.random.PRNGKey(0)

    rs_modes = ("sparse", "adaptive", "quantized", "sketch", "oktopk")
    compute = {}
    for mode in rs_modes:

        def spmd(gw, mode=mode):
            agg, own, _ = sparse_rs.exchange(
                gw[0],
                "data",
                W,
                ratio=ratio,
                rs_mode=mode,
                key=(key if mode in ("adaptive", "quantized") else None),
            )
            return agg[None]

        fn = jax.jit(
            shard_map(
                spmd,
                mesh=mesh,
                in_specs=(P("data"),),
                out_specs=P("data"),
                check_vma=False,
            )
        )
        _progress(f"rs-sweep: compiling rs_mode={mode} (d={d}, W={W})")
        with _span(f"bench/rs-sweep/compile/{mode}"):
            _sync(fn(g))
        _progress(f"rs-sweep: timing rs_mode={mode}")
        with _span(f"bench/rs-sweep/time/{mode}"):
            wall = _timeit(fn, g, iters=2 if quick else 3, reps=3)
        compute[mode] = wall / W
        _progress(f"rs-sweep: {mode} wall={wall:.4f}s compute/worker={wall / W:.4f}s")

    # the fused gather-then-decode competition: the three bloom flagship
    # shapes from the headline table, measured flat then priced W-aware
    bloom_cfgs = {
        "drqsgd_bloom": dict(
            deepreduce="both", index="bloom", value="qsgd", policy="p0",
            fpr=0.02, memory="none",
        ),
        "drqsgd_bloom_sampled": dict(
            compressor="topk_sampled", deepreduce="both", index="bloom",
            value="qsgd", policy="p0", fpr=0.02, memory="none",
        ),
        "drqsgd_bloom_direct": dict(
            compressor="topk_sampled", deepreduce="both", index="bloom",
            value="qsgd", policy="p0", fpr=0.02, memory="none",
            bloom_threshold_insert=True,
        ),
    }
    with _span("bench/rs-sweep/bloom-rows"):
        bloom_rows = {
            name: measure_config(d, ratio, kw, 2 if quick else 3)
            for name, kw in bloom_cfgs.items()
        }

    comparison = {}
    for Wm in (8, 16):
        fused = {n: cm.fused_step_time(m, Wm) for n, m in bloom_rows.items()}
        incoll = {
            mode: cm.rs_step_time(mode, d, Wm, ratio, t_compute_s=compute[mode])
            for mode in rs_modes
        }
        best_f = min(fused, key=fused.get)
        best_i = min(incoll, key=incoll.get)
        comparison[f"W{Wm}"] = {
            "fused_bloom_step_s": {n: round(v, 4) for n, v in fused.items()},
            "in_collective_step_s": {n: round(v, 4) for n, v in incoll.items()},
            # dense f32 ring allreduce, zero codec compute — the floor the
            # whole compression story is measured against
            "dense_allreduce_s": round(cm.allreduce_time(4.0 * d, Wm), 4),
            "best_fused": best_f,
            "best_in_collective": best_i,
            "speedup_best_incoll_vs_best_fused": round(
                fused[best_f] / incoll[best_i], 3
            ),
            "auto_selects": cm.select_rs_mode(d, Wm, ratio),
            # per-collective injection bytes per route — the exact numbers
            # the jx-wire-accounting 'collective' rule pins on the trace
            "wire_bytes_per_collective": {
                mode: cm.rs_wire_bytes(mode, d, Wm, ratio) for mode in rs_modes
            },
        }

    return {
        "metric": "in_collective_rs_vs_fused_bloom_step_time",
        "unit": "s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=[
                "W8", "W16", "wire_bytes_per_collective", "dense_allreduce_s",
            ],
            measured=["rs_compute_s_per_worker", "bloom_measurements"],
        ),
        "detail": {
            "model": "stackoverflow_lstm" if not quick else "quick",
            "d": d,
            "ratio": ratio,
            "workers_measured": W,
            "bw_bytes_per_s": cm.BW_100MBPS,
            "cost_model": (
                "W-aware ring model (costmodel.rs_step_time /"
                " fused_step_time); compute measured on the CPU mesh"
            ),
            "rs_compute_s_per_worker": {
                n: round(v, 4) for n, v in compute.items()
            },
            "bloom_measurements": {
                n: {
                    "payload_bytes": m["payload_bytes"],
                    "t_encode_s": round(m["t_encode_s"], 4),
                    "t_decode_s": round(m["t_decode_s"], 4),
                }
                for n, m in bloom_rows.items()
            },
            **comparison,
        },
    }


def oktopk_sweep(quick: bool = False, workers: int = 8) -> dict:
    """The Ok-Topk density x W grid arm (`--oktopk-sweep`, committed as
    BENCH_OKTOPK_r18.json): measure the oktopk route's per-step compute for
    real on the virtual CPU mesh at one anchor point (next to quantized and
    sparse, same wall/W amortization as `rs_sweep`), then price the full
    density x worker-count grid with the same W-aware ring model
    `select_rs_mode` argmins over. Every grid point is wire-only
    (t_compute_s=0) — exactly the selector's view, so `auto_selects` and
    the per-mode step times in a point agree by construction. Each point
    carries d/ratio/workers so `telemetry compare --profile` can re-price
    the whole grid under a fitted MachineProfile."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepreduce_tpu import sparse_rs
    from deepreduce_tpu.utils import enable_compile_cache
    from deepreduce_tpu.utils.compat import shard_map

    enable_compile_cache()
    cm = _costmodel()
    d = LSTM_D if not quick else 500_000
    anchor_ratio = 0.01  # the sparse regime the oktopk route targets
    W = workers
    mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
    rng = np.random.default_rng(0)
    g = jnp.asarray(
        (rng.normal(size=(W, d)) * rng.random((W, d)) ** 2).astype(np.float32)
    )
    key = jax.random.PRNGKey(0)

    compute = {}
    for mode in ("sparse", "quantized", "oktopk"):

        def spmd(gw, mode=mode):
            agg, own, _ = sparse_rs.exchange(
                gw[0],
                "data",
                W,
                ratio=anchor_ratio,
                rs_mode=mode,
                key=(key if mode in ("adaptive", "quantized") else None),
            )
            return agg[None]

        fn = jax.jit(
            shard_map(
                spmd,
                mesh=mesh,
                in_specs=(P("data"),),
                out_specs=P("data"),
                check_vma=False,
            )
        )
        _progress(f"oktopk-sweep: compiling rs_mode={mode} (d={d}, W={W})")
        with _span(f"bench/oktopk-sweep/compile/{mode}"):
            _sync(fn(g))
        _progress(f"oktopk-sweep: timing rs_mode={mode}")
        with _span(f"bench/oktopk-sweep/time/{mode}"):
            wall = _timeit(fn, g, iters=2 if quick else 3, reps=3)
        compute[mode] = wall / W
        _progress(
            f"oktopk-sweep: {mode} wall={wall:.4f}s compute/worker={wall / W:.4f}s"
        )

    ratios = (0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1)
    worker_grid = (8, 16, 32)
    modes = ("sparse", "adaptive", "quantized", "sketch", "oktopk")
    points = []
    oktopk_wins = 0
    sparse_regime_wins = 0
    sparse_regime_pts = 0
    for r in ratios:
        for Wm in worker_grid:
            step = {m: cm.rs_step_time(m, d, Wm, r) for m in modes}
            pick = cm.select_rs_mode(d, Wm, r)
            speedup = step["quantized"] / step["oktopk"]
            if pick == "oktopk":
                oktopk_wins += 1
            if r <= 0.01:
                sparse_regime_pts += 1
                if step["oktopk"] < step["quantized"]:
                    sparse_regime_wins += 1
            points.append(
                {
                    "d": d,
                    "ratio": r,
                    "workers": Wm,
                    "modeled_step_s": {
                        m: round(v, 6) for m, v in step.items()
                    },
                    "auto_selects": pick,
                    "speedup_oktopk_vs_quantized": round(speedup, 3),
                    # the exact per-collective injection bytes the
                    # jx-wire-accounting 'collective' rule pins on the trace
                    "oktopk_wire_bytes_per_collective": cm.rs_wire_bytes(
                        "oktopk", d, Wm, r
                    ),
                }
            )

    return {
        "metric": "oktopk_vs_quantized_modeled_step_time_grid",
        "unit": "s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=["detail.points", "detail.headline"],
            measured=["detail.oktopk_compute_anchor"],
        ),
        "detail": {
            "model": "stackoverflow_lstm" if not quick else "quick",
            "d": d,
            "workers_measured": W,
            "anchor_ratio": anchor_ratio,
            "bw_bytes_per_s": cm.BW_100MBPS,
            "cost_model": (
                "W-aware ring model (costmodel.rs_step_time), wire-only grid"
                " — the same argmin select_rs_mode('auto') runs; compute"
                " anchor measured on the CPU mesh at anchor_ratio"
            ),
            "oktopk_compute_anchor": {
                n: round(v, 4) for n, v in compute.items()
            },
            "headline": {
                "oktopk_auto_picks": oktopk_wins,
                "grid_points": len(points),
                "oktopk_beats_quantized_at_ratio_le_0.01": (
                    f"{sparse_regime_wins}/{sparse_regime_pts}"
                ),
            },
            "points": points,
        },
    }


def hier_sweep(quick: bool = False, n_slices: int = 8, per_slice: int = 4) -> dict:
    """The two-tier exchange sweep arm (`--hier-sweep`): run the
    hierarchical exchange for real on a (2, 4) virtual CPU mesh (both the
    dense-ici+fused-dcn baseline and the planner's pick), then price every
    {ici} x {dcn} plan at the deployment shape (`n_slices` slices of
    `per_slice` devices, 100 Mbps DCN / 10 Gbps ICI) with the SAME
    `costmodel.select_hier_plan` the hier_dcn='auto' construction path
    calls — so the committed report and the runtime planner argmin over
    identical numbers. The flat competition is every compressed
    single-axis route at W = n_slices*per_slice on the scarce link: the
    whole point of the hierarchy is that the flat routes pay the 100 Mbps
    link W-wide while hier pays it n_slices-wide."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.parallel.hierarchical import (
        HierarchicalExchanger, make_hybrid_mesh,
    )
    from deepreduce_tpu.utils import enable_compile_cache
    from deepreduce_tpu.utils.compat import shard_map

    enable_compile_cache()
    cm = _costmodel()
    d = LSTM_D if not quick else 500_000
    ratio = 0.10  # the paper's Top-r 10% LSTM setting, same as the headline
    W = n_slices * per_slice

    # -- real execution: the (2, 4) virtual mesh the analysis audits trace.
    # d_exec stays small — this proves the composed path runs end-to-end
    # and gives a per-worker compute ballpark; the pricing below is modeled
    d_exec = 200_000 if quick else 500_000
    mesh = make_hybrid_mesh(2, 4)
    rng = np.random.default_rng(0)
    g = jnp.asarray(
        (rng.normal(size=(8, d_exec)) * rng.random((8, d_exec)) ** 2).astype(
            np.float32
        )
    )
    key = jax.random.PRNGKey(0)
    exec_cfgs = {
        "dense+fused": DeepReduceConfig(
            compressor="topk", compress_ratio=ratio, memory="none",
            deepreduce=None, hier=True,
        ),
        "qar+quantized": DeepReduceConfig(
            compressor="topk", compress_ratio=ratio, memory="none",
            deepreduce=None, communicator="sparse_rs", rs_mode="quantized",
            hier=True, hier_ici="qar",
        ),
    }
    measured = {}
    for name, cfg in exec_cfgs.items():
        ex = HierarchicalExchanger(
            jax.ShapeDtypeStruct((d_exec,), jnp.float32), cfg,
            num_slices=2, per_slice=4,
        )

        def spmd(gw, _ex=ex):
            agg, _, _ = _ex.exchange(gw[0], None, key=key)
            return agg[None]

        fn = jax.jit(
            shard_map(
                spmd, mesh=mesh, in_specs=(P(("dcn", "ici")),),
                out_specs=P(("dcn", "ici")), check_vma=False,
            )
        )
        _progress(f"hier-sweep: compiling {name} on the (2,4) virtual mesh")
        with _span(f"bench/hier-sweep/compile/{name}"):
            _sync(fn(g))
        _progress(f"hier-sweep: timing {name}")
        with _span(f"bench/hier-sweep/time/{name}"):
            wall = _timeit(fn, g, iters=2, reps=3)
        measured[name] = {
            "wall_s": round(wall, 4),
            "compute_s_per_worker": round(wall / 8, 4),
            "dcn_payload_bytes": ex.payload_bytes(
                jax.ShapeDtypeStruct((d_exec,), jnp.float32)
            ),
            "ici_payload_bytes": ex.ici_payload_bytes(
                jax.ShapeDtypeStruct((d_exec,), jnp.float32)
            ),
        }
        _progress(f"hier-sweep: {name} wall={wall:.4f}s")

    # -- modeled pricing at the deployment shape --
    plan = cm.select_hier_plan(d, n_slices, per_slice, ratio)
    flat = {
        "fused": cm.hier_dcn_time("fused", d, W, ratio),
        **{
            mode: cm.rs_step_time(mode, d, W, ratio)
            for mode in ("sparse", "adaptive", "quantized", "sketch")
        },
    }
    best_flat = min(flat, key=flat.get)
    dense_s = cm.allreduce_time(4.0 * d, W)
    return {
        "metric": "hier_two_tier_vs_flat_step_time",
        "unit": "s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=[
                "auto_plan", "hier_plan_table_s", "flat_step_s",
                "dense_allreduce_s", "speedup_hier_vs_best_flat",
                "speedup_hier_vs_dense",
            ],
            measured=["measured_virtual_mesh"],
        ),
        "detail": {
            "model": "stackoverflow_lstm" if not quick else "quick",
            "d": d,
            "ratio": ratio,
            "n_slices": n_slices,
            "per_slice": per_slice,
            "bw_dcn_bytes_per_s": cm.BW_100MBPS,
            "bw_ici_bytes_per_s": cm.BW_ICI_10GBPS,
            "cost_model": (
                "two-tier serialized legs (costmodel.hier_step_time); flat "
                "arms pay the DCN link W-wide (rs_step_time / allgather "
                "model); execution measured on the (2,4) virtual CPU mesh"
            ),
            "measured_virtual_mesh": measured,
            "auto_plan": {
                "ici": plan["ici"],
                "dcn": plan["dcn"],
                "modeled_step_s": round(plan["modeled_step_s"], 4),
            },
            "hier_plan_table_s": {
                k: round(v, 4) for k, v in plan["table"].items()
            },
            "flat_step_s": {k: round(v, 4) for k, v in flat.items()},
            "dense_allreduce_s": round(dense_s, 4),
            "best_flat_compressed": best_flat,
            "hier_beats_best_flat": bool(
                plan["modeled_step_s"] < flat[best_flat]
            ),
            "speedup_hier_vs_best_flat": round(
                flat[best_flat] / plan["modeled_step_s"], 3
            ),
            "speedup_hier_vs_dense": round(
                dense_s / plan["modeled_step_s"], 3
            ),
        },
    }


def compose_sweep(quick: bool = False, n_slices: int = 8, per_slice: int = 4) -> dict:
    """The composed-legs sweep arm (`--compose-sweep`): the stream-over-hier
    schedule against its three parents — streaming-flat, barrier-hier, and
    the flat fused baseline — at the LSTM census geometry.

    Execution is real: all four arms run one grad+exchange step over the
    scaled six-leaf census on the 8-device CPU mesh (flat arms on the
    8-way axis, hier arms on the (2, 4) virtual two-axis mesh; the
    streaming arms dispatch every bucket's collectives from inside the
    custom_vjp backward hooks). The pricing grid is modeled at the
    deployment shape (`n_slices` slices of `per_slice` devices, 100 Mbps
    DCN / 10 Gbps ICI) with the SAME `costmodel.stream_hier_step_time`
    the overlap-aware planner calls, swept over {ratio} x {hideable
    compute}: the composed model hides the combined ici+dcn wire, the
    streaming-flat parent hides the W-wide flat gather, the barrier
    parents hide nothing — so every grid point prices what composing the
    two legs actually buys."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.comm import GradientExchanger
    from deepreduce_tpu.comm_stream import StreamingExchange
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.parallel.hierarchical import (
        HierarchicalExchanger, make_hybrid_mesh,
    )
    from deepreduce_tpu.utils import enable_compile_cache
    from deepreduce_tpu.utils.compat import shard_map

    enable_compile_cache()
    cm = _costmodel()
    tmap = jax.tree_util.tree_map
    d = LSTM_D
    ratio = 0.10  # the paper's Top-r 10% LSTM setting
    W = n_slices * per_slice

    # -- real execution: the six-leaf census (one embedding-style leaf that
    # buckets solo plus five gate/bias-style leaves) scaled so the FFD
    # partition keeps its three-bucket structure
    scale = 16 if quick else 64
    census = {
        "emb": 3000 * scale, "w1": 900 * scale, "w2": 700 * scale,
        "b1": 300 * scale, "b2": 150 * scale, "b3": 50 * scale,
    }
    bucket_bytes = 4800 * scale
    codec_kw = dict(
        deepreduce="index", index="bloom", bloom_blocked="mod",
        compress_ratio=ratio, fpr=0.01, min_compress_size=100,
        memory="residual", decode_strategy="loop",
    )
    arm_cfgs = {
        "flat": DeepReduceConfig(bucket_bytes=bucket_bytes, **codec_kw),
        "stream-flat": DeepReduceConfig(
            bucket_bytes=bucket_bytes, stream_exchange=True, **codec_kw
        ),
        "barrier-hier": DeepReduceConfig(
            bucket_bytes=bucket_bytes, hier=True, **codec_kw
        ),
        "stream-hier": DeepReduceConfig(
            bucket_bytes=bucket_bytes, stream_exchange=True, hier=True,
            **codec_kw
        ),
    }
    rng = np.random.default_rng(0)
    params = {
        n: jnp.asarray(rng.normal(size=sz).astype(np.float32))
        for n, sz in census.items()
    }
    batch_w = {
        n: jnp.asarray(
            (rng.normal(size=(8, sz)) * rng.random((8, sz)) ** 2).astype(
                np.float32
            )
        )
        for n, sz in census.items()
    }
    res_w = tmap(lambda b: jnp.zeros_like(b), batch_w)
    flat_mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    hier_mesh = make_hybrid_mesh(2, 4)

    def loss_fn(p, batch_stats, batch):
        # linear-in-params probe: each leaf's cotangent is its batch row,
        # so the hooks see ordinary per-worker gradients
        loss = sum(jnp.sum(pv * batch[n]) for n, pv in p.items())
        return loss, batch_stats

    measured = {}
    for name, cfg in arm_cfgs.items():
        hier = cfg.hier
        if hier:
            ex = HierarchicalExchanger(
                tmap(lambda pv: jax.ShapeDtypeStruct(pv.shape, pv.dtype),
                     params),
                cfg, num_slices=2, per_slice=4,
            )
            mesh, spec = hier_mesh, P(("dcn", "ici"))
        else:
            ex = GradientExchanger(
                tmap(lambda pv: jax.ShapeDtypeStruct(pv.shape, pv.dtype),
                     params),
                cfg, axis_name="data", num_workers=8,
            )
            mesh, spec = flat_mesh, P("data")
        if cfg.stream_exchange:
            stream = StreamingExchange(ex)

            def spmd(p, b, res, step, _s=stream):
                b0 = tmap(lambda x: x[0], b)
                res0 = tmap(lambda r: r[0], res)
                _, _, agg, new_res, _ = _s.value_and_grad_exchange(
                    loss_fn, p, {}, b0, res0, step=step
                )
                return (
                    tmap(lambda x: x[None], agg),
                    tmap(lambda r: r[None], new_res),
                )
        else:

            def spmd(p, b, res, step, _ex=ex):
                b0 = tmap(lambda x: x[0], b)
                res0 = tmap(lambda r: r[0], res)
                grads = jax.grad(
                    lambda pp: loss_fn(pp, {}, b0)[0]
                )(p)
                agg, new_res, _ = _ex.exchange(grads, res0, step=step)
                return (
                    tmap(lambda x: x[None], agg),
                    tmap(lambda r: r[None], new_res),
                )

        fn = jax.jit(
            shard_map(
                spmd, mesh=mesh, in_specs=(P(), spec, spec, P()),
                out_specs=(spec, spec), check_vma=False,
            )
        )
        step0 = jnp.zeros((), jnp.int32)
        _progress(f"compose-sweep: compiling {name}")
        with _span(f"bench/compose-sweep/compile/{name}"):
            _sync(fn(params, batch_w, res_w, step0))
        _progress(f"compose-sweep: timing {name}")
        with _span(f"bench/compose-sweep/time/{name}"):
            wall = _timeit(fn, params, batch_w, res_w, step0,
                           iters=2, reps=3)
        measured[name] = {
            "wall_s": round(wall, 4),
            "compute_s_per_worker": round(wall / 8, 4),
        }
        _progress(f"compose-sweep: {name} wall={wall:.4f}s")

    # -- modeled pricing grid at the deployment shape: the composed model
    # against min(parents) over {ratio} x {hideable compute} --
    anchor = measured["stream-hier"]["compute_s_per_worker"]
    ratios = (0.02, 0.05, 0.10)
    points = []
    wins = 0
    for r in ratios:
        m = {
            "payload_bytes": 8.0 * max(1, int(d * r)),
            "t_encode_s": 0.0, "t_decode_s": 0.0,
        }
        for ct in (0.0, anchor, 4.0 * anchor):
            flat_t = cm.fused_step_time(m, W)
            stream_flat_t = cm.overlapped_step_time(m, W, compute_time=ct)
            barrier_hier_t = cm.hier_step_time(
                "dense", "bucketed", d, n_slices, per_slice, r
            )
            composed_t = cm.stream_hier_step_time(
                "bucketed", d, n_slices, per_slice, r, compute_time=ct
            )
            le_parents = bool(
                composed_t <= min(stream_flat_t, barrier_hier_t) + 1e-12
            )
            wins += le_parents
            points.append({
                "ratio": r,
                "compute_time_s": round(ct, 4),
                "flat_s": round(flat_t, 4),
                "stream_flat_s": round(stream_flat_t, 4),
                "barrier_hier_s": round(barrier_hier_t, 4),
                "composed_s": round(composed_t, 4),
                "composed_le_min_parents": le_parents,
            })
    plan = cm.select_hier_plan(
        d, n_slices, per_slice, ratio, stream=True, compute_time=anchor,
        dcn_legs=("fused", "bucketed"),
    )
    return {
        "metric": "composed_stream_hier_step_time_vs_parents",
        "unit": "s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=["points", "overlap_aware_plan"],
            measured=["measured_virtual_mesh"],
        ),
        "detail": {
            "model": "stackoverflow_lstm" if not quick else "quick",
            "d": d,
            "ratio": ratio,
            "n_slices": n_slices,
            "per_slice": per_slice,
            "census_elements": int(sum(census.values())),
            "bucket_bytes": bucket_bytes,
            "bw_dcn_bytes_per_s": cm.BW_100MBPS,
            "bw_ici_bytes_per_s": cm.BW_ICI_10GBPS,
            "cost_model": (
                "composed overlap model (costmodel.stream_hier_step_time: "
                "hideable compute shaves the combined ici+dcn wire) vs the "
                "streaming-flat (overlapped_step_time, W-wide gather) and "
                "barrier-hier (hier_step_time, nothing hidden) parents; "
                "execution measured on the 8-device CPU mesh"
            ),
            "measured_virtual_mesh": measured,
            "points": points,
            "headline": {
                "composed_le_min_parents": f"{wins}/{len(points)}",
                "grid_points": len(points),
            },
            "overlap_aware_plan": {
                "ici": plan["ici"],
                "dcn": plan["dcn"],
                "modeled_step_s": round(plan["modeled_step_s"], 4),
                "compute_time_s": round(anchor, 4),
            },
        },
    }


def fed_sweep(quick: bool = False, workers: int = 8) -> dict:
    """The federated serving sweep arm (`--fed-sweep`): the client-sharded
    `fedsim` round on the virtual 8-way CPU mesh, swept over cohort sizes
    against a fixed 10^5-scale population — the ROADMAP's clients/sec
    serving bench. Each arm builds the full round program (in-step
    stratified sampling, vmapped local SGD + real TensorCodec uplinks with
    per-client EF against the device-sharded residual bank, ONE psum), runs
    one compile round plus timed rounds, and reports measured clients/sec
    next to the 100 Mbps cost-model pricing (`costmodel.fed_round_time`) —
    CPU wall time measures the simulator's serving rate; the model prices
    what the same uplink volume costs a real scarce-link deployment."""
    import jax
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem
    from deepreduce_tpu.utils import enable_compile_cache

    enable_compile_cache()
    cm = _costmodel()
    population = 1 << 17 if not quick else 1 << 12  # 131072 clients
    cohorts = (1024, 4096, 16384) if not quick else (256, 512)
    dim, batch, local_steps = 256, 4, 2
    chunk = 128 if not quick else 32  # divides every per-worker cohort
    rounds = 4  # 1 compile + 3 timed
    mesh = Mesh(np.array(jax.devices()[:workers]), ("data",))
    params0, data_fn, loss_fn = synthetic_linear_problem(dim, batch, local_steps)
    arms = {}
    for C in cohorts:
        cfg = DeepReduceConfig(
            deepreduce="index", index="bloom", bloom_blocked="mod",
            compress_ratio=0.25, fpr=0.01, memory="residual",
            min_compress_size=8,
            fed=True, fed_num_clients=population, fed_clients_per_round=C,
            fed_local_steps=local_steps,
        )
        fed = cfg.fed_config()
        fs = FedSim(
            loss_fn, cfg, fed, optax.sgd(0.1), data_fn,
            mesh=mesh, client_chunk=chunk,
        )
        _progress(f"fed-sweep: C={C} (pop {population}): compiling round")
        with _span(f"bench/fed-sweep/compile/C{C}"):
            state = fs.init(params0)
            key = jax.random.PRNGKey(0)
            state, m = fs.step(state, jax.random.fold_in(key, 0))
        _progress(f"fed-sweep: C={C}: timing {rounds - 1} rounds")
        with _span(f"bench/fed-sweep/time/C{C}"):
            for r in range(1, rounds):
                state, m = fs.step(state, jax.random.fold_in(key, r))
        summ = fs.summary(state)
        up_round = float(m["uplink_bytes"])
        up_client = up_round / max(float(m["clients"]), 1.0)
        modeled_t = cm.fed_round_time(up_client, C)
        arms[f"C{C}"] = {
            "clients_per_round": C,
            "measured_round_s": round(summ["round_time_s"], 4),
            "measured_clients_per_sec": round(summ["clients_per_sec"], 1),
            "uplink_bytes_per_round": round(up_round, 1),
            "uplink_bytes_per_client": round(up_client, 1),
            "downlink_bytes": round(float(m["downlink_bytes"]), 1),
            "rel_volume": round(float(m["rel_volume"]), 4),
            "modeled_100mbps_round_s": round(modeled_t, 4),
            # the modeled rate is a cost-model OUTPUT, recorded raw — any
            # clamping or rounding is display-side only (a rounded record
            # silently floors small-cohort arms and poisons downstream
            # ratio computations against the measured series)
            "modeled_100mbps_clients_per_sec": cm.fed_clients_per_sec(
                up_client, C
            ),
        }
        _progress(
            f"fed-sweep: C={C}: {arms[f'C{C}']['measured_clients_per_sec']} "
            "clients/s measured"
        )
    best = max(arms, key=lambda k: arms[k]["measured_clients_per_sec"])
    return {
        "metric": "fedsim_serving_clients_per_sec",
        "value": arms[best]["measured_clients_per_sec"],
        "unit": "clients/s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=[
                "cohorts.*.modeled_100mbps_round_s",
                "cohorts.*.modeled_100mbps_clients_per_sec",
            ],
            measured=[
                "cohorts.*.measured_round_s",
                "cohorts.*.measured_clients_per_sec",
                "cohorts.*.uplink_bytes_per_round",
                "cohorts.*.downlink_bytes",
            ],
        ),
        "detail": {
            "population": population,
            "dim": dim,
            "batch": batch,
            "local_steps": local_steps,
            "workers": workers,
            "client_chunk": chunk,
            "codec": "topk 25% + mod-blocked bloom, per-client EF residual bank",
            "bw_bytes_per_s": cm.BW_100MBPS,
            "cost_model": (
                "server-ingest-serialized uplink (costmodel.fed_round_time); "
                "simulation measured on the 8-way virtual CPU mesh"
            ),
            "best_cohort": best,
            "cohorts": arms,
        },
    }


def fed_async_sweep(quick: bool = False, workers: int = 8) -> dict:
    """The asynchronous buffered serving arm (`--fed-async-sweep`): the
    fedsim async tick at the SAME population/cohort geometry as the
    committed synchronous headline (BENCH_FED_r13.json: 8344 clients/s at
    C=16384 against a 131072-client population), swept over the buffered
    apply threshold K and the staleness exponent alpha under a 3-level
    deterministic latency distribution. Two throughput levers separate the
    stream from the round: the async tick donates its carried state (the
    synchronous driver's functional copy of the [num_clients, ...]
    residual bank is the dominant fixed cost per round at this population)
    and `stream()` dispatches ticks back-to-back without per-tick host
    syncs. A synchronous arm is re-measured in the same process for an
    apples-to-apples floor, and every async arm reports its final teacher
    error next to the sync arm's — the convergence band the throughput
    claim is conditioned on."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.fedsim.round import parse_latency
    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem
    from deepreduce_tpu.utils import enable_compile_cache

    enable_compile_cache()
    cm = _costmodel()
    population = 1 << 17 if not quick else 1 << 12
    C = 16384 if not quick else 256
    dim, batch, local_steps = 256, 4, 2
    chunk = 128 if not quick else 32
    ticks = 6 if not quick else 3  # timed ticks after the 1 compile tick
    latency = "0.5,0.3,0.2"
    probs = parse_latency(latency)
    ks = (C // 2, C, 2 * C)
    alphas = (0.0, 0.5, 1.0)
    mesh = Mesh(np.array(jax.devices()[:workers]), ("data",))
    params0, data_fn, loss_fn = synthetic_linear_problem(dim, batch, local_steps)
    w_true = jax.random.normal(jax.random.PRNGKey(42), (dim,))

    def _w_err(state) -> float:
        return float(
            jnp.linalg.norm(state.params["w"] - w_true) / jnp.linalg.norm(w_true)
        )

    base = dict(
        deepreduce="index", index="bloom", bloom_blocked="mod",
        compress_ratio=0.25, fpr=0.01, memory="residual",
        min_compress_size=8,
        fed=True, fed_num_clients=population, fed_clients_per_round=C,
        fed_local_steps=local_steps,
    )
    key = jax.random.PRNGKey(0)

    # synchronous floor, re-measured in-process (the committed r13 number
    # is a different run of the same geometry; the claim is made against
    # BOTH)
    cfg_s = DeepReduceConfig(**base)
    fs_s = FedSim(
        loss_fn, cfg_s, cfg_s.fed_config(), optax.sgd(0.1), data_fn,
        mesh=mesh, client_chunk=chunk,
    )
    _progress(f"fed-async-sweep: sync floor C={C}: compiling round")
    with _span("bench/fed-async-sweep/sync"):
        st = fs_s.init(params0)
        # two warmup rounds (both sharding variants compile), then `ticks`
        # timed rounds — the same tick budget every async arm gets
        for r in range(ticks + 2):
            st, m = fs_s.step(st, jax.random.fold_in(key, r))
    sync_times = fs_s._round_times[-ticks:]
    sync_rate = C * ticks / sum(sync_times)
    sync_err = _w_err(st)
    up_client = float(m["uplink_bytes"]) / max(float(m["clients"]), 1.0)
    _progress(
        f"fed-async-sweep: sync floor {round(sync_rate, 1)} clients/s, "
        f"w_err {round(sync_err, 4)}"
    )

    arms = {}

    def _async_arm(k_thresh: int, alpha: float):
        cfg = DeepReduceConfig(
            fed_async=True, fed_async_k=k_thresh, fed_async_alpha=alpha,
            fed_async_latency=latency, **base,
        )
        fs = FedSim(
            loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
            mesh=mesh, client_chunk=chunk,
        )
        label = f"K{k_thresh}_a{alpha}"
        _progress(f"fed-async-sweep: {label}: compiling tick")
        with _span(f"bench/fed-async-sweep/{label}"):
            state = fs.init(params0)
            # two warmup ticks: the first compiles for the uncommitted
            # init-state shardings, the second for the round outputs'
            # committed shardings — the timed stream then runs all-cached
            state, _ = fs.step(state, jax.random.fold_in(key, 0))
            state, _ = fs.step(state, jax.random.fold_in(key, 1))
            state, hist, wall = fs.stream(state, key, ticks)
        served = sum(float(h["clients"]) for h in hist)
        applies = sum(float(h["applied"]) for h in hist)
        rate = served / wall
        arms[label] = {
            "fed_async_k": k_thresh,
            "fed_async_alpha": alpha,
            "measured_wall_s": round(wall, 4),
            "measured_clients_per_sec": round(rate, 1),
            "applies": applies,
            "staleness_mean": round(
                sum(float(h["staleness_mean"]) for h in hist) / len(hist), 4
            ),
            "staleness_max": max(float(h["staleness_max"]) for h in hist),
            "final_w_rel_err": round(_w_err(state), 4),
            "modeled_100mbps_clients_per_sec": cm.fed_async_clients_per_sec(
                up_client, k_thresh, latency_probs=probs,
                overlap_depth=len(probs),
            ),
        }
        _progress(
            f"fed-async-sweep: {label}: "
            f"{arms[label]['measured_clients_per_sec']} clients/s, "
            f"w_err {arms[label]['final_w_rel_err']}"
        )

    for k_thresh in ks:  # K sweep at the middle alpha (K is traced:
        _async_arm(k_thresh, alphas[1])  # the three arms share one program)
    for alpha in (alphas[0], alphas[2]):  # alpha sweep at K == C
        _async_arm(C, alpha)

    # the convergence band the throughput headline is conditioned on:
    # an arm only qualifies for the headline if its final teacher error is
    # within +loss_band of the synchronous arm's after the same tick budget
    loss_band = 0.15
    within = {
        a: bool(arms[a]["final_w_rel_err"] <= sync_err + loss_band)
        for a in arms
    }
    qualified = [a for a in arms if within[a]] or list(arms)
    best = max(qualified, key=lambda a: arms[a]["measured_clients_per_sec"])
    return {
        "metric": "fedsim_async_serving_clients_per_sec",
        "value": arms[best]["measured_clients_per_sec"],
        "unit": "clients/s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=["arms.*.modeled_100mbps_clients_per_sec"],
            measured=[
                "arms.*.measured_wall_s",
                "arms.*.measured_clients_per_sec",
                "arms.*.final_w_rel_err",
                "sync.measured_clients_per_sec",
                "sync.final_w_rel_err",
            ],
        ),
        "detail": {
            "population": population,
            "clients_per_round": C,
            "dim": dim,
            "batch": batch,
            "local_steps": local_steps,
            "workers": workers,
            "client_chunk": chunk,
            "ticks": ticks,
            "fed_async_latency": latency,
            "codec": "topk 25% + mod-blocked bloom, per-client EF residual bank",
            "bw_bytes_per_s": cm.BW_100MBPS,
            "cost_model": (
                "buffered-ingest max(wire, compute) "
                "(costmodel.fed_async_apply_time); simulation measured on "
                "the 8-way virtual CPU mesh"
            ),
            "levers": (
                "donated carried state (no functional residual-bank copy) "
                "+ stream() host-pipelined dispatch (no per-tick sync)"
            ),
            "sync": {
                "measured_clients_per_sec": round(sync_rate, 1),
                "final_w_rel_err": round(sync_err, 4),
                "r13_reference_clients_per_sec": 8344.0,
            },
            "best_arm": best,
            "async_beats_sync": bool(
                arms[best]["measured_clients_per_sec"] > sync_rate
            ),
            "loss_band": loss_band,
            "within_loss_band": within,
            "arms": arms,
        },
    }


def fed_mt_sweep(quick: bool = False, workers: int = 8) -> dict:
    """The multi-tenant serving arm (`--fed-mt-sweep`): T independent
    async populations through the ONE vmapped jitted tick, at the same
    population/cohort geometry as the committed async headline
    (BENCH_FEDASYNC_r20.json: 12437.8 clients/s at C=16384 against a
    131072-client population). Two claims, stamped separately:

    - MODELED (the headline): on the serving cost model with per-tenant
      ingest links and client compute hidden behind the 3-deep overlap
      ring, the aggregate service rate is linear in T — the tick's
      collective count is independent of T (the fedsim:multi-tenant audit
      pins exactly one psum at T=2 and T=4), so consolidating T fleets
      onto one server multiplies throughput without multiplying
      collectives. T=1 collapses EXACTLY onto fed_async_clients_per_sec.
    - MEASURED (the evidence): the 8-way virtual CPU mesh simulates every
      tenant's full client compute, so wall clock grows with T (the mesh
      has no compute headroom to amortize); what the measured arms
      demonstrate is correctness at scale — every tenant of every fleet
      size converges inside the same loss band as the single-tenant
      driver, through one compiled program per fleet."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.fedsim.round import parse_latency
    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem
    from deepreduce_tpu.utils import enable_compile_cache

    enable_compile_cache()
    cm = _costmodel()
    population = 1 << 17 if not quick else 1 << 12
    C = 16384 if not quick else 256
    dim, batch, local_steps = 256, 4, 2
    chunk = 128 if not quick else 32
    ticks = 6 if not quick else 3
    latency = "0.5,0.3,0.2"
    probs = parse_latency(latency)
    tenant_counts = (1, 2, 4, 8) if not quick else (1, 2)
    # modeled client-side local-train latency: hidden behind the overlap
    # ring, it is what the per-tenant ingest links leave as the binding
    # resource (stamped modeled — the CPU arms simulate it instead)
    t_client_s = 1.0
    mesh = Mesh(np.array(jax.devices()[:workers]), ("data",))
    params0, data_fn, loss_fn = synthetic_linear_problem(dim, batch, local_steps)
    w_true = jax.random.normal(jax.random.PRNGKey(42), (dim,))

    base = dict(
        deepreduce="index", index="bloom", bloom_blocked="mod",
        compress_ratio=0.25, fpr=0.01, memory="residual",
        min_compress_size=8,
        fed=True, fed_num_clients=population, fed_clients_per_round=C,
        fed_local_steps=local_steps,
        fed_async=True, fed_async_k=C, fed_async_alpha=0.5,
        fed_async_latency=latency,
    )
    key = jax.random.PRNGKey(0)

    # single-tenant async floor, re-measured in-process
    cfg_1 = DeepReduceConfig(**base)
    fs_1 = FedSim(
        loss_fn, cfg_1, cfg_1.fed_config(), optax.sgd(0.1), data_fn,
        mesh=mesh, client_chunk=chunk,
    )
    _progress(f"fed-mt-sweep: single-tenant floor C={C}: compiling tick")
    with _span("bench/fed-mt-sweep/floor"):
        st = fs_1.init(params0)
        st, _ = fs_1.step(st, jax.random.fold_in(key, 0))
        st, m = fs_1.step(st, jax.random.fold_in(key, 1))
        st, hist, wall = fs_1.stream(st, key, ticks)
    floor_rate = sum(float(h["clients"]) for h in hist) / wall
    floor_err = float(
        jnp.linalg.norm(st.params["w"] - w_true) / jnp.linalg.norm(w_true)
    )
    up_client = float(m["uplink_bytes"]) / max(float(m["clients"]), 1.0)
    _progress(
        f"fed-mt-sweep: floor {round(floor_rate, 1)} clients/s, "
        f"w_err {round(floor_err, 4)}"
    )

    loss_band = 0.15
    arms = {}
    for T in tenant_counts:
        cfg = DeepReduceConfig(fed_tenants=T, **base)
        fs = FedSim(
            loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
            mesh=mesh, client_chunk=chunk,
        )
        label = f"T{T}"
        _progress(f"fed-mt-sweep: {label}: compiling tick")
        with _span(f"bench/fed-mt-sweep/{label}"):
            state = fs.init(params0)
            state, _ = fs.step(state, jax.random.fold_in(key, 0))
            state, _ = fs.step(state, jax.random.fold_in(key, 1))
            state, hist, wall = fs.stream(state, key, ticks)
        served = sum(float(np.sum(np.asarray(h["clients"]))) for h in hist)
        agg = served / wall
        errs = [
            float(
                jnp.linalg.norm(state.params["w"][t] - w_true)
                / jnp.linalg.norm(w_true)
            )
            for t in range(T)
        ]
        arms[label] = {
            "tenants": T,
            "measured_wall_s": round(wall, 4),
            "measured_aggregate_clients_per_sec": round(agg, 1),
            "measured_per_tenant_clients_per_sec": round(agg / T, 1),
            "w_rel_err_per_tenant": [round(e, 4) for e in errs],
            "all_tenants_within_loss_band": bool(
                max(errs) <= floor_err + loss_band
            ),
            "modeled_aggregate_clients_per_sec": cm.fed_mt_clients_per_sec(
                T, up_client, C, asynchronous=True, t_client_s=t_client_s,
                server_links=T, overlap_depth=len(probs),
                latency_probs=probs,
            ),
        }
        _progress(
            f"fed-mt-sweep: {label}: measured "
            f"{arms[label]['measured_aggregate_clients_per_sec']} agg "
            f"clients/s, modeled "
            f"{round(arms[label]['modeled_aggregate_clients_per_sec'], 1)}, "
            f"max w_err {round(max(errs), 4)}"
        )

    modeled_1 = arms["T1"]["modeled_aggregate_clients_per_sec"]
    # T=1 degeneracy of the cost model, checked in-record: the MT model at
    # T=1 IS the async model (same float expressions)
    modeled_1_ref = cm.fed_async_clients_per_sec(
        up_client, C, t_client_s=t_client_s, overlap_depth=len(probs),
        latency_probs=probs,
    )
    headline_T = "T4" if "T4" in arms else max(
        arms, key=lambda a: arms[a]["tenants"]
    )
    speedup = arms[headline_T]["modeled_aggregate_clients_per_sec"] / modeled_1
    return {
        "metric": "fedsim_mt_aggregate_clients_per_sec",
        "value": round(arms[headline_T]["modeled_aggregate_clients_per_sec"], 1),
        "unit": "clients/s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=[
                "arms.*.modeled_aggregate_clients_per_sec",
                "aggregate_speedup_vs_single_tenant",
                "t_client_s",
            ],
            measured=[
                "arms.*.measured_wall_s",
                "arms.*.measured_aggregate_clients_per_sec",
                "arms.*.w_rel_err_per_tenant",
                "floor.measured_clients_per_sec",
                "floor.final_w_rel_err",
                "uplink_bytes_per_client",
            ],
        ),
        "detail": {
            "population_per_tenant": population,
            "clients_per_round_per_tenant": C,
            "dim": dim,
            "batch": batch,
            "local_steps": local_steps,
            "workers": workers,
            "client_chunk": chunk,
            "ticks": ticks,
            "fed_async_k": C,
            "fed_async_alpha": 0.5,
            "fed_async_latency": latency,
            "t_client_s": t_client_s,
            "uplink_bytes_per_client": round(up_client, 1),
            "codec": "topk 25% + mod-blocked bloom, per-client EF residual bank",
            "bw_bytes_per_s": cm.BW_100MBPS,
            "cost_model": (
                "multi-tenant buffered ingest max(wire, compute) with "
                "per-tenant ingest links (costmodel.fed_mt_clients_per_sec); "
                "client compute hidden behind the overlap ring is the "
                "binding resource, so aggregate scales linearly in T"
            ),
            "collective_contract": (
                "one psum per tick at every T (fedsim:multi-tenant audit, "
                "ANALYSIS.json); psum operand bytes 4*(T*(n_elems+3)+4) — "
                "linear in T, collective count independent of T"
            ),
            "measured_caveat": (
                "the 8-way virtual CPU mesh simulates every tenant's full "
                "client compute, so measured wall grows with T; the "
                "measured arms are the convergence evidence, the modeled "
                "arms the serving-rate claim"
            ),
            "floor": {
                "measured_clients_per_sec": round(floor_rate, 1),
                "final_w_rel_err": round(floor_err, 4),
                "r20_reference_clients_per_sec": 12437.8,
            },
            "modeled_t1_equals_fed_async_model": bool(
                modeled_1 == modeled_1_ref
            ),
            "aggregate_speedup_vs_single_tenant": round(speedup, 2),
            "headline_arm": headline_T,
            "loss_band": loss_band,
            "arms": arms,
        },
    }


def pop_sweep(quick: bool = False, workers: int = 8) -> dict:
    """The heterogeneous-population serving arm (`--pop-sweep`): three
    client populations through the async buffered tick at the SAME
    population/cohort geometry as the committed async headline
    (BENCH_FEDASYNC_r20.json: C=16384 against a 131072-client
    population) — uniform (the degenerate single-class spec the bitwise
    degeneracy contract pins to the population-free program), mild
    non-IID label skew, and a pathological split (near-one-hot Dirichlet
    label mixtures + per-class latency rows + a 2x compute class). Every
    arm records its final teacher error against the uniform arm's and
    whether it stays inside the loss band — the convergence-band
    evidence the heterogeneity claim is conditioned on — plus the exact
    on-device per-class participation shares (the f32[K] histogram that
    rides the one fused psum) next to the spec's analytic population
    weights."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.fedsim.round import parse_class_latency, parse_latency
    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem
    from deepreduce_tpu.population.spec import PopulationSpec
    from deepreduce_tpu.utils import enable_compile_cache

    enable_compile_cache()
    cm = _costmodel()
    population = 1 << 17 if not quick else 1 << 12
    C = 16384 if not quick else 256
    dim, batch, local_steps = 256, 4, 2
    chunk = 128 if not quick else 32
    ticks = 6 if not quick else 3
    latency = "0.5,0.3,0.2"
    probs = parse_latency(latency)
    # modeled client-side local-train latency (hidden behind the overlap
    # ring; what the compute classes stretch) — stamped modeled
    t_client_s = 1.0
    mesh = Mesh(np.array(jax.devices()[:workers]), ("data",))
    params0, data_fn, loss_fn = synthetic_linear_problem(dim, batch, local_steps)
    w_true = jax.random.normal(jax.random.PRNGKey(42), (dim,))

    specs = {
        "uniform": '{"version": 1, "classes": [{"name": "uniform"}]}',
        # label_shift is kept small: the per-sample mean shift adds a
        # rank-one s*1 component to every feature row, so the data
        # covariance's top eigenvalue grows as ~1 + s^2*dim — at dim=256
        # and lr 0.1 the default shift of 1.0 would put SGD past its
        # stability limit on purpose-built-divergent data rather than
        # measuring heterogeneity
        "mild_skew": (
            '{"version": 1, "num_labels": 8, "label_shift": 0.05, '
            '"classes": ['
            '{"name": "bulk", "weight": 3.0, "data_alpha": 4.0}, '
            '{"name": "tail", "weight": 1.0, "data_alpha": 1.0, '
            '"data_bias": 2.0}]}'
        ),
        "pathological_skew": (
            '{"version": 1, "num_labels": 8, "label_shift": 0.05, '
            '"classes": ['
            '{"name": "onehot", "weight": 1.0, "data_alpha": 0.05, '
            '"data_bias": 8.0, "latency": "0.2,0.4,0.4", '
            '"local_steps_mult": 2.0}, '
            '{"name": "fast", "weight": 1.0, "data_alpha": 0.5, '
            '"latency": "0.8,0.15,0.05"}]}'
        ),
    }

    base = dict(
        deepreduce="index", index="bloom", bloom_blocked="mod",
        compress_ratio=0.25, fpr=0.01, memory="residual",
        min_compress_size=8,
        fed=True, fed_num_clients=population, fed_clients_per_round=C,
        fed_local_steps=local_steps,
        fed_async=True, fed_async_k=C, fed_async_alpha=0.5,
        fed_async_latency=latency,
    )
    key = jax.random.PRNGKey(0)
    loss_band = 0.15
    arms = {}
    up_client = 0.0
    for label, spec_json in specs.items():
        spec = PopulationSpec.load_any(spec_json)
        cfg = DeepReduceConfig(pop_spec=spec_json, **base)
        fs = FedSim(
            loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
            mesh=mesh, client_chunk=chunk,
        )
        _progress(f"pop-sweep: {label}: compiling tick")
        with _span(f"bench/pop-sweep/{label}"):
            state = fs.init(params0)
            state, _ = fs.step(state, jax.random.fold_in(key, 0))
            state, m = fs.step(state, jax.random.fold_in(key, 1))
            state, hist, wall = fs.stream(state, key, ticks)
        served = sum(float(h["clients"]) for h in hist)
        rate = served / wall
        err = float(
            jnp.linalg.norm(state.params["w"] - w_true)
            / jnp.linalg.norm(w_true)
        )
        if label == "uniform":
            up_client = float(m["uplink_bytes"]) / max(float(m["clients"]), 1.0)
        pop_tot = np.zeros(spec.num_classes)
        for h in hist:
            pop_tot += np.asarray(h["pop_hist"], dtype=np.float64)
        shares = (pop_tot / max(float(pop_tot.sum()), 1.0)).tolist()
        rows = (
            parse_class_latency([c.latency for c in spec.classes], latency)
            if spec.latency_on
            else None
        )
        arms[label] = {
            "pop_spec": json.loads(spec_json),
            "num_classes": spec.num_classes,
            "measured_wall_s": round(wall, 4),
            "measured_clients_per_sec": round(rate, 1),
            "final_w_rel_err": round(err, 4),
            "pop_shares_measured": [round(s, 4) for s in shares],
            "pop_weights_spec": [round(w, 4) for w in spec.weights],
            "staleness_mean": round(
                sum(float(h["staleness_mean"]) for h in hist) / len(hist), 4
            ),
            "modeled_100mbps_clients_per_sec": cm.fed_pop_async_clients_per_sec(
                up_client, C, weights=spec.weights,
                local_steps_mults=spec.local_steps_mults,
                class_latency_rows=rows, t_client_s=t_client_s,
                overlap_depth=len(probs), latency_probs=probs,
            ),
        }
        _progress(
            f"pop-sweep: {label}: "
            f"{arms[label]['measured_clients_per_sec']} clients/s, "
            f"w_err {arms[label]['final_w_rel_err']}, "
            f"shares {arms[label]['pop_shares_measured']}"
        )

    uni_err = arms["uniform"]["final_w_rel_err"]
    within = {
        a: bool(arms[a]["final_w_rel_err"] <= uni_err + loss_band)
        for a in arms
    }
    return {
        "metric": "fedsim_pop_serving_clients_per_sec",
        "value": arms["pathological_skew"]["measured_clients_per_sec"],
        "unit": "clients/s",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=[
                "arms.*.modeled_100mbps_clients_per_sec",
                "t_client_s",
            ],
            measured=[
                "arms.*.measured_wall_s",
                "arms.*.measured_clients_per_sec",
                "arms.*.final_w_rel_err",
                "arms.*.pop_shares_measured",
                "arms.*.staleness_mean",
                "uplink_bytes_per_client",
            ],
        ),
        "detail": {
            "population": population,
            "clients_per_round": C,
            "dim": dim,
            "batch": batch,
            "local_steps": local_steps,
            "workers": workers,
            "client_chunk": chunk,
            "ticks": ticks,
            "fed_async_k": C,
            "fed_async_alpha": 0.5,
            "fed_async_latency": latency,
            "t_client_s": t_client_s,
            "uplink_bytes_per_client": round(up_client, 1),
            "codec": "topk 25% + mod-blocked bloom, per-client EF residual bank",
            "bw_bytes_per_s": cm.BW_100MBPS,
            "cost_model": (
                "population-aware buffered ingest max(wire, compute) with "
                "the class-weighted compute stretch and mixture staleness "
                "(costmodel.fed_pop_async_clients_per_sec); uniform "
                "collapses exactly onto fed_async_clients_per_sec"
            ),
            "collective_contract": (
                "one psum per tick on every arm; the exact K-class "
                "participation histogram rides the fused tuple — operand "
                "bytes 4*(n+7+D+K), +D more with per-class latency rows "
                "(fedsim:population* audits, ANALYSIS.json)"
            ),
            "baseline_arm": "uniform",
            "loss_band": loss_band,
            "within_loss_band": within,
            "all_arms_within_loss_band": bool(all(within.values())),
            "arms": arms,
        },
    }


def ctrl_sweep(quick: bool = False, workers: int = 8) -> dict:
    """The adaptive-controller convergence arm (`--ctrl-sweep`): one fixed
    run per ladder rung vs one adaptive run on the same deterministic
    synthetic task the ctrl check trains (identical data, seeds and step
    count, so the arms differ ONLY in how compress_ratio is driven). Each
    arm reports its converged loss and its average wire volume per step
    (from the on-device accumulators), priced on the 100 Mbps cost model;
    the adaptive arm adds its decision trail. The committed record
    (BENCH_CTRL_r14.json) is the paper-trajectory evidence that the
    controller matches the best fixed configuration's loss while moving
    fewer bytes on average — it starts at the most expensive rung and
    settles on the cheapest rung whose fidelity stays in the err_cos
    band."""
    import pathlib
    import tempfile

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.controller import DecisionLog, Ladder
    from deepreduce_tpu.controller.__main__ import _build_cfg, _run_train

    cm = _costmodel()
    # long enough that every rung's loss has plateaued — the matched-loss
    # regime the adaptive-vs-fixed wire claim is stated in
    steps = 160 if not quick else 24
    tail = 10 if not quick else 4
    ladder_spec = "0.01,0.02,0.05"
    ladder = Ladder.parse(ladder_spec)
    base = dict(
        deepreduce="index", index="bloom", fpr=0.01, memory="residual",
        min_compress_size=100, telemetry=True, telemetry_every=5,
    )

    def _arm(losses, trainer):
        summ = trainer.telemetry_summary()
        n = max(float(summ["steps"]), 1.0)
        wire = float(summ["cumulative_total_bits"]) / 8.0 / n
        return {
            "final_loss": round(float(np.mean(losses[-tail:])), 6),
            "best_loss": round(float(min(losses)), 6),
            "wire_bytes_per_step": round(wire, 1),
            "rel_volume": round(float(summ["rel_volume"]), 5),
            "compress_err_cos": round(float(summ["compress_err_cos"]), 4),
            "modeled_100mbps_exchange_s": round(
                cm.allgather_time(wire, workers), 6
            ),
        }

    arms = {}
    for i in range(len(ladder)):
        r = ladder[i].ratio
        cfg = DeepReduceConfig(compress_ratio=r, **base)
        _progress(f"ctrl-sweep: fixed ratio={r}: {steps} steps")
        with _span(f"bench/ctrl-sweep/fixed/{r}"):
            losses, trainer, _ = _run_train(cfg, steps=steps, num_workers=workers)
        arms[f"fixed_{r}"] = {"compress_ratio": r, **_arm(losses, trainer)}

    acfg = _build_cfg()
    _progress(f"ctrl-sweep: adaptive (ladder {ladder_spec}): {steps} steps")
    with tempfile.TemporaryDirectory(prefix="drtpu_ctrl_sweep_") as td:
        log = pathlib.Path(td) / "decisions.jsonl"
        with _span("bench/ctrl-sweep/adaptive"):
            losses, trainer, _ = _run_train(
                acfg, steps=steps, num_workers=workers, log_path=log
            )
        decisions = DecisionLog.read(log)
    ctrl = trainer.controller
    adaptive = {
        "start_ratio": acfg.compress_ratio,
        **_arm(losses, trainer),
        "effective_ratio": round(ctrl.effective_ratio(), 5),
        "switches": int(ctrl.switches),
        "windows": int(ctrl.windows),
        "visited_indices": list(trainer.visited_ladder_indices),
        "trail": [
            f"{d['step']}: {d['old_index']}->{d['new_index']} "
            f"({d['trigger']}/{d['rationale']})"
            for d in decisions
            if d["switched"]
        ],
    }
    arms["adaptive"] = adaptive

    # the fixed arm the controller has to beat: best converged loss
    fixed = {k: v for k, v in arms.items() if k != "adaptive"}
    best = min(fixed, key=lambda k: fixed[k]["final_loss"])
    wire_ratio = adaptive["wire_bytes_per_step"] / max(
        fixed[best]["wire_bytes_per_step"], 1e-9
    )
    _progress(
        f"ctrl-sweep: adaptive {adaptive['final_loss']} loss @ "
        f"{adaptive['wire_bytes_per_step']} B/step vs best fixed [{best}] "
        f"{fixed[best]['final_loss']} @ {fixed[best]['wire_bytes_per_step']}"
    )
    return {
        "metric": "adaptive_ctrl_wire_vs_best_fixed",
        "value": round(wire_ratio, 4),
        "unit": "x (adaptive wire bytes/step over best fixed arm's)",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=["arms.*.modeled_100mbps_exchange_s"],
            measured=[
                "arms.*.final_loss", "arms.*.best_loss",
                "arms.*.wire_bytes_per_step", "arms.*.rel_volume",
                "arms.*.compress_err_cos",
            ],
        ),
        "detail": {
            "steps": steps,
            "workers": workers,
            "ladder": ladder_spec,
            "ctrl_target_err_cos": acfg.ctrl_target_err_cos,
            "ctrl_headroom": acfg.ctrl_headroom,
            "ctrl_hysteresis": acfg.ctrl_hysteresis,
            "telemetry_every": acfg.telemetry_every,
            "task": "deterministic synthetic MLP (the ctrl-check train)",
            "best_fixed": best,
            "loss_gap_vs_best_fixed": round(
                adaptive["final_loss"] - fixed[best]["final_loss"], 6
            ),
            "arms": arms,
        },
    }


def calib_sweep(quick: bool = False, run: str = "TRACE_OVERLAP_r15") -> dict:
    """The self-calibrating cost-model arm (`--calib-sweep`): fit a
    MachineProfile from the committed tracking run (`costmodel.calibrate`
    over TRACE_OVERLAP_r15 — deterministic: the fit reads only recorded
    telemetry, so re-running this arm reproduces the record byte for
    byte), then re-run `select_hier_plan` at a sweep of deployment shapes
    under the fitted profile next to the static-constants pick.

    Each point prices BOTH picks under BOTH models, so the record shows
    not just *that* the calibrated planner disagrees but what the
    disagreement is worth on the machine the profile was fitted on. The
    flip-prone shape is the small-slice-count hierarchy (2x16): statically
    the fused DCN leg wins at n_slices=2 because its (W-1)-scaled
    allgather is cheap, but the fitted profile charges the measured encode
    seconds on exactly that leg (the only profile-sensitive row — the rs
    routes are wire-only, so a bandwidth rescale cannot reorder them) and
    the planner walks away from it. `telemetry compare --profile P
    --against BENCH_CALIB_*.json` replays these points from `detail.points`.
    """
    import pathlib

    cm = _costmodel()
    prof = cm.calibrate(pathlib.Path(__file__).parent / run)
    d = LSTM_D
    shapes = ((2, 16), (8, 4)) if not quick else ((2, 16),)
    ratios = (0.001, 0.01, 0.1)
    points = []
    disagreements = 0
    wins = 0
    for n_slices, per_slice in shapes:
        for ratio in ratios:
            static = cm.select_hier_plan(d, n_slices, per_slice, ratio)
            calib = cm.select_hier_plan(
                d, n_slices, per_slice, ratio, profile=prof
            )
            s_key = f"{static['ici']}+{static['dcn']}"
            c_key = f"{calib['ici']}+{calib['dcn']}"
            disagree = s_key != c_key
            win = calib["table"][c_key] < calib["table"][s_key]
            disagreements += int(disagree)
            wins += int(disagree and win)
            points.append(
                {
                    "d": d,
                    "ratio": ratio,
                    "n_slices": n_slices,
                    "per_slice": per_slice,
                    "static_pick": s_key,
                    "calibrated_pick": c_key,
                    # both picks under both models: rows are the pick,
                    # columns the model that priced it
                    "static_pick_static_s": round(static["table"][s_key], 4),
                    "static_pick_fitted_s": round(calib["table"][s_key], 4),
                    "calibrated_pick_static_s": round(static["table"][c_key], 4),
                    "calibrated_pick_fitted_s": round(calib["table"][c_key], 4),
                    "disagree": disagree,
                    "calibrated_wins_under_fitted": bool(win),
                    "speedup_under_fitted": round(
                        calib["table"][s_key] / calib["table"][c_key], 3
                    ),
                }
            )
            _progress(
                f"calib-sweep: {n_slices}x{per_slice} ratio={ratio:g}: "
                f"static {s_key} vs calibrated {c_key}"
                + (" (DISAGREE)" if disagree else "")
            )
    return {
        "metric": "calibrated_vs_static_hier_plan_picks",
        "value": disagreements,
        "unit": "pick disagreements across the sweep",
        "platform": "cpu",
        "provenance": _provenance(
            modeled=[
                "points.*.static_pick", "points.*.calibrated_pick",
                "points.*.static_pick_static_s",
                "points.*.static_pick_fitted_s",
                "points.*.calibrated_pick_static_s",
                "points.*.calibrated_pick_fitted_s",
            ],
            measured=["profile"],
            profile=prof,
        ),
        "detail": {
            "run": run,
            "d": d,
            "ratios": list(ratios),
            "shapes": [f"{n}x{p}" for n, p in shapes],
            "cost_model": (
                "select_hier_plan argmin, static constants vs the profile "
                "fitted by costmodel.calibrate from the committed tracking "
                "run's telemetry"
            ),
            "profile": prof.to_record(),
            "disagreements": disagreements,
            "calibrated_wins_under_fitted": wins,
            "points": points,
        },
    }


def main() -> None:
    if _trace_out_path():
        from deepreduce_tpu.telemetry import spans

        spans.configure(enabled=True, reset=True)
    if "--decode-sweep" in sys.argv:
        # standalone sweep mode: CPU-mesh only, one JSON record on stdout
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")
        quick = "--quick" in sys.argv
        d = LSTM_D if not quick else 500_000
        sweep = decode_strategy_sweep(d=d)
        import jax

        print(
            json.dumps(
                {
                    "metric": "fused_exchange_decode_strategy_step_time",
                    "unit": "s",
                    "platform": "cpu",
                    "provenance": _provenance(
                        modeled=[], measured=["strategies"]
                    ),
                    "detail": {
                        "model": "stackoverflow_lstm" if not quick else "quick",
                        "d": d,
                        "workers": 8,
                        "config": "drqsgd_bloom (topk 10%, bloom P0 fpr=0.02, qsgd)",
                        "strategies": sweep,
                    },
                }
            )
        )
        return
    if "--hier-sweep" in sys.argv:
        # standalone two-tier sweep mode: CPU-mesh only, one JSON record on
        # stdout (committed as BENCH_HIER_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")
        print(json.dumps(hier_sweep(quick="--quick" in sys.argv)))
        return
    if "--compose-sweep" in sys.argv:
        # standalone composed-legs sweep: CPU-mesh only, one JSON record on
        # stdout (committed as BENCH_COMPOSE_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu", device_count=8)
        print(json.dumps(compose_sweep(quick="--quick" in sys.argv)))
        return
    if "--fed-sweep" in sys.argv:
        # standalone federated serving sweep: CPU-mesh only, one JSON
        # record on stdout (committed as BENCH_FED_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu", device_count=8)
        print(json.dumps(fed_sweep(quick="--quick" in sys.argv)))
        return
    if "--fed-async-sweep" in sys.argv:
        # standalone asynchronous buffered serving sweep: CPU-mesh only,
        # one JSON record on stdout (committed as BENCH_FEDASYNC_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu", device_count=8)
        print(json.dumps(fed_async_sweep(quick="--quick" in sys.argv)))
        return
    if "--fed-mt-sweep" in sys.argv:
        # standalone multi-tenant serving sweep: CPU-mesh only, one JSON
        # record on stdout (committed as BENCH_FEDMT_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu", device_count=8)
        print(json.dumps(fed_mt_sweep(quick="--quick" in sys.argv)))
        return
    if "--pop-sweep" in sys.argv:
        # standalone heterogeneous-population serving sweep: CPU-mesh
        # only, one JSON record on stdout (committed as BENCH_POP_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu", device_count=8)
        print(json.dumps(pop_sweep(quick="--quick" in sys.argv)))
        return
    if "--ctrl-sweep" in sys.argv:
        # standalone adaptive-controller convergence arm: CPU-mesh only,
        # one JSON record on stdout (committed as BENCH_CTRL_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu", device_count=8)
        print(json.dumps(ctrl_sweep(quick="--quick" in sys.argv)))
        return
    if "--calib-sweep" in sys.argv:
        # standalone self-calibration arm: no mesh needed — the fit reads
        # committed telemetry and the pricing is closed-form (committed as
        # BENCH_CALIB_*.json). Platform still pinned: the package __init__
        # pulls in jax, which must not dial the device tunnel here.
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")
        print(json.dumps(calib_sweep(quick="--quick" in sys.argv)))
        return
    if "--rs-sweep" in sys.argv:
        # standalone in-collective sweep mode: CPU-mesh only, one JSON
        # record on stdout (committed as BENCH_INCOLL_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")
        print(json.dumps(rs_sweep(quick="--quick" in sys.argv)))
        return
    if "--oktopk-sweep" in sys.argv:
        # standalone Ok-Topk density x W grid mode: CPU-mesh only, one JSON
        # record on stdout (committed as BENCH_OKTOPK_*.json)
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")
        print(json.dumps(oktopk_sweep(quick="--quick" in sys.argv)))
        return
    if "--bucketed-sweep" in sys.argv:
        # standalone bucketed-exchange mode: CPU-mesh only, one JSON record
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")
        rec = _bucketed_subprocess(_bucket_bytes_arg())
        # r15: price the streaming schedule against the r09 pipelined one
        # on the same measured codec row (costmodel.overlapped_step_time)
        overlap = _overlap_model(rec, iters=3 if "--quick" in sys.argv else 7)
        print(
            json.dumps(
                {
                    "metric": "bucketed_exchange_speedup_vs_pertensor",
                    "value": rec.get("bucketed_speedup_vs_pertensor"),
                    "unit": "x",
                    "platform": "cpu",
                    "provenance": _provenance(
                        modeled=[
                            "overlap_model.t_allgather_s",
                            "overlap_model.t_serialized_s",
                            "overlap_model.t_pipelined_r09_s",
                            "overlap_model.t_streaming_full_overlap_s",
                            "overlap_model.curve",
                        ],
                        measured=["detail.arms", "overlap_model.measurement"],
                    ),
                    "detail": rec,
                    "overlap_model": overlap,
                }
            )
        )
        return
    quick = "--quick" in sys.argv
    iters = 3 if quick else 7

    # The device tunnel wedges transiently and recovers within minutes —
    # give it a few chances before recording a degraded CPU run.
    degraded = True
    # the child re-probes once (cheap, trusts the parent's verdict)
    attempts = 1 if quick or "--_tpu-inproc" in sys.argv else 4
    for attempt in range(attempts):
        if _tpu_alive():
            degraded = False
            break
        if attempt + 1 < attempts:
            _progress(f"device probe {attempt + 1} unresponsive after 180s; retrying")
            time.sleep(120)

    # A probe can pass and the tunnel still wedge mid-measurement, which
    # would hang this process (the axon backend blocks inside sync with no
    # way to un-initialize it). So the TPU phase runs in a timeout-guarded
    # child; a hang or crash there falls back to the CPU path here.
    if not degraded and "--_tpu-inproc" not in sys.argv:
        import subprocess

        try:
            # 2400s: below every caller deadline (tpu_sweep.sh wraps bench
            # in `timeout 3000`), so the fallback fires before a wrapper
            # kills this parent and orphans a wedged child
            proc = subprocess.run(
                [sys.executable, __file__, *sys.argv[1:], "--_tpu-inproc"],
                stdout=subprocess.PIPE,
                timeout=2400,
                text=True,
            )
            if proc.returncode == 0 and proc.stdout.strip():
                rec = _last_json_line(proc.stdout)
                if rec is not None:
                    print(json.dumps(rec))
                    return
                _progress("TPU bench child emitted no JSON record; degrading to CPU")
            _progress(f"TPU bench child failed rc={proc.returncode}; degrading to CPU")
        except subprocess.TimeoutExpired:
            _progress("TPU bench child hung (tunnel wedged mid-run); degrading to CPU")
        except Exception as e:  # noqa: BLE001 — bench must not die on a spawn
            _progress(f"TPU bench child spawn failed ({e}); degrading to CPU")
        degraded = True
    if degraded:
        if "--_tpu-inproc" in sys.argv:
            # the parent's probe passed but ours failed: let the parent run
            # (and attribute) the CPU fallback instead of publishing a
            # silently-degraded child result
            _progress("child re-probe failed; deferring CPU fallback to parent")
            sys.exit(3)
        _progress("device backend unresponsive; benching on CPU fallback")
        from deepreduce_tpu.utils import force_platform

        force_platform("cpu")

    import jax
    import jax.numpy as jnp

    from deepreduce_tpu.utils import enable_compile_cache

    # persistent XLA cache (<repo>/.jax_cache, gitignored): repeat runs —
    # including the driver's — skip the multi-minute cold compiles
    enable_compile_cache()

    d = LSTM_D if not quick else 500_000
    ratio = 0.10  # the paper's Top-r 10% LSTM setting (Table 2)

    # residual per-dispatch cost of a trivial jitted op under the same
    # amortized protocol — reported for context, never subtracted
    probe = jax.jit(lambda v: v[:8] * 2.0)
    z = jnp.zeros((1024,), jnp.float32)
    _sync(probe(z))
    overhead = _timeit(probe, z, iters=iters)

    configs = {
        "topr": dict(deepreduce=None, memory="none"),
        "drqsgd_delta": dict(
            deepreduce="both", index="integer", value="qsgd", policy="p0", memory="none"
        ),
        "drqsgd_bloom": dict(
            deepreduce="both",
            index="bloom",
            value="qsgd",
            policy="p0",
            fpr=0.02,
            memory="none",
        ),
        # the flagship shape with the sortless sampled-threshold sparsifier
        # (sparse.topk_sampled) in place of approx_max_k — the candidate
        # tpu_defaults flip; same wire, cheaper selection
        "drqsgd_bloom_sampled": dict(
            compressor="topk_sampled",
            deepreduce="both",
            index="bloom",
            value="qsgd",
            policy="p0",
            fpr=0.02,
            memory="none",
        ),
        # the fused sparsifier-free encode (bloom.encode_dense_direct):
        # sampled threshold + scatter-free threshold insert — no top-k
        # anywhere; same wire, convergence-backed (bf_p0_index_sampled_ti)
        "drqsgd_bloom_direct": dict(
            compressor="topk_sampled",
            deepreduce="both",
            index="bloom",
            value="qsgd",
            policy="p0",
            fpr=0.02,
            memory="none",
            bloom_threshold_insert=True,
        ),
    }
    with _span("bench/codec-table"):
        measured = {
            name: measure_config(d, ratio, kw, iters) for name, kw in configs.items()
        }
    cm = _costmodel()
    dense = cm.dense_measurement(d)

    t_dense = cm.exchange_time(dense, cm.BW_100MBPS)
    speedups = {
        n: t_dense / cm.exchange_time(m, cm.BW_100MBPS) for n, m in measured.items()
    }
    best_name = max(speedups, key=speedups.get)
    best = speedups[best_name]

    detail = {
        "model": "stackoverflow_lstm" if not quick else "quick",
        "d": d,
        "ratio": ratio,
        "bw_bytes_per_s": cm.BW_100MBPS,
        "t_dense_s": round(t_dense, 4),
        "dispatch_overhead_s": round(overhead, 4),
        "best_config": best_name,
        "speedup_vs_topr": round(
            cm.exchange_time(measured["topr"], cm.BW_100MBPS)
            / cm.exchange_time(measured[best_name], cm.BW_100MBPS),
            3,
        ),
        "platform": jax.devices()[0].platform,
        "degraded_to_cpu": degraded,  # true = probe failed, NOT a TPU result
        # tunnel-outage insurance: when this run could not reach the TPU,
        # point at the newest mid-round on-silicon record so the round
        # still carries real-TPU codec numbers
        **(
            {"tpu_measurements_see": _latest_midround_record()}
            if degraded and _latest_midround_record()
            else {}
        ),
        "configs": {
            n: {
                "rel_volume": round(m["rel_volume"], 5),
                "t_encode_s": round(m["t_encode_s"], 4),
                "t_decode_s": round(m["t_decode_s"], 4),
                "e2e_speedup_vs_dense": round(speedups[n], 3),
            }
            for n, m in measured.items()
        },
    }
    if not quick:
        # ResNet-50-scale codec timings (the BASELINE.json north-star size):
        # the fastest config (delta) AND the paper's flagship (bloom P0)
        for rname, rkw in {
            "resnet50_drqsgd_delta": dict(
                deepreduce="both", index="integer", value="qsgd", policy="p0",
                memory="none",
            ),
            "resnet50_drqsgd_bloom": dict(
                deepreduce="both", index="bloom", value="qsgd", policy="p0",
                fpr=0.001, memory="none",
            ),
        }.items():
            with _span(f"bench/{rname}"):
                r50 = measure_config(RESNET50_D, 0.01, rkw, 3)
            detail[rname] = {
                "rel_volume": round(r50["rel_volume"], 5),
                "t_encode_s": round(r50["t_encode_s"], 4),
                "t_decode_s": round(r50["t_decode_s"], 4),
                # effective gradient-exchange bandwidth: dense bytes made
                # exchangeable per second of codec work (the BASELINE.md
                # north-star framing)
                "effective_exchange_GBps": round(
                    4.0 * RESNET50_D
                    / max(r50["t_encode_s"] + r50["t_decode_s"], 1e-9) / 1e9,
                    2,
                ),
            }

    if not quick:
        # OBSERVED exchange throughput next to the analytic model above
        try:
            with _span("bench/measured-exchange"):
                detail["measured_exchange"] = _measured_exchange(degraded)
        except Exception as e:  # noqa: BLE001 — headline must still print
            _progress(f"measured exchange failed: {e}")
        # loop-vs-vmap-vs-ring fused-decode sweep on the CPU mesh
        try:
            with _span("bench/decode-sweep"):
                detail["decode_strategy_sweep"] = decode_strategy_sweep()
        except Exception as e:  # noqa: BLE001
            _progress(f"decode strategy sweep failed: {e}")
        # per-tensor vs bucketed fused exchange on the leafy LSTM census
        try:
            with _span("bench/bucketed-exchange"):
                detail["bucketed_exchange"] = _bucketed_subprocess(
                    _bucket_bytes_arg()
                )
        except Exception as e:  # noqa: BLE001
            _progress(f"bucketed exchange arm failed: {e}")

    if not quick and not degraded and "--skip-models" not in sys.argv:
        # (CPU-degraded runs skip this: img/s and MFU of a conv net on the
        # host CPU say nothing about the chip-level north-star metric)
        # ResNet-50/20 images/sec + MFU at topk 1% (BASELINE.md north-star
        # metric): full fwd+bwd+compressed-exchange steps on the real chip.
        # The persistent compile cache makes repeat runs fast.
        try:
            with _span("bench/model-throughput"):
                models = _model_throughput()
            detail["model_throughput"] = models
            r50 = models.get("resnet50", {}).get("topk1_bloom", {})
            if r50:
                detail["resnet50_images_per_sec"] = r50["images_per_sec"]
                if "mfu_vs_bf16_peak" in r50:
                    detail["mfu"] = r50["mfu_vs_bf16_peak"]
        except Exception as e:  # noqa: BLE001
            _progress(f"model throughput failed: {e}")

    _maybe_save_trace()
    print(
        json.dumps(
            {
                "metric": "lstm_e2e_grad_exchange_speedup_vs_dense_100mbps",
                "value": round(best, 3),
                "unit": "x",
                "vs_baseline": round(best / PAPER_E2E_SPEEDUP, 4),
                "provenance": _provenance(
                    modeled=[
                        "t_dense_s", "configs.*.e2e_speedup_vs_dense",
                        "speedup_vs_topr",
                    ],
                    measured=[
                        "configs.*.t_encode_s", "configs.*.t_decode_s",
                        "configs.*.rel_volume", "dispatch_overhead_s",
                        "measured_exchange", "decode_strategy_sweep",
                        "bucketed_exchange", "model_throughput",
                    ],
                ),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
