.PHONY: analyze analyze-quick test test-quick

# full static-analysis gate: AST lint + jaxpr audit of every registered
# codec/communicator config; writes ANALYSIS.json, exits nonzero on any
# violation. CPU-only, trace-only (no compiles).
analyze:
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.analysis

# the tier-1 subset (flagship codec/query + the three fused decode
# strategies) — what tests/test_analysis.py also runs
analyze-quick:
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.analysis --quick --out -

# tier-1: the fast suite CI gates on (see ROADMAP.md for the full command)
test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

test-quick:
	JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q
