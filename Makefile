.PHONY: analyze analyze-quick matrix-check memcheck test test-quick telemetry-check chaos-check fedsim-check fedasync-check fedmt-check pop-check ctrl-check overlap-check calibrate-check slo-check

# full static-analysis gate: AST lint + jaxpr audit of every registered
# codec/communicator config; writes ANALYSIS.json, exits nonzero on any
# violation. CPU-only, trace-only (no compiles). Also exercises the
# telemetry round trip (telemetry-check), the resilience smoke
# (chaos-check), the federated round smoke (fedsim-check) and the
# composition-lattice legality matrix (matrix-check) so none of those
# paths can rot while the gate stays green.
analyze: memcheck matrix-check telemetry-check chaos-check fedsim-check fedasync-check fedmt-check pop-check slo-check ctrl-check overlap-check calibrate-check
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.analysis

# memory-liveness gate: the donation-aware liveness interpreter over the
# flagship fused/bucketed/streaming/fedsim traces — prints each trace's
# modeled peak live bytes, the top-3 contributing buffers with provenance,
# and the live bytes at each collective; exits nonzero on any violation
# (jx-peak-bytes residency, jx-dtype-flow, or any other armed rule).
memcheck:
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.analysis mem

# composition-lattice legality gate: probe the full feature cross-product
# (communicator x decode x buckets x stream x rs_mode x hier x resilience
# x ctrl x fed), trace every legal cell through the full rule set, and
# diff legality / reason codes / trace hashes against the committed
# MATRIX.json — exits nonzero on any violation or drift. Trace-only
# (abstract meshes, no compiles). Re-baseline deliberately with
# `python -m deepreduce_tpu.analysis matrix --update`.
matrix-check:
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.analysis matrix

# adaptive-controller smoke: a short adaptive train on the 8-worker CPU
# mesh asserts decisions.jsonl is non-empty and schema-valid, the
# controller actually switches operating points with bounded re-jit
# (compiled executables == ladder rungs visited), and a mid-run
# checkpoint resume replays the decision trail BITWISE with bit-identical
# final params (python -m deepreduce_tpu.controller check)
ctrl-check:
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.controller --platform cpu check

# federated-simulation smoke: a small client-sharded cohort run on the
# 8-device CPU mesh with FaultPlan churn + wire corruption under payload
# checksums — asserts convergence, recorded churn/checksum failures, and a
# BITWISE mid-run checkpoint resume; then the telemetry CLI digests the
# tracked run dir (clients/sec + uplink-bytes rows).
FEDSIM_CHECK_DIR := /tmp/drtpu_fedsim_check
fedsim-check:
	rm -rf $(FEDSIM_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.fedsim --platform cpu check \
		--track_dir $(FEDSIM_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry summary $(FEDSIM_CHECK_DIR)/check

# asynchronous-federated smoke: a short buffered-ingest run on the same
# 8-device CPU mesh (staleness-weighted deltas, K-threshold applies,
# 3-level latency distribution, churn + wire corruption) — asserts
# staleness was observed, the buffer applied, and a MID-BUFFER checkpoint
# (partially filled, staleness counters nonzero) resumes BITWISE; then the
# telemetry CLI digests the staleness rows (fed_staleness_mean/max,
# fed_buffer_fill_per_apply).
FEDASYNC_CHECK_DIR := /tmp/drtpu_fedasync_check
fedasync-check:
	rm -rf $(FEDASYNC_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.fedsim --platform cpu check \
		--async --rounds 8 --track_dir $(FEDASYNC_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry summary $(FEDASYNC_CHECK_DIR)/check

# multi-tenant federated smoke: T=2 heterogeneous async populations
# (distinct per-tenant K/alpha/latency/cohort) through the ONE vmapped
# tick on the 8-device CPU mesh — asserts tenant join/leave via the
# active mask WITHOUT retrace (jit cache size pinned across flips), a
# MID-FILL multi-tenant checkpoint (tenants at DIFFERENT buffer levels,
# staleness nonzero) resumes BITWISE replaying the same mask schedule,
# and restore across a tenant-geometry mismatch fails fast; then the
# telemetry CLI digests the per-tenant rows (fed_mt_clients_per_sec[t],
# fed_mt_staleness_mean/max, fed_mt_buffer_fill_per_apply).
FEDMT_CHECK_DIR := /tmp/drtpu_fedmt_check
fedmt-check:
	rm -rf $(FEDMT_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.fedsim --platform cpu check \
		--tenants 2 --rounds 8 --track_dir $(FEDMT_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry summary $(FEDMT_CHECK_DIR)/mt-check

# heterogeneous-population smoke: a skewed two-class population (planted
# non-IID label mixtures, per-class latency rows, a 2x compute class)
# through the async buffered tick on the 8-device CPU mesh — asserts the
# exact on-device per-class participation histogram (its mass each tick
# equals the tick's accepted count, every class served), churn recorded,
# and a MID-STREAM checkpoint (buffer partially filled, class-id vector
# riding the state) resumes BITWISE; then the telemetry CLI digests the
# per-class rows (fed_pop_shares, fed_pop_residency_min).
POP_CHECK_DIR := /tmp/drtpu_pop_check
pop-check:
	rm -rf $(POP_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.fedsim --platform cpu check \
		--population --rounds 8 --track_dir $(POP_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry summary $(POP_CHECK_DIR)/check

# SLO health-plane smoke: the async churn+chaos check run with the
# in-driver HealthMonitor armed (--slo) — asserts the run ends healthy,
# health.jsonl is schema-valid and matches the monitor's event stream,
# the post-checkpoint health tail replays BITWISE on resume, and the
# staleness p95 that feeds the monitor comes from the on-device
# histogram; then `telemetry slo` re-evaluates the recorded report
# stream against the committed slo.json spec and exit-gates on BREACH.
SLO_CHECK_DIR := /tmp/drtpu_slo_check
slo-check:
	rm -rf $(SLO_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.fedsim --platform cpu check \
		--async --slo --rounds 8 --track_dir $(SLO_CHECK_DIR)
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry slo \
		$(SLO_CHECK_DIR)/check --spec slo.json

# resilience smoke: a short 8-worker CPU-mesh train under a FaultPlan drop
# schedule + wire corruption with payload checksums — asserts finite,
# decreasing loss and incremented dropped_steps / checksum_failures
# counters (python -m deepreduce_tpu.resilience check)
chaos-check:
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.resilience --platform cpu check

# streaming-exchange overlap gate: two short mlp trains on the 8-worker
# CPU mesh with IDENTICAL seeds (batches are pure functions of
# (seed, step)) — one with the backprop-streamed bucket exchange, one
# with the barrier schedule (bucket_pipeline=False). The telemetry CLI
# asserts the streaming run's exchange/bucket/* spans overlap
# train/forward_backward (trace --overlap, threshold-gated exit code),
# then the two metrics.jsonl loss/rel_volume series are compared
# BITWISE: losses at steps >= 1 depend on the exchanged gradients, so
# series equality proves streaming moved only the dispatch order.
OVERLAP_CHECK_DIR := /tmp/drtpu_overlap_check
OVERLAP_CHECK_CFG := 'compressor':'topk','compress_ratio':0.05,'deepreduce':'index','index':'bloom','fpr':0.01,'memory':'residual','bucket_bytes':8192
overlap-check:
	rm -rf $(OVERLAP_CHECK_DIR)
	JAX_PLATFORMS=cpu python benchmarks/train.py --platform cpu \
		--model mlp --num_steps 6 --batch_size 8 --num_workers 8 --seed 0 \
		--telemetry --track_dir $(OVERLAP_CHECK_DIR) --run_name stream \
		--log_every 0 \
		--grace_config "{$(OVERLAP_CHECK_CFG),'stream_exchange':True}"
	JAX_PLATFORMS=cpu python benchmarks/train.py --platform cpu \
		--model mlp --num_steps 6 --batch_size 8 --num_workers 8 --seed 0 \
		--telemetry --track_dir $(OVERLAP_CHECK_DIR) --run_name barrier \
		--log_every 0 \
		--grace_config "{$(OVERLAP_CHECK_CFG),'bucket_pipeline':False}"
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry trace \
		$(OVERLAP_CHECK_DIR)/stream --overlap
	python -c "import json,sys; \
		rd=lambda n:[(r['loss'],r['rel_volume']) for r in map(json.loads, open('$(OVERLAP_CHECK_DIR)/'+n+'/metrics.jsonl'))]; \
		a,b=rd('stream'),rd('barrier'); \
		sys.exit(0 if a==b and a else (print('overlap-check: metrics diverge',a,b),1)[1])"
	# composed stream-over-hier run on the (2, 4) two-axis mesh: the gate
	# takes the MINIMUM overlap fraction across the bucket wrapper and the
	# nested exchange/dcn + exchange/ici leg spans, at the tighter 0.9
	# threshold — every leg of every bucket must dispatch from inside
	# backprop, not just the wrapper span
	JAX_PLATFORMS=cpu python benchmarks/train.py --platform cpu \
		--model mlp --num_steps 6 --batch_size 8 --num_workers 8 --seed 0 \
		--telemetry --track_dir $(OVERLAP_CHECK_DIR) --run_name composed \
		--log_every 0 \
		--grace_config "{$(OVERLAP_CHECK_CFG),'stream_exchange':True,'hier':True}"
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry trace \
		$(OVERLAP_CHECK_DIR)/composed --overlap --overlap-threshold 0.9

# cost-model calibration gate: a short telemetry-on train on the
# 8-worker CPU mesh writes a tracked run dir, then `telemetry calibrate`
# fits a MachineProfile from its trace + wire accumulators and exits
# nonzero unless the fitted model reproduces the measured (warmup-
# dropped) step time within tolerance and the profile record passes
# schema validation. A second fit must be byte-identical — the fit reads
# only recorded telemetry, never the wall clock.
#
# The hierarchical arm re-runs the same model on the (2,4) two-axis mesh
# (ici_size=4, hier_ici='qar'): its exchange/ici spans carry real ICI
# seconds, so the fit must move bw_ici from the static constants into the
# fitted set (--require-fitted bw_ici), and the v2 profile must carry
# per-route rows for both the 'fused' DCN leg and the 'qar' ICI codec.
# The cross-profile drift sentinel then gates both ways: the two bitwise-
# identical hier fits must not flip any committed bench plan selection
# (exit 0), while the TRACE_OVERLAP_r15 golden fit vs the static
# constants is a planted drift that MUST flip a BENCH_CALIB_r16 pick
# (exit 1) — proving the gate actually fires.
CALIB_CHECK_DIR := /tmp/drtpu_calib_check
CALIB_CHECK_CFG := 'compressor':'topk','compress_ratio':0.05,'deepreduce':'index','index':'bloom','fpr':0.01,'memory':'residual'
calibrate-check:
	rm -rf $(CALIB_CHECK_DIR)
	JAX_PLATFORMS=cpu python benchmarks/train.py --platform cpu \
		--model mlp --num_steps 8 --batch_size 8 --num_workers 8 --seed 0 \
		--telemetry --track_dir $(CALIB_CHECK_DIR) --run_name calib \
		--log_every 0 \
		--grace_config "{$(CALIB_CHECK_CFG)}"
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry calibrate \
		$(CALIB_CHECK_DIR)/calib --out $(CALIB_CHECK_DIR)/profile.json
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry calibrate \
		$(CALIB_CHECK_DIR)/calib --out $(CALIB_CHECK_DIR)/profile2.json
	cmp $(CALIB_CHECK_DIR)/profile.json $(CALIB_CHECK_DIR)/profile2.json
	JAX_PLATFORMS=cpu python benchmarks/train.py --platform cpu \
		--model mlp --num_steps 8 --batch_size 8 --num_workers 8 --seed 0 \
		--telemetry --track_dir $(CALIB_CHECK_DIR) --run_name hier \
		--log_every 0 \
		--grace_config "{$(CALIB_CHECK_CFG),'hier':True,'hier_ici':'qar','ici_size':4}"
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry calibrate \
		$(CALIB_CHECK_DIR)/hier --out $(CALIB_CHECK_DIR)/hier_profile.json \
		--require-fitted bw_ici
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry calibrate \
		$(CALIB_CHECK_DIR)/hier --out $(CALIB_CHECK_DIR)/hier_profile2.json \
		--require-fitted bw_ici
	cmp $(CALIB_CHECK_DIR)/hier_profile.json $(CALIB_CHECK_DIR)/hier_profile2.json
	python -c "import json; rec=json.load(open('$(CALIB_CHECK_DIR)/hier_profile.json')); \
		assert len(rec['routes']) >= 2, rec['routes']"
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry profiles \
		$(CALIB_CHECK_DIR)/hier_profile.json $(CALIB_CHECK_DIR)/hier_profile2.json \
		--against BENCH_HIER_r12.json --against BENCH_CALIB_r16.json \
		--against BENCH_OKTOPK_r18.json
	JAX_PLATFORMS=cpu python -c "from deepreduce_tpu import costmodel; \
		costmodel.static_profile().save('$(CALIB_CHECK_DIR)/static_profile.json')"
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry calibrate \
		TRACE_OVERLAP_r15 --out $(CALIB_CHECK_DIR)/golden_profile.json
	! JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry profiles \
		$(CALIB_CHECK_DIR)/golden_profile.json $(CALIB_CHECK_DIR)/static_profile.json \
		--against BENCH_CALIB_r16.json

# end-to-end telemetry round trip on the CPU virtual mesh: a short
# telemetry-on training run writes a tracked run dir (metrics + device
# accumulators + Chrome trace), then the CLI digests it and re-emits the
# merged trace — failure anywhere exits nonzero.
TELEMETRY_CHECK_DIR := /tmp/drtpu_telemetry_check
telemetry-check:
	rm -rf $(TELEMETRY_CHECK_DIR)
	JAX_PLATFORMS=cpu python benchmarks/train.py --platform cpu \
		--model resnet20 --num_steps 4 --batch_size 8 --num_workers 4 \
		--telemetry --track_dir $(TELEMETRY_CHECK_DIR) --run_name check \
		--log_every 0 \
		--grace_config "{'compressor':'topk','compress_ratio':0.05,'deepreduce':'index','index':'bloom','fpr':0.01,'memory':'residual'}"
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry summary $(TELEMETRY_CHECK_DIR)/check
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.telemetry trace \
		$(TELEMETRY_CHECK_DIR)/check --out $(TELEMETRY_CHECK_DIR)/merged_trace.json

# the tier-1 subset (flagship codec/query + the three fused decode
# strategies) — what tests/test_analysis.py also runs
analyze-quick:
	JAX_PLATFORMS=cpu python -m deepreduce_tpu.analysis --quick --out -

# tier-1: the fast suite CI gates on (see ROADMAP.md for the full command)
test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

test-quick:
	JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q
