"""StackOverflow-LSTM federated experiment — the paper's Table-2 shape.

Reference (paper §6.2 Table 2, BASELINE.md): the headline FL experiment —
a next-word LSTM trained by FedAvg over 56 sampled clients with
bidirectionally-compressed exchange. Table 2's claim is the relative-volume
ordering at accuracy parity:

    Top-r 0.2033  >  DR*BF-P0 0.1425  >  DRQSGD-BF-P0 0.0621

This harness runs the same topology end to end over the real WordLSTM
family at smoke scale (narrow model, synthetic next-token task from a fixed
random bigram teacher — no dataset egress) and records each method's
measured relative volume and accuracy against the dense FedAvg arm.

    python benchmarks/lstm_table2.py --out LSTM_TABLE2.json
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

PAPER = {
    "topr": {"rel_volume": 0.2033},
    "drbf_p0": {"rel_volume": 0.1425, "acc": 0.1841},
    "drqsgd_bf_p0": {"rel_volume": 0.0621, "acc": 0.1836},
    "dense": {"acc": 0.1856},
}


BRANCH_PROBS = (0.7, 0.2, 0.1)


def make_task(n, vocab, seq, seed, teacher_seed=3):
    """Sequences from a fixed STOCHASTIC bigram teacher: each token has 3
    candidate successors drawn with probs 0.7/0.2/0.1, so the Bayes-optimal
    top-1 accuracy is ~0.7 — the task cannot saturate at 1.0, making
    compression-induced degradation observable (VERDICT r3 #3). Identical
    teacher for every arm; splits differ in start tokens and transition
    draws. Returns (x, y, bayes_y) with bayes_y the optimal prediction."""
    t_rng = np.random.default_rng(teacher_seed)
    succ = np.stack(
        [t_rng.permutation(vocab) for _ in range(len(BRANCH_PROBS))], axis=1
    ).astype(np.int32)  # [vocab, 3] candidate successors
    rng = np.random.default_rng(seed)
    toks = np.empty((n, seq + 1), np.int32)
    toks[:, 0] = rng.integers(0, vocab, size=n)
    p = np.asarray(BRANCH_PROBS)
    for t in range(seq):
        choice = rng.choice(len(BRANCH_PROBS), size=n, p=p)
        toks[:, t + 1] = succ[toks[:, t], choice]
    x, y = toks[:, :-1], toks[:, 1:]
    return x, y, succ[x, 0]


def run_arm(cfg_params, rounds, seed, vocab=256, seq=16):
    import jax
    import jax.numpy as jnp
    import optax

    from deepreduce_tpu import FedAvg, FedConfig
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.models import WordLSTM

    model = WordLSTM(vocab_size=vocab, embed_dim=32, hidden_dim=64)
    x, y, _ = make_task(4096, vocab, seq, seed=seed * 31 + 1)
    xe, ye, bayes_ye = make_task(1024, vocab, seq, seed=seed * 31 + 2)

    def loss_fn(params, batch_xy):
        xb, yb = batch_xy
        logits = model.apply({"params": params}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    params = model.init(jax.random.PRNGKey(seed), jnp.asarray(x[:2]))["params"]
    if cfg_params:
        cfg = DeepReduceConfig.tpu_defaults(**cfg_params)
    else:
        cfg = DeepReduceConfig(compressor="none", memory="none")
    # paper: 56 of 57 clients sampled per round. Client momentum restarts
    # every round (client state is not federated), so with few local steps
    # it barely amplifies the lr — the client lr is set high to compensate
    # (central-training equivalent reaches the Bayes ceiling at
    # lr_eff ~ 2-5 on this task)
    fed = FedConfig(num_clients=57, clients_per_round=56, local_steps=4)
    fa = FedAvg(loss_fn, cfg, fed, optax.sgd(2.0, momentum=0.9))
    state = fa.init(params)
    run_round = jax.jit(fa.run_round)

    batch = 16
    vol = None
    rng = np.random.default_rng(seed + 10)
    for r in range(rounds):
        key = jax.random.PRNGKey(2000 + r)
        ids = fa.sample_clients(state, key)
        pick = rng.integers(0, len(x), size=(fed.clients_per_round, fed.local_steps, batch))
        state, out = run_round(
            state,
            ids,
            (jnp.asarray(x[pick]), jnp.asarray(y[pick])),
            jax.random.fold_in(key, 1),
        )
        vol = float(out["rel_volume"])

    @jax.jit
    def logits_fn(xb):
        return model.apply({"params": state.params}, xb)

    correct = total = 0
    for lo in range(0, len(xe), 256):
        out_l = np.asarray(logits_fn(jnp.asarray(xe[lo : lo + 256])))
        correct += int((np.argmax(out_l, axis=-1) == ye[lo : lo + 256]).sum())
        total += out_l.shape[0] * out_l.shape[1]
    bayes = float((bayes_ye == ye).mean())
    return correct / total, vol, bayes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=25)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default="cpu")
    args = ap.parse_args()

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=1)

    common = dict(compressor="topk", compress_ratio=0.1, min_compress_size=500)
    configs = {
        "topr": dict(common),
        "drbf_p0": dict(
            common, deepreduce="index", index="bloom", policy="p0", fpr=0.02
        ),
        "drqsgd_bf_p0": dict(
            common,
            deepreduce="both",
            index="bloom",
            value="qsgd",
            policy="p0",
            fpr=0.02,
        ),
    }
    seeds = list(range(max(1, args.seeds)))
    results = {}
    dense_accs, bayes_accs = {}, []
    for s in seeds:
        acc, _, bayes = run_arm(None, args.rounds, seed=s)
        dense_accs[s] = acc
        bayes_accs.append(bayes)
        print(json.dumps({"dense": {"seed": s, "acc": round(acc, 4)}}), file=sys.stderr)
    results["dense"] = {
        "acc_mean": round(float(np.mean(list(dense_accs.values()))), 4),
        "acc_std": round(float(np.std(list(dense_accs.values()))), 4),
        "per_seed": [round(a, 4) for a in dense_accs.values()],
    }
    for name, cp in configs.items():
        accs, gaps, vol = [], [], None
        for s in seeds:
            acc, vol, _ = run_arm(cp, args.rounds, seed=s)
            accs.append(acc)
            gaps.append(dense_accs[s] - acc)
        results[name] = {
            "acc_mean": round(float(np.mean(accs)), 4),
            "acc_std": round(float(np.std(accs)), 4),
            "acc_gap_vs_dense_mean": round(float(np.mean(gaps)), 4),
            "acc_gap_vs_dense_std": round(float(np.std(gaps)), 4),
            "per_seed": [round(a, 4) for a in accs],
            "rel_volume": round(vol, 4),
            "paper_rel_volume": PAPER[name].get("rel_volume"),
        }
        print(json.dumps({name: results[name]}), file=sys.stderr)
    vols = [results[n]["rel_volume"] for n in ("topr", "drbf_p0", "drqsgd_bf_p0")]
    out = {
        "experiment": "WordLSTM FedAvg, 56/57 clients per round (paper Table 2 "
                      "shape); stochastic bigram teacher — Bayes top-1 ceiling "
                      "~0.7, so the task cannot saturate and degradation is "
                      "observable",
        "rounds": args.rounds,
        "n_seeds": len(seeds),
        "bayes_ceiling": round(float(np.mean(bayes_accs)), 4),
        "paper_ordering": "topr 0.2033 > drbf_p0 0.1425 > drqsgd_bf_p0 0.0621",
        "ordering_holds": vols[0] > vols[1] > vols[2],
        "results": results,
    }
    print(json.dumps(out))
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(out, indent=1) + "\n")


if __name__ == "__main__":
    main()
