"""Collect tunnel_watch arm outputs into one mid-round TPU record.

Reads every ``<name>.json`` under the arms dir (one JSON object per arm, as
written by `tunnel_watch.sh`), verifies platform, computes the
threshold-insert and sampled-sparsifier A/B verdicts from the paired arms,
and writes ``BENCH_TPU_MIDROUND_r05.json``. Run whenever some arms exist —
re-running with more arms refreshes the record (restart-safe, like the
watcher).

    python benchmarks/bank_arms.py [--arms tpu_arms_r05] [--out BENCH_TPU_MIDROUND_r05.json]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import time


def _is_tpu(rec: dict) -> bool:
    """Strict: an arm with a missing platform field does NOT count as TPU."""
    return rec.get("platform", rec.get("detail", {}).get("platform")) in ("tpu", "axon")


def _pair_verdict(arms: dict, base: str, variant: str, stages=("insert", "encode", "decode")) -> dict:
    """A/B of a paired arm: per-stage ms and the whole-pipeline ratio.
    Refuses to compare across platforms — a CPU arm paired with a TPU arm
    would produce a bogus headline ratio."""
    a, b = arms.get(base), arms.get(variant)
    if not a or not b:
        return {"complete": False}
    if not (_is_tpu(a) and _is_tpu(b)):
        return {
            "complete": False,
            "reason": f"non-TPU side: {[n for n, r in ((base, a), (variant, b)) if not _is_tpu(r)]}",
        }
    sa, sb = a["stages_ms"], b["stages_ms"]
    pipe_a = sa.get("encode", 0) + sa.get("decode", 0)
    pipe_b = sb.get("encode", 0) + sb.get("decode", 0)
    out = {
        "complete": True,
        "stages_ms": {s: [sa.get(s), sb.get(s)] for s in stages if s in sa or s in sb},
        "pipeline_ms": [round(pipe_a, 3), round(pipe_b, 3)],
        "variant_speedup": round(pipe_a / pipe_b, 3) if pipe_b else None,
    }
    sat = [n for n, r in ((base, a), (variant, b)) if r.get("meta", {}).get("saturated")]
    if sat:
        out["saturated"] = sat
        out["note"] = f"{'/'.join(sat)} saturated its budget; selections differ — NOT comparable"
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arms", default="tpu_arms_r05")
    ap.add_argument("--out", default="BENCH_TPU_MIDROUND_r05.json")
    args = ap.parse_args()

    root = pathlib.Path(__file__).parent.parent
    arms = {}
    for p in sorted((root / args.arms).glob("*.json")):
        if p.name.endswith(".cpu-degraded.json"):
            continue
        try:
            arms[p.stem] = json.loads(p.read_text())
        except json.JSONDecodeError:
            continue

    non_tpu = [n for n, r in arms.items() if not _is_tpu(r)]
    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "arms_present": sorted(arms),
        "non_tpu_arms": non_tpu,
        "threshold_insert_ab": {
            k: _pair_verdict(arms, k, f"{k}_ti")
            for k in ("lstm_fpr02", "lstm_fpr001", "r50_fpr001")
        },
        "sampled_sparsifier_ab": {
            k: _pair_verdict(
                arms, k, f"{k}_sampled",
                stages=("sparsify", "sparsify_exact", "sparsify_approx", "sparsify_sampled", "encode", "decode"),
            )
            for k in ("lstm_fpr02", "r50_fpr001")
        },
        # sparsifier-free direct encode (sampled threshold + threshold insert
        # fused; bloom.encode_dense_direct) vs the standard approx-topk path
        "direct_encode_ab": {
            k: _pair_verdict(
                arms, k, f"{k}_sampled_ti",
                stages=("sparsify", "insert", "encode", "decode"),
            )
            for k in ("lstm_fpr02", "r50_fpr001")
        },
        "arms": arms,
    }
    (root / args.out).write_text(json.dumps(record, indent=1) + "\n")
    done = record["arms_present"]
    print(f"banked {len(done)} arms -> {args.out}: {', '.join(done) or '(none)'}")
    if non_tpu:
        print(f"WARNING: non-TPU arms present: {non_tpu}")


if __name__ == "__main__":
    main()
