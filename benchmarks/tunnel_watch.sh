#!/usr/bin/env bash
# Tunnel-resilient TPU sweep: probe the device tunnel in a loop and run one
# measurement arm at a time whenever it is up. Each arm writes its own file
# under $OUTDIR, so a mid-arm wedge loses only that arm, and completed arms
# are never rerun (restart-safe). The axon tunnel wedges transiently and
# recovers within minutes (rounds 3-5 observation) — this script turns a
# flaky window into a full sweep by outlasting the outages.
set -uo pipefail
cd "$(dirname "$0")/.."

OUTDIR=${OUTDIR:-tpu_arms_r05}
PY=${PY:-python}
ARM_TIMEOUT=${ARM_TIMEOUT:-1800}
# bench.py's internal TPU child guard is 2400s; its caller deadline must sit
# above that or a mid-run wedge orphans the child holding the tunnel
BENCH_TIMEOUT=${BENCH_TIMEOUT:-3000}
PROBE_SLEEP=${PROBE_SLEEP:-120}
MAX_TRIES=${MAX_TRIES:-6}
LSTM_D=4053428
R50_D=25557032
mkdir -p "$OUTDIR"

probe() {
  # one source of truth: the library's subprocess jit-roundtrip probe
  timeout 120 $PY -c "
from deepreduce_tpu.utils import device_responsive
import sys
sys.exit(0 if device_responsive(timeout_s=90) else 1)"
}

wait_for_tunnel() {
  until probe; do
    echo "$(date +%H:%M:%S) tunnel down; sleeping ${PROBE_SLEEP}s" >&2
    sleep "$PROBE_SLEEP"
  done
  echo "$(date +%H:%M:%S) tunnel up" >&2
}

# name | command...  — ordered by value-per-minute of tunnel uptime: the
# two-rounds-overdue threshold-insert A/B first, then the fused direct
# path, the lean headline bench, the rest, and the model probes last
arms() {
  cat <<EOF
lstm_fpr02|$PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.02
lstm_fpr02_ti|$PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.02 --threshold_insert
lstm_fpr02_sampled_ti|$PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.02 --compressor topk_sampled --threshold_insert
bench_skipmodels|$PY bench.py --skip-models
lstm_fpr001|$PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.001
lstm_fpr001_ti|$PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.001 --threshold_insert
r50_fpr001|$PY benchmarks/profile_codec.py --d $R50_D --ratio 0.01 --fpr 0.001
r50_fpr001_ti|$PY benchmarks/profile_codec.py --d $R50_D --ratio 0.01 --fpr 0.001 --threshold_insert
r50_fpr001_sampled_ti|$PY benchmarks/profile_codec.py --d $R50_D --ratio 0.01 --fpr 0.001 --compressor topk_sampled --threshold_insert
lstm_integer|$PY benchmarks/profile_codec.py --d $LSTM_D --index integer
lstm_fpr02_sampled|$PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.02 --compressor topk_sampled
r50_fpr001_sampled|$PY benchmarks/profile_codec.py --d $R50_D --ratio 0.01 --fpr 0.001 --compressor topk_sampled
bench_full|$PY bench.py
r50_b256|$PY benchmarks/model_throughput_probe.py --model resnet50 --batch 256
r50_b512|$PY benchmarks/model_throughput_probe.py --model resnet50 --batch 512
r50_b256_dense|$PY benchmarks/model_throughput_probe.py --model resnet50 --batch 256 --config dense
EOF
}

while :; do
  pending=0
  while IFS='|' read -r name cmd; do
    out="$OUTDIR/$name.json"
    tries="$OUTDIR/$name.tries"
    [ -s "$out" ] && continue
    n=$(cat "$tries" 2>/dev/null || echo 0)
    if [ "$n" -ge "$MAX_TRIES" ]; then
      echo "$name: gave up after $n tries" >&2
      continue
    fi
    pending=1
    wait_for_tunnel
    echo $((n + 1)) > "$tries"
    tmo=$ARM_TIMEOUT
    case "$name" in bench_*) tmo=$BENCH_TIMEOUT ;; esac
    echo "$(date +%H:%M:%S) == $name (try $((n + 1))/$MAX_TRIES, ${tmo}s): $cmd ==" >&2
    if timeout "$tmo" $cmd > "$out.tmp" 2> "$OUTDIR/$name.log"; then
      # keep only the final JSON line (progress riding on stdout never
      # lands in the artifact)
      grep '^{' "$out.tmp" | tail -1 > "$out"
      rm -f "$out.tmp"
      if [ ! -s "$out" ]; then
        echo "$name: no JSON produced" >&2
        rm -f "$out"
      elif grep -Eq '"degraded_to_cpu": true|"platform": "(cpu|cuda)"' "$out"; then
        # a record measured off-TPU (bench's degraded flag, or any arm's
        # platform field) is exactly what this sweep exists to avoid —
        # treat as failure and retry when the tunnel returns
        echo "$name: ran off-TPU; discarding and retrying" >&2
        mv "$out" "$OUTDIR/$name.cpu-degraded.json"
      fi
      echo "$(date +%H:%M:%S) $name done" >&2
    else
      echo "$(date +%H:%M:%S) $name failed/timeout (try $((n + 1)))" >&2
      rm -f "$out.tmp"
    fi
  done < <(arms)
  [ "$pending" = 0 ] && break
  sleep 5
done
echo "watcher finished -> $OUTDIR" >&2
