"""Benchmark training driver — the L6 layer (run_deepreduce.sh +
tf_cnn_benchmarks / trainer_grace / ncf_grace role, SURVEY.md §1).

One driver for every model family, configured exactly like the reference:
a ``--grace_config`` Python-literal dict with the reference's key names
(run_deepreduce.sh:35):

    python benchmarks/train.py --model resnet20 --num_steps 100 \
      --grace_config "{'compressor':'topk','compress_ratio':0.01,
                       'memory':'residual','communicator':'allgather',
                       'deepreduce':'both','index':'bloom','value':'polyfit',
                       'fpr':0.001,'policy':'leftmost'}"

Data is synthetic (shape-correct random batches): this driver measures the
framework — step time, wire volume, convergence mechanics — not dataset
accuracy (no dataset egress in this environment). Plug a real data iterator
into `run` for accuracy work.
"""

from __future__ import annotations

import argparse
import ast
import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


MODELS = {}


def _register(name):
    def deco(fn):
        MODELS[name] = fn
        return fn

    return deco


@_register("resnet20")
def _resnet20():
    from deepreduce_tpu.models import ResNet20

    return ResNet20(), ("image", (32, 32, 3), 10)


@_register("densenet40")
def _densenet40():
    from deepreduce_tpu.models import DenseNet40

    return DenseNet40(), ("image", (32, 32, 3), 10)


@_register("mobilenet")
def _mobilenet():
    from deepreduce_tpu.models import MobileNetV1

    return MobileNetV1(), ("image", (32, 32, 3), 10)


@_register("vgg16")
def _vgg16():
    from deepreduce_tpu.models import VGG16

    return VGG16(), ("image", (32, 32, 3), 10)


@_register("resnet50")
def _resnet50():
    from deepreduce_tpu.models import ResNet50

    return ResNet50(), ("image", (224, 224, 3), 1000)


@_register("mlp")
def _mlp():
    # tiny vector MLP: the fast model for smoke targets (chaos-check) and
    # the kill/resume test — compiles in seconds on the CPU mesh
    import flax.linen as nn

    class _MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(64)(x))
            x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(8)(x)

    return _MLP(), ("vec", (32,), 8)


@_register("ncf")
def _ncf():
    from deepreduce_tpu.models import NeuMF

    return NeuMF(), ("ncf", None, None)


@_register("lstm")
def _lstm():
    from deepreduce_tpu.models import WordLSTM

    m = WordLSTM()
    return m, ("lm", 20, m.vocab_size)


@_register("bert")
def _bert():
    from deepreduce_tpu.models import BertEncoder

    m = BertEncoder()
    return m, ("lm", 128, m.vocab_size)


def make_batch(kind, spec, classes, batch, rng, model=None):
    import jax.numpy as jnp

    if kind in ("image", "vec"):
        x = jnp.asarray(rng.normal(size=(batch,) + spec).astype(np.float32))
        y = jnp.asarray(rng.integers(0, classes, size=batch), jnp.int32)
        return (x, y)
    if kind == "lm":
        seq = spec
        toks = jnp.asarray(rng.integers(0, classes, size=(batch, seq)), jnp.int32)
        return (toks,)  # labels derived (next-token) in the loss
    if kind == "ncf":
        users = jnp.asarray(rng.integers(0, model.num_users, size=batch), jnp.int32)
        items = jnp.asarray(rng.integers(0, model.num_items, size=batch), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 2, size=batch).astype(np.float32))
        return ((users, items), labels)
    raise ValueError(kind)


def make_loss(kind, model):
    import jax.numpy as jnp
    import optax

    if kind in ("image", "vec"):
        from deepreduce_tpu.train import classification_loss

        return classification_loss(model)

    if kind == "lm":

        def loss_fn(params, batch_stats, batch):
            (toks,) = batch
            logits = model.apply({"params": params}, toks[:, :-1])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, toks[:, 1:]
            ).mean()
            return loss, batch_stats

        return loss_fn

    if kind == "ncf":

        def loss_fn(params, batch_stats, batch):
            (users, items), labels = batch
            logits = model.apply({"params": params}, users, items)
            loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
            return loss, batch_stats

        return loss_fn

    raise ValueError(kind)


def run(args) -> dict:
    import jax
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.config import from_params
    from deepreduce_tpu.train import Trainer

    params = ast.literal_eval(args.grace_config) if args.grace_config else {}
    # --telemetry must land before construction: config validation is
    # cross-field (ctrl=True requires telemetry=True at __post_init__)
    if args.telemetry:
        params.setdefault("telemetry", True)
    # CLI-entered dicts get the strict treatment: a typo'd knob should kill
    # the run, not silently bench the default
    cfg = from_params(params, strict=True)
    from deepreduce_tpu.telemetry import spans

    if cfg.telemetry:
        spans.configure(enabled=True)
    model, (kind, spec, classes) = MODELS[args.model]()

    n_dev = min(args.num_workers, len(jax.devices()))
    if cfg.hier:
        # hier needs the two-axis (dcn, ici) mesh the Trainer shard_maps over
        from deepreduce_tpu.parallel import make_hybrid_mesh

        per_slice = cfg.ici_size or max(
            s for s in range(1, n_dev + 1) if n_dev % s == 0 and s * s <= n_dev
        )
        mesh = make_hybrid_mesh(n_dev // per_slice, per_slice)
    else:
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    trainer = Trainer(
        model, cfg, optax.sgd(args.learning_rate, momentum=0.9), mesh,
        loss_fn=make_loss(kind, model),
    )

    # per-purpose seeded streams (not one sequential stream): batch at step
    # s is a pure function of (seed, s), so a resumed run regenerates the
    # exact batches the killed run would have seen
    batch = make_batch(
        kind, spec, classes, args.batch_size,
        np.random.default_rng((args.seed, 0, 0)), model=model,
    )
    if kind == "ncf":
        sample = (batch[0], batch[1])
        init_batch = (batch[0], batch[1])
    else:
        init_batch = batch
    state = trainer.init_state(jax.random.PRNGKey(args.seed), init_batch)

    tracker = None
    if args.track_dir:
        from deepreduce_tpu import tracking

        tracker = tracking.Run(
            args.track_dir,
            name=args.run_name or None,
            config={"model": args.model, "workers": n_dev, **params},
            tags=[t for t in args.tags.split(",") if t],
        )
    if cfg.ctrl and tracker is not None:
        # the auditable decision trail: every controller evaluation lands
        # in <run dir>/decisions.jsonl (telemetry trace/summary render it)
        trainer.attach_decision_log(tracker.dir / "decisions.jsonl")

    ckpt_path = None
    if args.checkpoint_every or args.resume:
        from deepreduce_tpu import checkpoint

        ckpt_root = args.checkpoint_dir or (
            str(tracker.dir / "ckpt") if tracker is not None else ""
        )
        if not ckpt_root:
            raise ValueError(
                "--checkpoint-every/--resume need --checkpoint-dir (or "
                "--track_dir to default under the run directory)"
            )
        ckpt_path = pathlib.Path(ckpt_root) / "last"

    start_step = 0
    if args.resume and ckpt_path is not None and ckpt_path.exists():
        from deepreduce_tpu import checkpoint

        template = {"state": state}
        if cfg.telemetry:
            from deepreduce_tpu.telemetry import MetricAccumulators

            template["telemetry"] = MetricAccumulators.zeros(
                trainer.exchanger.num_buckets
            )
        if cfg.ctrl:
            template["ctrl"] = trainer.controller_state()
        restored = checkpoint.restore(str(ckpt_path), template, config=cfg)
        state = restored["state"]
        if cfg.telemetry:
            # the accumulator resumes too: summaries keep counting from the
            # killed run's totals instead of restarting at zero
            trainer._telemetry_acc = restored["telemetry"]
        if cfg.ctrl:
            # the controller trajectory resumes bitwise: rung index, vote
            # streaks, and the window baseline all come from the checkpoint
            trainer.load_controller_state(restored["ctrl"])
        start_step = int(state.step)
        print(f"resumed from {ckpt_path} at step {start_step}", flush=True)

    key = jax.random.PRNGKey(args.seed + 1)
    losses = []
    profiling = False
    profile_dir = args.profile_dir
    if profile_dir and args.num_steps < 3:
        print(
            f"WARNING: --profile_dir needs num_steps >= 3 to skip the compile "
            f"step (got {args.num_steps}); profiling disabled", flush=True,
        )
        profile_dir = None
    t0 = time.perf_counter()
    try:
        for step in range(start_step, args.num_steps):
            if profile_dir and step == start_step + 2 and not profiling:
                jax.profiler.start_trace(profile_dir)  # skip compile steps
                profiling = True
            batch = make_batch(
                kind, spec, classes, args.batch_size,
                np.random.default_rng((args.seed, 1, step)), model=model,
            )
            with spans.span("train/step"):
                state, loss, wire = trainer.step(
                    state, batch, jax.random.fold_in(key, step)
                )
            losses.append(float(loss))
            if (
                ckpt_path is not None
                and args.checkpoint_every
                and (step + 1) % args.checkpoint_every == 0
            ):
                from deepreduce_tpu import checkpoint

                payload = {"state": state}
                if cfg.telemetry:
                    payload["telemetry"] = trainer._telemetry_acc
                if cfg.ctrl:
                    payload["ctrl"] = trainer.controller_state()
                checkpoint.save(str(ckpt_path), payload, config=cfg)
            if tracker is not None:
                rec = {"loss": losses[-1], "rel_volume": float(wire.rel_volume())}
                if cfg.telemetry and (
                    step % cfg.telemetry_every == 0 or step == args.num_steps - 1
                ):
                    # the telemetry_every host sync: fetch the on-device
                    # accumulators and log them under a stable prefix
                    rec.update(
                        {f"telemetry.{k}": v
                         for k, v in trainer.telemetry_summary().items()}
                    )
                tracker.log(rec, step=step)
            if args.log_every and step % args.log_every == 0:
                print(
                    f"step {step} loss {losses[-1]:.4f} "
                    f"rel_volume {float(wire.rel_volume()):.4f}"
                )
    except BaseException:
        if profiling:
            jax.profiler.stop_trace()
        if tracker is not None:
            if cfg.telemetry:
                # a failing run still gets its trace — spans record in
                # finally, so the aborted step's phases are all present
                spans.get_tracer().save(tracker.dir / "trace.json")
            tracker.finish({"status": "failed", "steps_completed": len(losses)})
        raise
    if profiling:
        jax.profiler.stop_trace()
    elapsed = time.perf_counter() - t0

    if not losses:
        # resumed at or past --num_steps: nothing left to run
        result = {
            "model": args.model,
            "workers": n_dev,
            "steps": 0,
            "resumed_at": start_step,
            "config": params,
        }
        print(json.dumps(result))
        if tracker is not None:
            tracker.finish(result)
        return result

    result = {
        "model": args.model,
        "workers": n_dev,
        "steps": args.num_steps,
        "resumed_at": start_step,
        "global_batch": args.batch_size,
        "first_loss": losses[0],
        "last_loss": losses[-1],
        "examples_per_sec": args.batch_size * len(losses) / elapsed,
        "rel_volume": float(wire.rel_volume()),
        "idx_rel_volume": float(wire.idx_rel_volume()),
        "val_rel_volume": float(wire.val_rel_volume()),
        "payload_bytes_per_step": trainer.exchanger.payload_bytes(state.params),
        "config": params,
    }
    if cfg.telemetry:
        result["telemetry"] = trainer.telemetry_summary()
        if tracker is not None:
            spans.get_tracer().save(tracker.dir / "trace.json")
    if cfg.ctrl:
        ctrl = trainer.controller
        result["ctrl"] = {
            "index": int(ctrl.index),
            "ladder": list(ctrl.ladder.labels()),
            "windows": int(ctrl.windows),
            "switches": int(ctrl.switches),
            "effective_ratio": ctrl.effective_ratio(),
            "visited_indices": list(trainer.visited_ladder_indices),
        }
    print(json.dumps(result))
    if tracker is not None:
        tracker.finish(result)
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=sorted(MODELS), default="resnet20")
    ap.add_argument("--grace_config", type=str, default="")
    ap.add_argument("--num_steps", type=int, default=20)
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--num_workers", type=int, default=8)
    ap.add_argument("--learning_rate", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--log_every", type=int, default=5)
    ap.add_argument("--track_dir", type=str, default="",
                    help="experiment-tracking root (the reference's WANDB role)")
    ap.add_argument("--run_name", type=str, default="")
    ap.add_argument("--tags", type=str, default="",
                    help="comma-separated run tags (--extra_wandb_tags role)")
    ap.add_argument("--telemetry", action="store_true",
                    help="enable the telemetry subsystem (deepreduce_tpu."
                         "telemetry): span tracing (trace.json in the run "
                         "dir when --track_dir is set) plus on-device "
                         "metric accumulators fetched every "
                         "cfg.telemetry_every steps")
    ap.add_argument("--profile_dir", type=str, default="",
                    help="write a jax.profiler trace of the steady-state steps "
                         "(the reference's --log_time timing role, but a real "
                         "XLA trace instead of wall-clock prints)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="save the full train state (params, opt state, "
                         "residual EF memory, telemetry accumulator) every N "
                         "steps via deepreduce_tpu.checkpoint (0 = off)")
    ap.add_argument("--checkpoint-dir", type=str, default="",
                    help="checkpoint directory (defaults to <run dir>/ckpt "
                         "when --track_dir is set)")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the last checkpoint in the checkpoint "
                         "dir if one exists (config-fingerprint checked); "
                         "batches are regenerated per step from --seed, so a "
                         "killed run continues exactly")
    ap.add_argument("--platform", type=str, default="",
                    help="pin the JAX platform (e.g. 'cpu' for the 8-device "
                         "virtual mesh). Needed because env vars alone don't "
                         "override the ambient TPU tunnel's jax.config.")
    args = ap.parse_args()
    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=max(2, args.num_workers))
    run(args)


if __name__ == "__main__":
    main()
