"""Convergence-parity experiment — the reference's Table-1 methodology
(SURVEY.md §4.1: train with and without compression, compare final accuracy;
"pass" = compressed accuracy within noise of dense at a fraction of the data
volume).

No dataset egress in this environment, so the task is a *learnable* synthetic
classification problem (fixed random teacher network labels deterministic
inputs) rather than CIFAR — the comparison dense-vs-compressed is what the
experiment measures, and both arms see identical data. Runs on the 8-device
virtual CPU mesh or real TPU.

Falsifiability (VERDICT r3 #3): accuracy is measured on a HELD-OUT split of
the teacher task, sized so the dense baseline lands visibly below 1.0 —
a saturated task cannot show compression-induced degradation. Every arm
runs over ``--seeds`` independent seeds (data, init, and batch order all
re-drawn); the artifact reports mean ± std and the per-seed gaps, so
"parity" means |mean gap| within the seed noise band, not a single lucky
draw.

    python benchmarks/convergence.py --steps 150 \
      --grace_config "{'compressor':'topk','compress_ratio':0.05,
                       'memory':'residual','deepreduce':'both',
                       'index':'bloom','value':'qsgd','fpr':0.01}"

Prints one JSON line: dense vs compressed final accuracy, gap, and the
compressed arm's relative wire volume.
"""

from __future__ import annotations

import argparse
import ast
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


def make_task(n_train, n_eval, dim, classes, seed):
    """Deterministic teacher-labelled dataset with a held-out eval split:
    learnable but not saturable (the student sees too little data to mimic
    the teacher perfectly), identical for both arms."""
    rng = np.random.default_rng(seed)
    w1 = rng.normal(size=(dim, 64)) / np.sqrt(dim)
    w2 = rng.normal(size=(64, classes)) / 8.0
    x = rng.normal(size=(n_train + n_eval, dim)).astype(np.float32)
    y = np.argmax(np.tanh(x @ w1) @ w2, axis=1).astype(np.int32)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def accuracy(model, params, batch_stats, x, y, batch=256):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def logits_fn(xb):
        variables = {"params": params}
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
        return model.apply(variables, xb)

    correct = 0
    for lo in range(0, len(x), batch):
        out = logits_fn(jnp.asarray(x[lo : lo + batch]))
        correct += int((np.argmax(np.asarray(out), axis=1) == y[lo : lo + batch]).sum())
    return correct / len(x)


def train_arm(cfg, train, evalset, classes, steps, batch, lr, seed, n_dev):
    import jax
    import optax
    from jax.sharding import Mesh

    import flax.linen as nn

    from deepreduce_tpu.train import Trainer

    class MLP(nn.Module):
        classes: int

        @nn.compact
        def __call__(self, xb):
            xb = nn.relu(nn.Dense(128)(xb))
            xb = nn.relu(nn.Dense(128)(xb))
            return nn.Dense(self.classes)(xb)

    x, y = train
    model = MLP(classes=classes)
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    trainer = Trainer(model, cfg, optax.sgd(lr, momentum=0.9), mesh)
    state = trainer.init_state(jax.random.PRNGKey(seed), (x[:batch], y[:batch]))

    key = jax.random.PRNGKey(seed + 1)
    order = np.random.default_rng(seed + 2).permutation(len(x))
    wire = None
    for step in range(steps):
        sel = order[(np.arange(batch) + step * batch) % len(x)]  # full batch, wraps
        state, loss, wire = trainer.step(
            state, (x[sel], y[sel]), jax.random.fold_in(key, step)
        )
    acc = accuracy(model, state.params, state.batch_stats, *evalset)
    return acc, float(wire.rel_volume())


# The reference's headline Table-2 shapes (paper §6.2), at topk 10% like the
# LSTM rows: rel-volume ordering must reproduce Top-r > BF-P0 > DRQSGD
# (0.2033 > 0.1425 > 0.0621 in the paper).
SUITE = {
    "topr": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
    },
    "bf_p0_index": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "index", "index": "bloom", "policy": "p0",
        "fpr": 0.02, "bloom_blocked": "mod", "min_compress_size": 500,
    },
    "drqsgd_bf_p0": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "bloom", "value": "qsgd",
        "policy": "p0", "fpr": 0.02, "bloom_blocked": "mod",
        "min_compress_size": 500,
    },
    # the scatter-free insert_from_dense A/B arm (config.bloom_threshold_insert):
    # inserts the threshold SUPERSET of the top-k (ties join), so the
    # candidate tpu_defaults flip needs its own convergence evidence, not
    # just the TPU timing win
    "bf_p0_index_ti": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "index", "index": "bloom", "policy": "p0",
        "fpr": 0.02, "bloom_blocked": "mod", "min_compress_size": 500,
        "bloom_threshold_insert": True,
    },
    # the fully fused sparsifier-free encode (bloom.encode_dense_direct):
    # sampled threshold + threshold insert — TensorCodec.direct_bloom routes
    # here; convergence evidence for the composition, not just its halves
    "bf_p0_index_sampled_ti": {
        "compressor": "topk_sampled", "topk_sample_size": 2048,
        "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "index", "index": "bloom", "policy": "p0",
        "fpr": 0.02, "bloom_blocked": "mod", "min_compress_size": 500,
        "bloom_threshold_insert": True,
    },
    "drfit_bf_p0": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "bloom", "value": "polyfit",
        "policy": "p0", "fpr": 0.02, "bloom_blocked": "mod",
        "min_compress_size": 500,
    },
    # flagship wire with the sortless sampled-threshold sparsifier; the
    # small sample bound keeps the sampled path LIVE at this harness's leaf
    # sizes (the default 32k sample would exact-fallback every leaf here)
    "drqsgd_bf_p0_sampled": {
        "compressor": "topk_sampled", "topk_sample_size": 2048,
        "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "bloom", "value": "qsgd",
        "policy": "p0", "fpr": 0.02, "bloom_blocked": "mod",
        "min_compress_size": 500,
    },
    # the repo bench's own headline config (bench.py drqsgd_delta): delta
    # bit-packed indices + QSGD values — convergence-backed like the rest
    "drqsgd_delta": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "integer", "value": "qsgd",
        "policy": "p0", "min_compress_size": 500,
    },
    # the paper's Fit-DExp value family (§6.1): 4-coefficient double
    # exponential over the kept magnitudes
    "drdexp_bf_p0": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "bloom", "value": "doubleexp",
        "policy": "p0", "fpr": 0.02, "bloom_blocked": "mod",
        "min_compress_size": 500,
    },
    # beyond-reference collectives, convergence-backed like the codecs:
    # int8 quantized reduce-scatter+allgather (EQuARX shape) ...
    "qar_int8": {
        "compressor": "none", "memory": "none", "communicator": "qar",
    },
    # ... and sparse reduce-scatter (Ok-Topk/SparCML shape)
    "sparse_rs_topk": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "communicator": "sparse_rs",
    },
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grace_config", type=str, default=(
        "{'compressor':'topk','compress_ratio':0.05,'memory':'residual',"
        "'deepreduce':'both','index':'bloom','value':'qsgd','fpr':0.01,"
        "'min_compress_size':500}"))
    ap.add_argument("--suite", type=str, default="",
                    help="run the paper's Table-2 config suite against one "
                         "shared dense baseline and write results to this "
                         "JSON file (ignores --grace_config)")
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.1)
    ap.add_argument("--n_examples", type=int, default=8192)
    ap.add_argument("--eval_examples", type=int, default=4096)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seeds", type=int, default=3,
                    help="independent repeats (data+init+order re-drawn); "
                         "suite mode reports mean±std over these")
    ap.add_argument("--platform", type=str, default="cpu",
                    help="'cpu' (default) forces the 8-device virtual CPU mesh "
                         "— accuracy results are platform-independent and the "
                         "ambient TPU tunnel can hang for hours; pass '' to "
                         "use the ambient platform")
    args = ap.parse_args()

    if args.steps < 1:
        ap.error("--steps must be >= 1")
    if args.n_examples < 2 * args.batch_size:
        ap.error("--n_examples must be at least 2x --batch_size")

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform)

    import jax

    n_dev = min(8, len(jax.devices()))
    if args.batch_size % n_dev:
        ap.error(f"--batch_size must be divisible by the {n_dev}-device mesh")

    from deepreduce_tpu.config import DeepReduceConfig, from_params

    dense_cfg = DeepReduceConfig(
        compressor="none", deepreduce=None, memory="none", communicator="allreduce"
    )

    seeds = [args.seed + 1000 * s for s in range(max(1, args.seeds))]
    tasks = {
        s: make_task(args.n_examples, args.eval_examples, args.dim, args.classes, s)
        for s in seeds
    }
    dense_accs = {}
    for s in seeds:
        train, evalset = tasks[s]
        dense_accs[s], _ = train_arm(
            dense_cfg, train, evalset, args.classes, args.steps,
            args.batch_size, args.learning_rate, s, n_dev,
        )
        print(json.dumps({"dense": {"seed": s, "acc": round(dense_accs[s], 4)}}),
              file=sys.stderr)
    d_mean = float(np.mean(list(dense_accs.values())))
    d_std = float(np.std(list(dense_accs.values())))

    def run_config(params, params_doc):
        cfg = from_params(params, strict=True)
        accs, gaps, rel_volume = [], [], None
        for s in seeds:
            train, evalset = tasks[s]
            acc, rel_volume = train_arm(
                cfg, train, evalset, args.classes, args.steps,
                args.batch_size, args.learning_rate, s, n_dev,
            )
            accs.append(acc)
            gaps.append(dense_accs[s] - acc)
        return {
            "dense_acc_mean": round(d_mean, 4),
            "dense_acc_std": round(d_std, 4),
            "compressed_acc_mean": round(float(np.mean(accs)), 4),
            "compressed_acc_std": round(float(np.std(accs)), 4),
            "acc_gap_mean": round(float(np.mean(gaps)), 4),
            "acc_gap_std": round(float(np.std(gaps)), 4),
            "per_seed_acc": [round(a, 4) for a in accs],
            "rel_volume": round(rel_volume, 4),
            "seeds": seeds,
            "config": params_doc,
        }

    if args.suite:
        results = {}
        for name, params in SUITE.items():
            results[name] = run_config(params, params)
            print(json.dumps({name: results[name]}), file=sys.stderr)
        doc = {
            "task": "synthetic-teacher classification, HELD-OUT eval (no "
                    "dataset egress); methodology = paper Table 1/2: accuracy "
                    "vs dense at a fraction of the wire volume; dense < 1.0 "
                    "so degradation is observable",
            "steps": args.steps,
            "batch_size": args.batch_size,
            "n_devices": n_dev,
            "n_seeds": len(seeds),
            "paper_table2_rel_volume_order": "topr 0.2033 > bf_p0 0.1425 > drqsgd 0.0621",
            "results": results,
        }
        with open(args.suite, "w") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps(doc))
        return

    params = ast.literal_eval(args.grace_config)
    out = run_config(params, params)
    out["steps"] = args.steps
    print(json.dumps(out))


if __name__ == "__main__":
    main()
