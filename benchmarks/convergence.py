"""Convergence-parity experiment — the reference's Table-1 methodology
(SURVEY.md §4.1: train with and without compression, compare final accuracy;
"pass" = compressed accuracy within noise of dense at a fraction of the data
volume).

No dataset egress in this environment, so the task is a *learnable* synthetic
classification problem (fixed random teacher network labels deterministic
inputs) rather than CIFAR — the comparison dense-vs-compressed is what the
experiment measures, and both arms see identical data. Runs on the 8-device
virtual CPU mesh or real TPU.

    python benchmarks/convergence.py --steps 150 \
      --grace_config "{'compressor':'topk','compress_ratio':0.05,
                       'memory':'residual','deepreduce':'both',
                       'index':'bloom','value':'qsgd','fpr':0.01}"

Prints one JSON line: dense vs compressed final accuracy, gap, and the
compressed arm's relative wire volume.
"""

from __future__ import annotations

import argparse
import ast
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


def make_task(n, dim, classes, seed):
    """Deterministic teacher-labelled dataset: learnable, identical for
    both arms."""
    rng = np.random.default_rng(seed)
    w1 = rng.normal(size=(dim, 64)) / np.sqrt(dim)
    w2 = rng.normal(size=(64, classes)) / 8.0
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = np.argmax(np.tanh(x @ w1) @ w2, axis=1).astype(np.int32)
    return x, y


def accuracy(model, params, batch_stats, x, y, batch=256):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def logits_fn(xb):
        variables = {"params": params}
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
        return model.apply(variables, xb)

    correct = 0
    for lo in range(0, len(x), batch):
        out = logits_fn(jnp.asarray(x[lo : lo + batch]))
        correct += int((np.argmax(np.asarray(out), axis=1) == y[lo : lo + batch]).sum())
    return correct / len(x)


def train_arm(cfg, x, y, steps, batch, lr, seed, n_dev):
    import jax
    import optax
    from jax.sharding import Mesh

    import flax.linen as nn

    from deepreduce_tpu.train import Trainer

    class MLP(nn.Module):
        classes: int

        @nn.compact
        def __call__(self, xb):
            xb = nn.relu(nn.Dense(128)(xb))
            xb = nn.relu(nn.Dense(128)(xb))
            return nn.Dense(self.classes)(xb)

    classes = int(y.max()) + 1
    model = MLP(classes=classes)
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    trainer = Trainer(model, cfg, optax.sgd(lr, momentum=0.9), mesh)
    state = trainer.init_state(jax.random.PRNGKey(seed), (x[:batch], y[:batch]))

    key = jax.random.PRNGKey(seed + 1)
    order = np.random.default_rng(seed + 2).permutation(len(x))
    wire = None
    for step in range(steps):
        sel = order[(np.arange(batch) + step * batch) % len(x)]  # full batch, wraps
        state, loss, wire = trainer.step(
            state, (x[sel], y[sel]), jax.random.fold_in(key, step)
        )
    acc = accuracy(model, state.params, state.batch_stats, x, y)
    return acc, float(wire.rel_volume())


# The reference's headline Table-2 shapes (paper §6.2), at topk 10% like the
# LSTM rows: rel-volume ordering must reproduce Top-r > BF-P0 > DRQSGD
# (0.2033 > 0.1425 > 0.0621 in the paper).
SUITE = {
    "topr": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
    },
    "bf_p0_index": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "index", "index": "bloom", "policy": "p0",
        "fpr": 0.02, "bloom_blocked": "mod", "min_compress_size": 500,
    },
    "drqsgd_bf_p0": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "bloom", "value": "qsgd",
        "policy": "p0", "fpr": 0.02, "bloom_blocked": "mod",
        "min_compress_size": 500,
    },
    "drfit_bf_p0": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "bloom", "value": "polyfit",
        "policy": "p0", "fpr": 0.02, "bloom_blocked": "mod",
        "min_compress_size": 500,
    },
    # the repo bench's own headline config (bench.py drqsgd_delta): delta
    # bit-packed indices + QSGD values — convergence-backed like the rest
    "drqsgd_delta": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "integer", "value": "qsgd",
        "policy": "p0", "min_compress_size": 500,
    },
    # the paper's Fit-DExp value family (§6.1): 4-coefficient double
    # exponential over the kept magnitudes
    "drdexp_bf_p0": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "deepreduce": "both", "index": "bloom", "value": "doubleexp",
        "policy": "p0", "fpr": 0.02, "bloom_blocked": "mod",
        "min_compress_size": 500,
    },
    # beyond-reference collectives, convergence-backed like the codecs:
    # int8 quantized reduce-scatter+allgather (EQuARX shape) ...
    "qar_int8": {
        "compressor": "none", "memory": "none", "communicator": "qar",
    },
    # ... and sparse reduce-scatter (Ok-Topk/SparCML shape)
    "sparse_rs_topk": {
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "communicator": "sparse_rs",
    },
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grace_config", type=str, default=(
        "{'compressor':'topk','compress_ratio':0.05,'memory':'residual',"
        "'deepreduce':'both','index':'bloom','value':'qsgd','fpr':0.01,"
        "'min_compress_size':500}"))
    ap.add_argument("--suite", type=str, default="",
                    help="run the paper's Table-2 config suite against one "
                         "shared dense baseline and write results to this "
                         "JSON file (ignores --grace_config)")
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.1)
    ap.add_argument("--n_examples", type=int, default=4096)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", type=str, default="",
                    help="'cpu' forces the 8-device virtual CPU mesh (env vars "
                         "alone don't stick under the axon TPU tunnel)")
    args = ap.parse_args()

    if args.steps < 1:
        ap.error("--steps must be >= 1")
    if args.n_examples < 2 * args.batch_size:
        ap.error("--n_examples must be at least 2x --batch_size")

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform)

    import jax

    n_dev = min(8, len(jax.devices()))
    if args.batch_size % n_dev:
        ap.error(f"--batch_size must be divisible by the {n_dev}-device mesh")

    from deepreduce_tpu.config import DeepReduceConfig, from_params

    x, y = make_task(args.n_examples, args.dim, args.classes, args.seed)

    dense_cfg = DeepReduceConfig(
        compressor="none", deepreduce=None, memory="none", communicator="allreduce"
    )

    dense_acc, _ = train_arm(
        dense_cfg, x, y, args.steps, args.batch_size, args.learning_rate, args.seed, n_dev
    )

    if args.suite:
        results = {}
        for name, params in SUITE.items():
            comp_acc, rel_volume = train_arm(
                from_params(params), x, y, args.steps, args.batch_size,
                args.learning_rate, args.seed, n_dev,
            )
            results[name] = {
                "dense_acc": round(dense_acc, 4),
                "compressed_acc": round(comp_acc, 4),
                "acc_gap": round(dense_acc - comp_acc, 4),
                "rel_volume": round(rel_volume, 4),
                "config": params,
            }
            print(json.dumps({name: results[name]}), file=sys.stderr)
        doc = {
            "task": "synthetic-teacher classification (no dataset egress); "
                    "methodology = paper Table 1/2: accuracy vs dense at a "
                    "fraction of the wire volume",
            "steps": args.steps,
            "batch_size": args.batch_size,
            "n_devices": n_dev,
            "paper_table2_rel_volume_order": "topr 0.2033 > bf_p0 0.1425 > drqsgd 0.0621",
            "results": results,
        }
        with open(args.suite, "w") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps(doc))
        return

    comp_cfg = from_params(ast.literal_eval(args.grace_config))
    comp_acc, rel_volume = train_arm(
        comp_cfg, x, y, args.steps, args.batch_size, args.learning_rate, args.seed, n_dev
    )

    print(json.dumps({
        "dense_acc": round(dense_acc, 4),
        "compressed_acc": round(comp_acc, 4),
        "acc_gap": round(dense_acc - comp_acc, 4),
        "rel_volume": round(rel_volume, 4),
        "steps": args.steps,
        "config": ast.literal_eval(args.grace_config),
    }))


if __name__ == "__main__":
    main()
