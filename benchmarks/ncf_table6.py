"""NCF natural-sparsity fidelity — the paper's Table-6 experiment shape.

The reference's natively-sparse benchmark (paper §6.2, Table 6; SURVEY.md
§6): NeuMF on ML-20m, threshold-0.0 sparsification (natural sparsity —
embedding rows untouched by the batch have exactly-zero gradient), bloom
index at FPR 0.6 with policy P0, QSGD values (7-bit, bucket 512). Paper
records DRQSGD-BF-P0 at 0.2063 relative volume, HR within noise.

Static-shape port: each tensor's threshold budget is calibrated from a
sample gradient (`sparse.calibrate_threshold_budget`), and
`sparse.threshold_overflow` verifies the budget captured every nonzero
(overflow 0) on fresh batches. Run:

    python benchmarks/ncf_table6.py --out NCF_TABLE6.json [--platform cpu]

Prints/writes: per-leaf natural sparsity, overflow on a held-out batch,
and the tree-wide relative volume next to the paper's 0.2063.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="")
    ap.add_argument("--interactions", type=int, default=150_000,
                    help="user-item pairs per batch (ML-20m-like geometry)")
    ap.add_argument("--platform", type=str, default="")
    ap.add_argument("--safety", type=float, default=1.25)
    args = ap.parse_args()

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform)

    import jax
    import jax.numpy as jnp
    import optax

    from deepreduce_tpu import sparse
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.models import NeuMF
    from deepreduce_tpu.wrappers import TensorCodec

    model = NeuMF()
    rng = np.random.default_rng(0)

    def batch_at(seed):
        r = np.random.default_rng(seed)
        users = jnp.asarray(r.integers(0, model.num_users, args.interactions))
        items = jnp.asarray(r.integers(0, model.num_items, args.interactions))
        labels = jnp.asarray(r.integers(0, 2, args.interactions).astype(np.float32))
        return users, items, labels

    users, items, labels = batch_at(0)
    params = model.init(jax.random.PRNGKey(0), users, items)["params"]

    def loss_fn(p, users, items, labels):
        logits = model.apply({"params": p}, users, items)
        return optax.sigmoid_binary_cross_entropy(logits, labels).mean()

    grad_fn = jax.jit(jax.grad(loss_fn))
    sample = grad_fn(params, users, items, labels)

    # Table-6 codec config: threshold 0.0 + bloom FPR 0.6 P0 + QSGD 7-bit
    base = DeepReduceConfig(
        compressor="threshold", threshold_val=0.0, memory="none",
        deepreduce="both", index="bloom", value="qsgd", policy="p0",
        fpr=0.6, bloom_blocked="mod", quantum_num=127, bucket_size=512,
        min_compress_size=1000,
    )

    leaves, treedef = jax.tree_util.tree_flatten_with_path(sample)
    fresh = grad_fn(params, *batch_at(1))
    fresh_leaves = jax.tree_util.tree_leaves(fresh)

    per_leaf = {}
    total_bits = 0.0
    dense_bits = 0.0
    key = jax.random.PRNGKey(0)
    for i, ((path, leaf), fresh_leaf) in enumerate(zip(leaves, fresh_leaves)):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        ratio = sparse.calibrate_threshold_budget(leaf, 0.0, safety=args.safety)
        cfg = dataclasses.replace(base, compress_ratio=ratio)
        codec = TensorCodec(tuple(leaf.shape), cfg, name=name)
        payload = jax.jit(lambda t: codec.encode(t, step=0, key=key))(fresh_leaf)
        stats = codec.wire_stats(payload)
        overflow = int(sparse.threshold_overflow(fresh_leaf, 0.0, budget_ratio=ratio))
        per_leaf[name] = {
            "d": int(np.prod(leaf.shape)),
            "natural_sparsity": round(float(sparse.natural_sparsity(fresh_leaf)), 4),
            "budget_ratio": round(ratio, 4),
            "overflow_on_fresh_batch": overflow,
            "rel_volume": round(float(stats.rel_volume()), 4),
        }
        total_bits += float(stats.total_bits)
        dense_bits += float(stats.dense_bits)
        print(json.dumps({name: per_leaf[name]}), file=sys.stderr)

    doc = {
        "experiment": "NCF/NeuMF natural sparsity (paper Table 6 shape): "
                      "threshold 0.0 + bloom FPR 0.6 P0 + QSGD 127/512",
        "interactions_per_batch": args.interactions,
        "paper_rel_volume": 0.2063,
        "rel_volume": round(total_bits / dense_bits, 4),
        "total_overflow": sum(v["overflow_on_fresh_batch"] for v in per_leaf.values()),
        "per_leaf": per_leaf,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
