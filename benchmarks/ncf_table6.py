"""NCF natural-sparsity fidelity — the paper's Table-6 experiment shape.

The reference's natively-sparse benchmark (paper §6.2, Table 6; SURVEY.md
§6): NeuMF on ML-20m with **10^6 local batch size** (Table-6 caption),
threshold-0.0 sparsification (natural sparsity — embedding rows untouched
by the batch have exactly-zero gradient), bloom index at FPR 0.6 with
policy P0, QSGD values at "7-bits quantization" (caption), bucket 512.
Paper records DRQSGD-BF-P0 at 0.2063 relative volume, HR within noise.

Geometry: ML-20m itself is not in this image (zero egress), so the batch
generator reproduces its *gradient geometry*: 1 positive + 4 uniform
negatives per interaction (the NCF training recipe), users drawn from a
power-law popularity model (``--user_zipf``, default 0.8) whose skew is
calibrated so the tree-wide nonzero fraction lands where the paper's own
Table-6 numbers imply (~0.6 — back-solved from DRQSGD 0.2063 vs
SKCompress 0.2175 at 7 bits/value). Item embeddings see the 4x uniform
negatives, so they are effectively dense — leaves whose calibrated budget
saturates at 1.0 are transmitted positionally dense through QSGD alone
(no index stream), the reference's bypass semantics
(pytorch/deepreduce.py:68): never ship an index structure that selects
everything.

Static-shape port: each tensor's threshold budget is calibrated from a
sample gradient (`sparse.calibrate_threshold_budget`), and
`sparse.threshold_overflow` verifies the budget captured every nonzero
(overflow 0) on fresh batches. Run:

    python benchmarks/ncf_table6.py --out NCF_TABLE6.json [--platform cpu]

Prints/writes: per-leaf natural sparsity, overflow on a held-out batch,
and the tree-wide relative volume next to the paper's 0.2063.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="")
    ap.add_argument("--interactions", type=int, default=1_000_000,
                    help="samples per local batch (paper Table-6 caption: 10^6)")
    ap.add_argument("--user_zipf", type=float, default=0.8,
                    help="user-popularity power-law exponent (ML-20m-like skew)")
    ap.add_argument("--negatives", type=int, default=4,
                    help="uniform negative items per positive (NCF recipe)")
    # default cpu: this is an accounting/accuracy harness whose numbers are
    # platform-independent, and the ambient axon tunnel can hang for hours;
    # pass --platform '' to use the ambient platform
    ap.add_argument("--platform", type=str, default="cpu")
    ap.add_argument("--safety", type=float, default=1.25)
    args = ap.parse_args()

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform)

    import jax
    import jax.numpy as jnp
    import optax

    from deepreduce_tpu import sparse
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.models import NeuMF
    from deepreduce_tpu.wrappers import TensorCodec

    model = NeuMF()

    # user popularity ~ power law (recommendation-data skew); items get the
    # 4x uniform negative sampling of the NCF recipe, which makes item
    # embeddings effectively dense at 10^6 batch
    u_w = (np.arange(1, model.num_users + 1, dtype=np.float64)) ** (-args.user_zipf)
    u_w /= u_w.sum()
    i_w = (np.arange(1, model.num_items + 1, dtype=np.float64)) ** (-args.user_zipf)
    i_w /= i_w.sum()
    per_pos = 1 + args.negatives
    n_pos = args.interactions // per_pos

    def batch_at(seed):
        r = np.random.default_rng(seed)
        pos_users = r.choice(model.num_users, size=n_pos, p=u_w)
        pos_items = r.choice(model.num_items, size=n_pos, p=i_w)
        neg_items = r.integers(0, model.num_items, n_pos * args.negatives)
        users = np.concatenate([pos_users, np.repeat(pos_users, args.negatives)])
        items = np.concatenate([pos_items, neg_items])
        labels = np.concatenate(
            [np.ones(n_pos, np.float32), np.zeros(n_pos * args.negatives, np.float32)]
        )
        return jnp.asarray(users), jnp.asarray(items), jnp.asarray(labels)

    users, items, labels = batch_at(0)
    params = model.init(jax.random.PRNGKey(0), users, items)["params"]

    def loss_fn(p, users, items, labels):
        logits = model.apply({"params": p}, users, items)
        return optax.sigmoid_binary_cross_entropy(logits, labels).mean()

    grad_fn = jax.jit(jax.grad(loss_fn))
    sample = grad_fn(params, users, items, labels)

    # Table-6 codec config: threshold 0.0 + bloom FPR 0.6 P0 + QSGD at the
    # caption's "7-bits quantization" (q=63: sign + 6-bit magnitude), bucket 512
    base = DeepReduceConfig(
        compressor="threshold", threshold_val=0.0, memory="none",
        deepreduce="both", index="bloom", value="qsgd", policy="p0",
        fpr=0.6, bloom_blocked="mod", quantum_num=63, bucket_size=512,
        min_compress_size=1000,
    )
    # fully-dense leaves (calibrated budget saturates): positional dense
    # QSGD, no index stream — a filter that selects everything is pure
    # overhead (reference bypass semantics, pytorch/deepreduce.py:68)
    dense_qsgd = DeepReduceConfig(
        compressor="none", memory="none", deepreduce="value", value="qsgd",
        quantum_num=63, bucket_size=512, min_compress_size=1000,
    )

    leaves, treedef = jax.tree_util.tree_flatten_with_path(sample)
    fresh = grad_fn(params, *batch_at(1))
    fresh_leaves = jax.tree_util.tree_leaves(fresh)

    per_leaf = {}
    total_bits = 0.0
    dense_bits = 0.0
    key = jax.random.PRNGKey(0)
    for i, ((path, leaf), fresh_leaf) in enumerate(zip(leaves, fresh_leaves)):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        ratio = sparse.calibrate_threshold_budget(leaf, 0.0, safety=args.safety)
        dense_leaf = ratio >= 1.0
        if dense_leaf:
            cfg = dense_qsgd
        else:
            cfg = dataclasses.replace(base, compress_ratio=ratio)
        codec = TensorCodec(tuple(leaf.shape), cfg, name=name)
        payload = jax.jit(lambda t: codec.encode(t, step=0, key=key))(fresh_leaf)
        stats = codec.wire_stats(payload)
        overflow = (
            0
            if dense_leaf
            else int(sparse.threshold_overflow(fresh_leaf, 0.0, budget_ratio=ratio))
        )
        per_leaf[name] = {
            "d": int(np.prod(leaf.shape)),
            "natural_sparsity": round(float(sparse.natural_sparsity(fresh_leaf)), 4),
            "budget_ratio": round(ratio, 4),
            "route": "dense_qsgd" if dense_leaf else "threshold_bloom_qsgd",
            "overflow_on_fresh_batch": overflow,
            "rel_volume": round(float(stats.rel_volume()), 4),
        }
        total_bits += float(stats.total_bits)
        dense_bits += float(stats.dense_bits)
        print(json.dumps({name: per_leaf[name]}), file=sys.stderr)

    doc = {
        "experiment": "NCF/NeuMF natural sparsity (paper Table 6 shape): "
                      "threshold 0.0 + bloom FPR 0.6 P0 + QSGD 7-bit/512; "
                      "saturated leaves positional dense QSGD (no index stream)",
        "interactions_per_batch": args.interactions,
        "user_zipf": args.user_zipf,
        "negatives_per_positive": args.negatives,
        "paper_rel_volume": 0.2063,
        "rel_volume": round(total_bits / dense_bits, 4),
        "total_overflow": sum(v["overflow_on_fresh_batch"] for v in per_leaf.values()),
        "per_leaf": per_leaf,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
