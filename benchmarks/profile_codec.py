"""Per-stage codec profiler — the micro-benchmark mode at stage granularity.

The reference's `'micro-benchmark': True` times whole compress/decompress
calls (pytorch/deepreduce.py:70-76); this tool additionally splits the
flagship bloom pipeline into its stages (sparsify / insert / query+prefix /
bloom-encode / value-codec / full encode / full decode) so a perf regression
points at a stage, not a codec. Timing is amortized: `reps` async dispatches
per synchronization, best of `iters` — the only reliable method through the
axon tunnel, whose per-dispatch overhead (50-70ms) and `block_until_ready`
semantics swamp single-call timings.

    python benchmarks/profile_codec.py --d 4053428 --ratio 0.1 --fpr 0.02
    python benchmarks/profile_codec.py --platform cpu   # structure check
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


from bench import _progress, _sync, _timeit  # noqa: E402 — shared sync + amortized timing


def amortized(fn, *args, reps: int = 10, iters: int = 4) -> float:
    """One timing protocol for the whole repo: bench._timeit."""
    return _timeit(fn, *args, iters=iters, reps=reps)


def _staged(stages: dict, label: str, fn, *args, reps: int) -> None:
    """Time one stage with progress markers so a wrapper timeout points at
    the stage that ate the budget, not at the whole run."""
    _progress(f"stage {label}: timing")
    stages[label] = amortized(fn, *args, reps=reps)
    _progress(f"stage {label}: {stages[label] * 1e3:.2f} ms")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d", type=int, default=4_053_428)
    ap.add_argument("--ratio", type=float, default=0.1)
    ap.add_argument("--fpr", type=float, default=0.02)
    ap.add_argument("--index", default="bloom")
    ap.add_argument("--value", default="qsgd")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument(
        "--compressor", default="topk",
        help="sparsifier for the pipeline arms (topk | topk_sampled | ...)",
    )
    ap.add_argument("--platform", default=None)
    ap.add_argument(
        "--threshold_insert",
        action="store_true",
        help="A/B: scatter-free insert_from_dense instead of the unique-scatter insert",
    )
    args = ap.parse_args()

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=1)

    import jax
    import jax.numpy as jnp

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.utils import enable_compile_cache
    from deepreduce_tpu.wrappers import TensorCodec

    enable_compile_cache()
    cfg = DeepReduceConfig.tpu_defaults(
        compressor=args.compressor,
        compress_ratio=args.ratio,
        deepreduce="both",
        index=args.index,
        value=args.value,
        policy="p0",
        fpr=args.fpr,
        bloom_threshold_insert=args.threshold_insert,
    )
    codec = TensorCodec((args.d,), cfg, name="profile")
    rng = np.random.default_rng(0)
    g = jnp.asarray((rng.normal(size=args.d) * rng.random(args.d) ** 2).astype(np.float32))
    key = jax.random.PRNGKey(0)

    stages = {}
    geometry = {}

    f_sp = jax.jit(lambda t: codec.sparsify(t, key=key))
    _progress("compiling sparsify")
    sp = _sync(f_sp(g))
    _staged(stages, "sparsify", f_sp, g, reps=args.reps)

    # standalone sparsifier A/B at this d/ratio: exact O(d log k) top_k vs
    # TPU approx_max_k vs the sortless sampled-threshold selection
    from deepreduce_tpu import sparse as sparse_mod

    for label, fn in [
        ("sparsify_exact", lambda t: sparse_mod.topk(t, args.ratio)),
        ("sparsify_approx", lambda t: sparse_mod.topk(t, args.ratio, approx=True)),
        ("sparsify_sampled", lambda t: sparse_mod.topk_sampled(t, args.ratio)),
    ]:
        f = jax.jit(fn)
        _progress(f"compiling {label}")
        _sync(f(g))
        _staged(stages, label, f, g, reps=args.reps)

    if args.index == "bloom":
        from deepreduce_tpu.codecs import bloom

        meta = codec.idx_codec.meta
        geometry = {
            "W_words": meta.m_bits // 32,
            "num_hash": meta.num_hash,
            "budget": meta.budget,
            "blocked": meta.blocked,
        }
        if args.threshold_insert:
            # live-masked min with a zero guard, exactly as encode computes
            # it — a kept zero value would otherwise saturate the filter and
            # the A/B would time a degenerate all-ones table
            live = jnp.arange(sp.k, dtype=jnp.int32) < sp.nnz
            thresh = jnp.min(jnp.where(live, jnp.abs(sp.values), jnp.inf))
            assert float(thresh) > 0, "degenerate input: kept zero magnitude"
            f_ins = jax.jit(lambda t, th: bloom.insert_from_dense(t, th, meta))
            _progress("compiling insert")
            words = _sync(f_ins(g, thresh))
            _staged(stages, "insert", f_ins, g, thresh, reps=args.reps)
        else:
            f_ins = jax.jit(lambda i, n: bloom.insert(i, n, meta))
            _progress("compiling insert")
            words = _sync(f_ins(sp.indices, sp.nnz))
            _staged(stages, "insert", f_ins, sp.indices, sp.nnz, reps=args.reps)

        f_qp = jax.jit(
            lambda w: bloom._prefix_positions(bloom.query_universe(w, meta), meta.budget)
        )
        _progress("compiling query+prefix")
        _sync(f_qp(words))
        _staged(stages, "query+prefix", f_qp, words, reps=args.reps)

        f_be = jax.jit(
            lambda s, t: bloom.encode(s, t, meta, threshold_insert=args.threshold_insert)
        )
        _progress("compiling bloom.encode")
        bpay = _sync(f_be(sp, g))
        _staged(stages, "bloom.encode", f_be, sp, g, reps=args.reps)
        # saturation guard (ADVICE r3): nsel == budget means the selection
        # truncated — a threshold-insert A/B would compare different
        # effective selections without this signal
        geometry["nsel"] = int(bpay.nsel)
        geometry["saturated"] = bool(bloom.saturated(bpay, meta))
        if args.threshold_insert and geometry["saturated"]:
            print(
                "WARNING: threshold_insert saturated its widened budget "
                f"(nsel == {meta.budget}); A/B timings are NOT comparable",
                file=sys.stderr,
            )

        # composite sub-chains, to localize where the whole exceeds the sum
        # of its parts (round-3 mystery: encode ~2x the stage sum):
        # sparsify+bloom in ONE program — if this matches its parts, fusion
        # across the sparsify/insert boundary is fine and the gap is later
        f_sb = jax.jit(
            lambda t: bloom.encode(
                codec.sparsify(t, key=key), t, meta,
                threshold_insert=args.threshold_insert,
            )
        )
        _progress("compiling sparsify+bloom.encode")
        _sync(f_sb(g))
        _staged(stages, "sparsify+bloom.encode", f_sb, g, reps=args.reps)

    # index side of the full wrapper encode (sparsify + idx codec, no value
    # codec / payload assembly): encode - encode_idx_only isolates the value
    # codec AND the BothPayload assembly as they run inside the full graph
    if codec.idx_codec is not None:
        if getattr(codec, "direct_bloom", False):
            # the wrapper's full encode routes the sparsifier-free direct
            # path — time the same path here or 'encode - encode_idx_only'
            # would subtract a stage the full graph never runs
            f_ei = jax.jit(
                lambda t, s: codec.idx_codec.encode_direct(
                    t,
                    sample_size=codec.cfg.topk_sample_size,
                    undershoot=codec.cfg.topk_undershoot,
                )
            )
        else:
            f_ei = jax.jit(
                lambda t, s: codec.idx_codec.encode(
                    codec.sparsify(t, key=key), dense=t, step=s, key=key
                )
            )
        _progress("compiling encode_idx_only")
        _sync(f_ei(g, 0))
        _staged(stages, "encode_idx_only", f_ei, g, 1, reps=args.reps)

    f_enc = jax.jit(lambda t, s: codec.encode(t, step=s, key=key))
    _progress("compiling encode")
    payload = _sync(f_enc(g, 0))
    if getattr(codec, "direct_bloom", False):
        # the wrapper routed the sparsifier-free encode_dense_direct: its
        # sampled threshold inserts a superset of the standard path's, so
        # nsel/saturation must be measured on THIS payload — the standard
        # bpay's flag above would let a truncated direct selection pass as
        # comparable (ADVICE-r3 guard, extended to the direct path)
        geometry["nsel"] = int(payload.nsel)
        geometry["saturated"] = bool(
            bloom.saturated(payload, codec.idx_codec.meta)
        )
        if geometry["saturated"]:
            print(
                "WARNING: direct encode saturated its widened budget "
                f"(nsel == {codec.idx_codec.meta.budget}); A/B timings are "
                "NOT comparable",
                file=sys.stderr,
            )
    _staged(stages, "encode", f_enc, g, 1, reps=args.reps)

    f_dec = jax.jit(lambda p, s: codec.decode(p, step=s))
    _progress("compiling decode")
    _sync(f_dec(payload, 0))
    _staged(stages, "decode", f_dec, payload, 1, reps=args.reps)

    out = {
        "d": args.d,
        "ratio": args.ratio,
        "fpr": args.fpr,
        "index": args.index,
        "value": args.value,
        "platform": jax.devices()[0].platform,
        "meta": geometry,
        "stages_ms": {k: round(v * 1e3, 3) for k, v in stages.items()},
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
