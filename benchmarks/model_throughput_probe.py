"""Standalone single-model training-step throughput probe with a batch knob.

`bench.py`'s model section pins ResNet-50 at batch 128 (the round-3 silicon
record: 1003 img/s, MFU ~0.062 vs bf16 peak). This probe varies the batch so
the MFU-vs-batch curve is measurable on the real chip — either a larger
batch lifts MFU toward the BASELINE.json north star, or the flat curve IS
the bottleneck analysis (HBM-bound convs / tunnel dispatch, not MXU
starvation). Same protocol as bench._model_throughput: device-resident
batch, chained async steps, amortized wall per step, XLA cost-analysis
flops.

    python benchmarks/model_throughput_probe.py --model resnet50 --batch 256
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from bench import (  # noqa: E402 — shared presets + protocol with bench's model table
    _chip_peak_flops,
    _progress,
    _step_flops,
    _sync,
    throughput_cfgs,
    throughput_models,
    time_chained_steps,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50", choices=["resnet50", "resnet20"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--config", default="topk1_bloom", choices=["topk1_bloom", "dense"])
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=1)

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu.train import Trainer
    from deepreduce_tpu.utils import enable_compile_cache

    enable_compile_cache()
    rng = np.random.default_rng(0)
    model, hw, nclass, _default_batch = throughput_models()[args.model]
    cfg = throughput_cfgs()[args.config]
    images = jnp.asarray(rng.normal(size=(args.batch, hw, hw, 3)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, nclass, args.batch).astype(np.int32))
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    trainer = Trainer(model, cfg, optax.sgd(0.1), mesh)
    _progress(f"{args.model} b{args.batch} {args.config}: compiling step")
    state = trainer.init_state(jax.random.PRNGKey(0), (images, labels))
    step = lambda s, i: trainer.step(s, (images, labels), jax.random.PRNGKey(i))
    state, _, _ = step(state, 0)
    _sync(state.params)
    _progress("timing")
    t_step, state = time_chained_steps(step, state, reps=args.reps)
    flops = _step_flops(trainer, state, images, labels)
    peak = _chip_peak_flops()
    out = {
        "model": args.model,
        "batch": args.batch,
        "config": args.config,
        "platform": jax.devices()[0].platform,
        "images_per_sec": round(args.batch / t_step, 2),
        "step_time_s": round(t_step, 4),
    }
    if flops:
        out["flops_per_step"] = flops
        out["mfu_vs_bf16_peak"] = round(flops / t_step / peak, 4)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
