#!/usr/bin/env bash
# Benchmark sweep driver — the run_deepreduce.sh role, minus MPI/Horovod:
# the "cluster" is the device mesh, so no mpirun, no host lists, no NCCL
# socket pinning. Each block mirrors a reference experiment family.
set -euo pipefail
cd "$(dirname "$0")/.."

STEPS=${STEPS:-20}
PY=${PY:-python}

# Artifact-freshness gate (r4 review: committed tables must come from the
# committed harnesses). Any table artifact older than the harness (or the
# wrapper/accounting layer) that produces it is flagged up front.
check_fresh() {  # check_fresh ARTIFACT SRC...
  local art="$1"; shift
  [ -f "$art" ] || return 0
  for src in "$@" deepreduce_tpu/wrappers.py deepreduce_tpu/metrics.py; do
    if [ "$src" -nt "$art" ]; then
      echo "STALE ARTIFACT: $art is older than $src — regenerate it" >&2
      STALE=1
    fi
  done
}
STALE=0
check_fresh CONVERGENCE.json benchmarks/convergence.py
check_fresh LSTM_TABLE2.json benchmarks/lstm_table2.py
check_fresh MOBILENET_TABLE5.json benchmarks/mobilenet_table5.py
check_fresh NCF_TABLE6.json benchmarks/ncf_table6.py
if [ "${STRICT_FRESH:-0}" = "1" ] && [ "$STALE" = "1" ]; then
  exit 3
fi

echo "== dense baseline (allreduce) =="
$PY benchmarks/train.py --model resnet20 --num_steps $STEPS \
  --grace_config "{'compressor':'none','memory':'none','communicator':'allreduce'}"

echo "== Top-r 1% + residual (plain sparsification) =="
$PY benchmarks/train.py --model resnet20 --num_steps $STEPS \
  --grace_config "{'compressor':'topk','compress_ratio':0.01,'memory':'residual','communicator':'allgather'}"

echo "== DR*BF (index bloom, fp-aware) =="
$PY benchmarks/train.py --model resnet20 --num_steps $STEPS \
  --grace_config "{'compressor':'topk','compress_ratio':0.01,'memory':'residual','communicator':'allgather','deepreduce':'index','index':'bloom','fpr':0.001,'policy':'leftmost'}"

echo "== DRFit-Poly (value polyfit) =="
$PY benchmarks/train.py --model resnet20 --num_steps $STEPS \
  --grace_config "{'compressor':'topk','compress_ratio':0.01,'memory':'residual','communicator':'allgather','deepreduce':'value','value':'polyfit'}"

echo "== DRQSGD-BF-P0 (the paper's headline combo) =="
$PY benchmarks/train.py --model resnet20 --num_steps $STEPS \
  --grace_config "{'compressor':'topk','compress_ratio':0.01,'memory':'residual','communicator':'allgather','deepreduce':'both','index':'bloom','value':'qsgd','fpr':0.01,'policy':'p0','quantum_num':127,'bucket_size':512}"

echo "== NCF natively-sparse (threshold 0, value qsgd, FPR 0.6 P0: paper Table 6) =="
$PY benchmarks/train.py --model ncf --num_steps $STEPS --batch_size 256 \
  --grace_config "{'compressor':'threshold','threshold':0.0,'compress_ratio':0.01,'memory':'residual','communicator':'allgather','deepreduce':'both','index':'bloom','value':'qsgd','fpr':0.6,'policy':'p0'}"

echo "== BERT-base allgather stress (new config, BASELINE.json #5) =="
$PY benchmarks/train.py --model bert --num_steps 3 --batch_size 8 \
  --grace_config "{'compressor':'topk','compress_ratio':0.001,'memory':'residual','communicator':'allgather','deepreduce':'both','index':'bloom','value':'polyfit','fpr':0.001,'bloom_blocked':True}"

echo "== Quantized allreduce (int8 in-collective, qar.py; beyond the reference) =="
$PY benchmarks/train.py --model resnet20 --num_steps $STEPS \
  --grace_config "{'communicator':'qar','compressor':'none','memory':'none','quantum_num':127,'bucket_size':512}"

echo "== Convergence parity (Table-1 methodology, dense vs compressed arm) =="
$PY benchmarks/convergence.py --steps ${CONV_STEPS:-150}

echo "== VGG16 (third PolySeg model family) with the flagship codec =="
$PY benchmarks/train.py --model vgg16 --num_steps $STEPS --batch_size 16 \
  --grace_config "{'compressor':'topk','compress_ratio':0.01,'memory':'residual','communicator':'allgather','deepreduce':'both','index':'bloom','value':'qsgd','fpr':0.02,'policy':'p0'}"

echo "== Sparse reduce-scatter communicator (Ok-Topk shape; beyond the reference) =="
$PY benchmarks/train.py --model resnet20 --num_steps $STEPS \
  --grace_config "{'communicator':'sparse_rs','compressor':'topk','compress_ratio':0.01,'memory':'residual'}"

echo "== MobileNet FedAvg (paper Table 5 shape) =="
$PY benchmarks/mobilenet_table5.py --rounds ${FED_ROUNDS:-25}

echo "== NCF natural-sparsity accounting (paper Table 6 shape) =="
$PY benchmarks/ncf_table6.py

echo "== Per-stage codec profile (flagship bloom pipeline) =="
$PY benchmarks/profile_codec.py

echo "== LSTM FedAvg 56 clients (paper Table 2 shape) =="
$PY benchmarks/lstm_table2.py --rounds ${T2_ROUNDS:-25}
