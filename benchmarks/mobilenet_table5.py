"""MobileNet federated-learning experiment — the paper's Table-5 shape.

Reference (paper §6.2 Table 5, BASELINE.md): MobileNet/CIFAR-10 FedAvg with
10 clients per round; DRQSGD-BF-P0 transmits 0.0713 relative volume at
87.40% vs the 88.17% dense baseline (800 rounds on the T4 testbed). This
harness runs the same topology end-to-end — bidirectionally-compressed
FedAvg over the real MobileNetV1 family — at smoke scale: a narrow model
(width_mult 0.25), a learnable synthetic image task (class prototypes +
noise; no dataset egress in this environment), and tens of rounds. The
measured quantities mirror the paper's: compressed-vs-dense accuracy gap
and Table-2-style relative wire volume across both directions.

    python benchmarks/mobilenet_table5.py --out MOBILENET_TABLE5.json
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

PAPER_REL_VOLUME = 0.0713  # DRQSGD-BF-P0, paper Table 5
PAPER_DENSE_ACC = 0.8817
PAPER_COMPRESSED_ACC = 0.8740


def make_task(n, classes, seed, size=16, proto_seed=1, noise=2.5):
    """Class-prototype images + heavy noise: learnable but NOT saturable —
    the noise level is chosen so the smoke-scale model lands visibly below
    1.0 (VERDICT r3 #3), making compression-induced degradation observable.
    Prototypes come from `proto_seed` so train and eval splits share the
    same classes and differ only in sampling noise."""
    protos = (
        np.random.default_rng(proto_seed)
        .normal(size=(classes, size, size, 3))
        .astype(np.float32)
    )
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    x = protos[y] + noise * rng.normal(size=(n, size, size, 3)).astype(np.float32)
    return x, y


def run_arm(cfg_params, rounds, seed, size=16, classes=10, noise=2.5):
    import jax
    import jax.numpy as jnp
    import optax

    from deepreduce_tpu import FedAvg, FedConfig
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.models import MobileNetV1

    model = MobileNetV1(num_classes=classes, width_mult=0.25)
    proto_seed = seed * 17 + 1
    x, y = make_task(4096, classes, seed=seed * 17 + 2, size=size,
                     proto_seed=proto_seed, noise=noise)
    xe, ye = make_task(1024, classes, seed=seed * 17 + 3, size=size,
                       proto_seed=proto_seed, noise=noise)

    variables = model.init(jax.random.PRNGKey(seed), jnp.asarray(x[:2]), train=True)
    params = variables["params"]
    # Batch-mode BN with locally-discarded running stats — the FedBN
    # pattern: normalization statistics stay client-local (never transmitted
    # or aggregated), while the learnable scale/bias ride in params through
    # the compressed exchange like every other weight. FedAvg state tracks
    # params only, and both arms see identical normalization semantics.
    bn_stats = variables.get("batch_stats")

    def apply_fn(params, xb):
        v = {"params": params}
        if bn_stats is not None:
            v["batch_stats"] = bn_stats
        out, _ = model.apply(v, xb, train=True, mutable=["batch_stats"])
        return out

    def loss_fn(params, batch_xy):
        xb, yb = batch_xy
        logits = apply_fn(params, xb)
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    cfg = DeepReduceConfig.tpu_defaults(**cfg_params) if cfg_params else None
    fed = FedConfig(num_clients=10, clients_per_round=10, local_steps=4)
    if cfg is None:
        cfg = DeepReduceConfig(compressor="none", memory="none")
    # momentum restarts every round (client state is not federated), so the
    # client lr carries the progress. At noise 2.5 BOTH arms keep improving
    # well past 40 rounds (dense 0.63 -> 0.93 between rounds 40 and 120);
    # an artifact taken mid-convergence measures convergence *speed*, not
    # the paper's at-convergence parity claim — default rounds below is
    # sized so both arms plateau (r5: gap 0.0068 at 120 rounds vs the
    # paper's own 0.0077 at its 800)
    fa = FedAvg(loss_fn, cfg, fed, optax.sgd(0.2, momentum=0.9))
    state = fa.init(params)
    run_round = jax.jit(fa.run_round)

    batch = 24
    vol = None
    rng = np.random.default_rng(seed + 10)
    for r in range(rounds):
        key = jax.random.PRNGKey(1000 + r)
        ids = fa.sample_clients(state, key)
        pick = rng.integers(0, len(x), size=(fed.clients_per_round, fed.local_steps, batch))
        xs = jnp.asarray(x[pick])
        ys = jnp.asarray(y[pick])
        state, out = run_round(state, ids, (xs, ys), jax.random.fold_in(key, 1))
        vol = float(out["rel_volume"])

    @jax.jit
    def logits_fn(xb):
        return apply_fn(state.params, xb)

    correct = 0
    for lo in range(0, len(xe), 256):
        out_l = logits_fn(jnp.asarray(xe[lo : lo + 256]))
        correct += int((np.argmax(np.asarray(out_l), axis=1) == ye[lo : lo + 256]).sum())
    return correct / len(xe), vol


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=120)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--noise", type=float, default=2.5)
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default="cpu")
    args = ap.parse_args()

    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=1)

    drqsgd = dict(
        compressor="topk",
        compress_ratio=0.1,
        deepreduce="both",
        index="bloom",
        value="qsgd",
        policy="p0",
        fpr=0.02,
        min_compress_size=500,
    )
    seeds = list(range(max(1, args.seeds)))
    dense_accs, comp_accs, gaps, vol = {}, [], [], None
    for s in seeds:
        dense_accs[s], _ = run_arm(None, args.rounds, seed=s, noise=args.noise)
        print(json.dumps({"dense": {"seed": s, "acc": round(dense_accs[s], 4)}}),
              file=sys.stderr)
    for s in seeds:
        acc, vol = run_arm(drqsgd, args.rounds, seed=s, noise=args.noise)
        comp_accs.append(acc)
        gaps.append(dense_accs[s] - acc)
        print(json.dumps({"drqsgd": {"seed": s, "acc": round(acc, 4)}}),
              file=sys.stderr)
    result = {
        "experiment": "MobileNet FedAvg, 10 clients/round, DRQSGD-BF-P0 both "
                      "ways (paper Table 5 shape); noise level keeps dense "
                      "visibly below 1.0 so degradation is observable",
        "rounds": args.rounds,
        "n_seeds": len(seeds),
        "noise": args.noise,
        "paper": {
            "rel_volume": PAPER_REL_VOLUME,
            "dense_acc": PAPER_DENSE_ACC,
            "compressed_acc": PAPER_COMPRESSED_ACC,
        },
        "dense_acc_mean": round(float(np.mean(list(dense_accs.values()))), 4),
        "dense_acc_std": round(float(np.std(list(dense_accs.values()))), 4),
        "compressed_acc_mean": round(float(np.mean(comp_accs)), 4),
        "compressed_acc_std": round(float(np.std(comp_accs)), 4),
        "acc_gap_mean": round(float(np.mean(gaps)), 4),
        "acc_gap_std": round(float(np.std(gaps)), 4),
        "per_seed_dense": [round(a, 4) for a in dense_accs.values()],
        "per_seed_compressed": [round(a, 4) for a in comp_accs],
        "rel_volume": round(vol, 4),
        "config": drqsgd,
    }
    line = json.dumps(result)
    print(line)
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(result, indent=1) + "\n")


if __name__ == "__main__":
    main()
