#!/usr/bin/env bash
# One-shot TPU measurement sweep — run when the device tunnel is up.
# Appends one JSON line per measurement to $OUT (default tpu_sweep.jsonl)
# so a tunnel drop mid-sweep loses only the in-flight measurement.
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${OUT:-tpu_sweep.jsonl}
PY=${PY:-python}
LSTM_D=4053428
R50_D=25557032

probe() {
  timeout 120 $PY -c "
import jax, jax.numpy as jnp, numpy as np
v = jax.jit(lambda t: t*2.0)(jnp.zeros((8,), jnp.float32))
assert np.asarray(v[:1]) is not None
print('tpu-ok')" 2>/dev/null | grep -q tpu-ok
}

if ! probe; then
  echo "tunnel down — aborting sweep" >&2
  exit 1
fi

run() {
  echo "== $* ==" >&2
  timeout 900 "$@" 2>/dev/null | tail -1 >> "$OUT" || echo "(failed: $*)" >&2
}

run $PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.02
run $PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.02 --threshold_insert
run $PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.001
run $PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.001 --threshold_insert
run $PY benchmarks/profile_codec.py --d $R50_D --ratio 0.01 --fpr 0.001
run $PY benchmarks/profile_codec.py --d $R50_D --ratio 0.01 --fpr 0.001 --threshold_insert
run $PY benchmarks/profile_codec.py --d $LSTM_D --index integer
# sampled-threshold sparsifier A/B: every profile run above already times
# sparsify_exact/approx/sampled standalone; these two measure the full
# pipeline with the sampled selection driving the flagship codec
run $PY benchmarks/profile_codec.py --d $LSTM_D --fpr 0.02 --compressor topk_sampled
run $PY benchmarks/profile_codec.py --d $R50_D --ratio 0.01 --fpr 0.001 --compressor topk_sampled
echo "== bench.py (full) ==" >&2
timeout 3000 $PY bench.py 2>/dev/null | tail -1 >> "$OUT" || echo "(bench failed)" >&2
echo "sweep done -> $OUT" >&2
