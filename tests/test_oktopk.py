"""Ok-Topk balanced in-collective route (rs_mode='oktopk', r18): psum'd
bit-pattern histogram threshold, capacity-capped balanced all_to_all,
transmitted-mass oracle exactness, capacity-spill EF containment, config
fences, cost-model mirror, selector regime split, telemetry rows."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import shared_mesh
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepreduce_tpu import costmodel, sparse, sparse_rs
from deepreduce_tpu.config import DeepReduceConfig

W = 8
LSTM_D = 4_053_428  # the paper's StackOverflow LSTM gradient length


def _run(flat_w, ratio, *, workers=W, out_headroom=1.0, bins=4096,
         cap_headroom=2.0, with_collect=False):
    """[workers, d] per-worker gradients -> (mean, own[, collect rows])."""

    def spmd(g):
        collect = {} if with_collect else None
        mean, own, stats = sparse_rs.exchange(
            g[0], "data", workers, ratio=ratio, rs_mode="oktopk",
            out_headroom=out_headroom, oktopk_bins=bins,
            oktopk_cap_headroom=cap_headroom, collect=collect,
        )
        if with_collect:
            return (mean[None], own[None],
                    collect["rs_oktopk_survivors"][None],
                    collect["rs_oktopk_threshold"][None],
                    collect["rs_oktopk_spills"][None])
        return mean[None], own[None]

    n_out = 5 if with_collect else 2
    fn = jax.jit(
        shard_map(
            spmd, mesh=shared_mesh(workers), in_specs=(P("data"),),
            out_specs=tuple(P("data") for _ in range(n_out)),
            check_vma=False,
        )
    )
    return fn(flat_w)


def _assert_transmitted_oracle(flat_w, mean, own, workers):
    """The route's exactness contract: the aggregate is the mean of the
    TRANSMITTED (own) masses — never of the full gradients; Ok-Topk keeps
    sub-threshold and capacity-spilled mass in the sender's residual. And
    own itself is a bitwise subset of the worker's gradient."""
    mean = np.asarray(mean)
    own = np.asarray(own)
    assert np.allclose(mean, mean[0][None])  # workers agree
    want = own.astype(np.float64).sum(axis=0) / workers
    np.testing.assert_allclose(mean[0], want, rtol=1e-6, atol=1e-7)
    for w in range(workers):
        nz = np.nonzero(own[w])[0]
        np.testing.assert_array_equal(own[w][nz], flat_w[w][nz])


def test_mean_equals_transmitted_oracle():
    """Random gradients, ample phase-2 budget: the mean must equal the
    sum-of-own-transmitted oracle (no coordinate is invented or dropped
    after routing), with every own entry bitwise from the sender."""
    rng = np.random.default_rng(20)
    d, ratio = 4096, 0.02
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, own = _run(jnp.asarray(flat_w), ratio, out_headroom=2.0 * W)
    _assert_transmitted_oracle(flat_w, mean, own, W)


def test_w2_mesh_exact():
    """The smallest real mesh (W=2): balanced routing with one peer."""
    rng = np.random.default_rng(21)
    W2, d, ratio = 2, 4096, 0.02
    flat_w = rng.normal(size=(W2, d)).astype(np.float32)
    mean, own = _run(
        jnp.asarray(flat_w), ratio, workers=W2, out_headroom=2.0 * W2
    )
    _assert_transmitted_oracle(flat_w, mean, own, W2)


def test_unaligned_d_padded_tail():
    """d not divisible by W: the short last shard must stay exact — local
    indices route relative to their shard and the [:d] slice drops the
    padding."""
    rng = np.random.default_rng(22)
    d, ratio = 4090, 0.02  # W*S = 4096 > d
    assert d % W != 0
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, own = _run(jnp.asarray(flat_w), ratio, out_headroom=2.0 * W)
    _assert_transmitted_oracle(flat_w, mean, own, W)


def test_all_equal_magnitudes_deterministic():
    """Degenerate histogram: every candidate ties in ONE bucket, so the
    threshold admits them all and capacity does the triage. The route has
    no PRNG — two runs must agree bitwise — and the collect observables
    must report the tie storm: survivors == W*k (identical workers),
    per-worker spills == survivors/W - kept."""
    d, ratio = 4096, 0.02
    k = sparse.num_slots(d, ratio)
    g = np.zeros(d, np.float32)
    g[:k] = 2.5  # all-equal magnitudes, all in shard 0
    flat_w = np.tile(g, (W, 1))
    out1 = _run(jnp.asarray(flat_w), ratio, with_collect=True)
    out2 = _run(jnp.asarray(flat_w), ratio, with_collect=True)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mean, own, survivors, threshold, spills = out1
    _assert_transmitted_oracle(flat_w, mean, own, W)
    assert np.all(np.asarray(survivors) == float(W * k))
    assert np.all(np.asarray(threshold) > 0.0)
    Bo = sparse_rs.oktopk_send_budget(d, ratio, W)
    kept = np.count_nonzero(np.asarray(own)[0])
    assert kept <= Bo  # every candidate lives in shard 0: one pair's cap
    assert np.all(np.asarray(spills) == float(k - kept))


def test_zero_gradient_zero_survivors():
    """All-zero gradients: the mag>0 guard keeps zeros out of the
    histogram, so nothing survives, nothing routes, and every observable
    reads zero — no NaNs from the empty threshold."""
    flat_w = np.zeros((W, 4096), np.float32)
    mean, own, survivors, threshold, spills = _run(
        jnp.asarray(flat_w), 0.02, with_collect=True
    )
    assert np.all(np.asarray(mean) == 0.0)
    assert np.all(np.asarray(own) == 0.0)
    assert np.all(np.asarray(survivors) == 0.0)
    assert np.all(np.asarray(spills) == 0.0)
    assert np.all(np.asarray(threshold) == 0.0)


def test_capacity_spill_lands_in_residual_bitwise():
    """Adversarial crowding: k distinct magnitudes all in shard 0. The
    per-pair capacity keeps only the largest Bo survivors; the residual
    (gradient minus own-transmitted) must hold every spilled entry at its
    exact bitwise value and zero at every kept position."""
    d, ratio = 4096, 0.05  # k=204
    k = sparse.num_slots(d, ratio)
    g = np.zeros(d, np.float32)
    g[:k] = np.arange(1, k + 1, dtype=np.float32)  # largest at highest idx
    flat_w = np.tile(g, (W, 1))
    mean, own = _run(jnp.asarray(flat_w), ratio, out_headroom=2.0 * W)
    own0 = np.asarray(own)[0]
    sent = np.nonzero(own0)[0]
    Bo = sparse_rs.oktopk_send_budget(d, ratio, W)
    assert 0 < len(sent) <= Bo  # capacity engaged (survivors >> Bo)
    # stable routing keeps descending-|v| order: kept == largest magnitudes
    np.testing.assert_array_equal(sent, np.arange(k - len(sent), k))
    residual = g - own0
    np.testing.assert_array_equal(residual[sent], np.zeros(len(sent)))
    spilled = np.setdiff1d(np.arange(k), sent)
    np.testing.assert_array_equal(residual[spilled], g[spilled])
    _assert_transmitted_oracle(flat_w, mean, own, W)


def test_dispatcher_rejects_approx_candidates():
    """The threshold-containment argument needs the EXACT local top-k
    candidate set; approximate candidates can miss global survivors. The
    traced-path backstop mirrors the config fence."""
    flat = jnp.zeros((4096,), jnp.float32)
    with pytest.raises(ValueError, match="approx_topk"):
        sparse_rs.exchange(
            flat, "data", W, ratio=0.02, rs_mode="oktopk", approx_topk=True
        )


def _cfg(**kw):
    return DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, memory="none",
        communicator="sparse_rs", deepreduce=None, **kw,
    )


def test_config_validates_oktopk_knobs():
    cfg = _cfg(rs_mode="oktopk", rs_oktopk_bins=1024, rs_oktopk_cap_headroom=1.5)
    assert cfg.rs_oktopk_bins == 1024
    for bad_bins in (0, 32, 1000, 1 << 25):
        with pytest.raises(ValueError, match="rs-oktopk-bins-range"):
            _cfg(rs_oktopk_bins=bad_bins)
    with pytest.raises(ValueError, match="rs-oktopk-cap-headroom-range"):
        _cfg(rs_oktopk_cap_headroom=0.0)
    with pytest.raises(ValueError, match="rs-oktopk-vs-approx-topk"):
        _cfg(rs_mode="oktopk", approx_topk=True)
    # the fence is oktopk-specific: approx candidates stay fine elsewhere
    assert _cfg(rs_mode="sparse", approx_topk=True).approx_topk


def test_costmodel_wire_dict_mirrors_route():
    """The per-collective byte dict the jx-wire-accounting rule pins must
    be exactly the route's static shapes: bins f32 lanes psum'd, W*Bo
    (value, index) pairs through the all_to_all, K2 pairs gathered."""
    for d, ratio, Wm in ((4096, 0.02, 8), (8192, 0.05, 16), (4090, 0.01, 2)):
        wire = costmodel.rs_wire_bytes("oktopk", d, Wm, ratio)
        Bo = sparse_rs.oktopk_send_budget(d, ratio, Wm)
        K2 = sparse_rs.out_budget(d, ratio, Wm)
        assert wire == {
            "psum": 4096 * 4.0,
            "all_to_all": Wm * Bo * 8.0,
            "all_gather": K2 * 8.0,
        }
        assert costmodel.rs_payload_bytes("oktopk", d, Wm, ratio) == sum(
            wire.values()
        )


def test_selector_regime_split():
    """The acceptance regime: at the LSTM gradient length the O(k) route
    dominates the whole sparse grid — including ratio <= 0.01 — while the
    small-d picks that seeded the committed lattice/calibration artifacts
    are untouched (argmin over 5 == argmin over the old 4)."""
    old = ("sparse", "adaptive", "quantized", "sketch")
    for ratio in (0.001, 0.01, 0.1):
        for Wm in (8, 16, 32):
            assert costmodel.select_rs_mode(LSTM_D, Wm, ratio) == "oktopk"
            t_ok = costmodel.rs_step_time("oktopk", LSTM_D, Wm, ratio)
            t_q = costmodel.rs_step_time("quantized", LSTM_D, Wm, ratio)
            if ratio <= 0.01:
                assert t_ok < t_q
    for d in (4096, 8192):
        for ratio in (0.001, 0.01, 0.02, 0.1):
            for Wm in (8, 16, 32):
                assert costmodel.select_rs_mode(d, Wm, ratio) == \
                    costmodel.select_rs_mode(d, Wm, ratio, modes=old)


def test_telemetry_accumulates_and_derives_oktopk_rows():
    from deepreduce_tpu.metrics import WireStats
    from deepreduce_tpu.telemetry.device_metrics import MetricAccumulators

    acc = MetricAccumulators.zeros()
    wire = WireStats(
        index_bits=jnp.asarray(32.0), value_bits=jnp.asarray(64.0),
        dense_bits=jnp.asarray(4096.0),
    )
    acc = acc.accumulate(
        wire, rs_oktopk_survivors=150.0, rs_oktopk_threshold=3.0,
        rs_oktopk_spills=4.0,
    )
    acc = acc.accumulate(
        wire, rs_oktopk_survivors=130.0, rs_oktopk_threshold=5.0,
        rs_oktopk_spills=0.0,
    )
    rows = acc.summary()
    assert rows["rs_oktopk_survivors_per_step"] == pytest.approx(140.0)
    assert rows["rs_oktopk_threshold"] == pytest.approx(4.0)
    assert rows["rs_oktopk_spill_rate"] == pytest.approx(2.0)


def test_trainer_path_oktopk_ef_residual():
    """Full GradientExchanger round: finite aggregate, wire volume far
    under dense (O(k) route), residual retains the untransmitted mass."""
    from deepreduce_tpu.comm import GradientExchanger

    rng = np.random.default_rng(23)
    d = 8192
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, memory="residual",
        communicator="sparse_rs", deepreduce=None, rs_mode="oktopk",
    )
    grads = {"g": jnp.asarray(rng.normal(size=d).astype(np.float32))}
    ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=W)
    state = ex.init_state(grads)

    def spmd(g, res):
        agg, new_res, stats = ex.exchange(
            g, res, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(0)
        )
        return agg, new_res, stats

    fn = jax.jit(
        shard_map(
            spmd, mesh=shared_mesh(W), in_specs=(P(), P()),
            out_specs=(P(), P(), P()), check_vma=False,
        )
    )
    agg, new_state, stats = fn(grads, state)
    assert np.isfinite(np.asarray(agg["g"])).all()
    vol = float(stats.rel_volume())
    assert 0 < vol < 1.0
    res = np.asarray(jax.tree_util.tree_leaves(new_state)[0])
    assert np.abs(res).sum() > 0
    assert ex.payload_bytes(grads) == costmodel.rs_payload_bytes(
        "oktopk", d, W, cfg.compress_ratio,
        bins=cfg.rs_oktopk_bins, cap_headroom=cfg.rs_oktopk_cap_headroom,
    )
