"""Sparsifier and SparseGrad tests (vs numpy oracles)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu import sparse


def test_topk_matches_numpy():
    rng = np.random.default_rng(0)
    g = rng.normal(size=(40, 50)).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.01)
    k = max(1, int(g.size * 0.01))
    assert sp.k == k
    want = set(np.argsort(-np.abs(g.reshape(-1)))[:k].tolist())
    assert set(np.asarray(sp.indices).tolist()) == want
    np.testing.assert_allclose(np.asarray(sp.values), g.reshape(-1)[np.asarray(sp.indices)])
    assert int(sp.nnz) == k


def test_topk_indices_sorted():
    g = np.random.default_rng(1).normal(size=(5000,)).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.02)
    idx = np.asarray(sp.indices)
    assert np.all(np.diff(idx) > 0)


def test_to_dense_round_trip():
    g = np.random.default_rng(2).normal(size=(64, 32)).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.05)
    dense = np.asarray(sp.to_dense())
    assert dense.shape == g.shape
    flat = g.reshape(-1)
    idx = np.asarray(sp.indices)
    np.testing.assert_allclose(dense.reshape(-1)[idx], flat[idx])
    mask = np.zeros(g.size, bool)
    mask[idx] = True
    assert np.all(dense.reshape(-1)[~mask] == 0)


def test_randomk_distinct_and_keyed():
    g = jnp.ones((10000,))
    k1 = jax.random.PRNGKey(0)
    k2 = jax.random.PRNGKey(1)
    sp1 = sparse.randomk(g, 0.01, k1)
    sp2 = sparse.randomk(g, 0.01, k2)
    idx1 = np.asarray(sp1.indices)
    assert len(set(idx1.tolist())) == sp1.k  # without replacement
    assert not np.array_equal(idx1, np.asarray(sp2.indices))  # key matters
    sp1b = sparse.randomk(g, 0.01, k1)
    np.testing.assert_array_equal(idx1, np.asarray(sp1b.indices))  # deterministic


def test_threshold_semantics():
    g = np.zeros(5000, np.float32)
    hot = np.random.default_rng(3).choice(5000, 37, replace=False)
    g[hot] = np.random.default_rng(4).normal(size=37).astype(np.float32) + 5.0
    sp = sparse.threshold(jnp.asarray(g), 1.0, budget_ratio=0.02)
    assert int(sp.nnz) == 37
    live_idx = np.asarray(sp.indices)[: int(sp.nnz)]
    assert set(live_idx.tolist()) == set(hot.tolist())
    # dense reconstruction exact
    np.testing.assert_allclose(np.asarray(sp.to_dense()), g)


def test_threshold_budget_overflow_keeps_largest():
    g = np.arange(1, 1001, dtype=np.float32)
    sp = sparse.threshold(jnp.asarray(g), 0.5, budget_ratio=0.01)  # budget 10, all pass thr
    assert int(sp.nnz) == 10
    live = np.asarray(sp.indices)[:10]
    assert set(live.tolist()) == set(range(990, 1000))


def test_none_sparsifier():
    g = np.random.default_rng(5).normal(size=(33,)).astype(np.float32)
    sp = sparse.none_sparsifier(jnp.asarray(g))
    np.testing.assert_allclose(np.asarray(sp.to_dense()), g)


def test_sparsifiers_jit_stable():
    g = jnp.asarray(np.random.default_rng(6).normal(size=(2048,)).astype(np.float32))
    f = jax.jit(lambda x: sparse.topk(x, 0.01))
    sp = f(g)
    sp2 = f(g * 2)
    assert sp.values.shape == sp2.values.shape


def test_stable_name_hash_cross_process():
    """Per-tensor keys must agree across processes regardless of
    PYTHONHASHSEED (the multi-host determinism contract,
    bloom_filter_compression.cc:217-218). Python's hash(str) is salted;
    stable_name_hash must not be."""
    import subprocess
    import sys

    prog = (
        "from deepreduce_tpu.sparse import stable_name_hash;"
        "print(stable_name_hash('resnet/conv1/kernel'), stable_name_hash(''))"
    )
    outs = set()
    for seed in ("0", "1", "31337"):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True,
            env=env, check=True, cwd=os.path.dirname(os.path.dirname(__file__)),
        ).stdout.strip()
        outs.add(out)
    assert len(outs) == 1, f"hash varies across processes: {outs}"
    # and matches this process too
    h1, h2 = outs.pop().split()
    assert int(h1) == sparse.stable_name_hash("resnet/conv1/kernel")
    assert int(h2) == sparse.stable_name_hash("")


def test_per_tensor_key_distinct():
    base = jax.random.PRNGKey(0)
    k1 = sparse.per_tensor_key(base, "a/kernel", jnp.asarray(0))
    k2 = sparse.per_tensor_key(base, "a/bias", jnp.asarray(0))
    k3 = sparse.per_tensor_key(base, "a/kernel", jnp.asarray(1))
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    assert not np.array_equal(np.asarray(k1), np.asarray(k3))


def test_threshold_zero_natural_sparsity_and_overflow():
    """threshold 0.0 = natural sparsity (nonzeros only, the NCF config):
    zeros are NOT selected, the calibrated budget captures every nonzero
    (overflow 0), and an undersized budget reports exactly the excess."""
    d = 10_000
    rng = np.random.default_rng(31)
    g = np.zeros(d, np.float32)
    nz = rng.choice(d, 700, replace=False)
    g[nz] = rng.normal(size=700).astype(np.float32)
    t = jnp.asarray(g)

    assert abs(float(sparse.natural_sparsity(t)) - 0.07) < 1e-6
    ratio = sparse.calibrate_threshold_budget({"g": t}, 0.0, safety=1.2)
    assert 0.07 <= ratio <= 0.09

    sp = sparse.threshold(t, 0.0, budget_ratio=ratio)
    assert int(sp.nnz) == 700  # all nonzeros, no zeros padded in
    sel = np.sort(np.asarray(sp.indices)[:700])
    np.testing.assert_array_equal(sel, np.sort(nz))
    assert int(sparse.threshold_overflow(t, 0.0, budget_ratio=ratio)) == 0
    # undersized budget: overflow reports the uncaptured nonzeros
    assert int(sparse.threshold_overflow(t, 0.0, budget_ratio=500 / d)) == 200


def test_topk_sampled_recall_and_contract():
    """Sortless sampled top-k: nnz <= k, strictly ascending live indices,
    values re-read from the tensor, and recall vs exact top-k comparable to
    approx_max_k's 0.95 target on gaussian gradients."""
    d = 300_000
    rng = np.random.default_rng(7)
    g = rng.normal(size=d).astype(np.float32)
    t = jnp.asarray(g)
    ratio = 0.01
    sp = jax.jit(lambda x: sparse.topk_sampled(x, ratio))(t)
    k = sparse.num_slots(d, ratio)
    nnz = int(sp.nnz)
    assert 0 < nnz <= k
    idxs = np.asarray(sp.indices)[:nnz]
    assert (np.diff(idxs) > 0).all()  # ascending, unique
    np.testing.assert_allclose(np.asarray(sp.values)[:nnz], g[idxs], rtol=1e-6)
    exact = set(np.argsort(-np.abs(g))[:k].tolist())
    recall = len(exact.intersection(idxs.tolist())) / k
    assert recall > 0.85, recall
    # the selection is a pure magnitude-threshold set: every selected value
    # outweighs every unselected one up to the threshold boundary
    tmin = np.abs(g[idxs]).min()
    assert (np.abs(np.delete(g, idxs)) <= tmin + 1e-6).all()


def test_topk_sampled_small_tensor_exact_fallback():
    d = 2_000
    rng = np.random.default_rng(11)
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk_sampled(jnp.asarray(g), 0.05)
    k = sparse.num_slots(d, 0.05)
    assert int(sp.nnz) == k
    want = np.sort(np.argsort(-np.abs(g))[:k])
    np.testing.assert_array_equal(np.sort(np.asarray(sp.indices)), want)


def test_topk_sampled_through_tensor_codec():
    """End-to-end: the sampled sparsifier composes with the flagship bloom
    codec (incl. the threshold-insert variant, which it is compatible with
    by construction — its selection IS a magnitude-threshold set)."""
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    d = 100_000
    rng = np.random.default_rng(13)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    for threshold_insert in (False, True):
        cfg = DeepReduceConfig(
            compressor="topk_sampled", compress_ratio=0.01,
            deepreduce="index", index="bloom", fpr=0.01,
            bloom_blocked="mod", bloom_threshold_insert=threshold_insert,
        )
        codec = TensorCodec((d,), cfg, name="t")
        payload = jax.jit(lambda x: codec.encode(x, step=0))(g)
        out = np.asarray(codec.decode(payload, step=0))
        nz = np.flatnonzero(out)
        assert len(nz) > 0
        np.testing.assert_allclose(out[nz], np.asarray(g)[nz], rtol=1e-6)


def test_topk_sampled_naturally_sparse_falls_back_exact():
    """Zero estimated threshold (sample saw only zeros) must NOT select the
    first-k positions: the cond fallback does exact magnitude selection, so
    every true nonzero is captured (r5 review finding)."""
    d = 300_000
    rng = np.random.default_rng(23)
    g = np.zeros(d, np.float32)
    nz = rng.choice(d, 500, replace=False)  # << 0.9*k nonzeros
    g[nz] = rng.normal(size=500).astype(np.float32) + np.sign(rng.normal(size=500))
    sp = jax.jit(lambda x: sparse.topk_sampled(x, 0.01))(jnp.asarray(g))
    idxs = np.asarray(sp.indices)[: int(sp.nnz)]
    captured = set(idxs.tolist()).intersection(nz.tolist())
    assert len(captured) == 500, f"only {len(captured)}/500 nonzeros captured"


def test_topk_sampled_config_knobs_plumb_through():
    """topk_sample_size / topk_undershoot reach the sparsifier via
    from_params + TensorCodec; a tighter undershoot captures fewer slots."""
    from deepreduce_tpu.config import from_params
    from deepreduce_tpu.wrappers import TensorCodec

    d = 300_000
    rng = np.random.default_rng(31)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    nnzs = {}
    for und in (0.95, 0.6):
        cfg = from_params({"compressor": "topk_sampled", "compress_ratio": 0.01,
                           "topk_undershoot": und, "topk_sample_size": 1 << 14})
        assert cfg.topk_undershoot == und and cfg.topk_sample_size == 1 << 14
        sp = TensorCodec((d,), cfg, name="t").sparsify(g)
        nnzs[und] = int(sp.nnz)
    k = 3000
    assert 0 < nnzs[0.6] < nnzs[0.95] <= k, nnzs
