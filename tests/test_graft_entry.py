"""The driver's own entry points must stay green: single-chip compile
check of the flagship forward, and the full multichip dry run (compressed
DP + the dp x sp ring-attention composition) on the virtual mesh."""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_entry_compiles_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn).lower(*args).compile()
    assert out is not None


@pytest.mark.slow
def test_dryrun_multichip_under_ambient_axon_config():
    """The driver's exact call pattern: a fresh interpreter where the axon
    sitecustomize has already set jax_platforms='axon' (no conftest CPU
    pinning), then `import __graft_entry__; dryrun_multichip(8)`. The
    function must pin its own virtual CPU mesh BEFORE any backend
    initializes — this is the failure mode that turned MULTICHIP red in
    rounds 1 (timeout) and 2 (libtpu mismatch inside device_put), and it
    must pass even when the device tunnel is wedged."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "axon"  # what the driver environment carries
    # conftest exports XLA_FLAGS=--xla_force_host_platform_device_count=8;
    # the real driver env carries no such flag — strip it so the child only
    # gets 8 CPU devices if dryrun_multichip pins them itself
    flags = " ".join(
        tok
        for tok in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in tok
    )
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = "import __graft_entry__ as ge; ge.dryrun_multichip(8); print('DRYRUN_OK')"
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout
