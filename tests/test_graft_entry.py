"""The driver's own entry points must stay green: single-chip compile
check of the flagship forward, and the full multichip dry run (compressed
DP + the dp x sp ring-attention composition) on the virtual mesh."""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_entry_compiles_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn).lower(*args).compile()
    assert out is not None
