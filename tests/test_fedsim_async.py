"""Asynchronous buffered federated mode (fedsim async): degenerate-case
equivalence with the synchronous round (bitwise under identity weighting),
mid-buffer bitwise checkpoint resume, staleness accounting, the stream
driver, the fed_async* config surface, and the buffered-ingest cost model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepreduce_tpu import checkpoint
from deepreduce_tpu.config import ConfigError, DeepReduceConfig, reason_code_of
from deepreduce_tpu.fedsim import FedSim, parse_latency, synthetic_linear_problem

DIM, BATCH, LOCAL = 16, 4, 2


def _cfg(**kw):
    base = dict(
        deepreduce="index",
        index="bloom",
        bloom_blocked="mod",
        compress_ratio=0.25,
        fpr=0.01,
        memory="residual",
        min_compress_size=8,
    )
    base.update(kw)
    return DeepReduceConfig(**base)


def _fed_kw(**kw):
    base = dict(fed=True, fed_num_clients=64, fed_clients_per_round=16,
                fed_local_steps=LOCAL)
    base.update(kw)
    return base


def _driver(cfg, mesh, chunk=2):
    params0, data_fn, loss_fn = synthetic_linear_problem(DIM, BATCH, LOCAL)
    fs = FedSim(loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
                mesh=mesh, client_chunk=chunk)
    return fs, fs.init(params0)


def _leaves_equal(a, b):
    return all(
        bool(jnp.all(x == y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


def _leaves_close(a, b, **kw):
    return all(
        bool(jnp.allclose(x, y, **kw))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


# ---------------------------------------------------------------------- #
# latency-plan parsing
# ---------------------------------------------------------------------- #


def test_parse_latency():
    assert parse_latency("") == (1.0,)
    probs = parse_latency("0.5,0.3,0.2")
    assert len(probs) == 3
    assert sum(probs) == pytest.approx(1.0)
    assert parse_latency("2,1,1") == pytest.approx((0.5, 0.25, 0.25))
    with pytest.raises(ValueError, match="float"):
        parse_latency("0.5,x")
    with pytest.raises(ValueError, match=">= 0"):
        parse_latency("0.5,-0.1")
    with pytest.raises(ValueError, match="all be zero"):
        parse_latency("0,0")
    with pytest.raises(ValueError, match="cap is 64"):
        parse_latency(",".join(["1"] * 65))


# ---------------------------------------------------------------------- #
# degenerate-case contract: K == cohort + zero latency == synchronous round
# ---------------------------------------------------------------------- #


def test_async_degenerate_equals_sync(mesh8):
    """fed_async with K == cohort size and a zero-latency distribution is
    the synchronous round: bitwise (params AND residual bank) under
    identity weighting (alpha=0), and within f32 tolerance for alpha>0
    (the weight is pow(1.0, -alpha) == 1.0, applied through one extra
    staged multiply)."""
    key = jax.random.PRNGKey(0)
    fs_s, st_s = _driver(_cfg(**_fed_kw()), mesh8)
    for r in range(3):
        st_s, m_s = fs_s.step(st_s, jax.random.fold_in(key, r))

    fs_a, st_a = _driver(
        _cfg(**_fed_kw(fed_async=True, fed_async_k=16)), mesh8
    )
    m_a = None
    for r in range(3):
        st_a, m_a = fs_a.step(st_a, jax.random.fold_in(key, r))
    assert _leaves_equal(st_s.params, st_a.params)
    assert _leaves_equal(st_s.residuals, st_a.residuals)
    # every tick applied (K == cohort, all live) and paid the broadcast
    assert float(m_a["applied"]) == 1.0
    assert float(m_a["staleness_mean"]) == 0.0
    assert float(m_a["downlink_bytes"]) == float(m_s["downlink_bytes"])
    assert float(m_a["uplink_bytes"]) == float(m_s["uplink_bytes"])

    fs_w, st_w = _driver(
        _cfg(**_fed_kw(fed_async=True, fed_async_k=16, fed_async_alpha=0.5)),
        mesh8,
    )
    for r in range(3):
        st_w, _ = fs_w.step(st_w, jax.random.fold_in(key, r))
    assert _leaves_close(st_s.params, st_w.params, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------- #
# buffered ingest: fill cadence, staleness, mid-buffer bitwise resume
# ---------------------------------------------------------------------- #


def _async_chaos_cfg():
    return _cfg(**_fed_kw(
        fed_async=True, fed_async_k=40, fed_async_alpha=0.5,
        fed_async_latency="0.5,0.3,0.2",
        resilience=True, fault_plan="3@1,5@2:4", drop_rate=0.05,
        payload_checksum=True, chaos_corrupt_rate=0.2,
    ))


def test_async_midbuffer_bitwise_resume(mesh8, tmp_path):
    """Kill/resume with the buffer partially filled and staleness counters
    nonzero: restoring the checkpoint into a FRESH driver and replaying the
    remaining ticks lands bitwise on the uninterrupted run's params,
    residual bank, AND aggregation buffer (mirrors the r13 sync resume)."""
    cfg = _async_chaos_cfg()
    key = jax.random.PRNGKey(0)
    ck = str(tmp_path / "ckpt")
    fs, st = _driver(cfg, mesh8)
    save_at = None
    for r in range(6):
        st, _ = fs.step(st, jax.random.fold_in(key, r))
        if save_at is None and r >= 2 and float(st.buffer.count) > 0 \
                and float(st.buffer.stale_sum) > 0:
            save_at = r + 1
            checkpoint.save(ck, st, config=cfg)
    assert save_at is not None and save_at < 6  # genuinely mid-buffer, mid-run

    fs2, template = _driver(cfg, mesh8)
    st2 = checkpoint.restore(ck, template, config=cfg)
    # the restored buffer is mid-fill with nonzero staleness counters
    assert float(st2.buffer.count) > 0
    assert float(st2.buffer.stale_sum) > 0
    for r in range(save_at, 6):
        st2, _ = fs2.step(st2, jax.random.fold_in(key, r))
    assert _leaves_equal(st.params, st2.params)
    assert _leaves_equal(st.residuals, st2.residuals)
    assert _leaves_equal(st.buffer, st2.buffer)


def test_async_buffer_cadence_and_staleness(mesh8):
    """K > cohort: the buffer fills across ticks and applies only at the
    threshold; the S2C broadcast is paid exactly on post-apply ticks; the
    deterministic latency distribution shows up in the staleness metrics."""
    cfg = _cfg(**_fed_kw(fed_async=True, fed_async_k=40, fed_async_alpha=0.5,
                         fed_async_latency="0.5,0.3,0.2"))
    key = jax.random.PRNGKey(0)
    fs, st = _driver(cfg, mesh8)
    hist = []
    for r in range(6):
        st, m = fs.step(st, jax.random.fold_in(key, r))
        hist.append({
            k: (np.asarray(v).tolist() if np.asarray(v).ndim else float(v))
            for k, v in m.items()
        })
    # 16 live clients/tick, K=40: applies at ticks 2 and 5 (48 buffered)
    assert [h["applied"] for h in hist] == [0.0, 0.0, 1.0, 0.0, 0.0, 1.0]
    assert [h["buffer_fill"] for h in hist] == [16.0, 32.0, 48.0, 16.0, 32.0, 48.0]
    # broadcast on tick 0 (initial) and on each post-apply tick
    paid = [h["downlink_bytes"] > 0 for h in hist]
    assert paid == [True, False, False, True, False, False]
    assert any(h["staleness_mean"] > 0 for h in hist)
    assert max(h["staleness_max"] for h in hist) <= 2.0
    # weighted mass is strictly below the raw count once staleness appears
    assert any(h["buffer_weight"] < h["buffer_fill"] for h in hist)
    assert all(
        bool(jnp.all(jnp.isfinite(x)))
        for x in jax.tree_util.tree_leaves(st.params)
    )


def test_async_staleness_histogram_exact(mesh8):
    """The on-device staleness histogram (a psum-tuple member, r23 health
    plane): f32[D] per tick, counting exactly the ACCEPTED contributions
    at each staleness level — sum equals the live-client count every tick,
    the exact tail quantiles derive from it, and the histogram-implied
    mean/max agree with the scalar staleness metrics."""
    from deepreduce_tpu.telemetry.device_metrics import hist_quantile

    cfg = _cfg(**_fed_kw(fed_async=True, fed_async_k=40, fed_async_alpha=0.5,
                         fed_async_latency="0.5,0.3,0.2"))
    key = jax.random.PRNGKey(1)
    fs, st = _driver(cfg, mesh8)
    total = None
    for r in range(5):
        st, m = fs.step(st, jax.random.fold_in(key, r))
        h = np.asarray(m["staleness_hist"], dtype=np.float64)
        assert h.shape == (3,)  # D = len(parse_latency("0.5,0.3,0.2"))
        assert np.all(h >= 0)
        # per-tick exactness: every accepted contribution lands in
        # exactly one level (no churn here, so accepted == clients)
        assert float(h.sum()) == float(m["clients"])
        # the scalar metrics are derivable from the histogram
        if h.sum() > 0:
            mean_h = float((h * np.arange(3)).sum() / h.sum())
            assert mean_h == pytest.approx(float(m["staleness_mean"]),
                                           abs=1e-5)
            max_h = float(np.max(np.nonzero(h)[0]))
            assert max_h == float(m["staleness_max"])
        total = h if total is None else total + h
    # the deterministic 3-level latency plan populates a genuine tail:
    # nonzero mass above level 0, and an exact p95 within the level range
    assert float(total[1:].sum()) > 0
    p95 = hist_quantile(total.tolist(), 0.95)
    assert 0.0 < p95 <= 2.0


def test_async_stream_matches_step_loop(mesh8):
    """stream() only changes the host dispatch pattern: T pipelined ticks
    land bitwise on the same state as T step() calls."""
    cfg = _cfg(**_fed_kw(fed_async=True, fed_async_k=40, fed_async_alpha=0.5,
                         fed_async_latency="0.5,0.3,0.2"))
    key = jax.random.PRNGKey(3)
    fs_a, st_a = _driver(cfg, mesh8)
    for r in range(4):
        st_a, _ = fs_a.step(st_a, jax.random.fold_in(key, r))
    fs_b, st_b = _driver(cfg, mesh8)
    st_b, metrics_hist, wall = fs_b.stream(st_b, key, 4)
    assert len(metrics_hist) == 4 and wall > 0
    assert _leaves_equal(st_a.params, st_b.params)
    assert _leaves_equal(st_a.buffer, st_b.buffer)
    fs_sync, st_sync = _driver(_cfg(**_fed_kw()), mesh8)
    with pytest.raises(ValueError, match="fed_async=True"):
        fs_sync.stream(st_sync, key, 2)


# ---------------------------------------------------------------------- #
# config surface
# ---------------------------------------------------------------------- #


def test_fed_async_config_validation():
    # engaged knobs without the master flag
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(fed_async_k=8))
    assert reason_code_of(ei.value) == "fed-async-knobs-disengaged"
    # async without the fed geometry
    with pytest.raises(ConfigError) as ei:
        _cfg(fed_async=True, fed_async_k=8)
    assert reason_code_of(ei.value) == "fed-async-needs-fed"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(fed_async=True, fed_async_k=0))
    assert reason_code_of(ei.value) == "fed-async-k-range"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(fed_async=True, fed_async_k=8, fed_async_alpha=-0.5))
    assert reason_code_of(ei.value) == "fed-async-alpha-range"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(fed_async=True, fed_async_k=8,
                       fed_async_latency="0.5,nope"))
    assert reason_code_of(ei.value) == "fed-async-latency-syntax"
    # a valid async config constructs
    cfg = _cfg(**_fed_kw(fed_async=True, fed_async_k=8, fed_async_alpha=0.5,
                         fed_async_latency="0.6,0.3,0.1"))
    assert cfg.fed_async and cfg.fed_async_k == 8


def test_trainer_rejects_fed_config(mesh8):
    """The Trainer must fail loudly on a fed config instead of silently
    dropping every fed_* (and fed_async*) knob."""
    import flax.linen as nn

    from deepreduce_tpu.train import Trainer

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x)

    with pytest.raises(ConfigError) as ei:
        Trainer(Tiny(), _cfg(**_fed_kw()), optax.sgd(0.1), mesh8)
    assert reason_code_of(ei.value) == "fed-vs-trainer"


# ---------------------------------------------------------------------- #
# buffered-ingest cost model
# ---------------------------------------------------------------------- #


def test_costmodel_fed_async():
    from deepreduce_tpu import costmodel as cm

    assert cm.expected_staleness((1.0,)) == 0.0
    assert cm.expected_staleness((0.5, 0.3, 0.2)) == pytest.approx(0.7)

    # pure-ingest limit: K payloads across the link, same per-byte price
    # as the synchronous round
    t = cm.fed_async_apply_time(1000.0, 100)
    assert t == pytest.approx(100 * 1000.0 / cm.BW_100MBPS)
    assert cm.fed_async_clients_per_sec(1000.0, 100) == pytest.approx(100 / t)
    # server links parallelize ingest
    assert cm.fed_async_apply_time(1000.0, 100, server_links=2) == pytest.approx(t / 2)
    # client latency is hidden behind ingest (max, not sum): with the same
    # parameters the async stream serves at least as fast as the sync round
    sync = cm.fed_clients_per_sec(1000.0, 100, t_client_s=0.5)
    asyn = cm.fed_async_clients_per_sec(1000.0, 100, t_client_s=0.5)
    assert asyn >= sync
    # deeper overlap hides more client compute; staleness stretches it
    slow = cm.fed_async_apply_time(1.0, 10, t_client_s=4.0, overlap_depth=1)
    deep = cm.fed_async_apply_time(1.0, 10, t_client_s=4.0, overlap_depth=8)
    assert deep < slow
    stale = cm.fed_async_apply_time(
        1.0, 10, t_client_s=4.0, overlap_depth=1, latency_probs=(0.5, 0.3, 0.2)
    )
    assert stale > slow


# ---------------------------------------------------------------------- #
# driver-level SLO gate (mesh-heavy: excluded from tier-1)
# ---------------------------------------------------------------------- #


@pytest.mark.slow
def test_fedsim_check_async_slo_gate(tmp_path, capsys):
    """`fedsim check --async --slo` end-to-end (what make slo-check runs):
    the churn+chaos smoke must end healthy, the monitor's staleness-p95
    verdict must be fed by the on-device histogram (nonzero under the
    3-level latency plan), health.jsonl must be schema-valid, and the
    post-checkpoint health tail must replay bitwise on resume."""
    import json as _json

    from deepreduce_tpu.fedsim.__main__ import main as fedsim_main
    from deepreduce_tpu.slo import HealthLog, validate_health_stream

    rc = fedsim_main([
        "check", "--async", "--slo", "--rounds", "8",
        "--track_dir", str(tmp_path),
    ])
    report = _json.loads(capsys.readouterr().out)
    assert rc == 0 and report["ok"]
    checks = report["checks"]
    assert checks["slo_end_healthy"]
    assert checks["slo_stream_valid"]
    assert checks["slo_resume_bitwise"]
    assert checks["staleness_hist_exact"]
    # the verdict's staleness tail comes from the on-device histogram
    verdict = report["slo"]["verdict"]["targets"]["staleness_p95_max"]
    assert verdict["ok"] and verdict["value"] > 0.0
    validate_health_stream(HealthLog.read(tmp_path / "check" / "health.jsonl"))
    # the monitor's checkpoint sidecar rides next to the run dir
    assert (tmp_path / "slo_state.json").exists()
