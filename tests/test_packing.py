"""Property tests for the dynamic-width static-budget bit packer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu.codecs import packing


@pytest.mark.parametrize("width", [1, 3, 7, 8, 13, 21, 32])
def test_pack_unpack_round_trip(width):
    rng = np.random.default_rng(width)
    n = 257
    hi = (1 << width) - 1
    vals = rng.integers(0, hi + 1, size=n, dtype=np.uint32)
    packed = packing.pack(jnp.asarray(vals), jnp.asarray(width, jnp.int32))
    out = np.asarray(packing.unpack(packed, n))
    np.testing.assert_array_equal(out, vals)


def test_pack_dynamic_width_under_jit():
    n = 100

    @jax.jit
    def round_trip(vals, width):
        packed = packing.pack(vals, width)
        return packing.unpack(packed, n)

    rng = np.random.default_rng(0)
    for width in (5, 11, 19):
        vals = rng.integers(0, 1 << width, size=n, dtype=np.uint32)
        out = np.asarray(round_trip(jnp.asarray(vals), jnp.asarray(width, jnp.int32)))
        np.testing.assert_array_equal(out, vals)


def test_bits_needed_exact():
    cases = {0: 1, 1: 1, 2: 2, 3: 2, 4: 3, 255: 8, 256: 9, (1 << 21) - 1: 21, 1 << 21: 22}
    for v, want in cases.items():
        assert int(packing.bits_needed(jnp.asarray(v, jnp.uint32))) == want, v


def test_bitmap_round_trip():
    rng = np.random.default_rng(7)
    m = 1003
    bits = rng.integers(0, 2, size=m).astype(np.uint8)
    words = packing.pack_bitmap(jnp.asarray(bits))
    out = np.asarray(packing.unpack_bitmap(words, m))
    np.testing.assert_array_equal(out, bits)


def test_wire_bits_counts_meaningful_payload():
    vals = jnp.arange(100, dtype=jnp.uint32)
    packed = packing.pack(vals, jnp.asarray(7, jnp.int32))
    assert int(packing.wire_bits(packed)) == 40 + 100 * 7


def _reference_pack3x21_words(vals: np.ndarray) -> np.ndarray:
    """The reference pack_'s int64 words, computed independently from its
    documented layout (pytorch/deepreduce.py:165-180): pad by 3 - n%3 zeros
    (always >= 1), view as strided thirds (3, nw), word = v0*2^42 + v1*2^21
    + v2, append [n]."""
    n = vals.size
    nw = n // 3 + 1
    padded = np.zeros(nw * 3, dtype=np.int64)
    padded[:n] = vals
    v0, v1, v2 = padded.reshape(3, nw)
    words = v0 * (1 << 42) + v1 * (1 << 21) + v2
    return np.concatenate([words, [n]]).astype(np.int64)


def test_pack3x21_round_trip():
    """The reference's special-case 3x21-bit-per-int64 packers
    (pytorch/deepreduce.py:165-191) — exact round trip at every length mod 3
    and at the 21-bit boundary values."""
    from deepreduce_tpu.codecs.packing import pack3x21, packed_count3x21, unpack3x21

    rng = np.random.default_rng(0)
    for n in (0, 1, 2, 3, 4, 7, 300):
        vals = rng.integers(0, 1 << 21, size=n).astype(np.uint32)
        if n:
            vals[0] = (1 << 21) - 1
        packed = pack3x21(jnp.asarray(vals))
        assert packed.shape == (n // 3 + 2, 2)  # nw = n//3+1 data + count
        assert int(packed_count3x21(packed)) == n
        out = np.asarray(unpack3x21(packed, n))
        np.testing.assert_array_equal(out, vals)


def test_pack3x21_matches_reference_word_layout():
    """Bit-exact fixture vs the reference layout: reassemble our uint32
    halves into int64 words and compare against the formula-computed
    reference words (strided thirds, first component at high bits, trailing
    count)."""
    from deepreduce_tpu.codecs.packing import pack3x21

    rng = np.random.default_rng(3)
    for n in (1, 2, 3, 6, 7, 100):
        vals = rng.integers(0, 1 << 21, size=n).astype(np.uint32)
        vals[-1] = (1 << 21) - 1
        halves = np.asarray(pack3x21(jnp.asarray(vals))).astype(np.uint64)
        ours = (halves[:, 0] | (halves[:, 1] << np.uint64(32))).astype(np.int64)
        np.testing.assert_array_equal(ours, _reference_pack3x21_words(vals))
    # hand-computed spot fixture: vals [1, 2, 3, 4] -> nw = 2, strided view
    # rows (1,2),(3,4),(0,0): word0 = 1*2^42 + 3*2^21, word1 = 2*2^42 + 4*2^21
    halves = np.asarray(pack3x21(jnp.asarray(np.array([1, 2, 3, 4], np.uint32))))
    ours = (halves.astype(np.uint64)[:, 0] | (halves.astype(np.uint64)[:, 1] << np.uint64(32)))
    expect = np.array([(1 << 42) + (3 << 21), (2 << 42) + (4 << 21), 4], np.uint64)
    np.testing.assert_array_equal(ours, expect)
