"""Model-zoo smoke tests: every image family initializes and produces
logits of the right shape on a tiny input (the reference exercises its
models only through full benchmark runs; this is the cheap CI-able slice).
"""

import jax
import jax.numpy as jnp
import pytest


@pytest.mark.parametrize(
    "name,num_classes",
    [
        ("ResNet20", 10),
        # DenseNet40's concatenative graph is ~3x the compile time of the
        # other families — slow tier only
        pytest.param("DenseNet40", 10, marks=pytest.mark.slow),
        ("MobileNetV1", 10),
        ("VGG16", 10),
    ],
)
def test_image_model_forward(name, num_classes):
    import deepreduce_tpu.models as zoo

    model = getattr(zoo, name)()
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, num_classes)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    assert n_params > 10_000


def test_vgg16_conv_layer_names_match_polyseg_whitelist():
    """The polyseg conv-pattern default (r'(?i)conv') must hit VGG16's conv
    kernels — the reference keys its per-model tables by conv layers
    (tensorflow/deepreduce.py:230-242 is_convolutional)."""
    import re

    import deepreduce_tpu.models as zoo

    model = zoo.VGG16()
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    flat = jax.tree_util.tree_leaves_with_path(variables["params"])
    conv_kernels = [
        jax.tree_util.keystr(path)
        for path, leaf in flat
        if re.search(r"(?i)conv", jax.tree_util.keystr(path)) and leaf.ndim == 4
    ]
    assert len(conv_kernels) == 13  # VGG16 configuration "D"


def test_word_lstm_jit_apply_after_eager_init():
    """Regression: the pre-nn.RNN WordLSTM leaked first-trace parameter
    tracers from a bare lax.scan over the cell — eager init followed by a
    jitted apply raised UnexpectedTracerError."""
    from deepreduce_tpu.models import WordLSTM

    m = WordLSTM(vocab_size=64, embed_dim=8, hidden_dim=16)
    toks = jnp.zeros((2, 5), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), toks)["params"]
    out = jax.jit(lambda p, t: m.apply({"params": p}, t))(params, toks)
    assert out.shape == (2, 5, 64)
