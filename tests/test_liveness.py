"""The memory-liveness & precision-flow auditor (analysis/liveness.py):
donation-aware peak pricing, the jx-peak-bytes budget gate, the
jx-dtype-flow forward dtype rule, the costmodel.peak_hbm_bytes
cross-check, and the canonical-hash order-invariance contract — each
claim proven by a clean/planted pair."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from deepreduce_tpu import costmodel
from deepreduce_tpu.analysis import liveness
from deepreduce_tpu.analysis.jaxpr_audit import (
    audit_fedsim_async_round,
    audit_fedsim_multitenant,
    audit_fedsim_round,
    audit_specs,
    peak_budget_violations,
)
from deepreduce_tpu.analysis.rules import (
    ALL_RULE_IDS,
    R_DTYPE_FLOW,
    R_PEAK_BYTES,
    AuditContext,
)

_CTX = AuditContext(label="fixture")


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _spec_record(label):
    """Trace one registered audit spec by label."""
    (rec,) = dict(audit_specs())[label]()
    return rec


# ---------------------------------------------------------------------- #
# the liveness model: donation semantics + determinism
# ---------------------------------------------------------------------- #


def test_donation_frees_at_aliased_output_birth():
    """r09's donate_argnums contract, priced: the donated in-place update
    peaks at ONE buffer (the invar dies the moment its alias is born),
    the undonated build double-buffers — exactly 2x."""
    d = 65536
    donated = jax.jit(lambda w: w * 0.999, donate_argnums=0)
    undonated = jax.jit(lambda w: w * 0.999)
    peak_don = liveness.analyze(
        jax.make_jaxpr(lambda w: donated(w))(_sds((d,)))
    ).peak_bytes
    peak_undon = liveness.analyze(
        jax.make_jaxpr(lambda w: undonated(w))(_sds((d,)))
    ).peak_bytes
    assert peak_don == 4 * d
    assert peak_undon == 2 * peak_don


def test_analyze_is_deterministic():
    closed = jax.make_jaxpr(lambda x: jnp.sum(x * 2.0))(_sds((1024,)))
    a = liveness.analyze(closed).to_dict()
    b = liveness.analyze(closed).to_dict()
    assert a == b
    assert a["peak_bytes"] > 0


def test_undonated_double_buffer_busts_committed_budget():
    """The planted negative fixture for jx-peak-bytes: commit the donated
    trace's budget, then audit the undonated double-buffer variant under
    the same label — the budget gate must fire with the 2x peak."""
    d = 65536
    donated = jax.jit(lambda w: w * 0.999, donate_argnums=0)
    undonated = jax.jit(lambda w: w * 0.999)
    budget = liveness.analyze(
        jax.make_jaxpr(lambda w: donated(w))(_sds((d,)))
    ).peak_bytes

    from deepreduce_tpu.analysis.jaxpr_audit import trace_and_check

    rec = trace_and_check(
        "fixture:double-buffer",
        lambda w: undonated(w),
        (_sds((d,)),),
        AuditContext(label="fixture:double-buffer"),
    )
    assert rec.violations == []  # the trace itself is rule-clean
    viols = peak_budget_violations([rec], {"fixture:double-buffer": budget})
    assert len(viols) == 1 and viols[0].rule == R_PEAK_BYTES
    assert str(rec.peak_bytes) in viols[0].detail
    # unknown labels and peak-less records bootstrap silently
    assert peak_budget_violations([rec], {}) == []


# ---------------------------------------------------------------------- #
# fedsim: the residual bank scales with N, not the cohort
# ---------------------------------------------------------------------- #


def test_fedsim_bank_peak_scales_with_population_not_cohort():
    d, n = 256, 64
    base = audit_fedsim_round(d=d, num_clients=n)[0]
    big_n = audit_fedsim_round(
        d=d, num_clients=2 * n, label="fedsim:round-n128"
    )[0]
    big_c = audit_fedsim_round(
        d=d, clients_per_round=32, label="fedsim:round-c32"
    )[0]
    assert not any(r.violations for r in (base, big_n, big_c))

    # the bank is the single biggest buffer at the peak and is exactly
    # [num_clients, d] f32 — resident ONCE (no double-buffering)
    top = base.peak_top[0]
    assert top["shape"] == [n, d] and top["bytes"] == 4 * n * d

    # doubling the population grows the peak by exactly the bank delta...
    bank_delta = 4 * n * d
    delta_n = big_n.peak_bytes - base.peak_bytes
    assert abs(delta_n - bank_delta) <= 0.05 * bank_delta
    # ...while doubling the cohort adds only vmapped working set, strictly
    # less than bank-scale growth
    delta_c = big_c.peak_bytes - base.peak_bytes
    assert delta_c < delta_n


def test_multitenant_t1_peak_matches_single_tenant():
    """Stacking T=1 population through the vmapped tick prices the same
    envelope as the plain async tick: byte-identical dominant buffers
    (modulo the leading [1] tenant dim), peak within 5%."""
    single = audit_fedsim_async_round()[0]
    (t1,) = audit_fedsim_multitenant(tenants=(1,))
    assert single.violations == [] and t1.violations == []
    assert [b["bytes"] for b in t1.peak_top] == [
        b["bytes"] for b in single.peak_top
    ]
    assert t1.peak_bytes == pytest.approx(single.peak_bytes, rel=0.05)


# ---------------------------------------------------------------------- #
# costmodel.peak_hbm_bytes cross-check: model == analyzer
# ---------------------------------------------------------------------- #


def test_costmodel_peak_matches_analyzer():
    fused = _spec_record("exchange:fused-loop")
    assert fused.peak_bytes == costmodel.peak_hbm_bytes("fused", 4096, 8)

    oktopk = _spec_record("exchange:sparse_rs-oktopk")
    assert oktopk.peak_bytes == costmodel.peak_hbm_bytes(
        "oktopk", 4096, 8, residual=False
    )

    bucketed = _spec_record("exchange:bucketed-loop")
    d_total = 3000 + 900 + 700 + 300 + 150 + 50  # _BUCKET_LEAVES census
    est = costmodel.peak_hbm_bytes("bucketed", d_total, 8)
    # the bucketed floor ignores O(payload) encode scratch — tight to <1%
    assert est <= bucketed.peak_bytes
    assert bucketed.peak_bytes == pytest.approx(est, rel=0.01)

    with pytest.raises(ValueError):
        costmodel.peak_hbm_bytes("ring", 4096, 8)


# ---------------------------------------------------------------------- #
# jx-dtype-flow: accept/reject pairs
# ---------------------------------------------------------------------- #


def test_dtype_flow_clean_f32_program():
    closed = jax.make_jaxpr(lambda x: x * 2.0 + 1.0)(_sds((64,)))
    assert liveness.rule_dtype_flow(closed, _CTX) == []


def test_dtype_flow_rejects_f64_promotion():
    from jax.experimental import enable_x64

    with enable_x64():
        closed = jax.make_jaxpr(lambda x: x.astype(jnp.float64) * 2.0)(
            _sds((64,))
        )
    (v,) = liveness.rule_dtype_flow(closed, _CTX)
    assert v.rule == R_DTYPE_FLOW
    assert "promotion" in v.detail and "f64" in v.detail


def _rogue_dequant(x):
    # a silent int8 -> f32 re-inflation OUTSIDE the registered sites
    return x.astype(jnp.float32) * 2.0


def test_dtype_flow_rejects_out_of_site_dequant():
    closed = jax.make_jaxpr(_rogue_dequant)(_sds((64,), jnp.int8))
    (v,) = liveness.rule_dtype_flow(closed, _CTX)
    assert v.rule == R_DTYPE_FLOW
    assert "dequant" in v.detail
    assert "test_liveness.py:_rogue_dequant" in v.detail


def test_dtype_flow_accepts_registered_dequant_site():
    from deepreduce_tpu import qar

    closed = jax.make_jaxpr(
        lambda lv, nm: qar.bucket_dequantize(lv, nm, 127, 64)
    )(_sds((256,), jnp.int8), _sds((4,)))
    assert liveness.rule_dtype_flow(closed, _CTX) == []
    assert ("qar.py", "bucket_dequantize") in liveness.DEQUANT_SITES


def test_dtype_flow_rejects_non_f32_output():
    closed = jax.make_jaxpr(lambda x: x.astype(jnp.float16))(_sds((64,)))
    (v,) = liveness.rule_dtype_flow(closed, _CTX)
    assert v.rule == R_DTYPE_FLOW
    assert "round-trip" in v.detail


def test_new_rules_registered():
    assert R_PEAK_BYTES in ALL_RULE_IDS and R_DTYPE_FLOW in ALL_RULE_IDS


# ---------------------------------------------------------------------- #
# CLI: budget-drift exit code, --update re-baseline, --only gating, mem
# ---------------------------------------------------------------------- #


def _fake_record(label, peak):
    from deepreduce_tpu.analysis.jaxpr_audit import TraceRecord

    return TraceRecord(
        label=label, violations=[], collectives={}, jaxpr_hash="ab" * 8,
        peak_bytes=peak, peak_top=[], collective_residency=None,
    )


def test_cli_budget_drift_exit_and_update(monkeypatch, tmp_path):
    import deepreduce_tpu.analysis.__main__ as cli
    import deepreduce_tpu.analysis.ast_lint as al
    import deepreduce_tpu.analysis.jaxpr_audit as ja

    out = tmp_path / "ANALYSIS.json"
    monkeypatch.setattr(al, "lint_repo", lambda root=None: [])
    monkeypatch.setattr(
        ja, "audit_all", lambda quick=False: ([_fake_record("t", 100)], [])
    )
    # no baseline: bootstrap silently, commit the budget
    assert cli.main(["audit", "--out", str(out)]) == 0
    committed = json.loads(out.read_text())
    assert committed["jaxpr_audit"]["traces"][0]["peak_bytes"] == 100

    # drift: exit 1 and the committed baseline is NOT overwritten
    monkeypatch.setattr(
        ja, "audit_all", lambda quick=False: ([_fake_record("t", 200)], [])
    )
    assert cli.main(["audit", "--out", str(out)]) == 1
    assert json.loads(out.read_text()) == committed

    # --only on an unrelated rule ungates the exit code (report still
    # withheld), --only jx-peak-bytes gates it
    assert cli.main(
        ["audit", "--out", str(out), "--only", "jx-dtype-flow"]
    ) == 0
    assert cli.main(
        ["audit", "--out", str(out),
         "--only", "jx-peak-bytes,jx-dtype-flow"]
    ) == 1

    # deliberate re-baseline
    assert cli.main(["audit", "--out", str(out), "--update"]) == 0
    assert json.loads(out.read_text())["jaxpr_audit"]["traces"][0][
        "peak_bytes"
    ] == 200
    assert cli.main(["audit", "--out", str(out)]) == 0


def test_cli_mem_gates_on_violations(monkeypatch, capsys):
    import deepreduce_tpu.analysis.__main__ as cli
    import deepreduce_tpu.analysis.jaxpr_audit as ja
    from deepreduce_tpu.analysis.rules import Violation

    clean = _fake_record("exchange:fused-loop", 64)
    clean.peak_top = [
        {"bytes": 64, "prim": "add", "shape": [16], "dtype": "float32",
         "site": "comm.py:decode"}
    ]
    monkeypatch.setattr(
        ja, "audit_specs",
        lambda quick=False: [("exchange:fused-loop", lambda: [clean])],
    )
    assert cli.main(["mem"]) == 0
    out = capsys.readouterr().out
    assert "exchange:fused-loop" in out and "comm.py:decode" in out

    bad = _fake_record("exchange:fused-loop", 64)
    bad.violations = [Violation(R_PEAK_BYTES, "exchange:fused-loop", "boom")]
    monkeypatch.setattr(
        ja, "audit_specs",
        lambda quick=False: [("exchange:fused-loop", lambda: [bad])],
    )
    assert cli.main(["mem"]) == 1
    # --only on an unrelated rule ungates
    assert cli.main(["mem", "--only", "jx-dtype-flow"]) == 0


# ---------------------------------------------------------------------- #
# canonical hash: trace-history order invariance (subprocess pair)
# ---------------------------------------------------------------------- #

_ORDER_SCRIPT = """
import sys
from deepreduce_tpu.analysis.jaxpr_audit import audit_specs
specs = dict(audit_specs())
for label in sys.argv[1].split(","):
    (rec,) = specs[label]()
    print(f"{rec.label}={rec.jaxpr_hash}")
"""


def _hashes_in_order(order):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", _ORDER_SCRIPT, order],
        capture_output=True, text=True, env=env, check=True,
    ).stdout
    return dict(line.split("=", 1) for line in out.split() if "=" in line)

def test_jaxpr_hash_is_trace_order_invariant():
    """The r21 bug, fenced: hashing the pretty-printer output made a
    trace's hash depend on which programs were traced before it (shared
    sub-jaxpr hoisting order). The canonical renderer must give identical
    hashes whichever order the audits run in — proven across processes."""
    a = _hashes_in_order("exchange:fused-loop,exchange:bucketed-loop")
    b = _hashes_in_order("exchange:bucketed-loop,exchange:fused-loop")
    assert a == b
    assert len(a) == 2 and all(len(h) == 16 for h in a.values())
