"""SLO health plane: spec schema validation, health.jsonl record/stream
contracts, the hysteretic OK/DEGRADED/BREACH ladder with pinned transition
ticks, fast/slow error-budget burn math, bitwise state_dict replay, and the
provable no-op of the degenerate (target-less) spec."""

import json

import pytest

from deepreduce_tpu.config import ConfigError, reason_code_of
from deepreduce_tpu.slo import (
    HEALTH_SCHEMA,
    HEALTH_STATES,
    HealthLog,
    HealthMonitor,
    SLOSpec,
    TARGET_KEYS,
    validate_health,
    validate_health_stream,
)


# ---------------------------------------------------------------------- #
# SLOSpec parsing + rejection
# ---------------------------------------------------------------------- #


def test_spec_defaults_and_roundtrip():
    spec = SLOSpec.from_dict({})
    assert spec.is_noop
    assert spec.window_ticks == 8 and spec.hysteresis_ticks == 2
    assert spec.burn_fast == 2.0 and spec.burn_slow == 1.0
    full = SLOSpec.from_dict({
        "version": 1,
        "window_ticks": 4,
        "targets": {"min_clients_per_round": 2.0, "staleness_p95_max": 3.0},
        "tenants": {"1": {"staleness_p95_max": 1.0}},
    })
    assert not full.is_noop
    # to_dict -> from_dict is the identity on the parsed form
    assert SLOSpec.from_dict(full.to_dict()) == full
    # overrides replace key-by-key, globals fill the rest
    assert full.effective_targets(0)["staleness_p95_max"] == 3.0
    assert full.effective_targets(1) == {
        "min_clients_per_round": 2.0, "staleness_p95_max": 1.0,
    }


@pytest.mark.parametrize("raw, code", [
    (["not", "an", "object"], "slo-spec-syntax"),
    ({"bogus_key": 1}, "slo-spec-syntax"),
    ({"version": 2}, "slo-spec-syntax"),
    ({"window_ticks": "four"}, "slo-spec-window-range"),
    ({"window_ticks": 0}, "slo-spec-window-range"),
    ({"fast_window_ticks": 4, "slow_window_ticks": 2},
     "slo-spec-window-range"),
    ({"burn_fast": 0.0}, "slo-spec-target-range"),
    ({"targets": {"made_up_target": 1.0}}, "slo-spec-unknown-target"),
    ({"targets": {"min_clients_per_round": True}}, "slo-spec-target-range"),
    ({"targets": {"checksum_failure_budget": 0.0}}, "slo-spec-target-range"),
    ({"targets": {"checksum_failure_budget": 1.5}}, "slo-spec-target-range"),
    ({"targets": {"convergence_residency_min": 0.5}},
     "slo-spec-target-range"),
    ({"tenants": "nope"}, "slo-spec-tenant-override"),
    ({"tenants": {"x": {}}}, "slo-spec-tenant-override"),
    ({"tenants": {"-1": {}}}, "slo-spec-tenant-override"),
    ({"tenants": {"0": {"made_up_target": 1.0}}},
     "slo-spec-unknown-target"),
])
def test_spec_rejections(raw, code):
    with pytest.raises(ConfigError) as ei:
        SLOSpec.from_dict(raw)
    assert reason_code_of(ei.value) == code


def test_spec_load_errors(tmp_path):
    with pytest.raises(ConfigError) as ei:
        SLOSpec.load(tmp_path / "missing.json")
    assert reason_code_of(ei.value) == "slo-spec-syntax"
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(ConfigError) as ei:
        SLOSpec.load(bad)
    assert reason_code_of(ei.value) == "slo-spec-syntax"
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"targets": {"buffer_fill_max": 4.0}}))
    assert SLOSpec.load(good).targets == {"buffer_fill_max": 4.0}


def test_spec_with_overrides():
    spec = SLOSpec.from_dict({"targets": {"min_clients_per_round": 1.0}})
    assert spec.with_overrides() is spec
    tuned = spec.with_overrides(window_ticks=3, hysteresis_ticks=5)
    assert (tuned.window_ticks, tuned.hysteresis_ticks) == (3, 5)
    assert tuned.targets == spec.targets


def test_config_rejects_engaged_slo_knobs_without_spec():
    from deepreduce_tpu.config import DeepReduceConfig

    with pytest.raises(ConfigError) as ei:
        DeepReduceConfig(slo_window=4)
    assert reason_code_of(ei.value) == "slo-knobs-disengaged"
    with pytest.raises(ConfigError) as ei:
        DeepReduceConfig(slo_spec="slo.json")
    assert reason_code_of(ei.value) == "slo-needs-fed"


# ---------------------------------------------------------------------- #
# health.jsonl record + stream contracts
# ---------------------------------------------------------------------- #


def _rec(**kw):
    base = dict(tick=4, tenant=0, window_ticks=2, from_state="OK",
                to_state="DEGRADED", trigger="min_clients_per_round",
                value=0.0, threshold=5.0, burn_fast=None, burn_slow=None)
    base.update(kw)
    return base


def test_validate_health_accepts_canonical_records():
    validate_health(_rec())
    validate_health(_rec(from_state="DEGRADED", to_state="OK",
                         trigger="recovered", value=None, threshold=None))
    validate_health(_rec(trigger="checksum_failure_budget",
                         value=0.2, threshold=0.1,
                         burn_fast=2.0, burn_slow=1.5))


@pytest.mark.parametrize("rec, match", [
    ("not a dict", "must be a dict"),
    (_rec(to_state="WEDGED"), "unknown health state"),
    ({k: v for k, v in _rec().items() if k != "window_ticks"},
     "missing=\\['window_ticks'\\]"),
    (dict(_rec(), surprise=1), "extra=\\['surprise'\\]"),
    (_rec(tick=True), "is bool"),
    (_rec(tick=-1), "out of range"),
    (_rec(window_ticks=0), "out of range"),
    (_rec(value="high"), "has type str"),
    (_rec(to_state="BREACH"), "exactly one rung"),
    (_rec(trigger="recovered"), "downward transitions"),
    (_rec(from_state="DEGRADED", to_state="OK"), "downward transitions"),
    (_rec(trigger="made_up_trigger"), "unknown trigger"),
])
def test_validate_health_rejects(rec, match):
    with pytest.raises(ValueError, match=match):
        validate_health(rec)


def test_validate_health_stream_contracts():
    up = _rec(tick=2)
    down = _rec(tick=5, from_state="DEGRADED", to_state="OK",
                trigger="recovered", value=None, threshold=None)
    validate_health_stream([up, down])
    # per-tenant interleaving is fine: tenant streams chain independently
    validate_health_stream([up, _rec(tick=2, tenant=1), down])
    with pytest.raises(ValueError, match="non-monotonic tick"):
        validate_health_stream([up, dict(down, tick=2)])
    with pytest.raises(ValueError, match="broken transition chain"):
        validate_health_stream([up, _rec(tick=9)])
    with pytest.raises(ValueError, match="record 1: unknown trigger"):
        validate_health_stream([up, dict(down, trigger="oops")])


def test_health_log_append_rejects_tick_regression(tmp_path):
    log = HealthLog(tmp_path / "health.jsonl")
    log.append(_rec(tick=3))
    with pytest.raises(ValueError, match="non-monotonic health tick"):
        log.append(_rec(tick=3, from_state="DEGRADED", to_state="BREACH"))
    log.append(_rec(tick=7, from_state="DEGRADED", to_state="BREACH"))
    recs = HealthLog.read(tmp_path / "health.jsonl")
    assert [r["tick"] for r in recs] == [3, 7]
    validate_health_stream(recs)
    assert HealthLog.read(tmp_path / "absent.jsonl") == []


# ---------------------------------------------------------------------- #
# the ladder: pinned escalation/recovery ticks, hysteresis, no storms
# ---------------------------------------------------------------------- #


def _ladder_spec(**kw):
    base = dict(window_ticks=2, fast_window_ticks=1, slow_window_ticks=3,
                hysteresis_ticks=2,
                targets={"min_clients_per_round": 5.0})
    base.update(kw)
    return SLOSpec(**base)


def test_monitor_escalation_and_recovery_ticks_pinned():
    mon = HealthMonitor(_ladder_spec())
    clients = [10, 10] + [0] * 5 + [10] * 4
    events = []
    for tick, c in enumerate(clients):
        events += mon.observe(tick, {"clients": c})
    # one rung per transition, hysteresis_ticks=2 consecutive evaluations
    # each: OK->DEGRADED at 4, ->BREACH at 6, back down at 8 and 10
    assert [(e["tick"], e["from_state"], e["to_state"]) for e in events] == [
        (4, "OK", "DEGRADED"),
        (6, "DEGRADED", "BREACH"),
        (8, "BREACH", "DEGRADED"),
        (10, "DEGRADED", "OK"),
    ]
    up = events[0]
    assert up["trigger"] == "min_clients_per_round"
    assert up["value"] == 0.0 and up["threshold"] == 5.0
    assert events[2]["trigger"] == "recovered"
    assert events[2]["value"] is None
    validate_health_stream(mon.events)
    assert mon.healthy() and mon.state_of() == "OK"
    assert mon.final_states() == {0: "OK"}


def test_monitor_flapping_emits_no_transition_storm():
    # window/slow of 1 make every violated tick BREACH-grade on its own;
    # the 2-tick hysteresis streak still never builds under alternation
    mon = HealthMonitor(_ladder_spec(
        window_ticks=1, slow_window_ticks=1, fast_window_ticks=1))
    for tick in range(20):
        mon.observe(tick, {"clients": 0 if tick % 2 == 0 else 10})
    assert mon.events == []
    assert mon.healthy()


def test_monitor_rejects_non_monotonic_observe():
    mon = HealthMonitor(_ladder_spec())
    mon.observe(3, {"clients": 10})
    with pytest.raises(ValueError, match="non-monotonic observe tick"):
        mon.observe(3, {"clients": 10})
    mon.observe(3, {"clients": 10}, tenant=1)  # other tenants unaffected


def test_monitor_missing_data_is_level_zero():
    # rows without the target's field carry no evidence: no transitions
    mon = HealthMonitor(_ladder_spec())
    for tick in range(6):
        mon.observe(tick, {"buffer_fill": 999.0})
    assert mon.events == [] and mon.healthy()
    row = mon.verdict(0)["targets"]["min_clients_per_round"]
    assert row["value"] is None and row["ok"]


# ---------------------------------------------------------------------- #
# error-budget burn rates (fast/slow windows)
# ---------------------------------------------------------------------- #


def _burn_spec():
    return SLOSpec(window_ticks=4, fast_window_ticks=2, slow_window_ticks=4,
                   hysteresis_ticks=1, burn_fast=2.0, burn_slow=1.0,
                   targets={"checksum_failure_budget": 0.1})


def test_burn_rate_fast_slow_window_math():
    mon = HealthMonitor(_burn_spec())
    events = []
    # 4 ticks at 20% failures (burn 2x a 10% budget), then clean ticks
    for tick in range(8):
        rep = ({"clients": 8, "checksum_failures": 2} if tick < 4
               else {"clients": 10, "checksum_failures": 0})
        events += mon.observe(tick, rep)
    assert [(e["tick"], e["to_state"], e["trigger"]) for e in events] == [
        (0, "DEGRADED", "checksum_failure_budget"),  # slow burn >= 1x
        (3, "BREACH", "checksum_failure_budget"),    # full slow window AND
                                                     # fast burn >= 2x
        (4, "DEGRADED", "recovered"),  # fast window cooled below 2x
        (6, "OK", "recovered"),        # slow window burn fell below 1x
    ]
    breach = events[1]
    assert breach["burn_fast"] == pytest.approx(2.0)
    assert breach["burn_slow"] == pytest.approx(2.0)
    # value is the observed failure fraction, threshold the budget
    assert breach["value"] == pytest.approx(0.2)
    assert breach["threshold"] == 0.1


def test_burn_rate_needs_full_slow_window_for_breach():
    # identical failure rate, but only 3 ticks: the slow window never
    # fills, so the grade caps at DEGRADED no matter how hot the burn
    mon = HealthMonitor(_burn_spec())
    for tick in range(3):
        mon.observe(tick, {"clients": 8, "checksum_failures": 2})
    assert [e["to_state"] for e in mon.events] == ["DEGRADED"]
    assert mon.state_of() == "DEGRADED"


# ---------------------------------------------------------------------- #
# staleness-histogram + per-tenant targets through the monitor
# ---------------------------------------------------------------------- #


def test_monitor_staleness_hist_and_tenant_overrides():
    spec = SLOSpec(
        window_ticks=1, fast_window_ticks=1, slow_window_ticks=1,
        hysteresis_ticks=1,
        targets={"staleness_p95_max": 2.0},
        tenant_targets={1: {"staleness_p95_max": 0.5}},
    )
    mon = HealthMonitor(spec)
    # hist [5,2,1]: cdf 0.625 / 0.875 / 1.0 -> p95 = level 2
    for tick in range(2):
        mon.observe(tick, {"staleness_hist": [5, 2, 1]}, tenant=0)
        mon.observe(tick, {"staleness_hist": [5, 2, 1]}, tenant=1)
    # tenant 0's ceiling (2.0) holds; tenant 1's override (0.5) breaches
    assert mon.state_of(0) == "OK"
    assert mon.state_of(1) == "BREACH"
    assert not mon.healthy()
    v = mon.verdict(1)["targets"]["staleness_p95_max"]
    assert v["value"] == 2.0 and v["threshold"] == 0.5 and not v["ok"]


# ---------------------------------------------------------------------- #
# bitwise state_dict replay + the degenerate no-op
# ---------------------------------------------------------------------- #


def _feed(mon, ticks):
    out = []
    for tick in ticks:
        c = 0 if 2 <= tick <= 6 else 10
        out += mon.observe(tick, {"clients": c})
    return out


def test_state_dict_replay_is_bitwise():
    a = HealthMonitor(_ladder_spec())
    _feed(a, range(5))
    snap = json.dumps(a.state_dict(), sort_keys=True)

    b = HealthMonitor(_ladder_spec())
    b.load_state_dict(json.loads(snap))
    assert json.dumps(b.state_dict(), sort_keys=True) == snap

    _feed(a, range(5, 12))
    _feed(b, range(5, 12))
    assert (json.dumps(a.state_dict(), sort_keys=True)
            == json.dumps(b.state_dict(), sort_keys=True))
    assert ([json.dumps(e, sort_keys=True) for e in a.events]
            == [json.dumps(e, sort_keys=True) for e in b.events])
    assert a.events  # the scenario actually transitions


def test_degenerate_spec_is_a_provable_noop():
    spec = SLOSpec.from_dict({"window_ticks": 3})
    assert spec.is_noop
    mon = HealthMonitor(spec)
    before = json.dumps(mon.state_dict(), sort_keys=True)
    for tick in range(10):
        assert mon.observe(tick, {"clients": 0, "checksum_failures": 99,
                                  "staleness_hist": [0, 0, 99]}) == []
    assert json.dumps(mon.state_dict(), sort_keys=True) == before
    assert mon.state_dict() == {"tenants": {}, "events": []}
    assert mon.events == [] and mon.healthy()
    # a spec whose only tenant override is empty is still target-less
    assert SLOSpec.from_dict({"tenants": {"0": {}}}).is_noop


def test_schema_key_tables_are_consistent():
    # the schema fields the docs pin: exactly these keys, no drift
    assert set(HEALTH_SCHEMA) == {
        "tick", "tenant", "window_ticks", "from_state", "to_state",
        "trigger", "value", "threshold", "burn_fast", "burn_slow",
    }
    assert HEALTH_STATES == ("OK", "DEGRADED", "BREACH")
    assert set(TARGET_KEYS) == {
        "min_clients_per_round", "min_clients_per_sec",
        "staleness_p95_max", "buffer_fill_max", "checksum_failure_budget",
        "convergence_band", "convergence_residency_min",
        "pop_residency_min",
    }
