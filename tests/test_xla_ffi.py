"""XLA FFI custom-call layer: native kernels inside jitted programs."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepreduce_tpu import native
from deepreduce_tpu.codecs import bloom
from deepreduce_tpu.native import xla_ops


def _require_ffi():
    """The FFI library is built lazily on first use; when the toolchain or
    the XLA headers are absent (no `xla/ffi/api/ffi.h` in this image) the
    build raises — that's an environment gap, not a code failure."""
    try:
        xla_ops.register()
    except Exception as e:  # build/toolchain unavailable
        pytest.skip(f"ffi unavailable: {e}")


def test_fbp_decode_custom_call_round_trip():
    _require_ffi()
    idx = np.sort(np.random.default_rng(0).choice(50000, 300, replace=False)).astype(np.uint32)
    enc = native.fbp_encode(idx)
    out = jax.jit(lambda w: xla_ops.fbp_decode(w, 300))(jnp.asarray(enc))
    np.testing.assert_array_equal(np.asarray(out), idx)


def test_varint_decode_custom_call_round_trip():
    _require_ffi()
    idx = np.sort(np.random.default_rng(1).choice(1 << 20, 200, replace=False)).astype(np.uint32)
    enc = native.varint_encode(idx)
    out = jax.jit(lambda b: xla_ops.varint_decode(b, 200))(jnp.asarray(enc))
    np.testing.assert_array_equal(np.asarray(out), idx)


def test_bloom_query_custom_call_matches_ctypes_and_jax():
    _require_ffi()
    rng = np.random.default_rng(2)
    d, k = 30000, 128
    idx = np.sort(rng.choice(d, k, replace=False)).astype(np.int32)
    meta = bloom.BloomMeta.create(k, d, fpr=0.01)
    bitmap = native.bloom_insert(idx, meta.m_bits, meta.num_hash)
    ffi_mask = jax.jit(lambda b: xla_ops.bloom_query(b, meta.num_hash, d))(jnp.asarray(bitmap))
    ref_mask = native.bloom_query_universe(bitmap, meta.num_hash, d)
    np.testing.assert_array_equal(np.asarray(ffi_mask), ref_mask)
    # and equal to the pure-JAX codec (shared hash mix)
    words = bloom.insert(jnp.asarray(idx), jnp.asarray(k), meta)
    jax_mask = np.asarray(bloom.query_universe(words, meta)).astype(np.uint8)
    np.testing.assert_array_equal(np.asarray(ffi_mask), jax_mask)


def test_ffi_bloom_insert_matches_ctypes():
    """Encode-side custom call: the FFI insert builds the byte-identical
    bitmap to the ctypes host path (same murmur mix, same bit order)."""
    xla_ops = pytest.importorskip("deepreduce_tpu.native.xla_ops")
    native = pytest.importorskip("deepreduce_tpu.native")
    try:
        xla_ops.register()
    except Exception as e:  # build/toolchain unavailable
        pytest.skip(f"ffi unavailable: {e}")
    rng = np.random.default_rng(5)
    k, m_bits, h = 500, 1 << 14, 5
    idx = np.sort(rng.choice(100_000, k, replace=False)).astype(np.int32)
    via_ffi = np.asarray(
        jax.jit(lambda i: xla_ops.bloom_insert(i, m_bits, h))(jnp.asarray(idx))
    )
    via_ctypes = native.bloom_insert(idx, m_bits, h)
    np.testing.assert_array_equal(via_ffi, np.asarray(via_ctypes))


@pytest.mark.parametrize("code", ["fbp", "varint", "pfor"])
def test_ffi_int_encode_round_trips_against_host_decode(code):
    """Name-keyed encode as an XLA custom call; host decode recovers the
    exact sorted indices for every family member."""
    xla_ops = pytest.importorskip("deepreduce_tpu.native.xla_ops")
    native = pytest.importorskip("deepreduce_tpu.native")
    try:
        xla_ops.register()
    except Exception as e:
        pytest.skip(f"ffi unavailable: {e}")
    rng = np.random.default_rng(6)
    k = 3000
    idx = np.sort(rng.choice(500_000, k, replace=False)).astype(np.uint32)
    cap = native.int_cap_words(k)
    words, nwords = jax.jit(
        lambda v, c: xla_ops.int_encode(v, c, code, cap)
    )(jnp.asarray(idx), jnp.asarray(k, jnp.int32))
    _, dec = native.int_codec_from_name(code)
    out = dec(np.asarray(words)[: int(nwords)], k)
    np.testing.assert_array_equal(out, idx)


@pytest.mark.parametrize("code", ["fbp", "varint", "pfor"])
def test_ffi_int_decode_round_trips_in_graph(code):
    """Name-keyed decode as an XLA custom call: encode + decode both inside
    one jitted program recover the exact sorted indices."""
    try:
        xla_ops.register()
    except Exception as e:
        pytest.skip(f"ffi unavailable: {e}")
    rng = np.random.default_rng(7)
    k = 2000
    idx = np.sort(rng.choice(300_000, k, replace=False)).astype(np.uint32)
    cap = native.int_cap_words(k)

    @jax.jit
    def round_trip(v, c):
        words, nwords = xla_ops.int_encode(v, c, code, cap)
        return xla_ops.int_decode(words, nwords, code, k)

    out = round_trip(jnp.asarray(idx), jnp.asarray(k, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out), idx)


def test_ffi_bloom_compress_decompress_match_ctypes():
    """Full-pipeline custom calls vs the ctypes host path: identical wire
    bytes, values, nsel, and recovered selection for the same inputs."""
    try:
        xla_ops.register()
    except Exception as e:
        pytest.skip(f"ffi unavailable: {e}")
    rng = np.random.default_rng(8)
    d, k = 40_000, 400
    g = rng.normal(size=d).astype(np.float32)
    idx = np.sort(rng.choice(d, k, replace=False)).astype(np.int32)
    from deepreduce_tpu.codecs import bloom_native

    meta = bloom_native.BloomNativeMeta.create(k, d, fpr=0.02, policy="p0")
    pid = native.POLICY_IDS[meta.policy]
    wire, nbytes, values, nsel = jax.jit(
        lambda gg, ii: xla_ops.bloom_compress(
            gg, ii, jnp.asarray(k, jnp.int32), jnp.asarray(3, jnp.int32),
            m_bits=meta.m_bits, num_hash=meta.num_hash, policy_id=pid,
            select_cap=meta.budget, wire_budget=meta.wire_budget,
        )
    )(jnp.asarray(g), jnp.asarray(idx))
    ref_wire = native.bloom_compress(g, idx, meta.m_bits, meta.num_hash,
                                     meta.policy, 3, meta.budget)
    np.testing.assert_array_equal(np.asarray(wire)[: int(nbytes)], ref_wire)
    ref_vals, ref_sel = native.bloom_decompress(
        ref_wire, d, k, meta.policy, 3, meta.budget
    )
    np.testing.assert_allclose(np.asarray(values)[: int(nsel)], ref_vals)
    assert int(nsel) == len(ref_sel)

    vals2, idxs2, nsel2 = jax.jit(
        lambda w, nb: xla_ops.bloom_decompress(
            w, nb, jnp.asarray(3, jnp.int32),
            d=d, k=k, policy_id=pid, select_cap=meta.budget,
        )
    )(wire, nbytes)
    np.testing.assert_array_equal(np.asarray(idxs2)[: int(nsel2)], ref_sel)
    np.testing.assert_allclose(np.asarray(vals2)[: int(nsel2)], ref_vals)


def _codec_payload_arrays(payload):
    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(payload)]


@pytest.mark.parametrize("name,params", [
    ("bloom_native", {"fpr": 0.02, "policy": "p0"}),
    ("integer_native", {"code": "pfor"}),
])
def test_production_ffi_route_matches_callback_fallback(name, params, monkeypatch):
    """The FFI production route and the pure_callback fallback must produce
    IDENTICAL payloads and decodes — and this test keeps the fallback branch
    covered now that CPU runs default to the FFI route (r4 review)."""
    try:
        xla_ops.register()
    except Exception as e:
        pytest.skip(f"ffi unavailable: {e}")
    from deepreduce_tpu import sparse
    from deepreduce_tpu.codecs.registry import get_codec

    rng = np.random.default_rng(9)
    d = 30_000
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    sp = sparse.topk(g, 0.01)
    codec = get_codec(name, "index")(sp.k, d, params)
    assert xla_ops.available()
    pay_ffi = jax.jit(lambda s, t: codec.encode(s, dense=t, step=2))(sp, g)
    dec_ffi = codec.decode(pay_ffi, (d,), step=2)

    monkeypatch.setattr(xla_ops, "available", lambda: False)
    pay_cb = jax.jit(lambda s, t: codec.encode(s, dense=t, step=2))(sp, g)
    dec_cb = codec.decode(pay_cb, (d,), step=2)

    for a, b in zip(_codec_payload_arrays(pay_ffi), _codec_payload_arrays(pay_cb)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(
        np.asarray(dec_ffi.to_dense()), np.asarray(dec_cb.to_dense())
    )
