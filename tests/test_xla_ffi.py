"""XLA FFI custom-call layer: native kernels inside jitted programs."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepreduce_tpu import native
from deepreduce_tpu.codecs import bloom
from deepreduce_tpu.native import xla_ops


def test_fbp_decode_custom_call_round_trip():
    idx = np.sort(np.random.default_rng(0).choice(50000, 300, replace=False)).astype(np.uint32)
    enc = native.fbp_encode(idx)
    out = jax.jit(lambda w: xla_ops.fbp_decode(w, 300))(jnp.asarray(enc))
    np.testing.assert_array_equal(np.asarray(out), idx)


def test_varint_decode_custom_call_round_trip():
    idx = np.sort(np.random.default_rng(1).choice(1 << 20, 200, replace=False)).astype(np.uint32)
    enc = native.varint_encode(idx)
    out = jax.jit(lambda b: xla_ops.varint_decode(b, 200))(jnp.asarray(enc))
    np.testing.assert_array_equal(np.asarray(out), idx)


def test_bloom_query_custom_call_matches_ctypes_and_jax():
    rng = np.random.default_rng(2)
    d, k = 30000, 128
    idx = np.sort(rng.choice(d, k, replace=False)).astype(np.int32)
    meta = bloom.BloomMeta.create(k, d, fpr=0.01)
    bitmap = native.bloom_insert(idx, meta.m_bits, meta.num_hash)
    ffi_mask = jax.jit(lambda b: xla_ops.bloom_query(b, meta.num_hash, d))(jnp.asarray(bitmap))
    ref_mask = native.bloom_query_universe(bitmap, meta.num_hash, d)
    np.testing.assert_array_equal(np.asarray(ffi_mask), ref_mask)
    # and equal to the pure-JAX codec (shared hash mix)
    words = bloom.insert(jnp.asarray(idx), jnp.asarray(k), meta)
    jax_mask = np.asarray(bloom.query_universe(words, meta)).astype(np.uint8)
    np.testing.assert_array_equal(np.asarray(ffi_mask), jax_mask)


def test_ffi_bloom_insert_matches_ctypes():
    """Encode-side custom call: the FFI insert builds the byte-identical
    bitmap to the ctypes host path (same murmur mix, same bit order)."""
    xla_ops = pytest.importorskip("deepreduce_tpu.native.xla_ops")
    native = pytest.importorskip("deepreduce_tpu.native")
    try:
        xla_ops.register()
    except Exception as e:  # build/toolchain unavailable
        pytest.skip(f"ffi unavailable: {e}")
    rng = np.random.default_rng(5)
    k, m_bits, h = 500, 1 << 14, 5
    idx = np.sort(rng.choice(100_000, k, replace=False)).astype(np.int32)
    via_ffi = np.asarray(
        jax.jit(lambda i: xla_ops.bloom_insert(i, m_bits, h))(jnp.asarray(idx))
    )
    via_ctypes = native.bloom_insert(idx, m_bits, h)
    np.testing.assert_array_equal(via_ffi, np.asarray(via_ctypes))


@pytest.mark.parametrize("code", ["fbp", "varint", "pfor"])
def test_ffi_int_encode_round_trips_against_host_decode(code):
    """Name-keyed encode as an XLA custom call; host decode recovers the
    exact sorted indices for every family member."""
    xla_ops = pytest.importorskip("deepreduce_tpu.native.xla_ops")
    native = pytest.importorskip("deepreduce_tpu.native")
    try:
        xla_ops.register()
    except Exception as e:
        pytest.skip(f"ffi unavailable: {e}")
    rng = np.random.default_rng(6)
    k = 3000
    idx = np.sort(rng.choice(500_000, k, replace=False)).astype(np.uint32)
    cap = native.int_cap_words(k)
    words, nwords = jax.jit(
        lambda v, c: xla_ops.int_encode(v, c, code, cap)
    )(jnp.asarray(idx), jnp.asarray(k, jnp.int32))
    _, dec = native.int_codec_from_name(code)
    out = dec(np.asarray(words)[: int(nwords)], k)
    np.testing.assert_array_equal(out, idx)
