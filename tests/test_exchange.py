"""Composable exchange legs (deepreduce_tpu/exchange.py): the Exchanger
protocol, the derived leg plans, and the one build factory every stack
routes through. The plans are derived by inspection of BUILT stacks, so
these tests double as a contract that wrapping (hier over flat, streaming
over either) composes the way ARCHITECTURE.md's invariant table says."""

import jax
import jax.numpy as jnp
import pytest

from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.comm_stream import StreamingExchange
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.exchange import (
    Exchanger, Leg, build_exchanger, describe, leg_plan, wrap_streaming,
)
from deepreduce_tpu.parallel.hierarchical import HierarchicalExchanger

W = 8

BLOOM = dict(
    deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01,
    bloom_blocked="mod", min_compress_size=100, memory="residual",
)

LIKE = {"g": jax.ShapeDtypeStruct((4096,), jnp.float32)}


def _kinds(ex):
    return [l.kind for l in leg_plan(ex)]


def _axes(ex):
    return [l.axis for l in leg_plan(ex)]


def test_protocol_satisfied_by_both_stacks():
    flat = build_exchanger(LIKE, DeepReduceConfig(**BLOOM), num_workers=W)
    hier = build_exchanger(
        LIKE, DeepReduceConfig(hier=True, **BLOOM),
        num_slices=2, per_slice=4,
    )
    assert isinstance(flat, GradientExchanger)
    assert isinstance(hier, HierarchicalExchanger)
    assert isinstance(flat, Exchanger)
    assert isinstance(hier, Exchanger)


def test_build_hier_requires_geometry():
    with pytest.raises(ValueError, match="num_slices"):
        build_exchanger(LIKE, DeepReduceConfig(hier=True, **BLOOM))


def test_flat_fused_plan():
    ex = build_exchanger(LIKE, DeepReduceConfig(**BLOOM), num_workers=W)
    assert _kinds(ex) == [
        "codec-pack", "fused-allgather", "per-worker-loop", "wire",
    ]
    assert "data" in _axes(ex)


def test_hier_plan_prepends_ici_leg():
    ex = build_exchanger(
        LIKE, DeepReduceConfig(hier=True, **BLOOM),
        num_slices=2, per_slice=4,
    )
    plan = leg_plan(ex)
    assert plan[0] == Leg("collective", "ici", "dense-psum")
    # the wrapped flat plan rides the dcn axis
    assert any(l.axis == "dcn" for l in plan[1:])


def test_streaming_wrapper_prepends_schedule_leg():
    cfg = DeepReduceConfig(
        stream_exchange=True, bucket_bytes=4096, **BLOOM
    )
    ex = build_exchanger(LIKE, cfg, num_workers=W)
    stream = wrap_streaming(ex)
    assert isinstance(stream, StreamingExchange)
    plan = leg_plan(stream)
    assert plan[0].kind == "stream-hooks"
    assert "bucketed-allgather" in [l.kind for l in plan]


def test_composed_stream_hier_plan():
    cfg = DeepReduceConfig(
        stream_exchange=True, bucket_bytes=4096, hier=True, **BLOOM
    )
    hier = build_exchanger(LIKE, cfg, num_slices=2, per_slice=4)
    stream = wrap_streaming(hier)
    kinds = [l.kind for l in leg_plan(stream)]
    assert kinds[0] == "stream-hooks"
    assert "dense-psum" in kinds and "bucketed-allgather" in kinds
    assert "stream-hooks" in describe(stream)


def test_wrap_streaming_none_when_off():
    ex = build_exchanger(LIKE, DeepReduceConfig(**BLOOM), num_workers=W)
    assert wrap_streaming(ex) is None


def test_masked_reowner_leg_on_resilient_sparse_rs():
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, memory="none",
        communicator="sparse_rs", deepreduce=None, resilience=True,
    )
    ex = build_exchanger(LIKE, cfg, num_workers=W)
    kinds = _kinds(ex)
    assert "masked-reowner" in kinds
    assert any(k.startswith("sparse_rs:") for k in kinds)
