"""Compressed-FedAvg topology tests (paper §6.2, Algorithm 2): round
mechanics, bidirectional wire accounting (Table-2-style relative volume),
convergence on a linear-regression federation, and per-client residual
bookkeeping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu import FedAvg, FedConfig
from deepreduce_tpu.config import DeepReduceConfig

import optax


def _problem(num_clients=6, local_steps=2, batch=32, dim=64, seed=0):
    """Each client holds data from the same linear teacher + noise."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim,)).astype(np.float32)

    def batches_for(ids, round_seed):
        r = np.random.default_rng(round_seed)
        xs = r.normal(size=(len(ids), local_steps, batch, dim)).astype(np.float32)
        ys = xs @ w_true + 0.01 * r.normal(size=(len(ids), local_steps, batch)).astype(
            np.float32
        )
        return jnp.asarray(xs), jnp.asarray(ys)

    def loss_fn(params, batch_xy):
        x, y = batch_xy
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    params = {"w": jnp.zeros((dim,), jnp.float32), "b": jnp.zeros((), jnp.float32)}
    return w_true, batches_for, loss_fn, params


def _run(cfg, rounds=25, num_clients=6, cpr=3, local_steps=2, server_lr=1.0):
    w_true, batches_for, loss_fn, params = _problem(
        num_clients=num_clients, local_steps=local_steps
    )
    fed = FedConfig(
        num_clients=num_clients,
        clients_per_round=cpr,
        local_steps=local_steps,
        server_lr=server_lr,
    )
    fa = FedAvg(loss_fn, cfg, fed, optax.sgd(0.05))
    state = fa.init(params)
    run_round = jax.jit(fa.run_round)
    vol = None
    for r in range(rounds):
        key = jax.random.PRNGKey(100 + r)
        ids = fa.sample_clients(state, key)
        xs, ys = batches_for(np.asarray(ids), round_seed=r)
        state, out = run_round(state, ids, (xs, ys), jax.random.fold_in(key, 1))
        vol = float(out["rel_volume"])
    err = float(jnp.linalg.norm(state.params["w"] - w_true) / np.linalg.norm(w_true))
    return err, vol, state


def test_fedavg_uncompressed_converges():
    cfg = DeepReduceConfig(compressor="none", deepreduce=None, memory="none")
    err, vol, _ = _run(cfg)
    assert err < 0.05, err
    assert vol == pytest.approx(1.0)


def test_fedavg_compressed_converges_with_less_volume():
    cfg = DeepReduceConfig(
        compressor="topk",
        compress_ratio=0.25,
        deepreduce="both",
        index="integer",
        value="qsgd",
        policy="p0",
        memory="residual",
        min_compress_size=16,
    )
    err, vol, state = _run(cfg, rounds=40)
    assert vol < 0.35, vol  # Table-2-style relative volume win
    assert err < 0.12, err  # EF keeps convergence near-dense
    assert state.c2s_residuals is not None
    # sampled clients' residuals are populated, and residual EF implies
    # at least one client holds nonzero dropped mass
    total = sum(
        float(jnp.abs(r).sum()) for r in jax.tree_util.tree_leaves(state.c2s_residuals)
    )
    assert total > 0


def test_fedavg_state_shapes_and_round_counter():
    cfg = DeepReduceConfig(compressor="none", deepreduce=None, memory="none")
    _, _, loss_fn, params = _problem()
    fed = FedConfig(num_clients=4, clients_per_round=2, local_steps=1)
    fa = FedAvg(loss_fn, cfg, fed, optax.sgd(0.1))
    state = fa.init(params)
    assert int(state.round) == 0
    assert state.c2s_residuals is None
    ids = fa.sample_clients(state, jax.random.PRNGKey(0))
    assert ids.shape == (2,)
    assert len(np.unique(np.asarray(ids))) == 2  # without replacement


def test_fedavg_sampling_varies_by_key():
    cfg = DeepReduceConfig(compressor="none", deepreduce=None, memory="none")
    _, _, loss_fn, params = _problem()
    fed = FedConfig(num_clients=20, clients_per_round=5)
    fa = FedAvg(loss_fn, cfg, fed, optax.sgd(0.1))
    state = fa.init(params)
    a = np.asarray(fa.sample_clients(state, jax.random.PRNGKey(1)))
    b = np.asarray(fa.sample_clients(state, jax.random.PRNGKey(2)))
    assert not np.array_equal(a, b)


def test_fedavg_56_clients_scan_compiles_fast():
    """The paper's 56-client round geometry (§6.2, Table 2) must compile a
    program whose size is independent of C (one lax.scan over the stacked
    client axis, not 56 unrolled copies) — this test is a compile-time
    smoke: two full rounds with compression in seconds, not minutes."""
    import time

    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.05, deepreduce="both",
        index="bloom", value="qsgd", policy="p0", fpr=0.05,
        bloom_blocked="mod", memory="residual", min_compress_size=8,
    )
    w_true, batches_for, loss_fn, params = _problem(num_clients=57)
    fed = FedConfig(num_clients=57, clients_per_round=56, local_steps=2)
    fa = FedAvg(loss_fn, cfg, fed, optax.sgd(0.05))
    state = fa.init(params)
    run_round = jax.jit(fa.run_round)
    t0 = time.time()
    for r in range(2):
        key = jax.random.PRNGKey(7 + r)
        ids = fa.sample_clients(state, key)
        xs, ys = batches_for(np.asarray(ids), round_seed=r)
        state, out = run_round(state, ids, (xs, ys), jax.random.fold_in(key, 1))
    elapsed = time.time() - t0
    assert int(state.round) == 2
    assert 0 < float(out["rel_volume"]) < 1.0
    # unrolled round-2's 56 copies took minutes to compile; scan is seconds
    assert elapsed < 120, f"56-client compile+2 rounds took {elapsed:.0f}s"
