"""Self-calibrating cost model: the telemetry fit, the profile schema, the
static-profile no-op contract, and the Trainer's profile-driven re-selection
under the bounded-retrace contract (compiled executables == plans visited).

The golden fixture is the committed TRACE_OVERLAP_r15 tracking run: its
trace has TWO compile-skewed warmup steps (streaming runs compile two
programs), no decode spans (t_dec must be held fixed) and zero ICI bytes
(bw_ici must be held fixed) — the exact identifiability shape the fit's
`fixed` honesty list exists for.
"""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepreduce_tpu import costmodel
from deepreduce_tpu.config import DeepReduceConfig

REPO = pathlib.Path(__file__).resolve().parent.parent
GOLDEN = REPO / "TRACE_OVERLAP_r15"
LSTM_D = 4_053_428


# --------------------------------------------------------------------- #
# drop_warmup
# --------------------------------------------------------------------- #


def test_drop_warmup_strips_leading_compile_steps():
    assert costmodel.drop_warmup([10.0, 1.0, 1.0, 1.0, 1.1]) == [
        1.0, 1.0, 1.0, 1.1,
    ]
    # multiple warmup steps (two compiled programs) all go
    assert costmodel.drop_warmup([9.0, 8.0, 1.0, 1.0, 1.0, 1.0]) == [1.0] * 4
    # steady-state runs are untouched
    assert costmodel.drop_warmup([1.0, 1.1, 0.9, 1.0]) == [1.0, 1.1, 0.9, 1.0]


def test_drop_warmup_keeps_at_least_one_sample():
    assert costmodel.drop_warmup([3.0]) == [3.0]
    assert costmodel.drop_warmup([]) == []
    # even an all-slow prefix cannot empty the list
    assert costmodel.drop_warmup([100.0, 90.0], k=0.1) == [90.0]


# --------------------------------------------------------------------- #
# the golden fit
# --------------------------------------------------------------------- #


def test_golden_fit_is_schema_valid_and_identifiable():
    prof = costmodel.calibrate(GOLDEN)
    costmodel.validate_profile(prof.to_record())
    # the r15 run: 6 steps, 2 compile-skewed (streaming compiles two
    # programs) — the median heuristic must drop exactly both
    assert prof.source["steps_total"] == 6
    assert prof.source["warmup_dropped"] == 2
    assert prof.source["steps_measured"] == 4
    # identifiability honesty: no decode spans and zero ICI bytes in this
    # run, so t_dec / bw_ici stay at the static constants
    assert set(prof.fitted) == {"t_enc", "bw_dcn", "compute_time"}
    assert set(prof.fixed) == {"t_dec", "bw_ici"}
    assert prof.t_dec_s == 0.0
    assert prof.bw_ici == costmodel.BW_ICI_10GBPS
    # the documented tolerance: the model-form round trip reproduces the
    # measured mean step time
    T, P = prof.source["measured_step_s"], prof.source["predicted_step_s"]
    assert abs(P - T) / T < 0.05


def test_golden_fit_is_deterministic():
    a = costmodel.calibrate(GOLDEN).to_record()
    b = costmodel.calibrate(GOLDEN).to_record()
    assert a == b
    # no wall clock may enter the record: serializations are bitwise equal
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_golden_fit_include_warmup_escape_hatch():
    prof = costmodel.calibrate(GOLDEN, include_warmup=True)
    assert prof.source["warmup_dropped"] == 0
    assert prof.source["steps_measured"] == 6
    # compile-skewed samples drag the mean up
    assert (
        prof.source["measured_step_s"]
        > costmodel.calibrate(GOLDEN).source["measured_step_s"]
    )


# --------------------------------------------------------------------- #
# synthetic run dir: plant the components, recover the parameters
# --------------------------------------------------------------------- #


def _plant_run(tmp_path, *, workers=4, dcn_bytes=3000.0):
    """Five identical 10ms steps (the fit refuses runs shorter than 4
    post-warmup samples), each decomposing as 3ms encode + 1ms DCN wire +
    6ms forward_backward (children nested inside train/step, so the
    self-time stack must not double-charge the container)."""
    run = tmp_path / "planted"
    run.mkdir()
    (run / "config.json").write_text(
        json.dumps({"config": {"workers": workers}})
    )
    events = []
    for i in range(5):
        t0 = i * 20_000
        events += [
            {"ph": "X", "pid": 1, "tid": 1, "name": "train/step",
             "ts": t0, "dur": 10_000},
            {"ph": "X", "pid": 1, "tid": 1, "name": "exchange/encode",
             "ts": t0, "dur": 3_000},
            {"ph": "X", "pid": 1, "tid": 1, "name": "exchange/allgather",
             "ts": t0 + 3_000, "dur": 1_000},
            {"ph": "X", "pid": 1, "tid": 1, "name": "train/forward_backward",
             "ts": t0 + 4_000, "dur": 6_000},
        ]
    (run / "trace.json").write_text(json.dumps({"traceEvents": events}))
    (run / "summary.json").write_text(
        json.dumps({"telemetry": {"dcn_bytes_per_step": dcn_bytes}})
    )
    return run


def test_synthetic_planted_parameters_are_recovered(tmp_path):
    run = _plant_run(tmp_path)
    prof = costmodel.calibrate(run)
    # T = 10ms; shares: encode 0.3, wire 0.1, compute 0.6 of the step
    assert prof.t_enc_s == pytest.approx(0.003)
    assert prof.compute_time_s == pytest.approx(0.006)
    # allgather inversion: bw = (W-1) * bytes / wire_s = 3 * 3000 / 1ms
    assert prof.bw_dcn == pytest.approx(9.0e6)
    assert set(prof.fitted) == {"t_enc", "bw_dcn", "compute_time"}
    # share-based decomposition is exact by construction
    assert prof.source["predicted_step_s"] == pytest.approx(0.01)
    assert prof.source["measured_step_s"] == pytest.approx(0.01)


def _plant_routed_run(tmp_path, *, workers=4, dcn_bytes=3000.0):
    """Five identical 10ms steps with ROUTE-LABELED codec spans: per step
    2ms encode on route 'sparse', 1ms encode + 2ms decode on route
    'fused', 1ms DCN wire, 4ms forward_backward. The route label rides in
    the event's args (the span name stays route-free), exactly as the
    exchangers emit it."""
    run = tmp_path / "routed"
    run.mkdir()
    (run / "config.json").write_text(
        json.dumps({"config": {"workers": workers}})
    )
    events = []
    for i in range(5):
        t0 = i * 20_000
        events += [
            {"ph": "X", "pid": 1, "tid": 1, "name": "train/step",
             "ts": t0, "dur": 10_000},
            {"ph": "X", "pid": 1, "tid": 1, "name": "exchange/encode",
             "ts": t0, "dur": 2_000, "args": {"route": "sparse"}},
            {"ph": "X", "pid": 1, "tid": 1, "name": "exchange/encode",
             "ts": t0 + 2_000, "dur": 1_000, "args": {"route": "fused"}},
            {"ph": "X", "pid": 1, "tid": 1, "name": "exchange/decode",
             "ts": t0 + 3_000, "dur": 2_000, "args": {"route": "fused"}},
            {"ph": "X", "pid": 1, "tid": 1, "name": "exchange/allgather",
             "ts": t0 + 5_000, "dur": 1_000},
            {"ph": "X", "pid": 1, "tid": 1, "name": "train/forward_backward",
             "ts": t0 + 6_000, "dur": 4_000},
        ]
    (run / "trace.json").write_text(json.dumps({"traceEvents": events}))
    (run / "summary.json").write_text(
        json.dumps({"telemetry": {"dcn_bytes_per_step": dcn_bytes}})
    )
    return run


def test_synthetic_two_route_rows_are_recovered(tmp_path):
    """The v2 tentpole: planted per-route encode/decode seconds come back
    as `routes` rows within 5%, on top of the unchanged global fit."""
    run = _plant_routed_run(tmp_path)
    prof = costmodel.calibrate(run)
    costmodel.validate_profile(prof.to_record())
    assert set(prof.routes) == {"sparse", "fused"}
    tol = dict(rel=0.05)
    # route 'sparse': encode-only codec, no decode row contribution
    assert prof.routes["sparse"]["t_enc_s"] == pytest.approx(0.002, **tol)
    assert prof.routes["sparse"]["t_dec_s"] == 0.0
    # route 'fused': gather-side decode pays W decodes/step, so the row
    # holds the per-decode cost (2ms / W=4)
    assert prof.routes["fused"]["t_enc_s"] == pytest.approx(0.001, **tol)
    assert prof.routes["fused"]["t_dec_s"] == pytest.approx(0.0005, **tol)
    assert prof.routes["sparse"]["samples"] == 5
    assert prof.routes["fused"]["samples"] == 10
    # the global fit is the sum over routes (same decomposition as before)
    assert prof.t_enc_s == pytest.approx(0.003, **tol)
    assert prof.t_dec_s == pytest.approx(0.0005, **tol)
    assert prof.bw_dcn == pytest.approx(9.0e6, **tol)
    # consumption plumbing: a row converts to the measurements spelling
    m = costmodel.route_measurement(prof, "sparse")
    assert m == {
        "t_encode_s": prof.routes["sparse"]["t_enc_s"],
        "t_decode_s": prof.routes["sparse"]["t_dec_s"],
    }
    assert costmodel.route_measurement(prof, "no-such-route") is None


def test_route_rows_survive_save_load_round_trip(tmp_path):
    prof = costmodel.calibrate(_plant_routed_run(tmp_path))
    path = tmp_path / "routed_profile.json"
    prof.save(path)
    again = costmodel.load_profile(path)
    assert again == prof
    assert again.routes == prof.routes
    assert again.content_hash() == prof.content_hash()


def test_calibrate_raises_on_non_run_dirs(tmp_path):
    with pytest.raises(ValueError, match="config.json"):
        costmodel.calibrate(tmp_path)
    run = tmp_path / "r"
    run.mkdir()
    (run / "config.json").write_text(json.dumps({"config": {"workers": 2}}))
    with pytest.raises(ValueError, match="telemetry"):
        costmodel.calibrate(run)


def test_calibrate_refuses_short_runs_naming_the_length(tmp_path):
    """A 3-step run leaves < 4 post-warmup samples — the fit must refuse
    with the run length in the message instead of emitting a profile built
    on noise."""
    run = tmp_path / "short"
    run.mkdir()
    (run / "config.json").write_text(json.dumps({"config": {"workers": 4}}))
    events = []
    for i in range(3):
        t0 = i * 20_000
        events += [
            {"ph": "X", "pid": 1, "tid": 1, "name": "train/step",
             "ts": t0, "dur": 10_000},
            {"ph": "X", "pid": 1, "tid": 1, "name": "train/forward_backward",
             "ts": t0, "dur": 10_000},
        ]
    (run / "trace.json").write_text(json.dumps({"traceEvents": events}))
    (run / "summary.json").write_text(json.dumps({"telemetry": {}}))
    with pytest.raises(ValueError, match=r"3 sample\(s\).*>= 4 post-warmup"):
        costmodel.calibrate(run)


# --------------------------------------------------------------------- #
# profile record schema
# --------------------------------------------------------------------- #


def test_profile_record_round_trips():
    prof = costmodel.calibrate(GOLDEN)
    rec = prof.to_record()
    again = costmodel.MachineProfile.from_record(rec)
    assert again == prof
    assert again.to_record() == rec


def test_profile_save_load_round_trips(tmp_path):
    prof = costmodel.calibrate(GOLDEN)
    path = tmp_path / "profile.json"
    prof.save(path)
    assert costmodel.load_profile(path) == prof


@pytest.mark.parametrize(
    "mutate, match",
    [
        (lambda r: r.update(schema="bogus/v0"), "schema"),
        (lambda r: r.update(bw_dcn_bytes_per_s=-1.0), "bw_dcn"),
        (lambda r: r.update(bw_ici_bytes_per_s=0.0), "bw_ici"),
        (lambda r: r.update(t_enc_s=float("nan")), "finite"),
        (lambda r: r.update(t_dec_s="fast"), "number"),
        # fitted+fixed must partition PROFILE_PARAMS exactly
        (lambda r: r.update(fitted=[], fixed=["bw_dcn"]), "partition"),
        (lambda r: r.update(fitted=r["fitted"] + r["fixed"]), "partition"),
        (lambda r: r.update(source="notes"), "source"),
    ],
)
def test_profile_schema_rejections(mutate, match):
    rec = costmodel.calibrate(GOLDEN).to_record()
    mutate(rec)
    with pytest.raises(ValueError, match=match):
        costmodel.validate_profile(rec)


def test_validate_rejects_non_dict():
    with pytest.raises(ValueError, match="dict"):
        costmodel.validate_profile([1, 2, 3])


def test_v1_record_loads_with_empty_routes_and_identical_selection():
    """Back-compat: a v1 record (no routes table) must load cleanly with
    routes={}, and every selector output under the loaded profile must be
    byte-identical to the v2-with-empty-routes profile it came from —
    committed records like BENCH_CALIB_r16 keep replaying unchanged."""
    prof = costmodel.calibrate(GOLDEN)
    assert prof.routes == {}
    rec_v1 = prof.to_record()
    rec_v1["schema"] = costmodel.PROFILE_SCHEMA_V1
    del rec_v1["routes"]
    again = costmodel.MachineProfile.from_record(rec_v1)
    assert again.routes == {}
    assert again == prof
    for ratio in (0.001, 0.01, 0.1):
        a = costmodel.select_hier_plan(LSTM_D, 2, 16, ratio, profile=prof)
        b = costmodel.select_hier_plan(LSTM_D, 2, 16, ratio, profile=again)
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
        assert costmodel.select_rs_mode(
            LSTM_D, 8, ratio, profile=prof
        ) == costmodel.select_rs_mode(LSTM_D, 8, ratio, profile=again)
    # loads from disk too: BENCH_CALIB_r16's embedded record is v1-era
    embedded = json.load(open(REPO / "BENCH_CALIB_r16.json"))
    costmodel.MachineProfile.from_record(embedded["detail"]["profile"])


def test_v1_record_with_routes_table_is_rejected():
    rec = costmodel.calibrate(GOLDEN).to_record()
    rec["schema"] = costmodel.PROFILE_SCHEMA_V1
    rec["routes"] = {"fused": {"t_enc_s": 0.1, "t_dec_s": 0.0, "samples": 1}}
    with pytest.raises(ValueError, match="v1 profile records carry no"):
        costmodel.validate_profile(rec)


_GOOD_ROW = {"t_enc_s": 0.001, "t_dec_s": 0.0005, "samples": 4}


@pytest.mark.parametrize(
    "routes, match",
    [
        (["fused"], "'routes' must be a dict"),
        ({"": dict(_GOOD_ROW)}, "non-empty string"),
        ({"fused": [0.1, 0.2]}, "must be a dict"),
        ({"fused": {**_GOOD_ROW, "extra": 1.0}}, "unknown keys"),
        ({"fused": {"t_enc_s": 0.1}}, "unknown keys|must be a number"),
        ({"fused": {**_GOOD_ROW, "t_enc_s": -0.1}}, "finite and\\s+>= 0"),
        ({"fused": {**_GOOD_ROW, "t_dec_s": float("nan")}}, "finite"),
        ({"fused": {**_GOOD_ROW, "t_enc_s": "fast"}}, "must be a number"),
        ({"fused": {**_GOOD_ROW, "t_enc_s": True}}, "must be a number"),
        ({"fused": {**_GOOD_ROW, "samples": 0}}, "positive"),
        ({"fused": {**_GOOD_ROW, "samples": 2.5}}, "positive"),
        ({"fused": {**_GOOD_ROW, "samples": True}}, "positive"),
    ],
)
def test_malformed_route_rows_are_rejected(routes, match):
    rec = costmodel.calibrate(GOLDEN).to_record()
    rec["routes"] = routes
    with pytest.raises(ValueError, match=match):
        costmodel.validate_profile(rec)


# --------------------------------------------------------------------- #
# selector contracts
# --------------------------------------------------------------------- #


def test_static_profile_is_selector_noop():
    """The constants-equivalent profile must not move a single float in any
    selector — the contract the jx-calib-reselect audit pins on every
    ANALYSIS.json rebuild."""
    prof = costmodel.static_profile()
    for d in (4096, LSTM_D):
        for ratio in (0.001, 0.01, 0.1):
            for W in (8, 32):
                assert costmodel.select_rs_mode(
                    d, W, ratio
                ) == costmodel.select_rs_mode(d, W, ratio, profile=prof)
            for n_slices, per_slice in ((8, 4), (2, 16)):
                base = costmodel.select_hier_plan(d, n_slices, per_slice, ratio)
                withp = costmodel.select_hier_plan(
                    d, n_slices, per_slice, ratio, profile=prof
                )
                assert (base["ici"], base["dcn"]) == (withp["ici"], withp["dcn"])
                assert base["table"] == withp["table"]


def test_golden_profile_flips_small_slice_hier_plan():
    """The fitted r15 profile charges measured encode seconds on the fused
    DCN leg — the only profile-sensitive candidate row — so at the
    small-slice-count shape where fused wins statically, the calibrated
    planner walks away from it and its pick prices strictly better under
    the fitted model (the BENCH_CALIB_r16 claim)."""
    prof = costmodel.calibrate(GOLDEN)
    static = costmodel.select_hier_plan(LSTM_D, 2, 16, 0.01)
    calib = costmodel.select_hier_plan(LSTM_D, 2, 16, 0.01, profile=prof)
    s_key = f"{static['ici']}+{static['dcn']}"
    c_key = f"{calib['ici']}+{calib['dcn']}"
    assert static["dcn"] == "fused"
    assert s_key != c_key
    assert calib["table"][c_key] < calib["table"][s_key]


def test_config_profile_knob_requires_auto_selector(tmp_path):
    path = tmp_path / "profile.json"
    costmodel.calibrate(GOLDEN).save(path)
    with pytest.raises(ValueError, match="auto"):
        DeepReduceConfig(profile=str(path))
    with pytest.raises(ValueError, match="ctrl"):
        DeepReduceConfig(
            profile=str(path), communicator="sparse_rs", rs_mode="auto",
            compressor="topk", memory="none", deepreduce=None,
            ctrl=True, telemetry=True,
        )
    # with an auto selector the knob is accepted
    cfg = DeepReduceConfig(
        profile=str(path), communicator="sparse_rs", rs_mode="auto",
        compressor="topk", memory="none", deepreduce=None,
    )
    assert cfg.profile == str(path)


# --------------------------------------------------------------------- #
# Trainer re-selection under the bounded-retrace contract
# --------------------------------------------------------------------- #


def test_trainer_apply_profile_bounded_retrace(tmp_path):
    """End-to-end: a hier-auto Trainer on the (2, 4) virtual mesh commits
    one plan; the constants-equivalent profile is a no-op; the fitted r15
    profile flips the plan (one new executable — cache size == plans
    visited); re-applying the same profile compiles nothing."""
    import flax.linen as nn

    from deepreduce_tpu.train import Trainer

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(4)(x)

    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.05, memory="none",
        deepreduce=None, hier=True, hier_ici="auto", hier_dcn="auto",
        ici_size=4,
    )
    trainer = Trainer(MLP(), cfg, optax.sgd(0.1))
    rng = np.random.default_rng(0)
    batch = (
        jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32)),
        jnp.zeros((8,), jnp.int32),
    )
    state = trainer.init_state(jax.random.PRNGKey(0), batch)
    assert trainer._plan_key is not None
    state, loss, _ = trainer.step(state, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    assert len(trainer.visited_plan_keys) == 1

    # constants-equivalent profile: keep the committed program
    rec = trainer.apply_profile(costmodel.static_profile())
    assert not rec["switched"]
    assert trainer.visited_plan_keys == (trainer._plan_key,)

    # fitted profile: re-select, swap the exchanger, compile ONE new step
    path = tmp_path / "profile.json"
    costmodel.calibrate(GOLDEN).save(path)
    rec = trainer.apply_profile(path)
    assert rec["switched"], rec
    assert rec["old"] != rec["new"]
    state, loss, _ = trainer.step(state, batch, jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))
    assert len(trainer.visited_plan_keys) == 2

    # idempotent re-apply: same pick, no third executable
    rec2 = trainer.apply_profile(path)
    assert not rec2["switched"]
    state, loss, _ = trainer.step(state, batch, jax.random.PRNGKey(3))
    assert len(trainer.visited_plan_keys) == 2


def test_trainer_apply_profile_rejected_under_ctrl():
    import flax.linen as nn

    from conftest import shared_mesh
    from deepreduce_tpu.train import Trainer

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    cfg = DeepReduceConfig(
        deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01,
        memory="residual", min_compress_size=10,
        ctrl=True, telemetry=True, ctrl_ladder="0.01,0.02",
    )
    trainer = Trainer(MLP(), cfg, optax.sgd(0.1), shared_mesh(4))
    with pytest.raises(ValueError, match="ctrl"):
        trainer.apply_profile(costmodel.static_profile())
