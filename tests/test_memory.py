"""Residual error-feedback semantics (tensorflow/deepreduce.py:31-52 spec)."""

import jax.numpy as jnp
import numpy as np

from deepreduce_tpu import memory


def test_compensate_update_cycle():
    grads = {"a": jnp.asarray([1.0, 2.0, 3.0]), "b": jnp.asarray([[4.0]])}
    res = memory.init(grads)
    comp = memory.compensate(grads, res, beta=0.9, gamma=1.0)
    np.testing.assert_allclose(np.asarray(comp["a"]), [1.0, 2.0, 3.0])
    # pretend the codec dropped half of 'a'
    decompressed = {"a": jnp.asarray([1.0, 0.0, 3.0]), "b": jnp.asarray([[4.0]])}
    res2 = memory.update(comp, decompressed)
    np.testing.assert_allclose(np.asarray(res2["a"]), [0.0, 2.0, 0.0])
    np.testing.assert_allclose(np.asarray(res2["b"]), [[0.0]])
    # next step re-injects the dropped mass
    comp2 = memory.compensate(grads, res2, beta=0.9, gamma=1.0)
    np.testing.assert_allclose(np.asarray(comp2["a"]), [1.0, 2.0 * 0.9 + 2.0, 3.0])


def test_dropped_mass_conserved():
    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.normal(size=64).astype(np.float32))
    res = memory.init(g)
    total_seen = jnp.zeros_like(g)
    for step in range(5):
        comp = memory.compensate(g, res)
        sent = jnp.where(jnp.abs(comp) > jnp.percentile(jnp.abs(comp), 75), comp, 0.0)
        res = memory.update(comp, sent)
        total_seen = total_seen + sent
    # residual + delivered == 5 * grad (nothing lost or double counted)
    np.testing.assert_allclose(np.asarray(total_seen + res), np.asarray(5.0 * g), rtol=1e-5)
