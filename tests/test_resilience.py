"""Resilience subsystem tests: elastic participation, chaos injection, and
graceful degradation of the compressed exchange.

Pinned contracts:

- all-ones participation mask is BITWISE identical to no mask, for every
  decode strategy (loop/vmap/ring), the bucketed path, the per-tensor path
  and the dense allreduce baseline;
- a dropped worker keeps its un-sent gradient mass in the residual EF
  accumulator and re-delivers it on rejoin (exact, on a lossless codec);
- a corrupted payload fails its checksum and degrades to an exact-zero
  contribution (params stay finite) while `checksum_failures` counts it;
- resilience off is zero-cost: the trainer step traces to the identical
  jaxpr with every resilience seam replaced by a raiser (never called);
- host-side retry backs off deterministically;
- the analysis gate's new rules fire on negative fixtures.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from conftest import shared_mesh
from deepreduce_tpu import FedAvg, FedConfig
from deepreduce_tpu.analysis.ast_lint import R_AST_MASK, lint_source
from deepreduce_tpu.analysis.jaxpr_audit import check_off_identical
from deepreduce_tpu.analysis.rules import R_RESILIENCE_OFF, jaxpr_hash
from deepreduce_tpu.comm import GradientExchanger, PayloadLayout
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.resilience import chaos, faults, retry
from deepreduce_tpu.train import Trainer
from deepreduce_tpu.utils.compat import shard_map

from test_train import TinyMLP, _data

W, D = 8, 2048

BLOOM_CFG = dict(
    deepreduce="index", index="bloom", compress_ratio=0.05, fpr=0.01,
    bloom_blocked="mod", policy="p0", memory="residual", min_compress_size=100,
)


# ---------------------------------------------------------------------- #
# FaultPlan + participation_mask
# ---------------------------------------------------------------------- #


def test_fault_plan_parse():
    plan = faults.FaultPlan.parse("2@5:9, 0@12")
    assert plan.entries == ((2, 5, 9), (0, 12, 13))


@pytest.mark.parametrize("bad", ["", "   ", "2@", "x@3", "1@5:5", "1@7:3", "2@5;9"])
def test_fault_plan_parse_rejects(bad):
    with pytest.raises(ValueError):
        faults.FaultPlan.parse(bad)


def test_fault_plan_mask_schedule():
    plan = faults.FaultPlan.parse("2@5:9,0@12")
    for step, dropped in [(4, set()), (5, {2}), (8, {2}), (9, set()),
                          (12, {0}), (13, set())]:
        m = np.asarray(plan.mask(step, 4))
        assert set(np.where(~m)[0].tolist()) == dropped, (step, m)


def test_fault_plan_mask_ignores_out_of_range_workers():
    # a plan written for an 8-way mesh still traces on a 4-way one
    m = np.asarray(faults.FaultPlan.parse("6@0:100").mask(3, 4))
    assert m.all()


def test_participation_mask_none_when_unconfigured():
    assert faults.participation_mask(8, 0, jax.random.PRNGKey(0)) is None


def test_participation_mask_deterministic_and_composed():
    key = jax.random.PRNGKey(7)
    kw = dict(drop_rate=0.5, fault_plan="1@3")
    m1 = np.asarray(faults.participation_mask(8, 3, key, **kw))
    m2 = np.asarray(faults.participation_mask(8, 3, key, **kw))
    np.testing.assert_array_equal(m1, m2)  # replicated by construction
    assert not m1[1]  # the plan drop survives the AND with PRNG dropout
    # pure-plan mask at a non-plan step is all ones
    m3 = np.asarray(faults.participation_mask(8, 0, key, fault_plan="1@3"))
    assert m3.all()


# ---------------------------------------------------------------------- #
# chaos injector + payload checksum units
# ---------------------------------------------------------------------- #


def _chaos(**kw):
    base = dict(drop_rate=0.0, corrupt_rate=0.0, truncate_rate=0.0, seed=0)
    base.update(kw)
    return chaos.ChaosInjector(**base)


def test_chaos_deterministic_and_modes():
    buf = jnp.asarray(np.arange(1, 65, dtype=np.uint8))
    drop = _chaos(drop_rate=1.0).perturb(buf, step=3, worker=2)
    assert np.asarray(drop).sum() == 0  # whole payload "never arrives"
    trunc = np.asarray(_chaos(truncate_rate=1.0).perturb(buf, step=3, worker=2))
    assert (trunc[32:] == 0).all() and (trunc[:32] == np.arange(1, 33)).all()
    inj = _chaos(corrupt_rate=1.0, corrupt_frac=0.5)
    c1 = np.asarray(inj.perturb(buf, step=3, worker=2))
    c2 = np.asarray(inj.perturb(buf, step=3, worker=2))
    np.testing.assert_array_equal(c1, c2)  # same (step, worker) -> same damage
    assert (c1 != np.asarray(buf)).any()
    c3 = np.asarray(inj.perturb(buf, step=4, worker=2))
    assert (c3 != c1).any()  # damage varies with the step


def test_chaos_from_config_gating():
    assert chaos.ChaosInjector.from_config(DeepReduceConfig(**BLOOM_CFG)) is None
    cfg = DeepReduceConfig(resilience=True, payload_checksum=True,
                           chaos_corrupt_rate=0.1, **BLOOM_CFG)
    inj = chaos.ChaosInjector.from_config(cfg)
    assert inj is not None and inj.corrupt_rate == 0.1


def test_payload_layout_checksum():
    sds = {"v": jax.ShapeDtypeStruct((16,), jnp.float32)}
    layout = PayloadLayout(sds, checksum=True)
    assert layout.nbytes == layout.payload_nbytes + 4 == 68
    payload = {"v": jnp.arange(16, dtype=jnp.float32)}
    buf = layout.pack(payload)
    assert buf.shape == (68,)
    np.testing.assert_array_equal(
        np.asarray(layout.unpack(buf)["v"]), np.asarray(payload["v"])
    )
    assert float(layout.verify(buf)) == 1.0
    corrupt = buf.at[5].set(buf[5] ^ np.uint8(0xFF))
    assert float(layout.verify(corrupt)) == 0.0
    # the XOR salt makes a fully-zeroed buffer fail its own zeroed word, so
    # a chaos 'drop' is detected too
    assert float(layout.verify(jnp.zeros_like(buf))) == 0.0
    # checksum off: wire footprint unchanged, verify is constant truth
    plain = PayloadLayout(sds)
    assert plain.nbytes == plain.payload_nbytes == 64
    assert float(plain.verify(plain.pack(payload))) == 1.0


# ---------------------------------------------------------------------- #
# masked exchange: all-ones identity + EF re-delivery
# ---------------------------------------------------------------------- #


def _grads(seed=0, n=W, d=D):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        (rng.normal(size=(n, d)) * rng.random((n, d)) ** 2).astype(np.float32)
    )


def _exchange_once(cfg_kwargs, grads_w, mask=None, step=0):
    """One jitted shard_map'd exchange; returns (agg, residual) as numpy
    pytrees (residual None when cfg has no memory)."""
    tmap = jax.tree_util.tree_map
    cfg = DeepReduceConfig(**cfg_kwargs)
    n = jax.tree_util.tree_leaves(grads_w)[0].shape[0]
    sds = tmap(lambda g: jax.ShapeDtypeStruct(g.shape[1:], jnp.float32), grads_w)
    ex = GradientExchanger(sds, cfg, num_workers=n)
    res0 = ex.init_state(tmap(lambda g: jnp.zeros(g.shape[1:], jnp.float32), grads_w))
    if res0 is not None:
        res0 = tmap(lambda r: jnp.broadcast_to(r[None], (n,) + r.shape), res0)
    res_spec = P() if res0 is None else P("data")

    if mask is None:

        def spmd(g, res):
            r0 = None if res is None else tmap(lambda r: r[0], res)
            agg, new_res, _ = ex.exchange(tmap(lambda x: x[0], g), r0, step=step)
            if new_res is not None:
                new_res = tmap(lambda r: r[None], new_res)
            return tmap(lambda x: x[None], agg), new_res

        fn = shard_map(spmd, mesh=shared_mesh(n), in_specs=(P("data"), res_spec),
                       out_specs=(P("data"), res_spec), check_vma=False)
        agg, res = jax.jit(fn)(grads_w, res0)
    else:

        def spmd(g, res, m):
            r0 = None if res is None else tmap(lambda r: r[0], res)
            agg, new_res, _ = ex.exchange(
                tmap(lambda x: x[0], g), r0, step=step, mask=m
            )
            if new_res is not None:
                new_res = tmap(lambda r: r[None], new_res)
            return tmap(lambda x: x[None], agg), new_res

        fn = shard_map(spmd, mesh=shared_mesh(n),
                       in_specs=(P("data"), res_spec, P()),
                       out_specs=(P("data"), res_spec), check_vma=False)
        agg, res = jax.jit(fn)(grads_w, res0, jnp.asarray(mask))
    to_np = lambda t: None if t is None else tmap(np.asarray, t)
    return to_np(agg), to_np(res)


@pytest.mark.parametrize(
    "extra",
    [
        {"decode_strategy": "loop"},
        {"decode_strategy": "vmap", "decode_batch": 4},
        {"decode_strategy": "ring"},
    ],
    ids=["loop", "vmap", "ring"],
)
def test_all_ones_mask_bitwise_identical_fused(extra):
    g = _grads()
    base, base_res = _exchange_once({**BLOOM_CFG, **extra}, g)
    ones, ones_res = _exchange_once(
        {**BLOOM_CFG, **extra}, g, mask=np.ones(W, bool)
    )
    np.testing.assert_array_equal(base, ones)
    np.testing.assert_array_equal(
        jax.tree_util.tree_leaves(base_res)[0], jax.tree_util.tree_leaves(ones_res)[0]
    )


def test_all_ones_mask_bitwise_identical_bucketed():
    rng = np.random.default_rng(3)
    g = {
        "a": jnp.asarray(rng.normal(size=(W, 1500)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(W, 600)).astype(np.float32)),
    }
    kw = {**BLOOM_CFG, "bucket_bytes": 4000}
    base, _ = _exchange_once(kw, g)
    ones, _ = _exchange_once(kw, g, mask=np.ones(W, bool))
    for k in base:
        np.testing.assert_array_equal(base[k], ones[k])


def test_all_ones_mask_bitwise_identical_per_tensor_and_dense():
    g = _grads(d=512)
    pt = {**BLOOM_CFG, "fused": False, "memory": "none"}
    np.testing.assert_array_equal(
        _exchange_once(pt, g)[0], _exchange_once(pt, g, mask=np.ones(W, bool))[0]
    )
    dense = dict(communicator="allreduce", compressor="none", deepreduce=None,
                 memory="none")
    np.testing.assert_array_equal(
        _exchange_once(dense, g)[0],
        _exchange_once(dense, g, mask=np.ones(W, bool))[0],
    )


@pytest.mark.parametrize("rs_mode", ["sparse", "quantized", "oktopk"])
def test_all_ones_mask_bitwise_identical_sparse_rs(rs_mode):
    """The re-owned reduce-scatter routes through the full
    GradientExchanger path (communicator='sparse_rs', resilience=True):
    mask=ones is bitwise the mask-free exchange on every re-ownable
    rs_mode — the identity the resilience-off-identical rule demands of
    every masked communicator."""
    g = _grads(seed=21, d=2048)
    kw = dict(
        compressor="topk", compress_ratio=0.03, memory="none",
        communicator="sparse_rs", rs_mode=rs_mode, deepreduce=None,
        resilience=True,
    )
    base, _ = _exchange_once(kw, g)
    ones, _ = _exchange_once(kw, g, mask=np.ones(W, bool))
    np.testing.assert_array_equal(base, ones)


def test_dropped_worker_mass_redelivers_through_residual():
    """On a lossless codec (top-k at ratio 1.0): dropping worker 0 moves
    its ENTIRE gradient into its residual, the masked mean renormalizes by
    the live count, and the next (all-live) step re-delivers the held mass
    exactly — the EF telescoping identity under elastic participation."""
    lossless = dict(compressor="topk", compress_ratio=1.0, deepreduce=None,
                    memory="residual", min_compress_size=1)
    g = _grads(d=256)
    gn = np.asarray(g)
    mask = np.ones(W, bool)
    mask[0] = False

    agg1, res1 = _exchange_once(lossless, g, mask=mask)
    # live workers decode losslessly -> zero residual; the dropped worker
    # holds its whole compensated gradient
    np.testing.assert_allclose(res1[0], gn[0], rtol=1e-6)
    assert np.abs(res1[1:]).max() < 1e-5
    np.testing.assert_allclose(
        agg1[0], gn[1:].sum(axis=0) / 7.0, rtol=1e-5, atol=1e-6
    )

    # rejoin: feed the held residual back as EF state, no mask this time
    cfg = DeepReduceConfig(**lossless)
    ex = GradientExchanger(
        jax.ShapeDtypeStruct((256,), jnp.float32), cfg, num_workers=W
    )

    def spmd(gw, res):
        agg, new_res, _ = ex.exchange(gw[0], res[0], step=1)
        return agg[None], new_res[None]

    fn = shard_map(spmd, mesh=shared_mesh(W), in_specs=(P("data"), P("data")),
                   out_specs=(P("data"), P("data")), check_vma=False)
    agg2, res2 = jax.jit(fn)(g, jnp.asarray(res1))
    # worker 0 ships g0 + held g0; every aggregate row sees the extra mass
    np.testing.assert_allclose(
        np.asarray(agg2)[0], (gn.sum(axis=0) + gn[0]) / 8.0, rtol=1e-5, atol=1e-6
    )
    assert np.abs(np.asarray(res2)).max() < 1e-5  # nothing left pending


# ---------------------------------------------------------------------- #
# trainer-level: drop schedule + chaos, telemetry counters, zero-cost-off
# ---------------------------------------------------------------------- #


def _trainer(cfg, n=4):
    return Trainer(TinyMLP(), cfg, optax.sgd(0.1, momentum=0.9), shared_mesh(n))


def test_train_under_drop_schedule_and_corruption():
    """20 steps on the 4-way mesh with a deterministic drop schedule AND
    20%-per-payload wire corruption: loss stays finite and decreases, the
    dropped-step count matches the plan exactly, and every corrupted
    payload lands in checksum_failures instead of the params."""
    cfg = DeepReduceConfig(
        telemetry=True, resilience=True, fault_plan="2@3:6,0@8:10",
        payload_checksum=True, chaos_corrupt_rate=0.2, **BLOOM_CFG
    )
    trainer = _trainer(cfg)
    x, y = _data(n=256)
    state = trainer.init_state(jax.random.PRNGKey(0), (x[:64], y[:64]))
    key = jax.random.PRNGKey(1)
    losses = []
    for step in range(20):
        lo = (step * 64) % 192
        state, loss, _ = trainer.step(
            state, (x[lo:lo + 64], y[lo:lo + 64]), jax.random.fold_in(key, step)
        )
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    summary = trainer.telemetry_summary()
    assert summary["dropped_steps"] == 5.0  # steps 3,4,5 + 8,9
    assert summary["checksum_failures"] > 0.0
    assert summary["live_workers_per_step"] < 4.0


def test_resilience_off_step_traces_identically(monkeypatch):
    """cfg.resilience=False must cost literally nothing: the step program
    hashes identically when every resilience seam is replaced by a raiser
    — i.e. the disabled program never even reaches the subsystem."""
    cfg = DeepReduceConfig(telemetry=False, **BLOOM_CFG)

    def _hash():
        import dataclasses

        trainer = _trainer(cfg)
        x, y = _data(n=64)
        state = trainer.init_state(jax.random.PRNGKey(0), (x[:32], y[:32]))
        trainer._build(state.residuals is not None)
        state_nores = dataclasses.replace(state, residuals=None)
        closed = jax.make_jaxpr(trainer._raw_step_fn)(
            state_nores, state.residuals, (x[:32], y[:32]), jax.random.PRNGKey(1)
        )
        return jaxpr_hash(closed)

    h_off = _hash()

    def _boom(*a, **kw):
        raise AssertionError("resilience seam reached with resilience off")

    monkeypatch.setattr(faults, "participation_mask", _boom)
    monkeypatch.setattr(chaos.ChaosInjector, "perturb", _boom)
    monkeypatch.setattr(PayloadLayout, "verify", _boom)
    assert _hash() == h_off


# ---------------------------------------------------------------------- #
# fedavg participation
# ---------------------------------------------------------------------- #

_FED_CFG = DeepReduceConfig(
    compressor="topk", compress_ratio=0.25, deepreduce="index", index="integer",
    policy="p0", memory="residual", min_compress_size=16,
)


def _fed_round(participation):
    from test_fedavg import _problem

    # clients_per_round is a power of two so the live-count division is
    # exact whether XLA divides by the traced live count or the constant C
    # — that keeps the all-ones assertion bitwise instead of 1-ulp fuzzy
    _, batches_for, loss_fn, params = _problem(num_clients=6)
    fed = FedConfig(num_clients=6, clients_per_round=4, local_steps=2)
    fa = FedAvg(loss_fn, _FED_CFG, fed, optax.sgd(0.05))
    state = fa.init(params)
    key = jax.random.PRNGKey(11)
    ids = fa.sample_clients(state, key)
    xs, ys = batches_for(np.asarray(ids), round_seed=0)
    if participation is None:
        new_state, out = jax.jit(fa.run_round)(
            state, ids, (xs, ys), jax.random.fold_in(key, 1)
        )
    else:
        run = jax.jit(
            lambda st, i, b, k, p: fa.run_round(st, i, b, k, participation=p)
        )
        new_state, out = run(
            state, ids, (xs, ys), jax.random.fold_in(key, 1),
            jnp.asarray(participation),
        )
    return state, new_state, out, np.asarray(ids)


def test_fedavg_all_ones_participation_identical():
    _, s_none, out_none, _ = _fed_round(None)
    _, s_ones, out_ones, _ = _fed_round(np.ones(4, bool))
    for a, b in zip(
        jax.tree_util.tree_leaves(s_none.params),
        jax.tree_util.tree_leaves(s_ones.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(out_none["rel_volume"]) == float(out_ones["rel_volume"])


def test_fedavg_dropped_client_excluded_and_residual_untouched():
    part = np.array([False, True, True, True])
    before, after, out, ids = _fed_round(part)
    _, full, _, _ = _fed_round(None)
    # excluding a client's update changes the server mean
    assert any(
        (np.asarray(a) != np.asarray(b)).any()
        for a, b in zip(
            jax.tree_util.tree_leaves(after.params),
            jax.tree_util.tree_leaves(full.params),
        )
    )
    for leaf in jax.tree_util.tree_leaves(after.params):
        assert np.isfinite(np.asarray(leaf)).all()
    # the dropped client never compressed, so its C2S residual row is
    # byte-identical to the pre-round state; live clients accrued EF mass
    dropped, live = int(ids[0]), int(ids[1])
    for b4, aft in zip(
        jax.tree_util.tree_leaves(before.c2s_residuals),
        jax.tree_util.tree_leaves(after.c2s_residuals),
    ):
        np.testing.assert_array_equal(np.asarray(b4)[dropped], np.asarray(aft)[dropped])
    assert any(
        np.abs(np.asarray(l)[live]).sum() > 0
        for l in jax.tree_util.tree_leaves(after.c2s_residuals)
    )


# ---------------------------------------------------------------------- #
# host-side retry
# ---------------------------------------------------------------------- #


def test_retry_backoff_sequence_and_success():
    sleeps, calls = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry.retry_call(flaky, attempts=4, sleep=sleeps.append) == "ok"
    assert sleeps == [0.05, 0.1]  # deterministic: base * multiplier^attempt


def test_retry_exhaustion_reraises():
    sleeps = []
    with pytest.raises(OSError):
        retry.retry_call(
            lambda: (_ for _ in ()).throw(OSError("down")),
            attempts=3, sleep=sleeps.append,
        )
    assert sleeps == [0.05, 0.1]  # attempts-1 sleeps, then the raise


def test_retry_non_retryable_propagates_immediately():
    sleeps = []

    def corrupt():
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry.retry_call(corrupt, sleep=sleeps.append)
    assert sleeps == []
    with pytest.raises(ValueError):
        retry.retry_call(lambda: 1, attempts=0)


# ---------------------------------------------------------------------- #
# analysis gate: negative fixtures for the new rules
# ---------------------------------------------------------------------- #


def test_ast_mask_host_branch_fires_on_value_branch():
    src = "def f(mask):\n    if mask.sum() > 0:\n        return 1\n    return 0\n"
    v = lint_source(src, "deepreduce_tpu/comm.py")
    assert [x.rule for x in v] == [R_AST_MASK]
    src_w = "def f(row_weights):\n    while row_weights.any():\n        pass\n"
    assert [x.rule for x in lint_source(src_w, "deepreduce_tpu/train.py")] == [
        R_AST_MASK
    ]


def test_ast_mask_host_branch_allows_presence_gates():
    src = (
        "def f(mask, cfg):\n"
        "    if mask is not None and cfg.communicator in ('qar',):\n"
        "        return 1\n"
        "    if not (mask is None):\n"
        "        return 2\n"
        "    return 0\n"
    )
    assert lint_source(src, "deepreduce_tpu/comm.py") == []
    # out of scope: host-side tooling may branch on anything
    src_val = "def f(mask):\n    if mask.sum() > 0:\n        return 1\n"
    assert lint_source(src_val, "deepreduce_tpu/tracking.py") == []


def test_check_off_identical_detects_trace_residue():
    class Seam:
        scale = staticmethod(lambda x: x)

    def make_fn():
        # fresh function object per trace (check_off_identical's contract:
        # jax caches traces by callable identity)
        return lambda x: Seam.scale(x) + 1.0

    args = (jnp.zeros((4,), jnp.float32),)
    clean = check_off_identical(
        "fixture", make_fn, args, [(Seam, "scale", lambda x: x)]
    )
    assert clean.violations == []
    dirty = check_off_identical(
        "fixture", make_fn, args, [(Seam, "scale", lambda x: x * 2.0)]
    )
    assert [v.rule for v in dirty.violations] == [R_RESILIENCE_OFF]
    # the seam is restored after the check
    assert Seam.scale(jnp.ones(())) == 1.0


def test_quick_audit_includes_resilience_specs():
    from deepreduce_tpu.analysis.jaxpr_audit import audit_specs

    labels = [label for label, _ in audit_specs(quick=True)]
    assert "resilience:off-identical" in labels
    assert "exchange:fused-loop-resilient" in labels
