"""PolySeg (in-graph knot search) and PolyFitHost (searched knots,
transmitted breaks) value codecs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu import sparse
from deepreduce_tpu.codecs import polyfit_host, polyseg


def _sp(d=30000, ratio=0.02, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.normal(size=d).astype(np.float32)
    return g, sparse.topk(jnp.asarray(g), ratio)


def test_polyseg_round_trip_quality():
    g, sp = _sp()
    meta = polyseg.PolySegMeta(k=sp.k)
    payload = polyseg.encode(sp, meta)
    out = polyseg.decode(payload, meta, sp.shape)
    # indices recovered exactly, signs ride the indices
    assert set(np.asarray(out.indices).tolist()) == set(np.asarray(sp.indices).tolist())
    got = np.asarray(out.values)
    lut = dict(zip(np.asarray(sp.indices).tolist(), np.asarray(sp.values).tolist()))
    want = np.asarray([lut[i] for i in np.asarray(out.indices).tolist()])
    assert np.mean(np.sign(got) == np.sign(want)) > 0.99
    rms = np.sqrt(np.mean((got - want) ** 2))
    assert rms / (np.abs(want).mean() + 1e-9) < 0.2


def test_polyseg_breaks_are_ascending_and_transmitted():
    g, sp = _sp(seed=1)
    meta = polyseg.PolySegMeta(k=sp.k, num_segments=4)
    payload = polyseg.encode(sp, meta)
    b = np.asarray(payload.breaks)
    assert b[0] == 0 and b[-1] == sp.k
    assert np.all(np.diff(b) >= 0)
    assert payload.coeffs.shape == (4, 6)


def test_polyseg_jit():
    g, sp = _sp(seed=2)
    meta = polyseg.PolySegMeta(k=sp.k)
    enc = jax.jit(lambda s: polyseg.encode(s, meta))
    dec = jax.jit(lambda p: polyseg.decode(p, meta, sp.shape))
    out = dec(enc(sp))
    assert out.values.shape == (sp.k,)


def test_polyfit_host_round_trip_quality():
    g, sp = _sp(seed=3)
    meta = polyfit_host.PolyFitHostMeta(k=sp.k)
    payload = polyfit_host.encode(sp, meta)
    out = polyfit_host.decode(payload, meta, sp.shape)
    want = np.sort(np.asarray(sp.values))[::-1]
    got = np.asarray(out.values)
    rms = np.sqrt(np.mean((got - want) ** 2))
    assert rms / (np.abs(want).mean() + 1e-9) < 0.15
    # breaks transmitted, pos/neg boundary among them
    num_pos = int((want > 0).sum())
    bounds = np.asarray(payload.bounds)[: int(payload.n_seg) + 1]
    assert num_pos in bounds.tolist()


def test_polyfit_host_knot_search_reference_shape():
    # knot search on a convex curve places breaks away from endpoints
    y = np.exp(-np.linspace(0, 5, 2000)).astype(np.float64)
    breaks = polyfit_host.find_breaks(y)
    assert all(0 < b < 2000 for b in breaks)
    assert breaks == sorted(breaks)
