"""Sequence/context- and tensor-parallelism tests on the 8-virtual-device
CPU mesh: ring attention and Ulysses all-to-all vs the O(s²) oracle
(forward AND gradients), sequence-parallel BERT vs its unsharded twin,
GSPMD tensor-parallel BERT vs single-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import shared_mesh
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deepreduce_tpu.parallel import (
    bert_tp_rules,
    factor_devices,
    make_mesh,
    ring_attention,
    tp_shardings,
    ulysses_attention,
)
from deepreduce_tpu.parallel.ring import ring_self_attention_reference


def _qkv(b=2, s=64, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda i: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    return mk(0), mk(1), mk(2)


def _seq_mesh(n):
    return shared_mesh(n, "seq")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [4, 8])
def test_ring_matches_oracle(causal, n):
    q, k, v = _qkv()
    mesh = _seq_mesh(n)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
        mesh=mesh,
        in_specs=P(None, "seq"),
        out_specs=P(None, "seq"),
    )
    got = jax.jit(fn)(q, k, v)
    want = ring_self_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_oracle(causal):
    q, k, v = _qkv(h=8)  # heads must divide by axis size
    mesh = _seq_mesh(4)
    fn = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq", causal=causal),
        mesh=mesh,
        in_specs=P(None, "seq"),
        out_specs=P(None, "seq"),
    )
    got = jax.jit(fn)(q, k, v)
    want = ring_self_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_gradients_match_oracle():
    q, k, v = _qkv(s=32, seed=3)
    mesh = _seq_mesh(4)
    sharded = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh,
        in_specs=P(None, "seq"),
        out_specs=P(None, "seq"),
    )
    co = jnp.asarray(np.random.default_rng(9).normal(size=q.shape).astype(np.float32))
    loss_s = lambda q, k, v: (sharded(q, k, v) * co).sum()
    loss_o = lambda q, k, v: (ring_self_attention_reference(q, k, v) * co).sum()
    gs = jax.jit(jax.grad(loss_s, argnums=(0, 1, 2)))(q, k, v)
    go = jax.jit(jax.grad(loss_o, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gs, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_bert_seq_parallel_matches_unsharded(attention):
    from deepreduce_tpu.models import BertEncoder

    n = 4
    kw = dict(vocab_size=64, hidden=16, layers=2, heads=4, mlp_dim=32, max_len=32)
    sp = BertEncoder(attention=attention, seq_axis="seq", **kw)
    local = BertEncoder(attention=attention, seq_axis=None, **kw)

    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 32)), jnp.int32
    )
    variables = local.init(jax.random.PRNGKey(0), tokens)
    want = local.apply(variables, tokens)

    mesh = _seq_mesh(n)
    fn = shard_map(
        lambda t: sp.apply(variables, t),
        mesh=mesh,
        in_specs=P(None, "seq"),
        out_specs=P(None, "seq"),
    )
    got = jax.jit(fn)(tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_bert_tensor_parallel_matches_single_device():
    from deepreduce_tpu.models import BertEncoder

    model = BertEncoder(
        vocab_size=64, hidden=16, layers=2, heads=4, mlp_dim=32, max_len=16
    )
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(2, 16)), jnp.int32
    )
    variables = model.init(jax.random.PRNGKey(0), tokens)
    want = model.apply(variables, tokens)

    mesh = make_mesh({"model": 2})
    shardings = tp_shardings(variables["params"], mesh, bert_tp_rules())
    # the rules must actually shard something (not everything replicated)
    n_sharded = sum(
        any(ax is not None for ax in s.spec)
        for s in jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
    )
    assert n_sharded >= 4 * 2 + 3  # qkv/out/mlp kernels+biases per layer + embeds
    params_tp = jax.device_put(variables["params"], shardings)
    got = jax.jit(lambda p, t: model.apply({"params": p}, t))(params_tp, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_bert_invalid_mode_combinations_raise():
    from deepreduce_tpu.models import BertEncoder
    from deepreduce_tpu.models.bert import TransformerLayer

    tokens = jnp.zeros((1, 8), jnp.int32)
    dense_sharded = BertEncoder(
        vocab_size=16, hidden=8, layers=1, heads=2, mlp_dim=16, max_len=8,
        attention="dense", seq_axis="seq",
    )
    with pytest.raises(ValueError, match="sequence-sharded"):
        dense_sharded.init(jax.random.PRNGKey(0), tokens)

    layer = TransformerLayer(hidden=8, heads=2, mlp_dim=16, attention="ring")
    x = jnp.zeros((1, 8, 8), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        layer.init(jax.random.PRNGKey(0), x, mask=jnp.ones((1, 1, 8, 8), bool))


def test_factor_devices_and_make_mesh():
    assert factor_devices(8, ("data", "seq")) == {"data": 4, "seq": 2}
    assert factor_devices(7, ("data", "seq")) == {"data": 7, "seq": 1}
    sizes = factor_devices(8, ("data", "seq", "model"))
    assert np.prod(list(sizes.values())) == 8
    mesh = make_mesh({"data": 2, "seq": 2})
    assert mesh.shape == {"data": 2, "seq": 2}


def test_bert_remat_matches_no_remat():
    """jax.checkpoint (nn.remat) must change memory, not math: gradients with
    and without rematerialization agree."""
    import optax

    from deepreduce_tpu.models import BertEncoder

    kw = dict(vocab_size=32, hidden=16, layers=2, heads=4, mlp_dim=32, max_len=16)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 32, (2, 16)), jnp.int32)

    def grads_for(remat):
        model = BertEncoder(remat=remat, **kw)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]

        def loss(p):
            logits = model.apply({"params": p}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tokens
            ).mean()

        return jax.grad(loss)(params)

    g0, g1 = grads_for(False), grads_for(True)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6)
