"""Decode-strategy equivalence on the virtual 8-worker CPU mesh.

The fused allgather exchange has three benchable decode strategies
(config.decode_strategy): the sequential 'loop', the batched 'vmap'
(groups of decode_batch workers under jax.vmap), and the overlapped
'ring' (W-1 double-buffered lax.ppermute hops, comm_ring.py). All three
share ONE decode program (`GradientExchanger._decode_fused_row`), so the
aggregate must be the same order-insensitive sum — equal within f32
associativity tolerance, with 'ring' additionally accumulating in a
per-worker rotation order. These tests pin that contract for the bloom
and qsgd configs, plus the ring's (W-1)/W wire accounting and the config
validation surface.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import shared_mesh
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.utils.compat import shard_map

W, D = 8, 4096

BLOOM_CFG = dict(
    deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01,
    bloom_blocked="mod", policy="p0", min_compress_size=100,
)
QSGD_CFG = dict(
    deepreduce="both", index="bloom", value="qsgd", policy="p0",
    compress_ratio=0.05, fpr=0.05, bloom_blocked="mod", min_compress_size=100,
)


def _mesh(n=W):
    return shared_mesh(n)


def _run(cfg, grads_w, step=0):
    n = grads_w.shape[0]
    ex = GradientExchanger(
        jax.ShapeDtypeStruct(grads_w.shape[1:], jnp.float32), cfg, num_workers=n
    )
    res0 = ex.init_state(jnp.zeros(grads_w.shape[1:], jnp.float32))
    if res0 is not None:
        res0 = jax.tree_util.tree_map(
            lambda r: jnp.broadcast_to(r[None], (n,) + r.shape), res0
        )

    def spmd(g, res):
        if res is not None:
            res = jax.tree_util.tree_map(lambda r: r[0], res)
        agg, new_res, stats = ex.exchange(g[0], res, step=step)
        if new_res is not None:
            new_res = jax.tree_util.tree_map(lambda r: r[None], new_res)
        return agg[None], new_res, stats.total_bits

    res_spec = P() if res0 is None else P("data")
    fn = shard_map(
        spmd,
        mesh=_mesh(n),
        in_specs=(P("data"), res_spec),
        out_specs=(P("data"), res_spec, P()),
        check_vma=False,
    )
    agg, res, bits = jax.jit(fn)(jnp.asarray(grads_w), res0)
    res_leaf = (
        None if res is None else np.asarray(jax.tree_util.tree_leaves(res)[0])
    )
    return np.asarray(agg), res_leaf, float(bits), ex


def _grads(seed=0, n=W, d=D):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, d)) * rng.random((n, d)) ** 2).astype(np.float32)


@pytest.mark.parametrize(
    "codec_cfg", [BLOOM_CFG, QSGD_CFG], ids=["bloom-index", "bloom-qsgd-both"]
)
@pytest.mark.parametrize("memory", ["none", "residual"])
def test_strategies_agree(codec_cfg, memory):
    """loop / vmap / ring produce the same aggregate (and residual state)
    within f32 sum-associativity tolerance, and identical wire bits."""
    grads_w = _grads(seed=3)
    outs = {}
    for strategy in ("loop", "vmap", "ring"):
        cfg = DeepReduceConfig(
            memory=memory, decode_strategy=strategy, decode_batch=3, **codec_cfg
        )
        outs[strategy] = _run(cfg, grads_w)
    agg_l, res_l, bits_l, _ = outs["loop"]
    for strategy in ("vmap", "ring"):
        agg_s, res_s, bits_s, _ = outs[strategy]
        np.testing.assert_allclose(agg_s, agg_l, rtol=1e-5, atol=1e-6)
        assert bits_s == bits_l  # same payloads cross the wire
        if memory == "residual":
            np.testing.assert_allclose(res_s, res_l, rtol=1e-5, atol=1e-6)


def test_ring_aggregate_replicated_within_tolerance():
    """The ring accumulates in per-worker rotation order, so worker copies
    of the aggregate agree only up to f32 associativity — but they must
    agree to tolerance (the replicated-update invariant, relaxed)."""
    cfg = DeepReduceConfig(memory="none", decode_strategy="ring", **BLOOM_CFG)
    agg, _, _, _ = _run(cfg, _grads(seed=5))
    for w in range(1, W):
        np.testing.assert_allclose(agg[w], agg[0], rtol=1e-5, atol=1e-6)


def test_vmap_group_size_does_not_change_result():
    """decode_batch only trades peak memory for kernel width; G=1, G=W and a
    non-divisor G all land on the same aggregate within f32 tolerance."""
    grads_w = _grads(seed=7)
    ref = None
    for G in (1, 3, W):
        cfg = DeepReduceConfig(
            memory="none", decode_strategy="vmap", decode_batch=G, **BLOOM_CFG
        )
        agg, _, _, _ = _run(cfg, grads_w)
        if ref is None:
            ref = agg
        else:
            np.testing.assert_allclose(agg, ref, rtol=1e-5, atol=1e-6)


def test_ring_payload_bytes_has_wire_factor():
    """payload_bytes reports the explicit ring hops: (W-1)·B per worker,
    versus the allgather path's logical injection B."""
    like = jax.ShapeDtypeStruct((D,), jnp.float32)
    g = jnp.zeros((D,), jnp.float32)
    base = dict(BLOOM_CFG)
    b_ag = GradientExchanger(
        like, DeepReduceConfig(memory="none", **base), num_workers=W
    ).payload_bytes(g)
    b_ring = GradientExchanger(
        like, DeepReduceConfig(memory="none", decode_strategy="ring", **base),
        num_workers=W,
    ).payload_bytes(g)
    assert b_ring == (W - 1) * b_ag


def test_config_validation():
    with pytest.raises(ValueError, match="decode_strategy"):
        DeepReduceConfig(decode_strategy="bogus")
    with pytest.raises(ValueError, match="decode_batch"):
        DeepReduceConfig(decode_batch=0)
    # non-fused / non-allgather routes never reach the fused decode: the
    # strategy would be silently ignored, so construction refuses
    with pytest.raises(ValueError, match="fused"):
        GradientExchanger(
            jax.ShapeDtypeStruct((D,), jnp.float32),
            DeepReduceConfig(fused=False, decode_strategy="ring", **BLOOM_CFG),
        )
    with pytest.raises(ValueError, match="ignored"):
        GradientExchanger(
            jax.ShapeDtypeStruct((D,), jnp.float32),
            DeepReduceConfig(
                communicator="allreduce", compressor="none", deepreduce=None,
                memory="none", decode_strategy="vmap",
            ),
        )


def test_ring_single_worker_degenerates():
    """W=1: no hops, the own decode IS the aggregate (mirrors the 1-chip
    self-gather path the TPU bench exercises)."""
    cfg = DeepReduceConfig(memory="residual", decode_strategy="ring", **BLOOM_CFG)
    grads_w = _grads(seed=9, n=1)
    agg, res, _, ex = _run(cfg, grads_w)
    assert agg.shape == (1, D)
    # aggregate == own decode; residual == grad - own decode
    np.testing.assert_allclose(agg[0] + res[0], grads_w[0], rtol=1e-5, atol=1e-6)
