"""Experiment tracker (the reference's WANDB regression-record role,
README.md:53): run dirs with config.json / metrics.jsonl / summary.json,
plus the offline query side."""

import json

from deepreduce_tpu import tracking


def test_run_records_config_metrics_summary(tmp_path):
    root = str(tmp_path / "track")
    with tracking.Run(root, name="exp1", config={"fpr": 0.001, "index": "bloom"},
                      tags=["bloom", "p0"]) as run:
        run.log({"loss": 1.5, "rel_volume": 0.12}, step=0)
        run.log({"loss": 0.9, "rel_volume": 0.12}, step=5)
        run.finish({"last_loss": 0.9})

    assert tracking.runs(root) == ["exp1"]
    cfg = tracking.config(root, "exp1")
    assert cfg["config"]["fpr"] == 0.001
    assert cfg["tags"] == ["bloom", "p0"]

    hist = list(tracking.history(root, "exp1"))
    assert [h["step"] for h in hist] == [0, 5]
    assert hist[1]["loss"] == 0.9
    assert tracking.summary(root, "exp1")["last_loss"] == 0.9


def test_numpy_scalars_jsonable(tmp_path):
    import numpy as np

    root = str(tmp_path / "track")
    run = tracking.Run(root, name="exp2", config={"ratio": np.float32(0.01)})
    run.log({"loss": np.float64(2.0), "k": np.int32(7)})
    run.finish({"arr": [np.int64(1), np.int64(2)]})
    hist = list(tracking.history(root, "exp2"))
    assert hist[0]["loss"] == 2.0 and hist[0]["k"] == 7
    assert tracking.summary(root, "exp2")["arr"] == [1, 2]
    # everything on disk is plain JSON
    for f in ("config.json", "summary.json"):
        json.load(open(f"{root}/exp2/{f}"))


def test_auto_step_and_missing_run(tmp_path):
    root = str(tmp_path / "t")
    run = tracking.Run(root)
    run.log({"a": 1})
    run.log({"a": 2})
    run.finish()
    name = tracking.runs(root)[0]
    assert [h["step"] for h in tracking.history(root, name)] == [0, 1]
    assert tracking.runs(str(tmp_path / "nope")) == []
    assert tracking.summary(root, name) == {}  # wrong-name guard below
    assert tracking.summary(root, "missing") == {}


def test_user_metric_named_step_or_ts_does_not_clobber(tmp_path):
    root = str(tmp_path / "t")
    run = tracking.Run(root, name="clash")
    run.log({"step": 999, "ts": -1.0, "loss": 0.5}, step=3)
    run.finish()
    h = list(tracking.history(root, "clash"))[0]
    assert h["step"] == 3  # record's own step wins
    assert h["ts"] > 0  # record's own timestamp wins
    assert h["metric.step"] == 999 and h["metric.ts"] == -1.0
    assert h["loss"] == 0.5
