"""Composition-layer tests: value/index/both modes, the idxs[mapping]
recombination, small-tensor bypass, wire accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu.config import DeepReduceConfig, from_params
from deepreduce_tpu.wrappers import TensorCodec


def _grad(d=30000, seed=0):
    return np.random.default_rng(seed).normal(size=d).astype(np.float32)


def _run(cfg, g, step=0):
    codec = TensorCodec(g.shape, cfg, name="t")
    key = jax.random.PRNGKey(0)
    payload = codec.encode(jnp.asarray(g), step=step, key=key)
    dense = np.asarray(codec.decode(payload, step=step)).reshape(-1)
    stats = codec.wire_stats(payload)
    return codec, payload, dense, stats


def test_mode_none_plain_topk():
    g = _grad()
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.01)
    codec, payload, dense, stats = _run(cfg, g)
    k = codec.k
    want_idx = np.argsort(-np.abs(g))[:k]
    np.testing.assert_allclose(dense[want_idx], g[want_idx], rtol=1e-6)
    assert float(stats.rel_volume()) == pytest.approx(2 * k * 32 / (g.size * 32))


def test_mode_value_polyfit():
    g = _grad(seed=1)
    cfg = DeepReduceConfig(deepreduce="value", value="polyfit", compress_ratio=0.01)
    codec, payload, dense, stats = _run(cfg, g)
    k = codec.k
    want_idx = np.argsort(-np.abs(g))[:k]
    # fitted values land at the true top-k positions with small error
    err = np.abs(dense[want_idx] - g[want_idx])
    assert np.median(err) < 0.2 * np.abs(g[want_idx]).mean()
    assert float(stats.val_rel_volume()) < 0.01 * 32 / 32 * 0.5  # coeffs << raw values


def test_mode_index_bloom_fp_aware():
    g = _grad(seed=2)
    cfg = DeepReduceConfig(deepreduce="index", index="bloom", compress_ratio=0.01, fpr=0.01)
    codec, payload, dense, stats = _run(cfg, g)
    # FP-aware contract: every nonzero of the reconstruction equals the dense value
    nz = np.flatnonzero(dense)
    np.testing.assert_allclose(dense[nz], g[nz], rtol=1e-6)
    # bloom index bits beat raw 32-bit indices
    assert float(stats.idx_rel_volume()) < codec.k * 32 / (g.size * 32)


@pytest.mark.parametrize("value_codec", ["polyfit", "qsgd"])
def test_mode_both_recombination(value_codec):
    g = _grad(seed=3)
    cfg = DeepReduceConfig(
        deepreduce="both", index="bloom", value=value_codec, compress_ratio=0.01, fpr=0.001
    )
    codec, payload, dense, stats = _run(cfg, g)
    nz = np.flatnonzero(dense)
    assert len(nz) > 0.9 * codec.k
    if value_codec == "qsgd":
        # lossy but bounded: per-bucket bound well under value scale
        err = np.abs(dense[nz] - g[nz])
        assert np.max(err) < 0.5
    else:
        # polyfit: values at reconstructed positions approximate dense values
        err = np.abs(dense[nz] - g[nz])
        assert np.median(err) < 0.25 * np.abs(g[nz]).mean()
    # total volume well below raw sparse (idx+val raw = 2*k*32 bits)
    assert float(stats.total_bits) < 2 * codec.k * 32


def test_both_qsgd_elides_mapping():
    g = _grad(seed=4)
    cfg = DeepReduceConfig(deepreduce="both", index="bloom", value="qsgd", compress_ratio=0.01)
    codec = TensorCodec(g.shape, cfg)
    payload = codec.encode(jnp.asarray(g), key=jax.random.PRNGKey(0))
    assert payload.mapping is None  # order-preserving value codec


def test_small_tensor_bypass():
    g = _grad(d=500, seed=5)
    cfg = DeepReduceConfig(deepreduce="both", compress_ratio=0.1)
    codec = TensorCodec(g.shape, cfg)
    assert not codec.compressed
    payload = codec.encode(jnp.asarray(g), key=jax.random.PRNGKey(0))
    dense = np.asarray(codec.decode(payload)).reshape(-1)
    k = codec.k
    want_idx = np.argsort(-np.abs(g))[:k]
    np.testing.assert_allclose(dense[want_idx], g[want_idx], rtol=1e-6)


def test_from_params_reference_keys():
    cfg = from_params(
        {
            "compressor": "topk",
            "compress_ratio": 0.01,
            "memory": "residual",
            "communicator": "allgather",
            "deepreduce": "both",
            "value": "qsgd",
            "index": "bloom",
            "fpr": 0.6,
            "policy": "p0",
            "quantum_num": 127,
            "bucket_size": 512,
            "micro-benchmark": True,
            "unknown_key": 42,
        }
    )
    assert cfg.deepreduce == "both" and cfg.policy == "p0" and cfg.fpr == 0.6
    assert cfg.micro_benchmark is True


def test_encode_decode_jit_stable():
    g = _grad(seed=6)
    cfg = DeepReduceConfig(deepreduce="both", index="bloom", value="polyfit", compress_ratio=0.01)
    codec = TensorCodec(g.shape, cfg)
    enc = jax.jit(lambda t, s, k: codec.encode(t, step=s, key=k))
    dec = jax.jit(lambda p, s: codec.decode(p, step=s))
    key = jax.random.PRNGKey(0)
    p1 = enc(jnp.asarray(g), 0, key)
    p2 = enc(jnp.asarray(g * 1.5), 1, key)
    d1 = dec(p1, 0)
    d2 = dec(p2, 1)
    assert d1.shape == d2.shape


def test_layer_pattern_whitelist():
    """TF PolySeg applies only to whitelisted conv layers
    (tensorflow/deepreduce.py:458,526); here the whitelist is a regex on the
    tensor's pytree path."""
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    cfg = DeepReduceConfig(
        deepreduce="index", index="integer", compress_ratio=0.1,
        min_compress_size=100, layer_pattern="Conv",
    )
    conv = TensorCodec((64, 64), cfg, name="Conv_1/kernel")
    dense = TensorCodec((64, 64), cfg, name="Dense_0/kernel")
    assert conv.compressed
    assert not dense.compressed

    # excluded layers pass through FULLY dense — not even sparsified
    # (tensorflow/deepreduce.py:515-516), unlike the small-size gate
    import numpy as np
    g = jnp.asarray(np.random.default_rng(0).normal(size=(64, 64)).astype(np.float32))
    payload = dense.encode(g, step=jnp.asarray(0))
    out = np.asarray(dense.decode(payload, step=jnp.asarray(0)))
    np.testing.assert_array_equal(out, np.asarray(g))
    stats = dense.wire_stats(payload)
    assert float(stats.rel_volume()) == 1.0  # dense bits, no index stream


@pytest.mark.parametrize("index_codec", ["bloom", "rle", "integer", "huffman"])
@pytest.mark.parametrize("value_codec", ["polyfit", "doubleexp", "qsgd"])
def test_both_mode_full_matrix(index_codec, value_codec):
    """Every index x value composition must round-trip with small top-coord
    error — the reference allows arbitrary registry pairs in 'both' mode
    (pytorch/deepreduce.py:36-46)."""
    import numpy as np

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    d, ratio = 5000, 0.1
    rng = np.random.default_rng(0)
    g = jnp.asarray((rng.normal(size=d) * rng.random(d) ** 2).astype(np.float32))
    cfg = DeepReduceConfig(
        deepreduce="both", index=index_codec, value=value_codec,
        compress_ratio=ratio, fpr=0.01, min_compress_size=100, memory="none",
    )
    codec = TensorCodec((d,), cfg, name="t")
    payload = codec.encode(g, step=jnp.asarray(0), key=jax.random.PRNGKey(0))
    out = np.asarray(codec.decode(payload, step=jnp.asarray(0)))
    k = int(d * ratio)
    top = np.argsort(-np.abs(np.asarray(g)))[:k]
    err = np.abs(out[top] - np.asarray(g)[top]).mean()
    # bloom pairs admit FP displacement error; exact-index codecs are tighter
    assert err < (0.25 if index_codec == "bloom" else 0.08), err


def test_tpu_defaults_preset_round_trips_on_cpu():
    """The measured-best preset (approx_topk + mod-blocked bloom + fused +
    pallas) must stay portable: on the CPU backend the pallas knob degrades
    to the XLA path and the full flagship shape still round-trips."""
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    cfg = DeepReduceConfig.tpu_defaults(
        compressor="topk", compress_ratio=0.02, deepreduce="both",
        index="bloom", value="qsgd", policy="p0", fpr=0.05,
        memory="none", min_compress_size=100,
    )
    assert cfg.approx_topk and cfg.fused and cfg.use_pallas
    assert cfg.bloom_blocked == "mod"
    d = 8192
    codec = TensorCodec((d,), cfg, name="t")
    rng = np.random.default_rng(21)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    key = jax.random.PRNGKey(0)
    payload = jax.jit(lambda t: codec.encode(t, step=0, key=key))(g)
    out = np.asarray(jax.jit(lambda p: codec.decode(p, step=0))(payload))
    assert np.isfinite(out).all() and (out != 0).sum() > 0


def test_doubleexp_9000_gate_default():
    """Reference parity (tensorflow/deepreduce.py:396,426): with the knobs
    left at defaults, DoubleExp compresses only tensors > 9000 elements;
    the generic gate stays 1000; explicit settings win."""
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    dexp = DeepReduceConfig(deepreduce="value", value="doubleexp")
    assert not TensorCodec((5000,), dexp, name="w").compressed
    assert TensorCodec((9001,), dexp, name="w").compressed
    # generic codecs keep the 1000-element PyTorch gate
    qsgd = DeepReduceConfig(deepreduce="value", value="qsgd")
    assert TensorCodec((5000,), qsgd, name="w").compressed
    # explicit min_compress_size overrides the per-codec default — even
    # when set to the generic default value itself
    explicit = DeepReduceConfig(
        deepreduce="value", value="doubleexp", min_compress_size=100
    )
    assert TensorCodec((5000,), explicit, name="w").compressed
    explicit_1000 = DeepReduceConfig(
        deepreduce="value", value="doubleexp", min_compress_size=1000
    )
    assert TensorCodec((5000,), explicit_1000, name="w").compressed


def test_polyseg_conv_whitelist_default():
    """Reference parity (tensorflow/deepreduce.py:458,515-516): with no
    layer_pattern set, PolySeg applies only to conv-named layers; others
    pass through uncompressed. An explicit pattern wins."""
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    pseg = DeepReduceConfig(deepreduce="value", value="polyseg")
    assert TensorCodec((20000,), pseg, name="Conv_3/kernel").compressed
    assert not TensorCodec((20000,), pseg, name="Dense_0/kernel").compressed
    # other value codecs are unaffected by the polyseg default
    qsgd = DeepReduceConfig(deepreduce="value", value="qsgd")
    assert TensorCodec((20000,), qsgd, name="Dense_0/kernel").compressed
    # explicit pattern overrides the conv default
    explicit = DeepReduceConfig(
        deepreduce="value", value="polyseg", layer_pattern="Dense"
    )
    assert TensorCodec((20000,), explicit, name="Dense_0/kernel").compressed
