"""DumpLogger: the reference's verbosity-gated dump tree
(compression_utils.hpp:96-176 + logger.cc) — directory scheme, file
contents, frequency gating — plus the policy-error and measured-FPR
diagnostics feeding it."""

import numpy as np

import jax.numpy as jnp

from deepreduce_tpu.codecs import bloom
from deepreduce_tpu.logging_utils import DumpLogger, policy_errors
from deepreduce_tpu.sparse import SparseGrad


def test_dump_tree_layout_and_contents(tmp_path):
    log = DumpLogger(str(tmp_path), rank=3, verbosity=1, frequency=2)
    log.log_fpr(0, "conv1", configured=0.01, measured=0.012)
    log.log_policy_errors(0, "conv1", errors=5, k=100)
    log.log_stats(0, "conv1", initial_bits=32000, final_bits=4000)
    log.log_values(0, "conv1", np.arange(4, dtype=np.float32))
    log.log_coefficients(0, "conv1", np.ones((2, 3)))

    d = tmp_path / "3" / "step_0" / "conv1"
    assert (d / "fpr.txt").read_text().startswith("FalsePositives_Rate: 0.012")
    assert "PolicyErrors: 5 / 100" in (d / "policy_errors.txt").read_text()
    assert "Initial_Size: 32000" in (d / "stats.txt").read_text()
    assert len((d / "values.csv").read_text().strip().splitlines()) == 4
    assert len((d / "coefficients.csv").read_text().strip().splitlines()) == 2


def test_frequency_and_verbosity_gating(tmp_path):
    log = DumpLogger(str(tmp_path), rank=0, verbosity=1, frequency=2)
    log.log_fpr(1, "g", 0.01, 0.01)  # step 1 % 2 != 0 -> gated
    assert not (tmp_path / "0" / "step_1").exists()

    off = DumpLogger(str(tmp_path), rank=0, verbosity=0)
    off.log_fpr(0, "g", 0.01, 0.01)  # verbosity 0 -> everything gated
    assert not (tmp_path / "0" / "step_0").exists()


def test_policy_errors_diagnostic():
    selected = np.array([1, 5, 9, 12])
    true_idx = np.array([1, 5, 7])
    assert policy_errors(selected, true_idx) == 2  # 9 and 12 are not true


def test_measured_fpr_feeds_logger(tmp_path):
    d, k = 4096, 128
    rng = np.random.default_rng(0)
    idx = jnp.asarray(np.sort(rng.choice(d, size=k, replace=False)).astype(np.int32))
    sp = SparseGrad(
        values=jnp.ones((k,), jnp.float32), indices=idx,
        nnz=jnp.asarray(k, jnp.int32), shape=(d,),
    )
    meta = bloom.BloomMeta.create(k, d, fpr=0.05, policy="p0")
    words = bloom.insert(sp.indices, sp.nnz, meta)
    measured = float(bloom.measured_fpr(sp, words, meta))
    assert 0.0 <= measured < 0.25  # calibrated well above-configured is a bug

    log = DumpLogger(str(tmp_path), rank=0, verbosity=1)
    log.log_fpr(0, "g0", configured=0.05, measured=measured)
    assert "configured: 0.05" in (tmp_path / "0" / "step_0" / "g0" / "fpr.txt").read_text()
