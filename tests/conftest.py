"""Test env: 8 virtual CPU devices so multi-worker collectives run without a
pod — the multi-host simulation the reference's MPI-only world couldn't do
(SURVEY.md §4). Must run before jax is imported anywhere."""

import os

# force CPU even when the ambient environment pins JAX_PLATFORMS (e.g. axon);
# backends initialize lazily, so this works even though pytest plugins may
# have already imported jax. Deliberately self-contained (not
# utils.force_platform): conftest must not import the package before the
# backend assert below.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
assert not jax._src.xla_bridge._backends, "jax backend initialized before conftest"
