"""Test env: 8 virtual CPU devices so multi-worker collectives run without a
pod — the multi-host simulation the reference's MPI-only world couldn't do
(SURVEY.md §4). Must run before jax is imported anywhere."""

import os

# force CPU even when the ambient environment pins JAX_PLATFORMS (e.g. axon);
# backends initialize lazily, so this works even though pytest plugins may
# have already imported jax. Deliberately self-contained (not
# utils.force_platform): conftest must not import the package before the
# backend assert below.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import functools

import jax

jax.config.update("jax_platforms", "cpu")
assert not jax._src.xla_bridge._backends, "jax backend initialized before conftest"

import numpy as np
import pytest


@functools.lru_cache(maxsize=None)
def shared_mesh(n: int, axis: str = "data"):
    """One Mesh object per (n, axis) for the whole session. Identical mesh
    objects let jax's jit cache hit across tests instead of re-tracing the
    same shard_map program per test module — test helpers import this
    (`from conftest import shared_mesh`) so their local `_mesh()` wrappers
    all resolve to the same instance."""
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), (axis,))


@pytest.fixture(scope="session")
def mesh8():
    return shared_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    return shared_mesh(4)
