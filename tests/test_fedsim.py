"""Federated-simulation subsystem tests (deepreduce_tpu.fedsim): round-body
equivalence (vmap == scan == chunked), churn/checksum degradation semantics,
path-keyed codec caching, the client-sharded FedSim driver on the 8-way
virtual mesh with bitwise checkpoint resume, the fed_* config surface, and
the uplink cost model."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepreduce_tpu import FedAvg, FedConfig, checkpoint
from deepreduce_tpu.comm import PayloadLayout
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.fedsim import (
    FedSim,
    TreeCodec,
    cohort_updates,
    make_client_step,
    synthetic_linear_problem,
)
from deepreduce_tpu.resilience.chaos import ChaosInjector

DIM, BATCH, LOCAL = 32, 4, 2


def _cfg(**kw):
    base = dict(
        deepreduce="index",
        index="bloom",
        bloom_blocked="mod",
        compress_ratio=0.25,
        fpr=0.01,
        memory="residual",
        min_compress_size=8,
    )
    base.update(kw)
    return DeepReduceConfig(**base)


def _problem(num_clients=8, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM,)).astype(np.float32)

    def batches_for(n, round_seed):
        r = np.random.default_rng(round_seed)
        xs = r.normal(size=(n, LOCAL, BATCH, DIM)).astype(np.float32)
        ys = (xs @ w_true).astype(np.float32)
        return jnp.asarray(xs), jnp.asarray(ys)

    def loss_fn(params, batch_xy):
        x, y = batch_xy
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    params = {"b": jnp.zeros(()), "w": jnp.zeros((DIM,))}
    return w_true, batches_for, loss_fn, params


def _local_train(loss_fn, opt):
    def train(params, batches, key):
        opt_state = opt.init(params)

        def one(carry, batch):
            p, o = carry
            g = jax.grad(loss_fn)(p, batch)
            u, o = opt.update(g, o, p)
            return (optax.apply_updates(p, u), o), None

        (p, _), _ = jax.lax.scan(one, (params, opt_state), batches)
        return p

    return train


def _leaves_equal(a, b):
    return all(
        bool(jnp.all(x == y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


def _leaves_close(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=atol)


# ---------------------------------------------------------------------- #
# FedConfig + fed_* config validation
# ---------------------------------------------------------------------- #


def test_fed_config_rejects_oversampled_cohort():
    with pytest.raises(ValueError, match="exceeds the"):
        FedConfig(num_clients=4, clients_per_round=5)


@pytest.mark.parametrize(
    "kw",
    [
        dict(num_clients=0, clients_per_round=1),
        dict(num_clients=-3, clients_per_round=1),
        dict(num_clients=4, clients_per_round=0),
        dict(num_clients=4, clients_per_round=2, local_steps=0),
        dict(num_clients=4, clients_per_round=2, server_lr=0.0),
        dict(num_clients=4, clients_per_round=2, server_lr=-1.0),
    ],
)
def test_fed_config_rejects_degenerate_geometry(kw):
    with pytest.raises(ValueError):
        FedConfig(**kw)


def test_fed_knobs_require_master_flag():
    with pytest.raises(ValueError, match="fed=True"):
        DeepReduceConfig(fed_num_clients=10)
    with pytest.raises(ValueError, match="fed=True"):
        DeepReduceConfig(fed_clients_per_round=4)


def test_fed_knobs_validated_under_master_flag():
    with pytest.raises(ValueError):
        DeepReduceConfig(fed=True, fed_num_clients=0, fed_clients_per_round=2)
    with pytest.raises(ValueError, match="exceeds"):
        DeepReduceConfig(fed=True, fed_num_clients=4, fed_clients_per_round=8)
    with pytest.raises(ValueError, match="divide"):
        DeepReduceConfig(
            fed=True, fed_num_clients=64, fed_clients_per_round=10,
            fed_client_chunk=3,
        )
    cfg = DeepReduceConfig(
        fed=True, fed_num_clients=64, fed_clients_per_round=16,
        fed_local_steps=3, fed_server_lr=0.5,
    )
    fed = cfg.fed_config()
    assert (fed.num_clients, fed.clients_per_round) == (64, 16)
    assert (fed.local_steps, fed.server_lr) == (3, 0.5)
    with pytest.raises(ValueError):
        DeepReduceConfig().fed_config()  # fed=False has no round geometry


# ---------------------------------------------------------------------- #
# TreeCodec: path-keyed codec cache (the str(i) flat-index bug)
# ---------------------------------------------------------------------- #


def test_tree_codec_keys_by_path_not_flat_index():
    tc = TreeCodec("c2s", _cfg())
    t_full = {"a": jnp.ones((64,)), "b": jnp.ones(())}
    key = jax.random.PRNGKey(0)
    tc.encode_tree(t_full, None, 0, key)
    expected_paths = set(tc.spec(t_full).paths)
    assert set(tc._codecs) == expected_paths  # paths, not "0"/"1"

    # 'b' alone sits at flat index 0 — index keying would hand it the
    # (64,)-shaped codec built for 'a'; path keying keeps them separate
    payloads, _, spec = tc.encode_tree({"b": jnp.ones(())}, None, 0, key)
    dec = tc.decode_tree(payloads, spec, 0)
    assert dec["b"].shape == ()

    # one path = one static shape, enforced loudly
    path_a = tc.spec(t_full).paths[0]
    with pytest.raises(ValueError, match="keyed by treedef path"):
        tc.codec(path_a, (128,))


def test_fedavg_codecs_are_path_keyed():
    _, _, loss_fn, params = _problem()
    fa = FedAvg(loss_fn, _cfg(), FedConfig(num_clients=4, clients_per_round=2),
                optax.sgd(0.05))
    fa.init(params)
    tc = fa._tree_codecs["c2s"]
    tc.compress_tree(params, None, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0))
    assert set(tc._codecs) == set(tc.spec(params).paths)


# ---------------------------------------------------------------------- #
# round-body equivalence: vmap == scan == chunked
# ---------------------------------------------------------------------- #


def _one_round(impl, participation=None):
    _, batches_for, loss_fn, params = _problem()
    fed = FedConfig(num_clients=8, clients_per_round=4, local_steps=LOCAL)
    fa = FedAvg(loss_fn, _cfg(), fed, optax.sgd(0.05))
    state = fa.init(params)
    key = jax.random.PRNGKey(3)
    ids = fa.sample_clients(state, key)
    batches = batches_for(len(ids), round_seed=0)
    run = jax.jit(fa.run_round, static_argnames=("impl",))
    state, out = run(
        state, ids, batches, jax.random.fold_in(key, 1),
        participation=participation, impl=impl,
    )
    return state, out


def test_run_round_vmap_matches_scan():
    """The acceptance contract: the population driver's vmapped cohort body
    is the scalar reference path up to f32 sum reassociation."""
    s_scan, o_scan = _one_round("scan")
    s_vmap, o_vmap = _one_round("vmap")
    _leaves_close(s_scan.params, s_vmap.params)
    _leaves_close(s_scan.c2s_residuals, s_vmap.c2s_residuals)
    assert float(o_scan["rel_volume"]) == pytest.approx(
        float(o_vmap["rel_volume"]), rel=1e-6
    )


def test_run_round_all_alive_mask_is_bitwise_noop():
    """An all-alive participation mask must not change a single bit: the
    where-SELECT gating and the live-count denominator both reduce to the
    mask-free program's values."""
    s_free, _ = _one_round("scan")
    s_mask, _ = _one_round("scan", participation=jnp.ones((4,), jnp.float32))
    assert _leaves_equal(s_free.params, s_mask.params)
    assert _leaves_equal(s_free.c2s_residuals, s_mask.c2s_residuals)


def test_cohort_chunked_matches_flat_vmap():
    _, batches_for, loss_fn, params = _problem()
    train = _local_train(loss_fn, optax.sgd(0.05))
    tc = TreeCodec("c2s", _cfg())
    C = 8
    batches = batches_for(C, round_seed=1)
    res0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros((C,) + p.shape, p.dtype), params
    )
    positions = jnp.arange(C, dtype=jnp.uint32)
    cs = make_client_step(tc, train, params, 0, jax.random.PRNGKey(5))
    run = functools.partial(
        cohort_updates, cs, batches, res0, positions, update_template=params,
        impl="vmap",
    )
    upd_f, res_f, wire_f, live_f = run(chunk=0)
    upd_c, res_c, wire_c, live_c = run(chunk=2)
    _leaves_close(upd_f, upd_c)
    _leaves_close(res_f, res_c, rtol=1e-6, atol=0)
    assert bool(jnp.all(live_f == live_c))
    for a, b in zip(wire_f, wire_c):
        assert float(a) == pytest.approx(float(b))


# ---------------------------------------------------------------------- #
# degradation: chaos-corrupted uplinks drop out, nothing else moves
# ---------------------------------------------------------------------- #


def test_chaos_round_equals_clean_round_minus_failed_clients():
    """A chaos-injected cohort round must equal the clean round with the
    checksum-failed clients' updates excluded — and the residual bank must
    advance identically (sender-side EF cannot observe wire corruption)."""
    cfg = _cfg(
        resilience=True, payload_checksum=True, chaos_corrupt_rate=0.5,
    )
    _, batches_for, loss_fn, params = _problem()
    train = _local_train(loss_fn, optax.sgd(0.05))
    C = 8
    batches = batches_for(C, round_seed=2)
    res0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros((C,) + p.shape, p.dtype), params
    )
    positions = jnp.arange(C, dtype=jnp.uint32)
    key = jax.random.PRNGKey(7)

    tc = TreeCodec("c2s", cfg)
    sds = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params
    )
    payload_sds, _ = tc.payload_sds(sds)
    layout = PayloadLayout(payload_sds, checksum=True)
    chaos = ChaosInjector.from_config(cfg)
    assert chaos is not None
    cs_chaos = make_client_step(
        tc, train, params, 0, key, layout=layout, chaos=chaos
    )
    upd, res, wire4, live = jax.jit(
        lambda b, r: cohort_updates(
            cs_chaos, b, r, positions, update_template=params,
            checksum=True, impl="vmap",
        )
    )(batches, res0)
    live_np = np.asarray(live)
    assert 0 < live_np.sum() < C, live_np  # both outcomes present

    # the clean reference: same keys/codecs, no wire stage at all
    cs_clean = make_client_step(TreeCodec("c2s", _cfg()), train, params, 0, key)
    dec, nres, _, ok = jax.jit(
        jax.vmap(lambda b, r, p: cs_clean(b, r, p))
    )(batches, res0, positions)
    assert bool(jnp.all(ok == 1.0))
    expected = jax.tree_util.tree_map(
        lambda u: jnp.sum(
            jnp.where(
                live.reshape((C,) + (1,) * (u.ndim - 1)) > 0, u, 0.0
            ),
            axis=0,
        ),
        dec,
    )
    assert _leaves_equal(upd, expected)
    assert _leaves_equal(res, nres)  # EF advances for failed clients too
    # checksum-failed clients still transmitted: wire bits count all C
    clean_wire = jax.jit(
        jax.vmap(lambda b, r, p: cs_clean(b, r, p)[2])
    )(batches, res0, positions)
    for got, per_client in zip(wire4, clean_wire):
        assert float(got) == pytest.approx(float(jnp.sum(per_client)))


# ---------------------------------------------------------------------- #
# FedSim: the client-sharded driver on the 8-way virtual mesh
# ---------------------------------------------------------------------- #


def test_fedsim_sharded_rounds_and_bitwise_resume(mesh8, tmp_path):
    cfg = _cfg(
        fed=True, fed_num_clients=64, fed_clients_per_round=16,
        fed_local_steps=LOCAL,
    )
    fed = cfg.fed_config()
    params0, data_fn, loss_fn = synthetic_linear_problem(DIM, BATCH, LOCAL)

    def build():
        fs = FedSim(
            loss_fn, cfg, fed, optax.sgd(0.1), data_fn,
            mesh=mesh8, client_chunk=2,
        )
        return fs, fs.init(params0)

    fs, state = build()
    assert state.residuals["w"].shape == (64, DIM)  # the sharded bank
    key = jax.random.PRNGKey(0)
    state, m = fs.step(state, jax.random.fold_in(key, 0))
    assert float(m["clients"]) == 16.0  # no churn configured
    assert float(m["checksum_failures"]) == 0.0
    assert float(m["uplink_bytes"]) > 0
    assert 0 < float(m["rel_volume"]) < 1.0
    ckpt = str(tmp_path / "ckpt")
    checkpoint.save(ckpt, state, config=cfg)
    for r in range(1, 3):
        state, m = fs.step(state, jax.random.fold_in(key, r))
    assert all(
        bool(jnp.all(jnp.isfinite(x)))
        for x in jax.tree_util.tree_leaves(state.params)
    )

    # restore into a FRESH driver and replay: bitwise-identical params (the
    # round is one deterministic jitted function of (state, key))
    fs2, template = build()
    state2 = checkpoint.restore(ckpt, template, config=cfg)
    for r in range(1, 3):
        state2, _ = fs2.step(state2, jax.random.fold_in(key, r))
    assert _leaves_equal(state.params, state2.params)
    assert _leaves_equal(state.residuals, state2.residuals)


def test_fedsim_geometry_validation(mesh8):
    params0, data_fn, loss_fn = synthetic_linear_problem(DIM, BATCH, LOCAL)
    cfg = _cfg(fed=True, fed_num_clients=60, fed_clients_per_round=16)
    with pytest.raises(ValueError, match="divide evenly"):
        FedSim(loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
               mesh=mesh8)
    cfg = _cfg(fed=True, fed_num_clients=64, fed_clients_per_round=12)
    with pytest.raises(ValueError, match="divide evenly"):
        FedSim(loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
               mesh=mesh8)
    cfg = _cfg(fed=True, fed_num_clients=64, fed_clients_per_round=16)
    with pytest.raises(ValueError, match="chunk"):
        FedSim(loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
               mesh=mesh8, client_chunk=3)


# ---------------------------------------------------------------------- #
# cost model + telemetry report
# ---------------------------------------------------------------------- #


def test_costmodel_fed_round_time():
    from deepreduce_tpu import costmodel as cm

    t1 = cm.fed_round_time(1000.0, 100)
    assert t1 == pytest.approx(100 * 1000.0 / cm.BW_100MBPS)
    assert cm.fed_round_time(1000.0, 200) > t1  # serialized server ingest
    assert cm.fed_round_time(1000.0, 100, t_client_s=0.5) == pytest.approx(
        t1 + 0.5
    )
    # doubling the server links halves the wire term
    assert cm.fed_round_time(1000.0, 100, server_links=2) == pytest.approx(
        t1 / 2
    )
    assert cm.fed_clients_per_sec(1000.0, 100) == pytest.approx(100 / t1)


def test_telemetry_fedsim_report_rates():
    from deepreduce_tpu.telemetry.__main__ import _fedsim_report

    hist = [
        {"ts": 100.0 + 2.0 * i, "round": i, "clients": 32.0,
         "uplink_bytes": 2048.0, "checksum_failures": 1.0}
        for i in range(5)
    ]
    rep = _fedsim_report(hist)
    assert rep is not None
    assert rep["clients_per_round"]["mean"] == pytest.approx(32.0)
    assert rep["uplink_bytes_per_round"]["mean"] == pytest.approx(2048.0)
    # 32 clients per 2s interval
    assert rep["clients_per_sec"]["mean"] == pytest.approx(16.0)
    assert rep["checksum_failures_total"] == pytest.approx(5.0)
    # sync runs log no staleness series — the async rows must stay absent
    assert "fed_staleness_mean" not in rep
    assert "fed_staleness_max" not in rep
    assert "fed_buffer_fill_per_apply" not in rep
    assert _fedsim_report([{"ts": 1.0, "loss": 0.5}]) is None  # not a fed run


def test_telemetry_fedsim_report_staleness_rows():
    from deepreduce_tpu.telemetry.__main__ import _fedsim_report

    # async driver history: buffer fills 16/32/48 with an apply at 48
    hist = [
        {"ts": 100.0 + 2.0 * i, "round": i, "clients": 16.0,
         "uplink_bytes": 2048.0, "checksum_failures": 0.0,
         "staleness_mean": [0.0, 0.5, 1.0][i],
         "staleness_max": [0.0, 1.0, 2.0][i],
         "buffer_fill": [16.0, 32.0, 48.0][i],
         "applied": [0.0, 0.0, 1.0][i]}
        for i in range(3)
    ]
    rep = _fedsim_report(hist)
    assert rep is not None
    assert rep["fed_staleness_mean"] == pytest.approx(0.5)
    assert rep["fed_staleness_max"] == pytest.approx(2.0)
    # occupancy averaged over APPLY ticks only, not every ingest tick
    assert rep["fed_buffer_fill_per_apply"] == pytest.approx(48.0)


def test_telemetry_fedsim_report_mt_rows():
    """Per-tenant `*_t` list rows from the multi-tenant driver become the
    tenant-indexed report rows — rates, staleness mean/max, buffer fill —
    each a length-T list; single-tenant histories emit none of them."""
    from deepreduce_tpu.telemetry.__main__ import _fedsim_report

    hist = [
        {"ts": 100.0 + 2.0 * i, "round": i, "clients": 24.0,
         "uplink_bytes": 2048.0, "checksum_failures": 0.0,
         "clients_t": [16.0, 8.0],
         "staleness_mean_t": [[0.0, 0.0], [0.0, 0.5], [0.0, 1.0]][i],
         "staleness_max_t": [[0.0, 0.0], [0.0, 1.0], [0.0, 2.0]][i],
         "buffer_fill_t": [[16.0, 8.0], [32.0, 16.0], [48.0, 24.0]][i],
         "applied_t": [[0.0, 0.0], [0.0, 0.0], [1.0, 1.0]][i]}
        for i in range(3)
    ]
    rep = _fedsim_report(hist)
    assert rep is not None
    assert rep["fed_tenants"] == 2
    # each tenant's live count over each 2s interval (first interval kept:
    # only two intervals exist)
    assert rep["fed_mt_clients_per_sec"] == pytest.approx([8.0, 4.0])
    assert rep["fed_mt_staleness_mean"] == pytest.approx([0.0, 0.5])
    assert rep["fed_mt_staleness_max"] == pytest.approx([0.0, 2.0])
    # per-tenant occupancy at that tenant's OWN applies
    assert rep["fed_mt_buffer_fill_per_apply"] == pytest.approx([48.0, 24.0])
    # a single-tenant history carries no tenant-indexed rows
    solo = _fedsim_report(
        [{"ts": 1.0 + i, "round": i, "clients": 16.0,
          "uplink_bytes": 2048.0, "checksum_failures": 0.0}
         for i in range(3)]
    )
    assert solo is not None
    assert "fed_tenants" not in solo
    assert "fed_mt_clients_per_sec" not in solo
