"""Value codecs: polyfit fit quality, qsgd error bounds, doubleexp on true
double-exp curves, gzip losslessness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu import sparse
from deepreduce_tpu.codecs import doubleexp, gzip_codec, polyfit, qsgd


def _topk_sp(d=50000, ratio=0.01, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.normal(size=d).astype(np.float32)
    return g, sparse.topk(jnp.asarray(g), ratio)


# ----------------------------- polyfit ---------------------------------- #


def test_polyfit_round_trip_error_small():
    g, sp = _topk_sp()
    meta = polyfit.PolyFitMeta(k=sp.k)
    payload = polyfit.encode(sp, meta)
    out = polyfit.decode(payload, meta, sp.shape)
    # decoded values are in descending sorted order; compare to sorted truth
    want = np.sort(np.asarray(sp.values))[::-1]
    got = np.asarray(out.values)
    rms = np.sqrt(np.mean((got - want) ** 2))
    scale = np.sqrt(np.mean(want**2))
    assert rms / scale < 0.05, rms / scale
    # indices carry the sort mapping: scattered values land at true positions
    np.testing.assert_array_equal(
        np.sort(np.asarray(payload.indices)), np.sort(np.asarray(sp.indices))
    )


def test_polyfit_segments_match_reference_shape():
    # int(num_pos*r) > 30 gate, pos/neg split (pytorch/deepreduce.py:362-377)
    sizes = np.asarray(polyfit.segment_sizes(1000, jnp.asarray(600)))
    num_pos, num_neg = 600, 400
    want_pos = [int(num_pos * r) for r in polyfit.RATIOS if int(num_pos * r) > 30]
    want_neg = [int(num_neg * r) for r in polyfit.RATIOS if int(num_neg * r) > 30]
    active = sizes[sizes > 0]
    want = want_pos[::-1] + [num_pos - sum(want_pos)] + [num_neg - sum(want_neg)] + want_neg
    np.testing.assert_array_equal(active, [w for w in want if w > 0])
    assert sizes.sum() == 1000


def test_polyfit_all_positive_and_all_negative():
    for sign in (+1.0, -1.0):
        vals = np.sort(np.random.default_rng(1).gamma(2.0, size=500)).astype(np.float32) * sign
        sp = sparse.SparseGrad(
            values=jnp.asarray(vals),
            indices=jnp.arange(500, dtype=jnp.int32),
            nnz=jnp.asarray(500, jnp.int32),
            shape=(5000,),
        )
        meta = polyfit.PolyFitMeta(k=500)
        out = polyfit.decode(polyfit.encode(sp, meta), meta, sp.shape)
        want = np.sort(vals)[::-1]
        rms = np.sqrt(np.mean((np.asarray(out.values) - want) ** 2))
        assert rms / (np.abs(want).mean() + 1e-9) < 0.1


def test_polyfit_wire_bits_much_smaller_than_values():
    g, sp = _topk_sp()
    meta = polyfit.PolyFitMeta(k=sp.k)
    payload = polyfit.encode(sp, meta)
    assert int(polyfit.wire_bits(payload, meta)) < sp.k * 32 * 0.2


# ------------------------------ qsgd ------------------------------------ #


def test_qsgd_error_bound_and_layout():
    g, sp = _topk_sp(seed=2)
    meta = qsgd.QSGDMeta(k=sp.k)
    payload = qsgd.encode(sp, meta, jax.random.PRNGKey(0))
    assert payload.data.shape == (meta.payload_len,)
    out = qsgd.decode(payload, meta, sp.shape)
    vals = np.asarray(sp.values)
    got = np.asarray(out.values)
    # per-bucket error bound: |err| <= norm/quantum per element
    for b in range(meta.num_buckets):
        lo, hi = b * meta.bucket_size, min((b + 1) * meta.bucket_size, sp.k)
        norm = np.linalg.norm(vals[lo:hi])
        assert np.max(np.abs(got[lo:hi] - vals[lo:hi])) <= norm / meta.quantum_num + 1e-6


def test_qsgd_stochastic_rounding_unbiased():
    vals = jnp.full((512,), 0.3)
    sp = sparse.SparseGrad(
        values=vals,
        indices=jnp.arange(512, dtype=jnp.int32),
        nnz=jnp.asarray(512, jnp.int32),
        shape=(512,),
    )
    meta = qsgd.QSGDMeta(k=512)
    outs = []
    for i in range(20):
        payload = qsgd.encode(sp, meta, jax.random.PRNGKey(i))
        outs.append(np.asarray(qsgd.decode(payload, meta, sp.shape).values))
    mean = np.mean(np.stack(outs))
    assert abs(mean - 0.3) < 0.005


def test_qsgd_norm_bytes_survive_wire():
    # int8 bitcast round trip of the f32 norm must be exact
    g, sp = _topk_sp(seed=3)
    meta = qsgd.QSGDMeta(k=sp.k)
    payload = qsgd.encode(sp, meta, jax.random.PRNGKey(0))
    rows = np.asarray(payload.data).reshape(meta.num_buckets, meta.bucket_size + 4)
    norms = np.frombuffer(rows[:, -4:].astype(np.int8).tobytes(), "<f4")
    vals = np.asarray(sp.values)
    for b in range(meta.num_buckets):
        lo, hi = b * meta.bucket_size, min((b + 1) * meta.bucket_size, sp.k)
        np.testing.assert_allclose(norms[b], np.linalg.norm(vals[lo:hi]), rtol=1e-6)


# ---------------------------- doubleexp --------------------------------- #


def _doubleexp_oracle_f64(y):
    """The reference's integral-equation fit in float64
    (tensorflow/deepreduce.py:67-144) as a numpy oracle."""
    k = len(y)
    x = np.arange(1, k + 1, dtype=np.float64)

    def cumtrapz(f):
        seg = 0.5 * (f[1:] + f[:-1])
        return np.concatenate([[0.0], np.cumsum(seg)])

    s = cumtrapz(y)
    ss = cumtrapz(s)
    a_mat = np.array(
        [
            [np.sum(ss * ss), np.sum(ss * s), np.sum(ss * x), np.sum(ss)],
            [np.sum(ss * s), np.sum(s * s), np.sum(s * x), np.sum(s)],
            [np.sum(ss * x), np.sum(s * x), np.sum(x * x), np.sum(x)],
            [np.sum(ss), np.sum(s), np.sum(x), float(k)],
        ]
    )
    b = np.array([np.sum(ss * y), np.sum(s * y), np.sum(x * y), np.sum(y)])
    sol = np.linalg.solve(a_mat, b)
    root = np.sqrt(max(sol[1] ** 2 + 4 * sol[0], 0.0))
    p, q = 0.5 * (sol[1] + root), 0.5 * (sol[1] - root)
    beta, eta = np.exp(p * x), np.exp(q * x)
    m = np.array([[np.sum(beta * beta), np.sum(beta * eta)], [np.sum(beta * eta), np.sum(eta * eta)]])
    amp = np.linalg.solve(m, np.array([np.sum(beta * y), np.sum(eta * y)]))
    return amp[0] * beta + amp[1] * eta


def test_doubleexp_recovers_true_double_exponential():
    k = 2000
    x = np.arange(1, k + 1, dtype=np.float64)
    y = 0.5 * np.exp(-0.002 * x) + 0.1 * np.exp(-0.0005 * x)
    sp = sparse.SparseGrad(
        values=jnp.asarray(y[::-1].astype(np.float32)),  # ascending for sort
        indices=jnp.arange(k, dtype=jnp.int32),
        nnz=jnp.asarray(k, jnp.int32),
        shape=(k * 10,),
    )
    meta = doubleexp.DoubleExpMeta(k=k)
    payload = doubleexp.encode(sp, meta)
    out = doubleexp.decode(payload, meta, sp.shape)
    got = np.asarray(out.values)
    want = np.sort(y)  # ascending |v|
    # parity: our f32 on-device fit tracks the reference's f64 algorithm
    oracle = _doubleexp_oracle_f64(want)
    rel_oracle = np.abs(got - oracle) / (np.abs(oracle) + 1e-9)
    assert np.median(rel_oracle) < 0.05, np.median(rel_oracle)
    # and the algorithm itself is a decent fit of the true curve
    rel_truth = np.abs(got - want) / (np.abs(want) + 1e-9)
    assert np.median(rel_truth) < 0.15, np.median(rel_truth)


def test_doubleexp_signs_ride_indices():
    g, sp = _topk_sp(seed=4, d=20000)
    meta = doubleexp.DoubleExpMeta(k=sp.k)
    payload = doubleexp.encode(sp, meta)
    out = doubleexp.decode(payload, meta, sp.shape)
    # positions recovered exactly; value signs match the true gradient signs
    got_idx = np.asarray(out.indices)
    want_sign = np.sign(g[got_idx])
    got_sign = np.sign(np.asarray(out.values))
    agree = np.mean(want_sign == got_sign)
    assert agree > 0.99
    assert set(got_idx.tolist()) == set(np.asarray(sp.indices).tolist())


# ------------------------------ gzip ------------------------------------ #


def test_gzip_lossless_round_trip():
    g, sp = _topk_sp(seed=5, d=20000)
    meta = gzip_codec.GzipMeta(k=sp.k)
    payload = gzip_codec.encode(sp, meta)
    out = gzip_codec.decode(payload, meta, sp.shape)
    np.testing.assert_array_equal(np.asarray(out.values), np.asarray(sp.values))
    np.testing.assert_array_equal(np.asarray(out.indices), np.asarray(sp.indices))


def test_doubleexp_offset_curve_no_f32_collapse():
    """Regression: exact-top-k magnitude curves start at the sparsification
    threshold (large offset, no near-zero head). The steep-exponent fit used
    to collapse in f32 (amplitudes ~1e-6, curve ~0 almost everywhere); the
    shifted column-normalized amplitude solve keeps it at f64 quality."""
    import numpy as np

    from deepreduce_tpu.codecs import doubleexp

    rng = np.random.default_rng(0)
    g = (rng.normal(size=5000) * rng.random(5000) ** 2).astype(np.float32)
    y = np.sort(np.abs(g))[-500:]
    coeffs = doubleexp._fit(jnp.asarray(y))
    fit = np.asarray(doubleexp._eval(coeffs, 500))
    assert np.abs(fit - y).mean() < 0.05  # was 0.92 before the fix


def test_doubleexp_negative_exponent_no_overflow():
    """Regression: a decaying second exponential (q < 0, generic when the
    4x4 solve returns sol[0] > 0) used to overflow sum(eta^2) in f32 at
    q <= ~-44, silently zeroing that basis column; peak-anchored evaluation
    keeps every basis value in (0, 1] for either sign."""
    import numpy as np

    from deepreduce_tpu.codecs import doubleexp

    x = np.arange(1, 501, dtype=np.float32) / 500.0
    # strongly decaying + strongly growing mixture forces q << 0 and p >> 0
    y = (np.exp(-60.0 * x) + 0.1 * np.exp(8.0 * (x - 1.0))).astype(np.float32)
    coeffs = doubleexp._fit(jnp.asarray(y))
    assert np.all(np.isfinite(np.asarray(coeffs)))
    fit = np.asarray(doubleexp._eval(coeffs, 500))
    assert np.all(np.isfinite(fit))
    assert np.abs(fit - y).mean() < 0.05


# --------------------------- countsketch --------------------------------- #


def test_countsketch_single_entry_exact():
    """One nonzero and one filled bucket per row: every row's point query
    returns the exact value, so the median does too — and queries at other
    indices see empty buckets (0.0) in all but colliding rows."""
    from deepreduce_tpu.codecs import countsketch

    rows, cols = 5, 64
    vals = jnp.asarray([3.5], jnp.float32)
    idxs = jnp.asarray([17], jnp.int32)
    sk = countsketch.sketch_from_sparse(vals, idxs, rows, cols)
    est = np.asarray(countsketch.unsketch_at(sk, idxs))
    np.testing.assert_allclose(est, [3.5], rtol=1e-6)


def test_countsketch_linearity_under_sum():
    """THE property the in-collective route rides: sketch(a) + sketch(b)
    == sketch(a concat b) — summing sketches via psum is summing signals."""
    from deepreduce_tpu.codecs import countsketch

    rng = np.random.default_rng(3)
    rows, cols, d = 5, 256, 4096
    ia = rng.choice(d, 40, replace=False).astype(np.int32)
    ib = rng.choice(d, 40, replace=False).astype(np.int32)
    va = rng.normal(size=40).astype(np.float32)
    vb = rng.normal(size=40).astype(np.float32)
    ska = countsketch.sketch_from_sparse(jnp.asarray(va), jnp.asarray(ia), rows, cols)
    skb = countsketch.sketch_from_sparse(jnp.asarray(vb), jnp.asarray(ib), rows, cols)
    both = countsketch.sketch_from_sparse(
        jnp.concatenate([jnp.asarray(va), jnp.asarray(vb)]),
        jnp.concatenate([jnp.asarray(ia), jnp.asarray(ib)]),
        rows, cols,
    )
    np.testing.assert_allclose(
        np.asarray(ska) + np.asarray(skb), np.asarray(both), rtol=1e-5, atol=1e-6
    )


def test_countsketch_median_estimate_error_bounded():
    """Classic count-sketch guarantee, checked empirically: per-query
    collision noise scales as ~‖v‖₂/√cols, so at cols ≫ k the median-of-
    rows point queries recover a k-sparse signal with aggregate error
    well under the signal norm — and widening the table shrinks it."""
    from deepreduce_tpu.codecs import countsketch

    rng = np.random.default_rng(4)
    rows, d, k = 5, 8192, 80
    idxs = rng.choice(d, k, replace=False).astype(np.int32)
    vals = (rng.normal(size=k) + 2.0 * np.sign(rng.normal(size=k))).astype(np.float32)

    def rel_at(cols):
        sk = countsketch.sketch_from_sparse(
            jnp.asarray(vals), jnp.asarray(idxs), rows, cols
        )
        est = np.asarray(countsketch.unsketch_at(sk, jnp.asarray(idxs)))
        return np.linalg.norm(est - vals) / np.linalg.norm(vals)

    rel_wide, rel_narrow = rel_at(2048), rel_at(256)
    assert rel_wide < 0.2, rel_wide
    # 8x more columns must beat the narrow table (1/sqrt(C) scaling)
    assert rel_wide < rel_narrow, (rel_wide, rel_narrow)


def test_countsketch_codec_registry_roundtrip():
    """The registry-facing TensorCodec stack (deepreduce='value',
    value='countsketch'): encode/decode roundtrip under jit, bounded
    error, and wire bits = the sketch table (indices elided on 'value'
    is not claimed — the value payload alone is the fixed-size table)."""
    from deepreduce_tpu.codecs import registry

    rng = np.random.default_rng(5)
    d, ratio = 8192, 0.01
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), ratio)
    codec = registry.CountSketchCodec(sp.k, d, params={})
    payload = jax.jit(codec.encode)(sp)
    out = jax.jit(lambda p: codec.decode(p, sp.shape))(payload)
    want = np.asarray(sp.values)
    got = np.asarray(out.values)
    rel = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert rel < 0.2, rel
    np.testing.assert_array_equal(np.asarray(out.indices), np.asarray(sp.indices))
    assert int(codec.value_wire_bits(payload)) == payload.sketch.size * 32
