"""Telemetry subsystem tests: span tracing emits valid Chrome traces, the
on-device accumulators agree with the per-step WireStats the trainer already
reports, telemetry-off compiles to a byte-identical program (pinned with the
analysis retrace hash), and the offline CLI consumes tracking run dirs.
Plus the observability satellites: metrics.timed and tracking._jsonable."""

import contextlib
import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from conftest import shared_mesh
from deepreduce_tpu import metrics, tracking
from deepreduce_tpu.analysis.rules import jaxpr_hash
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.telemetry import MetricAccumulators, Tracer, spans
from deepreduce_tpu.telemetry import __main__ as cli
from deepreduce_tpu.train import Trainer

from test_train import TinyMLP, _data


# ---------------------------------------------------------------------- #
# span tracing
# ---------------------------------------------------------------------- #


def test_tracer_emits_valid_chrome_trace(tmp_path):
    tr = Tracer(enabled=True)
    with tr.span("outer"):
        with tr.span("outer/inner"):
            pass
    with pytest.raises(RuntimeError):
        with tr.span("outer/raises"):
            raise RuntimeError("boom")
    tr.counter("wire", {"rel_volume": 0.1})

    trace = tr.to_chrome_trace()
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    events = trace["traceEvents"]
    spans_x = [e for e in events if e["ph"] == "X"]
    # the raising body is still recorded (span records on __exit__)
    assert {e["name"] for e in spans_x} == {"outer", "outer/inner", "outer/raises"}
    for e in spans_x:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0.0
    counters = [e for e in events if e["ph"] == "C"]
    assert counters and counters[0]["args"] == {"rel_volume": 0.1}
    # events come out time-ordered, and the inner span nests in the outer
    assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)
    outer = next(e for e in spans_x if e["name"] == "outer")
    inner = next(e for e in spans_x if e["name"] == "outer/inner")
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3

    path = tmp_path / "trace.json"
    tr.save(path)
    assert json.loads(path.read_text())["traceEvents"]


def test_span_route_label_rides_in_args_not_the_name():
    """Route attribution contract: the label lands in the Chrome event's
    args (where `calibrate` buckets per-route rows) while the span NAME is
    untouched — the name is what named_scope mirrors into HLO, so labeling
    must never move a compiled program."""
    tr = Tracer(enabled=True)
    with tr.span("exchange/encode", route="oktopk"):
        pass
    with tr.span("exchange/encode"):
        pass
    labeled, bare = tr.events
    assert labeled["name"] == bare["name"] == "exchange/encode"
    assert labeled["args"] == {"route": "oktopk"}
    assert "args" not in bare
    # disabled tracers hand back the same inert object regardless of route
    off = Tracer(enabled=False)
    assert off.span("x", route="r") is off.span("y")


def test_disabled_span_is_shared_noop():
    tr = Tracer(enabled=False)
    a, b = tr.span("x"), tr.span("y")
    assert a is b  # one shared inert object, no per-call allocation
    with a:
        pass
    assert tr.events == []
    # the module-level path behaves identically when the global tracer is off
    assert not spans.enabled()
    assert spans.span("anything") is spans.span("other")


def test_configure_reset_clears_events():
    tr = spans.configure(enabled=True, reset=True)
    try:
        with spans.span("probe"):
            pass
        assert len(tr.events) == 1
    finally:
        spans.configure(enabled=False, reset=True)
    assert tr.events == []


# ---------------------------------------------------------------------- #
# on-device accumulators vs. per-step WireStats
# ---------------------------------------------------------------------- #


def _fit_telemetry(cfg, steps=5, batch=64, workers=8):
    mesh = shared_mesh(workers)
    trainer = Trainer(TinyMLP(), cfg, optax.sgd(0.1), mesh)
    x, y = _data()
    state = trainer.init_state(jax.random.PRNGKey(0), (x[:batch], y[:batch]))
    key = jax.random.PRNGKey(1)
    wires = []
    for i in range(steps):
        lo = (i * batch) % (len(x) - batch)
        state, loss, wire = trainer.step(
            state, (x[lo : lo + batch], y[lo : lo + batch]), jax.random.fold_in(key, i)
        )
        wires.append(jax.tree_util.tree_map(float, wire))
    return trainer, wires


BLOOM_CFG = dict(
    deepreduce="index",
    index="bloom",
    compress_ratio=0.05,
    fpr=0.01,
    memory="residual",
    min_compress_size=100,
    telemetry=True,
)
QSGD_CFG = dict(
    deepreduce="value",
    value="qsgd",
    compress_ratio=0.05,
    memory="residual",
    min_compress_size=100,
    telemetry=True,
)


@pytest.mark.parametrize("cfg_kw", [BLOOM_CFG, QSGD_CFG], ids=["bloom", "qsgd"])
def test_accumulators_match_wirestats_sums(cfg_kw):
    steps = 5
    trainer, wires = _fit_telemetry(DeepReduceConfig(**cfg_kw), steps=steps)
    summ = trainer.telemetry_summary()

    assert summ["steps"] == steps
    total_bits = sum(w.index_bits + w.value_bits for w in wires)
    dense_bits = sum(w.dense_bits for w in wires)
    assert summ["cumulative_total_bits"] == pytest.approx(total_bits, rel=1e-4)
    assert summ["rel_volume"] == pytest.approx(total_bits / dense_bits, rel=1e-4)
    # dense_bits is step-constant, so the cumulative ratio equals the mean
    # of the per-step ratios
    per_step = [
        (w.index_bits + w.value_bits) / w.dense_bits for w in wires
    ]
    assert summ["rel_volume"] == pytest.approx(np.mean(per_step), rel=1e-4)
    assert 0.0 < summ["rel_volume"] < 1.0
    assert math.isfinite(summ["compress_err_l2"])
    assert -1.0 <= summ["compress_err_cos"] <= 1.0 + 1e-6
    if cfg_kw["deepreduce"] == "index":
        # bloom: the decoder reconstructs false positives, the accumulator
        # sees them — the measured FPR is in the ballpark of the configured
        # one (generously bounded; it's a probabilistic quantity)
        assert 0.0 < summ["measured_fpr"] < 20 * cfg_kw["fpr"] + 0.05
    else:
        assert summ["measured_fpr"] == 0.0  # value-only path has no bloom


def test_telemetry_accumulator_survives_across_steps():
    trainer, _ = _fit_telemetry(DeepReduceConfig(**QSGD_CFG), steps=3)
    acc = trainer.telemetry
    assert isinstance(acc, MetricAccumulators)
    assert float(acc.steps) == 3.0
    # and another fetch is idempotent
    assert trainer.telemetry_summary()["steps"] == 3.0


def test_summary_window_deltas_match_cumulative_diffs():
    """The per-window delta is EXACTLY the difference of two consecutive
    cumulative fetches — no separate windowed accumulator exists, so the
    CLI's window rows can't drift from the cumulative ones."""
    from deepreduce_tpu.telemetry.device_metrics import fetch_delta

    cfg = DeepReduceConfig(**QSGD_CFG)
    mesh = shared_mesh(8)
    trainer = Trainer(TinyMLP(), cfg, optax.sgd(0.1), mesh)
    x, y = _data()
    batch = 64
    state = trainer.init_state(jax.random.PRNGKey(0), (x[:batch], y[:batch]))
    key = jax.random.PRNGKey(1)

    def run(lo_step, n):
        nonlocal state
        for i in range(lo_step, lo_step + n):
            lo = (i * batch) % (len(x) - batch)
            state, _, _ = trainer.step(
                state, (x[lo : lo + batch], y[lo : lo + batch]),
                jax.random.fold_in(key, i),
            )

    run(0, 3)
    f1 = trainer.telemetry.fetch()
    run(3, 4)
    f2 = trainer.telemetry.fetch()

    delta = fetch_delta(f2, f1)
    assert delta["steps"] == pytest.approx(4.0)
    for k in MetricAccumulators.scalar_fields():
        assert delta[k] == pytest.approx(f2[k] - f1[k], abs=1e-9), k
    for a, b, d in zip(
        f1["bucket_saturated"], f2["bucket_saturated"], delta["bucket_saturated"]
    ):
        assert d == pytest.approx(b - a, abs=1e-9)

    # summary(prev=...) derives the window_* rows from exactly that delta
    summ = trainer.telemetry.summary(prev=f1)
    assert summ["window_steps"] == pytest.approx(4.0)
    derived = MetricAccumulators.derive(delta)
    for k, v in derived.items():
        got = summ["window_" + k]
        if isinstance(v, list):
            assert got == pytest.approx(v)
        else:
            assert got == pytest.approx(v), k
    # cumulative rows are untouched by the windowing
    assert summ["steps"] == pytest.approx(7.0)


# ---------------------------------------------------------------------- #
# disabled == absent: byte-identical step program
# ---------------------------------------------------------------------- #


def _step_jaxpr_hash():
    """Trace the (unjitted) shard_map'd step and hash its jaxpr."""
    cfg = DeepReduceConfig(
        deepreduce="index",
        index="bloom",
        compress_ratio=0.05,
        fpr=0.01,
        memory="residual",
        min_compress_size=100,
        telemetry=False,
    )
    mesh = shared_mesh(4)
    trainer = Trainer(TinyMLP(), cfg, optax.sgd(0.1), mesh)
    x, y = _data(n=64)
    state = trainer.init_state(jax.random.PRNGKey(0), (x[:32], y[:32]))
    trainer._build(state.residuals is not None)
    import dataclasses

    state_nores = dataclasses.replace(state, residuals=None)
    closed = jax.make_jaxpr(trainer._raw_step_fn)(
        state_nores, state.residuals, (x[:32], y[:32]), jax.random.PRNGKey(1)
    )
    return jaxpr_hash(closed)


def test_telemetry_off_jaxpr_identical_to_absent(monkeypatch):
    """cfg.telemetry=False must cost literally nothing: the step program
    with real (disabled) spans hashes identically to one where every span
    call is replaced by a bare nullcontext — i.e. disabled == absent."""
    h_disabled = _step_jaxpr_hash()
    monkeypatch.setattr(
        spans, "span", lambda name, route=None: contextlib.nullcontext()
    )
    h_absent = _step_jaxpr_hash()
    assert h_disabled == h_absent


# ---------------------------------------------------------------------- #
# CLI
# ---------------------------------------------------------------------- #


def _write_run(root, name, *, dt=0.1, n=6, config=None, telemetry=None,
               trace_events=None):
    """Hand-written tracking run dir with controlled step-time spacing."""
    d = root / name
    d.mkdir(parents=True)
    (d / "config.json").write_text(
        json.dumps({"name": name, "tags": [], "config": config or {}})
    )
    with open(d / "metrics.jsonl", "w") as f:
        for i in range(n):
            rec = {"step": i, "ts": 1000.0 + i * dt, "loss": 2.0 - 0.1 * i,
                   "rel_volume": 0.08}
            f.write(json.dumps(rec) + "\n")
    summary = {"last_loss": 2.0 - 0.1 * (n - 1)}
    if telemetry is not None:
        summary["telemetry"] = telemetry
    (d / "summary.json").write_text(json.dumps(summary))
    if trace_events is not None:
        (d / "trace.json").write_text(
            json.dumps({"traceEvents": trace_events, "displayTimeUnit": "ms"})
        )
    return d


def test_cli_summary(tmp_path, capsys):
    _write_run(tmp_path, "runA", telemetry={"steps": 5.0, "rel_volume": 0.08})
    # a tracking ROOT resolves to its latest run
    assert cli.main(["summary", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "runA" in out and "rel_volume" in out and "device accumulators" in out
    assert cli.main(["summary", str(tmp_path / "runA"), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["steps_logged"] == 6
    assert rep["telemetry"]["steps"] == 5.0
    assert rep["step_time_s"]["mean"] == pytest.approx(0.1, rel=1e-6)


def test_cli_summary_missing_run(tmp_path):
    assert cli.main(["summary", str(tmp_path / "nope")]) == 2


def test_cli_compare_two_runs(tmp_path, capsys):
    a = _write_run(tmp_path, "fast", dt=0.1)
    b = _write_run(tmp_path, "slow", dt=0.5)
    assert cli.main(["compare", str(a), str(b)]) == 1  # 5x slower: regression
    assert "REGRESSION" in capsys.readouterr().out
    assert cli.main(["compare", str(a), str(a)]) == 0
    assert cli.main(["compare", str(b), str(a)]) == 0  # faster is fine


def test_cli_compare_against_bench(tmp_path, capsys):
    bench = tmp_path / "BENCH_DECODE_fake.json"
    bench.write_text(
        json.dumps({"detail": {"strategies": {"loop": {"t_step_s": 0.1}}}})
    )
    slow = _write_run(tmp_path, "slow", dt=0.5)
    fast = _write_run(tmp_path, "fast", dt=0.05)
    assert cli.main(["compare", str(slow), "--against", str(bench)]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    assert cli.main(["compare", str(fast), "--against", str(bench)]) == 0
    # a run pinned to a strategy the record lacks is a data error, not a pass
    other = _write_run(tmp_path, "other", dt=0.05,
                       config={"decode_strategy": "vmap"})
    assert cli.main(["compare", str(other), "--against", str(bench)]) == 2


def test_cli_profiles_drift_sentinel(tmp_path, capsys):
    """`telemetry profiles`: identical profiles never flip a committed plan
    selection (exit 0); the fitted TRACE_OVERLAP_r15 golden profile vs the
    static constants is a known planted drift that flips BENCH_CALIB_r16's
    small-slice hier picks (exit 1)."""
    import pathlib

    from deepreduce_tpu import costmodel

    repo = pathlib.Path(__file__).resolve().parent.parent
    g = tmp_path / "golden.json"
    costmodel.calibrate(repo / "TRACE_OVERLAP_r15").save(g)
    s = tmp_path / "static.json"
    costmodel.static_profile().save(s)
    bench = repo / "BENCH_CALIB_r16.json"

    assert cli.main(["profiles", str(g), str(g), "--against", str(bench)]) == 0
    out = capsys.readouterr().out
    assert "0 plan flip(s)" in out and "parameter drift" in out

    assert cli.main(["profiles", str(g), str(s), "--against", str(bench)]) == 1
    cap = capsys.readouterr()
    assert "FLIP" in cap.out
    assert "REGRESSION" in cap.err

    # without --against the sentinel still reports drift, exit 0 (no picks)
    assert cli.main(["profiles", str(g), str(s)]) == 0
    capsys.readouterr()

    # usage/data errors: one profile, unreadable path, pointless bench
    assert cli.main(["profiles", str(g)]) == 2
    assert cli.main(["profiles", str(g), str(tmp_path / "nope.json")]) == 2
    bad = tmp_path / "bad_bench.json"
    bad.write_text(json.dumps({"detail": {"nothing": True}}))
    assert cli.main(["profiles", str(g), str(s), "--against", str(bad)]) == 2


def test_cli_trace_merges_spans_and_counters(tmp_path, capsys):
    tr = Tracer(enabled=True)
    with tr.span("train/step"):
        pass
    run = _write_run(tmp_path, "traced",
                     trace_events=tr.to_chrome_trace()["traceEvents"])
    out = tmp_path / "merged.json"
    assert cli.main(["trace", str(run), "--out", str(out)]) == 0
    merged = json.loads(out.read_text())
    names = {e["name"] for e in merged["traceEvents"]}
    assert "train/step" in names  # the span row
    assert "loss" in names and "rel_volume" in names  # metric counter rows
    phases = {e["ph"] for e in merged["traceEvents"]}
    assert phases == {"X", "C"}
    # without trace.json the metrics alone still produce a trace
    capsys.readouterr()  # drain the "wrote N events" line
    bare = _write_run(tmp_path, "bare")
    assert cli.main(["trace", str(bare)]) == 0
    merged = json.loads(capsys.readouterr().out)
    assert all(e["ph"] == "C" for e in merged["traceEvents"])


def _x(name, ts, dur):
    return {"name": name, "ph": "X", "ts": float(ts), "dur": float(dur),
            "pid": 1, "tid": 0}


def test_cli_trace_overlap(tmp_path, capsys):
    """`trace RUN --overlap` — the streaming-exchange CI gate. A run whose
    exchange/bucket/* spans sit inside train/forward_backward passes; a
    barrier-shaped run (buckets dispatched after backward) exits 1; runs
    without the span structure are data errors (exit 2)."""
    # streaming shape: every bucket dispatch inside the fwd+bwd interval
    streaming = [
        _x("train/forward_backward", 0, 1000),
        _x("exchange/bucket/emb", 100, 100),
        _x("exchange/bucket/bucket0", 300, 150),
        _x("exchange/bucket/bucket1", 600, 100),
        _x("train/apply_updates", 1010, 50),
    ]
    run = _write_run(tmp_path, "stream", trace_events=streaming)
    assert cli.main(["trace", str(run), "--overlap"]) == 0
    out = capsys.readouterr().out
    assert "fraction 1.000" in out and "ok" in out
    # barrier shape: buckets fire after forward_backward ends -> fraction 0
    barrier = [
        _x("train/forward_backward", 0, 1000),
        _x("exchange/bucket/emb", 1100, 100),
        _x("exchange/bucket/bucket0", 1250, 150),
    ]
    run_b = _write_run(tmp_path, "barrier", trace_events=barrier)
    assert cli.main(["trace", str(run_b), "--overlap"]) == 1
    assert "BELOW THRESHOLD" in capsys.readouterr().out
    # partial overlap straddling the boundary: 50% in -> threshold decides
    partial = [
        _x("train/forward_backward", 0, 1000),
        _x("exchange/bucket/emb", 900, 200),
    ]
    run_p = _write_run(tmp_path, "partial", trace_events=partial)
    assert cli.main(
        ["trace", str(run_p), "--overlap", "--overlap-threshold", "0.4"]
    ) == 0
    capsys.readouterr()
    assert cli.main(
        ["trace", str(run_p), "--overlap", "--overlap-threshold", "0.6"]
    ) == 1
    capsys.readouterr()
    # multi-step attribution: each bucket scored against ITS step's window
    two_step = [
        _x("train/forward_backward", 0, 1000),
        _x("exchange/bucket/emb", 500, 100),     # step 0, inside
        _x("train/forward_backward", 2000, 1000),
        _x("exchange/bucket/emb", 3200, 100),    # step 1, after bwd
    ]
    run_2 = _write_run(tmp_path, "two", trace_events=two_step)
    assert cli.main(
        ["trace", str(run_2), "--overlap", "--overlap-threshold", "0.4"]
    ) == 0
    assert "step 0" in capsys.readouterr().out
    # no forward_backward spans / no bucket spans / no trace: data errors
    no_fb = _write_run(tmp_path, "nofb",
                       trace_events=[_x("exchange/bucket/emb", 0, 10)])
    assert cli.main(["trace", str(no_fb), "--overlap"]) == 2
    no_bk = _write_run(tmp_path, "nobk",
                       trace_events=[_x("train/forward_backward", 0, 10)])
    assert cli.main(["trace", str(no_bk), "--overlap"]) == 2
    bare = _write_run(tmp_path, "notrace")
    assert cli.main(["trace", str(bare), "--overlap"]) == 2


def test_cli_telemetry_off_notice(tmp_path, capsys):
    """summary/trace on a telemetry-off run dir print a clean notice
    instead of partial or KeyError-prone output, and still exit 0."""
    off = _write_run(tmp_path, "off")  # no telemetry dict, no trace.json
    assert cli.main(["summary", str(off)]) == 0
    assert "telemetry: was off" in capsys.readouterr().out
    assert cli.main(["summary", str(off), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep.get("telemetry_off") is True
    # a run WITH device accumulators gets no notice and no flag
    on = _write_run(tmp_path, "on", telemetry={"steps": 5.0})
    assert cli.main(["summary", str(on)]) == 0
    assert "was off" not in capsys.readouterr().out
    assert cli.main(["summary", str(on), "--json"]) == 0
    assert "telemetry_off" not in json.loads(capsys.readouterr().out)
    # trace on a run with neither trace.json nor metrics: notice, exit 0
    empty = tmp_path / "empty"
    empty.mkdir()
    (empty / "config.json").write_text(
        json.dumps({"name": "empty", "tags": [], "config": {}})
    )
    assert cli.main(["trace", str(empty)]) == 0
    assert "telemetry was off" in capsys.readouterr().out


def _write_decisions(run, decs):
    with open(run / "decisions.jsonl", "w") as f:
        for d in decs:
            f.write(json.dumps(d, sort_keys=True) + "\n")


def _decision(step, *, switched, old_index, new_index, old_ratio, new_ratio,
              trigger, rationale, window_steps=5):
    return dict(
        step=step, window_steps=window_steps, trigger=trigger,
        rationale=rationale, switched=switched, old_index=old_index,
        new_index=new_index, old_ratio=old_ratio, new_ratio=new_ratio,
        old_fpr=None, new_fpr=None, err_cos=0.5, saturated_per_step=0.0,
        rel_volume=old_ratio,
    )


def test_cli_ctrl_summary_trace_compare(tmp_path, capsys):
    """The controller's decision trail surfaces in all three subcommands:
    summary rows, Perfetto counter/instant events, and the adaptive-vs-
    fixed matched-loss wire comparison."""
    adaptive = _write_run(tmp_path, "adaptive", n=12,
                          telemetry={"steps": 12.0})
    # cheaper rung after the switch at step 5: rel_volume 0.08 -> 0.03
    with open(adaptive / "metrics.jsonl", "w") as f:
        for i in range(12):
            f.write(json.dumps(
                {"step": i, "ts": 1000.0 + i * 0.1, "loss": 2.0 - 0.1 * i,
                 "rel_volume": 0.08 if i < 6 else 0.03}) + "\n")
    _write_decisions(adaptive, [
        _decision(5, switched=True, old_index=2, new_index=1,
                  old_ratio=0.08, new_ratio=0.03,
                  trigger="err_cos_headroom", rationale="move_down"),
        _decision(10, switched=False, old_index=1, new_index=1,
                  old_ratio=0.03, new_ratio=0.03,
                  trigger="in_band", rationale="hold_in_band"),
    ])

    assert cli.main(["summary", str(adaptive)]) == 0
    out = capsys.readouterr().out
    assert "ctrl_switches_per_step" in out and "effective_ratio" in out
    assert cli.main(["summary", str(adaptive), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ctrl"]["switches"] == 1
    assert rep["ctrl"]["final_index"] == 1
    assert rep["ctrl"]["effective_ratio"] == pytest.approx(
        (5 * 0.08 + 5 * 0.03) / 10
    )

    out_f = tmp_path / "ctrl_trace.json"
    assert cli.main(["trace", str(adaptive), "--out", str(out_f)]) == 0
    ev = json.loads(out_f.read_text())["traceEvents"]
    names = {e["name"] for e in ev}
    assert "ctrl_ladder_index" in names and "ctrl_ratio" in names
    assert any(e["ph"] == "i" and "ctrl switch" in e["name"] for e in ev)

    # fixed baseline: same loss trajectory at flat rel_volume 0.08 — the
    # adaptive run reaches the matched loss on strictly less wire
    fixed = _write_run(tmp_path, "fixed", n=12)
    capsys.readouterr()
    assert cli.main(["compare", str(adaptive), str(fixed), "--ctrl"]) == 0
    assert "less wire" in capsys.readouterr().out
    # flipped roles: the expensive run is flagged
    assert cli.main(["compare", str(fixed), str(adaptive), "--ctrl"]) == 1
    assert "REGRESSION" in capsys.readouterr().out


# ---------------------------------------------------------------------- #
# satellites: metrics.timed and tracking._jsonable
# ---------------------------------------------------------------------- #


def test_timed_sink_records_silently(capsys):
    sink = {}
    with metrics.timed("enc", sink=sink):
        pass
    with metrics.timed("enc", sink=sink):
        pass
    assert sink["enc"] > 0.0
    assert capsys.readouterr().out == ""  # sink means no console spam


def test_timed_records_on_raise(capsys):
    sink = {}
    with pytest.raises(ValueError):
        with metrics.timed("boom", sink=sink):
            raise ValueError
    assert sink["boom"] > 0.0
    with pytest.raises(ValueError):
        with metrics.timed("loud"):
            raise ValueError
    assert "loud time:" in capsys.readouterr().out


def test_timed_print_only_when_enabled(capsys):
    with metrics.timed("quiet", enabled=False):
        pass
    assert capsys.readouterr().out == ""
    with metrics.timed("loud"):
        pass
    assert "loud time:" in capsys.readouterr().out


def test_jsonable_maps_nonfinite_to_null():
    rec = tracking._jsonable(
        {"a": float("nan"), "b": float("inf"), "c": -float("inf"),
         "d": np.float32("nan"), "e": jnp.asarray(float("nan")),
         "f": 1.5, "g": [float("nan"), 2]}
    )
    assert rec == {"a": None, "b": None, "c": None, "d": None, "e": None,
                   "f": 1.5, "g": [None, 2]}
    # and the emitted line is strict JSON (bare NaN would blow up here)
    json.loads(json.dumps(rec, allow_nan=False))


def test_run_log_emits_strict_json(tmp_path):
    run = tracking.Run(str(tmp_path), name="strict")
    run.log({"loss": float("nan"), "ok": 1.0}, step=0)
    run.finish({"last": float("inf")})
    lines = (tmp_path / "strict" / "metrics.jsonl").read_text().splitlines()
    rec = json.loads(lines[0])  # parses strictly
    assert rec["loss"] is None and rec["ok"] == 1.0
    assert json.loads((tmp_path / "strict" / "summary.json").read_text())["last"] is None


# ---------------------------------------------------------------------- #
# SLO health plane CLI surface (r23)
# ---------------------------------------------------------------------- #


def test_dist_percentiles_pinned():
    """Sorted linear-interpolation quantiles, pinned on a fixed list —
    p95/p99 interpolate between order statistics instead of snapping."""
    d = cli._dist([float(v) for v in range(1, 11)])
    assert d["n"] == 10 and d["min"] == 1.0 and d["max"] == 10.0
    assert d["mean"] == pytest.approx(5.5)
    assert d["p50"] == pytest.approx(5.5)
    assert d["p90"] == pytest.approx(9.1)
    assert d["p95"] == pytest.approx(9.55)
    assert d["p99"] == pytest.approx(9.91)
    assert cli._dist([7.0]) == {
        "n": 1, "mean": 7.0, "p50": 7.0, "p90": 7.0, "p95": 7.0,
        "p99": 7.0, "min": 7.0, "max": 7.0,
    }
    assert cli._dist([]) == {"n": 0}
    # the human row renders the new tails
    line = cli._fmt_dist(d)
    assert "p95 9.55" in line and "p99 9.91" in line


def test_mt_fedsim_rows_tolerate_ragged_tenant_rows():
    """Regression: a run dir mixing tenant geometries logs `*_t` rows of
    different lengths; slot stats must skip the short rows instead of
    raising IndexError."""
    hist = [
        {"ts": 1000.0, "clients_t": [4.0, 6.0],
         "staleness_mean_t": [1.0, 2.0], "staleness_max_t": [1.0, 2.0],
         "staleness_hist_t": [[4.0, 0.0], [0.0, 6.0]],
         "buffer_fill_t": [3.0, 5.0], "applied_t": [1.0, 1.0]},
        # ragged: a single-tenant record in the same dir
        {"ts": 1000.5, "clients_t": [4.0],
         "staleness_mean_t": [3.0], "staleness_max_t": [5.0],
         "staleness_hist_t": [[4.0]],
         "buffer_fill_t": [7.0], "applied_t": [1.0]},
        {"ts": 1001.0, "clients_t": [2.0, 8.0],
         "staleness_mean_t": [1.0, 0.0], "staleness_max_t": [2.0, 1.0]},
    ]
    out = cli._mt_fedsim_rows(hist)
    assert out["fed_tenants"] == 2
    # slot means/maxes only over the rows that carry the slot
    assert out["fed_mt_staleness_mean"][0] == pytest.approx(5.0 / 3)
    assert out["fed_mt_staleness_mean"][1] == pytest.approx(1.0)
    assert out["fed_mt_staleness_max"] == [5.0, 2.0]
    # per-tenant tails from the summed [T, D] histogram rows
    assert out["fed_mt_staleness_p95"] == [0.0, 1.0]
    assert out["fed_mt_buffer_fill_per_apply"][0] == pytest.approx(5.0)


def test_fedsim_report_staleness_tail_from_histogram():
    hist = [
        {"clients": 8, "uplink_bytes": 100.0, "downlink_bytes": 10.0,
         "staleness_hist": [5.0, 2.0, 1.0]}
        for _ in range(3)
    ]
    rep = cli._fedsim_report(hist)
    assert rep["fed_staleness_hist_total"] == [15.0, 6.0, 3.0]
    assert rep["fed_staleness_p50"] == 0.0
    assert rep["fed_staleness_p95"] == 2.0
    assert rep["fed_staleness_p99"] == 2.0


def _write_fed_run(root, name, *, rows):
    d = root / name
    d.mkdir(parents=True)
    (d / "config.json").write_text(
        json.dumps({"name": name, "tags": [], "config": {}})
    )
    with open(d / "metrics.jsonl", "w") as f:
        for rec in rows:
            f.write(json.dumps(rec) + "\n")
    (d / "summary.json").write_text(json.dumps({}))
    return d


def _fed_rows(n=6, clients=8, hist=(5.0, 2.0, 1.0)):
    return [
        {"round": i, "ts": 1000.0 + 0.1 * i, "clients": clients,
         "checksum_failures": 0.0, "buffer_fill": 10.0, "w_rel_err": 0.5,
         "staleness_hist": list(hist)}
        for i in range(n)
    ]


def test_cli_slo_verdict_and_exit_gate(tmp_path, capsys):
    run = _write_fed_run(tmp_path, "fed", rows=_fed_rows())
    ok_spec = tmp_path / "ok.json"
    ok_spec.write_text(json.dumps({
        "window_ticks": 2, "hysteresis_ticks": 2,
        "targets": {"min_clients_per_round": 1.0,
                    "staleness_p95_max": 3.0},
    }))
    assert cli.main(["slo", str(run), "--spec", str(ok_spec)]) == 0
    out = capsys.readouterr().out
    assert "0 health transitions" in out and "tenant 0: OK" in out
    assert "staleness_p95_max: 2 vs 3  ok" in out

    # p95 of [5,2,1] is level 2 > the 0.5 ceiling: DEGRADED at tick 0,
    # BREACH at tick 1, and the command exit-gates on it
    breach_spec = tmp_path / "breach.json"
    breach_spec.write_text(json.dumps({
        "window_ticks": 1, "fast_window_ticks": 1, "slow_window_ticks": 1,
        "hysteresis_ticks": 1,
        "targets": {"staleness_p95_max": 0.5},
    }))
    assert cli.main(["slo", str(run), "--spec", str(breach_spec)]) == 1
    cap = capsys.readouterr()
    assert "OK -> DEGRADED" in cap.out and "DEGRADED -> BREACH" in cap.out
    assert "BREACH" in cap.err

    # --json carries events + verdicts and still gates
    assert cli.main(
        ["slo", str(run), "--spec", str(breach_spec), "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["verdicts"][0]["state"] == "BREACH"
    assert [e["to_state"] for e in rep["events"]] == ["DEGRADED", "BREACH"]


def test_cli_slo_degenerate_and_error_paths(tmp_path, capsys):
    run = _write_fed_run(tmp_path, "fed", rows=_fed_rows())
    noop = tmp_path / "noop.json"
    noop.write_text(json.dumps({"window_ticks": 4}))
    assert cli.main(["slo", str(run), "--spec", str(noop)]) == 0
    assert "no-op" in capsys.readouterr().out
    # malformed spec and non-fed run dirs are data errors (exit 2)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"targets": {"bogus": 1.0}}))
    assert cli.main(["slo", str(run), "--spec", str(bad)]) == 2
    plain = _write_run(tmp_path, "plain")
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps({"targets": {"min_clients_per_round": 1.0}}))
    assert cli.main(["slo", str(plain), "--spec", str(ok)]) == 2


def test_cli_slo_multi_tenant_overrides(tmp_path, capsys):
    rows = [
        {"round": i, "ts": 1000.0 + 0.1 * i, "clients_t": [8.0, 8.0],
         "checksum_failures_t": [0.0, 0.0], "buffer_fill_t": [1.0, 1.0],
         "w_rel_err_t": [0.1, 0.1],
         "staleness_hist_t": [[8.0, 0.0, 0.0], [5.0, 2.0, 1.0]]}
        for i in range(6)
    ]
    run = _write_fed_run(tmp_path, "mt", rows=rows)
    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({
        "window_ticks": 1, "fast_window_ticks": 1, "slow_window_ticks": 1,
        "hysteresis_ticks": 1,
        "targets": {"staleness_p95_max": 3.0},
        "tenants": {"1": {"staleness_p95_max": 0.5}},
    }))
    # tenant 0 under the global ceiling, tenant 1 breaches its override
    assert cli.main(["slo", str(run), "--spec", str(spec)]) == 1
    out = capsys.readouterr().out
    assert "tenant 0: OK" in out and "tenant 1: BREACH" in out


def test_cli_bench_history_shapes_and_gate(tmp_path, capsys):
    (tmp_path / "BENCH_MODERN_r07.json").write_text(json.dumps({
        "metric": "t_round_s", "value": 0.25, "unit": "s",
        "platform": "cpu",
        "provenance": {"modeled": ["t_round_s"], "measured": ["clients"]},
        "profile_sha256": "abcdef0123456789",
    }))
    (tmp_path / "BENCH_RAW_r02.json").write_text(json.dumps({
        "cmd": "python bench.py", "rc": 0, "n": 8,
        "parsed": {"metric": "img_s", "value": 120.0, "unit": "img/s"},
        "platform": "cpu",
    }))
    (tmp_path / "BENCH_HEADLINE_r03.json").write_text(json.dumps({
        "headline": {"metric": "t_step_s", "value": 0.5, "unit": "s"},
        "platform": "tpu",
    }))
    assert cli.main(["bench-history", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "3 record(s)" in out
    # ordered by round parsed from the filename
    assert out.index("r02") < out.index("r03") < out.index("r07")
    assert "modeled+measured" in out and "legacy" in out
    assert "profile:abcdef012345" in out

    assert cli.main(["bench-history", str(tmp_path), "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert [r["round"] for r in rows] == [2, 3, 7]
    assert rows[2]["provenance"] == "modeled+measured"
    assert rows[0]["provenance"] == "legacy"

    # a schema-less record poisons the ledger: exit 2
    (tmp_path / "BENCH_JUNK_r99.json").write_text(json.dumps({"oops": 1}))
    assert cli.main(["bench-history", str(tmp_path)]) == 2
    (tmp_path / "BENCH_JUNK_r99.json").unlink()
    # an empty dir is a data error too
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli.main(["bench-history", str(empty)]) == 2


def test_cli_bench_history_committed_ledger(capsys):
    """Every committed BENCH_*.json record must parse under one of the
    three ledger shapes — the repo's own history is the fixture."""
    import pathlib

    root = pathlib.Path(cli.__file__).resolve().parents[2]
    assert (root / "Makefile").exists()
    assert cli.main(["bench-history", str(root)]) == 0
    out = capsys.readouterr().out
    assert "record(s)" in out
