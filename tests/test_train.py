"""End-to-end training smoke tests: compressed DP training must learn, and
must track the dense baseline — the reference's convergence-test strategy
(SURVEY.md §4.1) shrunk to a synthetic task on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


import flax.linen as nn

from conftest import shared_mesh
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.train import Trainer


class TinyMLP(nn.Module):
    num_classes: int = 4

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(64)(x))
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(self.num_classes)(x)


def _data(n=512, dim=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(dim, classes))
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(n, classes)), axis=1).astype(np.int32)
    return x, y


def _fit(cfg, steps=30, batch=64, lr=0.1, seed=0):
    mesh = shared_mesh(4)
    model = TinyMLP()
    trainer = Trainer(model, cfg, optax.sgd(lr), mesh)
    x, y = _data(seed=seed)
    state = trainer.init_state(jax.random.PRNGKey(0), (x[:batch], y[:batch]))
    losses = []
    key = jax.random.PRNGKey(1)
    for i in range(steps):
        lo = (i * batch) % (len(x) - batch)
        kb = jax.random.fold_in(key, i)
        state, loss, wire = trainer.step(state, (x[lo : lo + batch], y[lo : lo + batch]), kb)
        losses.append(float(loss))
    return losses, state, wire


def test_dense_baseline_learns():
    cfg = DeepReduceConfig(communicator="allreduce", memory="none", deepreduce=None, compressor="none")
    # 60 steps: the 4-worker SGD run crosses the 0.6 ratio around step 40
    # on this fixture (0.61 at 30, 0.48 at 60) — give the strict threshold
    # a real margin instead of loosening it
    losses, _, wire = _fit(cfg, steps=60)
    assert losses[-1] < 0.6 * losses[0]
    assert float(wire.rel_volume()) == pytest.approx(1.0)


def test_topk_residual_learns():
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.05, memory="residual")
    losses, state, wire = _fit(cfg)
    assert losses[-1] < 0.7 * losses[0]
    assert state.residuals is not None


def test_deepreduce_both_learns():
    cfg = DeepReduceConfig(
        deepreduce="both",
        index="bloom",
        value="qsgd",
        compress_ratio=0.05,
        fpr=0.01,
        memory="residual",
        min_compress_size=100,
    )
    losses, state, wire = _fit(cfg)
    assert losses[-1] < 0.8 * losses[0]
    # compression actually engaged on the big layers
    assert float(wire.rel_volume()) < 0.2


def test_step_donates_state_buffers():
    """The jitted step donates its carries (params/opt_state inside the
    state, and the worker-local residuals) so XLA updates them in place —
    after a step, the PRIOR state's donated buffers must be consumed
    (`is_deleted`), and the returned state's buffers must be live."""
    cfg = DeepReduceConfig(
        deepreduce="index", index="bloom", compress_ratio=0.05, fpr=0.01,
        bloom_blocked="mod", policy="p0", memory="residual",
        min_compress_size=100,
    )
    mesh = shared_mesh(4)
    trainer = Trainer(TinyMLP(), cfg, optax.sgd(0.1), mesh)
    x, y = _data()
    batch = (x[:64], y[:64])
    state0 = trainer.init_state(jax.random.PRNGKey(0), batch)
    state1, _, _ = trainer.step(state0, batch, jax.random.PRNGKey(1))
    state2, _, _ = trainer.step(state1, (x[64:128], y[64:128]), jax.random.PRNGKey(2))
    donated = (
        jax.tree_util.tree_leaves(state1.params)
        + jax.tree_util.tree_leaves(state1.opt_state)
        + jax.tree_util.tree_leaves(state1.residuals)
    )
    assert donated and all(leaf.is_deleted() for leaf in donated)
    live = jax.tree_util.tree_leaves(state2.params) + jax.tree_util.tree_leaves(
        state2.residuals
    )
    assert live and not any(leaf.is_deleted() for leaf in live)


def test_compressed_matches_dense_trajectory_loosely():
    dense_cfg = DeepReduceConfig(communicator="allreduce", memory="none", deepreduce=None, compressor="none")
    comp_cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.25, memory="residual")
    dense_losses, _, _ = _fit(dense_cfg, steps=25)
    comp_losses, _, _ = _fit(comp_cfg, steps=25)
    # error feedback keeps compressed training within striking distance
    assert comp_losses[-1] < 1.5 * dense_losses[-1] + 0.1
