"""Two-level ICI x DCN exchange: dense within slice, compressed across
slices, on a (2 x 4) virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.parallel import HierarchicalExchanger, make_hybrid_mesh

N_SLICES, PER_SLICE = 2, 4
D = 4096


def _grads():
    rng = np.random.default_rng(0)
    # per-device distinct gradients, leading axis = 8 devices
    return jnp.asarray(rng.normal(size=(N_SLICES * PER_SLICE, D)).astype(np.float32))


def _run(cfg, grads):
    mesh = make_hybrid_mesh(N_SLICES, PER_SLICE)
    hx = HierarchicalExchanger({"w": jnp.zeros((D,))}, cfg)
    state0 = hx.init_state({"w": jnp.zeros((D,))})

    def spmd(g):
        g = g.reshape(D)  # one device's gradient
        agg, _, wire = hx.exchange(
            {"w": g}, state0, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(7)
        )
        return agg["w"], wire

    fn = jax.jit(
        shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(("dcn", "ici")),),
            out_specs=(P(("dcn", "ici")), P()),
            check_vma=False,
        )
    )
    out, wire = fn(grads)
    return np.asarray(out).reshape(N_SLICES * PER_SLICE, D), wire


def test_dense_hierarchical_is_exact_global_mean():
    cfg = DeepReduceConfig(
        compressor="none", deepreduce=None, memory="none", communicator="allreduce"
    )
    grads = _grads()
    out, _ = _run(cfg, grads)
    want = np.asarray(grads).mean(axis=0)
    for row in out:
        np.testing.assert_allclose(row, want, rtol=1e-5, atol=1e-6)


def test_compressed_all_devices_agree_and_approximate_mean():
    # p0: every filter-positive is transmitted (with its true value, FP-aware),
    # so no true-top-k coordinate is ever displaced — exactness holds below
    cfg = DeepReduceConfig(
        compressor="topk",
        compress_ratio=0.25,
        deepreduce="index",
        index="bloom",
        policy="p0",
        fpr=0.01,
        memory="none",
        min_compress_size=64,
    )
    grads = _grads()
    out, wire = _run(cfg, grads)
    # every device (incl. ICI replicas of each DCN group) agrees bit-for-bit
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])
    # sharp value property: a coordinate in BOTH slices' top-k sets is
    # transmitted exactly by both (no bloom false negatives; FP-aware re-read
    # sends true values), so the aggregate there equals the global mean
    g = np.asarray(grads)
    slice_means = g.reshape(N_SLICES, PER_SLICE, D).mean(axis=1)
    k = int(D * cfg.compress_ratio)
    tops = [set(np.argsort(-np.abs(m))[:k]) for m in slice_means]
    both = np.array(sorted(tops[0] & tops[1]))
    assert len(both) > 0
    want = g.mean(axis=0)
    np.testing.assert_allclose(out[0][both], want[both], rtol=1e-4, atol=1e-5)
    # wire accounting counts the DCN link only: n_slices payloads, not 8
    assert 0 < float(wire.rel_volume()) < 1.0


def test_payload_bytes_counts_dcn_only():
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.1, deepreduce="index", index="integer",
        memory="none", min_compress_size=64,
    )
    hx = HierarchicalExchanger({"w": jnp.zeros((D,))}, cfg)
    nbytes = hx.payload_bytes({"w": jnp.zeros((D,))})
    assert 0 < nbytes < D * 4  # compressed payload smaller than the dense tensor


@pytest.mark.parametrize("key_style", ["raw", "typed"])
def test_folded_key_repaired_across_ici_replicas(key_style):
    """The class contract is enforced by construction: even a caller that
    (wrongly) folds the ici position into the key gets bit-identical
    encodes across ICI replicas — replica 0's key is broadcast. Covers
    both raw uint32 PRNGKey arrays and new-style typed keys."""
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.25, deepreduce="value",
        value="qsgd",  # stochastic: desync would show immediately
        memory="none", min_compress_size=64,
    )
    mesh = make_hybrid_mesh(N_SLICES, PER_SLICE)
    hx = HierarchicalExchanger({"w": jnp.zeros((D,))}, cfg)
    state0 = hx.init_state({"w": jnp.zeros((D,))})

    def spmd(g):
        g = g.reshape(D)
        base = jax.random.PRNGKey(7) if key_style == "raw" else jax.random.key(7)
        bad_key = jax.random.fold_in(  # violates the contract on purpose
            base, jax.lax.axis_index("ici")
        )
        agg, _, _ = hx.exchange(
            {"w": g}, state0, step=jnp.zeros((), jnp.int32), key=bad_key
        )
        return agg["w"]

    fn = jax.jit(
        shard_map(
            spmd, mesh=mesh,
            in_specs=(P(("dcn", "ici")),),
            out_specs=P(("dcn", "ici")),
            check_vma=False,
        )
    )
    out = np.asarray(fn(_grads())).reshape(N_SLICES * PER_SLICE, D)
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])
