"""Two-level ICI x DCN exchange: dense within slice, compressed across
slices, on a (2 x 4) virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.parallel import HierarchicalExchanger, make_hybrid_mesh

N_SLICES, PER_SLICE = 2, 4
D = 4096


def _grads():
    rng = np.random.default_rng(0)
    # per-device distinct gradients, leading axis = 8 devices
    return jnp.asarray(rng.normal(size=(N_SLICES * PER_SLICE, D)).astype(np.float32))


def _run(cfg, grads):
    mesh = make_hybrid_mesh(N_SLICES, PER_SLICE)
    hx = HierarchicalExchanger(
        {"w": jnp.zeros((D,))}, cfg, num_slices=N_SLICES, per_slice=PER_SLICE
    )
    state0 = hx.init_state({"w": jnp.zeros((D,))})

    def spmd(g):
        g = g.reshape(D)  # one device's gradient
        agg, _, wire = hx.exchange(
            {"w": g}, state0, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(7)
        )
        return agg["w"], wire

    fn = jax.jit(
        shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(("dcn", "ici")),),
            out_specs=(P(("dcn", "ici")), P()),
            check_vma=False,
        )
    )
    out, wire = fn(grads)
    return np.asarray(out).reshape(N_SLICES * PER_SLICE, D), wire


def test_dense_hierarchical_is_exact_global_mean():
    cfg = DeepReduceConfig(
        compressor="none", deepreduce=None, memory="none", communicator="allreduce"
    )
    grads = _grads()
    out, _ = _run(cfg, grads)
    want = np.asarray(grads).mean(axis=0)
    for row in out:
        np.testing.assert_allclose(row, want, rtol=1e-5, atol=1e-6)


def test_compressed_all_devices_agree_and_approximate_mean():
    # p0: every filter-positive is transmitted (with its true value, FP-aware),
    # so no true-top-k coordinate is ever displaced — exactness holds below
    cfg = DeepReduceConfig(
        compressor="topk",
        compress_ratio=0.25,
        deepreduce="index",
        index="bloom",
        policy="p0",
        fpr=0.01,
        memory="none",
        min_compress_size=64,
    )
    grads = _grads()
    out, wire = _run(cfg, grads)
    # every device (incl. ICI replicas of each DCN group) agrees bit-for-bit
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])
    # sharp value property: a coordinate in BOTH slices' top-k sets is
    # transmitted exactly by both (no bloom false negatives; FP-aware re-read
    # sends true values), so the aggregate there equals the global mean
    g = np.asarray(grads)
    slice_means = g.reshape(N_SLICES, PER_SLICE, D).mean(axis=1)
    k = int(D * cfg.compress_ratio)
    tops = [set(np.argsort(-np.abs(m))[:k]) for m in slice_means]
    both = np.array(sorted(tops[0] & tops[1]))
    assert len(both) > 0
    want = g.mean(axis=0)
    np.testing.assert_allclose(out[0][both], want[both], rtol=1e-4, atol=1e-5)
    # wire accounting counts the DCN link only: n_slices payloads, not 8
    assert 0 < float(wire.rel_volume()) < 1.0


def test_payload_bytes_counts_dcn_only():
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.1, deepreduce="index", index="integer",
        memory="none", min_compress_size=64,
    )
    hx = HierarchicalExchanger({"w": jnp.zeros((D,))}, cfg)
    nbytes = hx.payload_bytes({"w": jnp.zeros((D,))})
    assert 0 < nbytes < D * 4  # compressed payload smaller than the dense tensor


@pytest.mark.parametrize("key_style", ["raw", "typed"])
def test_folded_key_repaired_across_ici_replicas(key_style):
    """The class contract is enforced by construction: even a caller that
    (wrongly) folds the ici position into the key gets bit-identical
    encodes across ICI replicas — replica 0's key is broadcast. Covers
    both raw uint32 PRNGKey arrays and new-style typed keys."""
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.25, deepreduce="value",
        value="qsgd",  # stochastic: desync would show immediately
        memory="none", min_compress_size=64,
    )
    mesh = make_hybrid_mesh(N_SLICES, PER_SLICE)
    hx = HierarchicalExchanger({"w": jnp.zeros((D,))}, cfg)
    state0 = hx.init_state({"w": jnp.zeros((D,))})

    def spmd(g):
        g = g.reshape(D)
        base = jax.random.PRNGKey(7) if key_style == "raw" else jax.random.key(7)
        bad_key = jax.random.fold_in(  # violates the contract on purpose
            base, jax.lax.axis_index("ici")
        )
        agg, _, _ = hx.exchange(
            {"w": g}, state0, step=jnp.zeros((), jnp.int32), key=bad_key
        )
        return agg["w"]

    fn = jax.jit(
        shard_map(
            spmd, mesh=mesh,
            in_specs=(P(("dcn", "ici")),),
            out_specs=P(("dcn", "ici")),
            check_vma=False,
        )
    )
    out = np.asarray(fn(_grads())).reshape(N_SLICES * PER_SLICE, D)
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])


# ---------------------------------------------------------------------- #
# flat equivalence: per_slice=1 degenerates to the flat exchange, bitwise
# ---------------------------------------------------------------------- #


def _run_flat(cfg, grads, like):
    """The same exchange over a flat 8-way mesh via GradientExchanger."""
    from jax.sharding import Mesh

    from deepreduce_tpu.comm import GradientExchanger

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    ex = GradientExchanger(like, cfg, axis_name="data", num_workers=8)
    tmap = jax.tree_util.tree_map

    def spmd(g):
        g0 = tmap(lambda x: x.reshape(x.shape[1:]), g)
        agg, _, _ = ex.exchange(
            g0, None, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(7)
        )
        return tmap(lambda x: x[None], agg)

    fn = jax.jit(
        shard_map(spmd, mesh=mesh, in_specs=(P("data"),),
                  out_specs=P("data"), check_vma=False)
    )
    return fn(grads)


def _run_hier_degenerate(cfg, grads, like):
    """The same exchange as a per_slice=1 hierarchy: 8 slices of 1 device."""
    mesh = make_hybrid_mesh(8, 1)
    hx = HierarchicalExchanger(like, cfg, num_slices=8, per_slice=1)
    tmap = jax.tree_util.tree_map

    def spmd(g):
        g0 = tmap(lambda x: x.reshape(x.shape[1:]), g)
        agg, _, _ = hx.exchange(
            g0, None, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(7)
        )
        return tmap(lambda x: x[None], agg)

    fn = jax.jit(
        shard_map(spmd, mesh=mesh, in_specs=(P(("dcn", "ici")),),
                  out_specs=P(("dcn", "ici")), check_vma=False)
    )
    return fn(grads)


@pytest.mark.parametrize(
    "name,extra",
    [
        ("loop", dict(decode_strategy="loop")),
        ("vmap", dict(decode_strategy="vmap", decode_batch=4)),
        # stochastic value codec: any key divergence between the two paths
        # would break bitwise equality immediately
        ("qsgd", dict(deepreduce="value", value="qsgd")),
    ],
)
def test_flat_equivalence_per_slice_one(name, extra):
    """A per_slice=1 hierarchy IS the flat exchange: the ici psum averages
    one device (exact), the key repair broadcasts over a singleton axis
    (identity), and the dcn leg is the flat communicator verbatim — so the
    outputs must agree BITWISE, including under a stochastic codec."""
    base = dict(
        compressor="topk", compress_ratio=0.25, deepreduce="index",
        index="bloom", policy="p0", fpr=0.01, memory="none",
        min_compress_size=64,
    )
    if "deepreduce" in extra:
        base = dict(compressor="topk", compress_ratio=0.25, memory="none",
                    min_compress_size=64)
    flat_cfg = DeepReduceConfig(**base, **extra)
    hier_cfg = DeepReduceConfig(**base, **extra, hier=True)
    grads = {"w": _grads()}
    like = {"w": jnp.zeros((D,))}
    flat = _run_flat(flat_cfg, grads, like)
    hier = _run_hier_degenerate(hier_cfg, grads, like)
    np.testing.assert_array_equal(np.asarray(flat["w"]), np.asarray(hier["w"]))


def test_flat_equivalence_bucketed():
    """Same degenerate-hierarchy contract on the bucketed exchange: the
    multi-leaf FFD-partitioned payload path must also be bitwise equal."""
    leaves = {"emb": 3000, "w1": 900, "b1": 300}
    base = dict(
        compressor="topk", compress_ratio=0.25, deepreduce="index",
        index="bloom", policy="p0", fpr=0.01, memory="none",
        min_compress_size=64, bucket_bytes=4800,
    )
    flat_cfg = DeepReduceConfig(**base)
    hier_cfg = DeepReduceConfig(**base, hier=True)
    rng = np.random.default_rng(1)
    grads = {
        n: jnp.asarray(rng.normal(size=(8, sz)).astype(np.float32))
        for n, sz in leaves.items()
    }
    like = {n: jnp.zeros((sz,)) for n, sz in leaves.items()}
    flat = _run_flat(flat_cfg, grads, like)
    hier = _run_hier_degenerate(hier_cfg, grads, like)
    for n in leaves:
        np.testing.assert_array_equal(np.asarray(flat[n]), np.asarray(hier[n]))


# ---------------------------------------------------------------------- #
# the composed legs on the (2, 4) mesh
# ---------------------------------------------------------------------- #


def test_qar_ici_leg_agrees_and_approximates_mean():
    """int8 quantized slice reduction + dense DCN allreduce: all 8 devices
    agree bitwise and land within quantization error of the global mean."""
    cfg = DeepReduceConfig(
        compressor="none", deepreduce=None, memory="none",
        communicator="allreduce", hier=True, hier_ici="qar",
    )
    grads = _grads()
    out, wire = _run(cfg, grads)
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])
    want = np.asarray(grads).mean(axis=0)
    # two int8 phases over buckets of |max| <= ~4 sigma: generous bound
    assert float(np.abs(out[0] - want).max()) < 0.2
    assert float(np.asarray(wire.ici_bits)) > 0.0


def test_bucketed_dcn_leg_on_two_axis_mesh():
    """bucket_bytes routes the DCN leg through BucketedExchanger under the
    hierarchy: all devices agree, and the DCN payload stays compressed."""
    leaves = {"emb": 3000, "w1": 900, "b1": 300}
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.25, deepreduce="index",
        index="bloom", policy="p0", fpr=0.01, memory="none",
        min_compress_size=64, bucket_bytes=4800, hier=True,
    )
    mesh = make_hybrid_mesh(N_SLICES, PER_SLICE)
    rng = np.random.default_rng(2)
    grads = {
        n: jnp.asarray(rng.normal(size=(8, sz)).astype(np.float32))
        for n, sz in leaves.items()
    }
    like = {n: jnp.zeros((sz,)) for n, sz in leaves.items()}
    hx = HierarchicalExchanger(like, cfg, num_slices=N_SLICES, per_slice=PER_SLICE)
    tmap = jax.tree_util.tree_map

    def spmd(g):
        g0 = tmap(lambda x: x.reshape(x.shape[1:]), g)
        agg, _, wire = hx.exchange(
            g0, None, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(7)
        )
        return tmap(lambda x: x[None], agg), wire

    fn = jax.jit(
        shard_map(spmd, mesh=mesh, in_specs=(P(("dcn", "ici")),),
                  out_specs=(P(("dcn", "ici")), P()), check_vma=False)
    )
    out, wire = fn(grads)
    for n in leaves:
        rows = np.asarray(out[n])
        for row in rows[1:]:
            np.testing.assert_array_equal(row, rows[0])
    assert 0 < float(wire.rel_volume()) < 1.0
    d_total = sum(leaves.values())
    assert 0 < hx.payload_bytes(like) < d_total * 4


def test_quantized_rs_dcn_leg_on_two_axis_mesh():
    """The in-collective quantized reduce-scatter as the DCN leg: devices
    agree bitwise; ici accounting stays separate from the dcn volume."""
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.25, memory="none",
        deepreduce=None, communicator="sparse_rs", rs_mode="quantized",
        hier=True,
    )
    grads = _grads()
    out, wire = _run(cfg, grads)
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])
    assert np.isfinite(out).all()
    # dense slice psum on ici: 2(p-1)/p * 32d bits per device
    assert float(np.asarray(wire.ici_bits)) > 0.0
    assert 0 < float(wire.rel_volume()) < 1.0


def test_auto_plan_rewrites_inner_route():
    """hier_dcn='auto' at the headline shape rewrites the inner exchanger
    to the planner's pick and exposes the plan."""
    from deepreduce_tpu import costmodel

    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.10, memory="none",
        deepreduce=None, hier=True, hier_ici="auto", hier_dcn="auto",
    )
    d = 4_053_428
    hx = HierarchicalExchanger(
        jax.ShapeDtypeStruct((d,), jnp.float32), cfg,
        num_slices=8, per_slice=4,
    )
    plan = costmodel.select_hier_plan(d, 8, 4, 0.10)
    assert hx.plan["ici"] == plan["ici"] == hx.ici_leg
    assert hx.plan["dcn"] == plan["dcn"]
    if plan["dcn"] in ("fused", "bucketed"):
        assert hx.inner_cfg.communicator == "allgather"
    else:
        assert hx.inner_cfg.communicator == "sparse_rs"
        assert hx.inner_cfg.rs_mode == plan["dcn"]


# ---------------------------------------------------------------------- #
# config validation surface
# ---------------------------------------------------------------------- #


def test_config_rejects_hier_with_ring_decode():
    with pytest.raises(ValueError, match="ring"):
        DeepReduceConfig(
            compressor="topk", compress_ratio=0.1, deepreduce="index",
            index="bloom", memory="residual", decode_strategy="ring",
            hier=True,
        )


def test_config_rejects_hier_with_resilience():
    with pytest.raises(ValueError, match="resilience"):
        DeepReduceConfig(
            compressor="topk", compress_ratio=0.1, memory="residual",
            resilience=True, hier=True,
        )


@pytest.mark.parametrize(
    "kw",
    [dict(ici_size=4), dict(hier_ici="qar"), dict(hier_dcn="auto")],
)
def test_config_rejects_hier_knobs_without_hier(kw):
    with pytest.raises(ValueError, match="hier"):
        DeepReduceConfig(compressor="topk", compress_ratio=0.1, **kw)


def test_config_rejects_bad_hier_enums():
    with pytest.raises(ValueError):
        DeepReduceConfig(hier=True, hier_ici="bogus")
    with pytest.raises(ValueError):
        DeepReduceConfig(hier=True, hier_dcn="bogus")
    with pytest.raises(ValueError):
        DeepReduceConfig(hier=True, ici_size=0)


def test_config_rejects_hier_dcn_auto_with_pinned_codec():
    with pytest.raises(ValueError, match="auto"):
        DeepReduceConfig(
            compressor="topk", compress_ratio=0.1, deepreduce="index",
            index="bloom", hier=True, hier_dcn="auto",
        )


# ---------------------------------------------------------------------- #
# cost model
# ---------------------------------------------------------------------- #


@pytest.mark.parametrize("d,W,block", [(4096, 4, 512), (4_053_428, 4, 512),
                                       (100_000, 8, 256), (77, 2, 512)])
def test_costmodel_qar_wire_mirror(d, W, block):
    """costmodel.qar_wire_bytes_per_worker (jax-free, used by the planner)
    must stay numerically identical to qar.wire_bits_per_worker/8 (the
    traced accounting the exchange adds to WireStats.ici_bits)."""
    from deepreduce_tpu import costmodel, qar

    want = qar.wire_bits_per_worker(d, W, block) / 8.0
    got = costmodel.qar_wire_bytes_per_worker(d, W, block)
    assert got == pytest.approx(want)


def test_select_hier_plan_headline_shape():
    """At the committed BENCH_HIER shape (8 slices x 4, LSTM d, top-10%,
    100 Mbps DCN / 10 Gbps ICI) the planner picks qar+quantized and the
    plan beats every flat compressed arm paying the DCN link 32-wide."""
    from deepreduce_tpu import costmodel as cm

    d, ratio = 4_053_428, 0.10
    plan = cm.select_hier_plan(d, 8, 4, ratio)
    assert (plan["ici"], plan["dcn"]) == ("qar", "quantized")
    assert len(plan["table"]) == len(cm.HIER_ICI_LEGS) * len(cm.HIER_DCN_LEGS)
    best_flat = min(
        cm.rs_step_time(m, d, 32, ratio)
        for m in ("sparse", "adaptive", "quantized", "sketch")
    )
    assert plan["modeled_step_s"] < best_flat
    # per_slice=1 degenerates: the ici leg costs nothing, any ici choice ties
    p1 = cm.select_hier_plan(d, 8, 1, ratio)
    assert p1["table"]["dense+quantized"] == p1["table"]["qar+quantized"]
