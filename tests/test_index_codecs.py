"""Lossless index codecs: RLE, integer delta-pack, huffman — exact round
trips (SURVEY.md §4: property tests the reference lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu import sparse
from deepreduce_tpu.codecs import huffman, integer, rle


def _sp(d=20000, ratio=0.01, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.normal(size=d).astype(np.float32)
    return g, sparse.topk(jnp.asarray(g), ratio)


def _sp_clustered(d=20000, k=200, seed=1):
    """Clustered indices — RLE's favourable case."""
    rng = np.random.default_rng(seed)
    starts = rng.choice(d // 100, 10, replace=False) * 100
    idx = np.unique(np.concatenate([s + np.arange(k // 10) for s in starts]))[:k]
    vals = rng.normal(size=len(idx)).astype(np.float32)
    sp = sparse.SparseGrad(
        values=jnp.asarray(vals),
        indices=jnp.asarray(idx, jnp.int32),
        nnz=jnp.asarray(len(idx), jnp.int32),
        shape=(d,),
    )
    return vals, idx, sp


@pytest.mark.parametrize("maker", ["random", "clustered"])
def test_rle_round_trip_exact(maker):
    if maker == "random":
        g, sp = _sp()
        want_idx = np.sort(np.asarray(sp.indices))
        lut = dict(zip(np.asarray(sp.indices).tolist(), np.asarray(sp.values).tolist()))
        want_vals = np.asarray([lut[i] for i in want_idx])
    else:
        vals, idx, sp = _sp_clustered()
        order = np.argsort(idx)
        want_idx, want_vals = idx[order], vals[order]
    meta = rle.RLEMeta(k=sp.k, d=sp.dense_size)
    payload = rle.encode(sp, meta)
    out = rle.decode(payload, meta, sp.shape)
    n = int(out.nnz)
    np.testing.assert_array_equal(np.asarray(out.indices)[:n], want_idx)
    np.testing.assert_allclose(np.asarray(out.values)[:n], want_vals)


def test_rle_clustered_beats_raw():
    vals, idx, sp = _sp_clustered()
    meta = rle.RLEMeta(k=sp.k, d=sp.dense_size)
    payload = rle.encode(sp, meta)
    assert int(rle.wire_bits(payload, meta)) < sp.k * 32


def test_integer_round_trip_exact():
    g, sp = _sp(seed=2)
    meta = integer.IntegerMeta(k=sp.k, d=sp.dense_size)
    payload = integer.encode(sp, meta)
    out = integer.decode(payload, meta, sp.shape)
    want_idx = np.sort(np.asarray(sp.indices))
    np.testing.assert_array_equal(np.asarray(out.indices), want_idx)
    # delta coding of sorted top-k indices beats raw 32-bit indices
    assert int(integer.wire_bits(payload, meta)) < sp.k * 32


def test_integer_handles_partial_nnz():
    _, _, sp = _sp_clustered(k=150)
    # pad budget beyond nnz
    k = sp.k + 10
    padded = sparse.SparseGrad(
        values=jnp.zeros((k,), jnp.float32).at[: sp.k].set(sp.values),
        indices=jnp.zeros((k,), jnp.int32).at[: sp.k].set(sp.indices),
        nnz=sp.nnz,
        shape=sp.shape,
    )
    meta = integer.IntegerMeta(k=k, d=sp.dense_size)
    out = integer.decode(integer.encode(padded, meta), meta, sp.shape)
    n = int(out.nnz)
    np.testing.assert_array_equal(
        np.asarray(out.indices)[:n], np.sort(np.asarray(sp.indices))
    )


@pytest.mark.parametrize("pad", [0, 10])
def test_integer_decode_dense_matches_list_decode(pad):
    """decode_dense (sorted unique scatter fast path) is an oracle match for
    decode().to_dense(), including padded dead slots and a value override."""
    _, _, sp = _sp_clustered(k=150, seed=4)
    k = sp.k + pad
    padded = sparse.SparseGrad(
        values=jnp.zeros((k,), jnp.float32).at[: sp.k].set(sp.values),
        indices=jnp.zeros((k,), jnp.int32).at[: sp.k].set(sp.indices),
        nnz=sp.nnz,
        shape=sp.shape,
    )
    meta = integer.IntegerMeta(k=k, d=sp.dense_size)
    payload = integer.encode(padded, meta)
    want = np.asarray(integer.decode(payload, meta, sp.shape).to_dense())
    got = np.asarray(integer.decode_dense(payload, meta, sp.shape))
    np.testing.assert_allclose(got, want)
    # value override substitutes positionally (the 'both'-mode contract)
    table = jnp.arange(1, k + 1, dtype=jnp.float32)
    got2 = np.asarray(integer.decode_dense(payload, meta, sp.shape, values=table))
    sp_dec = integer.decode(payload, meta, sp.shape)
    n = int(sp_dec.nnz)
    idx = np.asarray(sp_dec.indices)[:n]
    np.testing.assert_allclose(got2[idx], np.asarray(table)[:n])
    # every other coordinate stays zero (dead slots and table tail must not
    # leak in-range)
    rest = got2.copy()
    rest[idx] = 0.0
    np.testing.assert_array_equal(rest, np.zeros_like(rest))


def test_huffman_round_trip_exact():
    g, sp = _sp(d=4096, ratio=0.05, seed=3)
    meta = huffman.HuffmanMeta(k=sp.k, d=sp.dense_size)
    payload = huffman.encode(sp, meta)
    out = huffman.decode(payload, meta, sp.shape)
    np.testing.assert_array_equal(np.asarray(out.indices), np.asarray(sp.indices))
    np.testing.assert_array_equal(np.asarray(out.values), np.asarray(sp.values))
    # order-preserving: no sort happened
    assert int(huffman.wire_bits(payload, meta)) < sp.k * 32 + 64


def test_huffman_codec_is_universe_deterministic():
    # two independent encodes of different data use the same code table
    _, sp1 = _sp(d=4096, ratio=0.05, seed=4)
    _, sp2 = _sp(d=4096, ratio=0.05, seed=5)
    meta = huffman.HuffmanMeta(k=sp1.k, d=4096)
    p1 = huffman.encode(sp1, meta)
    out1 = huffman.decode(p1, meta, sp1.shape)
    np.testing.assert_array_equal(np.asarray(out1.indices), np.asarray(sp1.indices))
    p2 = huffman.encode(sp2, meta)
    out2 = huffman.decode(p2, meta, sp2.shape)
    np.testing.assert_array_equal(np.asarray(out2.indices), np.asarray(sp2.indices))
