"""Heterogeneous population plane (deepreduce_tpu.population): spec schema
and reason codes, the config fences, the deterministic sampler (quota-exact
assignments, planted-skew marginals), the shared latency-row parser family,
bitwise IID degeneracy of the uniform spec (sync AND async — params,
residual bank, buffer), the exact per-class participation histogram riding
the one fused psum, the accumulator/costmodel/SLO plumbing, and the
committed BENCH_POP_r25 ledger row."""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepreduce_tpu import costmodel as cm
from deepreduce_tpu.config import ConfigError, DeepReduceConfig, reason_code_of
from deepreduce_tpu.fedsim import FedSim, parse_latency, synthetic_linear_problem
from deepreduce_tpu.fedsim.round import parse_class_latency, parse_tenant_latency
from deepreduce_tpu.population import (
    ClassSpec,
    PopulationSpec,
    class_assignments,
    label_mixtures,
    make_population_data_fn,
)
from deepreduce_tpu.population.sampler import (
    class_counts,
    concentration_table,
    expected_marginals,
    label_means,
)

DIM, BATCH, LOCAL = 16, 4, 2

UNIFORM_SPEC = '{"version": 1, "classes": [{"name": "uniform"}]}'
SKEW_SPEC = json.dumps({
    "version": 1,
    "num_labels": 4,
    "label_shift": 0.05,
    "classes": [
        {"name": "bulk", "weight": 3.0, "data_alpha": 2.0},
        {"name": "skewed", "weight": 1.0, "data_alpha": 0.5, "data_bias": 4.0},
    ],
})


def _cfg(**kw):
    base = dict(
        deepreduce="index",
        index="bloom",
        bloom_blocked="mod",
        compress_ratio=0.25,
        fpr=0.01,
        memory="residual",
        min_compress_size=8,
    )
    base.update(kw)
    return DeepReduceConfig(**base)


def _fed_kw(**kw):
    base = dict(fed=True, fed_num_clients=64, fed_clients_per_round=16,
                fed_local_steps=LOCAL)
    base.update(kw)
    return base


def _driver(cfg, mesh, chunk=2):
    params0, data_fn, loss_fn = synthetic_linear_problem(DIM, BATCH, LOCAL)
    fs = FedSim(loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
                mesh=mesh, client_chunk=chunk)
    return fs, fs.init(params0)


def _leaves_equal(a, b):
    return all(
        bool(jnp.all(x == y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


# ---------------------------------------------------------------------- #
# spec schema: parse, views, roundtrip
# ---------------------------------------------------------------------- #


def test_spec_roundtrip_and_views():
    spec = PopulationSpec.load_any(SKEW_SPEC)
    assert spec.num_classes == 2 and spec.num_labels == 4
    assert spec.weights == pytest.approx((0.75, 0.25))
    assert spec.skew_on and not spec.latency_on and not spec.is_uniform
    # to_dict -> from_dict is the identity on the parsed form
    assert PopulationSpec.from_dict(spec.to_dict()) == spec

    uni = PopulationSpec.uniform()
    assert uni.is_uniform and uni.num_classes == 1
    assert uni.weights == (1.0,) and uni.local_steps_mults == (1.0,)
    assert not uni.skew_on and not uni.latency_on
    # the config-knob override replaces only the label universe
    assert uni.with_overrides(num_labels=16).num_labels == 16
    assert uni.with_overrides(num_labels=0) == uni

    lat = PopulationSpec(classes=(
        ClassSpec(name="fast", latency="0.6,0.3,0.1"),
        ClassSpec(name="slow"),
    ))
    assert lat.latency_on and not lat.is_uniform


def test_spec_load_paths(tmp_path):
    p = tmp_path / "pop.json"
    p.write_text(SKEW_SPEC)
    assert PopulationSpec.load(p) == PopulationSpec.load_any(SKEW_SPEC)
    assert PopulationSpec.load_any(str(p)) == PopulationSpec.load_any(SKEW_SPEC)

    with pytest.raises(ConfigError) as ei:
        PopulationSpec.load(tmp_path / "missing.json")
    assert reason_code_of(ei.value) == "pop-spec-syntax"
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(ConfigError) as ei:
        PopulationSpec.load(bad)
    assert reason_code_of(ei.value) == "pop-spec-syntax"
    with pytest.raises(ConfigError) as ei:
        PopulationSpec.load_any("")
    assert reason_code_of(ei.value) == "pop-spec-syntax"
    with pytest.raises(ConfigError) as ei:
        PopulationSpec.load_any("{not json")
    assert reason_code_of(ei.value) == "pop-spec-syntax"


def _cls(**kw):
    base = {"name": "c0"}
    base.update(kw)
    return base


@pytest.mark.parametrize("raw, code", [
    (["not", "an", "object"], "pop-spec-syntax"),
    ({"bogus_key": 1, "classes": [_cls()]}, "pop-spec-syntax"),
    ({"version": 2, "classes": [_cls()]}, "pop-spec-syntax"),
    ({"classes": "nope"}, "pop-spec-syntax"),
    ({"classes": ["nope"]}, "pop-spec-syntax"),
    ({"classes": [{"weight": 1.0}]}, "pop-spec-syntax"),       # missing name
    ({"classes": [_cls(bogus=1)]}, "pop-spec-syntax"),
    ({"classes": [_cls(weight="3")]}, "pop-spec-syntax"),
    ({"classes": [_cls(), _cls()]}, "pop-spec-syntax"),        # duplicate name
    ({"classes": []}, "pop-spec-range"),
    ({"classes": [{"name": f"c{i}"} for i in range(65)]}, "pop-spec-range"),
    ({"classes": [_cls(weight=0.0)]}, "pop-spec-range"),
    ({"classes": [_cls(data_alpha=-0.5)]}, "pop-spec-range"),
    ({"classes": [_cls(data_bias=-1.0)]}, "pop-spec-range"),
    # bias on the IID sentinel: there is no Dirichlet to bias
    ({"classes": [_cls(data_bias=2.0)]}, "pop-spec-range"),
    ({"classes": [_cls(local_steps_mult=0.5)]}, "pop-spec-range"),
    ({"classes": [_cls(latency=7)]}, "pop-spec-syntax"),
    ({"classes": [_cls(latency="0.5,x")]}, "pop-latency-syntax"),
    ({"classes": [_cls()], "num_labels": 1}, "pop-labels-range"),
    ({"classes": [_cls()], "num_labels": "many"}, "pop-labels-range"),
    ({"classes": [_cls()], "label_shift": -0.1}, "pop-spec-range"),
    ({"classes": [_cls()], "seed": -1}, "pop-spec-range"),
])
def test_spec_rejections(raw, code):
    with pytest.raises(ConfigError) as ei:
        PopulationSpec.from_dict(raw)
    assert reason_code_of(ei.value) == code


# ---------------------------------------------------------------------- #
# config fences
# ---------------------------------------------------------------------- #


def test_config_population_fences():
    # pop_spec without the federated geometry: nothing to classify
    with pytest.raises(ConfigError) as ei:
        _cfg(pop_spec=UNIFORM_SPEC)
    assert reason_code_of(ei.value) == "pop-needs-fed"
    # engaged override knob without its consumer
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(pop_labels=4))
    assert reason_code_of(ei.value) == "pop-knobs-disengaged"
    # per-class and per-tenant heterogeneity do not compose
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(pop_spec=UNIFORM_SPEC, fed_tenants=2))
    assert reason_code_of(ei.value) == "pop-vs-mt"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(pop_spec=UNIFORM_SPEC, pop_labels=1))
    assert reason_code_of(ei.value) == "pop-labels-range"
    # per-class latency rows configure the async staleness draw only
    lat_spec = json.dumps({"version": 1, "classes": [
        {"name": "slow", "latency": "0.5,0.5"}]})
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(pop_spec=lat_spec))
    assert reason_code_of(ei.value) == "pop-knobs-disengaged"
    # a typo'd spec fails at config construction, not driver build
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(pop_spec='{"classes": [{"name": "a", "weight": 0}]}'))
    assert reason_code_of(ei.value) == "pop-spec-range"
    # valid engagements construct: sync skew, async per-class latency
    cfg = _cfg(**_fed_kw(pop_spec=SKEW_SPEC, pop_labels=8))
    assert cfg.pop_spec == SKEW_SPEC and cfg.pop_labels == 8
    cfg = _cfg(**_fed_kw(fed_async=True, fed_async_k=16, pop_spec=lat_spec))
    assert cfg.fed_async


# ---------------------------------------------------------------------- #
# shared latency-row parser family (r25 hardening)
# ---------------------------------------------------------------------- #


def test_parse_latency_rejects_non_finite_and_labels_knob():
    with pytest.raises(ValueError, match="finite"):
        parse_latency("inf,1")
    with pytest.raises(ValueError, match="finite"):
        parse_latency("nan")
    # the name kwarg labels the failing knob in the message
    with pytest.raises(ValueError, match="my_knob"):
        parse_latency("0.5,x", name="my_knob")


def test_parse_tenant_latency_rejects_empty_row():
    with pytest.raises(ValueError, match="empty per-tenant row"):
        parse_tenant_latency("0.5,0.5;;1", 3, "")
    # '' broadcasts the default to the fleet
    assert parse_tenant_latency("", 2, "0.5,0.5") == ((0.5, 0.5), (0.5, 0.5))


def test_parse_class_latency_inheritance_and_padding():
    # '' inherits the global default; rows zero-pad to the common depth D
    rows = parse_class_latency(["", "0.5,0.3,0.2"], default="1")
    assert rows == ((1.0, 0.0, 0.0), (0.5, 0.3, 0.2))
    # padding is draw-preserving: no probability mass lands on the tail
    assert all(sum(r) == pytest.approx(1.0) for r in rows)
    # no default and no overrides: everyone on the zero-latency row
    assert parse_class_latency(["", ""]) == ((1.0,), (1.0,))
    with pytest.raises(ValueError, match=r"class\[1\]"):
        parse_class_latency(["", "0.5,x"])


# ---------------------------------------------------------------------- #
# sampler determinism: quotas, assignments, mixtures, planted skew
# ---------------------------------------------------------------------- #


def test_class_counts_largest_remainder():
    spec = PopulationSpec.load_any(SKEW_SPEC)
    assert class_counts(spec, 64) == (48, 16)          # exact quotas
    assert class_counts(spec, 10) == (8, 2)            # tie -> class order
    assert sum(class_counts(spec, 7)) == 7             # always sums to N
    with pytest.raises(ValueError, match=">= 1"):
        class_counts(spec, 0)


def test_class_assignments_deterministic():
    spec = PopulationSpec.load_any(SKEW_SPEC)
    a1 = np.asarray(class_assignments(spec, 64))
    a2 = np.asarray(class_assignments(spec, 64))
    np.testing.assert_array_equal(a1, a2)              # bitwise from (spec, N)
    assert a1.dtype == np.int32
    # quota-exact composition survives the permutation
    assert np.bincount(a1, minlength=2).tolist() == [48, 16]
    # the permutation is spec-seeded: a different seed reshuffles
    reseeded = PopulationSpec.from_dict(
        json.loads(SKEW_SPEC) | {"seed": 7})
    a3 = np.asarray(class_assignments(reseeded, 64))
    assert np.bincount(a3, minlength=2).tolist() == [48, 16]
    assert np.any(a1 != a3)


def test_planted_skew_marginals_analytic():
    spec = PopulationSpec.load_any(SKEW_SPEC)
    c = concentration_table(spec)
    # c[k, l] = data_alpha_k + data_bias_k * [l == k % L]
    np.testing.assert_allclose(c[0], [2.0, 2.0, 2.0, 2.0])
    np.testing.assert_allclose(c[1], [0.5, 4.5, 0.5, 0.5])
    m = expected_marginals(spec)
    np.testing.assert_allclose(m[0], [0.25] * 4)
    np.testing.assert_allclose(m[1], c[1] / c[1].sum())
    # an alpha=0 (IID sentinel) class gets the uniform marginal
    iid = PopulationSpec.uniform(num_labels=4)
    np.testing.assert_allclose(expected_marginals(iid), [[0.25] * 4])
    # label means: centered over the universe, spanning +-label_shift
    mu = label_means(spec)
    assert float(mu.sum()) == pytest.approx(0.0, abs=1e-7)
    assert float(mu.min()) == pytest.approx(-spec.label_shift)
    assert float(mu.max()) == pytest.approx(spec.label_shift)


def test_label_mixtures_deterministic_and_match_marginals():
    spec = PopulationSpec.load_any(SKEW_SPEC)
    ids = list(range(256))
    m1 = np.asarray(label_mixtures(spec, ids, [1] * 256))
    m2 = np.asarray(label_mixtures(spec, ids, [1] * 256))
    np.testing.assert_array_equal(m1, m2)              # bitwise across calls
    np.testing.assert_allclose(m1.sum(axis=1), 1.0, atol=1e-5)
    # empirical mean over many clients approaches the analytic marginal
    np.testing.assert_allclose(
        m1.mean(axis=0), expected_marginals(spec)[1], atol=0.05)
    # alpha=0 classes get the exact uniform mixture, not a degenerate draw
    iid = PopulationSpec(classes=(
        ClassSpec(name="iid"), ClassSpec(name="skew", data_alpha=1.0)),
        num_labels=4)
    rows = np.asarray(label_mixtures(iid, [0, 1], [0, 0]))
    np.testing.assert_array_equal(rows, np.full((2, 4), 0.25))


def test_pop_data_fn_gates_are_exact_selects():
    _, data_fn, _ = synthetic_linear_problem(DIM, BATCH, LOCAL)
    key = jax.random.PRNGKey(5)
    # no skewed class: the base generator comes back untouched
    uni = PopulationSpec.uniform()
    uni_fn = make_population_data_fn(uni, data_fn)
    assert _leaves_equal(uni_fn(3, 0, 2, key), data_fn(3, 2, key))
    # skewed spec: an alpha=0 class's batch is the base output BITWISE
    # (jnp.where SELECT, never a mask-multiply); the skewed class shifts
    mixed = PopulationSpec(classes=(
        ClassSpec(name="iid"),
        ClassSpec(name="skew", data_alpha=0.3, data_bias=3.0)),
        num_labels=4, label_shift=0.5)
    pop_fn = make_population_data_fn(mixed, data_fn)
    assert _leaves_equal(pop_fn(3, 0, 2, key), data_fn(3, 2, key))
    assert not _leaves_equal(pop_fn(3, 1, 2, key), data_fn(3, 2, key))


# ---------------------------------------------------------------------- #
# driver degeneracy: the uniform spec IS the IID program, bitwise
# ---------------------------------------------------------------------- #


def test_uniform_spec_bitwise_degenerate_sync(mesh8):
    """A single-class uniform spec changes the wire (the f32[K=1] histogram
    rides the fused psum) but not the math: params AND residual bank land
    bitwise on the population-free round's."""
    key = jax.random.PRNGKey(0)
    fs_i, st_i = _driver(_cfg(**_fed_kw()), mesh8)
    m_i = None
    for r in range(3):
        st_i, m_i = fs_i.step(st_i, jax.random.fold_in(key, r))

    fs_p, st_p = _driver(_cfg(**_fed_kw(pop_spec=UNIFORM_SPEC)), mesh8)
    assert st_p.classes is not None and st_p.classes.shape == (64,)
    m_p = None
    for r in range(3):
        st_p, m_p = fs_p.step(st_p, jax.random.fold_in(key, r))
    assert _leaves_equal(st_i.params, st_p.params)
    assert _leaves_equal(st_i.residuals, st_p.residuals)
    # the exact histogram accounts for every sampled client, every round
    assert "pop_hist" not in m_i
    h = np.asarray(m_p["pop_hist"])
    assert h.shape == (1,) and float(h[0]) == float(m_p["clients"])


def test_uniform_spec_bitwise_degenerate_async(mesh8):
    """Same contract on the buffered-async tick: params, residual bank,
    AND the aggregation buffer are bitwise, with the staleness draw and
    buffer cadence untouched by the riding histogram."""
    kw = dict(fed_async=True, fed_async_k=40, fed_async_alpha=0.5,
              fed_async_latency="0.5,0.3,0.2")
    key = jax.random.PRNGKey(0)
    fs_i, st_i = _driver(_cfg(**_fed_kw(**kw)), mesh8)
    for r in range(4):
        st_i, _ = fs_i.step(st_i, jax.random.fold_in(key, r))

    fs_p, st_p = _driver(_cfg(**_fed_kw(pop_spec=UNIFORM_SPEC, **kw)), mesh8)
    m_p = None
    for r in range(4):
        st_p, m_p = fs_p.step(st_p, jax.random.fold_in(key, r))
    assert _leaves_equal(st_i.params, st_p.params)
    assert _leaves_equal(st_i.residuals, st_p.residuals)
    assert _leaves_equal(st_i.buffer, st_p.buffer)
    assert np.asarray(m_p["pop_hist"]).shape == (1,)

    # stream() is only a dispatch change under populations too
    fs_s, st_s = _driver(_cfg(**_fed_kw(pop_spec=UNIFORM_SPEC, **kw)), mesh8)
    st_s, hist, _ = fs_s.stream(st_s, key, 4)
    assert len(hist) == 4
    assert _leaves_equal(st_p.params, st_s.params)
    assert _leaves_equal(st_p.buffer, st_s.buffer)


def test_pop_hist_exact_mass_and_shares(mesh8):
    """The per-class histogram is EXACT per-round accounting: its mass
    equals the live-client count every round, and the cumulative shares
    track the quota composition (0.75/0.25) once enough cohorts sample."""
    key = jax.random.PRNGKey(2)
    fs, st = _driver(_cfg(**_fed_kw(pop_spec=SKEW_SPEC)), mesh8)
    total = np.zeros(2)
    for r in range(6):
        st, m = fs.step(st, jax.random.fold_in(key, r))
        h = np.asarray(m["pop_hist"], dtype=np.float64)
        assert h.shape == (2,) and np.all(h >= 0)
        assert float(h.sum()) == float(m["clients"])
        total += h
    shares = total / total.sum()
    np.testing.assert_allclose(shares, [0.75, 0.25], atol=0.15)
    assert all(
        bool(jnp.all(jnp.isfinite(x)))
        for x in jax.tree_util.tree_leaves(st.params)
    )


def test_pop_per_class_latency_async(mesh8):
    """Per-class latency rows drive the staleness draw: a population whose
    slow class carries all its mass at tau=2 shows a staleness tail, and
    the histogram still accounts every accepted contribution."""
    spec = json.dumps({"version": 1, "classes": [
        {"name": "fast", "weight": 1.0, "latency": "1"},
        {"name": "slow", "weight": 1.0, "latency": "0,0,1"},
    ]})
    cfg = _cfg(**_fed_kw(fed_async=True, fed_async_k=16, pop_spec=spec))
    key = jax.random.PRNGKey(4)
    fs, st = _driver(cfg, mesh8)
    saw_tail = False
    for r in range(4):
        st, m = fs.step(st, jax.random.fold_in(key, r))
        h = np.asarray(m["pop_hist"], dtype=np.float64)
        assert float(h.sum()) == float(m["clients"])
        sh = np.asarray(m["staleness_hist"], dtype=np.float64)
        assert sh.shape == (3,)  # D = per-class common depth
        saw_tail = saw_tail or sh[2] > 0
    # the slow class's deterministic tau=2 row produced a genuine tail
    assert saw_tail


# ---------------------------------------------------------------------- #
# accumulator plumbing: the optional f32[K] child
# ---------------------------------------------------------------------- #


def test_metric_accumulators_pop_hist_vector():
    from deepreduce_tpu.metrics import WireStats
    from deepreduce_tpu.telemetry import MetricAccumulators
    from deepreduce_tpu.telemetry.device_metrics import fetch_delta

    wire = WireStats(
        index_bits=jnp.asarray(10.0), value_bits=jnp.asarray(20.0),
        dense_bits=jnp.asarray(100.0), saturated=jnp.asarray(0.0),
    )
    acc = MetricAccumulators.zeros(num_pop_classes=2)
    assert acc.pop_hist is not None and acc.pop_hist.shape == (2,)
    acc = acc.accumulate(wire, pop_hist=jnp.asarray([3.0, 1.0]))
    acc = acc.accumulate(wire, pop_hist=jnp.asarray([1.0, 3.0]))
    vals = acc.fetch()
    assert vals["pop_hist"] == [4.0, 4.0]
    d = MetricAccumulators.derive(vals)
    assert d["pop_shares"] == [0.5, 0.5]
    assert d["pop_residency_min"] == 0.5
    # a window delta subtracts the histogram elementwise
    acc2 = acc.accumulate(wire, pop_hist=jnp.asarray([2.0, 0.0]))
    delta = fetch_delta(acc2.fetch(), vals)
    assert delta["pop_hist"] == [2.0, 0.0]
    with pytest.raises(ValueError, match="pop_hist length mismatch"):
        fetch_delta(acc2.fetch(), vals | {"pop_hist": [1.0]})
    # population-off accumulators are STRUCTURALLY unchanged: the None
    # child contributes no pytree leaf and no fetched key
    off = MetricAccumulators.zeros()
    assert off.pop_hist is None
    assert "pop_hist" not in off.fetch()
    assert "pop_shares" not in MetricAccumulators.derive(off.fetch())
    off2 = off.accumulate(wire)
    assert off2.pop_hist is None
    assert jax.tree_util.tree_structure(off) == jax.tree_util.tree_structure(
        MetricAccumulators.zeros())


# ---------------------------------------------------------------------- #
# cost model: collapse-exact population pricing
# ---------------------------------------------------------------------- #


def test_costmodel_pop_compute_factor():
    # uniform multipliers collapse to the EXACT literal 1.0 (no rounding)
    assert cm.pop_compute_factor((0.3, 0.7), (1.0, 1.0)) == 1.0
    assert cm.pop_compute_factor((3.0, 1.0), (1.0, 2.0)) == pytest.approx(1.25)
    with pytest.raises(ValueError, match="class weights"):
        cm.pop_compute_factor((1.0,), (1.0, 2.0))
    with pytest.raises(ValueError, match="at least one class"):
        cm.pop_compute_factor((), ())
    with pytest.raises(ValueError, match="sum"):
        cm.pop_compute_factor((0.0, 0.0), (1.0, 2.0))


def test_costmodel_pop_staleness_and_throughput():
    # mixture staleness: equal-weight tau=0 and tau=2 classes average to 1
    rows = ((1.0, 0.0, 0.0), (0.0, 0.0, 1.0))
    assert cm.pop_expected_staleness((1.0, 1.0), rows) == pytest.approx(1.0)
    # uniform population prices EXACTLY like no population at all
    assert cm.fed_pop_clients_per_sec(1000.0, 100, t_client_s=0.5) == \
        cm.fed_clients_per_sec(1000.0, 100, t_client_s=0.5)
    assert cm.fed_pop_async_clients_per_sec(1000.0, 100, t_client_s=0.5) == \
        cm.fed_async_clients_per_sec(1000.0, 100, t_client_s=0.5)
    # a heavier compute class slows the cohort barrier
    slow = cm.fed_pop_clients_per_sec(
        1000.0, 100, weights=(1.0, 1.0), local_steps_mults=(1.0, 4.0),
        t_client_s=0.5)
    assert slow < cm.fed_clients_per_sec(1000.0, 100, t_client_s=0.5)
    # per-class latency rows stretch the async pipeline vs zero latency
    base = cm.fed_pop_async_clients_per_sec(1.0, 10, t_client_s=4.0)
    stale = cm.fed_pop_async_clients_per_sec(
        1.0, 10, weights=(1.0, 1.0), local_steps_mults=(1.0, 1.0),
        class_latency_rows=rows, t_client_s=4.0)
    assert stale < base


# ---------------------------------------------------------------------- #
# SLO health plane: the pop_residency_min target
# ---------------------------------------------------------------------- #


def test_slo_pop_residency_spec_and_monitor():
    from deepreduce_tpu.slo import HealthMonitor, SLOSpec

    spec = SLOSpec.from_dict({"targets": {"pop_residency_min": 0.25}})
    assert spec.targets["pop_residency_min"] == 0.25
    for bad in (-0.1, 1.5):
        with pytest.raises(ConfigError) as ei:
            SLOSpec.from_dict({"targets": {"pop_residency_min": bad}})
        assert reason_code_of(ei.value) == "slo-spec-target-range"

    mon_spec = SLOSpec(window_ticks=1, fast_window_ticks=1,
                       slow_window_ticks=1, hysteresis_ticks=1,
                       targets={"pop_residency_min": 0.25})
    # a starved class (share 0.1 < 0.25) breaches
    mon = HealthMonitor(mon_spec)
    for tick in range(2):
        mon.observe(tick, {"pop_hist": [9.0, 1.0]})
    assert mon.state_of() == "BREACH"
    v = mon.verdict(0)["targets"]["pop_residency_min"]
    assert v["value"] == pytest.approx(0.1) and not v["ok"]
    # a balanced population holds
    mon = HealthMonitor(mon_spec)
    for tick in range(2):
        mon.observe(tick, {"pop_hist": [5.0, 5.0]})
    assert mon.state_of() == "OK" and mon.healthy()
    # rows without a histogram carry no evidence: no transitions
    mon = HealthMonitor(mon_spec)
    for tick in range(4):
        mon.observe(tick, {"clients": 16.0})
    assert mon.events == [] and mon.healthy()
    row = mon.verdict(0)["targets"]["pop_residency_min"]
    assert row["value"] is None and row["ok"]


# ---------------------------------------------------------------------- #
# committed bench ledger: the r25 population convergence-band sweep
# ---------------------------------------------------------------------- #


def test_bench_pop_ledger_row_committed(capsys):
    """BENCH_POP_r25.json must stay a valid modeled+measured ledger record
    (bench-history renders it), and its convergence-band evidence must
    hold: every skew arm inside the loss band, per-class shares summing
    to one."""
    from deepreduce_tpu.telemetry import __main__ as cli

    root = pathlib.Path(cli.__file__).resolve().parents[2]
    rec = json.loads((root / "BENCH_POP_r25.json").read_text())
    assert rec["metric"] == "fedsim_pop_serving_clients_per_sec"
    assert rec["provenance"]["modeled"] and rec["provenance"]["measured"]
    detail = rec["detail"]
    arms = detail["arms"]
    assert set(arms) == {"uniform", "mild_skew", "pathological_skew"}
    assert detail["all_arms_within_loss_band"]
    assert all(detail["within_loss_band"].values())
    for arm in arms.values():
        shares = arm["pop_shares_measured"]
        assert sum(shares) == pytest.approx(1.0, abs=1e-3)
        assert len(shares) == arm["num_classes"]
    assert arms["uniform"]["num_classes"] == 1
    assert arms["pathological_skew"]["num_classes"] == 2

    assert cli.main(["bench-history", str(root)]) == 0
    out = capsys.readouterr().out
    assert "r25" in out
