"""The analysis gate, in tier-1: the shipped tree must audit clean (AST
lint repo-wide + the quick jaxpr subset), and each rule must actually fire
— every known-bad fixture here is caught with exactly one violation
carrying its distinct rule id."""

import jax
import jax.numpy as jnp
import pytest

from deepreduce_tpu.analysis import ast_lint, rules
from deepreduce_tpu.analysis.ast_lint import lint_repo, lint_source
from deepreduce_tpu.analysis.jaxpr_audit import (
    AXIS,
    audit_all,
    audit_mesh,
    audit_mod_query,
    trace_and_check,
)
from deepreduce_tpu.analysis.rules import AuditContext, run_rules
from deepreduce_tpu.config import DeepReduceConfig, from_params
from deepreduce_tpu.utils.compat import shard_map


def _only(violations, rule):
    """Assert exactly one violation and that it carries `rule`."""
    assert len(violations) == 1, [v.to_dict() for v in violations]
    assert violations[0].rule == rule
    return violations[0]


# ---------------------------------------------------------------------- #
# the shipped tree is clean
# ---------------------------------------------------------------------- #


def test_repo_ast_lint_clean():
    assert lint_repo() == []


def test_quick_jaxpr_audit_clean():
    records, violations = audit_all(quick=True)
    assert violations == [], [v.to_dict() for v in violations]
    assert not any(r.skipped for r in records)
    labels = {r.label for r in records}
    assert "query:bloom-mod" in labels
    assert {"exchange:fused-loop", "exchange:fused-vmap",
            "exchange:fused-ring", "exchange:bucketed-loop"} <= labels


def test_mod_query_is_gather_free():
    """The flagship structural claim, checked on its own: zero gather eqns
    in the mod-blocked universe query."""
    (rec,) = audit_mod_query()
    assert rec.violations == []


# ---------------------------------------------------------------------- #
# AST negative fixtures
# ---------------------------------------------------------------------- #


def test_ast_catches_direct_shard_map_import():
    src = "from jax.experimental.shard_map import shard_map\n"
    _only(lint_source(src, "deepreduce_tpu/newmod.py"), ast_lint.R_AST_COMPAT)


def test_ast_catches_host_entropy_in_traced_module():
    src = (
        "import numpy as np\n"
        "def encode(x):\n"
        "    noise = np.random.normal(size=x.shape)\n"
        "    return x + noise\n"
    )
    _only(lint_source(src, "deepreduce_tpu/codecs/fake.py"), ast_lint.R_AST_ENTROPY)


def test_ast_catches_time_in_traced_module():
    src = "import time\n\ndef encode(x):\n    return x * time.time()\n"
    _only(lint_source(src, "deepreduce_tpu/sparse.py"), ast_lint.R_AST_ENTROPY)


def test_ast_catches_python_branch_on_traced_value():
    src = (
        "import jax.numpy as jnp\n"
        "def decode(x):\n"
        "    if jnp.max(x) > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    _only(lint_source(src, "deepreduce_tpu/codecs/fake.py"), ast_lint.R_AST_BRANCH)


def test_ast_catches_span_in_codec_module():
    src = (
        "from deepreduce_tpu.telemetry import spans\n"
        "def encode(x):\n"
        "    with spans.span('encode/inner'):\n"
        "        return x * 2\n"
    )
    _only(lint_source(src, "deepreduce_tpu/codecs/fake.py"), ast_lint.R_AST_SPAN)
    # the identical source is fine in the communicator layer — spans belong
    # around traced regions, not inside them
    assert lint_source(src, "deepreduce_tpu/comm.py") == []


def test_ast_catches_dump_logger_in_codec_module():
    src = (
        "from somewhere import DumpLogger\n"
        "def decode(p):\n"
        "    DumpLogger('decode').write(p)\n"
        "    return p\n"
    )
    violations = lint_source(src, "deepreduce_tpu/codecs/fake.py")
    assert violations, "DumpLogger construction in codecs/ must be flagged"
    assert all(v.rule == ast_lint.R_AST_SPAN for v in violations)


def test_ast_span_rule_ignores_local_variable_named_span():
    # codecs/polyseg.py uses `span` as a local float — assignments and
    # arithmetic on a name are not telemetry calls
    src = "def fit(lo, hi):\n    span = hi - lo\n    return span / 2\n"
    assert lint_source(src, "deepreduce_tpu/codecs/fake.py") == []


def test_ast_rules_scope_correctly():
    # host entropy is fine in untraced tooling; compat module may import
    # shard_map directly (it IS the shim)
    src = "import time\nt = time.time()\n"
    assert lint_source(src, "deepreduce_tpu/tracking.py") == []
    src = "from jax.experimental.shard_map import shard_map\n"
    assert lint_source(src, "deepreduce_tpu/utils/compat.py") == []


# ---------------------------------------------------------------------- #
# jaxpr negative fixtures — each rule fires, alone, with its own id
# ---------------------------------------------------------------------- #


def test_f64_mini_codec_caught():
    """A deliberately-f64 'codec': accumulate in double, cast back."""
    from jax.experimental import enable_x64

    def bad_encode(x):
        return (x.astype(jnp.float64) * 2.0).astype(jnp.float32)

    with enable_x64():
        closed = jax.make_jaxpr(bad_encode)(jax.ShapeDtypeStruct((64,), jnp.float32))
    v = _only(run_rules(closed, AuditContext(label="fixture:f64")), rules.R_F64)
    assert "float64" in v.detail


def test_unsorted_budget_gather_caught():
    """Sorted indices whose gather doesn't carry the promise."""
    k = 64

    def bad_read(flat, idxs):
        idxs = jnp.sort(idxs)
        return flat[idxs]  # budget-scale gather, indices_are_sorted lost

    closed = jax.make_jaxpr(bad_read)(
        jax.ShapeDtypeStruct((1024,), jnp.float32),
        jax.ShapeDtypeStruct((k,), jnp.int32),
    )
    ctx = AuditContext(label="fixture:unsorted", budget_scale=k)
    _only(run_rules(closed, ctx), rules.R_UNSORTED_BUDGET_GATHER)


def test_two_collective_fused_exchange_caught():
    """A 'fused' exchange that issues two all_gathers breaks the
    one-collective-per-step contract."""
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()

    def spmd(x):
        a = jax.lax.all_gather(x[0], AXIS)
        b = jax.lax.all_gather(x[0] * 2.0, AXIS)
        return (a + b).sum(axis=0)[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 128), jnp.float32))
    ctx = AuditContext(
        label="fixture:two-collectives", expect_collectives={"all_gather": 1}
    )
    v = _only(run_rules(closed, ctx), rules.R_COLLECTIVE_COUNT)
    assert "all_gather" in v.detail


def test_per_axis_collective_inventory_caught():
    """The per-axis form of jx-collective-count: a psum that rides the dcn
    axis when the contract puts it on ici is caught, and so is any
    collective on an axis the contract does not mention."""
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.analysis.jaxpr_audit import audit_hier_mesh

    mesh = audit_hier_mesh(2, 4)

    def spmd(x):
        # slice reduction on the WRONG axis (dcn instead of ici), plus the
        # gather the contract expects on dcn
        m = jax.lax.psum(x[0], "dcn") / 2.0
        return jax.lax.all_gather(m, "dcn").sum(axis=0)[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(("dcn", "ici")),),
                   out_specs=P(("dcn", "ici")), check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 128), jnp.float32))
    ctx = AuditContext(
        label="fixture:axis-swap",
        expect_collectives_by_axis={
            "ici": {"psum": 1}, "dcn": {"all_gather": 1},
        },
    )
    v = _only(run_rules(closed, ctx), rules.R_COLLECTIVE_COUNT)
    assert "ici/psum" in v.detail and "dcn/psum" in v.detail

    # an axis the contract does not mention is itself a violation
    ctx2 = AuditContext(
        label="fixture:unmentioned-axis",
        expect_collectives_by_axis={"ici": {"psum": 1, "all_gather": 1}},
    )
    v2 = _only(run_rules(closed, ctx2), rules.R_COLLECTIVE_COUNT)
    assert "does not mention" in v2.detail


def test_gather_in_mod_query_caught():
    def bad_query(words, idxs):
        return words[idxs]  # a gather in what must be a broadcast path

    closed = jax.make_jaxpr(bad_query)(
        jax.ShapeDtypeStruct((256,), jnp.uint32),
        jax.ShapeDtypeStruct((16,), jnp.int32),
    )
    ctx = AuditContext(label="fixture:mod-gather", forbid_gather=True)
    _only(run_rules(closed, ctx), rules.R_GATHER_IN_MOD_QUERY)


def test_unwhitelisted_callback_caught():
    def bad(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    closed = jax.make_jaxpr(bad)(jax.ShapeDtypeStruct((32,), jnp.float32))
    _only(
        run_rules(closed, AuditContext(label="fixture:callback")),
        rules.R_CALLBACK,
    )
    # the same trace is fine for a whitelisted host codec
    ok = run_rules(closed, AuditContext(label="fixture:host", allow_callbacks=True))
    assert ok == []


def test_wire_accounting_mismatch_caught():
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()
    d = 128

    def spmd(x):
        return jax.lax.all_gather(x[0], AXIS).sum(axis=0)[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, d), jnp.float32))
    good = AuditContext(label="fixture:wire-ok", wire_mode="allgather",
                        expected_wire_bytes=4 * d)
    assert run_rules(closed, good) == []
    bad = AuditContext(label="fixture:wire-bad", wire_mode="allgather",
                       expected_wire_bytes=4 * d + 1)
    _only(run_rules(closed, bad), rules.R_WIRE_ACCOUNTING)


def test_wire_accounting_collective_mode():
    """The r11 'collective' wire mode sums operand bytes over EVERY
    collective eqn (a psum'd sketch + a gathered payload here), so routes
    whose wire story spans multiple collective shapes get exact
    accounting; one byte of drift is a violation."""
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()
    rows_cols, d = (5, 64), 128

    def spmd(x):
        sk = jax.lax.psum(x[0, : rows_cols[0] * rows_cols[1]], AXIS)
        out = jax.lax.all_gather(x[0, : d // 2], AXIS)
        return (sk.sum() + out.sum())[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, d * 4), jnp.float32))
    want = 4 * rows_cols[0] * rows_cols[1] + 4 * (d // 2)
    good = AuditContext(label="fixture:coll-ok", wire_mode="collective",
                        expected_wire_bytes=want)
    assert run_rules(closed, good) == []
    bad = AuditContext(label="fixture:coll-bad", wire_mode="collective",
                       expected_wire_bytes=want + 1)
    _only(run_rules(closed, bad), rules.R_WIRE_ACCOUNTING)


def test_codec_invocation_count_caught():
    """A 'bucketed' exchange that runs a per-leaf top-k breaks the
    O(buckets) codec contract — the count of selection eqns is the proxy."""
    k = 16

    def per_leaf_select(a, b):
        va, _ = jax.lax.top_k(a, k)
        vb, _ = jax.lax.top_k(b, k)
        return va.sum() + vb.sum()

    closed = jax.make_jaxpr(per_leaf_select)(
        jax.ShapeDtypeStruct((256,), jnp.float32),
        jax.ShapeDtypeStruct((128,), jnp.float32),
    )
    good = AuditContext(label="fixture:codec-ok", expect_codec_invocations=2)
    assert run_rules(closed, good) == []
    bad = AuditContext(label="fixture:codec-bad", expect_codec_invocations=1)
    v = _only(run_rules(closed, bad), rules.R_CODEC_COUNT)
    assert "2" in v.detail


def test_retrace_hash_stable():
    """Two traces of the same codec program hash identically — the guard
    that trips means every step would recompile."""
    rec = trace_and_check(
        "retrace-probe",
        lambda x: x * 2.0,
        (jax.ShapeDtypeStruct((64,), jnp.float32),),
        AuditContext(label="retrace-probe"),
    )
    assert rec.violations == []
    assert len(rec.jaxpr_hash) == 16


# ---------------------------------------------------------------------- #
# CLI gate
# ---------------------------------------------------------------------- #


def test_cli_exit_codes(monkeypatch, tmp_path):
    """`python -m deepreduce_tpu.analysis` exits 0 clean, 1 on violations."""
    from deepreduce_tpu.analysis import __main__ as cli
    from deepreduce_tpu.analysis import ast_lint as al
    from deepreduce_tpu.analysis import jaxpr_audit as ja

    monkeypatch.setattr(ja, "audit_all", lambda quick=False: ([], []))
    monkeypatch.setattr(al, "lint_repo", lambda root=None: [])
    out = tmp_path / "report.json"
    assert cli.main(["--quick", "--out", str(out)]) == 0
    assert out.exists()

    bad = rules.Violation("ast-compat-route", "x.py:1", "fixture")
    monkeypatch.setattr(al, "lint_repo", lambda root=None: [bad])
    assert cli.main(["--quick", "--out", "-"]) == 1


# ---------------------------------------------------------------------- #
# config satellites
# ---------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "field,value",
    [
        ("compressor", "topkk"),
        ("communicator", "allgater"),
        ("memory", "residuals"),
        ("deepreduce", "indices"),
        ("policy", "left"),
        ("index", "bloomfilter"),
        ("value", "polyfit2"),
        ("bloom_blocked", "modulo"),
    ],
)
def test_config_rejects_typos(field, value):
    with pytest.raises(ValueError, match=field):
        DeepReduceConfig(**{field: value})


def test_config_enums_match_registry():
    """The documented enumerations stay in lock-step with the codec
    registry — adding a codec without teaching config (or vice versa) is a
    test failure, not a latent KeyError."""
    from deepreduce_tpu.codecs import registry

    assert set(DeepReduceConfig.INDEX_CODECS) == set(registry.INDEX_CODECS)
    assert set(DeepReduceConfig.VALUE_CODECS) == set(registry.VALUE_CODECS)


def test_from_params_strict():
    params = {"compressor": "topk", "compress_ratio": 0.05}
    assert from_params(params, strict=True).compress_ratio == 0.05
    bad = {"compres_ratio": 0.05, "deepreduce": "index"}
    assert from_params(bad).compress_ratio == 0.01  # lenient: silently dropped
    with pytest.raises(ValueError, match="compres_ratio"):
        from_params(bad, strict=True)
    # reference-spelled aliases still map in strict mode
    cfg = from_params({"threshold": 0.5, "micro-benchmark": True}, strict=True)
    assert cfg.threshold_val == 0.5 and cfg.micro_benchmark
