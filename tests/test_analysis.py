"""The analysis gate, in tier-1: the shipped tree must audit clean (AST
lint repo-wide + the quick jaxpr subset), and each rule must actually fire
— every known-bad fixture here is caught with exactly one violation
carrying its distinct rule id."""

import jax
import jax.numpy as jnp
import pytest

from deepreduce_tpu.analysis import ast_lint, rules
from deepreduce_tpu.analysis.ast_lint import lint_repo, lint_source
from deepreduce_tpu.analysis.jaxpr_audit import (
    AXIS,
    audit_all,
    audit_mesh,
    audit_mod_query,
    trace_and_check,
)
from deepreduce_tpu.analysis.rules import AuditContext, run_rules
from deepreduce_tpu.config import DeepReduceConfig, from_params
from deepreduce_tpu.utils.compat import shard_map


def _only(violations, rule):
    """Assert exactly one violation and that it carries `rule`."""
    assert len(violations) == 1, [v.to_dict() for v in violations]
    assert violations[0].rule == rule
    return violations[0]


# ---------------------------------------------------------------------- #
# the shipped tree is clean
# ---------------------------------------------------------------------- #


def test_repo_ast_lint_clean():
    assert lint_repo() == []


def test_quick_jaxpr_audit_clean():
    records, violations = audit_all(quick=True)
    assert violations == [], [v.to_dict() for v in violations]
    assert not any(r.skipped for r in records)
    labels = {r.label for r in records}
    assert "query:bloom-mod" in labels
    assert {"exchange:fused-loop", "exchange:fused-vmap",
            "exchange:fused-ring", "exchange:bucketed-loop"} <= labels


def test_mod_query_is_gather_free():
    """The flagship structural claim, checked on its own: zero gather eqns
    in the mod-blocked universe query."""
    (rec,) = audit_mod_query()
    assert rec.violations == []


# ---------------------------------------------------------------------- #
# AST negative fixtures
# ---------------------------------------------------------------------- #


def test_ast_catches_direct_shard_map_import():
    src = "from jax.experimental.shard_map import shard_map\n"
    _only(lint_source(src, "deepreduce_tpu/newmod.py"), ast_lint.R_AST_COMPAT)


def test_ast_catches_host_entropy_in_traced_module():
    src = (
        "import numpy as np\n"
        "def encode(x):\n"
        "    noise = np.random.normal(size=x.shape)\n"
        "    return x + noise\n"
    )
    _only(lint_source(src, "deepreduce_tpu/codecs/fake.py"), ast_lint.R_AST_ENTROPY)


def test_ast_catches_time_in_traced_module():
    src = "import time\n\ndef encode(x):\n    return x * time.time()\n"
    _only(lint_source(src, "deepreduce_tpu/sparse.py"), ast_lint.R_AST_ENTROPY)


def test_ast_catches_python_branch_on_traced_value():
    src = (
        "import jax.numpy as jnp\n"
        "def decode(x):\n"
        "    if jnp.max(x) > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    _only(lint_source(src, "deepreduce_tpu/codecs/fake.py"), ast_lint.R_AST_BRANCH)


def test_ast_catches_span_in_codec_module():
    src = (
        "from deepreduce_tpu.telemetry import spans\n"
        "def encode(x):\n"
        "    with spans.span('encode/inner'):\n"
        "        return x * 2\n"
    )
    _only(lint_source(src, "deepreduce_tpu/codecs/fake.py"), ast_lint.R_AST_SPAN)
    # the identical source is fine in the communicator layer — spans belong
    # around traced regions, not inside them
    assert lint_source(src, "deepreduce_tpu/comm.py") == []


def test_ast_catches_dump_logger_in_codec_module():
    src = (
        "from somewhere import DumpLogger\n"
        "def decode(p):\n"
        "    DumpLogger('decode').write(p)\n"
        "    return p\n"
    )
    violations = lint_source(src, "deepreduce_tpu/codecs/fake.py")
    assert violations, "DumpLogger construction in codecs/ must be flagged"
    assert all(v.rule == ast_lint.R_AST_SPAN for v in violations)


def test_ast_span_rule_ignores_local_variable_named_span():
    # codecs/polyseg.py uses `span` as a local float — assignments and
    # arithmetic on a name are not telemetry calls
    src = "def fit(lo, hi):\n    span = hi - lo\n    return span / 2\n"
    assert lint_source(src, "deepreduce_tpu/codecs/fake.py") == []


def test_ast_rules_scope_correctly():
    # host entropy is fine in untraced tooling; compat module may import
    # shard_map directly (it IS the shim)
    src = "import time\nt = time.time()\n"
    assert lint_source(src, "deepreduce_tpu/tracking.py") == []
    src = "from jax.experimental.shard_map import shard_map\n"
    assert lint_source(src, "deepreduce_tpu/utils/compat.py") == []


# ---------------------------------------------------------------------- #
# jaxpr negative fixtures — each rule fires, alone, with its own id
# ---------------------------------------------------------------------- #


def test_f64_mini_codec_caught():
    """A deliberately-f64 'codec': accumulate in double, cast back."""
    from jax.experimental import enable_x64

    def bad_encode(x):
        return (x.astype(jnp.float64) * 2.0).astype(jnp.float32)

    with enable_x64():
        closed = jax.make_jaxpr(bad_encode)(jax.ShapeDtypeStruct((64,), jnp.float32))
    # the f64 *presence* rule catches the values; jx-dtype-flow catches the
    # promotion that minted them — one planted fixture, two distinct stories
    viols = run_rules(closed, AuditContext(label="fixture:f64"))
    assert {v.rule for v in viols} == {rules.R_F64, rules.R_DTYPE_FLOW}, [
        v.to_dict() for v in viols
    ]
    v = next(v for v in viols if v.rule == rules.R_F64)
    assert "float64" in v.detail


def test_unsorted_budget_gather_caught():
    """Sorted indices whose gather doesn't carry the promise."""
    k = 64

    def bad_read(flat, idxs):
        idxs = jnp.sort(idxs)
        return flat[idxs]  # budget-scale gather, indices_are_sorted lost

    closed = jax.make_jaxpr(bad_read)(
        jax.ShapeDtypeStruct((1024,), jnp.float32),
        jax.ShapeDtypeStruct((k,), jnp.int32),
    )
    ctx = AuditContext(label="fixture:unsorted", budget_scale=k)
    _only(run_rules(closed, ctx), rules.R_UNSORTED_BUDGET_GATHER)


def test_two_collective_fused_exchange_caught():
    """A 'fused' exchange that issues two all_gathers breaks the
    one-collective-per-step contract."""
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()

    def spmd(x):
        a = jax.lax.all_gather(x[0], AXIS)
        b = jax.lax.all_gather(x[0] * 2.0, AXIS)
        return (a + b).sum(axis=0)[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 128), jnp.float32))
    ctx = AuditContext(
        label="fixture:two-collectives", expect_collectives={"all_gather": 1}
    )
    v = _only(run_rules(closed, ctx), rules.R_COLLECTIVE_COUNT)
    assert "all_gather" in v.detail


def test_per_axis_collective_inventory_caught():
    """The per-axis form of jx-collective-count: a psum that rides the dcn
    axis when the contract puts it on ici is caught, and so is any
    collective on an axis the contract does not mention."""
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.analysis.jaxpr_audit import audit_hier_mesh

    mesh = audit_hier_mesh(2, 4)

    def spmd(x):
        # slice reduction on the WRONG axis (dcn instead of ici), plus the
        # gather the contract expects on dcn
        m = jax.lax.psum(x[0], "dcn") / 2.0
        return jax.lax.all_gather(m, "dcn").sum(axis=0)[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(("dcn", "ici")),),
                   out_specs=P(("dcn", "ici")), check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 128), jnp.float32))
    ctx = AuditContext(
        label="fixture:axis-swap",
        expect_collectives_by_axis={
            "ici": {"psum": 1}, "dcn": {"all_gather": 1},
        },
    )
    v = _only(run_rules(closed, ctx), rules.R_COLLECTIVE_COUNT)
    assert "ici/psum" in v.detail and "dcn/psum" in v.detail

    # an axis the contract does not mention is itself a violation
    ctx2 = AuditContext(
        label="fixture:unmentioned-axis",
        expect_collectives_by_axis={"ici": {"psum": 1, "all_gather": 1}},
    )
    v2 = _only(run_rules(closed, ctx2), rules.R_COLLECTIVE_COUNT)
    assert "does not mention" in v2.detail


def test_gather_in_mod_query_caught():
    def bad_query(words, idxs):
        return words[idxs]  # a gather in what must be a broadcast path

    closed = jax.make_jaxpr(bad_query)(
        jax.ShapeDtypeStruct((256,), jnp.uint32),
        jax.ShapeDtypeStruct((16,), jnp.int32),
    )
    ctx = AuditContext(label="fixture:mod-gather", forbid_gather=True)
    _only(run_rules(closed, ctx), rules.R_GATHER_IN_MOD_QUERY)


def test_unwhitelisted_callback_caught():
    def bad(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    closed = jax.make_jaxpr(bad)(jax.ShapeDtypeStruct((32,), jnp.float32))
    _only(
        run_rules(closed, AuditContext(label="fixture:callback")),
        rules.R_CALLBACK,
    )
    # the same trace is fine for a whitelisted host codec
    ok = run_rules(closed, AuditContext(label="fixture:host", allow_callbacks=True))
    assert ok == []


def test_wire_accounting_mismatch_caught():
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()
    d = 128

    def spmd(x):
        return jax.lax.all_gather(x[0], AXIS).sum(axis=0)[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, d), jnp.float32))
    good = AuditContext(label="fixture:wire-ok", wire_mode="allgather",
                        expected_wire_bytes=4 * d)
    assert run_rules(closed, good) == []
    bad = AuditContext(label="fixture:wire-bad", wire_mode="allgather",
                       expected_wire_bytes=4 * d + 1)
    _only(run_rules(closed, bad), rules.R_WIRE_ACCOUNTING)


def test_wire_accounting_collective_mode():
    """The r11 'collective' wire mode sums operand bytes over EVERY
    collective eqn (a psum'd sketch + a gathered payload here), so routes
    whose wire story spans multiple collective shapes get exact
    accounting; one byte of drift is a violation."""
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()
    rows_cols, d = (5, 64), 128

    def spmd(x):
        sk = jax.lax.psum(x[0, : rows_cols[0] * rows_cols[1]], AXIS)
        out = jax.lax.all_gather(x[0, : d // 2], AXIS)
        return (sk.sum() + out.sum())[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, d * 4), jnp.float32))
    want = 4 * rows_cols[0] * rows_cols[1] + 4 * (d // 2)
    good = AuditContext(label="fixture:coll-ok", wire_mode="collective",
                        expected_wire_bytes=want)
    assert run_rules(closed, good) == []
    bad = AuditContext(label="fixture:coll-bad", wire_mode="collective",
                       expected_wire_bytes=want + 1)
    _only(run_rules(closed, bad), rules.R_WIRE_ACCOUNTING)


def test_codec_invocation_count_caught():
    """A 'bucketed' exchange that runs a per-leaf top-k breaks the
    O(buckets) codec contract — the count of selection eqns is the proxy."""
    k = 16

    def per_leaf_select(a, b):
        va, _ = jax.lax.top_k(a, k)
        vb, _ = jax.lax.top_k(b, k)
        return va.sum() + vb.sum()

    closed = jax.make_jaxpr(per_leaf_select)(
        jax.ShapeDtypeStruct((256,), jnp.float32),
        jax.ShapeDtypeStruct((128,), jnp.float32),
    )
    good = AuditContext(label="fixture:codec-ok", expect_codec_invocations=2)
    assert run_rules(closed, good) == []
    bad = AuditContext(label="fixture:codec-bad", expect_codec_invocations=1)
    v = _only(run_rules(closed, bad), rules.R_CODEC_COUNT)
    assert "2" in v.detail


def test_retrace_hash_stable():
    """Two traces of the same codec program hash identically — the guard
    that trips means every step would recompile."""
    rec = trace_and_check(
        "retrace-probe",
        lambda x: x * 2.0,
        (jax.ShapeDtypeStruct((64,), jnp.float32),),
        AuditContext(label="retrace-probe"),
    )
    assert rec.violations == []
    assert len(rec.jaxpr_hash) == 16


# ---------------------------------------------------------------------- #
# CLI gate
# ---------------------------------------------------------------------- #


def test_cli_exit_codes(monkeypatch, tmp_path):
    """`python -m deepreduce_tpu.analysis` exits 0 clean, 1 on violations."""
    from deepreduce_tpu.analysis import __main__ as cli
    from deepreduce_tpu.analysis import ast_lint as al
    from deepreduce_tpu.analysis import jaxpr_audit as ja

    monkeypatch.setattr(ja, "audit_all", lambda quick=False: ([], []))
    monkeypatch.setattr(al, "lint_repo", lambda root=None: [])
    out = tmp_path / "report.json"
    assert cli.main(["--quick", "--out", str(out)]) == 0
    assert out.exists()

    bad = rules.Violation("ast-compat-route", "x.py:1", "fixture")
    monkeypatch.setattr(al, "lint_repo", lambda root=None: [bad])
    assert cli.main(["--quick", "--out", "-"]) == 1


# ---------------------------------------------------------------------- #
# config satellites
# ---------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "field,value",
    [
        ("compressor", "topkk"),
        ("communicator", "allgater"),
        ("memory", "residuals"),
        ("deepreduce", "indices"),
        ("policy", "left"),
        ("index", "bloomfilter"),
        ("value", "polyfit2"),
        ("bloom_blocked", "modulo"),
    ],
)
def test_config_rejects_typos(field, value):
    with pytest.raises(ValueError, match=field):
        DeepReduceConfig(**{field: value})


def test_config_enums_match_registry():
    """The documented enumerations stay in lock-step with the codec
    registry — adding a codec without teaching config (or vice versa) is a
    test failure, not a latent KeyError."""
    from deepreduce_tpu.codecs import registry

    assert set(DeepReduceConfig.INDEX_CODECS) == set(registry.INDEX_CODECS)
    assert set(DeepReduceConfig.VALUE_CODECS) == set(registry.VALUE_CODECS)


def test_from_params_strict():
    params = {"compressor": "topk", "compress_ratio": 0.05}
    assert from_params(params, strict=True).compress_ratio == 0.05
    bad = {"compres_ratio": 0.05, "deepreduce": "index"}
    assert from_params(bad).compress_ratio == 0.01  # lenient: silently dropped
    with pytest.raises(ValueError, match="compres_ratio"):
        from_params(bad, strict=True)
    # reference-spelled aliases still map in strict mode
    cfg = from_params({"threshold": 0.5, "micro-benchmark": True}, strict=True)
    assert cfg.threshold_val == 0.5 and cfg.micro_benchmark


# ---------------------------------------------------------------------- #
# dataflow negative fixtures — each SPMD rule fires, alone, with its id
# ---------------------------------------------------------------------- #


def test_collective_under_cond_caught():
    """A collective nested under a data-dependent lax.cond deadlocks the
    moment workers disagree on the predicate — caught statically."""
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()

    def spmd(x):
        def yes(v):
            return jax.lax.psum(v, AXIS)

        def no(v):
            return v

        out = jax.lax.cond(x[0, 0] > 0.0, yes, no, x[0])
        return out[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 64), jnp.float32))
    v = _only(
        run_rules(closed, AuditContext(label="fixture:cond-collective")),
        rules.R_COLLECTIVE_SCHEDULE,
    )
    assert "cond" in v.detail


def test_collective_in_scan_is_legal():
    """The ring decode's per-step ppermute lives inside a fori_loop (a
    scan with a FIXED trip count) — that is schedulable and must NOT trip
    the rule; only data-dependent branching is a deadlock hazard."""
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()

    def spmd(x):
        def body(i, acc):
            return acc + jax.lax.ppermute(
                x[0], AXIS, [(j, (j + 1) % 8) for j in range(8)]
            )

        return jax.lax.fori_loop(0, 4, body, jnp.zeros_like(x[0]))[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 64), jnp.float32))
    assert run_rules(closed, AuditContext(label="fixture:scan-ok")) == []


def test_broken_token_chain_caught():
    """A 'streaming' exchange whose all_gather is not pinned between
    optimization_barriers can be hoisted by XLA to a bulk tail — the
    barrier census (2 per bucket) and dominance check catch it."""
    from jax.sharding import PartitionSpec as P

    mesh = audit_mesh()

    def spmd(x):
        return jax.lax.all_gather(x[0], AXIS).sum(axis=0)[None]

    fn = shard_map(spmd, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 64), jnp.float32))
    ctx = AuditContext(label="fixture:no-tokens", expect_stream_buckets=1)
    _only(run_rules(closed, ctx), rules.R_TOKEN_DOMINANCE)


def test_read_after_donation_caught():
    """An equation consuming a donated buffer after its aliased output is
    live reads freed memory under XLA aliasing."""

    @jax.jit
    def inner(x):
        return x * 2.0

    donating = jax.jit(lambda x: x + 1.0, donate_argnums=0)

    def bad(x):
        y = donating(x)
        return y + x  # x was donated to `y` — stale read

    closed = jax.make_jaxpr(bad)(jax.ShapeDtypeStruct((64,), jnp.float32))
    _only(
        run_rules(closed, AuditContext(label="fixture:donation")),
        rules.R_DONATION,
    )

    def ok(x):
        z = inner(x) + x  # reads BEFORE the donating call
        return donating(x) + z.sum()

    closed_ok = jax.make_jaxpr(ok)(jax.ShapeDtypeStruct((64,), jnp.float32))
    assert run_rules(closed_ok, AuditContext(label="fixture:donation-ok")) == []


def test_reused_prng_key_caught():
    """Two draws from one fold signature produce correlated 'noise' —
    silent statistical corruption, caught by signature collision."""

    def bad(key):
        k = jax.random.fold_in(key, 7)
        return jax.random.normal(k, (3,)) + jax.random.normal(k, (3,))

    closed = jax.make_jaxpr(bad)(jax.random.PRNGKey(0))
    ctx = AuditContext(label="fixture:key-reuse", require_key_lineage=True)
    v = _only(run_rules(closed, ctx), rules.R_KEY_LINEAGE)
    assert "share one fold signature" in v.detail


def test_unfolded_key_draw_caught():
    """A draw straight from the step key (no worker/tensor fold) gives
    every worker identical 'noise' — the per-trace fold discipline."""

    def bad(key):
        return jax.random.normal(key, (3,))

    closed = jax.make_jaxpr(bad)(jax.random.PRNGKey(0))
    ctx = AuditContext(label="fixture:unfolded", require_key_lineage=True)
    v = _only(run_rules(closed, ctx), rules.R_KEY_LINEAGE)
    assert "never passed through fold_in" in v.detail

    def ok(key):
        k1 = jax.random.fold_in(jax.random.fold_in(key, 0), 1)
        k2 = jax.random.fold_in(jax.random.fold_in(key, 0), 2)
        ka, kb = jax.random.split(jax.random.fold_in(key, 9))
        return (jax.random.normal(k1, (3,)) + jax.random.normal(k2, (3,))
                + jax.random.normal(ka, (3,)) + jax.random.normal(kb, (3,)))

    closed_ok = jax.make_jaxpr(ok)(jax.random.PRNGKey(0))
    assert run_rules(closed_ok, AuditContext(
        label="fixture:folds-ok", require_key_lineage=True)) == []


def test_key_lineage_armed_per_trace():
    """Codec unit audits legitimately receive raw keys — the rule is off
    unless the harness arms it."""

    def raw_draw(key):
        return jax.random.normal(key, (3,))

    closed = jax.make_jaxpr(raw_draw)(jax.random.PRNGKey(0))
    assert run_rules(closed, AuditContext(label="fixture:unarmed")) == []


# ---------------------------------------------------------------------- #
# rule registry + CLI surface
# ---------------------------------------------------------------------- #


def test_rule_descriptions_cover_every_rule():
    """--list prints one line per rule; a new rule without a description
    (or a stale description for a removed rule) fails here."""
    assert set(rules.RULE_DESCRIPTIONS) == set(rules.ALL_RULE_IDS)
    assert all(rules.RULE_DESCRIPTIONS[r] for r in rules.ALL_RULE_IDS)


def test_cli_list_and_only(monkeypatch, capsys):
    from deepreduce_tpu.analysis import __main__ as cli
    from deepreduce_tpu.analysis import ast_lint as al
    from deepreduce_tpu.analysis import jaxpr_audit as ja

    assert cli.main(["list"]) == 0
    out = capsys.readouterr().out
    for rule in rules.ALL_RULE_IDS:
        assert rule in out

    # --only gates the exit code on the named rules without shrinking the
    # audit: a violation outside the gate still prints in the report but
    # exits 0; inside the gate it exits 1
    bad = rules.Violation(rules.R_F64, "fixture", "f64 fixture")
    monkeypatch.setattr(ja, "audit_all", lambda quick=False: ([], [bad]))
    monkeypatch.setattr(al, "lint_repo", lambda root=None: [])
    assert cli.main(["audit", "--quick", "--out", "-",
                     "--only", rules.R_CALLBACK]) == 0
    assert cli.main(["audit", "--quick", "--out", "-",
                     "--only", f"{rules.R_F64},{rules.R_CALLBACK}"]) == 1

    with pytest.raises(SystemExit):
        cli.main(["audit", "--only", "jx-not-a-rule", "--out", "-"])


# ---------------------------------------------------------------------- #
# the composition-lattice legality matrix
# ---------------------------------------------------------------------- #


def _repo_root():
    from pathlib import Path

    return Path(__file__).resolve().parents[1]


def test_matrix_schema_and_codes_registered():
    """The committed MATRIX.json is schema-tagged, covers the full lattice,
    and every rejection carries a reason code registered in config."""
    from deepreduce_tpu.analysis import lattice
    from deepreduce_tpu.config import REASON_CODES

    report = lattice.load_report(_repo_root() / "MATRIX.json")
    assert len(report["cells"]) == lattice.n_cells()
    assert report["axes"] == [[n, list(v)] for n, v in lattice.AXES]
    for entry in report["entries"]:
        if entry["status"] == "rejected":
            assert entry["reason_code"], entry
            assert entry["reason_code"] in REASON_CODES, entry
        else:
            assert entry["trace"] in report["traces"]
    assert report["summary"]["violations"] == 0


def test_analysis_json_schema_tagged():
    from deepreduce_tpu.analysis import lattice

    report = lattice.load_report(_repo_root() / "ANALYSIS.json")
    assert report["jaxpr_audit"]["traces"]


def test_load_report_rejects_foreign_schema(tmp_path):
    import json as _json

    from deepreduce_tpu.analysis import lattice

    p = tmp_path / "stale.json"
    p.write_text(_json.dumps({"schema": "other/v0"}))
    with pytest.raises(ValueError, match="schema"):
        lattice.load_report(p)
    p.write_text(_json.dumps({"cells": []}))  # untagged pre-schema report
    with pytest.raises(ValueError, match="schema"):
        lattice.load_report(p)


def test_config_partition_matches_committed_matrix():
    """The config-stage legality surface, re-derived in-process cell by
    cell (no tracing — cheap), must agree with the committed MATRIX.json:
    same partition, same reason code, for every one of the 7680 cells."""
    from deepreduce_tpu.analysis import lattice

    report = lattice.load_report(_repo_root() / "MATRIX.json")
    entries = report["entries"]
    for cell, idx in zip(lattice.iter_cells(), report["cells"]):
        committed = entries[idx]
        part = lattice.probe_partition(cell)
        slug = lattice._cell_slug(cell)
        if committed["status"] == "rejected" and committed["stage"] == "config":
            assert part[0] == "rejected", slug
            assert part[3] == committed["reason_code"], slug
        else:
            # legal cells and build-stage rejections both pass config
            assert part[0] == "legal", (slug, part)


def test_every_config_rejection_carries_reason_code():
    """Any ValueError out of DeepReduceConfig construction — across the
    whole lattice AND the typo guards — carries a registered reason_code:
    nothing is refused with prose only."""
    from deepreduce_tpu.analysis import lattice
    from deepreduce_tpu.config import REASON_CODES, reason_code_of

    seen = set()
    for cell in lattice.iter_cells():
        try:
            DeepReduceConfig(**lattice.cell_kwargs(cell))
        except ValueError as e:
            code = reason_code_of(e)
            assert code is not None, lattice._cell_slug(cell)
            assert code in REASON_CODES, code
            seen.add(code)
    # the committed matrix's code set is exactly what the lattice produces
    # at config stage plus the recorded build-stage codes
    report = lattice.load_report(_repo_root() / "MATRIX.json")
    build_codes = {
        e["reason_code"]
        for e in report["entries"]
        if e["status"] == "rejected" and e["stage"] == "build"
    }
    assert seen | build_codes == set(report["summary"]["reason_codes"])

    with pytest.raises(ValueError) as ei:
        DeepReduceConfig(compressor="topkk")
    assert reason_code_of(ei.value) in REASON_CODES


def test_trace_fingerprint_strips_host_side_knobs():
    """ctrl/telemetry are host-side (the audited off-identity contract):
    cells differing only by them share one memoized trace."""
    from deepreduce_tpu.analysis import lattice

    base = dict(communicator="allgather", decode="loop", buckets="off",
                stream="off", rs_mode="sparse", hier="off", resilience="off",
                ctrl="off", fed="off", fed_async="off", fed_mt="off",
                population="off")
    on = dict(base, ctrl="on")
    fp_off = lattice.trace_fingerprint(lattice.cell_kwargs(base), "flat")
    fp_on = lattice.trace_fingerprint(lattice.cell_kwargs(on), "flat")
    assert fp_off == fp_on
    # but a knob that DOES reach the trace splits the fingerprint
    ring = dict(base, decode="ring")
    assert lattice.trace_fingerprint(lattice.cell_kwargs(ring), "flat") != fp_off


def test_matrix_cli_drift_detection(monkeypatch, tmp_path):
    """`analysis matrix` exits 0 against a faithful baseline, 1 when a
    cell's legality, reason code, or trace hash drifts — without re-probing
    the lattice (build_matrix is stubbed with the committed report)."""
    import copy
    import json as _json

    from deepreduce_tpu.analysis import __main__ as cli
    from deepreduce_tpu.analysis import lattice

    committed = lattice.load_report(_repo_root() / "MATRIX.json")
    monkeypatch.setattr(
        lattice,
        "build_matrix",
        lambda progress=None, stats=None: copy.deepcopy(committed),
    )
    baseline = tmp_path / "MATRIX.json"
    lattice.write_matrix(committed, baseline)
    assert cli.main(["matrix", "--out", str(baseline)]) == 0

    # drift one rejected cell's reason code in the baseline
    drifted = copy.deepcopy(committed)
    for e in drifted["entries"]:
        if e["status"] == "rejected":
            e["reason_code"] = "f64-requires-opt-in"
            break
    lattice.write_matrix(drifted, baseline)
    assert cli.main(["matrix", "--out", str(baseline)]) == 1

    # a missing baseline bootstraps (exit 0) and writes the file
    fresh = tmp_path / "bootstrap.json"
    assert cli.main(["matrix", "--out", str(fresh)]) == 0
    assert _json.loads(fresh.read_text())["schema"] == lattice.SCHEMA


@pytest.mark.slow
def test_full_matrix_regenerates_without_drift():
    """The heavyweight gate: re-probe the whole lattice (config + build +
    trace of every legal cell) and diff against the committed artifact."""
    from deepreduce_tpu.analysis import lattice

    report = lattice.build_matrix()
    assert report["violations"] == [], report["violations"][:5]
    baseline = lattice.load_report(_repo_root() / "MATRIX.json")
    assert lattice.compare_matrix(baseline, report) == []
