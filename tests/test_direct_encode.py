"""Sparsifier-free bloom encode (bloom.encode_dense_direct + wrapper routing).

The direct path composes the sampled-threshold selection with the
scatter-free threshold insert so no top-k is ever materialized; these tests
pin the invariants that make it wire-compatible with the standard path:
FP-aware values (every decoded value is the true dense value at its
position), the exact fallback when the sample sees only zeros, the static
small-tensor fallback, and the wrapper's static routing predicate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu.codecs import bloom
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.wrappers import TensorCodec


def _meta(d, k, fpr=0.02):
    return bloom.BloomMeta.create(
        k, d, fpr=fpr, policy="p0", blocked="mod", threshold_insert=True
    )


class TestEncodeDenseDirect:
    def test_fp_aware_roundtrip(self):
        """Every decoded nonzero equals the dense tensor at that position,
        and the captured set covers ~undershoot*k of the top magnitudes."""
        d, k = 60_000, 3_000
        rng = np.random.default_rng(0)
        g = jnp.asarray((rng.normal(size=d) * rng.random(d) ** 2).astype(np.float32))
        meta = _meta(d, k)
        pay = jax.jit(
            lambda t: bloom.encode_dense_direct(t, meta, sample_size=4096)
        )(g)
        nsel = int(pay.nsel)
        assert 0 < nsel <= meta.budget
        dec = bloom.decode_dense(pay, meta, (d,))
        dec = np.asarray(dec)
        gnp = np.asarray(g)
        sel = np.nonzero(dec)[0]
        np.testing.assert_array_equal(dec[sel], gnp[sel])
        # the selection is a threshold set: it contains the very largest
        # magnitudes (the top 10% of k can't be missed by a 4096-sample
        # quantile at undershoot 0.9)
        top = np.argsort(-np.abs(gnp))[: k // 10]
        assert np.isin(top, sel).all()

    def test_zero_threshold_falls_back_to_exact(self):
        """Mass the sample's stride can't see -> t == 0 -> exact top-k
        branch; the support is fully recovered."""
        d, k = 50_000, 2_500
        g = np.zeros(d, np.float32)
        g[:10] = np.arange(1, 11, dtype=np.float32)  # all mass in 10 slots
        meta = _meta(d, k)
        pay = jax.jit(
            lambda t: bloom.encode_dense_direct(t, meta, sample_size=4096)
        )(jnp.asarray(g))
        dec = np.asarray(bloom.decode_dense(pay, meta, (d,)))
        np.testing.assert_array_equal(dec, g)

    def test_small_tensor_static_exact(self):
        d, k = 4_000, 200
        rng = np.random.default_rng(1)
        g = jnp.asarray(rng.normal(size=d).astype(np.float32))
        meta = _meta(d, k)
        pay = bloom.encode_dense_direct(g, meta, sample_size=4096)
        dec = np.asarray(bloom.decode_dense(pay, meta, (d,)))
        gnp = np.asarray(g)
        sel = np.nonzero(dec)[0]
        np.testing.assert_array_equal(dec[sel], gnp[sel])
        # exact static path: the top-k set itself is selected (plus FPs)
        top = np.argsort(-np.abs(gnp))[: k // 2]
        assert np.isin(top, sel).all()

    def test_small_tensor_bitwise_matches_standard_encode(self):
        """On the static exact path the direct encode must be BIT-IDENTICAL
        to the standard encode fed the exact top-k: same inserted set, same
        filter words, same FP-aware value stream — the wire-compatibility
        contract _fp_aware_payload exists to enforce."""
        from deepreduce_tpu import sparse

        d, k = 4_000, 200
        rng = np.random.default_rng(4)
        g = jnp.asarray(rng.normal(size=d).astype(np.float32))
        meta = _meta(d, k)
        direct = bloom.encode_dense_direct(g, meta, sample_size=4096)
        sp = sparse.topk(g, k / d)
        std = bloom.encode(sp, g, meta)
        np.testing.assert_array_equal(np.asarray(direct.words), np.asarray(std.words))
        np.testing.assert_array_equal(np.asarray(direct.values), np.asarray(std.values))
        assert int(direct.nsel) == int(std.nsel)

    def test_small_tensor_bitwise_matches_threshold_insert_encode(self):
        """Companion to the scatter-insert comparison above: the PRODUCTION
        standard path under this config runs threshold_insert=True
        (insert_from_dense), whose inserted set is {|g| >= t} — on a
        TIE-FREE input that set equals the exact top-k set, so the two
        encodes must be bit-identical there too (ties are the only benign
        divergence between the inserts; ADVICE.md round-5 item 3)."""
        from deepreduce_tpu import sparse

        d, k = 4_000, 200
        rng = np.random.default_rng(5)
        # tie-free by construction: distinct magnitudes everywhere
        mags = np.argsort(rng.permutation(d)).astype(np.float32) + 1.0
        g = jnp.asarray(np.where(rng.random(d) < 0.5, mags, -mags) / d)
        assert np.unique(np.abs(np.asarray(g))).size == d  # no magnitude ties
        meta = _meta(d, k)
        direct = bloom.encode_dense_direct(g, meta, sample_size=4096)
        sp = sparse.topk(g, k / d)
        std = bloom.encode(sp, g, meta, threshold_insert=True)
        np.testing.assert_array_equal(np.asarray(direct.words), np.asarray(std.words))
        np.testing.assert_array_equal(np.asarray(direct.values), np.asarray(std.values))
        assert int(direct.nsel) == int(std.nsel)

    def test_layout_and_policy_guards(self):
        m_hash = bloom.BloomMeta.create(100, 10_000, policy="p0", blocked="hash")
        with pytest.raises(ValueError, match="mod"):
            bloom.encode_dense_direct(jnp.zeros(10_000), m_hash)
        m_rand = bloom.BloomMeta.create(
            100, 10_000, policy="random", blocked="mod"
        )
        with pytest.raises(ValueError, match="prefix"):
            bloom.encode_dense_direct(jnp.zeros(10_000), m_rand)


class TestWrapperRouting:
    CFG = dict(
        compressor="topk_sampled",
        compress_ratio=0.05,
        deepreduce="index",
        index="bloom",
        policy="p0",
        fpr=0.02,
        bloom_blocked="mod",
        bloom_threshold_insert=True,
        topk_sample_size=4096,
    )

    def test_predicate_and_roundtrip(self):
        d = 60_000
        cfg = DeepReduceConfig(**self.CFG)
        codec = TensorCodec((d,), cfg, name="t")
        assert codec.direct_bloom
        rng = np.random.default_rng(2)
        g = jnp.asarray((rng.normal(size=d) * rng.random(d) ** 2).astype(np.float32))
        pay = jax.jit(lambda t: codec.encode(t, step=0))(g)
        dec = np.asarray(jax.jit(lambda p: codec.decode(p, step=0))(pay, ))
        gnp = np.asarray(g)
        sel = np.nonzero(dec)[0]
        np.testing.assert_array_equal(dec[sel], gnp[sel])
        # wire accounting identical to the standard bloom path
        stats = codec.wire_stats(pay)
        assert float(stats.rel_volume()) < 0.25

    def test_both_mode_routes_direct(self):
        d = 60_000
        cfg = DeepReduceConfig(
            **{**self.CFG, "deepreduce": "both", "value": "qsgd"}
        )
        codec = TensorCodec((d,), cfg, name="t")
        assert codec.direct_bloom
        rng = np.random.default_rng(3)
        g = jnp.asarray((rng.normal(size=d) * rng.random(d) ** 2).astype(np.float32))
        pay = jax.jit(lambda t: codec.encode(t, step=0))(g)
        dec = np.asarray(jax.jit(lambda p: codec.decode(p, step=0))(pay))
        gnp = np.asarray(g)
        sel = np.nonzero(dec)[0]
        assert sel.size > 0
        # QSGD is lossy: decoded values approximate the true ones
        err = np.abs(dec[sel] - gnp[sel]) / (np.abs(gnp[sel]).max() + 1e-12)
        assert float(err.max()) < 0.2

    def test_predicate_off_without_flag(self):
        cfg = DeepReduceConfig(**{**self.CFG, "bloom_threshold_insert": False})
        codec = TensorCodec((60_000,), cfg, name="t")
        assert not codec.direct_bloom
        cfg2 = DeepReduceConfig(**{**self.CFG, "compressor": "topk"})
        codec2 = TensorCodec((60_000,), cfg2, name="t")
        assert not codec2.direct_bloom
