"""Multi-tenant federated serving (fedsim fed_tenants): bitwise T=1
degeneracy against the single-tenant driver (sync AND async planes),
heterogeneous per-tenant knobs through the one compiled tick, tenant
join/leave without retracing, mid-fill multi-tenant checkpoint resume with
the tenant-geometry fail-fast, and the multi-tenant cost model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepreduce_tpu import checkpoint
from deepreduce_tpu.config import ConfigError, DeepReduceConfig, reason_code_of
from deepreduce_tpu.fedsim import FedSim, synthetic_linear_problem

DIM, BATCH, LOCAL = 16, 4, 2


def _cfg(**kw):
    base = dict(
        deepreduce="index",
        index="bloom",
        bloom_blocked="mod",
        compress_ratio=0.25,
        fpr=0.01,
        memory="residual",
        min_compress_size=8,
    )
    base.update(kw)
    return DeepReduceConfig(**base)


def _fed_kw(**kw):
    base = dict(fed=True, fed_num_clients=64, fed_clients_per_round=16,
                fed_local_steps=LOCAL)
    base.update(kw)
    return base


def _async_kw(**kw):
    base = _fed_kw(fed_async=True, fed_async_k=40, fed_async_alpha=0.5,
                   fed_async_latency="0.5,0.3,0.2")
    base.update(kw)
    return base


def _driver(cfg, mesh, chunk=2):
    params0, data_fn, loss_fn = synthetic_linear_problem(DIM, BATCH, LOCAL)
    fs = FedSim(loss_fn, cfg, cfg.fed_config(), optax.sgd(0.1), data_fn,
                mesh=mesh, client_chunk=chunk)
    return fs, fs.init(params0)


def _leaves_equal(a, b):
    return all(
        bool(jnp.all(x == y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


def _tenant(tree, t):
    """Slice tenant t's plane out of a stacked multi-tenant pytree."""
    return jax.tree_util.tree_map(lambda x: x[t], tree)


# ---------------------------------------------------------------------- #
# T=1 degeneracy: the multi-tenant tick IS the single-tenant round
# ---------------------------------------------------------------------- #


def test_mt_t1_degenerate_sync(mesh8):
    """fed_tenants=1 on the synchronous plane is the single-tenant round,
    bitwise: tenant 0 replays the exact PRNG stream (the tenant-0 key is
    the undevided round key), so params AND the residual bank agree to the
    byte after several rounds."""
    key = jax.random.PRNGKey(0)
    fs_s, st_s = _driver(_cfg(**_fed_kw()), mesh8)
    fs_m, st_m = _driver(_cfg(**_fed_kw(fed_tenants=1)), mesh8)
    for r in range(3):
        k = jax.random.fold_in(key, r)
        st_s, m_s = fs_s.step(st_s, k)
        st_m, m_m = fs_m.step(st_m, k)
    assert _leaves_equal(st_s.params, _tenant(st_m.params, 0))
    assert _leaves_equal(st_s.w_ref, _tenant(st_m.w_ref, 0))
    assert _leaves_equal(st_s.residuals, _tenant(st_m.residuals, 0))
    assert float(np.asarray(m_m["clients"]).reshape(-1)[0]) == float(
        m_s["clients"]
    )


def test_mt_t1_degenerate_async(mesh8):
    """fed_tenants=1 on the async plane: the buffered ingest tick with the
    fed_async_* knobs broadcast to the one tenant lands bitwise on the
    single-tenant async driver — params, residual bank, AND every
    aggregation-buffer leaf (fill, staleness counters, w_hist ring)."""
    key = jax.random.PRNGKey(0)
    fs_a, st_a = _driver(_cfg(**_async_kw()), mesh8)
    fs_m, st_m = _driver(_cfg(**_async_kw(fed_tenants=1)), mesh8)
    for r in range(4):
        k = jax.random.fold_in(key, r)
        st_a, _ = fs_a.step(st_a, k)
        st_m, _ = fs_m.step(st_m, k)
    assert _leaves_equal(st_a.params, _tenant(st_m.params, 0))
    assert _leaves_equal(st_a.residuals, _tenant(st_m.residuals, 0))
    for sa, sm in zip(
        jax.tree_util.tree_leaves(st_a.buffer),
        jax.tree_util.tree_leaves(_tenant(st_m.buffer, 0)),
    ):
        assert bool(jnp.all(sa == sm))


# ---------------------------------------------------------------------- #
# heterogeneous fleet through ONE compiled program
# ---------------------------------------------------------------------- #


def test_mt_heterogeneous_knobs(mesh8):
    """Per-tenant K/alpha/latency ride as traced operands of the shared
    tick: a zero-latency tenant accrues zero staleness while its neighbor
    (drawing from a 3-level distribution) does not, and per-tenant K sets
    distinct apply cadences — all without a second compiled program."""
    cfg = _cfg(**_async_kw(
        fed_tenants=2, fed_mt_k="16,40", fed_mt_alpha="0,0.5",
        fed_mt_latency="1;0.5,0.3,0.2",
    ))
    key = jax.random.PRNGKey(0)
    fs, st = _driver(cfg, mesh8)
    applied, stale = [], []
    for r in range(6):
        st, m = fs.step(st, jax.random.fold_in(key, r))
        applied.append(np.asarray(m["applied"], dtype=np.float64))
        stale.append(np.asarray(m["staleness_mean"], dtype=np.float64))
    # the zero-latency tenant never goes stale; its neighbor does
    assert all(s[0] == 0.0 for s in stale)
    assert max(s[1] for s in stale) > 0.0
    # K=16 == cohort: tenant 0 applies every tick; K=40: ticks 2, 5, ...
    assert [a[0] for a in applied] == [1.0] * 6
    assert [a[1] for a in applied] == [0.0, 0.0, 1.0, 0.0, 0.0, 1.0]


def test_mt_join_leave_freeze_no_retrace(mesh8):
    """Flipping the active-slot mask is a traced operand: an inactive
    tenant's whole state (params, bank, buffer) freezes bitwise, and the
    flip adds ZERO new jit cache entries (no retrace)."""
    cfg = _cfg(**_async_kw(fed_tenants=2))
    key = jax.random.PRNGKey(1)
    fs, st = _driver(cfg, mesh8)
    for r in range(2):
        st, _ = fs.step(st, jax.random.fold_in(key, r))
    steady_cache = fs._round._cache_size()
    frozen_params = _tenant(st.params, 1)
    frozen_buf = _tenant(st.buffer, 1)
    st = fs.set_active(st, [True, False])
    for r in range(2, 4):
        st, m = fs.step(st, jax.random.fold_in(key, r))
        # a parked slot serves nobody
        assert float(np.asarray(m["clients"])[1]) == 0.0
    assert _leaves_equal(frozen_params, _tenant(st.params, 1))
    assert _leaves_equal(frozen_buf, _tenant(st.buffer, 1))
    # the active tenant kept moving
    assert not _leaves_equal(_tenant(st.params, 0), _tenant(st.params, 1))
    st = fs.set_active(st, [True, True])
    st, _ = fs.step(st, jax.random.fold_in(key, 4))
    assert fs._round._cache_size() == steady_cache


# ---------------------------------------------------------------------- #
# mid-fill checkpoint kill/resume + tenant-geometry fail-fast
# ---------------------------------------------------------------------- #


def test_mt_midfill_bitwise_resume(mesh8, tmp_path):
    """Kill/resume with the tenants' buffers at DIFFERENT fill levels:
    restoring into a fresh driver and replaying the remaining ticks lands
    bitwise on the uninterrupted run — params, bank, and both tenants'
    aggregation buffers. A checkpoint stamped for T=2 must fail fast
    against a T=3 config instead of shape-erroring mid-restore."""
    cfg = _cfg(**_async_kw(fed_tenants=2, fed_mt_k="24,56"))
    key = jax.random.PRNGKey(0)
    ck = str(tmp_path / "ckpt")
    fs, st = _driver(cfg, mesh8)
    save_at = None
    for r in range(6):
        st, _ = fs.step(st, jax.random.fold_in(key, r))
        fills = np.asarray(st.buffer.count, dtype=np.float64)
        stales = np.asarray(st.buffer.stale_sum, dtype=np.float64)
        if save_at is None and fills.min() > 0 and stales.max() > 0 \
                and len(set(fills.tolist())) > 1:
            save_at = r + 1
            checkpoint.save(ck, st, config=cfg)
    assert save_at is not None and save_at < 6  # genuinely mid-fill, mid-run

    fs2, template = _driver(cfg, mesh8)
    st2 = checkpoint.restore(ck, template, config=cfg)
    fills = np.asarray(st2.buffer.count, dtype=np.float64)
    assert fills.min() > 0 and len(set(fills.tolist())) > 1
    for r in range(save_at, 6):
        st2, _ = fs2.step(st2, jax.random.fold_in(key, r))
    assert _leaves_equal(st.params, st2.params)
    assert _leaves_equal(st.residuals, st2.residuals)
    assert _leaves_equal(st.buffer, st2.buffer)

    cfg_bad = _cfg(**_async_kw(fed_tenants=3, fed_mt_k="24,56,56"))
    fs3, template3 = _driver(cfg_bad, mesh8)
    with pytest.raises(ValueError, match="tenant-geometry"):
        checkpoint.restore(ck, template3, config=cfg_bad)


# ---------------------------------------------------------------------- #
# config surface
# ---------------------------------------------------------------------- #


def test_fed_mt_config_validation():
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(fed_tenants=-1))
    assert reason_code_of(ei.value) == "fed-mt-tenants-range"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_async_kw(fed_mt_k="16,40"))
    assert reason_code_of(ei.value) == "fed-mt-knobs-disengaged"
    with pytest.raises(ConfigError) as ei:
        _cfg(fed_tenants=2)
    assert reason_code_of(ei.value) == "fed-mt-needs-fed"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_fed_kw(fed_tenants=2, fed_mt_k="16,40"))
    assert reason_code_of(ei.value) == "fed-mt-async-knobs"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_async_kw(fed_tenants=2, fed_mt_k="16,nope"))
    assert reason_code_of(ei.value) == "fed-mt-k-syntax"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_async_kw(fed_tenants=2, fed_mt_alpha="0.5,-1"))
    assert reason_code_of(ei.value) == "fed-mt-alpha-syntax"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_async_kw(fed_tenants=2, fed_mt_latency="0.5,0.5;oops"))
    assert reason_code_of(ei.value) == "fed-mt-latency-syntax"
    with pytest.raises(ConfigError) as ei:
        _cfg(**_async_kw(fed_tenants=2, fed_mt_cohort="16,0"))
    assert reason_code_of(ei.value) == "fed-mt-cohort-syntax"
    # a valid heterogeneous fleet constructs
    cfg = _cfg(**_async_kw(fed_tenants=2, fed_mt_k="16,40",
                           fed_mt_alpha="0,0.5",
                           fed_mt_latency="1;0.5,0.3,0.2",
                           fed_mt_cohort="16,8"))
    assert cfg.fed_tenants == 2


# ---------------------------------------------------------------------- #
# multi-tenant cost model
# ---------------------------------------------------------------------- #


def test_costmodel_fed_mt_t1_exact():
    """T=1 collapses EXACTLY (same float expressions, not approximately)
    onto the single-tenant models, sync and async."""
    from deepreduce_tpu import costmodel as cm

    assert cm.fed_mt_clients_per_sec(
        1, 1000.0, 100, t_client_s=0.5
    ) == cm.fed_clients_per_sec(1000.0, 100, t_client_s=0.5)
    assert cm.fed_mt_clients_per_sec(
        1, 1000.0, 100, asynchronous=True, t_client_s=0.5,
        overlap_depth=4, latency_probs=(0.5, 0.3, 0.2),
    ) == cm.fed_async_clients_per_sec(
        1000.0, 100, t_client_s=0.5, overlap_depth=4,
        latency_probs=(0.5, 0.3, 0.2),
    )


def test_costmodel_fed_mt_monotone():
    """While client compute dominates, aggregate service rate grows with
    tenant count (shared tick, no per-tenant collective tax) on both
    planes; once the serialized server link saturates, adding tenants is
    free but not faster. Per-tenant lists are length-validated."""
    from deepreduce_tpu import costmodel as cm

    rates = [
        cm.fed_mt_clients_per_sec(
            T, 1000.0, 100, asynchronous=True, t_client_s=10.0
        )
        for T in (1, 2, 4, 8)
    ]
    assert all(b > a for a, b in zip(rates, rates[1:]))
    # near-linear while compute-bound
    assert rates[2] / rates[0] > 3.0
    # ingest-bound limit: the shared link caps the aggregate (flat, never
    # decreasing)
    wire_bound = [
        cm.fed_mt_clients_per_sec(T, 1000.0, 100, asynchronous=True)
        for T in (1, 2, 4)
    ]
    assert wire_bound[0] == pytest.approx(wire_bound[-1])
    sync_rates = [
        cm.fed_mt_clients_per_sec(T, 1000.0, 100, t_client_s=10.0)
        for T in (1, 2, 4)
    ]
    assert all(b > a for a, b in zip(sync_rates, sync_rates[1:]))
    # heterogeneous per-tenant lists must match T
    with pytest.raises(ValueError, match="per-tenant"):
        cm.fed_mt_clients_per_sec(3, 1000.0, [100, 50], asynchronous=True)
