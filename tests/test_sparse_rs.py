"""Sparse reduce-scatter + allgather communicator (sparse_rs.py — the
Ok-Topk/SparCML collective shape, PAPERS.md) on the 8-device virtual mesh:
oracle exactness when budgets are ample, graceful truncation + error
feedback when they are not, trainer integration, wire accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import shared_mesh
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepreduce_tpu import sparse, sparse_rs
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig

W = 8


def _mesh():
    return shared_mesh(W)


def _run(flat_w, ratio, headroom, out_headroom=1.0):
    """flat_w: [W, d] per-worker gradients -> (mean, own[W,d], stats)."""
    d = flat_w.shape[1]

    def spmd(g):
        g = g[0]
        mean, own, stats = sparse_rs.exchange(
            g, "data", W, ratio=ratio,
            headroom=headroom, out_headroom=out_headroom,
        )
        return mean[None], own[None], stats

    fn = jax.jit(
        shard_map(
            spmd, mesh=_mesh(), in_specs=(P("data"),),
            out_specs=(P("data"), P("data"), P()),
            check_vma=False,
        )
    )
    return fn(flat_w)


def _oracle_mean_of_topk(flat_w, ratio):
    """Mean over workers of each worker's exact top-k scatter (the
    allgather path's semantics, before any sharded re-selection)."""
    out = np.zeros(flat_w.shape[1], np.float64)
    for w in range(flat_w.shape[0]):
        sp = sparse.topk(jnp.asarray(flat_w[w]), ratio)
        n = int(sp.nnz)
        out[np.asarray(sp.indices)[:n]] += np.asarray(sp.values)[:n]
    return (out / flat_w.shape[0]).astype(np.float32)


def test_exact_when_budgets_ample():
    """With generous headroom and every surviving entry refitting phase 2,
    the result equals the mean-of-topk-scatters oracle exactly."""
    rng = np.random.default_rng(0)
    d, ratio = 4096, 0.02
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    # ample: phase-1 budget >> k/W AND phase-2 slots cover the union of
    # all workers' selections — the exchange must then be lossless
    mean, own, stats = _run(
        jnp.asarray(flat_w), ratio, headroom=float(W), out_headroom=2.0 * W
    )
    want = _oracle_mean_of_topk(flat_w, ratio)
    got = np.asarray(mean)[0]
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_default_output_volume_keeps_largest():
    """At the default Ok-Topk volume convention (output == k entries, W*k
    gathered by allgather), phase 2 keeps per-shard largest — every kept
    position is exact and dropped positions are only ever smaller-|v| than
    the kept ones within their shard."""
    rng = np.random.default_rng(7)
    d, ratio = 4096, 0.02
    S = sparse_rs.shard_size(d, W)
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, _, _ = _run(jnp.asarray(flat_w), ratio, headroom=float(W))
    want = _oracle_mean_of_topk(flat_w, ratio)
    got = np.asarray(mean)[0]
    kept = np.nonzero(got)[0]
    np.testing.assert_allclose(got[kept], want[kept], rtol=1e-6)
    for p in range(W):
        lo, hi = p * S, min((p + 1) * S, d)
        kept_p = kept[(kept >= lo) & (kept < hi)]
        if len(kept_p) == 0:
            continue
        dropped = np.setdiff1d(np.nonzero(want[lo:hi])[0] + lo, kept_p)
        if len(dropped):
            assert np.abs(want[dropped]).max() <= np.abs(want[kept_p]).min() + 1e-6


def test_identical_workers_exact():
    """All workers hold the same gradient: the union of selections is just
    the global top-k, so with phase-2 slots covering each shard's occupancy
    (top-k coords are not perfectly balanced across shards — hence the
    modest out-headroom) the output IS the top-k scatter of the shared
    gradient."""
    rng = np.random.default_rng(1)
    d, ratio = 4096, 0.02
    g = rng.normal(size=d).astype(np.float32)
    flat_w = np.tile(g, (W, 1))
    mean, _, _ = _run(jnp.asarray(flat_w), ratio, headroom=float(W), out_headroom=2.0)
    got = np.asarray(mean)[0]
    sp = sparse.topk(jnp.asarray(g), ratio)
    n = int(sp.nnz)
    want = np.zeros(d, np.float32)
    want[np.asarray(sp.indices)[:n]] = np.asarray(sp.values)[:n]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_own_mass_reflects_phase1_truncation():
    """own (the EF reference) contains exactly the entries that fit the
    phase-1 budget: with tiny headroom, strictly less than the full top-k
    mass; untransmitted mass must be the largest-|v|-truncated remainder."""
    rng = np.random.default_rng(2)
    d, ratio = 4096, 0.05
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    _, own_full, _ = _run(jnp.asarray(flat_w), ratio, headroom=float(W))
    _, own_tight, _ = _run(jnp.asarray(flat_w), ratio, headroom=1.0)
    full = np.abs(np.asarray(own_full)).sum()
    tight = np.abs(np.asarray(own_tight)).sum()
    assert tight < full
    assert tight > 0.5 * full  # headroom 1.0 still carries most mass


def test_trainer_path_and_wire_accounting():
    """Full GradientExchanger round with residual EF: volume well under
    dense, residual captures untransmitted mass, repeated steps shrink a
    constant gradient's residual (EF re-sends)."""
    rng = np.random.default_rng(3)
    d = 8192
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, memory="residual",
        communicator="sparse_rs", deepreduce=None, rs_headroom=2.0,
    )
    grads = {"g": jnp.asarray(rng.normal(size=d).astype(np.float32))}
    ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=W)
    state = ex.init_state(grads)

    def spmd(g, res):
        agg, new_res, stats = ex.exchange(
            g, res, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(0)
        )
        return agg, new_res, stats

    fn = jax.jit(
        shard_map(
            spmd, mesh=_mesh(), in_specs=(P(), P()), out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )
    agg, new_state, stats = fn(grads, state)
    vol = float(stats.rel_volume())
    assert 0 < vol < 0.5
    assert np.isfinite(np.asarray(agg["g"])).all()
    res = np.asarray(jax.tree_util.tree_leaves(new_state)[0])
    assert np.abs(res).sum() > 0  # truncated mass retained
    # per-worker wire bytes accounting exists and is under dense
    assert 0 < ex.payload_bytes(grads) < d * 4


def test_rejects_codec_stack():
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, communicator="sparse_rs",
        deepreduce="both", index="bloom", value="qsgd",
    )
    with pytest.raises(ValueError, match="sparse_rs"):
        GradientExchanger(
            {"g": jnp.zeros((4096,), jnp.float32)}, cfg,
            axis_name="data", num_workers=W,
        )


def test_phase1_overflow_drops_smallest_magnitude():
    """With headroom forcing overflow in one crowded shard, the entries
    that DO get transmitted must be that shard's largest magnitudes —
    the Ok-Topk overflow property (depends on unsorted top_k order)."""
    d, ratio = 4096, 0.05  # k=205
    S = sparse_rs.shard_size(d, W)
    g = np.zeros(d, np.float32)
    # all top-k mass in shard 0: magnitudes 205..1 at positions 0..204,
    # with the LARGEST magnitudes at the HIGHEST indices (adversarial for
    # any index-ordered truncation)
    k = sparse.num_slots(d, ratio)
    g[:k] = np.arange(1, k + 1, dtype=np.float32)
    flat_w = np.tile(g, (W, 1))
    _, own, _ = _run(jnp.asarray(flat_w), ratio, headroom=1.0 / W)
    own0 = np.asarray(own)[0]
    B = sparse_rs.send_budget(d, ratio, W, 1.0 / W)
    sent = np.nonzero(own0)[0]
    assert len(sent) == B  # exactly the budget went out
    # the B sent entries are the B largest magnitudes (highest positions)
    np.testing.assert_array_equal(np.sort(sent), np.arange(k - B, k))
