"""Sparse reduce-scatter + allgather communicator (sparse_rs.py — the
Ok-Topk/SparCML collective shape, PAPERS.md) on the 8-device virtual mesh:
oracle exactness when budgets are ample, graceful truncation + error
feedback when they are not, trainer integration, wire accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import shared_mesh
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepreduce_tpu import sparse, sparse_rs
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig

W = 8


def _mesh():
    return shared_mesh(W)


def _run(flat_w, ratio, headroom, out_headroom=1.0):
    """flat_w: [W, d] per-worker gradients -> (mean, own[W,d], stats)."""
    d = flat_w.shape[1]

    def spmd(g):
        g = g[0]
        mean, own, stats = sparse_rs.exchange(
            g, "data", W, ratio=ratio,
            headroom=headroom, out_headroom=out_headroom,
        )
        return mean[None], own[None], stats

    fn = jax.jit(
        shard_map(
            spmd, mesh=_mesh(), in_specs=(P("data"),),
            out_specs=(P("data"), P("data"), P()),
            check_vma=False,
        )
    )
    return fn(flat_w)


def _oracle_mean_of_topk(flat_w, ratio):
    """Mean over workers of each worker's exact top-k scatter (the
    allgather path's semantics, before any sharded re-selection)."""
    out = np.zeros(flat_w.shape[1], np.float64)
    for w in range(flat_w.shape[0]):
        sp = sparse.topk(jnp.asarray(flat_w[w]), ratio)
        n = int(sp.nnz)
        out[np.asarray(sp.indices)[:n]] += np.asarray(sp.values)[:n]
    return (out / flat_w.shape[0]).astype(np.float32)


def test_exact_when_budgets_ample():
    """With generous headroom and every surviving entry refitting phase 2,
    the result equals the mean-of-topk-scatters oracle exactly."""
    rng = np.random.default_rng(0)
    d, ratio = 4096, 0.02
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    # ample: phase-1 budget >> k/W AND phase-2 slots cover the union of
    # all workers' selections — the exchange must then be lossless
    mean, own, stats = _run(
        jnp.asarray(flat_w), ratio, headroom=float(W), out_headroom=2.0 * W
    )
    want = _oracle_mean_of_topk(flat_w, ratio)
    got = np.asarray(mean)[0]
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_default_output_volume_keeps_largest():
    """At the default Ok-Topk volume convention (output == k entries, W*k
    gathered by allgather), phase 2 keeps per-shard largest — every kept
    position is exact and dropped positions are only ever smaller-|v| than
    the kept ones within their shard."""
    rng = np.random.default_rng(7)
    d, ratio = 4096, 0.02
    S = sparse_rs.shard_size(d, W)
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, _, _ = _run(jnp.asarray(flat_w), ratio, headroom=float(W))
    want = _oracle_mean_of_topk(flat_w, ratio)
    got = np.asarray(mean)[0]
    kept = np.nonzero(got)[0]
    np.testing.assert_allclose(got[kept], want[kept], rtol=1e-6)
    for p in range(W):
        lo, hi = p * S, min((p + 1) * S, d)
        kept_p = kept[(kept >= lo) & (kept < hi)]
        if len(kept_p) == 0:
            continue
        dropped = np.setdiff1d(np.nonzero(want[lo:hi])[0] + lo, kept_p)
        if len(dropped):
            assert np.abs(want[dropped]).max() <= np.abs(want[kept_p]).min() + 1e-6


def test_identical_workers_exact():
    """All workers hold the same gradient: the union of selections is just
    the global top-k, so with phase-2 slots covering each shard's occupancy
    (top-k coords are not perfectly balanced across shards — hence the
    modest out-headroom) the output IS the top-k scatter of the shared
    gradient."""
    rng = np.random.default_rng(1)
    d, ratio = 4096, 0.02
    g = rng.normal(size=d).astype(np.float32)
    flat_w = np.tile(g, (W, 1))
    mean, _, _ = _run(jnp.asarray(flat_w), ratio, headroom=float(W), out_headroom=2.0)
    got = np.asarray(mean)[0]
    sp = sparse.topk(jnp.asarray(g), ratio)
    n = int(sp.nnz)
    want = np.zeros(d, np.float32)
    want[np.asarray(sp.indices)[:n]] = np.asarray(sp.values)[:n]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_own_mass_reflects_phase1_truncation():
    """own (the EF reference) contains exactly the entries that fit the
    phase-1 budget: with tiny headroom, strictly less than the full top-k
    mass; untransmitted mass must be the largest-|v|-truncated remainder."""
    rng = np.random.default_rng(2)
    d, ratio = 4096, 0.05
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    _, own_full, _ = _run(jnp.asarray(flat_w), ratio, headroom=float(W))
    _, own_tight, _ = _run(jnp.asarray(flat_w), ratio, headroom=1.0)
    full = np.abs(np.asarray(own_full)).sum()
    tight = np.abs(np.asarray(own_tight)).sum()
    assert tight < full
    assert tight > 0.5 * full  # headroom 1.0 still carries most mass


def test_trainer_path_and_wire_accounting():
    """Full GradientExchanger round with residual EF: volume well under
    dense, residual captures untransmitted mass, repeated steps shrink a
    constant gradient's residual (EF re-sends)."""
    rng = np.random.default_rng(3)
    d = 8192
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, memory="residual",
        communicator="sparse_rs", deepreduce=None, rs_headroom=2.0,
    )
    grads = {"g": jnp.asarray(rng.normal(size=d).astype(np.float32))}
    ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=W)
    state = ex.init_state(grads)

    def spmd(g, res):
        agg, new_res, stats = ex.exchange(
            g, res, step=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(0)
        )
        return agg, new_res, stats

    fn = jax.jit(
        shard_map(
            spmd, mesh=_mesh(), in_specs=(P(), P()), out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )
    agg, new_state, stats = fn(grads, state)
    vol = float(stats.rel_volume())
    assert 0 < vol < 0.5
    assert np.isfinite(np.asarray(agg["g"])).all()
    res = np.asarray(jax.tree_util.tree_leaves(new_state)[0])
    assert np.abs(res).sum() > 0  # truncated mass retained
    # per-worker wire bytes accounting exists and is under dense
    assert 0 < ex.payload_bytes(grads) < d * 4


def test_rejects_codec_stack():
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, communicator="sparse_rs",
        deepreduce="both", index="bloom", value="qsgd",
    )
    with pytest.raises(ValueError, match="sparse_rs"):
        GradientExchanger(
            {"g": jnp.zeros((4096,), jnp.float32)}, cfg,
            axis_name="data", num_workers=W,
        )


def test_phase1_overflow_drops_smallest_magnitude():
    """With headroom forcing overflow in one crowded shard, the entries
    that DO get transmitted must be that shard's largest magnitudes —
    the Ok-Topk overflow property (depends on unsorted top_k order)."""
    d, ratio = 4096, 0.05  # k=205
    S = sparse_rs.shard_size(d, W)
    g = np.zeros(d, np.float32)
    # all top-k mass in shard 0: magnitudes 205..1 at positions 0..204,
    # with the LARGEST magnitudes at the HIGHEST indices (adversarial for
    # any index-ordered truncation)
    k = sparse.num_slots(d, ratio)
    g[:k] = np.arange(1, k + 1, dtype=np.float32)
    flat_w = np.tile(g, (W, 1))
    _, own, _ = _run(jnp.asarray(flat_w), ratio, headroom=1.0 / W)
    own0 = np.asarray(own)[0]
    B = sparse_rs.send_budget(d, ratio, W, 1.0 / W)
    sent = np.nonzero(own0)[0]
    assert len(sent) == B  # exactly the budget went out
    # the B sent entries are the B largest magnitudes (highest positions)
    np.testing.assert_array_equal(np.sort(sent), np.arange(k - B, k))


# --------------------------------------------------------------------- #
# r11: edge-case geometry (W=2, unaligned d, capped out budget)
# --------------------------------------------------------------------- #


def _run_mode(flat_w, ratio, mode, *, workers=W, headroom=2.0,
              out_headroom=1.0, density_threshold=1.0, with_collect=False,
              **kw):
    """Generic runner for any rs_mode on a `workers`-wide mesh."""
    key = jax.random.PRNGKey(0)

    def spmd(g):
        collect = {} if with_collect else None
        mean, own, stats = sparse_rs.exchange(
            g[0], "data", workers, ratio=ratio, rs_mode=mode,
            headroom=headroom, out_headroom=out_headroom,
            density_threshold=density_threshold,
            key=(key if mode in ("adaptive", "quantized") else None),
            collect=collect, **kw,
        )
        if with_collect:
            return (mean[None], own[None],
                    collect["rs_density"][None], collect["rs_dense_switches"][None])
        return mean[None], own[None]

    out_specs = (
        (P("data"), P("data"), P("data"), P("data")) if with_collect
        else (P("data"), P("data"))
    )
    fn = jax.jit(
        shard_map(
            spmd, mesh=shared_mesh(workers), in_specs=(P("data"),),
            out_specs=out_specs, check_vma=False,
        )
    )
    return fn(flat_w)


def test_w2_mesh_exact_with_ample_budgets():
    """The smallest real mesh (W=2): ample budgets must still be lossless
    against the mean-of-topk oracle — shard routing with exactly one peer."""
    rng = np.random.default_rng(10)
    W2, d, ratio = 2, 4096, 0.02
    flat_w = rng.normal(size=(W2, d)).astype(np.float32)
    mean, _ = _run_mode(
        jnp.asarray(flat_w), ratio, "sparse", workers=W2,
        headroom=float(W2), out_headroom=2.0 * W2,
    )
    want = _oracle_mean_of_topk(flat_w, ratio)
    np.testing.assert_allclose(np.asarray(mean)[0], want, rtol=1e-6, atol=1e-7)


def test_unaligned_d_padded_tail_exact():
    """d not divisible by W: the last shard is short, phase-2 top_k can pick
    zero-padding positions whose global index lands past d — the clipped
    scatter plus [:d] slice must keep the result exact (padding carries
    value 0.0, so even the clip target accumulates nothing)."""
    rng = np.random.default_rng(11)
    d, ratio = 4090, 0.02  # W*S = 4096 > d: 6-element padded tail
    assert d % W != 0
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, _ = _run_mode(
        jnp.asarray(flat_w), ratio, "sparse",
        headroom=float(W), out_headroom=2.0 * W,
    )
    want = _oracle_mean_of_topk(flat_w, ratio)
    np.testing.assert_allclose(np.asarray(mean)[0], want, rtol=1e-6, atol=1e-7)


def test_out_budget_hits_shard_size_cap():
    """A ratio/headroom combination whose phase-2 budget exceeds the shard
    size must clamp to it (a shard cannot emit more entries than it has) —
    and the clamped exchange stays exact when phase-1 budgets are ample."""
    d, ratio, oh = 4096, 0.5, 4.0
    S = sparse_rs.shard_size(d, W)
    assert sparse_rs.out_budget(d, ratio, W, oh) == S  # the cap engaged
    rng = np.random.default_rng(12)
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, _ = _run_mode(
        jnp.asarray(flat_w), ratio, "sparse", headroom=float(W), out_headroom=oh,
    )
    want = _oracle_mean_of_topk(flat_w, ratio)
    np.testing.assert_allclose(np.asarray(mean)[0], want, rtol=1e-6, atol=1e-7)


# --------------------------------------------------------------------- #
# r11: the in-collective routes (rs_mode = adaptive / quantized / sketch)
# --------------------------------------------------------------------- #


def test_adaptive_equals_sparse_below_threshold():
    """The numerical contract of the density switch: at the default
    threshold (1.0 — strict compare, density <= 1.0 never exceeds it) the
    adaptive route must produce the SAME mean and own-transmitted arrays
    as the always-sparse route, bit for bit."""
    rng = np.random.default_rng(13)
    d, ratio = 4096, 0.02
    flat_w = jnp.asarray(rng.normal(size=(W, d)).astype(np.float32))
    mean_s, own_s = _run_mode(flat_w, ratio, "sparse")
    mean_a, own_a = _run_mode(flat_w, ratio, "adaptive")
    np.testing.assert_array_equal(np.asarray(mean_s), np.asarray(mean_a))
    np.testing.assert_array_equal(np.asarray(own_s), np.asarray(own_a))


def test_adaptive_dense_switch_correctness_and_observables():
    """threshold=0.0 forces every worker's phase-2 row dense: the whole
    reduced shard travels int8-quantized, so the result must match the
    UNtruncated phase-1 oracle (no top-K2 loss) within one quantization
    step per block — and the collect dict must report the switch."""
    rng = np.random.default_rng(14)
    d, ratio, block = 4096, 0.02, 256
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, _, density, switches = _run_mode(
        jnp.asarray(flat_w), ratio, "adaptive", headroom=float(W),
        density_threshold=0.0, with_collect=True,
    )
    got = np.asarray(mean)[0]
    want = _oracle_mean_of_topk(flat_w, ratio)  # ample headroom: no truncation
    # per-element quantization tolerance: one step = ||block||_2 / 127 of
    # the SUMMED shard (= W * want), divided back by W
    blk = (want * W).reshape(-1, block)
    tol = np.repeat(np.linalg.norm(blk, axis=1) / 127.0, block) / W
    assert np.all(np.abs(got - want) <= tol + 1e-6)
    # every worker saw a live shard and switched dense
    assert np.all(np.asarray(switches) == 1.0)
    dens = np.asarray(density)
    assert np.all(dens > 0.0) and np.all(dens <= 1.0)


def test_quantized_mode_error_bounded_by_shared_norms():
    """The quantized reduce-scatter arm: no sparsifier in phase 1, so on
    its output support the mean must equal the TRUE dense mean within one
    stochastic-rounding step against the pmax-shared block norms
    (levels bounded by 127//W make the int8 psum_scatter sum exact, so
    quantization is the only error source)."""
    rng = np.random.default_rng(15)
    d, ratio, block = 4096, 0.05, 256
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mean, own = _run_mode(
        jnp.asarray(flat_w), ratio, "quantized", block_size=block,
    )
    got = np.asarray(mean)[0]
    assert np.allclose(np.asarray(mean), got[None])  # workers agree
    truth = flat_w.mean(axis=0)
    q = sparse_rs.quantized_levels_budget(W)
    # shared scale per block: max over workers of the local block L2 norm;
    # per-worker rounding error <= norm/q, summed over W then /W
    norms = np.linalg.norm(flat_w.reshape(W, -1, block), axis=2).max(axis=0)
    tol = np.repeat(norms / q, block)
    support = np.nonzero(got)[0]
    assert support.size > 0
    assert np.all(np.abs(got[support] - truth[support]) <= tol[support] + 1e-6)
    assert np.isfinite(np.asarray(own)).all()


def test_sketch_mode_recovers_signal_and_feeds_back_own_estimate():
    """Count-sketch route on identical workers: the psum'd sketch is W x
    one worker's sketch (linearity), so the decoded mean is the unsketch
    of a single worker's selection — bounded collision noise — and the
    own-transmitted EF estimate must agree with the decoded mean on its
    support (own = unsketch of MY sketch at the same indices)."""
    rng = np.random.default_rng(16)
    d, ratio = 4096, 0.01
    g = np.zeros(d, np.float32)
    k = sparse.num_slots(d, ratio)
    hot = rng.choice(d, size=k, replace=False)
    g[hot] = (rng.normal(size=k) + np.sign(rng.normal(size=k)) * 3.0).astype(
        np.float32
    )
    flat_w = np.tile(g, (W, 1))
    # collision noise scales as ~‖v‖₂/√C per query, so size the table well
    # above k (C ≫ k) and give phase 2 headroom for per-shard hot-count
    # variance — the default C targets wire volume, not exact recovery
    mean, own = _run_mode(
        jnp.asarray(flat_w), ratio, "sketch", out_headroom=2.0,
        sketch_cols=2048,
    )
    got = np.asarray(mean)[0]
    own0 = np.asarray(own)[0]
    assert np.allclose(np.asarray(mean), got[None])  # workers agree
    # aggregate signal recovery: collision noise well under the signal
    rel = np.linalg.norm(got - g * (got != 0)) / np.linalg.norm(g[hot])
    assert rel < 0.25, rel
    # EF contract: own == mean on the transmitted support (identical
    # workers: unsketch(psum)/W == unsketch(own sketch), both linear)
    support = np.nonzero(got)[0]
    np.testing.assert_allclose(own0[support], got[support], rtol=1e-4, atol=1e-5)


def test_exchange_rejects_unknown_and_unresolved_mode():
    flat = jnp.zeros((64,), jnp.float32)
    for mode in ("auto", "bogus"):
        with pytest.raises(ValueError, match="rs_mode"):
            sparse_rs.exchange(flat, "data", W, ratio=0.1, rs_mode=mode)
    for mode in ("adaptive", "quantized"):
        with pytest.raises(ValueError, match="PRNG key"):
            sparse_rs.exchange(flat, "data", W, ratio=0.1, rs_mode=mode)


# --------------------------------------------------------------------- #
# r11: config plumbing + auto selection
# --------------------------------------------------------------------- #


def _rs_cfg(**kw):
    return DeepReduceConfig(
        compressor="topk", compress_ratio=0.03, memory="none",
        communicator="sparse_rs", deepreduce=None, **kw,
    )


def test_config_validates_rs_fields():
    for mode in ("adaptive", "quantized", "sketch", "oktopk", "auto"):
        assert _rs_cfg(rs_mode=mode).rs_mode == mode
    with pytest.raises(ValueError, match="rs_mode"):
        _rs_cfg(rs_mode="bogus")
    # a non-default rs_mode on a non-sparse_rs communicator would be
    # silently ignored — must fail loudly instead
    with pytest.raises(ValueError, match="sparse_rs"):
        DeepReduceConfig(rs_mode="sketch")
    with pytest.raises(ValueError, match="multiple of 4"):
        _rs_cfg(rs_block_size=6)
    with pytest.raises(ValueError, match="rs_density_threshold"):
        _rs_cfg(rs_density_threshold=1.5)
    with pytest.raises(ValueError, match="rs_sketch_rows"):
        _rs_cfg(rs_sketch_rows=0)


def test_resilience_restriction_documents_shard_ownership():
    """The flat loop-decoded sparse_rs routes (sparse/quantized/oktopk/auto)
    re-own a dropped worker's shards over the live set, so resilience=True
    now constructs there.  Ownership has no re-routing path on qar (the
    mean folds into one int8 psum_scatter with no per-worker decode row)
    or on the adaptive/sketch routes (per-worker wire state) — the config
    must still refuse those and say why."""
    for rs_mode in ("sparse", "quantized", "oktopk", "auto"):
        cfg = DeepReduceConfig(
            compressor="topk", compress_ratio=0.03, memory="none",
            communicator="sparse_rs", rs_mode=rs_mode, deepreduce=None,
            resilience=True,
        )
        assert cfg.resilience
    with pytest.raises(ValueError, match="shard owner"):
        DeepReduceConfig(
            compressor="none", compress_ratio=0.03, memory="none",
            communicator="qar", deepreduce=None, resilience=True,
        )
    for rs_mode in ("adaptive", "sketch"):
        with pytest.raises(ValueError, match="shard owner"):
            DeepReduceConfig(
                compressor="topk", compress_ratio=0.03, memory="none",
                communicator="sparse_rs", rs_mode=rs_mode, deepreduce=None,
                resilience=True,
            )


def test_auto_mode_resolves_via_costmodel():
    from deepreduce_tpu import costmodel

    d = 8192
    cfg = _rs_cfg(rs_mode="auto")
    grads = {"g": jnp.zeros((d,), jnp.float32)}
    ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=W)
    want = costmodel.select_rs_mode(
        d, W, cfg.compress_ratio,
        headroom=cfg.rs_headroom, out_headroom=cfg.rs_out_headroom,
        block=cfg.rs_block_size, rows=cfg.rs_sketch_rows,
        cols=cfg.rs_sketch_cols, bins=cfg.rs_oktopk_bins,
        cap_headroom=cfg.rs_oktopk_cap_headroom,
    )
    assert ex._rs_mode == want
    assert ex._rs_mode in sparse_rs.RS_EXCHANGE_MODES
    # auto without a static worker count cannot price the routes
    with pytest.raises(ValueError, match="num_workers"):
        GradientExchanger(grads, cfg, axis_name="data", num_workers=None)


def test_payload_bytes_matches_costmodel_per_mode():
    from deepreduce_tpu import costmodel

    d = 8192
    grads = {"g": jnp.zeros((d,), jnp.float32)}
    for mode in sparse_rs.RS_EXCHANGE_MODES:
        cfg = _rs_cfg(rs_mode=mode)
        ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=W)
        want = costmodel.rs_payload_bytes(
            mode, d, W, cfg.compress_ratio,
            headroom=cfg.rs_headroom, out_headroom=cfg.rs_out_headroom,
            block=cfg.rs_block_size, rows=cfg.rs_sketch_rows,
            cols=cfg.rs_sketch_cols, bins=cfg.rs_oktopk_bins,
            cap_headroom=cfg.rs_oktopk_cap_headroom,
        )
        assert ex.payload_bytes(grads) == want
        assert 0 < want < 4 * d * 2


def test_trainer_path_quantized_and_sketch_modes():
    """Full GradientExchanger round for the two non-sparse phase-1 routes:
    finite aggregates, volume under dense, EF residual retains mass."""
    rng = np.random.default_rng(17)
    d = 8192
    for mode in ("quantized", "sketch"):
        cfg = DeepReduceConfig(
            compressor="topk", compress_ratio=0.03, memory="residual",
            communicator="sparse_rs", deepreduce=None, rs_mode=mode,
        )
        grads = {"g": jnp.asarray(rng.normal(size=d).astype(np.float32))}
        ex = GradientExchanger(grads, cfg, axis_name="data", num_workers=W)
        state = ex.init_state(grads)

        def spmd(g, res):
            agg, new_res, stats = ex.exchange(
                g, res, step=jnp.zeros((), jnp.int32),
                key=jax.random.PRNGKey(0),
            )
            return agg, new_res, stats

        fn = jax.jit(
            shard_map(
                spmd, mesh=_mesh(), in_specs=(P(), P()),
                out_specs=(P(), P(), P()), check_vma=False,
            )
        )
        agg, new_state, stats = fn(grads, state)
        assert np.isfinite(np.asarray(agg["g"])).all(), mode
        vol = float(stats.rel_volume())
        assert 0 < vol < 1.0, (mode, vol)
        res = np.asarray(jax.tree_util.tree_leaves(new_state)[0])
        assert np.abs(res).sum() > 0, mode


# --------------------------------------------------------------------- #
# resilient routes: live-mask re-ownership of reduce-scatter shards
# --------------------------------------------------------------------- #


def _run_masked(flat_w, ratio, mask, rs_mode="sparse", headroom=2.0,
                out_headroom=1.0, key=None):
    """Masked exchange on the 8-way mesh; mask=None runs the mask-free
    path on the SAME harness (bitwise comparability)."""
    def spmd(g, *m):
        mean, own, stats = sparse_rs.exchange(
            g[0], "data", W, ratio=ratio, headroom=headroom,
            out_headroom=out_headroom, rs_mode=rs_mode, key=key,
            mask=m[0] if m else None,
        )
        return mean[None], own[None], stats

    in_specs = (P("data"),) if mask is None else (P("data"), P())
    fn = jax.jit(
        shard_map(
            spmd, mesh=_mesh(), in_specs=in_specs,
            out_specs=(P("data"), P("data"), P()), check_vma=False,
        )
    )
    args = (flat_w,) if mask is None else (flat_w, jnp.asarray(mask))
    mean, own, stats = fn(*args)
    return np.asarray(mean), np.asarray(own), stats


def test_owner_permutation_identity_and_reroute():
    """All-live is the identity map; a dropped worker's shard goes to the
    live worker at rank (shard mod n_live) of the ascending live set, and
    live workers always keep their own shards."""
    ones = np.asarray(sparse_rs.owner_permutation(jnp.ones(W, bool), W))
    np.testing.assert_array_equal(ones, np.arange(W))
    mask = np.ones(W, bool)
    mask[3] = False
    om = np.asarray(sparse_rs.owner_permutation(jnp.asarray(mask), W))
    live = [0, 1, 2, 4, 5, 6, 7]
    for v in live:
        assert om[v] == v
    assert om[3] == live[3 % len(live)]


@pytest.mark.parametrize("rs_mode", ["sparse", "quantized", "oktopk"])
def test_masked_all_ones_bitwise_identical(rs_mode):
    """mask=ones is the identity: the re-owned route returns bitwise the
    mask-free route's mean AND own-transmitted, every rs_mode."""
    rng = np.random.default_rng(11)
    flat_w = jnp.asarray(rng.normal(size=(W, 4096)).astype(np.float32))
    key = jax.random.PRNGKey(5) if rs_mode == "quantized" else None
    base = _run_masked(flat_w, 0.03, None, rs_mode=rs_mode, key=key)
    ones = _run_masked(
        flat_w, 0.03, np.ones(W, bool), rs_mode=rs_mode, key=key
    )
    np.testing.assert_array_equal(base[0], ones[0])
    np.testing.assert_array_equal(base[1], ones[1])


def test_masked_drop_reowns_shards_exact_oracle():
    """Ample budgets + worker 3 dropped: the masked sparse route equals
    the mean-of-topk oracle over the LIVE workers exactly — including
    coordinates in the dropped worker's shard range, which a deputy now
    owns instead of black-holing (the old shard-ownership fence's failure
    mode), renormalized by the live count."""
    rng = np.random.default_rng(12)
    d, ratio = 4096, 0.02
    flat_w = rng.normal(size=(W, d)).astype(np.float32)
    mask = np.ones(W, bool)
    mask[3] = False
    mean, _, _ = _run_masked(
        jnp.asarray(flat_w), ratio, mask, headroom=float(W),
        out_headroom=2.0 * W,
    )
    want = _oracle_mean_of_topk(flat_w[mask], ratio)
    np.testing.assert_allclose(mean[0], want, rtol=1e-6, atol=1e-7)
    # the dropped worker's shard range is populated by its deputy
    S = sparse_rs.shard_size(d, W)
    assert np.abs(want[3 * S:4 * S]).sum() > 0  # oracle has mass there
    np.testing.assert_allclose(
        mean[0][3 * S:4 * S], want[3 * S:4 * S], rtol=1e-6, atol=1e-7
    )


@pytest.mark.parametrize("rs_mode", ["sparse", "oktopk"])
def test_masked_dropped_owner_transmits_nothing(rs_mode):
    """Transmitted-mass conservation under re-ownership: a dropped
    worker's own-transmitted is exactly zero, so EF keeps its ENTIRE
    compensated gradient in residual (nothing silently lost), while live
    workers still transmit and the mean carries only live mass."""
    rng = np.random.default_rng(13)
    flat_w = jnp.asarray(rng.normal(size=(W, 4096)).astype(np.float32))
    mask = np.ones(W, bool)
    mask[5] = False
    mean, own, _ = _run_masked(flat_w, 0.03, mask, rs_mode=rs_mode)
    np.testing.assert_array_equal(own[5], np.zeros_like(own[5]))
    for v in (0, 1, 4, 7):
        assert np.abs(own[v]).sum() > 0
    assert np.isfinite(mean).all()
