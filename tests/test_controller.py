"""Tier-1 contract for the adaptive compression controller: the ladder
is a bounded set of operating points (one compiled executable per rung
visited, never more), the control law is deterministic with hysteresis,
its state round-trips bitwise through a checkpoint, and turning the
controller OFF leaves every committed ANALYSIS.json trace hash unchanged
(the controller is host-side only — zero traced residue)."""

import json
import pathlib

import pytest

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.controller import (
    CompressionController,
    Ladder,
    validate_decision,
)
from deepreduce_tpu.controller.controller import _zero_fetch

REPO = pathlib.Path(__file__).resolve().parents[1]

FIXED = dict(
    deepreduce="index",
    index="bloom",
    compress_ratio=0.02,
    fpr=0.01,
    memory="residual",
    min_compress_size=100,
)


def _ctrl_cfg(**overrides):
    base = dict(
        telemetry=True,
        ctrl=True,
        ctrl_ladder="0.01,0.02,0.05",
        ctrl_hysteresis=2,
        ctrl_target_err_cos=0.5,
        ctrl_headroom=0.1,
        **FIXED,
    )
    base.update(overrides)
    return DeepReduceConfig(**base)


class _Stream:
    """Synthetic cumulative fetch stream: feed per-window RATES, get the
    running cumulative snapshot `observe` expects."""

    def __init__(self):
        self.cum = _zero_fetch(0)
        self.step = 0

    def window(self, n=5, *, err_cos=0.5, saturated=0.0):
        self.step += n
        self.cum = dict(self.cum)
        self.cum["steps"] += float(n)
        self.cum["err_cos"] += err_cos * n
        self.cum["saturated"] += saturated * n
        self.cum["index_bits"] += 100.0 * n
        self.cum["dense_bits"] += 1000.0 * n
        return self.step, dict(self.cum)


# ---------------------------------------------------------------------- #
# ladder
# ---------------------------------------------------------------------- #


def test_ladder_parse_apply_and_nearest():
    lad = Ladder.parse("0.01,0.02@0.05,0.05")
    assert len(lad) == 3
    assert lad[1].ratio == 0.02 and lad[1].fpr == 0.05
    assert lad[0].fpr is None
    # nearest rung, ties break to the cheaper side
    assert lad.index_near(0.0005) == 0
    assert lad.index_near(0.02) == 1
    assert lad.index_near(0.9) == 2
    cfg = DeepReduceConfig(**FIXED)
    cfg1 = lad.apply(cfg, 1)
    assert cfg1.compress_ratio == 0.02 and cfg1.fpr == 0.05
    cfg0 = lad.apply(cfg, 0)
    assert cfg0.compress_ratio == 0.01 and cfg0.fpr == cfg.fpr  # fpr untouched


@pytest.mark.parametrize(
    "spec",
    ["0.02", "0.05,0.02", "0,0.02", "0.02,1.5", "0.01,0.02@2", "a,b"],
    ids=["single", "decreasing", "zero", "over-one", "bad-fpr", "garbage"],
)
def test_ladder_rejects_bad_specs(spec):
    with pytest.raises(ValueError):
        Ladder.parse(spec)


def test_config_rejects_engaged_ctrl_knobs_without_ctrl():
    with pytest.raises(ValueError):
        DeepReduceConfig(telemetry=True, ctrl_hysteresis=5, **FIXED)
    with pytest.raises(ValueError):  # ctrl needs telemetry
        DeepReduceConfig(ctrl=True, **FIXED)


# ---------------------------------------------------------------------- #
# the control law (host-side, no jax)
# ---------------------------------------------------------------------- #


def test_controller_hysteresis_and_bounds():
    ctrl = CompressionController(_ctrl_cfg())
    assert ctrl.index == 1  # nearest rung to compress_ratio=0.02
    s = _Stream()

    # one low-fidelity window: vote up, but hysteresis=2 holds
    rec = ctrl.observe(*s.window(err_cos=0.2))
    validate_decision(rec)
    assert (rec["trigger"], rec["rationale"]) == ("err_cos_low", "hold_hysteresis")
    # second consecutive low window: move up
    rec = ctrl.observe(*s.window(err_cos=0.2))
    assert rec["switched"] and rec["rationale"] == "move_up"
    assert (rec["old_index"], rec["new_index"]) == (1, 2)
    # two more at the top rung: the ladder is a hard bound
    ctrl.observe(*s.window(err_cos=0.2))
    rec = ctrl.observe(*s.window(err_cos=0.2))
    assert rec["rationale"] == "hold_at_top" and not rec["switched"]

    # an in-band window resets the streak...
    ctrl.observe(*s.window(err_cos=0.95))  # headroom vote (down), streak 1
    rec = ctrl.observe(*s.window(err_cos=0.55))  # in band
    assert rec["rationale"] == "hold_in_band"
    # ...so one more down-vote is NOT enough, two are
    rec = ctrl.observe(*s.window(err_cos=0.95))
    assert rec["rationale"] == "hold_hysteresis"
    rec = ctrl.observe(*s.window(err_cos=0.95))
    assert rec["rationale"] == "move_down"
    assert (rec["old_index"], rec["new_index"]) == (2, 1)

    for r in ctrl.decisions:
        validate_decision(r)
    assert ctrl.switches == 2
    # the rung in effect during each window is the OLD one
    assert ctrl.effective_ratio() == pytest.approx(
        (0.02 * 10 + 0.05 * 30) / 40
    )


def test_controller_saturation_trigger_outranks_headroom():
    cfg = _ctrl_cfg(ctrl_saturation_ceiling=0.5, ctrl_hysteresis=1)
    ctrl = CompressionController(cfg)
    s = _Stream()
    # fidelity says DOWN, saturation says UP — saturation wins
    rec = ctrl.observe(*s.window(err_cos=0.95, saturated=2.0))
    assert rec["trigger"] == "saturation_high" and rec["rationale"] == "move_up"


def test_controller_empty_window_is_a_noop():
    ctrl = CompressionController(_ctrl_cfg())
    s = _Stream()
    step, fetch = s.window(err_cos=0.2)
    assert ctrl.observe(step, fetch) is not None
    # same cumulative snapshot again: zero steps elapsed, no decision
    assert ctrl.observe(step, dict(fetch)) is None
    assert ctrl.windows == 1


def test_controller_state_roundtrip_replays_identically():
    a = CompressionController(_ctrl_cfg())
    b = CompressionController(_ctrl_cfg())
    sa, sb = _Stream(), _Stream()
    for err in (0.2, 0.2, 0.9):
        a.observe(*sa.window(err_cos=err))
        b.observe(*sb.window(err_cos=err))
    restored = CompressionController(_ctrl_cfg())
    restored.load_state_dict(b.state_dict())
    # continue both from the same point: decisions must be byte-identical
    tail_a, tail_r = [], []
    for err in (0.9, 0.9, 0.55, 0.2):
        tail_a.append(a.observe(*sa.window(err_cos=err)))
        tail_r.append(restored.observe(*sb.window(err_cos=err)))
    assert [json.dumps(r, sort_keys=True) for r in tail_a] == [
        json.dumps(r, sort_keys=True) for r in tail_r
    ]
    assert restored.index == a.index and restored.switches == a.switches


# ---------------------------------------------------------------------- #
# 50 adaptive steps on the 8-way mesh: bounded re-jit, end to end
# ---------------------------------------------------------------------- #


def test_adaptive_run_bounded_rejit(tmp_path):
    """The whole tentpole claim in one run: 50 adaptive steps compile
    exactly one step executable per ladder rung VISITED — switching
    operating points re-jits at most len(ladder) times, ever."""
    from deepreduce_tpu.controller.__main__ import _build_cfg, _run_train

    cfg = _build_cfg()
    log = tmp_path / "decisions.jsonl"
    losses, trainer, _ = _run_train(cfg, steps=50, num_workers=8, log_path=log)

    assert all(l == l for l in losses)  # finite
    visited = trainer.visited_ladder_indices
    ladder = trainer.controller.ladder
    # distinct compiled step executables == ladder points visited
    assert len(trainer._step_cache) == len(visited)
    assert 1 <= len(visited) <= len(ladder)
    assert trainer.controller.switches >= 1  # it actually adapted
    # each cached step function compiled exactly once (no silent retraces)
    sizes = [
        fn._cache_size()
        for fn in trainer._step_cache.values()
        if hasattr(fn, "_cache_size")
    ]
    if sizes:
        assert sum(sizes) == len(visited), sizes
    recs = [json.loads(l) for l in log.read_text().splitlines() if l.strip()]
    assert recs and len(recs) == trainer.controller.windows
    for r in recs:
        validate_decision(r)
    assert {r["new_index"] for r in recs} <= set(visited)


# ---------------------------------------------------------------------- #
# ctrl off == committed baseline: every ANALYSIS.json hash unchanged
# ---------------------------------------------------------------------- #


def _committed_hashes():
    traces = json.load(open(REPO / "ANALYSIS.json"))["jaxpr_audit"]["traces"]
    by_label = {}
    for t in traces:
        assert t["label"] not in by_label, f"duplicate label {t['label']}"
        by_label[t["label"]] = t["jaxpr_hash"]
    return by_label


def test_full_audit_matches_committed_hashes():
    """Every committed trace hash — the full pre-controller inventory —
    reproduces bitwise with the controller code in the tree (ctrl=False
    everywhere the audit traces the legacy configs).

    Runs in a SUBPROCESS on purpose: jaxpr string hashes are stable only
    within a fresh interpreter (jax name counters are per-process and the
    committed baseline comes from `python -m deepreduce_tpu.analysis`,
    which audits from a cold start); an in-process audit after other
    tests have traced functions would diff on counter suffixes, not real
    program changes."""
    import subprocess
    import sys

    committed = _committed_hashes()
    out = subprocess.run(
        [
            sys.executable,
            "-c",
            "import json\n"
            "from deepreduce_tpu.analysis.jaxpr_audit import audit_all\n"
            "records, _ = audit_all(quick=False)\n"
            "print(json.dumps({r.label: r.jaxpr_hash for r in records"
            " if not r.skipped}))\n",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    fresh = json.loads(out.stdout.strip().splitlines()[-1])
    missing = sorted(set(committed) - set(fresh))
    assert not missing, f"committed traces no longer audited: {missing}"
    changed = sorted(
        lbl for lbl, h in committed.items() if h and fresh[lbl] != h
    )
    assert not changed, f"committed trace hashes changed: {changed}"
