"""Quantized allreduce (qar.py): int8 reduce-scatter + allgather on the
virtual 8-device mesh — accuracy vs the exact mean, unbiasedness of the
two-phase quantization, wire accounting, and the communicator='qar'
trainer path."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from conftest import shared_mesh
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepreduce_tpu import qar
from deepreduce_tpu.config import DeepReduceConfig

W = 8
D = 6000  # deliberately NOT a multiple of W*bucket


def _mesh():
    return shared_mesh(W)


def _run_qar(grads, key, bucket=512):
    n = qar.pad_len(D, W, bucket)
    padded = np.zeros((W, n), np.float32)
    padded[:, :D] = grads

    def spmd(g):
        return qar.quantized_allreduce(
            g.reshape(n), "data", W, key=key, bucket_size=bucket
        )

    fn = jax.jit(
        shard_map(
            spmd, mesh=_mesh(), in_specs=(P("data"),), out_specs=P("data"),
            check_vma=False,
        )
    )
    out = np.asarray(fn(jnp.asarray(padded))).reshape(W, n)[:, :D]
    return out


def test_qar_close_to_exact_mean():
    rng = np.random.default_rng(0)
    grads = rng.normal(size=(W, D)).astype(np.float32)
    out = _run_qar(grads, jax.random.PRNGKey(3))
    want = grads.mean(axis=0)
    # every worker reconstructs the same mean
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])
    # two-phase 127-level bucket-512 quantization on Gaussian data has
    # ~7.3% relative error per phase (step = ||v||/127 ~ sqrt(512)sigma/127,
    # stochastic-rounding std ~ step/sqrt(6)); two independent phases
    # compose to ~10%. Anything well past that indicates a scale bug.
    rel = np.linalg.norm(out[0] - want) / np.linalg.norm(want)
    assert rel < 0.15, rel


def test_qar_unbiased_over_keys():
    rng = np.random.default_rng(1)
    grads = rng.normal(size=(W, D)).astype(np.float32)
    want = grads.mean(axis=0)
    acc = np.zeros(D, np.float64)
    trials = 12
    for t in range(trials):
        acc += _run_qar(grads, jax.random.PRNGKey(100 + t))[0]
    est = acc / trials
    # E[qar] = mean: averaging over keys must beat any single trial
    single = np.abs(_run_qar(grads, jax.random.PRNGKey(500))[0] - want).mean()
    assert np.abs(est - want).mean() < 0.5 * single


def test_qar_wire_accounting_quarter_of_dense():
    bits = qar.wire_bits_per_worker(D, W, 512)
    n = qar.pad_len(D, W, 512)
    dense_bits = 2.0 * (W - 1) / W * n * 32
    ratio = bits / dense_bits
    assert 0.2 < ratio < 0.3  # int8 + norm overhead ~ 0.26


def test_qar_pad_len_contract():
    assert qar.pad_len(6000, 8, 512) % (8 * 512) == 0
    assert qar.pad_len(6000, 8, 512) >= 6000
    with pytest.raises(ValueError):
        qar.quantized_allreduce(
            jnp.zeros((100,)), "data", 8, key=jax.random.PRNGKey(0)
        )


def test_trainer_qar_communicator_learns():
    import flax.linen as nn

    from deepreduce_tpu.train import Trainer

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(4)(x)

    rng = np.random.default_rng(0)
    w = rng.normal(size=(16, 4))
    x = rng.normal(size=(512, 16)).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)

    cfg = DeepReduceConfig(communicator="qar", memory="none", deepreduce=None,
                           compressor="none")
    trainer = Trainer(MLP(), cfg, optax.sgd(0.1), _mesh())
    state = trainer.init_state(jax.random.PRNGKey(0), (x[:64], y[:64]))
    losses = []
    for i in range(40):
        lo = (i * 64) % (len(x) - 64)
        state, loss, wire = trainer.step(
            state, (x[lo : lo + 64], y[lo : lo + 64]), jax.random.PRNGKey(i)
        )
        losses.append(float(loss))
    # tracks the dense trajectory (measured: identical 0.48 ratio at 40 steps)
    assert losses[-1] < 0.6 * losses[0]
    # at this tiny d (1348 padded to 4096) padding dominates the accounting;
    # still strictly cheaper than dense, and -> ~0.26 as d >> W*bucket
    assert float(wire.rel_volume()) < 1.0


def test_qar_quantum_num_over_int8_rejected():
    with pytest.raises(ValueError, match="int8"):
        qar.quantized_allreduce(
            jnp.zeros((8 * 512,)), "data", 8, key=jax.random.PRNGKey(0),
            quantum_num=200,
        )


def test_qar_no_residual_state_and_wire_bytes():
    from deepreduce_tpu.comm import GradientExchanger

    cfg = DeepReduceConfig(communicator="qar", memory="none",
                           compressor="none", deepreduce=None)
    grads = {"w": jnp.zeros((D,))}
    ex = GradientExchanger(grads, cfg, num_workers=W)
    assert ex.init_state(grads) is None  # unbiased path carries no residual
    # any config naming a sparsifier/codec/error-feedback that qar would
    # silently ignore is rejected at construction (consistently)
    with pytest.raises(ValueError, match="qar"):
        GradientExchanger(grads, DeepReduceConfig(communicator="qar"), num_workers=W)
    with pytest.raises(ValueError, match="memory"):
        GradientExchanger(
            grads,
            DeepReduceConfig(communicator="qar", memory="residual",
                             compressor="none", deepreduce=None),
            num_workers=W,
        )
    n = qar.pad_len(D, W, 512)
    want = int(qar.wire_bits_per_worker(D, W, 512) // 8)
    assert ex.payload_bytes(grads) == want
    assert want < D * 4  # cheaper than one dense fp32 gradient
