"""Bucketed tensor-fusion exchange on the virtual 8-worker CPU mesh.

The bucketed mode (cfg.bucket_bytes, comm_bucket.py) partitions the
gradient pytree into size-balanced buckets, runs ONE TensorCodec and one
all_gather per bucket, and slices the aggregates back by static offsets.
These tests pin its contracts:

- solo buckets (big leaves, and any bucket holding exactly one leaf) reuse
  the leaf's codec name, so their exchange is equal to the per-tensor
  fused 'loop' path within f32 associativity — exactly, payload-for-
  payload, even for stochastic codecs;
- a fused multi-leaf bucket is equivalent to per-tensor-exchanging the
  CONCATENATED super-tensor (the concat oracle) — selection scope moves
  to the bucket, the wire slot budget does not;
- the partition is deterministic from (name, size) alone, covers every
  leaf exactly once, and never builds a fused bucket over budget;
- `PayloadLayout` round-trips its edge cases (empty pytree, bool leaves,
  single leaf);
- pipelining and decode strategy are pure schedule choices: bucketed
  loop / vmap / pipeline-off all land on identical results and wire bits;
- the config validation surface refuses the combinations that would
  silently ignore bucketing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import shared_mesh
from deepreduce_tpu.comm import GradientExchanger, PayloadLayout
from deepreduce_tpu.comm_bucket import partition_buckets
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.sparse import bucket_num_slots, num_slots
from deepreduce_tpu.utils.compat import shard_map

W, D = 8, 4096

BLOOM_CFG = dict(
    deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01,
    bloom_blocked="mod", policy="p0", min_compress_size=100,
)
QSGD_CFG = dict(
    deepreduce="both", index="bloom", value="qsgd", policy="p0",
    compress_ratio=0.05, fpr=0.05, bloom_blocked="mod", min_compress_size=100,
)


def _run(cfg, grads_w, step=0):
    """Exchange a worker-stacked pytree (each leaf [W, ...]) on the shared
    mesh; returns (agg pytree of np arrays, residual leaves or None, wire
    bits, exchanger)."""
    tmap = jax.tree_util.tree_map
    n = jax.tree_util.tree_leaves(grads_w)[0].shape[0]
    like = tmap(lambda g: jax.ShapeDtypeStruct(g.shape[1:], jnp.float32), grads_w)
    ex = GradientExchanger(like, cfg, num_workers=n)
    res0 = ex.init_state(tmap(lambda s: jnp.zeros(s.shape, s.dtype), like))
    if res0 is not None:
        res0 = tmap(lambda r: jnp.broadcast_to(r[None], (n,) + r.shape), res0)

    def spmd(g, res):
        if res is not None:
            res = tmap(lambda r: r[0], res)
        agg, new_res, stats = ex.exchange(tmap(lambda x: x[0], g), res, step=step)
        if new_res is not None:
            new_res = tmap(lambda r: r[None], new_res)
        return tmap(lambda x: x[None], agg), new_res, stats.total_bits

    res_spec = P() if res0 is None else P("data")
    fn = shard_map(
        spmd,
        mesh=shared_mesh(n),
        in_specs=(P("data"), res_spec),
        out_specs=(P("data"), res_spec, P()),
        check_vma=False,
    )
    agg, res, bits = jax.jit(fn)(tmap(jnp.asarray, grads_w), res0)
    agg = tmap(np.asarray, agg)
    res = None if res is None else tmap(np.asarray, res)
    return agg, res, float(bits), ex


def _grads(seed=0, n=W, d=D):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, d)) * rng.random((n, d)) ** 2).astype(np.float32)


def _grads_tree(shapes, seed=0, n=W):
    rng = np.random.default_rng(seed)
    return {
        name: (rng.normal(size=(n, d)) * rng.random((n, d)) ** 2).astype(
            np.float32
        )
        for name, d in shapes.items()
    }


# --------------------------------------------------------------------- #
# partition properties
# --------------------------------------------------------------------- #

CENSUS = {
    "emb": 3000, "w1": 900, "w2": 700, "b1": 300, "b2": 150, "b3": 50,
}


def test_partition_covers_every_leaf_exactly_once_within_budget():
    names, sizes = list(CENSUS), list(CENSUS.values())
    specs = partition_buckets(names, sizes, bucket_bytes=4800)
    placed = [n for s in specs for n in s.names]
    assert sorted(placed) == sorted(names)  # exactly once, no leaf dropped
    cap = 4800 // 4
    for s in specs:
        assert s.total == sum(s.sizes)
        assert s.offsets == tuple(np.cumsum((0,) + s.sizes[:-1]).tolist())
        if not s.solo:
            assert len(s.names) > 1  # 1-member bins are demoted to solo
            assert s.total <= cap    # fused buckets never over budget
        else:
            assert s.names == (s.label,)  # solo keeps the leaf's name


def test_partition_deterministic_from_shapes_alone():
    names, sizes = list(CENSUS), list(CENSUS.values())
    a = partition_buckets(names, sizes, bucket_bytes=4800)
    b = partition_buckets(names, sizes, bucket_bytes=4800)
    assert a == b
    # labels are unique even when a gradient leaf is literally named like
    # a fused-bucket label (the collision guard appends underscores)
    specs = partition_buckets(["bucket0", "x", "y"], [10, 20, 30], 4000)
    labels = [s.label for s in specs]
    assert len(set(labels)) == len(labels)


def test_partition_big_leaves_stay_solo():
    specs = partition_buckets(["big", "tiny"], [10_000, 8], bucket_bytes=1024)
    by_label = {s.label: s for s in specs}
    assert by_label["big"].solo and by_label["big"].total == 10_000
    assert by_label["tiny"].solo  # 1-member bin demoted, keeps leaf name


def test_partition_reverse_order_properties():
    """order='reverse' (the streaming schedule's backward-completion
    policy): same coverage/budget/solo invariants as 'trace', fused
    buckets hold CONTIGUOUS reverse-trace runs, and the bucket list is
    sorted by descending earliest member — bucket 0 is the first one
    backprop can close."""
    names, sizes = list(CENSUS), list(CENSUS.values())
    specs = partition_buckets(names, sizes, bucket_bytes=4800, order="reverse")
    placed = [n for s in specs for n in s.names]
    assert sorted(placed) == sorted(names)  # exactly once, no leaf dropped
    cap = 4800 // 4
    index = {n: i for i, n in enumerate(names)}
    for s in specs:
        assert s.total == sum(s.sizes)
        if not s.solo:
            assert len(s.names) > 1  # 1-member bins still demoted to solo
            assert s.total <= cap    # budget respected under the new policy
            # members concatenate in pytree order AND form one contiguous
            # reverse-trace stretch (no gaps a later bucket fills)
            idxs = [index[n] for n in s.names]
            assert idxs == sorted(idxs)
        else:
            assert s.names == (s.label,)
    # backward-completion order: strictly descending earliest member
    mins = [min(index[n] for n in s.names) for s in specs]
    assert mins == sorted(mins, reverse=True)
    # deterministic from (name, size) alone
    again = partition_buckets(names, sizes, bucket_bytes=4800, order="reverse")
    assert specs == again


def test_partition_reverse_contiguity_differs_from_ffd():
    """The census where FFD and next-fit-reverse disagree: reverse packs
    strictly contiguous runs even when size-sorted FFD would bin-pack
    tighter, and every fused reverse bucket's members are adjacent in
    reverse-trace order."""
    names = ["a", "b", "c", "d", "e"]
    sizes = [500, 100, 500, 100, 500]
    cap_bytes = 4 * 600
    rev = partition_buckets(names, sizes, cap_bytes, order="reverse")
    index = {n: i for i, n in enumerate(names)}
    for s in rev:
        if not s.solo:
            idxs = sorted(index[n] for n in s.names)
            assert idxs == list(range(idxs[0], idxs[-1] + 1))  # contiguous
    # walking e,d,c,b,a next-fit with cap 600: [e,d], [c,b], [a]
    fused = [s.names for s in rev if not s.solo]
    assert fused == [("d", "e"), ("b", "c")]
    assert [s.label for s in rev if s.solo] == ["a"]


def test_partition_trace_order_is_default_and_unchanged():
    """order='trace' IS the historical partition: explicit arg, default
    arg, and the pre-policy call all produce identical specs, so existing
    configs cannot shift."""
    names, sizes = list(CENSUS), list(CENSUS.values())
    default = partition_buckets(names, sizes, bucket_bytes=4800)
    explicit = partition_buckets(names, sizes, bucket_bytes=4800, order="trace")
    assert default == explicit
    with pytest.raises(ValueError, match="order"):
        partition_buckets(names, sizes, bucket_bytes=4800, order="backward")


def test_bucket_budget_is_sum_of_member_budgets():
    """Fusing never changes the total wire slot budget: the bucket codec's
    k is the SUM of its member leaves' per-tensor budgets (rounding and
    the max(1,.) floor preserved leaf-by-leaf)."""
    ratio = 0.02
    assert bucket_num_slots((900, 300), ratio) == num_slots(900, ratio) + num_slots(300, ratio)
    # tiny leaves keep their max(1,.) floor inside a bucket
    assert bucket_num_slots((10, 10, 10), ratio) == 3
    like = {n: jax.ShapeDtypeStruct((d,), jnp.float32) for n, d in CENSUS.items()}
    cfg_b = DeepReduceConfig(memory="none", bucket_bytes=4800, **BLOOM_CFG)
    cfg_l = DeepReduceConfig(memory="none", **BLOOM_CFG)
    ex_b = GradientExchanger(like, cfg_b, num_workers=W)
    ex_l = GradientExchanger(like, cfg_l, num_workers=W)
    k_bucketed = sum(c.k for c in ex_b._bucketed.codecs.values())
    k_perleaf = sum(c.k for c in ex_l.codecs.values())
    assert k_bucketed == k_perleaf


# --------------------------------------------------------------------- #
# equivalence: solo buckets == the per-tensor fused 'loop' path
# --------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "codec_cfg", [BLOOM_CFG, QSGD_CFG], ids=["bloom-index", "bloom-qsgd-both"]
)
@pytest.mark.parametrize("memory", ["none", "residual"])
def test_single_leaf_bucketed_equals_fused_loop(codec_cfg, memory):
    """A leaf too big for any bucket is a SOLO bucket labelled by the leaf
    name, so its codec and per-tensor PRNG key are identical to the
    unbucketed path — the aggregate, residual, and wire bits must match
    exactly, stochastic value codec included."""
    grads_w = _grads(seed=3)
    cfg_b = DeepReduceConfig(
        memory=memory, bucket_bytes=1024, **codec_cfg  # 1 KB << 16 KB leaf
    )
    cfg_l = DeepReduceConfig(memory=memory, decode_strategy="loop", **codec_cfg)
    agg_b, res_b, bits_b, ex_b = _run(cfg_b, grads_w)
    agg_l, res_l, bits_l, _ = _run(cfg_l, grads_w)
    assert ex_b.num_buckets == 1 and ex_b.bucket_specs[0].solo
    assert bits_b == bits_l  # identical payloads cross the wire
    np.testing.assert_allclose(agg_b, agg_l, rtol=1e-5, atol=1e-6)
    if memory == "residual":
        np.testing.assert_allclose(res_b, res_l, rtol=1e-5, atol=1e-6)


def test_fused_bucket_matches_concat_oracle():
    """A multi-leaf bucket must behave exactly like per-tensor-exchanging
    the concatenated super-tensor: same selection, same payload budget,
    same aggregate (sliced back by static offsets). Deterministic codec so
    the differing codec names can't matter."""
    shapes = {"a": 2800, "b": 1200, "c": 400}  # all divisible by 1/ratio
    grads_w = _grads_tree(shapes, seed=11)
    total = sum(shapes.values())
    cfg_b = DeepReduceConfig(
        memory="none", bucket_bytes=4 * total, **BLOOM_CFG
    )
    agg_b, _, bits_b, ex_b = _run(cfg_b, grads_w)
    assert ex_b.num_buckets == 1 and not ex_b.bucket_specs[0].solo

    # oracle: one concatenated leaf through the plain per-tensor fused path,
    # concatenated in the bucket's member order
    spec = ex_b.bucket_specs[0]
    cat = np.concatenate([grads_w[n] for n in spec.names], axis=1)
    cfg_l = DeepReduceConfig(memory="none", decode_strategy="loop", **BLOOM_CFG)
    agg_cat, _, bits_cat, _ = _run(cfg_l, {"cat": cat})
    assert bits_b == bits_cat
    for name, size, off in zip(spec.names, spec.sizes, spec.offsets):
        np.testing.assert_allclose(
            agg_b[name], agg_cat["cat"][:, off : off + size],
            rtol=1e-5, atol=1e-6,
        )


@pytest.mark.parametrize("memory", ["none", "residual"])
def test_bucketed_schedules_agree(memory):
    """Pipelining and decode strategy are pure schedule choices — bucketed
    loop / vmap / pipeline-off produce identical aggregates, residuals,
    and wire bits on the multi-bucket census."""
    grads_w = _grads_tree(CENSUS, seed=13)
    variants = {
        "loop": dict(decode_strategy="loop"),
        "vmap": dict(decode_strategy="vmap", decode_batch=3),
        "no-pipeline": dict(decode_strategy="loop", bucket_pipeline=False),
    }
    outs = {}
    for vname, kw in variants.items():
        cfg = DeepReduceConfig(
            memory=memory, bucket_bytes=4800, **kw, **BLOOM_CFG
        )
        outs[vname] = _run(cfg, grads_w)
    agg_l, res_l, bits_l, ex = outs["loop"]
    assert ex.num_buckets == 3  # emb solo + two fused bins
    for vname in ("vmap", "no-pipeline"):
        agg_v, res_v, bits_v, _ = outs[vname]
        assert bits_v == bits_l
        for name in CENSUS:
            np.testing.assert_allclose(
                agg_v[name], agg_l[name], rtol=1e-5, atol=1e-6
            )
            if memory == "residual":
                np.testing.assert_allclose(
                    res_v[name], res_l[name], rtol=1e-5, atol=1e-6
                )


def test_bucketed_payload_bytes_matches_layouts():
    """payload_bytes() is the sum of the per-bucket PayloadLayout sizes —
    what the C all_gather operands actually carry (the wire-accounting
    rule's ground truth)."""
    like = {n: jax.ShapeDtypeStruct((d,), jnp.float32) for n, d in CENSUS.items()}
    g = {n: jnp.zeros((d,), jnp.float32) for n, d in CENSUS.items()}
    cfg = DeepReduceConfig(memory="none", bucket_bytes=4800, **BLOOM_CFG)
    ex = GradientExchanger(like, cfg, num_workers=W)
    assert ex.payload_bytes(g) == sum(
        l.nbytes for l in ex._bucketed.layouts.values()
    )


# --------------------------------------------------------------------- #
# PayloadLayout edge cases
# --------------------------------------------------------------------- #


def test_payload_layout_empty_pytree():
    layout = PayloadLayout({})
    assert layout.nbytes == 0
    buf = layout.pack({})
    assert buf.shape == (0,) and buf.dtype == jnp.uint8
    assert layout.unpack(buf) == {}


def test_payload_layout_bool_leaves_roundtrip():
    payload = {
        "mask": jnp.asarray(np.arange(13) % 3 == 0),
        "vals": jnp.asarray(np.linspace(-2, 2, 5), jnp.float32),
    }
    layout = PayloadLayout(jax.eval_shape(lambda: payload))
    buf = layout.pack(payload)
    assert buf.dtype == jnp.uint8 and buf.shape == (13 + 20,)
    out = layout.unpack(buf)
    assert out["mask"].dtype == jnp.bool_
    np.testing.assert_array_equal(out["mask"], payload["mask"])
    np.testing.assert_array_equal(out["vals"], payload["vals"])


def test_payload_layout_single_leaf_roundtrip():
    payload = jnp.asarray(np.arange(7, dtype=np.uint8))
    layout = PayloadLayout(jax.eval_shape(lambda: payload))
    assert layout.nbytes == 7
    np.testing.assert_array_equal(layout.unpack(layout.pack(payload)), payload)


# --------------------------------------------------------------------- #
# validation surface
# --------------------------------------------------------------------- #


def test_bucketed_config_validation():
    with pytest.raises(ValueError, match="bucket_bytes"):
        DeepReduceConfig(bucket_bytes=2)
    like = jax.ShapeDtypeStruct((D,), jnp.float32)
    with pytest.raises(ValueError, match="fused"):
        GradientExchanger(
            like, DeepReduceConfig(fused=False, bucket_bytes=4096, **BLOOM_CFG)
        )
    with pytest.raises(ValueError, match="ring"):
        GradientExchanger(
            like,
            DeepReduceConfig(
                decode_strategy="ring", bucket_bytes=4096, **BLOOM_CFG
            ),
        )
    with pytest.raises(ValueError, match="dense"):
        GradientExchanger(
            like,
            DeepReduceConfig(
                compressor="none", deepreduce=None, memory="none",
                bucket_bytes=4096,
            ),
        )
    with pytest.raises(ValueError, match="layer_pattern"):
        GradientExchanger(
            like,
            DeepReduceConfig(
                layer_pattern="bias", bucket_bytes=4096, **BLOOM_CFG
            ),
        )


# --------------------------------------------------------------------- #
# telemetry plumbing
# --------------------------------------------------------------------- #


def test_bucket_saturation_collected_per_bucket():
    """collect['bucket_saturated'] is an f32[C] vector in bucket-spec
    order; with compress_ratio=1.0 every selection fills its budget, so
    every bucket reports saturated."""
    shapes = {"a": 300, "b": 200, "c": 2000}
    tmap = jax.tree_util.tree_map
    like = {n: jax.ShapeDtypeStruct((d,), jnp.float32) for n, d in shapes.items()}
    cfg = DeepReduceConfig(
        memory="none", bucket_bytes=4000, deepreduce="index", index="bloom",
        compress_ratio=1.0, fpr=0.01, bloom_blocked="mod", policy="p0",
        min_compress_size=100,
    )
    ex = GradientExchanger(like, cfg, num_workers=W)
    grads_w = _grads_tree(shapes, seed=17)

    def spmd(g):
        collect = {}
        agg, _, _ = ex.exchange(tmap(lambda x: x[0], g), None, collect=collect)
        return collect["bucket_saturated"][None]

    fn = shard_map(
        spmd, mesh=shared_mesh(W), in_specs=(P("data"),),
        out_specs=P("data"), check_vma=False,
    )
    sat = np.asarray(jax.jit(fn)(tmap(jnp.asarray, grads_w)))
    assert sat.shape == (W, ex.num_buckets)
    np.testing.assert_array_equal(sat, np.ones_like(sat))


def test_metric_accumulators_bucket_vector():
    from deepreduce_tpu.metrics import WireStats
    from deepreduce_tpu.telemetry import MetricAccumulators

    wire = WireStats(
        index_bits=jnp.asarray(10.0), value_bits=jnp.asarray(20.0),
        dense_bits=jnp.asarray(100.0), saturated=jnp.asarray(1.0),
    )
    acc = MetricAccumulators.zeros(num_buckets=3)
    assert acc.bucket_saturated.shape == (3,)
    acc = acc.accumulate(wire, bucket_saturated=jnp.asarray([1.0, 0.0, 1.0]))
    acc = acc.accumulate(wire)  # a step with nothing to report broadcasts 0
    summary = acc.summary()
    assert summary["bucket_saturated_per_step"] == [0.5, 0.0, 0.5]
    # unbucketed accumulators keep the scalar summary surface unchanged
    assert "bucket_saturated_per_step" not in MetricAccumulators.zeros().summary()
