"""Native C++ layer: cross-implementation golden tests vs the JAX codecs
(same hash mix -> byte-identical bitmaps), policy semantics, wire codecs."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax

from deepreduce_tpu import native, sparse
from deepreduce_tpu.codecs import bloom, packing


def test_fmix32_matches_jax():
    xs = np.array([0, 1, 2, 42, 0xDEADBEEF, 2**32 - 1], np.uint32)
    want = np.asarray(bloom.fmix32(jnp.asarray(xs)))
    got = np.array([native.fmix32(int(x)) for x in xs], np.uint32)
    np.testing.assert_array_equal(got, want)


def test_bitmap_bit_identical_with_jax():
    rng = np.random.default_rng(0)
    idx = np.sort(rng.choice(10000, 128, replace=False)).astype(np.int32)
    meta = bloom.BloomMeta.create(128, 10000, fpr=0.01)
    # JAX side
    words = bloom.insert(jnp.asarray(idx), jnp.asarray(128), meta)
    jax_bytes = np.asarray(words).view(np.uint8)  # little-endian word layout
    # native side
    nat_bytes = native.bloom_insert(idx, meta.m_bits, meta.num_hash)
    np.testing.assert_array_equal(nat_bytes, jax_bytes)


def test_query_universe_matches_jax():
    rng = np.random.default_rng(1)
    idx = np.sort(rng.choice(5000, 64, replace=False)).astype(np.int32)
    meta = bloom.BloomMeta.create(64, 5000, fpr=0.02)
    words = bloom.insert(jnp.asarray(idx), jnp.asarray(64), meta)
    jax_mask = np.asarray(bloom.query_universe(words, meta)).astype(np.uint8)
    nat_mask = native.bloom_query_universe(
        np.asarray(words).view(np.uint8), meta.num_hash, 5000
    )
    np.testing.assert_array_equal(nat_mask, jax_mask)


def test_leftmost_and_p0_match_jax_selection():
    rng = np.random.default_rng(2)
    idx = np.sort(rng.choice(5000, 64, replace=False)).astype(np.int32)
    for policy in ("leftmost", "p0"):
        meta = bloom.BloomMeta.create(64, 5000, fpr=0.05, policy=policy)
        words = bloom.insert(jnp.asarray(idx), jnp.asarray(64), meta)
        mask = bloom.query_universe(words, meta)
        jsel, jn = bloom.select(mask, meta, step=0)
        jsel = np.asarray(jsel)[: int(jn)]
        nsel = native.select(policy, np.asarray(mask).astype(np.uint8), 64, cap=meta.budget)
        np.testing.assert_array_equal(nsel, jsel)


def test_random_policy_deterministic_by_step():
    mask = np.zeros(1000, np.uint8)
    mask[np.random.default_rng(3).choice(1000, 100, replace=False)] = 1
    a = native.select("random", mask, 20, step=5)
    b = native.select("random", mask, 20, step=5)
    c = native.select("random", mask, 20, step=6)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert len(set(a.tolist())) == 20
    assert mask[a].all()


def test_conflict_sets_policy():
    rng = np.random.default_rng(4)
    d, k = 2000, 50
    idx = np.sort(rng.choice(d, k, replace=False)).astype(np.int32)
    meta = bloom.BloomMeta.create(k, d, fpr=0.1)
    bitmap = native.bloom_insert(idx, meta.m_bits, meta.num_hash)
    mask = native.bloom_query_universe(bitmap, meta.num_hash, d)
    sel = native.select(
        "conflict_sets", mask, k, m_bits=meta.m_bits, num_hash=meta.num_hash, step=3
    )
    assert len(sel) == k
    assert mask[sel].all()
    assert len(set(sel.tolist())) == k  # dedup guarantee
    # deterministic
    sel2 = native.select(
        "conflict_sets", mask, k, m_bits=meta.m_bits, num_hash=meta.num_hash, step=3
    )
    np.testing.assert_array_equal(sel, sel2)


def test_bloom_wire_codec_round_trip():
    rng = np.random.default_rng(5)
    d, k = 8000, 80
    g = rng.normal(size=d).astype(np.float32)
    idx = np.sort(np.argsort(-np.abs(g))[:k]).astype(np.int32)
    meta = bloom.BloomMeta.create(k, d, fpr=0.01)
    payload = native.bloom_compress(g, idx, meta.m_bits, meta.num_hash, "leftmost", 0, k)
    vals, out_idx = native.bloom_decompress(payload, d, k, "leftmost", 0, k)
    # FP-aware: values match dense at derived indices
    np.testing.assert_allclose(vals, g[out_idx])
    overlap = len(set(out_idx.tolist()) & set(idx.tolist()))
    assert overlap >= k - 3 * max(meta.fpr * d, 5)


def test_fbp_bit_layout_matches_jax_packing():
    rng = np.random.default_rng(6)
    idx = np.sort(rng.choice(100000, 500, replace=False)).astype(np.uint32)
    deltas = np.diff(idx, prepend=np.uint32(0)).astype(np.uint32)
    width = int(packing.bits_needed(jnp.asarray(deltas.max(), jnp.uint32)))
    jax_packed = packing.pack(jnp.asarray(deltas), jnp.asarray(width, jnp.int32), max_width=width)
    nat = native.fbp_encode(idx)
    assert int(nat[0]) == 500 and int(nat[1]) == width
    body_words = (500 * width + 31) // 32
    np.testing.assert_array_equal(nat[2 : 2 + body_words], np.asarray(jax_packed.words)[:body_words])
    np.testing.assert_array_equal(native.fbp_decode(nat, 500), idx)


def test_varint_round_trip():
    rng = np.random.default_rng(7)
    idx = np.sort(rng.choice(2**28, 1000, replace=False)).astype(np.uint32)
    enc = native.varint_encode(idx)
    np.testing.assert_array_equal(native.varint_decode(enc, 1000), idx)
    assert len(enc) < 4 * 1000  # beats raw despite 28-bit universe


def test_bloom_native_registry_codec_round_trip():
    """BloomCPU role: the C++ host library as a registry codec under
    pure_callback — incl. conflict_sets, the native-only P2 policy."""
    import jax

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    d, ratio = 4096, 0.05
    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    for policy in ("leftmost", "p0", "conflict_sets"):
        cfg = DeepReduceConfig(
            deepreduce="index", index="bloom_native", policy=policy,
            compress_ratio=ratio, fpr=0.01, min_compress_size=100, memory="none",
        )
        codec = TensorCodec((d,), cfg, name="t")
        enc = jax.jit(lambda t, s: codec.encode(t, step=s))
        dec = jax.jit(lambda p, s: codec.decode(p, step=s))
        payload = enc(g, jnp.asarray(3))
        out = np.asarray(dec(payload, jnp.asarray(3)))
        k = int(d * ratio)
        top = np.argsort(-np.abs(np.asarray(g)))[:k]
        hit = np.isin(top, np.nonzero(out)[0]).mean()
        # only p0 (all positives) guarantees no false negatives; leftmost
        # can displace up to ~fpr*d of the k slots (~40 of 204 here), and
        # conflict_sets draws one random member per set so a true index can
        # lose to an FP sharing its buckets — the reference accepts both
        # (its get_policy_errors diagnostic exists for exactly this)
        floor = {"p0": 0.99, "conflict_sets": 0.9, "leftmost": 0.8}[policy]
        assert hit > floor, (policy, hit)
        nz = np.nonzero(out)[0]
        np.testing.assert_allclose(out[nz], np.asarray(g)[nz], rtol=1e-6)
        stats = codec.wire_stats(payload)
        assert 0 < float(stats.rel_volume()) < 1.0


def test_bloom_native_rejected_in_both_mode():
    import pytest as _pytest

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    cfg = DeepReduceConfig(deepreduce="both", index="bloom_native", value="qsgd",
                           min_compress_size=100)
    with _pytest.raises(ValueError, match="index-mode only"):
        TensorCodec((4096,), cfg, name="t")


# ------------------- FastPFor-family name-keyed codecs -------------------- #


def _sorted_indices(rng, k, d):
    return np.sort(rng.choice(d, size=k, replace=False)).astype(np.uint32)


@pytest.mark.parametrize("name", ["fbp", "varint", "pfor"])
def test_int_codec_family_round_trip(name):
    """Every named member (CODECFactory::getFromName role) round-trips
    sorted index arrays exactly."""
    native = pytest.importorskip("deepreduce_tpu.native")
    try:
        enc, dec = native.int_codec_from_name(name)
    except OSError:
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(0)
    for k, d in ((1, 10), (100, 1000), (5000, 200000)):
        idx = _sorted_indices(rng, k, d)
        words = enc(idx)
        out = dec(words, k)
        np.testing.assert_array_equal(out, idx)


def test_int_codec_unknown_name_raises():
    native = pytest.importorskip("deepreduce_tpu.native")
    try:
        native.load()
    except OSError:
        pytest.skip("native lib unavailable")
    with pytest.raises(KeyError):
        native.int_codec_from_name("simdpfor9000")


def test_pfor_patched_exceptions_beat_fbp_on_skewed_deltas():
    """PFor's point: FBP pays the max delta's width for EVERY element; PFor
    patches the few outliers as exceptions. A run of dense indices with a
    handful of giant jumps must compress strictly smaller under pfor."""
    native = pytest.importorskip("deepreduce_tpu.native")
    try:
        enc_p, dec_p = native.int_codec_from_name("pfor")
        enc_f, _ = native.int_codec_from_name("fbp")
    except OSError:
        pytest.skip("native lib unavailable")
    # 2000 mostly-consecutive indices with 8 jumps of ~1M (delta width 20+)
    deltas = np.ones(2000, np.uint64)
    deltas[::250] = 1_000_003
    idx = np.cumsum(deltas).astype(np.uint32)
    w_pfor = enc_p(idx)
    w_fbp = enc_f(idx)
    np.testing.assert_array_equal(dec_p(w_pfor, len(idx)), idx)
    assert len(w_pfor) < len(w_fbp) // 2, (len(w_pfor), len(w_fbp))


def test_integer_native_codec_config_selectable():
    """index='integer_native' + code=<member> flows from config through the
    registry wrapper and round-trips inside jit."""
    pytest.importorskip("deepreduce_tpu.native")
    import jax
    import jax.numpy as jnp

    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    d = 50_000
    rng = np.random.default_rng(3)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    for code in ("fbp", "varint", "pfor"):
        cfg = DeepReduceConfig(
            compressor="topk", compress_ratio=0.02, deepreduce="index",
            index="integer_native", code=code, memory="none",
            min_compress_size=100,
        )
        codec = TensorCodec((d,), cfg, name=f"t_{code}")
        key = jax.random.PRNGKey(0)
        payload = jax.jit(lambda t: codec.encode(t, step=0, key=key))(g)
        out = np.asarray(jax.jit(lambda p: codec.decode(p, step=0))(payload))
        sp = codec.sparsify(g, key=key)
        sel = np.asarray(sp.indices)[: int(sp.nnz)]
        np.testing.assert_allclose(out[sel], np.asarray(g)[sel], rtol=1e-6)
        assert int(codec.wire_stats(payload).total_bits) < d * 32
