"""Bloom index codec: no false negatives, FPR near config, policy
determinism, FP-aware round trip (reference spec pytorch/deepreduce.py:431-555)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepreduce_tpu import sparse
from deepreduce_tpu.codecs import bloom


def _make(d=20000, ratio=0.01, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), ratio)
    return g, sp


def test_no_false_negatives():
    g, sp = _make()
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.01)
    words = bloom.insert(sp.indices, sp.nnz, meta)
    mask = np.asarray(bloom.query_universe(words, meta))
    assert mask[np.asarray(sp.indices)].all()


@pytest.mark.slow
def test_measured_fpr_near_config():
    g, sp = _make(d=50000)
    for fpr in (0.05, 0.01, 0.001):
        meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=fpr)
        words = bloom.insert(sp.indices, sp.nnz, meta)
        measured = float(bloom.measured_fpr(sp, words, meta))
        # optimal-m geometry should land within ~3x of configured fpr
        assert measured <= fpr * 3 + 1e-4, (fpr, measured)


def test_default_fpr_rule():
    # fpr defaults to 0.1*k/d (pytorch/deepreduce.py:511)
    meta = bloom.BloomMeta.create(100, 10000, fpr=None)
    assert meta.fpr == pytest.approx(0.1 * 100 / 10000)


# leftmost compiles the scan-based first-k selection (~19s); random/p0 keep
# the FP-aware agreement property in the quick tier.
@pytest.mark.parametrize(
    "policy",
    [pytest.param("leftmost", marks=pytest.mark.slow), "random", "p0"],
)
def test_encode_decode_agree_on_indices(policy):
    g, sp = _make(d=30000)
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.01, policy=policy)
    payload = bloom.encode(sp, jnp.asarray(g), meta, step=7)
    out = bloom.decode(payload, meta, sp.shape, step=7)
    nsel = int(out.nnz)
    sel = np.asarray(out.indices)[:nsel]
    # FP-aware: transmitted values are the dense values at the derived indices
    np.testing.assert_allclose(np.asarray(payload.values)[:nsel], g[sel], rtol=1e-6)
    # derived set is a superset-selection from positives: contains no index
    # that fails the filter
    words = bloom.insert(sp.indices, sp.nnz, meta)
    mask = np.asarray(bloom.query_universe(words, meta))
    assert mask[sel].all()


def test_p0_returns_all_positives():
    g, sp = _make(d=30000)
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.01, policy="p0")
    words = bloom.insert(sp.indices, sp.nnz, meta)
    mask = np.asarray(bloom.query_universe(words, meta))
    payload = bloom.encode(sp, jnp.asarray(g), meta)
    out = bloom.decode(payload, meta, sp.shape)
    assert int(out.nnz) == int(mask.sum())
    np.testing.assert_array_equal(
        np.sort(np.asarray(out.indices)[: int(out.nnz)]), np.flatnonzero(mask)
    )


def test_leftmost_takes_first_k_positives():
    g, sp = _make(d=30000)
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.05, policy="leftmost")
    words = bloom.insert(sp.indices, sp.nnz, meta)
    mask = np.asarray(bloom.query_universe(words, meta))
    payload = bloom.encode(sp, jnp.asarray(g), meta)
    out = bloom.decode(payload, meta, sp.shape)
    want = np.flatnonzero(mask)[: sp.k]
    np.testing.assert_array_equal(np.asarray(out.indices)[: len(want)], want)


def test_random_policy_step_determinism():
    g, sp = _make(d=30000)
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.05, policy="random")
    p1 = bloom.encode(sp, jnp.asarray(g), meta, step=3)
    o1 = bloom.decode(p1, meta, sp.shape, step=3)
    o1b = bloom.decode(p1, meta, sp.shape, step=3)
    np.testing.assert_array_equal(np.asarray(o1.indices), np.asarray(o1b.indices))
    o2 = bloom.decode(p1, meta, sp.shape, step=4)
    # different step -> different draw (the reference bug this fixes)
    assert not np.array_equal(np.asarray(o1.indices), np.asarray(o2.indices))


def test_round_trip_recovers_gradient_mass():
    """End-to-end: scatter of decoded (vals, idxs) must reproduce the dense
    values at every selected position (FP-aware contract)."""
    g, sp = _make(d=30000)
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.001, policy="leftmost")
    payload = bloom.encode(sp, jnp.asarray(g), meta)
    out = bloom.decode(payload, meta, sp.shape)
    dense = np.asarray(out.to_dense()).reshape(-1)
    nsel = int(out.nnz)
    sel = np.asarray(out.indices)[:nsel]
    np.testing.assert_allclose(dense[sel], g[sel], rtol=1e-6)
    # leftmost policy error: each false positive ahead of a true index
    # displaces it — expected loss ~ fpr*(d-k); allow 3x headroom
    overlap = len(set(sel.tolist()) & set(np.asarray(sp.indices).tolist()))
    expected_fp = meta.fpr * (sp.dense_size - sp.k)
    assert overlap >= sp.k - 3 * max(expected_fp, 5)


def test_jit_and_budget_static():
    g, sp = _make(d=30000)
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.01, policy="p0")
    enc = jax.jit(lambda s, t: bloom.encode(s, t, meta))
    payload = enc(sp, jnp.asarray(g))
    assert payload.values.shape == (meta.budget,)
    assert payload.words.shape == (meta.m_bits // 32,)


def test_wire_bits_smaller_than_raw_indices():
    g, sp = _make(d=100000, ratio=0.01)
    meta = bloom.BloomMeta.create(sp.k, sp.dense_size, fpr=0.001)
    payload = bloom.encode(sp, jnp.asarray(g), meta)
    raw_idx_bits = sp.k * 32
    bloom_idx_bits = int(bloom.wire_bits(payload, meta)) - int(payload.nsel) * 32
    assert bloom_idx_bits < raw_idx_bits  # the -33% claim territory (BASELINE.md)


# ---------------------- blocked (TPU fast path) -------------------------- #


@pytest.mark.parametrize("fpr", [0.05, 0.01, 0.001])
@pytest.mark.parametrize("blocked", ["hash", "mod"])
def test_blocked_no_false_negatives_and_fpr(fpr, blocked):
    rng = np.random.default_rng(10)
    d = 100000
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.01)
    meta = bloom.BloomMeta.create(sp.k, d, fpr=fpr, blocked=blocked)
    words = bloom.insert(sp.indices, sp.nnz, meta)
    mask = np.asarray(bloom.query_universe(words, meta))
    assert mask[np.asarray(sp.indices)].all()
    measured = float(bloom.measured_fpr(sp, words, meta))
    # Poisson-calibrated geometry should land at or under ~1.5x target
    assert measured <= fpr * 1.5 + 1e-4, (fpr, measured)


@pytest.mark.parametrize("policy", ["leftmost", "random", "p0"])
@pytest.mark.parametrize("blocked", ["hash", "mod"])
def test_blocked_encode_decode_agree(policy, blocked):
    rng = np.random.default_rng(11)
    d = 50000
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.01)
    meta = bloom.BloomMeta.create(sp.k, d, fpr=0.01, policy=policy, blocked=blocked)
    payload = bloom.encode(sp, jnp.asarray(g), meta, step=9)
    out = bloom.decode(payload, meta, sp.shape, step=9)
    nsel = int(out.nnz)
    sel = np.asarray(out.indices)[:nsel]
    np.testing.assert_allclose(np.asarray(payload.values)[:nsel], g[sel], rtol=1e-6)


# ------------------- rank-based selection & decode ----------------------- #


def test_prefix_select_exact_large_d():
    """Exact stream compaction at large d: first `budget` positives,
    ascending, dead slots zeroed — including clustered masks."""
    rng = np.random.default_rng(12)
    d = 41_234
    for mask_np in (
        rng.random(d) < 0.01,  # uniform positives
        np.concatenate([np.ones(3000, bool), np.zeros(d - 3000, bool)]),  # cluster
    ):
        budget = 600
        idx, count = jax.jit(lambda m: bloom._prefix_select(m, budget))(
            jnp.asarray(mask_np)
        )
        want = np.nonzero(mask_np)[0]
        n = min(len(want), budget)
        assert int(count) == n
        np.testing.assert_array_equal(np.asarray(idx)[:n], want[:n])
        assert (np.asarray(idx)[n:] == 0).all()


@pytest.mark.slow
def test_bloom_round_trip_large_d():
    """Encode/decode at larger d: FP-aware agreement (values land at the
    derived indices) on both classic and blocked filters."""
    rng = np.random.default_rng(13)
    d = 24_653
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.01)
    for blocked in (False, "hash", "mod"):
        meta = bloom.BloomMeta.create(sp.k, d, fpr=0.01, policy="p0", blocked=blocked)
        payload = bloom.encode(sp, jnp.asarray(g), meta, step=3)
        out = bloom.decode(payload, meta, sp.shape, step=3)
        nsel = int(out.nnz)
        sel = np.asarray(out.indices)[:nsel]
        np.testing.assert_allclose(np.asarray(payload.values)[:nsel], g[sel], rtol=1e-6)
        # every true top-k index was recovered (no false negatives, p0 keeps all)
        true_idx = set(np.asarray(sp.indices).tolist())
        assert true_idx.issubset(set(sel.tolist()))


@pytest.mark.parametrize("policy", ["leftmost", "p0"])
def test_decode_dense_matches_list_decode(policy):
    """The rank-gather dense decode is bit-identical to scattering the
    list-based decode."""
    rng = np.random.default_rng(14)
    d = 30_011
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.02)
    meta = bloom.BloomMeta.create(sp.k, d, fpr=0.01, policy=policy, blocked=True)
    payload = bloom.encode(sp, jnp.asarray(g), meta, step=5)
    via_list = np.asarray(bloom.decode(payload, meta, sp.shape, step=5).to_dense())
    via_rank = np.asarray(bloom.decode_dense(payload, meta, sp.shape, step=5))
    np.testing.assert_array_equal(via_rank, via_list)


def test_both_mode_bloom_random_policy_decodes_real_values():
    """Regression: deepreduce='both' + index='bloom' + policy='random' goes
    through decode_dense's list fallback, which must honor the value-codec
    table instead of the stripped (zeroed) index-payload values."""
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    rng = np.random.default_rng(15)
    d = 20_000
    g = rng.normal(size=d).astype(np.float32)
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.05, deepreduce="both",
        index="bloom", value="qsgd", policy="random", fpr=0.01,
        memory="none", min_compress_size=100,
    )
    codec = TensorCodec((d,), cfg, name="t")
    payload = codec.encode(jnp.asarray(g), step=2, key=jax.random.PRNGKey(0))
    out = np.asarray(codec.decode(payload, step=2)).reshape(-1)
    nz = np.nonzero(out)[0]
    assert len(nz) > 0, "decoded all zeros — value table was discarded"
    # QSGD is unbiased per coordinate; decoded values must correlate with
    # the true gradient at the selected positions
    corr = np.corrcoef(out[nz], g[nz])[0, 1]
    assert corr > 0.8, corr


def test_mod_blocked_structured_indices_fpr():
    """mod-W block assignment with W odd must stay at/under target FPR for
    the structured index sets gradients actually produce: contiguous runs
    and power-of-2 strides (both spread perfectly round-robin mod odd W)."""
    d = 120_000
    k = 12_000
    meta = bloom.BloomMeta.create(k, d, fpr=0.02, blocked="mod")
    assert (meta.m_bits // 32) % 2 == 1  # W odd
    for idx_np in (
        np.arange(5000, 5000 + k, dtype=np.int32),  # contiguous run
        (np.arange(k, dtype=np.int64) * 8 % d).astype(np.int32),  # stride 8
    ):
        idx_np = np.unique(idx_np)
        kk = len(idx_np)
        sp = sparse.SparseGrad(
            values=jnp.ones((kk,), jnp.float32),
            indices=jnp.asarray(idx_np),
            nnz=jnp.int32(kk),
            shape=(d,),
        )
        words = bloom.insert(sp.indices, sp.nnz, meta)
        mask = np.asarray(bloom.query_universe(words, meta))
        assert mask[idx_np].all()  # no false negatives
        truth = np.zeros(d, bool)
        truth[idx_np] = True
        fpr = np.logical_and(mask, ~truth).sum() / (d - kk)
        assert fpr <= 0.02 * 1.5, fpr


def test_decode_dense_tolerates_short_value_table():
    """'both'-mode callers may hand decode_dense a value table shorter than
    p0's budget; positions ranked past the table get zero, not garbage."""
    rng = np.random.default_rng(15)
    d = 20_000
    g = rng.normal(size=d).astype(np.float32)
    sp = sparse.topk(jnp.asarray(g), 0.01)
    meta = bloom.BloomMeta.create(sp.k, d, fpr=0.1, policy="p0", blocked="mod")
    assert meta.budget > sp.k
    payload = bloom.encode(sp, jnp.asarray(g), meta)
    short = jnp.asarray(rng.normal(size=sp.k).astype(np.float32))
    out = np.asarray(bloom.decode_dense(payload, meta, sp.shape, values=short))
    # first k selected positions carry the table, the rest decode to zero
    mask = np.asarray(bloom.query_universe(payload.words, meta))
    want_pos = np.nonzero(mask)[0]
    np.testing.assert_allclose(out[want_pos[: sp.k]], np.asarray(short), rtol=1e-6)
    assert (out[want_pos[sp.k :]] == 0).all()


def test_both_bloom_p0_round_trip():
    """Full wrapper round trip for the flagship DRQSGD-BF-P0 shape
    (deepreduce='both', bloom index, qsgd values, policy p0)."""
    from deepreduce_tpu.config import DeepReduceConfig
    from deepreduce_tpu.wrappers import TensorCodec

    d = 20_000
    cfg = DeepReduceConfig(
        compressor="topk", compress_ratio=0.01, deepreduce="both",
        index="bloom", value="qsgd", policy="p0", fpr=0.05,
        bloom_blocked=True, memory="none", min_compress_size=100,
    )
    codec = TensorCodec((d,), cfg, name="t")
    rng = np.random.default_rng(16)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    key = jax.random.PRNGKey(0)
    payload = jax.jit(lambda t: codec.encode(t, step=0, key=key))(g)
    out = np.asarray(jax.jit(lambda p: codec.decode(p, step=0))(payload))
    # every true top-k position decodes near its gradient value — this
    # checks PLACEMENT through the mapping/rank machinery; qsgd is lossy
    # (one 127-level bucket step ~ norm/127 ~ 0.5 here), so the bound is a
    # quantization-step bound, not an exactness bound
    sp = codec.sparsify(g, key=key)
    sel = np.asarray(sp.indices)[: int(sp.nnz)]
    err = np.abs(out[sel] - np.asarray(g)[sel])
    assert err.max() < 1.0, err.max()
    assert np.corrcoef(out[sel], np.asarray(g)[sel])[0, 1] > 0.95
    assert (out != 0).sum() >= int(sp.nnz)


def test_prefix_positions_edge_cases():
    """Rank inversion must agree with np.nonzero on degenerate masks:
    empty, full, single positive at each boundary, budget=1."""
    pp = jax.jit(bloom._prefix_positions, static_argnums=1)
    for d in (31, 32, 33, 1000):
        for mask_np in (
            np.zeros(d, bool),
            np.ones(d, bool),
            np.eye(1, d, 0, dtype=bool)[0],      # only j=0
            np.eye(1, d, d - 1, dtype=bool)[0],  # only j=d-1
        ):
            for budget in (1, 7, d):
                pos, count = pp(jnp.asarray(mask_np), budget)
                want = np.nonzero(mask_np)[0][:budget]
                n = len(want)
                assert int(count) == min(int(mask_np.sum()), budget)
                np.testing.assert_array_equal(np.asarray(pos)[:n], want)


def test_mod_insert_matches_membership_oracle_awkward_geometries():
    """The sort-free mod insert (unique scatter + OR-reduce) must produce a
    filter with NO false negatives at every awkward geometry: d smaller than
    the word count, single-element universes, nnz=0, and non-divisible
    rows."""
    for d, k in ((1, 1), (7, 3), (33, 5), (1000, 100), (4097, 64)):
        meta = bloom.BloomMeta.create(k, d, fpr=0.05, policy="p0", blocked="mod")
        rng = np.random.default_rng(d)
        idx = rng.choice(d, size=k, replace=False).astype(np.int32)
        for nnz in (0, 1, k):
            sp_idx = jnp.asarray(idx)
            words = jax.jit(lambda i, n: bloom.insert(i, n, meta))(
                sp_idx, jnp.int32(nnz)
            )
            mask = np.asarray(bloom.query_universe(words, meta))
            live = idx[:nnz]
            assert mask[live].all(), (d, k, nnz)
            if nnz == 0:
                assert int(np.asarray(words).sum()) == 0


def test_threshold_insert_matches_scatter_insert():
    """With an exact top-k selection over continuous values (ties have
    measure zero), |dense| >= min-kept-magnitude IS the selected set, so
    insert_from_dense must build the identical filter — and the full
    encode/decode round trip must agree with the scatter-insert path."""
    d = 50_000
    rng = np.random.default_rng(21)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    sp = sparse.topk(g, 0.02)
    meta = bloom.BloomMeta.create(sp.k, d, fpr=0.02, policy="p0", blocked="mod")
    w_scatter = bloom.insert(sp.indices, sp.nnz, meta)
    thresh = jnp.min(jnp.abs(sp.values))
    w_thresh = bloom.insert_from_dense(g, thresh, meta)
    np.testing.assert_array_equal(np.asarray(w_scatter), np.asarray(w_thresh))

    p1 = bloom.encode(sp, g, meta)
    p2 = bloom.encode(sp, g, meta, threshold_insert=True)
    np.testing.assert_array_equal(np.asarray(p1.words), np.asarray(p2.words))
    np.testing.assert_allclose(np.asarray(p1.values), np.asarray(p2.values))
    assert int(p1.nsel) == int(p2.nsel)

    out = np.asarray(bloom.decode_dense(p2, meta, (d,)))
    sel = np.asarray(sp.indices)[: int(sp.nnz)]
    np.testing.assert_allclose(out[sel], np.asarray(g)[sel])


def test_threshold_insert_zero_threshold_falls_back():
    """Fewer true nonzeros than k means the kept minimum magnitude is 0 —
    a zero threshold would saturate the filter, so encode must fall back
    to the scatter insert and produce the identical payload."""
    d = 20_000
    g_np = np.zeros(d, np.float32)
    g_np[:50] = np.random.default_rng(5).normal(size=50)
    g = jnp.asarray(g_np)
    sp = sparse.topk(g, 0.01)  # k=200 > 50 nonzeros -> min kept value is 0
    meta = bloom.BloomMeta.create(sp.k, d, fpr=0.02, policy="p0", blocked="mod")
    p_scatter = bloom.encode(sp, g, meta)
    p_thresh = jax.jit(
        lambda s, t: bloom.encode(s, t, meta, threshold_insert=True)
    )(sp, g)
    np.testing.assert_array_equal(np.asarray(p_scatter.words), np.asarray(p_thresh.words))
    np.testing.assert_allclose(np.asarray(p_scatter.values), np.asarray(p_thresh.values))


def test_saturated_flags_budget_truncation():
    """`bloom.saturated` (ADVICE r3): nsel == budget must read True — the
    signal that `_prefix_positions` may have truncated trailing positives —
    and False on a comfortably under-budget payload."""
    d = 50_000
    rng = np.random.default_rng(11)
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    sp = sparse.topk(g, 0.02)
    meta = bloom.BloomMeta.create(sp.k, d, fpr=0.02, policy="p0", blocked="mod")
    pay = bloom.encode(sp, g, meta)
    assert not bool(bloom.saturated(pay, meta))
    # force truncation: same payload judged against a tiny claimed budget
    tiny = dataclasses.replace(meta, budget=int(pay.nsel))
    assert bool(bloom.saturated(pay, tiny))


def test_threshold_insert_config_rejects_non_mod():
    from deepreduce_tpu.codecs.registry import get_codec

    with pytest.raises(ValueError, match="'mod' blocked layout"):
        get_codec("bloom", "index")(
            100, 10_000, {"bloom_threshold_insert": True, "bloom_blocked": "hash"}
        )


class TestConflictSetsApprox:
    """In-graph P2 redesign (policies.hpp:43-146 via SURVEY §7 hard-part 2):
    round-robin one-per-set draw, smallest sets first, step-keyed."""

    def _setup(self, blocked, d=50_000, ratio=0.02, fpr=0.05, seed=5):
        rng = np.random.default_rng(seed)
        g = jnp.asarray(rng.normal(size=d).astype(np.float32))
        sp = sparse.topk(g, ratio)
        meta = bloom.BloomMeta.create(sp.k, d, fpr, "conflict_sets_approx", blocked=blocked)
        return g, sp, meta

    @pytest.mark.parametrize("blocked", ["mod", False])
    def test_fp_aware_round_trip_and_determinism(self, blocked):
        g, sp, meta = self._setup(blocked)
        d = meta.d
        pay = jax.jit(lambda s, t: bloom.encode(s, t, meta, step=3))(sp, g)
        dec = jax.jit(lambda p: bloom.decode(p, meta, (d,), step=3))(pay)
        nnz = int(dec.nnz)
        assert nnz == meta.budget == sp.k  # enough positives to fill k
        idxs = np.asarray(dec.indices)[:nnz]
        assert (np.diff(idxs) > 0).all()  # canonical ascending, unique
        # FP-aware: every decoded value equals the dense tensor there
        np.testing.assert_allclose(
            np.asarray(dec.values)[:nnz], np.asarray(g)[idxs], rtol=1e-6
        )
        # encode/decode bit-agreement: decoder re-derives the identical
        # selection from the wire alone (policies.hpp:117,172 contract)
        mask = bloom.query_universe(pay.words, meta)
        s1, _ = bloom.select(mask, meta, step=jnp.asarray(3))
        s2, _ = bloom.select(mask, meta, step=jnp.asarray(3))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        # a different step re-draws: selection changes (randomized policy)
        s3, _ = bloom.select(mask, meta, step=jnp.asarray(4))
        assert not np.array_equal(np.asarray(s1), np.asarray(s3))

    def test_round_robin_fairness(self):
        """Counts per conflict set among the chosen differ by at most 1,
        except sets exhausted below the fair share — the reference's
        one-per-set-per-pass visit order (policies.hpp:112-134)."""
        g, sp, meta = self._setup("mod")
        pay = bloom.encode(sp, g, meta, step=0)
        mask = bloom.query_universe(pay.words, meta)
        chosen, cnt = bloom.select(mask, meta, step=jnp.asarray(0))
        chosen = np.asarray(chosen)[: int(cnt)]
        groups = np.asarray(bloom.conflict_group(jnp.asarray(chosen), meta))
        pos = np.flatnonzero(np.asarray(mask))
        all_groups = np.asarray(bloom.conflict_group(jnp.asarray(pos), meta))
        import collections

        csel = collections.Counter(groups.tolist())
        call = collections.Counter(all_groups.tolist())
        cmax = max(csel.values())
        for gid, avail in call.items():
            took = csel.get(gid, 0)
            if took < avail:  # not exhausted -> must be within 1 of the max
                assert took >= cmax - 1, (gid, took, avail, cmax)

    def test_exact_native_p2_still_refuses_jax_route(self):
        with pytest.raises(NotImplementedError, match="conflict_sets_approx"):
            bloom.BloomMeta.create(100, 10_000, 0.05, "conflict_sets")

    def test_through_tensor_codec(self):
        from deepreduce_tpu.config import DeepReduceConfig
        from deepreduce_tpu.wrappers import TensorCodec

        d = 40_000
        rng = np.random.default_rng(9)
        g = jnp.asarray(rng.normal(size=d).astype(np.float32))
        cfg = DeepReduceConfig(
            deepreduce="index", index="bloom", policy="conflict_sets_approx",
            compress_ratio=0.02, fpr=0.05, bloom_blocked="mod",
        )
        codec = TensorCodec((d,), cfg, name="t")
        payload = jax.jit(lambda t: codec.encode(t, step=0))(g)
        out = np.asarray(jax.jit(lambda p: codec.decode(p, step=0))(payload))
        nz = np.flatnonzero(out)
        assert len(nz) == codec.k
        np.testing.assert_allclose(out[nz], np.asarray(g)[nz], rtol=1e-6)

    def test_precision_beats_random_at_high_fpr(self):
        """The policy's purpose (paper P2 motivation): at high FPR the
        one-per-set draw picks true insertions more often than uniform
        random choice among positives — FP-rich words are exactly the
        crowded conflict sets the smallest-first order deprioritizes.
        FPR 0.1 is the highest rate where word-granularity sets still
        carry signal: at the NCF-style 0.6 the filter shrinks to ~27
        words for ~30k positives, every set is ~1k-wide, and any
        one-per-set order degenerates to a uniform draw (measured: 0.019
        vs 0.022 precision — pure noise; 0.137 vs 0.112 here).
        Fully deterministic fixture (fixed tensor, fixed steps)."""
        d, ratio, fpr = 60_000, 0.01, 0.1
        rng = np.random.default_rng(11)
        g = jnp.asarray(rng.normal(size=d).astype(np.float32))
        sp = sparse.topk(g, ratio)
        truth = set(np.asarray(sp.indices).tolist())
        prec = {}
        for policy in ("random", "conflict_sets_approx"):
            meta = bloom.BloomMeta.create(sp.k, d, fpr, policy, blocked="mod")
            pay = bloom.encode(sp, g, meta, step=0)
            mask = bloom.query_universe(pay.words, meta)
            ps = []
            for step in range(5):
                sel, cnt = bloom.select(mask, meta, step=jnp.asarray(step))
                sel = np.asarray(sel)[: int(cnt)]
                ps.append(len(truth.intersection(sel.tolist())) / len(sel))
            prec[policy] = float(np.mean(ps))
        assert prec["conflict_sets_approx"] > prec["random"], prec
