"""Pin the per-platform native-codec routing (PARITY.md §2.4).

The FFI custom-call targets are registered for platform='cpu' only; on the
TPU backend `xla_ops.available()` must be False so `bloom_native` /
`integer_native` take the `pure_callback` host route — the same host-only
split the reference has (policies.hpp:43-146 runs conflict_sets on the CPU
inside the TF op, never on the accelerator). Payload equality between the
two routes is covered by test_xla_ffi.py; this file covers the gate itself.
"""

import jax
import pytest

from deepreduce_tpu.native import xla_ops


def test_available_true_only_on_cpu_backend(monkeypatch):
    try:
        xla_ops.register()
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"ffi unavailable: {e}")
    assert jax.default_backend() == "cpu"
    assert xla_ops.available()
    for backend in ("tpu", "gpu"):
        monkeypatch.setattr(jax, "default_backend", lambda b=backend: b)
        assert not xla_ops.available(), (
            f"FFI route must be gated off on {backend}: the targets are "
            "registered for platform='cpu' only"
        )


def test_native_codecs_use_callback_off_cpu(monkeypatch):
    """On a non-CPU backend the native codecs must trace the pure_callback
    route (no cpu-only custom call baked into the program)."""
    import numpy as np
    import jax.numpy as jnp

    from deepreduce_tpu import sparse
    from deepreduce_tpu.codecs.registry import get_codec

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    calls = []
    real_cb = jax.pure_callback

    def spy(*args, **kwargs):
        calls.append(1)
        return real_cb(*args, **kwargs)

    monkeypatch.setattr(jax, "pure_callback", spy)
    rng = np.random.default_rng(3)
    d = 20_000
    g = jnp.asarray(rng.normal(size=d).astype(np.float32))
    sp = sparse.topk(g, 0.01)
    codec = get_codec("bloom_native", "index")(sp.k, d, {"fpr": 0.02, "policy": "conflict_sets"})
    payload = codec.encode(sp, dense=g, step=0)
    out = codec.decode(payload, (d,), step=0)
    assert calls, "expected the pure_callback host route off-CPU"
    assert int(out.nnz) > 0
