"""Communicator tests on the 8-virtual-device CPU mesh: compressed
allgather-aggregate vs per-worker oracle, dense psum baseline, residual
error feedback across steps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import shared_mesh
from deepreduce_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig


def _mesh(n=4):
    return shared_mesh(n)


def _worker_grads(n, d=4096, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, d)).astype(np.float32)


def _run_exchange(cfg, grads_w, mesh, step=0):
    n = grads_w.shape[0]
    ex = GradientExchanger(jax.ShapeDtypeStruct(grads_w.shape[1:], jnp.float32), cfg)
    res0 = ex.init_state(jnp.zeros(grads_w.shape[1:], jnp.float32))
    if res0 is not None:
        res0 = jax.tree_util.tree_map(
            lambda r: jnp.broadcast_to(r[None], (n,) + r.shape), res0
        )

    def spmd(g, res):
        if res is not None:
            res = jax.tree_util.tree_map(lambda r: r[0], res)
        agg, new_res, stats = ex.exchange(g[0], res, step=step)
        if new_res is not None:
            new_res = jax.tree_util.tree_map(lambda r: r[None], new_res)
        return agg[None], new_res, stats.rel_volume()

    res_spec = P() if res0 is None else P("data")
    fn = shard_map(
        spmd,
        mesh=mesh,
        in_specs=(P("data"), res_spec),
        out_specs=(P("data"), res_spec, P()),
        check_vma=False,
    )
    agg, res, vol = jax.jit(fn)(jnp.asarray(grads_w), res0)
    return np.asarray(agg), res, float(vol), ex


def test_dense_allreduce_baseline():
    mesh = _mesh()
    grads_w = _worker_grads(4)
    cfg = DeepReduceConfig(communicator="allreduce", memory="none", deepreduce=None)
    agg, _, vol, _ = _run_exchange(cfg, grads_w, mesh)
    # every worker's aggregate == mean of all workers' grads
    want = grads_w.mean(axis=0)
    for w in range(4):
        np.testing.assert_allclose(agg[w], want, rtol=1e-5, atol=1e-6)
    assert vol == pytest.approx(1.0)


def test_topk_allgather_matches_oracle():
    mesh = _mesh()
    grads_w = _worker_grads(4, seed=1)
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.05, memory="none")
    agg, _, vol, ex = _run_exchange(cfg, grads_w, mesh)
    # oracle: mean of per-worker top-k scatters
    k = list(ex.codecs.values())[0].k
    want = np.zeros(grads_w.shape[1], np.float32)
    for w in range(4):
        g = grads_w[w]
        idx = np.argsort(-np.abs(g))[:k]
        scat = np.zeros_like(g)
        scat[idx] = g[idx]
        want += scat / 4
    for w in range(4):
        np.testing.assert_allclose(agg[w], want, rtol=1e-5, atol=1e-6)
    assert vol == pytest.approx(2 * k * 32 / (grads_w.shape[1] * 32), rel=1e-3)


def test_bloom_index_allgather_runs_and_compresses():
    mesh = _mesh()
    grads_w = _worker_grads(4, d=8192, seed=2)
    cfg = DeepReduceConfig(
        deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01, memory="none"
    )
    agg, _, vol, ex = _run_exchange(cfg, grads_w, mesh)
    k = list(ex.codecs.values())[0].k
    raw_vol = 2 * k * 32 / (grads_w.shape[1] * 32)
    assert vol < raw_vol  # compressed below raw sparse
    # aggregate is identical on every worker (replicated update invariant)
    for w in range(1, 4):
        np.testing.assert_allclose(agg[w], agg[0], rtol=1e-6)


def test_residual_memory_accumulates_across_steps():
    mesh = _mesh()
    grads_w = _worker_grads(4, seed=3)
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.05, memory="residual")
    agg, res, _, ex = _run_exchange(cfg, grads_w, mesh)
    assert res is not None
    res_np = np.asarray(jax.tree_util.tree_leaves(res)[0])
    k = list(ex.codecs.values())[0].k
    for w in range(4):
        g = grads_w[w]
        idx = np.argsort(-np.abs(g))[:k]
        want_res = g.copy()
        want_res[idx] = 0.0  # sent mass removed, dropped mass kept
        np.testing.assert_allclose(res_np[w], want_res, rtol=1e-5, atol=1e-6)


def test_payload_bytes_static_accounting():
    cfg = DeepReduceConfig(deepreduce="index", index="bloom", compress_ratio=0.01, fpr=0.01)
    g = jax.ShapeDtypeStruct((100000,), jnp.float32)
    ex = GradientExchanger(g, cfg)
    nbytes = ex.payload_bytes(jnp.zeros((100000,), jnp.float32))
    assert 0 < nbytes < 100000 * 4  # well under dense


@pytest.mark.parametrize(
    "codec_cfg,exact",
    [
        (dict(deepreduce=None, compress_ratio=0.05), True),
        (dict(deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01), True),
        (dict(deepreduce="both", index="bloom", value="qsgd", policy="p0",
              compress_ratio=0.05, fpr=0.05, bloom_blocked="mod"), True),
        (dict(deepreduce="both", index="integer", value="qsgd", policy="p0",
              compress_ratio=0.05), True),
        # polyfit decode is a polynomial evaluation whose reassociation XLA
        # is free to change between the two programs — tight tolerance, not
        # bit identity
        (dict(deepreduce="value", value="polyfit", compress_ratio=0.05), False),
    ],
    ids=["topr", "bloom-index", "modbloom-qsgd-both", "integer-qsgd-both",
         "polyfit-value"],
)
def test_fused_matches_per_tensor(codec_cfg, exact):
    """The fused one-buffer exchange matches the reference-shaped per-tensor
    exchange: same payload bytes cross the wire, same decode (bit-identical
    for every codec whose decode has a fixed evaluation order)."""
    mesh = _mesh()
    grads_w = _worker_grads(4, d=4096, seed=9)
    base = dict(memory="residual", min_compress_size=100, **codec_cfg)
    agg_f, res_f, vol_f, _ = _run_exchange(
        DeepReduceConfig(fused=True, **base), grads_w, mesh
    )
    agg_u, res_u, vol_u, _ = _run_exchange(
        DeepReduceConfig(fused=False, **base), grads_w, mesh
    )
    assert_close = (
        np.testing.assert_array_equal
        if exact
        else lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    )
    assert_close(agg_f, agg_u)
    assert_close(
        np.asarray(jax.tree_util.tree_leaves(res_f)[0]),
        np.asarray(jax.tree_util.tree_leaves(res_u)[0]),
    )
    assert vol_f == pytest.approx(vol_u)


def test_fused_multi_tensor_pytree_matches_oracle():
    """Fused path with a multi-tensor pytree (mixed shapes incl. a small
    bypassed tensor): aggregate equals the per-worker top-k scatter mean."""
    mesh = _mesh()
    rng = np.random.default_rng(11)
    shapes = {"w1": (64, 32), "b1": (32,), "w2": (2048,)}
    grads = {
        n: rng.normal(size=(4,) + s).astype(np.float32) for n, s in shapes.items()
    }
    cfg = DeepReduceConfig(
        deepreduce=None, compress_ratio=0.25, memory="none", min_compress_size=100
    )
    like = {n: jax.ShapeDtypeStruct(s, jnp.float32) for n, s in shapes.items()}
    ex = GradientExchanger(like, cfg)

    def spmd(g):
        g = jax.tree_util.tree_map(lambda x: x[0], g)
        agg, _, stats = ex.exchange(g, None, step=jnp.zeros((), jnp.int32))
        return jax.tree_util.tree_map(lambda x: x[None], agg), stats.rel_volume()

    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=({n: P("data") for n in shapes},),
        out_specs=({n: P("data") for n in shapes}, P()),
        check_vma=False,
    )
    agg, vol = jax.jit(fn)(jax.tree_util.tree_map(jnp.asarray, grads))
    for n, s in shapes.items():
        d = int(np.prod(s))
        flat = grads[n].reshape(4, d)
        # deepreduce=None: every tensor (incl. the codec-bypassed small one)
        # is top-k sparsified, so the oracle is the same for all
        k = max(1, int(d * cfg.compress_ratio))
        want = np.zeros(d, np.float32)
        for w in range(4):
            idx = np.argsort(-np.abs(flat[w]))[:k]
            scat = np.zeros(d, np.float32)
            scat[idx] = flat[w][idx]
            want += scat / 4
        got = np.asarray(agg[n]).reshape(4, d)
        for w in range(4):
            np.testing.assert_allclose(got[w], want, rtol=1e-5, atol=1e-6)
    assert 0 < float(vol) < 1.0


@pytest.mark.parametrize("fused", [True, False], ids=["fused", "per-tensor"])
def test_bf16_grads_keep_dtype_through_exchange(fused):
    """bf16 gradients: aggregate and residual state come back bf16 on both
    paths, so jitted train steps don't retrace (and scan carries don't
    change type) after the first step."""
    mesh = _mesh()
    rng = np.random.default_rng(21)
    grads_w = rng.normal(size=(4, 4096)).astype(np.float32)
    cfg = DeepReduceConfig(
        fused=fused, deepreduce=None, compress_ratio=0.05, memory="residual",
        min_compress_size=100,
    )
    like = jax.ShapeDtypeStruct((4096,), jnp.bfloat16)
    ex = GradientExchanger(like, cfg)
    res0 = ex.init_state(jnp.zeros((4096,), jnp.bfloat16))

    def spmd(g, res):
        res = jax.tree_util.tree_map(lambda r: r[0], res)
        agg, new_res, _ = ex.exchange(g[0].astype(jnp.bfloat16), res, step=0)
        return agg[None], jax.tree_util.tree_map(lambda r: r[None], new_res)

    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data")),
        check_vma=False,
    )
    res0_w = jax.tree_util.tree_map(
        lambda r: jnp.broadcast_to(r[None], (4,) + r.shape), res0
    )
    agg, new_res = jax.jit(fn)(jnp.asarray(grads_w), res0_w)
    assert agg.dtype == jnp.bfloat16
    assert jax.tree_util.tree_leaves(new_res)[0].dtype == jnp.bfloat16
