"""Communicator tests on the 8-virtual-device CPU mesh: compressed
allgather-aggregate vs per-worker oracle, dense psum baseline, residual
error feedback across steps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig


def _mesh(n=4):
    devs = np.array(jax.devices()[:n])
    return Mesh(devs, ("data",))


def _worker_grads(n, d=4096, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, d)).astype(np.float32)


def _run_exchange(cfg, grads_w, mesh, step=0):
    n = grads_w.shape[0]
    ex = GradientExchanger(jax.ShapeDtypeStruct(grads_w.shape[1:], jnp.float32), cfg)
    res0 = ex.init_state(jnp.zeros(grads_w.shape[1:], jnp.float32))
    if res0 is not None:
        res0 = jax.tree_util.tree_map(
            lambda r: jnp.broadcast_to(r[None], (n,) + r.shape), res0
        )

    def spmd(g, res):
        if res is not None:
            res = jax.tree_util.tree_map(lambda r: r[0], res)
        agg, new_res, stats = ex.exchange(g[0], res, step=step)
        if new_res is not None:
            new_res = jax.tree_util.tree_map(lambda r: r[None], new_res)
        return agg[None], new_res, stats.rel_volume()

    res_spec = P() if res0 is None else P("data")
    fn = shard_map(
        spmd,
        mesh=mesh,
        in_specs=(P("data"), res_spec),
        out_specs=(P("data"), res_spec, P()),
        check_rep=False,
    )
    agg, res, vol = jax.jit(fn)(jnp.asarray(grads_w), res0)
    return np.asarray(agg), res, float(vol), ex


def test_dense_allreduce_baseline():
    mesh = _mesh()
    grads_w = _worker_grads(4)
    cfg = DeepReduceConfig(communicator="allreduce", memory="none", deepreduce=None)
    agg, _, vol, _ = _run_exchange(cfg, grads_w, mesh)
    # every worker's aggregate == mean of all workers' grads
    want = grads_w.mean(axis=0)
    for w in range(4):
        np.testing.assert_allclose(agg[w], want, rtol=1e-5, atol=1e-6)
    assert vol == pytest.approx(1.0)


def test_topk_allgather_matches_oracle():
    mesh = _mesh()
    grads_w = _worker_grads(4, seed=1)
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.05, memory="none")
    agg, _, vol, ex = _run_exchange(cfg, grads_w, mesh)
    # oracle: mean of per-worker top-k scatters
    k = list(ex.codecs.values())[0].k
    want = np.zeros(grads_w.shape[1], np.float32)
    for w in range(4):
        g = grads_w[w]
        idx = np.argsort(-np.abs(g))[:k]
        scat = np.zeros_like(g)
        scat[idx] = g[idx]
        want += scat / 4
    for w in range(4):
        np.testing.assert_allclose(agg[w], want, rtol=1e-5, atol=1e-6)
    assert vol == pytest.approx(2 * k * 32 / (grads_w.shape[1] * 32), rel=1e-3)


def test_bloom_index_allgather_runs_and_compresses():
    mesh = _mesh()
    grads_w = _worker_grads(4, d=8192, seed=2)
    cfg = DeepReduceConfig(
        deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01, memory="none"
    )
    agg, _, vol, ex = _run_exchange(cfg, grads_w, mesh)
    k = list(ex.codecs.values())[0].k
    raw_vol = 2 * k * 32 / (grads_w.shape[1] * 32)
    assert vol < raw_vol  # compressed below raw sparse
    # aggregate is identical on every worker (replicated update invariant)
    for w in range(1, 4):
        np.testing.assert_allclose(agg[w], agg[0], rtol=1e-6)


def test_residual_memory_accumulates_across_steps():
    mesh = _mesh()
    grads_w = _worker_grads(4, seed=3)
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.05, memory="residual")
    agg, res, _, ex = _run_exchange(cfg, grads_w, mesh)
    assert res is not None
    res_np = np.asarray(jax.tree_util.tree_leaves(res)[0])
    k = list(ex.codecs.values())[0].k
    for w in range(4):
        g = grads_w[w]
        idx = np.argsort(-np.abs(g))[:k]
        want_res = g.copy()
        want_res[idx] = 0.0  # sent mass removed, dropped mass kept
        np.testing.assert_allclose(res_np[w], want_res, rtol=1e-5, atol=1e-6)


def test_payload_bytes_static_accounting():
    cfg = DeepReduceConfig(deepreduce="index", index="bloom", compress_ratio=0.01, fpr=0.01)
    g = jax.ShapeDtypeStruct((100000,), jnp.float32)
    ex = GradientExchanger(g, cfg)
    nbytes = ex.payload_bytes(jnp.zeros((100000,), jnp.float32))
    assert 0 < nbytes < 100000 * 4  # well under dense
