"""Checkpoint/resume: full TrainState round trip incl. residuals."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax


import flax.linen as nn

from conftest import shared_mesh
from deepreduce_tpu import checkpoint
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.train import Trainer


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(4)(nn.relu(nn.Dense(32)(x)))


def test_train_state_round_trip(tmp_path):
    mesh = shared_mesh(2)
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.25, memory="residual")
    trainer = Trainer(Tiny(), cfg, optax.sgd(0.1), mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 4, size=16), jnp.int32)
    state = trainer.init_state(jax.random.PRNGKey(0), (x, y))
    state, _, _ = trainer.step(state, (x, y), jax.random.PRNGKey(1))

    path = str(tmp_path / "ckpt")
    checkpoint.save(path, state)

    template = trainer.init_state(jax.random.PRNGKey(0), (x, y))
    restored = checkpoint.restore(path, template)
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # residuals survived (the gap the reference leaves open, SURVEY.md §5)
    assert restored.residuals is not None
    res_leaves = jax.tree_util.tree_leaves(restored.residuals)
    assert any(np.abs(np.asarray(l)).sum() > 0 for l in res_leaves)


def test_common_init_round_trip(tmp_path):
    model = Tiny()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    path = str(tmp_path / "model_init")
    checkpoint.save_common_init(path, params)
    loaded = checkpoint.load_common_init(path, params)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
