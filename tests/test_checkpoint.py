"""Checkpoint/resume: full TrainState round trip incl. residuals."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax


import flax.linen as nn

from conftest import shared_mesh
from deepreduce_tpu import checkpoint
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.train import Trainer


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(4)(nn.relu(nn.Dense(32)(x)))


def test_train_state_round_trip(tmp_path):
    mesh = shared_mesh(2)
    cfg = DeepReduceConfig(deepreduce=None, compress_ratio=0.25, memory="residual")
    trainer = Trainer(Tiny(), cfg, optax.sgd(0.1), mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 4, size=16), jnp.int32)
    state = trainer.init_state(jax.random.PRNGKey(0), (x, y))
    state, _, _ = trainer.step(state, (x, y), jax.random.PRNGKey(1))

    path = str(tmp_path / "ckpt")
    checkpoint.save(path, state)

    template = trainer.init_state(jax.random.PRNGKey(0), (x, y))
    restored = checkpoint.restore(path, template)
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # residuals survived (the gap the reference leaves open, SURVEY.md §5)
    assert restored.residuals is not None
    res_leaves = jax.tree_util.tree_leaves(restored.residuals)
    assert any(np.abs(np.asarray(l)).sum() > 0 for l in res_leaves)


def test_common_init_round_trip(tmp_path):
    model = Tiny()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    path = str(tmp_path / "model_init")
    checkpoint.save_common_init(path, params)
    loaded = checkpoint.load_common_init(path, params)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------- #
# config fingerprint (resilience: fail-fast restore mismatch)
# ---------------------------------------------------------------------- #


def _cfg(**kw):
    base = dict(deepreduce=None, compress_ratio=0.25, memory="residual")
    base.update(kw)
    return DeepReduceConfig(**base)


def test_config_fingerprint_semantics():
    assert checkpoint.config_fingerprint(_cfg()) == checkpoint.config_fingerprint(_cfg())
    # codec-bearing fields change the fingerprint
    assert checkpoint.config_fingerprint(_cfg()) != checkpoint.config_fingerprint(
        _cfg(compress_ratio=0.5)
    )
    # observability-only knobs do not — a telemetry toggle never blocks resume
    assert checkpoint.config_fingerprint(_cfg()) == checkpoint.config_fingerprint(
        _cfg(telemetry=True)
    )


def test_restore_fails_fast_on_config_mismatch(tmp_path):
    mesh = shared_mesh(2)
    trainer = Trainer(Tiny(), _cfg(), optax.sgd(0.1), mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 4, size=16), jnp.int32)
    state = trainer.init_state(jax.random.PRNGKey(0), (x, y))

    path = str(tmp_path / "ckpt")
    checkpoint.save(path, state, config=_cfg())
    # the stamp is a sibling file, outside the orbax-owned directory
    assert (tmp_path / "ckpt.config.json").exists()

    template = trainer.init_state(jax.random.PRNGKey(0), (x, y))
    restored = checkpoint.restore(path, template, config=_cfg())  # same cfg: ok
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(restored)[0]),
        np.asarray(jax.tree_util.tree_leaves(state)[0]),
    )
    with pytest.raises(ValueError, match="fingerprint"):
        checkpoint.restore(path, template, config=_cfg(compress_ratio=0.5))
    # a legacy checkpoint without a stamp restores under any config
    (tmp_path / "ckpt.config.json").unlink()
    checkpoint.restore(path, template, config=_cfg(compress_ratio=0.5))


# ---------------------------------------------------------------------- #
# kill / resume through the benchmark driver
# ---------------------------------------------------------------------- #


def _bench_args(**kw):
    import argparse

    base = dict(
        model="mlp",
        grace_config=(
            "{'compressor':'topk','compress_ratio':0.25,'deepreduce':None,"
            "'memory':'residual','min_compress_size':16}"
        ),
        num_steps=6, batch_size=32, num_workers=4, learning_rate=0.1, seed=0,
        log_every=0, track_dir="", run_name="", tags="", telemetry=True,
        profile_dir="", checkpoint_every=0, checkpoint_dir="", resume=False,
        platform="",
    )
    base.update(kw)
    return argparse.Namespace(**base)


def _bench_module():
    import importlib.util
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    spec = importlib.util.spec_from_file_location(
        "bench_train", root / "benchmarks" / "train.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_kill_and_resume_continues_exactly(tmp_path):
    """A run checkpointed at step 4 and resumed to step 6 must land on the
    same loss as an uninterrupted 6-step run: batches are a pure function
    of (seed, step) and the checkpoint carries params, optimizer state,
    residual EF memory, step counter AND the telemetry accumulator."""
    from deepreduce_tpu.telemetry import spans

    bench = _bench_module()
    ck = str(tmp_path / "ck")

    try:
        full = bench.run(_bench_args(num_steps=6))

        killed = bench.run(_bench_args(num_steps=4, checkpoint_every=2,
                                       checkpoint_dir=ck))
        assert killed["steps"] == 4
        resumed = bench.run(_bench_args(num_steps=6, checkpoint_dir=ck,
                                        resume=True))
        assert resumed["resumed_at"] == 4
        # the resumed tail reproduces the uninterrupted run exactly
        np.testing.assert_allclose(resumed["last_loss"], full["last_loss"],
                                   rtol=1e-6)
        # telemetry accumulator resumed too: counts all 6 steps, not just 2
        assert resumed["telemetry"]["steps"] == 6.0
        # resuming with a different codec config fails fast
        with pytest.raises(ValueError, match="fingerprint"):
            bench.run(_bench_args(
                num_steps=6, checkpoint_dir=ck, resume=True,
                grace_config=(
                    "{'compressor':'topk','compress_ratio':0.5,"
                    "'deepreduce':None,'memory':'residual',"
                    "'min_compress_size':16}"
                ),
            ))
    finally:
        # run() enables the process-global tracer for telemetry runs;
        # don't leak that into later tests
        spans.configure(enabled=False, reset=True)
