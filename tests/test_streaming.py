"""Backprop-overlapped streaming bucket exchange (cfg.stream_exchange).

comm_stream.StreamingExchange moves each bucket's encode + all_gather into
the backward pass via identity custom_vjp hooks. These tests pin its one
load-bearing contract — the streamed step is BITWISE identical to the
bucketed barrier and pipeline schedules (same codecs, same PRNG keys, same
wire bytes; only the dispatch order moves) — plus the satellites:

- exact equality of aggregates, residuals, raw grads, and wire bits vs
  `bucket_pipeline` on/off, across loop/vmap decode and the stochastic
  qsgd value codec;
- donated-buffer chained steps stay bitwise equal;
- a flat streaming exchange over a two-axis (2, 4) mesh with a tuple
  axis_name matches the barrier schedule on the same mesh;
- the adaptive controller still compiles exactly one step executable per
  ladder rung visited with streaming on (one StreamingExchange per rung);
- the config validation surface refuses the combinations streaming cannot
  honor (no buckets, resilience, hier, fed);
- `costmodel.overlapped_step_time` / `overlap_fraction` against
  hand-computed cases, including the acceptance bound overlapped <= fused.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from conftest import shared_mesh
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.comm_stream import StreamingExchange
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.utils.compat import shard_map

W = 8

CENSUS = {
    "emb": 3000, "w1": 900, "w2": 700, "b1": 300, "b2": 150, "b3": 50,
}

BLOOM_CFG = dict(
    deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01,
    bloom_blocked="mod", policy="p0", min_compress_size=100,
)
QSGD_CFG = dict(
    deepreduce="both", index="bloom", value="qsgd", policy="p0",
    compress_ratio=0.05, fpr=0.05, bloom_blocked="mod", min_compress_size=100,
)


def _params(seed=5):
    rng = np.random.default_rng(seed)
    return {
        name: jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
        for name, d in CENSUS.items()
    }


def _batches(seed=7, n=W):
    rng = np.random.default_rng(seed)
    return {
        name: jnp.asarray(
            (rng.normal(size=(n, d)) * rng.random((n, d)) ** 2).astype(
                np.float32
            )
        )
        for name, d in CENSUS.items()
    }


def _loss(params, batch_stats, batch):
    """Per-worker loss with worker-distinct gradients: grad wrt each leaf
    is batch[name] + p (linear data term + quadratic regularizer)."""
    loss = sum(
        jnp.sum(p * batch[name]) + 0.5 * jnp.sum(jnp.square(p))
        for name, p in params.items()
    )
    return loss, batch_stats


def _one_step(cfg, params, batch_w, *, step=0, seed=21, mesh=None,
              in_spec=None):
    """One full grad+exchange step on the mesh; streamed when
    cfg.stream_exchange, else value_and_grad + exchanger.exchange exactly
    as train.make_worker_step. Returns np pytrees
    (agg, grads[W,...], residuals or None, wire bits)."""
    tmap = jax.tree_util.tree_map
    n = jax.tree_util.tree_leaves(batch_w)[0].shape[0]
    like = tmap(lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32), params)
    ex = GradientExchanger(
        like, cfg, num_workers=n,
        axis_name="data" if mesh is None else mesh.axis_names,
    )
    res0 = ex.init_state(tmap(lambda s: jnp.zeros(s.shape, s.dtype), like))
    has_res = res0 is not None
    if has_res:
        res0 = tmap(lambda r: jnp.broadcast_to(r[None], (n,) + r.shape), res0)
    key = jax.random.PRNGKey(seed)
    stream = StreamingExchange(ex) if cfg.stream_exchange else None
    step_arr = jnp.asarray(step)

    def spmd(p, b_w, res):
        b = tmap(lambda x: x[0], b_w)
        if has_res:
            res = tmap(lambda r: r[0], res)
        if stream is not None:
            (loss, _), grads, agg, new_res, stats = (
                stream.value_and_grad_exchange(
                    _loss, p, {}, b, res, step=step_arr, key=key
                )
            )
        else:
            (loss, _), grads = jax.value_and_grad(_loss, has_aux=True)(
                p, {}, b
            )
            agg, new_res, stats = ex.exchange(
                grads, res, step=step_arr, key=key
            )
        out_res = tmap(lambda r: r[None], new_res) if has_res else None
        return (
            tmap(lambda x: x[None], agg),
            tmap(lambda g: g[None], grads),
            out_res,
            stats.total_bits,
        )

    mesh = mesh or shared_mesh(n)
    shard = in_spec if in_spec is not None else P("data")
    res_spec = P() if not has_res else shard
    fn = shard_map(
        spmd,
        mesh=mesh,
        in_specs=(P(), shard, res_spec),
        out_specs=(shard, shard, res_spec, P()),
        check_vma=False,
    )
    agg, grads, res, bits = jax.jit(fn)(params, batch_w, res0)
    to_np = lambda t: tmap(np.asarray, t)
    return (
        to_np(agg),
        to_np(grads),
        None if res is None else to_np(res),
        float(bits),
    )


def _assert_trees_equal(a, b):
    ja, jb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(ja) == len(jb)
    for x, y in zip(ja, jb):
        np.testing.assert_array_equal(x, y)


# --------------------------------------------------------------------- #
# the contract: streaming == pipeline == barrier, bitwise
# --------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "codec_cfg", [BLOOM_CFG, QSGD_CFG], ids=["bloom-index", "bloom-qsgd-both"]
)
@pytest.mark.parametrize("memory", ["none", "residual"])
# vmap decode re-compiles the whole streamed step per combo (~15-25s each);
# the loop variants pin the same bitwise contract in the quick tier, and
# vmap-vs-loop decode equivalence is covered by test_decode_strategies.
@pytest.mark.parametrize(
    "decode", ["loop", pytest.param("vmap", marks=pytest.mark.slow)]
)
def test_streaming_bitwise_equals_bucket_schedules(codec_cfg, memory, decode):
    """Aggregates, residuals, raw per-worker grads, and wire bits from the
    streamed step equal the pipeline AND barrier schedules EXACTLY —
    stochastic value codec included (same per-tensor PRNG keys)."""
    params = _params()
    batch_w = _batches()
    dec = dict(decode_strategy=decode)
    if decode == "vmap":
        dec["decode_batch"] = 3
    base = dict(memory=memory, bucket_bytes=4800, **dec, **codec_cfg)
    out_s = _one_step(
        DeepReduceConfig(stream_exchange=True, **base), params, batch_w
    )
    out_p = _one_step(DeepReduceConfig(**base), params, batch_w)
    out_b = _one_step(
        DeepReduceConfig(bucket_pipeline=False, **base), params, batch_w
    )
    for other in (out_p, out_b):
        _assert_trees_equal(out_s[0], other[0])   # aggregates
        _assert_trees_equal(out_s[1], other[1])   # raw grads
        if memory == "residual":
            _assert_trees_equal(out_s[2], other[2])  # residuals
        assert out_s[3] == other[3]               # wire bits


def test_streaming_bitwise_equal_on_reverse_bucket_order():
    """bucket_order='reverse' is a shared partition policy: streaming and
    barrier agree bitwise on it too (they see the same specs)."""
    params = _params(seed=9)
    batch_w = _batches(seed=10)
    base = dict(
        memory="residual", bucket_bytes=4800, bucket_order="reverse",
        **BLOOM_CFG,
    )
    out_s = _one_step(
        DeepReduceConfig(stream_exchange=True, **base), params, batch_w
    )
    out_b = _one_step(DeepReduceConfig(**base), params, batch_w)
    _assert_trees_equal(out_s[0], out_b[0])
    _assert_trees_equal(out_s[2], out_b[2])
    assert out_s[3] == out_b[3]


def test_streaming_donated_chained_steps():
    """Two chained steps with donated residual buffers (the real training
    loop's memory discipline) stay bitwise equal to the barrier chain."""
    params = _params(seed=3)
    tmap = jax.tree_util.tree_map
    like = tmap(lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32), params)

    def chain(cfg):
        ex = GradientExchanger(like, cfg, num_workers=W)
        stream = StreamingExchange(ex) if cfg.stream_exchange else None
        key = jax.random.PRNGKey(13)

        def spmd(p, b_w, res, step):
            b = tmap(lambda x: x[0], b_w)
            res = tmap(lambda r: r[0], res)
            if stream is not None:
                _, _, agg, new_res, _ = stream.value_and_grad_exchange(
                    _loss, p, {}, b, res, step=step, key=key
                )
            else:
                _, grads = jax.value_and_grad(_loss, has_aux=True)(p, {}, b)
                agg, new_res, _ = ex.exchange(grads, res, step=step, key=key)
            return (
                tmap(lambda x: x[None], agg),
                tmap(lambda r: r[None], new_res),
            )

        fn = shard_map(
            spmd,
            mesh=shared_mesh(W),
            in_specs=(P(), P("data"), P("data"), P()),
            out_specs=(P("data"), P("data")),
            check_vma=False,
        )
        # residual buffer donated each step, as Trainer's loop donates state
        jfn = jax.jit(fn, donate_argnums=(2,))
        res = tmap(
            lambda p: jnp.zeros((W,) + p.shape, jnp.float32), params
        )
        for step in range(2):
            agg, res = jfn(
                params, _batches(seed=40 + step), res, jnp.asarray(step)
            )
        return tmap(np.asarray, agg), tmap(np.asarray, res)

    base = dict(memory="residual", bucket_bytes=4800, **QSGD_CFG)
    agg_s, res_s = chain(DeepReduceConfig(stream_exchange=True, **base))
    agg_b, res_b = chain(DeepReduceConfig(bucket_pipeline=False, **base))
    _assert_trees_equal(agg_s, agg_b)
    _assert_trees_equal(res_s, res_b)


def test_streaming_on_two_axis_mesh():
    """The rejected-hier escape hatch: a FLAT streaming exchange over a
    (2, 4) two-axis mesh with the tuple axis_name ('dcn', 'ici') — the
    collectives span both axes, and streaming matches the barrier schedule
    on the same mesh bitwise."""
    params = _params(seed=15)
    batch_w = _batches(seed=16)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici"))
    spec = P(("dcn", "ici"))
    base = dict(memory="residual", bucket_bytes=4800, **BLOOM_CFG)
    out_s = _one_step(
        DeepReduceConfig(stream_exchange=True, **base), params, batch_w,
        mesh=mesh, in_spec=spec,
    )
    out_b = _one_step(
        DeepReduceConfig(bucket_pipeline=False, **base), params, batch_w,
        mesh=mesh, in_spec=spec,
    )
    _assert_trees_equal(out_s[0], out_b[0])
    _assert_trees_equal(out_s[2], out_b[2])
    assert out_s[3] == out_b[3]


# --------------------------------------------------------------------- #
# the composed stack: stream-over-hier == barrier hier, bitwise
# --------------------------------------------------------------------- #


def _one_step_hier(cfg, params, batch_w, *, step=0, seed=21):
    """One full grad+exchange step with the HierarchicalExchanger on the
    (2, 4) hybrid mesh; streamed when cfg.stream_exchange (the composed
    stack — each bucket's ici psum + dcn gather dispatch from its backward
    hook), else barrier-scheduled exactly as train.make_worker_step.
    Returns np pytrees (agg, grads[W,...], residuals, dcn bits, ici bits).
    """
    from deepreduce_tpu.parallel.hierarchical import (
        HierarchicalExchanger, make_hybrid_mesh,
    )

    tmap = jax.tree_util.tree_map
    like = tmap(lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32), params)
    ex = HierarchicalExchanger(like, cfg, num_slices=2, per_slice=4)
    res0 = ex.init_state(tmap(lambda s: jnp.zeros(s.shape, s.dtype), like))
    res0 = tmap(lambda r: jnp.broadcast_to(r[None], (W,) + r.shape), res0)
    key = jax.random.PRNGKey(seed)
    stream = StreamingExchange(ex) if cfg.stream_exchange else None
    step_arr = jnp.asarray(step)

    def spmd(p, b_w, res):
        b = tmap(lambda x: x[0], b_w)
        res = tmap(lambda r: r[0], res)
        if stream is not None:
            (loss, _), grads, agg, new_res, stats = (
                stream.value_and_grad_exchange(
                    _loss, p, {}, b, res, step=step_arr, key=key
                )
            )
        else:
            (loss, _), grads = jax.value_and_grad(_loss, has_aux=True)(
                p, {}, b
            )
            agg, new_res, stats = ex.exchange(
                grads, res, step=step_arr, key=key
            )
        return (
            tmap(lambda x: x[None], agg),
            tmap(lambda g: g[None], grads),
            tmap(lambda r: r[None], new_res),
            stats.total_bits,
            stats.ici_bits,
        )

    spec = P(("dcn", "ici"))
    fn = shard_map(
        spmd,
        mesh=make_hybrid_mesh(2, 4),
        in_specs=(P(), spec, spec),
        out_specs=(spec, spec, spec, P(), P()),
        check_vma=False,
    )
    agg, grads, res, bits, ici_bits = jax.jit(fn)(params, batch_w, res0)
    to_np = lambda t: tmap(np.asarray, t)
    return (
        to_np(agg), to_np(grads), to_np(res), float(bits), float(ici_bits)
    )


@pytest.mark.parametrize(
    "codec_cfg", [BLOOM_CFG, QSGD_CFG], ids=["bloom-index", "bloom-qsgd-both"]
)
@pytest.mark.parametrize("order", ["trace", "reverse"])
def test_stream_over_hier_bitwise_equals_barrier_hier(codec_cfg, order):
    """The composed stack's one load-bearing contract: streaming the
    buckets over the hierarchical (dcn, ici) legs — each bucket's dense
    ICI slice-mean psum AND its compressed DCN gather dispatched from the
    bucket's custom_vjp backward hook — is BITWISE identical to the
    barrier-scheduled HierarchicalExchanger: aggregates, raw per-worker
    grads, residuals, DCN wire bits, and ICI bits all equal, stochastic
    qsgd value codec included (same per-tensor PRNG keys, same ici key
    repair), under both bucket orders."""
    params = _params(seed=17)
    batch_w = _batches(seed=18)
    base = dict(
        memory="residual", bucket_bytes=4800, bucket_order=order,
        hier=True, **codec_cfg,
    )
    out_s = _one_step_hier(
        DeepReduceConfig(stream_exchange=True, **base), params, batch_w
    )
    out_b = _one_step_hier(DeepReduceConfig(**base), params, batch_w)
    _assert_trees_equal(out_s[0], out_b[0])
    _assert_trees_equal(out_s[1], out_b[1])
    _assert_trees_equal(out_s[2], out_b[2])
    assert out_s[3] == out_b[3]
    assert out_s[4] == out_b[4]


# --------------------------------------------------------------------- #
# controller composition: one executable per rung, streaming on
# --------------------------------------------------------------------- #


@pytest.mark.slow
def test_controller_rung_cache_with_streaming(tmp_path):
    """With stream_exchange on, the adaptive run still compiles exactly
    one step executable per ladder rung visited — a StreamingExchange is
    built per rung inside make_worker_step, never per step."""
    from deepreduce_tpu.controller.__main__ import _build_cfg, _run_train

    cfg = _build_cfg(bucket_bytes=4800, stream_exchange=True)
    log = tmp_path / "decisions.jsonl"
    losses, trainer, _ = _run_train(cfg, steps=50, num_workers=8, log_path=log)
    assert all(l == l for l in losses)  # finite
    visited = trainer.visited_ladder_indices
    assert len(trainer._step_cache) == len(visited)
    assert trainer.controller.switches >= 1  # it actually adapted
    sizes = [
        fn._cache_size()
        for fn in trainer._step_cache.values()
        if hasattr(fn, "_cache_size")
    ]
    if sizes:
        assert sum(sizes) == len(visited), sizes


# --------------------------------------------------------------------- #
# validation surface
# --------------------------------------------------------------------- #


def test_streaming_config_validation():
    with pytest.raises(ValueError, match="bucket_bytes"):
        DeepReduceConfig(stream_exchange=True, **BLOOM_CFG)
    with pytest.raises(ValueError, match="resilience"):
        DeepReduceConfig(
            stream_exchange=True, bucket_bytes=4096, resilience=True,
            **BLOOM_CFG,
        )
    # the composable stream-over-hier stack (dense ici, config-pinned
    # bucketed-allgather dcn leg) constructs; any other hier shape under
    # streaming still refuses
    cfg = DeepReduceConfig(
        stream_exchange=True, bucket_bytes=4096, hier=True, **BLOOM_CFG
    )
    assert cfg.stream_exchange and cfg.hier
    with pytest.raises(ValueError, match="hier"):
        DeepReduceConfig(
            stream_exchange=True, bucket_bytes=4096, hier=True,
            hier_ici="qar", **BLOOM_CFG,
        )
    with pytest.raises(ValueError, match="hier"):
        DeepReduceConfig(
            stream_exchange=True, bucket_bytes=4096, hier=True,
            decode_strategy="ring", **BLOOM_CFG,
        )
    with pytest.raises(ValueError, match="fed"):
        DeepReduceConfig(
            stream_exchange=True, bucket_bytes=4096, fed=True, **BLOOM_CFG
        )
    with pytest.raises(ValueError, match="bucket_order"):
        DeepReduceConfig(
            bucket_bytes=4096, bucket_order="nope", **BLOOM_CFG
        )
    with pytest.raises(ValueError, match="bucket_order"):
        DeepReduceConfig(bucket_order="reverse", **BLOOM_CFG)


def test_streaming_needs_bucketed_exchanger():
    like = {"x": jax.ShapeDtypeStruct((4096,), jnp.float32)}
    ex = GradientExchanger(
        like, DeepReduceConfig(memory="none", **BLOOM_CFG), num_workers=W
    )
    with pytest.raises(ValueError, match="bucket_bytes"):
        StreamingExchange(ex)


# --------------------------------------------------------------------- #
# cost model: overlapped_step_time / overlap_fraction
# --------------------------------------------------------------------- #


def test_overlapped_step_time_hand_computed():
    from deepreduce_tpu import costmodel as cm

    m = {"payload_bytes": 1e6, "t_encode_s": 0.5, "t_decode_s": 0.25}
    bw = 12.5e6
    wire = (8 - 1) * 1e6 / bw  # allgather_time = 0.56 s
    # no compute to hide behind: identical to the fused serialized model
    assert cm.overlapped_step_time(m, 8, bw) == cm.fused_step_time(m, 8, bw)
    # partial hiding: exposed wire shrinks by exactly compute_time
    t = cm.overlapped_step_time(m, 8, bw, compute_time=0.2)
    assert t == pytest.approx(0.5 + (wire - 0.2) + 8 * 0.25)
    # full hiding: only encode + decode remain, monotone floor
    t_full = cm.overlapped_step_time(m, 8, bw, compute_time=10.0)
    assert t_full == pytest.approx(0.5 + 8 * 0.25)
    assert cm.overlapped_step_time(m, 8, bw, compute_time=20.0) == t_full
    # negative compute_time never helps (clamped to 0)
    assert cm.overlapped_step_time(
        m, 8, bw, compute_time=-1.0
    ) == cm.fused_step_time(m, 8, bw)
    # the acceptance bound: overlapped <= fused, always
    for ct in (0.0, 0.1, 0.56, 3.0):
        assert cm.overlapped_step_time(m, 8, bw, compute_time=ct) <= (
            cm.fused_step_time(m, 8, bw)
        )


def test_overlap_fraction_hand_computed():
    from deepreduce_tpu import costmodel as cm

    m = {"payload_bytes": 1e6, "t_encode_s": 0.0, "t_decode_s": 0.0}
    bw = 12.5e6
    wire = (8 - 1) * 1e6 / bw
    assert cm.overlap_fraction(m, 8, bw) == 0.0
    assert cm.overlap_fraction(m, 8, bw, compute_time=wire / 2) == pytest.approx(0.5)
    assert cm.overlap_fraction(m, 8, bw, compute_time=wire * 3) == 1.0
    assert cm.overlap_fraction(m, 8, bw, compute_time=-1.0) == 0.0
    # degenerate zero-wire measurement: everything is hidden by definition
    z = {"payload_bytes": 0.0, "t_encode_s": 0.0, "t_decode_s": 0.0}
    assert cm.overlap_fraction(z, 8, bw) == 1.0


def test_stream_hier_step_time_composition():
    """The composed model: compute hides the COMBINED ici+dcn wire.
    At compute_time=0 the fused form IS hier_step_time('dense','fused');
    for any compute it never exceeds the barrier-hier parent (the barrier
    schedule hides nothing) nor what the same compute buys streaming-flat
    on the W-wide gather; and the allgather-family fence rejects rs legs."""
    from deepreduce_tpu import costmodel as cm

    d, ns, ps, r = 4_000_000, 8, 4, 0.05
    W = ns * ps
    assert cm.stream_hier_step_time("fused", d, ns, ps, r) == (
        cm.hier_step_time("dense", "fused", d, ns, ps, r)
    )
    m = {
        "payload_bytes": 8.0 * int(d * r),
        "t_encode_s": 0.0, "t_decode_s": 0.0,
    }
    for ct in (0.0, 0.01, 0.5, 100.0):
        for dcn in ("fused", "bucketed"):
            composed = cm.stream_hier_step_time(
                dcn, d, ns, ps, r, compute_time=ct
            )
            assert composed <= cm.hier_step_time(
                "dense", dcn, d, ns, ps, r
            ) + 1e-12
        assert cm.stream_hier_step_time(
            "fused", d, ns, ps, r, compute_time=ct
        ) <= cm.overlapped_step_time(m, W, compute_time=ct) + 1e-12
    with pytest.raises(ValueError, match="allgather family"):
        cm.stream_hier_step_time("sparse", d, ns, ps, r)


def test_select_hier_plan_overlap_aware_flag():
    """stream=False keeps the historical candidate table to the last
    float (the calib-reselect audit pins it); stream=True re-prices ONLY
    the composable dense+fused/bucketed cells, never upward."""
    from deepreduce_tpu import costmodel as cm

    d, ns, ps, r = 4_000_000, 8, 4, 0.05
    base = cm.select_hier_plan(d, ns, ps, r)
    again = cm.select_hier_plan(d, ns, ps, r, stream=False)
    assert base["table"] == again["table"]
    # compute_time already shaves the dcn leg in the barrier model for
    # every candidate, so the fair baseline carries the same compute_time
    # and differs from `aware` only by the stream flag.
    base_ct = cm.select_hier_plan(d, ns, ps, r, stream=False, compute_time=0.5)
    aware = cm.select_hier_plan(d, ns, ps, r, stream=True, compute_time=0.5)
    for key, t in aware["table"].items():
        ici, dcn = key.split("+")
        if ici == "dense" and dcn in ("fused", "bucketed"):
            assert t <= base_ct["table"][key] + 1e-12
        else:
            assert t == base_ct["table"][key]
