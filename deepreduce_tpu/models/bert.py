"""BERT-base encoder — BASELINE.json config 5: a *new* stress test of the
allgather path at 110M params (the reference has no attention models;
SURVEY.md §5 'long-context: absent'). Written MXU-first: fused QKV matmul,
bf16-friendly, static seq length."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class TransformerLayer(nn.Module):
    hidden: int
    heads: int
    mlp_dim: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, qkv_features=self.hidden, dtype=self.dtype
        )(h, h, mask=mask)
        x = x + attn
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.hidden, dtype=self.dtype)(h)
        return x + h


class BertEncoder(nn.Module):
    vocab_size: int = 30_522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):  # [batch, seq] int32 -> MLM logits
        seq = tokens.shape[1]
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype, name="tok")(tokens)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype, name="pos")(
            jnp.arange(seq, dtype=jnp.int32)
        )
        x = x + pos[None, :, :]
        x = nn.LayerNorm(dtype=self.dtype)(x)
        for _ in range(self.layers):
            x = TransformerLayer(self.hidden, self.heads, self.mlp_dim, dtype=self.dtype)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="mlm")(x)
