"""BERT-base encoder — BASELINE.json config 5: a *new* stress test of the
allgather path at 110M params (the reference has no attention models;
SURVEY.md §5 'long-context: absent'). Written MXU-first: fused QKV matmul,
bf16-friendly, static seq length.

Long-context modes: ``attention='ring'`` / ``'ulysses'`` with
``seq_axis='seq'`` shard the sequence over a mesh axis — call the model
inside ``shard_map`` with per-device token chunks; position embeddings are
offset by the device's global chunk start. For a given non-dense attention
mode, ``seq_axis=None`` computes the same function locally with an
identical parameter tree, so sharded and unsharded forwards are directly
comparable. (``attention='dense'`` uses flax's MHA module and therefore a
*different* param layout — checkpoints don't transfer across modes.)
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepreduce_tpu.parallel.ring import ring_attention
from deepreduce_tpu.parallel.ulysses import ulysses_attention

Dtype = Any


class SeqParallelSelfAttention(nn.Module):
    """Self-attention whose score/softmax stage runs ring / Ulysses /
    local-dense over a sequence-sharded mesh axis. QKV and output
    projections are plain per-token matmuls, so they need no communication
    under sequence sharding."""

    heads: int
    qkv_features: int
    attention: str = "dense"  # dense | ring | ulysses
    seq_axis: Optional[str] = None
    causal: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):  # [batch, chunk, hidden]
        head_dim = self.qkv_features // self.heads
        proj = lambda name: nn.DenseGeneral(
            features=(self.heads, head_dim), dtype=self.dtype, name=name
        )
        q, k, v = proj("query")(x), proj("key")(x), proj("value")(x)
        axis = self.seq_axis if self.attention != "dense" else None
        if self.attention == "ulysses":
            out = ulysses_attention(q, k, v, axis, causal=self.causal)
        else:
            out = ring_attention(q, k, v, axis, causal=self.causal)
        return nn.DenseGeneral(
            features=x.shape[-1], axis=(-2, -1), dtype=self.dtype, name="out"
        )(out)


class TransformerLayer(nn.Module):
    hidden: int
    heads: int
    mlp_dim: int
    dtype: Dtype = jnp.float32
    attention: str = "dense"
    seq_axis: Optional[str] = None
    causal: bool = False

    @nn.compact
    def __call__(self, x, mask=None):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.attention == "dense":
            if self.seq_axis is not None:
                raise ValueError(
                    "attention='dense' cannot run sequence-sharded; "
                    "use attention='ring' or 'ulysses' with seq_axis"
                )
            attn = nn.MultiHeadDotProductAttention(
                num_heads=self.heads, qkv_features=self.hidden, dtype=self.dtype
            )(h, h, mask=mask)
        else:
            if mask is not None:
                raise ValueError(
                    "ring/ulysses attention supports only the built-in causal "
                    "mask; arbitrary masks need the dense path"
                )
            attn = SeqParallelSelfAttention(
                heads=self.heads,
                qkv_features=self.hidden,
                attention=self.attention,
                seq_axis=self.seq_axis,
                causal=self.causal,
                dtype=self.dtype,
            )(h)
        x = x + attn
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.hidden, dtype=self.dtype)(h)
        return x + h


class BertEncoder(nn.Module):
    vocab_size: int = 30_522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Dtype = jnp.float32
    attention: str = "dense"  # dense | ring | ulysses
    seq_axis: Optional[str] = None  # sequence-sharded mesh axis (shard_map)
    causal: bool = False
    remat: bool = False  # rematerialize each layer's activations on the
    # backward pass — the jax.checkpoint HBM-for-FLOPs trade; makes
    # activation memory O(1) in depth for long-context runs

    @nn.compact
    def __call__(self, tokens):  # [batch, chunk] int32 -> MLM logits
        seq = tokens.shape[1]
        offset = 0
        if self.seq_axis is not None and self.attention != "dense":
            offset = jax.lax.axis_index(self.seq_axis) * seq
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype, name="tok")(tokens)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype, name="pos")(
            offset + jnp.arange(seq, dtype=jnp.int32)
        )
        x = x + pos[None, :, :]
        x = nn.LayerNorm(dtype=self.dtype)(x)
        layer_cls = nn.remat(TransformerLayer) if self.remat else TransformerLayer
        for i in range(self.layers):
            x = layer_cls(
                self.hidden,
                self.heads,
                self.mlp_dim,
                dtype=self.dtype,
                attention=self.attention,
                seq_axis=self.seq_axis,
                causal=self.causal,
                # explicit name: nn.remat's auto-name prefix would otherwise
                # change the param tree, breaking checkpoint transfer
                # between remat settings
                name=f"TransformerLayer_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="mlm")(x)
