"""Next-word LSTM — the reference's StackOverflow FedAvg model (paper
Table 1: 4.05M params, 18.56% top-1 after 200 rounds). Standard federated
next-word architecture: embed 96 -> LSTM 670 -> dense 96 -> tied-size vocab
projection, sized to land at ~4M params."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class WordLSTM(nn.Module):
    vocab_size: int = 10_004  # 10k vocab + pad/bos/eos/oov
    embed_dim: int = 96
    hidden_dim: int = 670
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):  # [batch, seq] int32
        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype)(tokens)
        # nn.RNN is the sanctioned scan-over-cell: a bare lax.scan around a
        # flax cell leaks the first trace's parameter tracers into later
        # applies (UnexpectedTracerError on jit(apply) after an eager init)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim, dtype=self.dtype))(x)
        h = nn.Dense(self.embed_dim, dtype=self.dtype)(h)
        return nn.Dense(self.vocab_size, dtype=jnp.float32)(h)
