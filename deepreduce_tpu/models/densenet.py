"""DenseNet-40 (growth 12) for CIFAR-10 — paper Table 1's second CIFAR
model (357,491 params, baseline 91.76%)."""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class DenseLayer(nn.Module):
    growth: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        y = nn.relu(y)
        y = nn.Conv(self.growth, (3, 3), use_bias=False, dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)


class Transition(nn.Module):
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        y = nn.relu(y)
        y = nn.Conv(x.shape[-1], (1, 1), use_bias=False, dtype=self.dtype)(y)
        return nn.avg_pool(y, (2, 2), (2, 2))


class DenseNet40(nn.Module):
    """3 dense blocks x 12 layers, growth 12."""

    num_classes: int = 10
    growth: int = 12
    layers_per_block: int = 12
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(16, (3, 3), use_bias=False, dtype=self.dtype)(x)
        for block in range(3):
            for _ in range(self.layers_per_block):
                x = DenseLayer(self.growth, dtype=self.dtype)(x, train)
            if block < 2:
                x = Transition(dtype=self.dtype)(x, train)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
