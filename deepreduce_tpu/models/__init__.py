"""Model zoo covering the reference's benchmark families (BASELINE.md):

- ResNet-20 / CIFAR-10 and ResNet-50 / ImageNet (paper Table 1)
- DenseNet40-K12 / CIFAR-10 (paper Table 1)
- MobileNet / CIFAR-10 (paper Table 5, FL testbed)
- VGG16 (third family in PolySeg's per-model tables, tensorflow/deepreduce.py:182-219)
- NCF / MovieLens-20M (paper Table 1/6 — the natively-sparse config)
- LSTM / StackOverflow next-word (paper Table 1/2, FedAvg testbed)
- BERT-base encoder (BASELINE.json config 5 — the new ICI stress test)

All flax.linen, bfloat16-friendly, written for the MXU (convs/matmuls
batched and channel-last; no dynamic shapes).
"""

from deepreduce_tpu.models.bert import BertEncoder
from deepreduce_tpu.models.densenet import DenseNet40
from deepreduce_tpu.models.lstm import WordLSTM
from deepreduce_tpu.models.mobilenet import MobileNetV1
from deepreduce_tpu.models.ncf import NeuMF
from deepreduce_tpu.models.resnet import ResNet20, ResNet50
from deepreduce_tpu.models.vgg import VGG16

__all__ = [
    "ResNet20",
    "ResNet50",
    "DenseNet40",
    "MobileNetV1",
    "VGG16",
    "NeuMF",
    "WordLSTM",
    "BertEncoder",
]
