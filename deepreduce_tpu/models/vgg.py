"""VGG16 — the third model family the reference's PolySeg codec carries
per-model segment tables for (/root/reference/tensorflow/deepreduce.py:
182-219 `get_breaks` keys resnet20_v2 / vgg16 / resnet50; :244-253
`get_num_of_segments`). CIFAR-sized variant (conv stacks + GAP head) so the
polyseg conv-whitelist path has its reference-named third target."""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class VGG16(nn.Module):
    num_classes: int = 10
    # (filters, convs) per stage, max-pooled between stages — the standard
    # 13-conv VGG16 configuration "D"
    stages: Sequence[Tuple[int, int]] = (
        (64, 2),
        (128, 2),
        (256, 3),
        (512, 3),
        (512, 3),
    )
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        for filters, convs in self.stages:
            for _ in range(convs):
                x = nn.Conv(filters, (3, 3), use_bias=False, dtype=self.dtype)(x)
                x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
