"""MobileNetV1 (depthwise separable) — the reference's FL benchmark model
(paper Table 5: CIFAR-10, 800 rounds, 10 clients, baseline 88.17%)."""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class SeparableBlock(nn.Module):
    filters: int
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        in_ch = x.shape[-1]
        x = nn.Conv(
            in_ch,
            (3, 3),
            (self.stride, self.stride),
            feature_group_count=in_ch,
            use_bias=False,
            dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        return nn.relu(x)


class MobileNetV1(nn.Module):
    num_classes: int = 10
    width_mult: float = 1.0
    # (filters, stride) after the stem; CIFAR variant keeps early strides 1
    blocks: Sequence[Tuple[int, int]] = (
        (64, 1),
        (128, 2),
        (128, 1),
        (256, 2),
        (256, 1),
        (512, 2),
        (512, 1),
        (512, 1),
        (512, 1),
        (512, 1),
        (512, 1),
        (1024, 2),
        (1024, 1),
    )
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        w = lambda f: max(8, int(f * self.width_mult))
        x = nn.Conv(w(32), (3, 3), use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        for filters, stride in self.blocks:
            x = SeparableBlock(w(filters), stride, dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
