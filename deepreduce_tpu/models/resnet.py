"""ResNets: ResNet-20 (CIFAR, v2 pre-activation — the reference benchmarks
resnet20_v2, tensorflow/deepreduce.py:184) and ResNet-50 (ImageNet,
bottleneck v1.5). 269,722 params for ResNet-20 / 25.6M for ResNet-50 per
BASELINE.md Table 1 — the gradient pytrees the codecs are sized against."""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class BasicBlockV2(nn.Module):
    filters: int
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        y = norm()(x)
        y = nn.relu(y)
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.filters:
            shortcut = conv(self.filters, (1, 1), (self.stride, self.stride))(y)
        y = conv(self.filters, (3, 3), (self.stride, self.stride))(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3))(y)
        return y + shortcut


class ResNet20(nn.Module):
    """Pre-activation ResNet-20 for 32x32 inputs, 10 classes."""

    num_classes: int = 10
    width: int = 16
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.width, (3, 3), use_bias=False, dtype=self.dtype)(x)
        for i, filters in enumerate((self.width, 2 * self.width, 4 * self.width)):
            for j in range(3):
                stride = 2 if i > 0 and j == 0 else 1
                x = BasicBlockV2(filters, stride, dtype=self.dtype)(x, train)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class BottleneckBlock(nn.Module):
    filters: int
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        shortcut = x
        if self.stride != 1 or x.shape[-1] != 4 * self.filters:
            shortcut = conv(4 * self.filters, (1, 1), (self.stride, self.stride))(x)
            shortcut = norm()(shortcut)
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), (self.stride, self.stride))(y)
        y = nn.relu(norm()(y))
        y = conv(4 * self.filters, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        return nn.relu(y + shortcut)


class ResNet50(nn.Module):
    """Bottleneck ResNet-50 for 224x224 ImageNet (25.6M params)."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            filters = 64 * 2**i
            for j in range(block_count):
                stride = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(filters, stride, dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
