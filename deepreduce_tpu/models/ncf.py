"""Neural Collaborative Filtering (NeuMF = GMF + MLP) — the reference's
natively-sparse benchmark (NVIDIA NCF port, README.md:22; paper Table 1:
31.8M params on ML-20m, best HR 94.97%). Embedding gradients are naturally
sparse, which is why the reference pairs it with threshold-0 sparsification
+ FPR 0.6 + P0 (paper Table 6)."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class NeuMF(nn.Module):
    num_users: int = 138_493  # ML-20m
    num_items: int = 26_744
    mf_dim: int = 64
    mlp_layers: Sequence[int] = (256, 256, 128, 64)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, user_ids, item_ids):
        mf_u = nn.Embed(self.num_users, self.mf_dim, dtype=self.dtype, name="mf_user")(user_ids)
        mf_i = nn.Embed(self.num_items, self.mf_dim, dtype=self.dtype, name="mf_item")(item_ids)
        gmf = mf_u * mf_i

        mlp_dim = self.mlp_layers[0] // 2
        mlp_u = nn.Embed(self.num_users, mlp_dim, dtype=self.dtype, name="mlp_user")(user_ids)
        mlp_i = nn.Embed(self.num_items, mlp_dim, dtype=self.dtype, name="mlp_item")(item_ids)
        h = jnp.concatenate([mlp_u, mlp_i], axis=-1)
        for width in self.mlp_layers[1:]:
            h = nn.relu(nn.Dense(width, dtype=self.dtype)(h))

        logit = nn.Dense(1, dtype=jnp.float32)(jnp.concatenate([gmf, h], axis=-1))
        return logit[..., 0]
