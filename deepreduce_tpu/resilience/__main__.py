"""Resilience CLI: chaos smoke-check and drop/corrupt sweep.

    python -m deepreduce_tpu.resilience check --platform cpu
    python -m deepreduce_tpu.resilience sweep --platform cpu

`check` is the `make chaos-check` body: a short 8-worker CPU-mesh train
under a FaultPlan drop schedule AND wire corruption with payload checksums,
asserting that loss stays finite and decreases, that dropped steps were
recorded, and that corrupted payloads were caught by the checksum (counter
incremented) instead of poisoning the params. `sweep` runs a small grid of
drop-rate × corrupt-rate cells and prints one JSON row per cell — the
degradation surface of the compressed exchange under hostile conditions.
"""

from __future__ import annotations

import argparse
import json
import sys


def _build_cfg(**overrides):
    from deepreduce_tpu.config import DeepReduceConfig

    base = dict(
        deepreduce="index",
        index="bloom",
        compress_ratio=0.05,
        fpr=0.01,
        memory="residual",
        min_compress_size=100,
        telemetry=True,
    )
    base.update(overrides)
    return DeepReduceConfig(**base)


def _run_train(cfg, *, steps: int, num_workers: int, seed: int = 0, lr: float = 0.1):
    """Short synthetic-data train on the CPU mesh; returns (losses, summary)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import flax.linen as nn
    from jax.sharding import Mesh

    from deepreduce_tpu.train import Trainer

    class _MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(64)(x))
            x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(8)(x)

    n_dev = min(num_workers, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    trainer = Trainer(_MLP(), cfg, optax.sgd(lr, momentum=0.9), mesh)

    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(512, 32)), jnp.float32)
    # learnable labels (a fixed random projection), so loss actually falls
    w_true = rng.normal(size=(32, 8))
    y = jnp.asarray(np.argmax(rng.normal(size=(512, 8)) * 0.1 + x @ w_true, axis=1), jnp.int32)

    batch = 64
    state = trainer.init_state(jax.random.PRNGKey(seed), (x[:batch], y[:batch]))
    key = jax.random.PRNGKey(seed + 1)
    losses = []
    for step in range(steps):
        lo = (step * batch) % (512 - batch)
        state, loss, _ = trainer.step(
            state, (x[lo : lo + batch], y[lo : lo + batch]), jax.random.fold_in(key, step)
        )
        losses.append(float(loss))
    return losses, trainer.telemetry_summary()


def cmd_check(args) -> int:
    cfg = _build_cfg(
        resilience=True,
        fault_plan="2@5:9,0@12:14",
        payload_checksum=True,
        chaos_corrupt_rate=0.2,
    )
    losses, summary = _run_train(cfg, steps=args.steps, num_workers=args.num_workers)
    checks = {
        "losses_finite": all(l == l and abs(l) != float("inf") for l in losses),
        "loss_decreased": losses[-1] < losses[0],
        "dropped_steps_recorded": summary.get("dropped_steps", 0.0) > 0.0,
        "checksum_failures_caught": summary.get("checksum_failures", 0.0) > 0.0,
    }
    report = {
        "ok": all(checks.values()),
        "checks": checks,
        "first_loss": losses[0],
        "last_loss": losses[-1],
        "steps": len(losses),
        "live_workers_per_step": summary.get("live_workers_per_step"),
        "dropped_steps": summary.get("dropped_steps"),
        "checksum_failures": summary.get("checksum_failures"),
        "config": {
            "fault_plan": cfg.fault_plan,
            "chaos_corrupt_rate": cfg.chaos_corrupt_rate,
            "payload_checksum": cfg.payload_checksum,
        },
    }
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


def cmd_sweep(args) -> int:
    drop_rates = [float(v) for v in args.drop_rates.split(",")]
    corrupt_rates = [float(v) for v in args.corrupt_rates.split(",")]
    rows = []
    ok = True
    for dr in drop_rates:
        for cr in corrupt_rates:
            cfg = _build_cfg(
                resilience=True,
                drop_rate=dr,
                payload_checksum=cr > 0.0,
                chaos_corrupt_rate=cr,
            )
            losses, summary = _run_train(
                cfg, steps=args.steps, num_workers=args.num_workers
            )
            finite = all(l == l and abs(l) != float("inf") for l in losses)
            ok = ok and finite
            row = {
                "drop_rate": dr,
                "chaos_corrupt_rate": cr,
                "first_loss": losses[0],
                "last_loss": losses[-1],
                "losses_finite": finite,
                "live_workers_per_step": summary.get("live_workers_per_step"),
                "dropped_steps": summary.get("dropped_steps"),
                "checksum_failures": summary.get("checksum_failures"),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    print(json.dumps({"ok": ok, "cells": len(rows)}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m deepreduce_tpu.resilience")
    ap.add_argument("--platform", type=str, default="",
                    help="pin the JAX platform (e.g. 'cpu' for the virtual "
                         "8-device mesh)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_check = sub.add_parser("check", help="chaos smoke-check (make chaos-check)")
    p_check.add_argument("--steps", type=int, default=24)
    p_check.add_argument("--num_workers", type=int, default=8)
    p_sweep = sub.add_parser("sweep", help="drop-rate x corrupt-rate grid")
    p_sweep.add_argument("--steps", type=int, default=12)
    p_sweep.add_argument("--num_workers", type=int, default=8)
    p_sweep.add_argument("--drop_rates", type=str, default="0.0,0.125,0.25")
    p_sweep.add_argument("--corrupt_rates", type=str, default="0.0,0.2")
    args = ap.parse_args(argv)
    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=max(2, args.num_workers))
    if args.cmd == "check":
        return cmd_check(args)
    return cmd_sweep(args)


if __name__ == "__main__":
    sys.exit(main())
