"""Chaos injector: deterministic payload perturbation at the wire boundary.

Exercises decode paths against hostile inputs *inside* the jitted step:
after a worker packs its fused byte payload (and after the checksum word
is appended), `ChaosInjector.perturb` may drop it (zero the whole buffer),
bit-corrupt a random subset of bytes, or truncate its tail — each an
independent Bernoulli draw per (step, worker, salt) from a PRNG stream
keyed off `cfg.seed`, so every run of a given config injects the identical
fault sequence and failures reproduce exactly.

Perturbation happens strictly between pack and all_gather, so the decode
side sees corrupt bytes exactly as a lossy transport would deliver them.
With `payload_checksum=True` the receiver detects the damage, zeroes the
contribution, and bumps the `checksum_failures` telemetry counter — the
graceful-degradation path `make chaos-check` pins. All control flow is
elementwise `jnp.where`; nothing branches on traced values on the host.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

# domain-separation tag for the chaos PRNG stream (vs. dropout's 0x0FA17)
_CHAOS_TAG = 0x0C405


@dataclasses.dataclass(frozen=True)
class ChaosInjector:
    """Per-payload fault model: drop / bit-corrupt / truncate, each with an
    independent per-(step, worker, salt) Bernoulli rate."""

    drop_rate: float = 0.0
    corrupt_rate: float = 0.0
    truncate_rate: float = 0.0
    seed: int = 0
    # fraction of bytes XOR-flipped when a corrupt event fires: sparse
    # enough that most of the payload stays plausible (the hard case for
    # a decoder), dense enough the checksum always trips
    corrupt_frac: float = 0.05

    @classmethod
    def from_config(cls, cfg) -> Optional["ChaosInjector"]:
        """None (no wiring, byte-identical program) unless resilience is on
        and at least one chaos rate is non-zero."""
        if not getattr(cfg, "resilience", False):
            return None
        rates = (cfg.chaos_drop_rate, cfg.chaos_corrupt_rate, cfg.chaos_truncate_rate)
        if all(r <= 0.0 for r in rates):
            return None
        return cls(
            drop_rate=float(cfg.chaos_drop_rate),
            corrupt_rate=float(cfg.chaos_corrupt_rate),
            truncate_rate=float(cfg.chaos_truncate_rate),
            seed=int(getattr(cfg, "seed", 0) or 0),
        )

    def perturb(self, buf: jax.Array, *, step, worker, salt: int = 0) -> jax.Array:
        """Perturb a packed uint8 payload. `worker` may be traced
        (axis_index); `salt` distinguishes multiple payloads per step
        (bucket index) so buckets don't fail in lockstep."""
        B = buf.shape[0]
        if B == 0:
            return buf
        key = jax.random.PRNGKey(self.seed)
        key = jax.random.fold_in(key, _CHAOS_TAG)
        key = jax.random.fold_in(key, salt)
        key = jax.random.fold_in(key, jnp.asarray(step, jnp.uint32))
        key = jax.random.fold_in(key, worker)
        kd, kc, kt, ksel, kbytes = jax.random.split(key, 5)

        out = buf
        if self.corrupt_rate > 0.0:
            corrupt = jax.random.bernoulli(kc, self.corrupt_rate)
            # minval=1: the XOR mask never degenerates to a no-op flip
            noise = jax.random.randint(kbytes, (B,), 1, 256, jnp.uint8)
            sel = jax.random.bernoulli(ksel, self.corrupt_frac, (B,))
            out = jnp.where(corrupt & sel, out ^ noise, out)
        if self.truncate_rate > 0.0:
            trunc = jax.random.bernoulli(kt, self.truncate_rate)
            tail = jnp.arange(B) >= B // 2
            out = jnp.where(trunc & tail, jnp.uint8(0), out)
        if self.drop_rate > 0.0:
            # drop last: a dropped payload is all-zero regardless of what
            # corrupt/truncate did (the XOR-salted checksum still trips —
            # an all-zero buffer never matches its zeroed checksum word)
            drop = jax.random.bernoulli(kd, self.drop_rate)
            out = jnp.where(drop, jnp.zeros_like(out), out)
        return out
