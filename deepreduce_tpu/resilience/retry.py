"""Host-side retry with deterministic exponential backoff.

The device-side halves of the resilience subsystem (participation masks,
checksummed payloads) handle faults *inside* the jitted step; this module
is the host half: transient I/O failure around checkpoint save/restore
(checkpoint.py) and tracking writes (tracking.py). Pure stdlib — no jax,
no telemetry import — so it is safe to import from anywhere, including
modules that must stay light (tracking.py is imported by CLI tooling).

Backoff is deterministic (no jitter): delays are `base_delay * multiplier
** attempt` capped at `max_delay`, so tests can assert the exact sleep
sequence. Single-process single-writer I/O has no thundering-herd problem
for jitter to solve.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")

# the transient-I/O family: OSError covers IOError/FileNotFoundError-on-NFS
# races/disk-full; orbax surfaces backend write failures as ValueError too
# rarely to whitelist broadly — callers widen retry_on explicitly if needed
DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (OSError,)


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.05,
    multiplier: float = 2.0,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = DEFAULT_RETRY_ON,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
) -> T:
    """Call ``fn()``; on a `retry_on` exception, back off and try again.

    Re-raises the last exception after `attempts` total tries. Exceptions
    outside `retry_on` propagate immediately (a corrupt checkpoint is not
    transient). `on_retry(attempt, exc, delay)` fires before each sleep —
    the hook telemetry/tests attach to.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delay = base_delay
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as exc:
            if attempt == attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)
            delay = min(delay * multiplier, max_delay)
    raise AssertionError("unreachable")  # pragma: no cover


def retry_io(fn: Callable[[], T], **kwargs) -> T:
    """`retry_call` with the default transient-I/O policy — the form the
    checkpoint and tracking call sites use."""
    return retry_call(fn, **kwargs)
