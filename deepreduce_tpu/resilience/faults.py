"""Participation faults: deterministic FaultPlan schedules + PRNG dropout.

The elastic-participation half of the resilience subsystem. A *participation
mask* is a traced bool[W] vector over the mesh's data axis: True = the
worker's payload enters this step's aggregate, False = it contributes zero
and the mean renormalizes by the live count. Both sources are deterministic
functions of (config, step, key), computed identically on every worker from
replicated inputs — no coordination, no host control flow (the
ast-mask-host-branch lint rule pins that):

- `FaultPlan` — an explicit schedule parsed from a spec string like
  ``"2@5:9,0@12"`` (worker 2 dropped for steps 5..8, worker 0 at step 12),
  the reproducible-failure harness the chaos CLI and tests drive;
- PRNG dropout — each worker dropped i.i.d. with `drop_rate` per step,
  keyed from the step's *shared* key (never the worker-folded one), so the
  mask is replicated by construction.

Dropped workers keep their residual error-feedback accumulator: the
exchange scales their own-payload decode to zero, so `memory.update`
(residual' = compensated - own_decode) retains the whole compensated
gradient — un-sent mass re-delivers on rejoin through the EF telescoping
identity. See ARCHITECTURE.md "Resilience".
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# `worker@step` or `worker@start:stop`
_ENTRY_RE = re.compile(r"^\s*(\d+)\s*@\s*(\d+)\s*(?::\s*(\d+)\s*)?$")

# domain-separation tag so the dropout stream never collides with other
# fold_in consumers of the step key
_DROPOUT_TAG = 0x0FA17


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Static drop schedule: (worker, start, stop) triples, dropped for
    steps ``start <= t < stop``. Parsed once at config validation; the
    traced mask is a pure elementwise function of the step counter."""

    entries: Tuple[Tuple[int, int, int], ...]

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        if not isinstance(spec, str) or not spec.strip():
            raise ValueError(
                f"fault_plan must be a non-empty spec string like "
                f"'2@5:9,0@12', got {spec!r}"
            )
        entries = []
        for part in spec.split(","):
            m = _ENTRY_RE.match(part)
            if m is None:
                raise ValueError(
                    f"bad fault_plan entry {part.strip()!r} — expected "
                    "'worker@step' or 'worker@start:stop'"
                )
            worker, start = int(m.group(1)), int(m.group(2))
            stop = int(m.group(3)) if m.group(3) is not None else start + 1
            if stop <= start:
                raise ValueError(
                    f"fault_plan entry {part.strip()!r} has empty range "
                    f"[{start}, {stop})"
                )
            entries.append((worker, start, stop))
        return cls(entries=tuple(entries))

    def mask(self, step, num_workers: int) -> jax.Array:
        """Traced bool[W]: True = live at `step`. Entries whose worker id
        exceeds the mesh width are ignored (mode='drop' scatter)."""
        W = int(num_workers)
        if not self.entries:
            return jnp.ones((W,), jnp.bool_)
        workers = jnp.asarray(np.array([e[0] for e in self.entries]), jnp.int32)
        starts = jnp.asarray(np.array([e[1] for e in self.entries]), jnp.int32)
        stops = jnp.asarray(np.array([e[2] for e in self.entries]), jnp.int32)
        s = jnp.asarray(step, jnp.int32)
        hit = ((s >= starts) & (s < stops)).astype(jnp.int32)  # [E]
        dropped = (
            jnp.zeros((W,), jnp.int32).at[workers].max(hit, mode="drop")
        )
        return dropped == 0


def participation_mask(
    num_workers: int,
    step,
    key: Optional[jax.Array],
    *,
    drop_rate: float = 0.0,
    fault_plan: Optional[str] = None,
) -> Optional[jax.Array]:
    """The per-step mask the trainer threads into `exchange`: AND of the
    FaultPlan schedule and the PRNG dropout. Returns None when neither
    source is configured, so a resilience-on-but-drop-free program carries
    no mask arithmetic at all (chaos injection composes independently)."""
    if drop_rate <= 0.0 and fault_plan is None:
        return None
    W = int(num_workers)
    mask = jnp.ones((W,), jnp.bool_)
    if fault_plan is not None:
        mask = mask & FaultPlan.parse(fault_plan).mask(step, W)
    if drop_rate > 0.0:
        if key is None:
            key = jax.random.PRNGKey(0)
        # keyed from the SHARED step key + step counter (never the
        # worker-folded key): every worker derives the identical mask
        k = jax.random.fold_in(key, _DROPOUT_TAG)
        k = jax.random.fold_in(k, jnp.asarray(step, jnp.uint32))
        mask = mask & jax.random.bernoulli(k, 1.0 - float(drop_rate), (W,))
    return mask
