"""Resilience subsystem: elastic participation, chaos injection, retry.

Three layers, each independently gated so resilience-off programs trace to
a byte-identical jaxpr (pinned by the `jx-resilience-off-identical`
analysis rule):

- `faults` — participation masks (FaultPlan schedules + PRNG dropout)
  threaded through the jitted step; dropped workers keep their residual
  EF accumulator so un-sent mass re-delivers on rejoin;
- `chaos` — deterministic payload perturbation at the wire boundary,
  detected by the `PayloadLayout` checksum word and degraded to a zero
  contribution plus a `checksum_failures` telemetry counter;
- `retry` — host-side exponential backoff for checkpoint/tracking I/O.

Only `retry` is re-exported here: it is pure stdlib, and light importers
(tracking.py) must not drag jax in transitively. Traced consumers import
`faults`/`chaos` directly.
"""

from deepreduce_tpu.resilience.retry import (  # noqa: F401
    DEFAULT_RETRY_ON,
    retry_call,
    retry_io,
)
