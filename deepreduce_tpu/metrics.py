"""Observability: bytes-on-wire accounting and micro-benchmark timers.

Reference parity: GRACE's `tensor_bits` relative-volume prints
(pytorch/deepreduce.py:93-95,148-150), the C++ stats dumps
(compression_utils.hpp:137-148: Initial_Size/Final_Size in bits), and the
`micro-benchmark` wall-time mode (pytorch/deepreduce.py:70-76). On TPU the
volume numbers are computed *statically or on-device* from payload pytrees —
no file dumps in the hot loop; timers use `block_until_ready` in host code.
"""

from __future__ import annotations

import dataclasses
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class WireStats:
    """Per-tensor per-step wire accounting (bits)."""

    index_bits: jax.Array
    value_bits: jax.Array
    dense_bits: jax.Array  # d * 32 (pytorch/deepreduce.py:93)
    # payload-saturation counter: number of tensor payloads whose selection
    # filled every budget slot (bloom nsel == budget) this step. A static
    # budget that chronically saturates silently truncates high-index
    # large-magnitude entries (bloom's FP-aware prefix read drops by
    # ascending index) — training runs watch this instead of discovering the
    # truncation in a loss curve. 0.0 for codecs without a budget notion.
    saturated: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.zeros((), jnp.float32)
    )
    # bits this device moved on the intra-slice ICI fabric (hierarchical
    # exchange only: the slice-mean psum/qar leg plus the key-repair
    # all_gather, ring-adjusted like costmodel's per-collective terms).
    # index_bits/value_bits remain the scarce-link (flat axis or DCN)
    # accounting — total_bits deliberately excludes this counter, so every
    # pre-hier rel_volume number keeps its meaning. The default is a HOST
    # numpy scalar, not jnp.zeros: a jnp constant built while a trace is
    # active is itself a Tracer, and summing Tracers in `combine` would
    # stage an `add 0 0` into every flat-exchange jaxpr — which the
    # committed ANALYSIS.json trace hashes pin byte-identical.
    ici_bits: jax.Array = dataclasses.field(
        default_factory=lambda: np.zeros((), np.float32)
    )

    @property
    def total_bits(self) -> jax.Array:
        return self.index_bits + self.value_bits

    @property
    def dcn_bits(self) -> jax.Array:
        """Alias for the scarce-link volume (index + value bits): what the
        hierarchical exchange moves across DCN, i.e. `total_bits`."""
        return self.total_bits

    def rel_volume(self) -> jax.Array:
        return self.total_bits.astype(jnp.float32) / self.dense_bits.astype(jnp.float32)

    def idx_rel_volume(self) -> jax.Array:
        return self.index_bits.astype(jnp.float32) / self.dense_bits.astype(jnp.float32)

    def val_rel_volume(self) -> jax.Array:
        return self.value_bits.astype(jnp.float32) / self.dense_bits.astype(jnp.float32)


def combine(stats: Dict[str, WireStats]) -> WireStats:
    """Sum wire stats across a gradient pytree's tensors."""
    vals = list(stats.values())
    # ici_bits is only ever set by the hierarchical exchange, AFTER this
    # per-tensor combine — inside the flat exchanges every instance holds
    # its concrete default zero. Summing those on the host (instead of
    # through staged jnp adds) keeps every pre-hier jaxpr byte-identical,
    # which ANALYSIS.json's committed trace hashes pin.
    ici = [s.ici_bits for s in vals]
    if any(isinstance(x, jax.core.Tracer) for x in ici):
        ici_sum = sum(ici)
    else:
        ici_sum = np.float32(sum(float(x) for x in ici))
    return WireStats(
        index_bits=sum(s.index_bits for s in vals),
        value_bits=sum(s.value_bits for s in vals),
        dense_bits=sum(s.dense_bits for s in vals),
        saturated=sum(s.saturated for s in vals),
        ici_bits=ici_sum,
    )


def ring_wire_bytes(buffer_bytes: int, num_workers: int) -> int:
    """Per-worker wire bytes of the explicit W-1-hop ppermute ring exchange
    (comm_ring.py): each worker forwards the B-byte fused buffer W-1 times,
    i.e. (W-1)/W of the total gathered volume W·B. The bulk all_gather path
    reports B (the worker's logical injection; XLA owns the physical
    schedule) — the ring's hops are explicit, so they are accounted
    explicitly."""
    return int(buffer_bytes) * max(0, int(num_workers) - 1)


def payload_device_bytes(payload: Any) -> int:
    """Actual (padded) bytes the allgather moves — the static buffer size, as
    opposed to WireStats' meaningful bits."""
    leaves = jax.tree_util.tree_leaves(payload)
    return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)


@contextmanager
def timed(label: str, enabled: bool = True, sink: Dict[str, float] | None = None) -> Iterator[None]:
    """micro-benchmark timer (the reference's cuda-synchronized prints,
    pytorch/deepreduce.py:70-76). Call inside host code around
    block_until_ready'd work.

    Records in a ``finally`` so a raising body still reports its elapsed
    time. A `sink` always receives the accumulated total; printing happens
    only when `enabled` AND no sink is given — a sink means programmatic
    consumption, not console spam (the two flags used to be tangled:
    sink-only callers could not record silently)."""
    start = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - start
        if sink is not None:
            sink[label] = sink.get(label, 0.0) + elapsed
        elif enabled:
            print(f"{label} time:{elapsed}")
