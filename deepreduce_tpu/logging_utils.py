"""Verbosity-gated debug/metrics dump subsystem.

Reference parity: the C++ `CompressionUtilities` logging layer writes
`fpr.txt`, `policy_errors.txt`, `stats.txt` and full bit-array/values dumps
under ``bloom_logs_path/<rank>/step_<s>/<gradient_id>/``
(compression_utils.hpp:96-176), and the `Logger` TF op dumps the full
gradient (`values.csv`) and fit coefficients (`coefficients.csv`) per
rank/step/gradient at a verbosity frequency (logger.cc:37-52).

TPU version: a host-side `DumpLogger` with the same directory scheme and
file names, driven from *fetched* arrays (numpy) rather than in-kernel
`system("mkdir -p")` calls — debug dumps have no business inside the jit
hot loop on TPU. For in-graph use, `attach` wraps it in `jax.debug.callback`
(CPU/testing only: the axon TPU PJRT has no host callbacks)."""

from __future__ import annotations

import os
import pathlib
from typing import Optional

import numpy as np


class DumpLogger:
    """Per (rank, step, gradient_id) dump directory tree, reference layout."""

    def __init__(self, root: str, rank: int = 0, verbosity: int = 0, frequency: int = 1):
        self.root = pathlib.Path(root)
        self.rank = rank
        self.verbosity = verbosity
        self.frequency = max(1, frequency)

    def enabled(self, step: int) -> bool:
        return self.verbosity > 0 and step % self.frequency == 0

    def _dir(self, step: int, gradient_id: str) -> pathlib.Path:
        path = self.root / str(self.rank) / f"step_{step}" / str(gradient_id)
        path.mkdir(parents=True, exist_ok=True)
        return path

    def log_fpr(self, step: int, gradient_id: str, configured: float, measured: float) -> None:
        """fpr.txt (compression_utils.hpp logging_compressor role)."""
        if not self.enabled(step):
            return
        with open(self._dir(step, gradient_id) / "fpr.txt", "a") as f:
            f.write(f"FalsePositives_Rate: {measured}  (configured: {configured})\n")

    def log_policy_errors(self, step: int, gradient_id: str, errors: int, k: int) -> None:
        """policy_errors.txt: selected indices not in the true set
        (policies.hpp:32-41 get_policy_errors)."""
        if not self.enabled(step):
            return
        with open(self._dir(step, gradient_id) / "policy_errors.txt", "a") as f:
            f.write(f"PolicyErrors: {errors} / {k}\n")

    def log_stats(self, step: int, gradient_id: str, initial_bits: float, final_bits: float) -> None:
        """stats.txt: Initial_Size/Final_Size in bits
        (compression_utils.hpp:145-148)."""
        if not self.enabled(step):
            return
        with open(self._dir(step, gradient_id) / "stats.txt", "a") as f:
            f.write(f"Initial_Size: {int(initial_bits)}   Final_Size: {int(final_bits)}\n")

    def log_values(self, step: int, gradient_id: str, values: np.ndarray) -> None:
        """values.csv — the Logger op's gradient dump (logger.cc:37-52)."""
        if not self.enabled(step):
            return
        np.savetxt(self._dir(step, gradient_id) / "values.csv", np.asarray(values), delimiter=",")

    def log_coefficients(self, step: int, gradient_id: str, coeffs: np.ndarray) -> None:
        """coefficients.csv — fit-coefficient dump for offline curve
        inspection."""
        if not self.enabled(step):
            return
        np.savetxt(
            self._dir(step, gradient_id) / "coefficients.csv", np.asarray(coeffs), delimiter=","
        )


def policy_errors(selected: np.ndarray, true_indices: np.ndarray) -> int:
    """How many selected indices are not true sparsifier indices — the
    diagnostic the C++ policies layer computes (policies.hpp:32-41)."""
    return int(len(np.setdiff1d(np.asarray(selected), np.asarray(true_indices))))
