"""Composable exchange legs: one protocol over every gradient-exchange stack.

Every exchange path in this repo — flat fused allgather, bucketed, ring
decode, the in-collective sparse_rs routes, qar, the two-tier hierarchical
exchange, and the backprop-streamed bucket schedule — shares one shape:

    encode -> collective plan (over named mesh axes) -> decode -> stats

This module names that shape.  `Exchanger` is the structural protocol the
stacks implement (`GradientExchanger`, `HierarchicalExchanger`); `Leg`
describes one stage of a stack's collective plan — which role it plays and
which named mesh axis its collectives ride; `leg_plan` derives the plan of
any built stack by inspection; `build_exchanger` is the one factory that
composes a stack from a config (flat / hier, ctrl-rung substitution aside);
`wrap_streaming` adds the backprop-overlap scheduling leg on top.

Composition facts the plans make visible (enforced by config validation
and the MATRIX audits, not by this module):

- Stacking is by *wrapping*: `HierarchicalExchanger` wraps a flat
  exchanger whose `axis_name` is the dcn axis, and prepends a dense psum
  leg on ici; `StreamingExchange` wraps either and re-schedules the wrapped
  stack's per-bucket legs into custom_vjp backward hooks (the ici leg rides
  INSIDE each bucket's optimization-barrier bracket).
- A leg's wire accounting is axis-local: `payload_bytes()` is the dcn-leg
  (or flat-axis) injection only; ici traffic is reported separately via
  `WireStats.ici_bits` (the jx-wire-accounting rule pins both).
- Resilience is a decode-side leg property: the allgather path scales
  gathered rows, the sparse_rs routes re-own shards over the live set
  (`sparse_rs.owner_permutation`); both renormalize by the live count.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Protocol, Tuple, runtime_checkable

from deepreduce_tpu.config import DeepReduceConfig


@runtime_checkable
class Exchanger(Protocol):
    """The structural protocol every exchange stack implements.

    `axis_name` is the named mesh axis (or axis tuple) the stack's
    collectives ride; `exchange` runs one encode -> collective -> decode
    round inside shard_map and returns (aggregated grads, new residual
    state, WireStats); `payload_bytes` is the static per-worker injection
    on the stack's wire-accounted axis."""

    cfg: DeepReduceConfig

    @property
    def axis_name(self): ...

    def init_state(self, params: Any) -> Any: ...

    def exchange(self, grads: Any, state: Any, *, step, key=None,
                 collect=None, mask=None) -> Tuple[Any, Any, Any]: ...

    def payload_bytes(self, grads_like: Any) -> int: ...


@dataclasses.dataclass(frozen=True)
class Leg:
    """One stage of an exchange stack's collective plan.

    role: 'encode' | 'collective' | 'decode' | 'stats' | 'schedule'
    axis: the named mesh axis the leg's collectives ride (None for
          host/compute-only legs)
    kind: the concrete mechanism, e.g. 'dense-psum', 'fused-allgather',
          'bucketed-allgather', 'ring-permute', 'sparse_rs:oktopk',
          'qar', 'stream-hooks', 'masked-reowner'
    """

    role: str
    axis: Optional[str]
    kind: str

    def __str__(self) -> str:
        ax = self.axis or "-"
        return f"{self.role}@{ax}:{self.kind}"


def _flat_legs(ex, axis) -> Tuple[Leg, ...]:
    """Collective plan of a flat GradientExchanger on `axis`."""
    cfg = ex.cfg
    if cfg.communicator == "qar":
        return (
            Leg("encode", None, "int8-bucket-quantize"),
            Leg("collective", axis, "qar"),
            Leg("decode", None, "dequantize"),
            Leg("stats", None, "wire"),
        )
    if cfg.communicator == "sparse_rs":
        kind = f"sparse_rs:{ex._rs_mode}"
        legs = [Leg("encode", None, "topk-route")]
        if cfg.resilience:
            legs.append(Leg("decode", axis, "masked-reowner"))
        legs += [
            Leg("collective", axis, kind),
            Leg("decode", None, "shard-reselect"),
            Leg("stats", None, "wire"),
        ]
        return tuple(legs)
    if cfg.communicator == "allreduce" or (
        cfg.deepreduce is None and cfg.compressor == "none"
    ):
        return (
            Leg("collective", axis, "dense-psum"),
            Leg("stats", None, "wire"),
        )
    # fused / bucketed allgather family
    gather = (
        "bucketed-allgather" if ex._bucketed is not None else "fused-allgather"
    )
    decode = {
        "loop": "per-worker-loop",
        "vmap": "batched-vmap",
        "ring": "ring-permute",
    }[cfg.decode_strategy]
    legs = [Leg("encode", None, "codec-pack")]
    if cfg.decode_strategy == "ring":
        legs.append(Leg("collective", axis, "ring-permute"))
    else:
        legs.append(Leg("collective", axis, gather))
    if cfg.resilience:
        legs.append(Leg("decode", None, "masked-row-weights"))
    legs += [Leg("decode", None, decode), Leg("stats", None, "wire")]
    return tuple(legs)


def leg_plan(ex) -> Tuple[Leg, ...]:
    """Derive the collective plan of any built exchange stack by
    inspection (duck-typed, like StreamingExchange's hier detection —
    no import cycles, works on wrapped stacks)."""
    # streaming wrapper: re-schedules the wrapped plan into bwd hooks
    if hasattr(ex, "value_and_grad_exchange"):
        inner = ex.hier if getattr(ex, "hier", None) is not None else ex.exchanger
        return (Leg("schedule", None, "stream-hooks"),) + leg_plan(inner)
    # hierarchical wrapper: ici leg + the inner dcn-leg plan
    if hasattr(ex, "ici_axis") and hasattr(ex, "exchanger"):
        ici = (
            Leg("collective", ex.ici_axis, "dense-psum")
            if ex.ici_leg == "dense"
            else Leg("collective", ex.ici_axis, "qar")
        )
        return (ici,) + _flat_legs(ex.exchanger, ex.dcn_axis)
    axis = ex.axis_name
    return _flat_legs(ex, axis)


def describe(ex) -> str:
    """One-line plan description, e.g.
    'stream-hooks | collective@ici:dense-psum | ...'."""
    return " | ".join(str(l) for l in leg_plan(ex))


def build_exchanger(
    grads_like: Any,
    cfg: DeepReduceConfig,
    *,
    axis_name: str = "data",
    num_workers: Optional[int] = None,
    num_slices: Optional[int] = None,
    per_slice: Optional[int] = None,
    profile=None,
    bucket_points=None,
):
    """The one factory from config to composed exchange stack.

    cfg.hier composes the hierarchical wrapper over the (dcn, ici) axes
    (`num_slices`/`per_slice` give the static two-axis geometry);
    otherwise a flat GradientExchanger on `axis_name`/`num_workers`.
    Streaming is a scheduling property of the step, not of the stack —
    wrap the result with `wrap_streaming` (train.make_worker_step does)."""
    if cfg.hier:
        from deepreduce_tpu.parallel.hierarchical import HierarchicalExchanger

        if num_slices is None or per_slice is None:
            raise ValueError(
                "hier exchange needs the static two-axis geometry: "
                "build_exchanger(..., num_slices=..., per_slice=...)"
            )
        return HierarchicalExchanger(
            grads_like, cfg, num_slices=num_slices, per_slice=per_slice,
            profile=profile,
        )
    from deepreduce_tpu.comm import GradientExchanger

    return GradientExchanger(
        grads_like, cfg, axis_name=axis_name, num_workers=num_workers,
        profile=profile, bucket_points=bucket_points,
    )


def wrap_streaming(exchanger):
    """The backprop-overlap scheduling leg: returns a StreamingExchange
    over the stack when cfg.stream_exchange is set, else None. Works over
    flat AND hierarchical stacks (the composed stream-over-hier path runs
    each bucket's ici psum + compressed dcn gather inside the bucket's
    backward hook)."""
    if not exchanger.cfg.stream_exchange:
        return None
    from deepreduce_tpu.comm_stream import StreamingExchange

    return StreamingExchange(exchanger)
