"""Typed configuration with the reference's param-dict key names.

The reference threads one flat `params` dict (serialized as a Python
literal on the CLI, run_deepreduce.sh:35) through every wrapper and codec:
keys ``compressor, compress_ratio, memory, communicator, deepreduce, value,
index, fpr, policy, poly_degree, quantum_num, bucket_size, sort, threshold,
micro-benchmark`` (README.md:30-48). `from_params` accepts exactly that
dict; `DeepReduceConfig` is the typed equivalent.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

# ------------------------------------------------------------------------ #
# machine-readable rejection codes
#
# Every configuration rejection — the __post_init__ raises below and the
# construction-time raises in comm.py — carries one of these codes, so the
# composition-lattice auditor (analysis/lattice.py) can partition the
# feature cross-product into LEGAL/REJECTED cells keyed by code instead of
# scraping prose. The human-readable message stays primary; the code rides
# at the end as `[reason_code=...]`. Codes are registered here so a typo'd
# code fails at raise time and the MATRIX.json code set can be asserted to
# be a subset of this registry.
# ------------------------------------------------------------------------ #

REASON_CODES: Dict[str, str] = {
    # enum membership (config.check): one code per enumerated field
    "enum-compressor": "compressor not in COMPRESSORS",
    "enum-memory": "memory not in MEMORIES",
    "enum-communicator": "communicator not in COMMUNICATORS",
    "enum-deepreduce": "deepreduce not in DEEPREDUCE_MODES",
    "enum-policy": "policy not in POLICIES",
    "enum-value": "value not in VALUE_CODECS",
    "enum-index": "index not in INDEX_CODECS",
    "enum-bloom_blocked": "bloom_blocked not in BLOOM_BLOCKED",
    "enum-rs_mode": "rs_mode not in RS_MODES",
    "enum-bucket_order": "bucket_order not in BUCKET_ORDERS",
    "enum-hier_ici": "hier_ici not in HIER_ICI_LEGS",
    "enum-hier_dcn": "hier_dcn not in HIER_DCN_MODES",
    "enum-decode_strategy": "decode_strategy not in ('loop', 'vmap', 'ring')",
    # scalar range checks
    "rs-block-size-range": "rs_block_size must be a positive multiple of 4",
    "rs-density-threshold-range": "rs_density_threshold outside [0, 1]",
    "rs-sketch-rows-range": "rs_sketch_rows < 1",
    "rs-sketch-cols-range": "rs_sketch_cols < 0",
    "rs-oktopk-bins-range":
        "rs_oktopk_bins not a power of two in [64, 2**24]",
    "rs-oktopk-cap-headroom-range": "rs_oktopk_cap_headroom <= 0",
    "decode-batch-range": "decode_batch < 1",
    "telemetry-every-range": "telemetry_every < 1",
    "bucket-bytes-range": "bucket_bytes < 4 (one f32 element)",
    "ici-size-range": "ici_size < 1",
    "resilience-rate-range": "a drop/chaos rate outside [0, 1]",
    "ctrl-target-range": "ctrl_target_err_cos outside (0, 1]",
    "ctrl-headroom-range": "ctrl_headroom < 0",
    "ctrl-saturation-range": "ctrl_saturation_ceiling < 0",
    "ctrl-hysteresis-range": "ctrl_hysteresis < 1",
    "fed-population-range": "fed_num_clients <= 0 with fed=True",
    "fed-cohort-range": "fed_clients_per_round <= 0 with fed=True",
    "fed-cohort-exceeds-population": "cohort larger than the population",
    "fed-local-steps-range": "fed_local_steps <= 0",
    "fed-server-lr-range": "fed_server_lr <= 0",
    "fed-client-chunk-range": "fed_client_chunk < 0",
    "fed-chunk-divides-cohort": "fed_client_chunk does not divide the cohort",
    # feature-exclusion constraints (the legality matrix proper)
    "rs-mode-needs-sparse-rs": "rs_mode set without communicator='sparse_rs'",
    "bucket-order-needs-buckets": "bucket_order set without bucket_bytes",
    "stream-needs-buckets": "stream_exchange without bucket_bytes",
    "stream-vs-resilience": "stream_exchange cannot thread resilience state",
    "stream-vs-hier":
        "stream_exchange composes over the dense-ICI hier leg only "
        "(allgather, loop/vmap decode, no ctrl/fed)",
    "stream-vs-fed": "stream_exchange hooks a path the fed round never runs",
    "resilience-knobs-disengaged": "resilience knob(s) without resilience=True",
    "resilience-vs-owner-communicator":
        "participation mask cannot re-own this communicator's shards "
        "(qar; sparse_rs adaptive/sketch routes)",
    "chaos-needs-checksum": "chaos injection without payload_checksum",
    "checksum-needs-fused-allgather":
        "payload_checksum outside the fused allgather wire format",
    "hier-knobs-disengaged": "hier knob(s) without hier=True",
    "hier-vs-ring": "ring hop schedule addresses ici replicas under hier",
    "hier-vs-resilience": "per-worker mask cannot mask a slice-mean psum",
    "hier-dcn-auto-needs-topk":
        "hier_dcn='auto' rewrites among plain top-k routes only",
    "rs-oktopk-vs-approx-topk":
        "approximate candidates break the oktopk threshold-count containment",
    "fed-knobs-disengaged": "fed_* knob(s) without fed=True",
    "fed-vs-hier": "the fed round ignores the hierarchical exchange",
    "fed-vs-communicator":
        "the fed round aggregates via ONE fused psum; communicator unused",
    "fed-vs-buckets": "the fed round's TreeCodec path ignores bucket_bytes",
    "fed-vs-decode-strategy":
        "the fed round has no gathered-worker decode to restructure",
    "fed-vs-trainer": "Trainer runs the data-parallel exchange, not fed rounds",
    "fed-async-needs-fed": "fed_async=True without the fed round geometry",
    "fed-async-knobs-disengaged": "fed_async_* knob(s) without fed_async=True",
    "fed-async-k-range": "fed_async_k < 1 with fed_async=True",
    "fed-async-alpha-range": "fed_async_alpha < 0",
    "fed-async-latency-syntax": "fed_async_latency failed parse_latency",
    "fed-mt-needs-fed": "fed_tenants > 0 without the fed round geometry",
    "fed-mt-tenants-range": "fed_tenants < 0",
    "fed-mt-knobs-disengaged": "fed_mt_* knob(s) without fed_tenants >= 1",
    "fed-mt-async-knobs":
        "per-tenant K/alpha/latency knob(s) without fed_async=True",
    "fed-mt-k-syntax": "fed_mt_k failed the per-tenant list parse or has K < 1",
    "fed-mt-alpha-syntax":
        "fed_mt_alpha failed the per-tenant list parse or has alpha < 0",
    "fed-mt-latency-syntax": "fed_mt_latency failed parse_tenant_latency",
    "fed-mt-cohort-syntax":
        "fed_mt_cohort failed the per-tenant list parse or has a size "
        "outside [1, fed_clients_per_round]",
    "pop-needs-fed": "pop_spec without the federated serving path",
    "pop-knobs-disengaged":
        "pop_* knob(s) (or per-class latency rows) without their consumer",
    "pop-vs-mt":
        "pop_spec with fed_tenants >= 1 (per-class and per-tenant "
        "heterogeneity do not compose yet)",
    "pop-labels-range": "pop_labels/num_labels outside its legal range",
    # population spec-file rejections (population/spec.py): the spec
    # parser raises these so a typo'd population spec fails loudly instead
    # of silently serving an IID population
    "pop-spec-syntax": "population spec failed PopulationSpec parse",
    "pop-spec-range": "population spec value outside its legal range",
    "pop-latency-syntax": "a per-class latency row failed parse_latency",
    "slo-needs-fed": "slo_spec without the federated serving path",
    "slo-knobs-disengaged": "slo_* override knob(s) without slo_spec",
    "slo-window-range": "slo_window < 0",
    "slo-hysteresis-range": "slo_hysteresis < 0",
    # SLO spec-file rejections (slo/spec.py): the spec parser raises these
    # so a typo'd slo.json fails loudly instead of silently monitoring
    # nothing
    "slo-spec-syntax": "SLO spec file failed SLOSpec parse",
    "slo-spec-unknown-target": "SLO spec target not in slo.spec.TARGET_KEYS",
    "slo-spec-target-range": "SLO spec target value outside its legal range",
    "slo-spec-window-range": "SLO spec window/hysteresis tick count invalid",
    "slo-spec-tenant-override": "SLO spec per-tenant override malformed",
    "ctrl-knobs-disengaged": "ctrl_* knob(s) without ctrl=True",
    "ctrl-needs-telemetry": "ctrl=True without telemetry=True",
    "ctrl-needs-compressor": "ctrl=True with compressor='none'",
    "ctrl-vs-hier-fed": "ctrl drives the flat exchanger only",
    "profile-needs-auto-selector": "profile without any 'auto' selector",
    "profile-vs-ctrl": "profile and ctrl both own the operating point",
    # syntax checks delegated to the owning subsystem's parser
    "fault-plan-syntax": "fault_plan failed FaultPlan.parse",
    "ctrl-ladder-syntax": "ctrl_ladder failed Ladder.parse",
    # exchanger-construction rejections (comm.py): combos the config cannot
    # see alone (they need the fused/bucketed build context)
    "build-qar-codec-stack":
        "qar quantizes in-collective; codec/memory stack would be ignored",
    "build-sparse-rs-codec-stack":
        "sparse_rs routes its own top-k; codec stack would be ignored",
    "build-rs-auto-needs-workers": "rs_mode='auto' needs the static mesh size",
    "build-buckets-need-fused-allgather":
        "bucket_bytes outside the fused allgather exchange",
    "build-buckets-vs-ring": "bucket_bytes would nest two pipelines under ring",
    "build-buckets-need-compression": "bucket_bytes on the dense psum baseline",
    "build-buckets-vs-layer-pattern": "fused buckets dissolve leaf identity",
    "build-bucket-points-need-buckets": "bucket_points without bucket_bytes",
    "build-decode-strategy-needs-fused-allgather":
        "vmap/ring decode outside the fused allgather exchange",
}


class ConfigError(ValueError):
    """A rejected configuration, tagged with a machine-readable reason code.

    Subclasses ValueError so every existing `except ValueError` /
    `pytest.raises(ValueError, match=...)` contract keeps working; the code
    is appended to the message and exposed as `.reason_code` for the
    composition-lattice auditor."""

    def __init__(self, reason_code: str, message: str):
        if reason_code not in REASON_CODES:
            raise AssertionError(
                f"unregistered reason_code {reason_code!r} — add it to "
                "config.REASON_CODES"
            )
        super().__init__(f"{message} [reason_code={reason_code}]")
        self.reason_code = reason_code


def reason_code_of(exc: BaseException) -> Optional[str]:
    """The machine-readable rejection code of a config/build error, or None
    for a plain (uncoded) exception."""
    return getattr(exc, "reason_code", None)


@dataclasses.dataclass(frozen=True)
class DeepReduceConfig:
    # sparsifier (GRACE 'compressor' role)
    # topk_sampled = sortless O(d) sampled-quantile top-k (sparse.py
    # topk_sampled): no top_k/sort over d, nnz <= k dynamic — candidate
    # replacement for approx_topk on TPU, pending the silicon A/B
    compressor: str = "topk"  # topk | topk_sampled | randomk | threshold | none
    compress_ratio: float = 0.01
    threshold_val: float = 0.0
    approx_topk: bool = False  # TPU-native approx_max_k sparsifier (~4x faster)
    # topk_sampled tuning: sample size for the quantile estimate, and the
    # capture-undershoot factor (expected captures = undershoot*k; lower =
    # fewer truncation risks / lower recall — sparse.topk_sampled)
    topk_sample_size: int = 1 << 15
    topk_undershoot: float = 0.9
    # residual error-feedback (GRACE 'memory' role)
    memory: str = "residual"  # residual | none
    beta: float = 1.0
    gamma: float = 1.0
    # collective (GRACE 'communicator' role). 'qar' = int8 quantized
    # reduce-scatter+allgather (qar.py) — a TPU-native third shape beyond
    # the reference's two
    # 'sparse_rs' = sparse reduce-scatter+allgather (sparse_rs.py, the
    # Ok-Topk/SparCML shape): O(k) per-worker decode vs allgather's O(W*k)
    communicator: str = "allgather"  # allgather | allreduce | qar | sparse_rs
    # DeepReduce wrapper mode (README.md:31-35)
    deepreduce: Optional[str] = None  # None | 'value' | 'index' | 'both'
    value: str = "polyfit"  # polyfit | doubleexp | qsgd | gzip
    index: str = "bloom"  # bloom | rle | integer | huffman (+ *_native)
    # codec knobs
    fpr: Optional[float] = None  # default 0.1*k/d (pytorch/deepreduce.py:511)
    # conflict_sets = exact P2, native/host only (as in the reference);
    # conflict_sets_approx = in-graph parallel P2 redesign, runs on TPU
    policy: str = "leftmost"  # leftmost | random | p0 | conflict_sets(native) | conflict_sets_approx
    # register-blocked filter (~1.5x filter size for equal FPR): all h bits
    # of a key live in one 32-bit word. False = classic; 'hash' = block by
    # hash (1 gather per universe query); True or 'mod' = block by j mod W,
    # W odd — the universe query becomes a pure broadcast, zero gathers
    # (measured-fastest TPU variant)
    bloom_blocked: Any = False  # False | True | 'hash' | 'mod'
    # mod-blocked encode variant: build the filter from |dense| >= t (t =
    # smallest kept magnitude) as a pure elementwise pass over the [rows, W]
    # layout — zero scatters. The inserted set is the threshold superset of
    # the sparsifier's selection (ties and any approx-top-k misses above t
    # join the filter; bloom membership is a superset contract, and the
    # FP-aware re-read keeps decoded values true). Off by default pending
    # an on-silicon A/B against the unique-scatter insert.
    bloom_threshold_insert: bool = False
    # native integer-codec family member for index='integer_native' — the
    # reference op's string attr `code` routed through
    # CODECFactory::getFromName (integer_compression.cc:62)
    code: str = "fbp"  # fbp | varint | pfor
    poly_degree: int = 5
    quantum_num: int = 127
    bucket_size: int = 512
    sort: bool = False
    seed: int = 0
    # sparse_rs phase-1 per-shard budget multiplier over the expected k/W
    # occupancy; overflow mass stays in the sender's residual
    rs_headroom: float = 2.0
    # sparse_rs phase-2 output budget multiplier: 1.0 = the Ok-Topk
    # output-volume convention (k entries total); raise to trade wire bytes
    # for coverage of shard-occupancy fluctuations
    rs_out_headroom: float = 1.0
    # sparse_rs route (sparse_rs.py):
    #   'sparse'    — the two-phase sparse reduce-scatter (pre-r11 trace,
    #                 byte-identical when selected)
    #   'adaptive'  — same phase 1; phase 2 switches per worker between
    #                 (values, indices) and an int8 block-quantized dense
    #                 shard on a traced density estimate (SparCML switch)
    #   'quantized' — EQuARX arm: int8 psum_scatter against pmax-shared
    #                 per-block norms, then the sparse phase 2
    #   'sketch'    — S2-Reducer arm: count-sketched top-k summed by one
    #                 psum, per-shard unsketch, then the sparse phase 2
    #   'oktopk'    — Ok-Topk balanced arm: psum'd magnitude histogram picks
    #                 one global threshold (~k survivors TOTAL), survivors
    #                 route via a W×-smaller all_to_all, then the sparse
    #                 phase 2; spill and sub-threshold mass stay in the
    #                 residual
    #   'auto'      — costmodel.select_rs_mode picks from (d, W, ratio) at
    #                 construction via the W-aware ring wire model
    rs_mode: str = "sparse"  # sparse | adaptive | quantized | sketch | oktopk | auto
    # quantization block length (elements) for the adaptive dense rows and
    # the quantized arm — one f32 norm per block on the wire. Distinct from
    # `bucket_size` (QSGD codec / qar communicator bucket length).
    rs_block_size: int = 256
    # adaptive switch point: a worker's phase-2 row goes dense when its
    # reduced shard's live fraction exceeds this. 1.0 = never (density is
    # capped at 1.0), so the default adaptive trace equals the sparse route
    # unless the threshold is lowered.
    rs_density_threshold: float = 1.0
    # count-sketch geometry for rs_mode='sketch': rows of the table, and
    # its width (0 = auto-size to ~2k/rows buckets)
    rs_sketch_rows: int = 5
    rs_sketch_cols: int = 0
    # oktopk histogram resolution: power-of-two bucket count of the psum'd
    # bit-pattern magnitude histogram (4096 = 16 sub-bins per f32 exponent
    # octave, ~4% relative threshold granularity; bins*4 bytes ride the
    # psum, so more bins = finer threshold but a larger fixed wire term)
    rs_oktopk_bins: int = 4096
    # oktopk per-(worker, shard) capacity multiplier over the expected
    # k/W**2 survivor occupancy; overflow spills into the sender's residual
    rs_oktopk_cap_headroom: float = 2.0
    use_pallas: bool = False  # pallas TPU kernels where applicable (QSGD PRNG)
    # fuse the whole pytree's payloads into ONE uint8 buffer per step and
    # run a single all_gather + one worker-decode loop, instead of one
    # collective per tensor (ResNet-50 would otherwise issue ~160
    # latency-bound collectives per step). False = per-tensor collectives
    # (the reference's shape, one allgather per hook fire,
    # pytorch/deepreduce.py:54-61).
    fused: bool = True
    # fused-exchange decode strategy (comm.py / comm_ring.py). How the W
    # gathered payloads become one aggregate:
    #   'loop' — sequential fori_loop over workers (one decode program per
    #            iteration; lowest peak memory, O(W*d) serial critical path)
    #   'vmap' — the gathered [W, B] buffer is decoded in groups of
    #            `decode_batch` workers under jax.vmap (one batched kernel
    #            per group; peak memory bounded at decode_batch dense
    #            tensors instead of W)
    #   'ring' — no all_gather at all: W-1 lax.ppermute hops over the fused
    #            uint8 buffer, double-buffered so the permute of chunk w+1
    #            overlaps the decode+accumulate of chunk w; the own-payload
    #            decode for residual feedback falls out of step 0 for free
    # All three produce the same aggregate up to f32 sum associativity
    # ('ring' accumulates in ring order, which differs per worker).
    decode_strategy: str = "loop"  # loop | vmap | ring
    # 'vmap' group size: workers decoded per batched kernel. Bounds the
    # W-way peak-memory blowup the sequential loop was avoiding.
    decode_batch: int = 4
    # bucketed fused exchange (comm_bucket.py): partition the gradient
    # pytree into size-balanced buckets of <= bucket_bytes dense f32 bytes
    # (small leaves concatenated into one contiguous super-tensor per
    # bucket, big leaves solo) and run ONE TensorCodec + ONE all_gather per
    # BUCKET instead of per leaf — encode fixed cost drops from O(leaves)
    # to O(buckets) on many-leaf models (StackOverflow LSTM, MobileNet's
    # dozens of BN/bias tensors). None = per-leaf codecs (the default
    # fused shape). Distinct from `bucket_size`, which is the QAR
    # quantization bucket length in elements.
    bucket_bytes: Optional[int] = None
    # software-pipeline the per-bucket collectives: dispatch the all_gather
    # for bucket b+1 before decoding bucket b, so XLA overlaps the next
    # transfer with the current decode (the SparCML streaming shape).
    # False = gather every bucket, then decode (barrier shape, for A/Bs).
    bucket_pipeline: bool = True
    # bucket-list ordering policy (comm_bucket.partition_buckets):
    #   'trace'   — buckets ordered by earliest member leaf in pytree
    #               (forward-trace) order; the r09 default, byte-identical
    #   'reverse' — backward-completion order: small leaves packed as
    #               contiguous reverse-trace runs and the bucket list
    #               sorted by when backprop produces each bucket's LAST
    #               member gradient, so streaming buckets close as early
    #               as possible. Deterministic from (name, size) alone.
    bucket_order: str = "trace"  # trace | reverse
    # backprop-overlapped streaming exchange (comm_stream.py): wrap the
    # loss in per-bucket custom_vjp hooks so each bucket's encode +
    # all_gather dispatches the moment backprop produces its last member
    # gradient — interleaved with the remaining backward compute via an
    # optimization_barrier-pinned token chain — instead of after the full
    # value_and_grad. Bitwise identical to the bucket_pipeline schedule
    # (same codecs, same PRNG keys, same wire bytes); only the dispatch
    # order moves. Requires bucket_bytes.
    stream_exchange: bool = False
    # small-tensor bypass (pytorch/deepreduce.py:68). None = the reference
    # default for the selected codec: 1000 (PyTorch generic gate), or 9000
    # when value='doubleexp' (tensorflow/deepreduce.py:396,426). An explicit
    # int always wins.
    min_compress_size: Optional[int] = None
    # per-layer whitelist: regex on the tensor's pytree path; non-matching
    # tensors pass through uncompressed. The data-driven form of TF PolySeg's
    # hard-coded conv-layer whitelist (tensorflow/deepreduce.py:458,526
    # is_convolutional) — e.g. layer_pattern='Conv|kernel'
    layer_pattern: Optional[str] = None
    # observability
    micro_benchmark: bool = False
    # telemetry subsystem (deepreduce_tpu.telemetry): thread the on-device
    # MetricAccumulators pytree through the jitted step and enable span
    # tracing in the drivers. Off by default — the telemetry-off step
    # program is byte-identical to a build without telemetry (pinned by the
    # retrace-hash test), so this knob is provably free when False.
    telemetry: bool = False
    # host fetch cadence for the accumulators (steps between device->host
    # syncs of the ten-scalar pytree); the hot loop itself never syncs
    telemetry_every: int = 10
    # resilience subsystem (deepreduce_tpu.resilience): elastic
    # participation + chaos injection + graceful degradation for the
    # compressed exchange. Off by default — the resilience-off step program
    # is byte-identical to a build without the subsystem (pinned by the
    # jx-resilience-off-identical analysis rule and the retrace-hash test).
    resilience: bool = False
    # per-step PRNG worker dropout: each step, every worker is dropped from
    # the exchange with this probability (the mask is derived from the
    # step's shared key, so all workers agree on who is live). Dropped
    # workers contribute zero payload; the mean renormalizes by live count
    # and un-sent gradient mass stays in the dropped worker's residual.
    drop_rate: float = 0.0
    # deterministic fault schedule: comma-separated `worker@start:stop`
    # (worker dropped for steps start <= t < stop) or `worker@step` (one
    # step), e.g. "2@5:9,0@12". Composes with drop_rate (AND of both masks).
    fault_plan: Optional[str] = None
    # append a 4-byte checksum word to every PayloadLayout buffer and
    # verify it on decode: a failed payload degrades to zero contribution
    # plus a `checksum_failures` telemetry count instead of NaN. Requires
    # the fused allgather exchange (the wire format that has a layout).
    payload_checksum: bool = False
    # chaos injector (resilience/chaos.py): deterministic per-(step,worker)
    # wire-boundary perturbations of the packed payload, keyed from `seed`.
    # All three require payload_checksum so the damage is detected and
    # degraded instead of silently decoded.
    chaos_drop_rate: float = 0.0      # P(whole payload zeroed — never arrives)
    chaos_corrupt_rate: float = 0.0   # P(random bytes XOR-flipped)
    chaos_truncate_rate: float = 0.0  # P(trailing half of the buffer zeroed)
    # hierarchical two-axis exchange (parallel/hierarchical.py): reduce the
    # gradient densely (or int8-quantized) over the fast intra-slice ICI
    # axis first, then run the compressed exchange this config describes
    # across slices only, on the scarce DCN axis. The Trainer builds a
    # (dcn, ici) mesh and shard_maps over both axes when this is on.
    hier: bool = False
    # devices per slice = the ici-axis extent. The Trainer needs it to
    # build the two-axis mesh (dcn extent = device_count // ici_size);
    # None defers to an explicitly passed two-axis mesh.
    ici_size: Optional[int] = None
    # ICI-leg algorithm: 'dense' = f32 psum of the slice mean; 'qar' =
    # int8 block-quantized allreduce reusing qar.py's bucket helpers
    # (pays ~9 bits/element on ICI instead of 32); 'auto' = let
    # costmodel.select_hier_plan argmin both legs at construction.
    hier_ici: str = "dense"  # dense | qar | auto
    # DCN-leg selection: 'config' = run exactly the communicator/codec
    # stack this config describes across slices; 'auto' = rewrite the
    # cross-slice route to costmodel.select_hier_plan's argmin (fused
    # allgather vs the sparse_rs routes) at construction.
    hier_dcn: str = "config"  # config | auto
    # federated simulation subsystem (deepreduce_tpu.fedsim): population-
    # scale FedAvg rounds — cohorts sampled per round, sharded over the mesh
    # worker axis, executed as vmapped client batches inside one jitted
    # round step. Off by default; the knobs below describe the round
    # geometry the drivers (fedsim CLI, bench --fed-sweep) build their
    # `FedConfig` from.
    fed: bool = False
    # population size: total simulated clients, each holding a persistent
    # per-client error-feedback residual row in the device-sharded bank
    fed_num_clients: int = 0
    # cohort size: clients sampled (without replacement) per round; must
    # divide evenly across the mesh worker axis at driver construction
    fed_clients_per_round: int = 0
    # local SGD steps per sampled client per round (paper §6.2 E)
    fed_local_steps: int = 1
    # server-side step size applied to the renormalized cohort mean
    fed_server_lr: float = 1.0
    # peak-memory bound for the vmapped cohort: > 0 scans over blocks of
    # this many vmapped clients per worker instead of one [C_local, ...]
    # batch (must divide the per-worker cohort). 0 = single vmap block.
    fed_client_chunk: int = 0
    # asynchronous buffered aggregation (FedBuff-style): the jitted round
    # becomes an ingest *tick* that accumulates staleness-weighted client
    # deltas into a server-side buffer carried across steps, applying a
    # buffered update whenever fed_async_k contributions have arrived.
    # Off by default: fed_async=False leaves the synchronous round program
    # byte-identical to the pre-async driver (pinned by the fedsim:round
    # audit spec).
    fed_async: bool = False
    # apply threshold K: the server applies the buffered update once the
    # buffer holds >= K live contributions (K may exceed the per-tick
    # cohort — the buffer then fills across ticks). Required >= 1 when
    # fed_async=True.
    fed_async_k: int = 0
    # staleness exponent alpha: a contribution trained from the model as of
    # tau server versions ago is down-weighted by 1/(1+tau)^alpha. 0.0 is
    # identity weighting (every live contribution weighs 1.0 — the
    # degenerate case that is bitwise-equal to the synchronous round when
    # K == cohort and the latency distribution is zero).
    fed_async_alpha: float = 0.0
    # per-client latency distribution over staleness tau = 0, 1, 2, ...:
    # comma-separated non-negative weights, e.g. "0.6,0.3,0.1" (normalized
    # at parse). Drawn deterministically per (round key, cohort position)
    # like FaultPlan churn, so every worker agrees without a collective.
    # "" = zero latency (every client trains from the current model).
    fed_async_latency: str = ""
    # multi-tenant federated serving: T independent (model, population)
    # pairs stacked along a leading tenant axis and vmapped through the ONE
    # jitted round/tick program, so codec tracing, cohort sampling, and the
    # single fused psum (tuple operands grow a tenant dim; collective count
    # stays independent of T) amortize across tenants. 0 (default) is the
    # plain single-tenant driver — its state pytrees and traced programs
    # are untouched (pinned by the fedsim:round / fedsim:async-round audit
    # specs); >= 1 builds the stacked MultiTenantState with an active-mask
    # ring of tenant slots (tenants join/leave without retracing).
    fed_tenants: int = 0
    # per-tenant apply thresholds K (async): comma-separated ints, one per
    # tenant (or one value broadcast to the fleet). "" = fed_async_k for
    # every tenant. K is a TRACED buffer leaf, so a K-heterogeneous fleet
    # shares one compiled tick.
    fed_mt_k: str = ""
    # per-tenant staleness exponents alpha (async): comma-separated floats,
    # broadcast like fed_mt_k. "" = fed_async_alpha everywhere. Rides as a
    # traced f32[T] operand — re-knobbing a tenant's alpha never retraces.
    fed_mt_alpha: str = ""
    # per-tenant latency distributions (async): semicolon-separated
    # parse_latency comma lists, e.g. "0.5,0.3,0.2;1;0.7,0.3", zero-padded
    # to the fleet's common overlap depth D = max over tenants (padding is
    # draw-preserving). "" = fed_async_latency everywhere. The normalized
    # rows ride as a traced f32[T, D] operand.
    fed_mt_latency: str = ""
    # per-tenant effective cohort sizes: comma-separated ints <= the shared
    # fed_clients_per_round C (broadcast like fed_mt_k). A tenant with
    # c_t < C gates cohort positions >= c_t out of its round (they never
    # transmit), so tenant fleets with different per-round demand share the
    # one static [C]-shaped program; c_t is a traced f32[T] operand. "" =
    # every tenant runs the full cohort, and NO gate ops are staged.
    fed_mt_cohort: str = ""
    # heterogeneous population plane (deepreduce_tpu.population): a
    # schema-validated PopulationSpec — a JSON file path OR an inline JSON
    # object (leading '{') — assigning every client in the residual bank
    # to a class with three heterogeneity axes: Dirichlet data skew (the
    # in-trace non-IID generator), a per-class latency row (replacing the
    # single global fed_async_latency for that class's clients), and a
    # compute multiplier priced by costmodel. None (default) is the IID
    # population — the round/tick programs are byte-identical to a build
    # without the subsystem (pinned by the fedsim:round / async-round
    # audit specs), and the uniform single-class spec is bitwise identical
    # to None (params AND residual bank, sync and async).
    pop_spec: Optional[str] = None
    # label-universe override for the non-IID generator (>= 2); 0
    # (default) keeps the spec file's num_labels
    pop_labels: int = 0
    # adaptive compression controller (deepreduce_tpu.controller): every
    # `telemetry_every` steps the Trainer feeds the fetched
    # MetricAccumulators window delta to a host-side controller that moves
    # compress_ratio/fpr along the discrete `ctrl_ladder` of pre-declared
    # operating points — one static step program per rung, so re-jit is
    # bounded at len(ladder) (pinned by the jx-ctrl-ladder analysis rule).
    # Off by default: the ctrl-off step program is byte-identical to a
    # build without the subsystem. Requires telemetry=True (the controller
    # reads only the fetch the trainer was already doing — zero extra
    # hot-loop syncs).
    ctrl: bool = False
    # the operating-point ladder: comma-separated `ratio` or `ratio@fpr`
    # entries with strictly increasing ratios (controller/ladder.py). The
    # run starts at the rung nearest compress_ratio and moves ±1 rung per
    # decision.
    ctrl_ladder: str = "0.005,0.01,0.02,0.05"
    # window mean compress-error cosine the controller defends: below it
    # the controller votes for more wire budget (a higher rung)
    ctrl_target_err_cos: float = 0.97
    # fidelity surplus before spending it: window err_cos must exceed
    # target + headroom before the controller votes to step down a rung
    ctrl_headroom: float = 0.015
    # saturated payloads per step above which the controller votes up
    # regardless of err_cos. Effectively disabled by default (1e9): top-k
    # selection fills its budget by construction (nsel == k flags every
    # payload every step), so saturation is an anomaly signal only for the
    # threshold-superset encodes — set a small finite ceiling with those
    ctrl_saturation_ceiling: float = 1e9
    # consecutive same-direction votes required before a move; any hold or
    # opposite vote resets the streak (anti-oscillation)
    ctrl_hysteresis: int = 2
    # fitted machine profile (costmodel.MachineProfile JSON, written by
    # `python -m deepreduce_tpu.telemetry calibrate RUN --out P.json`): the
    # 'auto' selectors (rs_mode='auto', hier_ici/hier_dcn='auto') argmin
    # over the profile's measured bandwidths/overheads instead of the
    # static constants. None (default) keeps every selection byte-identical
    # to the constants; a profile that agrees with the constants changes
    # nothing (pinned by the jx-calib-reselect analysis rule). Requires an
    # 'auto' selector to consume it — a fully explicit plan has nothing for
    # the profile to re-select.
    profile: Optional[str] = None
    # SLO health plane (deepreduce_tpu.slo): path to a schema-validated
    # SLOSpec JSON. The monitor it configures is host-side only — a pure
    # function of the telemetry report stream, exactly like the r14
    # controller — so the traced tick programs are byte-identical with or
    # without it; the on-device half (the staleness histogram riding the
    # one fused psum) is keyed off telemetry+fed_async, not this knob.
    # None (default) = no health plane.
    slo_spec: Optional[str] = None
    # rolling-window override (ticks) applied over the spec file's
    # window_ticks; 0 (default) keeps the spec value
    slo_window: int = 0
    # hysteresis override (consecutive same-direction evaluations before
    # a state transition); 0 (default) keeps the spec value
    slo_hysteresis: int = 0

    # the documented enumerations (comments above + codecs/registry.py).
    # __post_init__ checks against these so a typo like
    # communicator='allgater' fails at construction with the valid set in
    # the message, not three layers deep inside a trace.
    COMPRESSORS = ("topk", "topk_sampled", "randomk", "threshold", "none")
    MEMORIES = ("residual", "none")
    COMMUNICATORS = ("allgather", "allreduce", "qar", "sparse_rs")
    DEEPREDUCE_MODES = (None, "value", "index", "both")
    VALUE_CODECS = ("polyfit", "polyfit_host", "polyseg", "doubleexp", "qsgd", "gzip",
                    "countsketch")
    INDEX_CODECS = ("bloom", "bloom_native", "integer_native", "rle", "integer",
                    "huffman")
    POLICIES = ("leftmost", "random", "p0", "conflict_sets", "conflict_sets_approx")
    BLOOM_BLOCKED = (False, True, "hash", "mod")
    RS_MODES = ("sparse", "adaptive", "quantized", "sketch", "oktopk", "auto")
    HIER_ICI_LEGS = ("dense", "qar", "auto")
    HIER_DCN_MODES = ("config", "auto")
    BUCKET_ORDERS = ("trace", "reverse")

    def __post_init__(self):
        def check(name, value, allowed):
            if value not in allowed:
                raise ConfigError(
                    f"enum-{name}",
                    f"{name} must be one of {allowed}, got {value!r}",
                )

        check("compressor", self.compressor, self.COMPRESSORS)
        check("memory", self.memory, self.MEMORIES)
        check("communicator", self.communicator, self.COMMUNICATORS)
        check("deepreduce", self.deepreduce, self.DEEPREDUCE_MODES)
        check("policy", self.policy, self.POLICIES)
        # value/index are only consulted when the deepreduce wrapper engages
        # that side, but an invalid name is a typo in every mode — reject it
        # before it becomes a KeyError inside the registry
        check("value", self.value, self.VALUE_CODECS)
        check("index", self.index, self.INDEX_CODECS)
        check("bloom_blocked", self.bloom_blocked, self.BLOOM_BLOCKED)
        check("rs_mode", self.rs_mode, self.RS_MODES)
        if self.rs_mode != "sparse" and self.communicator != "sparse_rs":
            raise ConfigError(
                "rs-mode-needs-sparse-rs",
                f"rs_mode={self.rs_mode!r} selects a sparse_rs route and "
                "would be silently ignored with "
                f"communicator={self.communicator!r} — use "
                "communicator='sparse_rs' (or drop rs_mode)"
            )
        if self.rs_block_size < 4 or self.rs_block_size % 4:
            raise ConfigError(
                "rs-block-size-range",
                "rs_block_size must be a positive multiple of 4 (int8 levels "
                f"ride bitcast 4-per-f32-lane), got {self.rs_block_size}"
            )
        if not 0.0 <= self.rs_density_threshold <= 1.0:
            raise ConfigError(
                "rs-density-threshold-range",
                "rs_density_threshold is a live fraction of the reduced "
                f"shard and must be in [0, 1], got {self.rs_density_threshold}"
            )
        if self.rs_sketch_rows < 1:
            raise ConfigError(
                "rs-sketch-rows-range",
                f"rs_sketch_rows must be >= 1, got {self.rs_sketch_rows}"
            )
        if self.rs_sketch_cols < 0:
            raise ConfigError(
                "rs-sketch-cols-range",
                "rs_sketch_cols must be >= 1, or 0 to auto-size (~2k/rows), "
                f"got {self.rs_sketch_cols}"
            )
        b = self.rs_oktopk_bins
        if b < 64 or b > (1 << 24) or (b & (b - 1)) != 0:
            raise ConfigError(
                "rs-oktopk-bins-range",
                "rs_oktopk_bins must be a power of two in [64, 2**24] (the "
                "bit-pattern bucket shift needs an exact log2 and the "
                f"histogram must fit the psum), got {b}"
            )
        if self.rs_oktopk_cap_headroom <= 0.0:
            raise ConfigError(
                "rs-oktopk-cap-headroom-range",
                "rs_oktopk_cap_headroom scales the per-(worker, shard) "
                f"capacity and must be > 0, got {self.rs_oktopk_cap_headroom}"
            )
        if self.rs_mode == "oktopk" and self.approx_topk:
            raise ConfigError(
                "rs-oktopk-vs-approx-topk",
                "rs_mode='oktopk' solves its global threshold against the "
                "psum'd candidate histogram, which is only unbiased when the "
                "local candidate set is the EXACT top-k — approx_topk=True "
                "can miss above-threshold entries and skew the survivor "
                "count; use exact top-k with oktopk"
            )
        if self.decode_strategy not in ("loop", "vmap", "ring"):
            raise ConfigError(
                "enum-decode_strategy",
                f"decode_strategy must be 'loop', 'vmap' or 'ring', got "
                f"{self.decode_strategy!r}"
            )
        if self.decode_batch < 1:
            raise ConfigError(
                "decode-batch-range",
                f"decode_batch must be >= 1, got {self.decode_batch}"
            )
        if self.telemetry_every < 1:
            raise ConfigError(
                "telemetry-every-range",
                f"telemetry_every must be >= 1, got {self.telemetry_every}"
            )
        if self.bucket_bytes is not None and self.bucket_bytes < 4:
            raise ConfigError(
                "bucket-bytes-range",
                "bucket_bytes must be >= 4 (one f32 element) or None, got "
                f"{self.bucket_bytes}"
            )
        check("bucket_order", self.bucket_order, self.BUCKET_ORDERS)
        if self.bucket_order != "trace" and self.bucket_bytes is None:
            raise ConfigError(
                "bucket-order-needs-buckets",
                f"bucket_order={self.bucket_order!r} orders the bucketed "
                "exchange's partition and would be silently ignored with "
                "bucket_bytes=None — set bucket_bytes (or drop bucket_order)"
            )
        # --- streaming exchange: loud failure for silently-ignored or
        # --- structurally impossible combinations ---
        if self.stream_exchange and self.bucket_bytes is None:
            raise ConfigError(
                "stream-needs-buckets",
                "stream_exchange=True streams the BUCKETED exchange out of "
                "the backward pass (one custom_vjp hook per bucket) — with "
                "bucket_bytes=None there is no bucket partition to stream. "
                "Set bucket_bytes (or drop stream_exchange)"
            )
        if self.stream_exchange and self.resilience:
            # The hooks fire per bucket DURING backprop, but the
            # participation mask / chaos / checksum state is derived once
            # per step and threaded through the single exchange call —
            # there is no sound place to rebuild it inside a custom_vjp
            # backward rule without replicating the mask derivation per
            # bucket (and the checksum-failure counter is accumulated
            # across buckets in one spot). Until the hooks learn to thread
            # resilience state, the combination fails loudly here.
            raise ConfigError(
                "stream-vs-resilience",
                "stream_exchange=True dispatches each bucket from inside a "
                "custom_vjp backward rule, which does not thread the "
                "resilience subsystem's participation mask / chaos / "
                "checksum state — run streaming without resilience, or the "
                "barrier/pipeline schedules with it"
            )
        if self.stream_exchange and self.hier:
            # Streaming composes with the hierarchical schedule on exactly
            # one shape of the plan space: the dense-ICI, config-pinned-DCN,
            # bucketed-allgather leg stack. There the custom_vjp hooks run
            # each bucket's ICI slice-mean psum AND its compressed DCN
            # gather inside backprop, with optimization_barrier tokens
            # pinning the per-axis collective order (comm_stream.py).
            # Everything else keeps the loud fence: a qar ICI leg and an
            # auto-rewritten DCN route restructure the legs per step, the
            # ring decode addresses flat peers, and the ctrl/fed planes
            # rebuild the exchanger the hooks captured.
            composable_hier_stream = (
                self.communicator == "allgather"
                and self.hier_ici == "dense"
                and self.hier_dcn == "config"
                and self.decode_strategy in ("loop", "vmap")
                and not self.ctrl
                and not self.fed
                and not self.fed_async
                and self.fed_tenants == 0
            )
            if not composable_hier_stream:
                raise ConfigError(
                    "stream-vs-hier",
                    "stream_exchange=True over hier=True composes only as "
                    "the dense-ICI + config-pinned bucketed-allgather DCN "
                    "leg stack (communicator='allgather', hier_ici='dense', "
                    "hier_dcn='config', decode_strategy in loop/vmap, no "
                    "ctrl/fed planes) — this config restructures a leg the "
                    "streaming hooks captured at trace time"
                )
        if self.stream_exchange and self.fed:
            raise ConfigError(
                "stream-vs-fed",
                "stream_exchange=True hooks the Trainer's per-step "
                "value_and_grad; the federated round (fed=True) aggregates "
                "client deltas through its own vmapped path and would "
                "silently ignore it — drop one of the two"
            )
        # --- resilience surface: loud failure for silently-ignored knobs ---
        for rate_name in (
            "drop_rate", "chaos_drop_rate", "chaos_corrupt_rate",
            "chaos_truncate_rate",
        ):
            rate = getattr(self, rate_name)
            if not 0.0 <= rate <= 1.0:
                raise ConfigError(
                    "resilience-rate-range",
                    f"{rate_name} must be in [0, 1], got {rate}"
                )
        engaged = [
            name
            for name, default in (
                ("drop_rate", 0.0),
                ("fault_plan", None),
                ("payload_checksum", False),
                ("chaos_drop_rate", 0.0),
                ("chaos_corrupt_rate", 0.0),
                ("chaos_truncate_rate", 0.0),
            )
            if getattr(self, name) != default
        ]
        if engaged and not self.resilience:
            raise ConfigError(
                "resilience-knobs-disengaged",
                f"{', '.join(engaged)} configure the resilience subsystem "
                "and would be silently ignored with resilience=False — set "
                "resilience=True (or drop the knob(s))"
            )
        if self.resilience and self.communicator not in ("allgather", "allreduce"):
            # Shard ownership used to fence resilience off EVERY sparse_rs
            # route: the static all_to_all/psum_scatter routing makes each
            # worker the owner of one universe shard, so a dropped worker's
            # shard would black-hole for every survivor. The sparse /
            # quantized / oktopk routes now re-own shards under the mask — a
            # traced permutation of the live set (owner_of[s]) re-assigns a
            # dropped owner's shard to a live deputy inside the SAME static
            # trace, and the decode renormalizes by the live count like the
            # allgather path (sparse_rs.py). That carve-out is exactly the
            # flat loop-decoded sparse_rs exchange: the adaptive lane split
            # and the sketch route still bake per-worker state into the
            # wire layout (no deputy can reproduce a dead worker's lanes /
            # sketch rows), and the bucketed / hier / streaming / fed
            # shapes never thread the mask to the reduce-scatter leg.
            reowned_sparse_rs = (
                self.communicator == "sparse_rs"
                and self.rs_mode in ("sparse", "quantized", "oktopk", "auto")
                and not self.hier
                and not self.stream_exchange
                and self.decode_strategy == "loop"
                and self.bucket_bytes is None
                and not self.fed
                and not self.fed_async
                and self.fed_tenants == 0
            )
            if not reowned_sparse_rs:
                raise ConfigError(
                    "resilience-vs-owner-communicator",
                    "resilience=True threads a participation mask through "
                    "the exchange, which the allgather/allreduce "
                    "communicators and the flat loop-decoded sparse_rs "
                    "routes (rs_mode sparse/quantized/oktopk/auto, no "
                    "buckets/hier/stream/fed) support — communicator="
                    f"{self.communicator!r} with this shape makes every "
                    "worker a shard owner whose shard has no live-set "
                    "re-ownership path, so a dropped worker would "
                    "black-hole its shard of the aggregate instead of "
                    "degrading gracefully"
                )
        chaos_on = (
            self.chaos_drop_rate > 0
            or self.chaos_corrupt_rate > 0
            or self.chaos_truncate_rate > 0
        )
        if chaos_on and not self.payload_checksum:
            raise ConfigError(
                "chaos-needs-checksum",
                "chaos_*_rate perturbs payloads at the wire boundary; without "
                "payload_checksum=True the damage decodes silently (NaNs or "
                "skewed means) instead of degrading to a counted zero "
                "contribution — enable payload_checksum with chaos injection"
            )
        if self.payload_checksum and not (
            self.fused and self.communicator == "allgather"
        ):
            raise ConfigError(
                "checksum-needs-fused-allgather",
                "payload_checksum appends a checksum word to the fused "
                "PayloadLayout wire format and would be silently ignored here "
                f"(communicator={self.communicator!r}, fused={self.fused}) — "
                "use fused=True with communicator='allgather'"
            )
        # --- hierarchical surface: loud failure for silently-ignored or
        # --- structurally impossible combinations ---
        check("hier_ici", self.hier_ici, self.HIER_ICI_LEGS)
        check("hier_dcn", self.hier_dcn, self.HIER_DCN_MODES)
        if self.ici_size is not None and self.ici_size < 1:
            raise ConfigError(
                "ici-size-range",
                f"ici_size must be >= 1 or None, got {self.ici_size}"
            )
        hier_engaged = [
            name
            for name, default in (
                ("ici_size", None),
                ("hier_ici", "dense"),
                ("hier_dcn", "config"),
            )
            if getattr(self, name) != default
        ]
        if hier_engaged and not self.hier:
            raise ConfigError(
                "hier-knobs-disengaged",
                f"{', '.join(hier_engaged)} configure the hierarchical "
                "exchange and would be silently ignored with hier=False — "
                "set hier=True (or drop the knob(s))"
            )
        if self.hier and self.decode_strategy == "ring":
            raise ConfigError(
                "hier-vs-ring",
                "hier=True cannot use decode_strategy='ring': the ring "
                "decode issues W-1 ppermute hops sized from the FLAT worker "
                "count, but the hierarchical DCN leg runs over the dcn axis "
                "only (n_slices workers) — the hop schedule would address "
                "workers that are ici replicas, not ring peers. Use 'loop' "
                "or 'vmap' for the cross-slice decode"
            )
        if self.hier and self.resilience:
            # Why the participation mask cannot compose with the two-axis
            # exchange: the mask contract is per-WORKER, but under hier the
            # unit of exchange on the DCN axis is a SLICE. The ICI slice
            # mean is a bare psum with no mask threading — a single dropped
            # device inside a slice would black-hole into the slice mean
            # for its ici peers with no renormalization path (the live-count
            # renorm lives in the DCN-leg exchangers, which only ever see
            # the already-reduced slice mean). Masking at slice granularity
            # instead would require a [n_slices] mask agreed across the ici
            # axis — ownership of "is my slice live" cannot be decided per
            # device, the same shard-ownership argument that rejects
            # resilience over sparse_rs. Until the ICI leg learns masked
            # reduction, the combination fails loudly here.
            raise ConfigError(
                "hier-vs-resilience",
                "resilience=True threads a per-worker participation mask "
                "through the exchange, but hier=True exchanges per-SLICE on "
                "the dcn axis: the ici-axis slice mean is an unmasked psum, "
                "so a dropped device would poison its slice's mean instead "
                "of degrading gracefully — hierarchical resilience needs "
                "slice-granular masks, which the per-device contract cannot "
                "express"
            )
        if self.hier and self.hier_dcn == "auto" and (
            self.deepreduce is not None or self.compressor != "topk"
        ):
            raise ConfigError(
                "hier-dcn-auto-needs-topk",
                "hier_dcn='auto' rewrites the cross-slice route among the "
                "plain top-k fused allgather and the sparse_rs routes, all "
                "of which require compressor='topk' with no deepreduce "
                f"wrapper — got compressor={self.compressor!r}, "
                f"deepreduce={self.deepreduce!r}. Use hier_dcn='config' to "
                "run this codec stack across slices as-is"
            )
        if self.fault_plan is not None:
            # syntax check at construction (deferred import: faults.py is
            # config-free, so no cycle)
            from deepreduce_tpu.resilience.faults import FaultPlan

            try:
                FaultPlan.parse(self.fault_plan)
            except ValueError as e:
                raise ConfigError("fault-plan-syntax", str(e)) from e
        # --- federated surface: loud failure for silently-ignored knobs ---
        fed_engaged = [
            name
            for name, default in (
                ("fed_num_clients", 0),
                ("fed_clients_per_round", 0),
                ("fed_local_steps", 1),
                ("fed_server_lr", 1.0),
                ("fed_client_chunk", 0),
            )
            if getattr(self, name) != default
        ]
        if fed_engaged and not self.fed:
            raise ConfigError(
                "fed-knobs-disengaged",
                f"{', '.join(fed_engaged)} configure the federated "
                "simulation subsystem and would be silently ignored with "
                "fed=False — set fed=True (or drop the knob(s))"
            )
        if self.fed:
            # geometry checks mirror FedConfig.__post_init__ so a bad round
            # shape fails at config construction, not at driver build
            if self.fed_num_clients <= 0:
                raise ConfigError(
                    "fed-population-range",
                    "fed=True requires a positive fed_num_clients "
                    f"population, got {self.fed_num_clients}"
                )
            if self.fed_clients_per_round <= 0:
                raise ConfigError(
                    "fed-cohort-range",
                    "fed=True requires a positive fed_clients_per_round "
                    f"cohort, got {self.fed_clients_per_round}"
                )
            if self.fed_clients_per_round > self.fed_num_clients:
                raise ConfigError(
                    "fed-cohort-exceeds-population",
                    f"fed_clients_per_round={self.fed_clients_per_round} "
                    f"exceeds fed_num_clients={self.fed_num_clients} — "
                    "cohorts are sampled without replacement"
                )
            if self.fed_local_steps <= 0:
                raise ConfigError(
                    "fed-local-steps-range",
                    f"fed_local_steps must be positive, got {self.fed_local_steps}"
                )
            if self.fed_server_lr <= 0:
                raise ConfigError(
                    "fed-server-lr-range",
                    f"fed_server_lr must be positive, got {self.fed_server_lr}"
                )
            if self.fed_client_chunk < 0:
                raise ConfigError(
                    "fed-client-chunk-range",
                    "fed_client_chunk must be >= 0 (0 = one vmap block), "
                    f"got {self.fed_client_chunk}"
                )
            if (
                self.fed_client_chunk > 0
                and self.fed_clients_per_round % self.fed_client_chunk
            ):
                raise ConfigError(
                    "fed-chunk-divides-cohort",
                    f"fed_client_chunk={self.fed_client_chunk} must divide "
                    f"fed_clients_per_round={self.fed_clients_per_round} "
                    "(the chunked cohort scan needs equal blocks)"
                )
            # the fed round never builds a GradientExchanger: aggregation is
            # ONE fused psum of the vmapped client deltas, and compression
            # rides the path-keyed TreeCodec pair (fedsim/round.py). Knobs
            # that only restructure the flat gathered-worker exchange would
            # be silently ignored — fail loudly, same contract as the
            # resilience/hier/ctrl fences above.
            if self.hier:
                raise ConfigError(
                    "fed-vs-hier",
                    "fed=True aggregates client deltas through the fedsim "
                    "round's single fused psum; the hierarchical two-leg "
                    "exchange (hier=True) would be silently ignored — drop "
                    "one of the two"
                )
            if self.communicator != "allgather":
                raise ConfigError(
                    "fed-vs-communicator",
                    f"communicator={self.communicator!r} selects a gathered-"
                    "worker exchange the federated round never runs (its "
                    "aggregate is ONE fused psum; compression is the "
                    "TreeCodec pair) — keep the default communicator="
                    "'allgather' with fed=True"
                )
            if self.bucket_bytes is not None:
                raise ConfigError(
                    "fed-vs-buckets",
                    "bucket_bytes partitions the fused gathered-worker "
                    "exchange; the federated round compresses per leaf "
                    "through the path-keyed TreeCodec and would silently "
                    "ignore it — use bucket_bytes=None with fed=True"
                )
            if self.decode_strategy != "loop":
                raise ConfigError(
                    "fed-vs-decode-strategy",
                    f"decode_strategy={self.decode_strategy!r} restructures "
                    "the gathered-worker decode of the flat exchange; the "
                    "federated round decodes one summed TreeCodec payload "
                    "and would silently ignore it — keep the default 'loop' "
                    "with fed=True"
                )
        # --- asynchronous buffered aggregation (fedsim async mode) ---
        fed_async_engaged = [
            name
            for name, default in (
                ("fed_async_k", 0),
                ("fed_async_alpha", 0.0),
                ("fed_async_latency", ""),
            )
            if getattr(self, name) != default
        ]
        if fed_async_engaged and not self.fed_async:
            raise ConfigError(
                "fed-async-knobs-disengaged",
                f"{', '.join(fed_async_engaged)} configure the asynchronous "
                "buffered aggregation and would be silently ignored with "
                "fed_async=False — set fed_async=True (or drop the knob(s))"
            )
        if self.fed_async:
            if not self.fed:
                raise ConfigError(
                    "fed-async-needs-fed",
                    "fed_async=True buffers the federated round's client "
                    "deltas across ingest ticks — there is no round to "
                    "buffer without fed=True (set the fed_* geometry too)"
                )
            if self.fed_async_k < 1:
                raise ConfigError(
                    "fed-async-k-range",
                    "fed_async=True requires a positive apply threshold "
                    f"fed_async_k, got {self.fed_async_k}"
                )
            if self.fed_async_alpha < 0:
                raise ConfigError(
                    "fed-async-alpha-range",
                    "fed_async_alpha is a down-weighting exponent "
                    f"1/(1+tau)^alpha and must be >= 0, got "
                    f"{self.fed_async_alpha}"
                )
            # syntax check at construction (deferred import: round.py's
            # parser is config-free at parse time — mirrors FaultPlan.parse)
            from deepreduce_tpu.fedsim.round import parse_latency

            try:
                parse_latency(self.fed_async_latency)
            except ValueError as e:
                raise ConfigError("fed-async-latency-syntax", str(e)) from e
        # --- multi-tenant federated serving (stacked vmapped tick) ---
        if self.fed_tenants < 0:
            raise ConfigError(
                "fed-mt-tenants-range",
                f"fed_tenants must be >= 0 (0 = single-tenant driver), got "
                f"{self.fed_tenants}"
            )
        mt_engaged = [
            name
            for name in ("fed_mt_k", "fed_mt_alpha", "fed_mt_latency",
                         "fed_mt_cohort")
            if getattr(self, name) != ""
        ]
        if mt_engaged and self.fed_tenants < 1:
            raise ConfigError(
                "fed-mt-knobs-disengaged",
                f"{', '.join(mt_engaged)} configure per-tenant knobs of the "
                "multi-tenant federated driver and would be silently "
                "ignored with fed_tenants=0 — set fed_tenants >= 1 (or "
                "drop the knob(s))"
            )
        if self.fed_tenants >= 1:
            if not self.fed:
                raise ConfigError(
                    "fed-mt-needs-fed",
                    "fed_tenants >= 1 stacks T federated populations "
                    "through the one jitted round tick — there is no round "
                    "to stack without fed=True (set the fed_* geometry too)"
                )
            async_knobs = [
                n for n in ("fed_mt_k", "fed_mt_alpha", "fed_mt_latency")
                if getattr(self, n) != ""
            ]
            if async_knobs and not self.fed_async:
                raise ConfigError(
                    "fed-mt-async-knobs",
                    f"{', '.join(async_knobs)} configure the per-tenant "
                    "buffered-async knobs (K / alpha / latency) and would "
                    "be silently ignored with fed_async=False — set "
                    "fed_async=True (or drop the knob(s))"
                )
            # per-tenant list syntax + ranges at construction (deferred
            # import mirrors the parse_latency check above)
            from deepreduce_tpu.fedsim.round import (
                parse_tenant_floats,
                parse_tenant_latency,
            )

            T = self.fed_tenants
            try:
                ks = parse_tenant_floats(
                    self.fed_mt_k, T, "fed_mt_k", float(self.fed_async_k)
                )
            except ValueError as e:
                raise ConfigError("fed-mt-k-syntax", str(e)) from e
            if self.fed_async and any(k < 1 for k in ks):
                raise ConfigError(
                    "fed-mt-k-syntax",
                    f"fed_mt_k={self.fed_mt_k!r}: every per-tenant apply "
                    "threshold must be >= 1"
                )
            try:
                alphas = parse_tenant_floats(
                    self.fed_mt_alpha, T, "fed_mt_alpha", self.fed_async_alpha
                )
            except ValueError as e:
                raise ConfigError("fed-mt-alpha-syntax", str(e)) from e
            if any(a < 0 for a in alphas):
                raise ConfigError(
                    "fed-mt-alpha-syntax",
                    f"fed_mt_alpha={self.fed_mt_alpha!r}: every per-tenant "
                    "staleness exponent must be >= 0"
                )
            try:
                parse_tenant_latency(
                    self.fed_mt_latency, T, self.fed_async_latency
                )
            except ValueError as e:
                raise ConfigError("fed-mt-latency-syntax", str(e)) from e
            try:
                cohorts = parse_tenant_floats(
                    self.fed_mt_cohort, T, "fed_mt_cohort",
                    float(self.fed_clients_per_round),
                )
            except ValueError as e:
                raise ConfigError("fed-mt-cohort-syntax", str(e)) from e
            if any(
                c < 1 or c > self.fed_clients_per_round or c != int(c)
                for c in cohorts
            ):
                raise ConfigError(
                    "fed-mt-cohort-syntax",
                    f"fed_mt_cohort={self.fed_mt_cohort!r}: every per-tenant "
                    "effective cohort must be an integer in [1, "
                    f"fed_clients_per_round={self.fed_clients_per_round}]"
                )
        # --- heterogeneous population plane (per-class clients) ---
        if self.pop_labels != 0 and self.pop_spec is None:
            raise ConfigError(
                "pop-knobs-disengaged",
                f"pop_labels={self.pop_labels} overrides the population "
                "spec's label universe and would be silently ignored with "
                "pop_spec=None — set pop_spec (or drop the knob)"
            )
        if self.pop_spec is not None:
            if not self.fed:
                raise ConfigError(
                    "pop-needs-fed",
                    "pop_spec assigns the federated client population to "
                    "heterogeneity classes — there is no population to "
                    "classify with fed=False (set the fed_* geometry too)"
                )
            if self.fed_tenants >= 1:
                raise ConfigError(
                    "pop-vs-mt",
                    "pop_spec with fed_tenants >= 1: per-class and "
                    "per-tenant heterogeneity do not compose yet — the "
                    "class-id vector is sharded with the single-tenant "
                    "residual bank. Run populations single-tenant (or drop "
                    "pop_spec)"
                )
            if self.pop_labels < 0 or self.pop_labels == 1:
                raise ConfigError(
                    "pop-labels-range",
                    f"pop_labels must be 0 (keep the spec value) or >= 2, "
                    f"got {self.pop_labels}"
                )
            # full spec parse at construction (deferred import mirrors the
            # parse_latency check above): inline JSON and spec files both
            # fail HERE with their registered pop-spec-* codes, not three
            # layers deep inside the driver build
            from deepreduce_tpu.population.spec import PopulationSpec

            spec = PopulationSpec.load_any(self.pop_spec)
            if spec.latency_on and not self.fed_async:
                raise ConfigError(
                    "pop-knobs-disengaged",
                    "the population spec carries per-class latency row(s), "
                    "which configure the async staleness draw and would be "
                    "silently ignored with fed_async=False — set "
                    "fed_async=True (or drop the class latency rows)"
                )
            if spec.latency_on:
                from deepreduce_tpu.fedsim.round import parse_class_latency

                try:
                    parse_class_latency(
                        [c.latency for c in spec.classes],
                        self.fed_async_latency,
                    )
                except ConfigError:
                    raise
                except ValueError as e:
                    raise ConfigError("pop-latency-syntax", str(e)) from e
        # --- SLO health plane: host-side monitor over the fed tick stream --
        slo_engaged = [
            name for name in ("slo_window", "slo_hysteresis")
            if getattr(self, name) != 0
        ]
        if slo_engaged and self.slo_spec is None:
            raise ConfigError(
                "slo-knobs-disengaged",
                f"{', '.join(slo_engaged)} override the SLO spec windows "
                "and would be silently ignored with slo_spec=None — set "
                "slo_spec (or drop the knob(s))"
            )
        if self.slo_spec is not None:
            if not self.fed:
                raise ConfigError(
                    "slo-needs-fed",
                    "slo_spec configures the serving health monitor, which "
                    "consumes the federated tick report stream — it has "
                    "nothing to watch with fed=False"
                )
            if self.slo_window < 0:
                raise ConfigError(
                    "slo-window-range",
                    f"slo_window must be >= 0 (0 keeps the spec value), "
                    f"got {self.slo_window}"
                )
            if self.slo_hysteresis < 0:
                raise ConfigError(
                    "slo-hysteresis-range",
                    f"slo_hysteresis must be >= 0 (0 keeps the spec "
                    f"value), got {self.slo_hysteresis}"
                )
        # --- adaptive controller: loud failure for silently-ignored knobs ---
        ctrl_engaged = [
            name
            for name, default in (
                ("ctrl_ladder", type(self).ctrl_ladder),
                ("ctrl_target_err_cos", type(self).ctrl_target_err_cos),
                ("ctrl_headroom", type(self).ctrl_headroom),
                ("ctrl_saturation_ceiling", type(self).ctrl_saturation_ceiling),
                ("ctrl_hysteresis", type(self).ctrl_hysteresis),
            )
            if getattr(self, name) != default
        ]
        if ctrl_engaged and not self.ctrl:
            raise ConfigError(
                "ctrl-knobs-disengaged",
                f"{', '.join(ctrl_engaged)} configure the adaptive "
                "compression controller and would be silently ignored with "
                "ctrl=False — set ctrl=True (or drop the knob(s))"
            )
        if self.ctrl:
            if not self.telemetry:
                raise ConfigError(
                    "ctrl-needs-telemetry",
                    "ctrl=True requires telemetry=True: the controller "
                    "consumes the MetricAccumulators fetch and adds no "
                    "syncs of its own"
                )
            if self.compressor == "none":
                raise ConfigError(
                    "ctrl-needs-compressor",
                    "ctrl=True has nothing to tune with compressor='none' "
                    "(no sparsifier budget); pick a sparsifying compressor"
                )
            if self.hier or self.fed:
                raise ConfigError(
                    "ctrl-vs-hier-fed",
                    "ctrl=True currently drives the flat GradientExchanger "
                    "only — it cannot rebuild the hierarchical or federated "
                    "pipelines per rung (hier=False, fed=False required)"
                )
            if not 0.0 < self.ctrl_target_err_cos <= 1.0:
                raise ConfigError(
                    "ctrl-target-range",
                    "ctrl_target_err_cos must be in (0, 1], got "
                    f"{self.ctrl_target_err_cos}"
                )
            if self.ctrl_headroom < 0.0:
                raise ConfigError(
                    "ctrl-headroom-range",
                    f"ctrl_headroom must be >= 0, got {self.ctrl_headroom}"
                )
            if self.ctrl_saturation_ceiling < 0.0:
                raise ConfigError(
                    "ctrl-saturation-range",
                    "ctrl_saturation_ceiling must be >= 0, got "
                    f"{self.ctrl_saturation_ceiling}"
                )
            if self.ctrl_hysteresis < 1:
                raise ConfigError(
                    "ctrl-hysteresis-range",
                    f"ctrl_hysteresis must be >= 1, got {self.ctrl_hysteresis}"
                )
            # ladder syntax check at construction (deferred import:
            # controller/ladder.py imports this module, so import lazily
            # here to avoid the cycle — mirrors the FaultPlan.parse idiom)
            from deepreduce_tpu.controller.ladder import Ladder

            try:
                Ladder.parse(self.ctrl_ladder)
            except ValueError as e:
                raise ConfigError("ctrl-ladder-syntax", str(e)) from e
        # --- fitted machine profile: must have a selector to re-select ------
        if self.profile is not None:
            has_auto = (
                self.rs_mode == "auto"
                or self.hier_ici == "auto"
                or self.hier_dcn == "auto"
            )
            if not has_auto:
                raise ConfigError(
                    "profile-needs-auto-selector",
                    f"profile={self.profile!r} re-prices the 'auto' plan "
                    "selection and would be silently ignored with every "
                    "selector explicit — set rs_mode='auto' or "
                    "hier_ici/hier_dcn='auto' (or drop profile)"
                )
            if self.ctrl:
                raise ConfigError(
                    "profile-vs-ctrl",
                    "profile with ctrl=True would fight the adaptive "
                    "controller for the operating point — calibrate the "
                    "construction-time plan (profile) or adapt at runtime "
                    "(ctrl), not both"
                )

    def fed_config(self):
        """The round-geometry view of the fed_* knobs (deferred import:
        fedsim.round imports this module, so no cycle at import time)."""
        if not self.fed:
            raise ValueError("fed_config() requires fed=True")
        from deepreduce_tpu.fedsim.round import FedConfig

        return FedConfig(
            num_clients=self.fed_num_clients,
            clients_per_round=self.fed_clients_per_round,
            local_steps=self.fed_local_steps,
            server_lr=self.fed_server_lr,
        )

    @classmethod
    def tpu_defaults(cls, **overrides) -> "DeepReduceConfig":
        """The measured-fastest TPU configuration (bench.py, real v5e):
        approx top-k sparsifier (~4x faster than exact at d=4M), mod-blocked
        bloom (gather-free universe query), fused single-buffer exchange,
        and Pallas kernels where present (QSGD PRNG). Every knob here won
        its A/B on silicon; override freely for experiments."""
        base = dict(
            approx_topk=True,
            bloom_blocked="mod",
            fused=True,
            use_pallas=True,
        )
        base.update(overrides)
        return cls(**base)

    def codec_params(self) -> Dict[str, Any]:
        return {
            "fpr": self.fpr,
            "policy": self.policy,
            "bloom_blocked": self.bloom_blocked,
            "bloom_threshold_insert": self.bloom_threshold_insert,
            "code": self.code,
            "poly_degree": self.poly_degree,
            "quantum_num": self.quantum_num,
            "bucket_size": self.bucket_size,
            "sort": self.sort,
            "seed": self.seed,
            "use_pallas": self.use_pallas,
            "rs_sketch_rows": self.rs_sketch_rows,
            "rs_sketch_cols": self.rs_sketch_cols,
        }


_KEY_MAP = {
    "micro-benchmark": "micro_benchmark",
    "threshold": "threshold_val",
    "threshold_val": "threshold_val",
}


def from_params(params: Dict[str, Any], *, strict: bool = False) -> DeepReduceConfig:
    """Build a config from a reference-style params dict
    (`deepreduce_from_params` role, pytorch/deepreduce.py:28-48). Unknown
    keys are ignored, like the reference's dict.get discipline — unless
    `strict=True`, which raises on any key that would be dropped (the
    bench/CLI entrypoints use strict so a misspelled knob fails loudly
    instead of silently running the default)."""
    fields = {f.name for f in dataclasses.fields(DeepReduceConfig)}
    kwargs = {}
    dropped = []
    for key, val in params.items():
        key = _KEY_MAP.get(key, key)
        if key in fields:
            kwargs[key] = val
        else:
            dropped.append(key)
    if strict and dropped:
        known = sorted(fields | set(_KEY_MAP))
        raise ValueError(
            f"unknown config key(s) {sorted(dropped)}; known keys: {known}"
        )
    return DeepReduceConfig(**kwargs)
