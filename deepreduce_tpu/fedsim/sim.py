"""Population-scale federated simulation: client-sharded cohorts in ONE
jitted round step.

`fedavg.FedAvg` is the paper-faithful harness — tens of clients, one
`lax.scan`. This driver is the ROADMAP's "million-client federated serving
simulation": the population's per-client error-feedback state lives in a
device-sharded residual *bank* (`[num_clients, ...]` leaves, `P(axis)` on
dim 0) instead of a Python-side dict, and each round

1. every worker samples its stratum's share of the cohort *inside* the
   jitted step (`jax.random.choice` without replacement over the worker's
   contiguous `num_clients / W` clients — gather and scatter against the
   bank stay purely local, no cross-worker addressing),
2. synthesizes the sampled clients' batches from their global client ids
   (`data_fn`, traced under vmap — no [population, ...] dataset ever
   materializes),
3. runs the shared `fedsim.round.client_step` body — local SGD, real
   `TensorCodec` compression with per-client EF, and (when engaged) the
   pack → chaos → checksum uplink stage — over its cohort shard as vmapped
   client batches (optionally chunked to bound peak memory),
4. contributes to exactly ONE `lax.psum` of the tuple
   (update sum, wire bits, live count, checksum failures) — the whole
   cross-worker traffic of a round, pinned by the `fedsim:round` audit
   spec — and applies the live-count renormalized server update
   replicated.

Churn (`FaultPlan` / drop_rate) is drawn over *global cohort positions*
from the shared round key, so every worker agrees on who is live; a
worker's slice of that mask gates its local clients. Rounds are
checkpointable via `checkpoint.py` (the state is one pytree: params, w_ref,
residual bank, round counter, telemetry accumulators).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepreduce_tpu.comm import PayloadLayout
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.fedsim.codec_tree import TreeCodec
from deepreduce_tpu.fedsim.round import (
    FedConfig,
    WIRE_FIELDS,
    _LATENCY_TAG,
    cohort_updates,
    draw_latency,
    make_async_client_step,
    make_client_step,
    parse_class_latency,
    parse_latency,
    parse_tenant_floats,
    parse_tenant_latency,
    staleness_weights,
    tree_add,
    tree_sub,
)
from deepreduce_tpu.metrics import WireStats
from deepreduce_tpu.resilience.chaos import ChaosInjector
from deepreduce_tpu.resilience.faults import participation_mask
from deepreduce_tpu.telemetry import spans
from deepreduce_tpu.telemetry.device_metrics import MetricAccumulators
from deepreduce_tpu.utils.compat import shard_map


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class AsyncBuffer:
    """Server-side aggregation buffer for the asynchronous (FedBuff-style)
    mode, carried across ingest ticks inside `FedSimState`. Everything here
    is replicated device state and checkpoints with the rest of the state —
    a mid-buffer kill/resume replays bitwise.

    - `delta_sum`: staleness-weighted sum of decoded client deltas (tree
      like params) accumulated since the last apply.
    - `weight` / `count`: accumulated `sum(1/(1+tau)^alpha)` over live
      contributions (the apply denominator) and the raw live-contribution
      count (compared against `k`).
    - `k`: the apply threshold as a TRACED f32 scalar — a K sweep shares
      one compiled tick program.
    - `version`: int32 server model version (number of buffered applies).
    - `hist`: the w_ref ring — [D, ...] leaves of the last D reference
      models, one per staleness level of the latency distribution; None
      when D == 1 (zero latency: clients read w_ref directly and the staged
      client program matches the synchronous one).
    - `stale_sum` / `stale_max`: per-buffer staleness counters over the
      contributions currently buffered (reset at apply) — the "staleness
      counters nonzero" half of the mid-buffer resume contract.
    - `pending`: 1.0 when the previous tick applied, so THIS tick pays the
      S2C broadcast (w_ref advance + downlink bytes); the broadcast ops are
      always staged and gated by exact SELECTs.
    """

    delta_sum: Any
    weight: jax.Array
    count: jax.Array
    k: jax.Array
    version: jax.Array
    hist: Optional[Any]
    stale_sum: jax.Array
    stale_max: jax.Array
    pending: jax.Array

    def tree_flatten(self):
        return (
            (
                self.delta_sum,
                self.weight,
                self.count,
                self.k,
                self.version,
                self.hist,
                self.stale_sum,
                self.stale_max,
                self.pending,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class FedSimState:
    params: Any  # server's true model (replicated)
    w_ref: Any  # what every client can reconstruct from broadcasts
    residuals: Optional[Any]  # [num_clients, ...] bank, sharded on dim 0
    round: jax.Array
    telemetry: Optional[MetricAccumulators]
    # asynchronous aggregation buffer; None in synchronous mode, so the
    # sync state's pytree leaves (and checkpoints) are unchanged
    buffer: Optional[AsyncBuffer] = None
    # population class-id vector, i32[num_clients] sharded with the
    # residual bank; None when the population plane is off (same
    # leaf-list-unchanged contract as `buffer`)
    classes: Optional[jax.Array] = None

    def tree_flatten(self):
        return (
            (
                self.params,
                self.w_ref,
                self.residuals,
                self.round,
                self.telemetry,
                self.buffer,
                self.classes,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class MultiTenantState:
    """T independent federated populations stacked along a leading tenant
    dimension, served through the ONE jitted round tick (the round body is
    vmapped over this axis inside the existing `shard_map`, so the tick
    still issues exactly one psum — its tuple operands just grow a tenant
    dim; collective COUNT is independent of T).

    - `params` / `w_ref` / `residuals` / `buffer` / `telemetry`: the
      single-tenant `FedSimState` leaves with a leading `[T]` dim (the
      residual bank is `[T, num_clients, ...]`, client dim still sharded).
    - `round`: int32[T] per-tenant round counters — an inactive tenant's
      counter (and every other leaf) is frozen by exact SELECTs.
    - `active`: bool[T] tenant-slot ring mask, a TRACED operand — tenants
      join/leave by flipping bits without retracing (the fed_async
      pending-gate pattern generalized to whole populations).
    - `alpha` / `latency` / `cohort`: per-tenant knobs as TRACED stacked
      scalars/rows (f32[T], f32[T, D], f32[T]) so a heterogeneous fleet
      shares one compiled program; None when the corresponding subsystem
      is off (sync mode / no per-tenant cohort override).
    - `tick`: int32 global tick counter driving the stream key schedule
      (tenant rounds freeze with their slot; the tick never does).
    """

    params: Any
    w_ref: Any
    residuals: Optional[Any]
    round: jax.Array
    telemetry: Optional[MetricAccumulators]
    buffer: Optional[AsyncBuffer]
    active: jax.Array
    alpha: Optional[jax.Array]
    latency: Optional[jax.Array]
    cohort: Optional[jax.Array]
    tick: jax.Array

    def tree_flatten(self):
        return (
            (
                self.params,
                self.w_ref,
                self.residuals,
                self.round,
                self.telemetry,
                self.buffer,
                self.active,
                self.alpha,
                self.latency,
                self.cohort,
                self.tick,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def synthetic_linear_problem(
    dim: int, batch_size: int, local_steps: int
) -> Tuple[Any, Callable, Callable]:
    """A linear-teacher population: every client sees noiseless samples of
    one shared ground-truth regressor, with batches derived from the
    client's GLOBAL id (same id -> same data distribution regardless of
    which worker simulates it). Returns (params0, data_fn, loss_fn)."""

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    def data_fn(client_id, rnd, key):
        # the teacher is a fixed constant of the problem, not of the round
        w_true = jax.random.normal(jax.random.PRNGKey(42), (dim,))
        x = jax.random.normal(key, (local_steps, batch_size, dim))
        y = x @ w_true
        return (x, y)

    params0 = {"b": jnp.zeros(()), "w": jnp.zeros((dim,))}
    return params0, data_fn, loss_fn


class FedSim:
    """Client-sharded federated round driver.

    loss_fn(params, batch) -> scalar; data_fn(global_client_id, round, key)
    -> one client's [local_steps, ...] batch pytree (traced under vmap).
    `mesh` (or None for single-device) provides the worker axis the
    population is sharded over; both `num_clients` and `clients_per_round`
    must divide its extent.
    """

    def __init__(
        self,
        loss_fn: Callable,
        cfg_c2s: DeepReduceConfig,
        fed: FedConfig,
        client_optimizer: optax.GradientTransformation,
        data_fn: Callable,
        *,
        cfg_s2c: Optional[DeepReduceConfig] = None,
        mesh=None,
        axis: str = "data",
        client_chunk: int = 0,
    ):
        self.loss_fn = loss_fn
        self.cfg_c2s = cfg_c2s
        self.cfg_s2c = cfg_s2c if cfg_s2c is not None else cfg_c2s
        self.fed = fed
        self.client_opt = client_optimizer
        self.data_fn = data_fn
        self.mesh = mesh
        self.axis = axis
        self.W = int(mesh.shape[axis]) if mesh is not None else 1
        if fed.num_clients % self.W:
            raise ValueError(
                f"num_clients={fed.num_clients} must divide evenly over the "
                f"{self.W}-worker '{axis}' axis — each worker owns a "
                "contiguous stratum of the residual bank"
            )
        if fed.clients_per_round % self.W:
            raise ValueError(
                f"clients_per_round={fed.clients_per_round} must divide "
                f"evenly over the {self.W}-worker '{axis}' axis — cohorts "
                "are sampled stratum-by-stratum"
            )
        self.n_local = fed.num_clients // self.W
        self.c_local = fed.clients_per_round // self.W
        if self.c_local > self.n_local:
            raise ValueError(
                f"per-worker cohort {self.c_local} exceeds the per-worker "
                f"population {self.n_local} — stratified sampling is without "
                "replacement"
            )
        if client_chunk and self.c_local % client_chunk:
            raise ValueError(
                f"client_chunk={client_chunk} must divide the per-worker "
                f"cohort {self.c_local}"
            )
        self.client_chunk = int(client_chunk)
        self.use_res = cfg_c2s.memory == "residual"
        # resilience wiring (all None/0 when the subsystem is off: the
        # plain round's trace carries no resilience ops at all)
        res_on = bool(getattr(cfg_c2s, "resilience", False))
        self.drop_rate = cfg_c2s.drop_rate if res_on else 0.0
        self.fault_plan = cfg_c2s.fault_plan if res_on else None
        self.checksum = bool(res_on and cfg_c2s.payload_checksum)
        self.chaos = ChaosInjector.from_config(cfg_c2s)
        # asynchronous buffered mode (all inert defaults when off: the
        # synchronous round body/trace is not touched at all)
        self.fed_async = bool(getattr(cfg_c2s, "fed_async", False))
        self.async_k = int(getattr(cfg_c2s, "fed_async_k", 0) or 0)
        self.async_alpha = float(getattr(cfg_c2s, "fed_async_alpha", 0.0))
        self.latency_probs = parse_latency(
            getattr(cfg_c2s, "fed_async_latency", "") or ""
        )
        # heterogeneous population plane: the spec is STATIC (class table,
        # skew concentrations, per-class latency rows baked into the trace);
        # only the class-id vector rides as a traced operand. The config
        # fences already guarantee fed=True, single-tenant, and fed_async
        # whenever a class carries a latency row. None everywhere below
        # keeps every population-free build byte-identical.
        self.pop = None
        self.pop_data_fn = None
        self.pop_latency_rows = None
        pop_spec = getattr(cfg_c2s, "pop_spec", None)
        if pop_spec is not None:
            from deepreduce_tpu.population.sampler import (
                make_population_data_fn,
            )
            from deepreduce_tpu.population.spec import PopulationSpec

            spec = PopulationSpec.load_any(pop_spec)
            labels = int(getattr(cfg_c2s, "pop_labels", 0) or 0)
            if labels:
                spec = spec.with_overrides(num_labels=labels)
            self.pop = spec
            self.pop_data_fn = make_population_data_fn(spec, data_fn)
            if self.fed_async and spec.latency_on:
                rows = parse_class_latency(
                    [c.latency for c in spec.classes],
                    getattr(cfg_c2s, "fed_async_latency", "") or "",
                )
                # one common overlap depth D across the class rows AND the
                # global default row: ring depth and accumulator sizing both
                # key off len(self.latency_probs), so zero-pad everything to
                # the deepest distribution in play
                D = max(len(rows[0]), len(self.latency_probs))
                self.pop_latency_rows = tuple(
                    r + (0.0,) * (D - len(r)) for r in rows
                )
                self.latency_probs = tuple(self.latency_probs) + (0.0,) * (
                    D - len(self.latency_probs)
                )
        # multi-tenant serving: stack T populations through the one tick
        # (0 = the single-tenant driver, whose build path is untouched)
        self.tenants = int(getattr(cfg_c2s, "fed_tenants", 0) or 0)
        self.mt_k = self.mt_alpha = self.mt_latency = self.mt_cohort = None
        if self.tenants >= 1:
            T = self.tenants
            self.mt_k = parse_tenant_floats(
                getattr(cfg_c2s, "fed_mt_k", "") or "", T, "fed_mt_k",
                float(max(self.async_k, 1)),
            )
            self.mt_alpha = parse_tenant_floats(
                getattr(cfg_c2s, "fed_mt_alpha", "") or "", T, "fed_mt_alpha",
                self.async_alpha,
            )
            self.mt_latency = parse_tenant_latency(
                getattr(cfg_c2s, "fed_mt_latency", "") or "", T,
                getattr(cfg_c2s, "fed_async_latency", "") or "",
            )
            coh_spec = getattr(cfg_c2s, "fed_mt_cohort", "") or ""
            # the cohort gate stages extra SELECT ops, so it is only wired
            # when the knob is set (keeps the default MT trace minimal and
            # the T=1 degeneracy structural)
            self.mt_cohort = (
                parse_tenant_floats(
                    coh_spec, T, "fed_mt_cohort",
                    float(fed.clients_per_round),
                )
                if coh_spec
                else None
            )
        self.tc_c2s = TreeCodec("c2s", cfg_c2s)
        self.tc_s2c = TreeCodec("s2c", self.cfg_s2c)
        self._layout: Optional[PayloadLayout] = None
        self._round: Optional[Callable] = None
        self._round_times: list = []

    # ------------------------------------------------------------------ #

    def _local_train(self, params: Any, batches: Any, key: jax.Array) -> Any:
        opt_state = self.client_opt.init(params)

        def one_step(carry, batch):
            p, o = carry
            grads = jax.grad(self.loss_fn)(p, batch)
            updates, o = self.client_opt.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), None

        (p_end, _), _ = jax.lax.scan(one_step, (params, opt_state), batches)
        return p_end

    def build_layout(self, params_like: Any) -> None:
        """Derive the checksum/chaos uplink payload layout from param
        shapes alone — the piece of `init` that trace-only callers (the
        analysis gate on an abstract mesh) need, without allocating the
        residual bank on real devices. Accepts arrays or ShapeDtypeStructs."""
        sds = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params_like
        )
        payload_sds, _ = self.tc_c2s.payload_sds(sds)
        self._layout = PayloadLayout(payload_sds, checksum=self.checksum)

    def init(self, params: Any) -> FedSimState:
        if self.tenants >= 1:
            return self._init_mt(params)
        # async mode donates the state: take a private copy so the caller's
        # param arrays survive the first tick (sync keeps the no-copy view)
        copy = jnp.array if self.fed_async else jnp.asarray
        params = jax.tree_util.tree_map(copy, params)
        bank = None
        if self.use_res:
            N = self.fed.num_clients

            def _zeros():
                return jax.tree_util.tree_map(
                    lambda p: jnp.zeros((N,) + p.shape, p.dtype), params
                )

            if self.mesh is not None:
                shardings = jax.tree_util.tree_map(
                    lambda p: NamedSharding(self.mesh, P(self.axis)), params
                )
                bank = jax.jit(_zeros, out_shardings=shardings)()
            else:
                bank = _zeros()
        acc = None
        if self.cfg_c2s.telemetry:
            # async mode grows the accumulator's staleness-histogram vector
            # to the latency depth D (f32[0] otherwise — sync fetch/derive
            # output is unchanged); the population plane adds a per-class
            # participation vector (None when off — no extra leaf)
            acc = MetricAccumulators.zeros(
                num_stale_levels=len(self.latency_probs) if self.fed_async else 0,
                num_pop_classes=(
                    self.pop.num_classes if self.pop is not None else 0
                ),
            )
        if self.checksum or self.chaos is not None:
            self.build_layout(params)
        classes = None
        if self.pop is not None:
            from deepreduce_tpu.population.sampler import class_assignments

            classes = class_assignments(self.pop, self.fed.num_clients)
            if self.mesh is not None:
                # sharded exactly like the residual bank: worker w owns the
                # class ids of its contiguous client stratum
                classes = jax.device_put(
                    classes, NamedSharding(self.mesh, P(self.axis))
                )
        w_ref = jax.tree_util.tree_map(jnp.array, params)
        buffer = self._init_buffer(w_ref) if self.fed_async else None
        self._round = self._build_async(params) if self.fed_async else self._build(params)
        return FedSimState(
            params=params,
            w_ref=w_ref,
            residuals=bank,
            round=jnp.zeros((), jnp.int32),
            telemetry=acc,
            buffer=buffer,
            classes=classes,
        )

    def _init_buffer(self, w_ref: Any) -> AsyncBuffer:
        """Empty aggregation buffer: version 0, pending broadcast (tick 0
        pays the S2C exactly like synchronous round 0), every w_hist ring
        slot pre-filled with the initial reference model."""
        D = len(self.latency_probs)
        hist = (
            jax.tree_util.tree_map(
                lambda w: jnp.repeat(w[None], D, axis=0), w_ref
            )
            if D > 1
            else None
        )
        # distinct zero arrays per field: the async program donates the
        # buffer, and donating one array through two arguments is an error
        def zero():
            return jnp.zeros((), jnp.float32)

        return AsyncBuffer(
            delta_sum=jax.tree_util.tree_map(jnp.zeros_like, w_ref),
            weight=zero(),
            count=zero(),
            k=jnp.asarray(float(max(self.async_k, 1)), jnp.float32),
            version=jnp.zeros((), jnp.int32),
            hist=hist,
            stale_sum=zero(),
            stale_max=zero(),
            pending=jnp.ones((), jnp.float32),
        )

    def _init_mt(self, params: Any) -> MultiTenantState:
        """Stacked multi-tenant initial state: every tenant slot starts
        from the same caller params (tenant trajectories diverge through
        their per-tenant PRNG streams), with per-tenant knobs materialized
        as traced stacked operands. `jnp.stack` gives each stacked field a
        FRESH buffer — required by async donation."""
        T = self.tenants

        def stack(tree):
            return jax.tree_util.tree_map(
                lambda p: jnp.stack([jnp.asarray(p)] * T), tree
            )

        params_mt = stack(params)
        w_ref_mt = stack(params)
        bank = None
        if self.use_res:
            N = self.fed.num_clients

            def _zeros():
                return jax.tree_util.tree_map(
                    lambda p: jnp.zeros((T, N) + p.shape, p.dtype), params
                )

            if self.mesh is not None:
                # tenant dim replicated, client dim sharded — each worker
                # still owns a contiguous stratum of every tenant's bank
                shardings = jax.tree_util.tree_map(
                    lambda p: NamedSharding(self.mesh, P(None, self.axis)),
                    params,
                )
                bank = jax.jit(_zeros, out_shardings=shardings)()
            else:
                bank = _zeros()
        acc = None
        if self.cfg_c2s.telemetry:
            acc = jax.tree_util.tree_map(
                lambda a: jnp.zeros((T,) + a.shape, a.dtype),
                MetricAccumulators.zeros(
                    num_stale_levels=(
                        len(self.mt_latency[0]) if self.fed_async else 0
                    )
                ),
            )
        if self.checksum or self.chaos is not None:
            self.build_layout(params)
        buffer = alpha = latency = None
        if self.fed_async:
            D = len(self.mt_latency[0])  # fleet overlap depth (padded)
            hist = (
                jax.tree_util.tree_map(
                    lambda p: jnp.stack(
                        [jnp.repeat(jnp.asarray(p)[None], D, axis=0)] * T
                    ),
                    params,
                )
                if D > 1
                else None
            )

            def zero_t():
                return jnp.zeros((T,), jnp.float32)

            buffer = AsyncBuffer(
                delta_sum=jax.tree_util.tree_map(
                    lambda p: jnp.zeros((T,) + p.shape, p.dtype), params
                ),
                weight=zero_t(),
                count=zero_t(),
                k=jnp.asarray(self.mt_k, jnp.float32),
                version=jnp.zeros((T,), jnp.int32),
                hist=hist,
                stale_sum=zero_t(),
                stale_max=zero_t(),
                pending=jnp.ones((T,), jnp.float32),
            )
            alpha = jnp.asarray(self.mt_alpha, jnp.float32)
            latency = jnp.asarray(self.mt_latency, jnp.float32)
        cohort = (
            jnp.asarray(self.mt_cohort, jnp.float32)
            if self.mt_cohort is not None
            else None
        )
        self._round = self._build_mt(params)
        return MultiTenantState(
            params=params_mt,
            w_ref=w_ref_mt,
            residuals=bank,
            round=jnp.zeros((T,), jnp.int32),
            telemetry=acc,
            buffer=buffer,
            active=jnp.ones((T,), jnp.bool_),
            alpha=alpha,
            latency=latency,
            cohort=cohort,
            tick=jnp.zeros((), jnp.int32),
        )

    def set_active(self, state: MultiTenantState, mask) -> MultiTenantState:
        """Tenant join/leave: flip slots in the active ring mask. The mask
        is a TRACED operand of the compiled tick, so this never retraces —
        an inactive slot's state freezes (exact SELECTs) until it rejoins."""
        act = jnp.asarray(mask, jnp.bool_).reshape(state.active.shape)
        return dataclasses.replace(state, active=act)

    # ------------------------------------------------------------------ #

    def _round_body(
        self, params, w_ref, bank, acc, rnd, key, widx,
        *, cohort=None, classes_local=None,
    ):
        fed = self.fed
        C = fed.clients_per_round
        C_local, n_local = self.c_local, self.n_local
        key_s2c, key_c2s, key_sample, key_part, key_data = jax.random.split(key, 5)

        # --- S2C: broadcast the compressed model delta (replicated; the
        # delta is against the receiver-reconstructable w_ref, the
        # self-correcting loop fedavg.py documents)
        delta = tree_sub(params, w_ref)
        dec_delta, _, wire_s2c = self.tc_s2c.compress_tree(delta, None, rnd, key_s2c)
        w_ref = tree_add(w_ref, dec_delta)

        # --- stratified cohort sampling inside the step: worker w draws
        # its C/W cohort slots from its own n_local clients
        ids_local = jax.random.choice(
            jax.random.fold_in(key_sample, widx),
            n_local,
            (C_local,),
            replace=False,
        )
        gids = widx * n_local + ids_local
        positions = jnp.uint32(widx * C_local) + jnp.arange(C_local, dtype=jnp.uint32)

        # --- synthesize the sampled clients' local datasets from their
        # global ids (the population never materializes); with the
        # population plane engaged the class id rides into the generator
        # (gather against this worker's class-id shard — purely local,
        # exactly like the residual gather below)
        cls_sampled = None
        if classes_local is not None:
            cls_sampled = classes_local[ids_local]
            batches = jax.vmap(
                lambda g, c: self.pop_data_fn(
                    g, c, rnd, jax.random.fold_in(key_data, g)
                )
            )(gids, cls_sampled)
        else:
            batches = jax.vmap(
                lambda g: self.data_fn(g, rnd, jax.random.fold_in(key_data, g))
            )(gids)
        res_stack = (
            jax.tree_util.tree_map(lambda r: r[ids_local], bank)
            if self.use_res
            else None
        )

        # --- churn over GLOBAL cohort positions from the shared key (every
        # worker agrees), sliced to this worker's stratum
        mask = participation_mask(
            C, rnd, key_part, drop_rate=self.drop_rate, fault_plan=self.fault_plan
        )
        part_local = None
        if mask is not None:
            part_local = jax.lax.dynamic_slice(
                mask.astype(jnp.float32), (widx * C_local,), (C_local,)
            )
        if cohort is not None:
            # per-tenant effective cohort: only global positions < cohort
            # participate (a traced gate — the heterogeneous fleet shares
            # one program; staged only when fed_mt_cohort is set)
            coh_local = (positions.astype(jnp.float32) < cohort).astype(
                jnp.float32
            )
            part_local = (
                coh_local if part_local is None else part_local * coh_local
            )

        client_step = make_client_step(
            self.tc_c2s,
            self._local_train,
            w_ref,
            rnd,
            key_c2s,
            layout=self._layout,
            chaos=self.chaos,
        )
        upd_sum, new_res_stack, wire4, live = cohort_updates(
            client_step,
            batches,
            res_stack,
            positions,
            update_template=params,
            participation=part_local,
            checksum=self.checksum,
            impl="vmap",
            chunk=self.client_chunk,
        )
        if self.use_res:
            bank = jax.tree_util.tree_map(
                lambda b, nr: b.at[ids_local].set(nr), bank, new_res_stack
            )
        nlive = jnp.sum(live)
        sent = jnp.sum(part_local) if part_local is not None else jnp.float32(C_local)
        nfail = sent - nlive  # transmitted but rejected by the checksum
        # exact per-class participation histogram of ACCEPTED contributions
        # in this worker's stratum, f32[K] — one extra member of the fused
        # psum below (the fedsim:population audit spec re-pins the round's
        # collective law to 4*(n_elems+6+K) bytes; still ONE collective)
        pop_hist = None
        if classes_local is not None:
            k_levels = jnp.arange(
                self.pop.num_classes, dtype=cls_sampled.dtype
            )
            pop_hist = jnp.sum(
                live[:, None]
                * (cls_sampled[:, None] == k_levels[None, :]).astype(
                    jnp.float32
                ),
                axis=0,
            )

        # --- the round's ONE cross-worker collective: partial update sums,
        # wire accounting, live/failure counts, all in a single psum tuple
        if self.W > 1:
            if pop_hist is not None:
                upd_sum, wire4, nlive, nfail, pop_hist = jax.lax.psum(
                    (upd_sum, wire4, nlive, nfail, pop_hist), self.axis
                )
            else:
                upd_sum, wire4, nlive, nfail = jax.lax.psum(
                    (upd_sum, wire4, nlive, nfail), self.axis
                )
        denom = jnp.maximum(nlive, 1.0)
        new_params = jax.tree_util.tree_map(
            lambda w, s: w + fed.server_lr * (s / denom), params, upd_sum
        )

        # wire accounting: C2S per live uplink + the S2C broadcast once
        wire = WireStats(
            index_bits=wire4[0] + wire_s2c.index_bits,
            value_bits=wire4[1] + wire_s2c.value_bits,
            dense_bits=wire4[2] + wire_s2c.dense_bits,
            saturated=wire4[3] + wire_s2c.saturated,
        )
        metrics = {
            "clients": nlive,
            "checksum_failures": nfail,
            "uplink_bytes": (wire4[0] + wire4[1]) / 8.0,
            "downlink_bytes": wire_s2c.total_bits / 8.0,
            "rel_volume": wire.rel_volume(),
        }
        if pop_hist is not None:
            metrics["pop_hist"] = pop_hist
        if acc is not None:
            if pop_hist is not None:
                acc = acc.accumulate(
                    wire,
                    live_workers=nlive,
                    dropped_steps=jnp.asarray(nlive < C, jnp.float32),
                    checksum_failures=nfail,
                    pop_hist=pop_hist,
                )
            else:
                acc = acc.accumulate(
                    wire,
                    live_workers=nlive,
                    dropped_steps=jnp.asarray(nlive < C, jnp.float32),
                    checksum_failures=nfail,
                )
        return new_params, w_ref, bank, acc, rnd + 1, metrics

    def _build(self, params):
        pop = self.pop is not None
        if self.mesh is None:
            if pop:
                def fn(params, w_ref, bank, acc, rnd, key, classes):
                    return self._round_body(
                        params, w_ref, bank, acc, rnd, key, 0,
                        classes_local=classes,
                    )
            else:
                def fn(params, w_ref, bank, acc, rnd, key):
                    return self._round_body(
                        params, w_ref, bank, acc, rnd, key, 0
                    )

            return jax.jit(fn)

        axis = self.axis

        if pop:
            # the class-id vector shards with the residual bank (same
            # stratum ownership); it is carried host-side, never returned
            def spmd(params, w_ref, bank, acc, rnd, key, classes):
                widx = jax.lax.axis_index(axis)
                return self._round_body(
                    params, w_ref, bank, acc, rnd, key, widx,
                    classes_local=classes,
                )

            in_specs = (P(), P(), P(axis), P(), P(), P(), P(axis))
        else:
            def spmd(params, w_ref, bank, acc, rnd, key):
                widx = jax.lax.axis_index(axis)
                return self._round_body(params, w_ref, bank, acc, rnd, key, widx)

            in_specs = (P(), P(), P(axis), P(), P(), P())

        fn = shard_map(
            spmd,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(), P(), P(axis), P(), P(), P()),
            check_rep=False,
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------ #
    # asynchronous buffered mode: one ingest *tick* — same cohort body as
    # the synchronous round (same key split, same sampling, same churn),
    # but client deltas land staleness-weighted in a buffer carried across
    # ticks, and the server applies only when K contributions have arrived.
    # ------------------------------------------------------------------ #

    def _async_round_body(
        self, params, w_ref, bank, acc, rnd, key, buf, widx,
        *, alpha=None, latency_row=None, cohort=None, classes_local=None,
    ):
        fed = self.fed
        C = fed.clients_per_round
        C_local, n_local = self.c_local, self.n_local
        # multi-tenant callers pass TRACED per-tenant knobs (f32 scalar
        # alpha, f32[D] latency row, f32 cohort); the single-tenant path
        # keeps the static config values and stages the identical ops
        if latency_row is None:
            probs = self.latency_probs
            D = len(probs)
        else:
            probs = latency_row
            D = int(latency_row.shape[0])
        if alpha is None:
            alpha = self.async_alpha
        key_s2c, key_c2s, key_sample, key_part, key_data = jax.random.split(key, 5)

        # --- S2C: staged every tick, *paid* only on ticks following an
        # apply (`pending` gate). The gates are exact SELECTs / scalar
        # multiplies by 1.0, so an always-applying run (K == cohort, zero
        # latency) broadcasts bitwise like the synchronous round.
        pending = buf.pending
        delta = tree_sub(params, w_ref)
        dec_delta, _, wire_s2c = self.tc_s2c.compress_tree(delta, None, rnd, key_s2c)
        w_ref = jax.tree_util.tree_map(
            lambda w, d: jnp.where(pending > 0, w + d, w), w_ref, dec_delta
        )
        # the ring slot for the CURRENT version always holds the current
        # reference model (idempotent rewrite on non-broadcast ticks)
        hist = buf.hist
        if hist is not None:
            slot = jnp.mod(buf.version, D)
            hist = jax.tree_util.tree_map(
                lambda h, w: h.at[slot].set(w), hist, w_ref
            )

        # --- cohort sampling / data synthesis / churn: identical to the
        # synchronous round (same subkeys, same derivations)
        ids_local = jax.random.choice(
            jax.random.fold_in(key_sample, widx),
            n_local,
            (C_local,),
            replace=False,
        )
        gids = widx * n_local + ids_local
        positions = jnp.uint32(widx * C_local) + jnp.arange(C_local, dtype=jnp.uint32)
        cls_sampled = None
        if classes_local is not None:
            cls_sampled = classes_local[ids_local]
            batches = jax.vmap(
                lambda g, c: self.pop_data_fn(
                    g, c, rnd, jax.random.fold_in(key_data, g)
                )
            )(gids, cls_sampled)
        else:
            batches = jax.vmap(
                lambda g: self.data_fn(g, rnd, jax.random.fold_in(key_data, g))
            )(gids)
        res_stack = (
            jax.tree_util.tree_map(lambda r: r[ids_local], bank)
            if self.use_res
            else None
        )
        mask = participation_mask(
            C, rnd, key_part, drop_rate=self.drop_rate, fault_plan=self.fault_plan
        )
        part_local = None
        if mask is not None:
            part_local = jax.lax.dynamic_slice(
                mask.astype(jnp.float32), (widx * C_local,), (C_local,)
            )
        coh_global = None
        if cohort is not None:
            # per-tenant effective cohort over GLOBAL positions (replicated
            # draw-free gate; staged only when fed_mt_cohort is set)
            coh_global = (
                jnp.arange(C, dtype=jnp.float32) < cohort
            ).astype(jnp.float32)
            coh_local = jax.lax.dynamic_slice(
                coh_global, (widx * C_local,), (C_local,)
            )
            part_local = (
                coh_local if part_local is None else part_local * coh_local
            )

        # --- per-client staleness over GLOBAL cohort positions from the
        # shared tick key (replicated on every worker — no collective),
        # exactly the FaultPlan-churn trick. With per-CLASS latency rows
        # engaged the draw is worker-LOCAL instead (an inverse-CDF gather
        # by the sampled class ids, from the same `_LATENCY_TAG` uniform
        # stream) and scattered into the full-C vector at this worker's
        # own positions — the only ones `make_async_client_step` reads
        # (taus[pos]); the transmit-side staleness stats below come from
        # a psum'd histogram instead of the replicated vector.
        pop_rows = (
            self.pop_latency_rows if classes_local is not None else None
        )
        if pop_rows is not None:
            rows_t = jnp.asarray(pop_rows, jnp.float32)  # [K, D]
            u = jax.random.uniform(
                jax.random.fold_in(key, _LATENCY_TAG), (C,)
            )
            u_local = jax.lax.dynamic_slice(
                u, (widx * C_local,), (C_local,)
            )
            cdf_local = jnp.cumsum(rows_t, axis=1)[cls_sampled]  # [C_local, D]
            tau_local = jnp.sum(
                (u_local[:, None] > cdf_local[:, :-1]).astype(jnp.int32),
                axis=1,
            )
            taus = jax.lax.dynamic_update_slice(
                jnp.zeros((C,), tau_local.dtype),
                tau_local,
                (widx * C_local,),
            )
        else:
            taus = draw_latency(key, probs, C)

        client_step = make_async_client_step(
            self.tc_c2s,
            self._local_train,
            w_ref,
            hist,
            buf.version,
            taus,
            alpha,
            rnd,
            key_c2s,
            layout=self._layout,
            chaos=self.chaos,
        )
        upd_sum, new_res_stack, wire4, live = cohort_updates(
            client_step,
            batches,
            res_stack,
            positions,
            update_template=params,
            participation=part_local,
            checksum=self.checksum,
            impl="vmap",
            chunk=self.client_chunk,
        )
        if self.use_res:
            bank = jax.tree_util.tree_map(
                lambda b, nr: b.at[ids_local].set(nr), bank, new_res_stack
            )
        nlive = jnp.sum(live)
        sent = jnp.sum(part_local) if part_local is not None else jnp.float32(C_local)
        nfail = sent - nlive  # transmitted but rejected by the checksum
        # weighted live mass of this worker's stratum: the apply denominator
        taus_local = jax.lax.dynamic_slice(taus, (widx * C_local,), (C_local,))
        wsum = jnp.sum(live * staleness_weights(taus_local.astype(jnp.float32), alpha))
        # exact per-level staleness histogram of ACCEPTED contributions in
        # this worker's stratum: `live` is churn- and checksum-gated, so
        # the histogram prices what the buffer actually ingested — the tail
        # statistics (p50/p95/p99) the SLO health plane gates on. f32[D],
        # one extra member of the fused psum below (zero extra collectives)
        levels = jnp.arange(D, dtype=taus_local.dtype)
        st_hist = jnp.sum(
            live[:, None]
            * (taus_local[:, None] == levels[None, :]).astype(jnp.float32),
            axis=0,
        )
        # exact per-class participation histogram of ACCEPTED contributions
        # (f32[K], the sync round's new member — see _round_body)
        pop_hist = None
        if classes_local is not None:
            k_levels = jnp.arange(
                self.pop.num_classes, dtype=cls_sampled.dtype
            )
            pop_hist = jnp.sum(
                live[:, None]
                * (cls_sampled[:, None] == k_levels[None, :]).astype(
                    jnp.float32
                ),
                axis=0,
            )
        # per-class latency path: the transmit-side staleness histogram,
        # f32[D] over TRANSMITTING clients (churn-gated, NOT checksum-gated
        # — a checksum-failed contribution still arrived with its
        # staleness). taus is only locally correct here, so the global
        # st_mean/st_max bookkeeping below derives exactly from this
        # histogram once psum'd — still ONE collective for the tick.
        tx_hist = None
        if pop_rows is not None:
            m_local = (
                part_local
                if part_local is not None
                else jnp.ones((C_local,), jnp.float32)
            )
            tx_hist = jnp.sum(
                m_local[:, None]
                * (taus_local[:, None] == levels[None, :]).astype(
                    jnp.float32
                ),
                axis=0,
            )

        # --- the tick's ONE cross-worker collective (the fedsim:async-round
        # audit spec pins it): partial weighted update sums, wire bits,
        # live/failure counts, the weighted live mass and the staleness
        # histogram, one psum tuple — grown by the per-class participation
        # histogram (and, under per-class latency, the transmit histogram)
        # when the population plane is engaged
        if self.W > 1:
            if pop_hist is not None and tx_hist is not None:
                (upd_sum, wire4, nlive, nfail, wsum, st_hist, pop_hist,
                 tx_hist) = jax.lax.psum(
                    (upd_sum, wire4, nlive, nfail, wsum, st_hist, pop_hist,
                     tx_hist),
                    self.axis,
                )
            elif pop_hist is not None:
                (upd_sum, wire4, nlive, nfail, wsum, st_hist,
                 pop_hist) = jax.lax.psum(
                    (upd_sum, wire4, nlive, nfail, wsum, st_hist, pop_hist),
                    self.axis,
                )
            else:
                upd_sum, wire4, nlive, nfail, wsum, st_hist = jax.lax.psum(
                    (upd_sum, wire4, nlive, nfail, wsum, st_hist), self.axis
                )

        # --- staleness bookkeeping over TRANSMITTING clients (a
        # checksum-failed contribution still arrived, with its staleness);
        # churn and taus are both replicated draws over global positions,
        # so these stats need no collective
        taus_f = taus.astype(jnp.float32)
        if tx_hist is not None:
            # per-class latency: the replicated-taus trick does not hold
            # (each worker drew only its own stratum), so the transmit
            # stats come EXACTLY from the globally-summed histogram
            levels_f = levels.astype(jnp.float32)
            sent_global = jnp.sum(tx_hist)
            st_sum = jnp.sum(levels_f * tx_hist)
            st_max = jnp.maximum(
                jnp.max(jnp.where(tx_hist > 0, levels_f, -1.0)), 0.0
            )
        elif coh_global is not None:
            # cohort-gated transmitters: compose the gate with churn (the
            # cohort branch is staged only when fed_mt_cohort is set, so
            # the default trace below stays byte-identical)
            m_f = (
                coh_global
                if mask is None
                else mask.astype(jnp.float32) * coh_global
            )
            sent_global = jnp.sum(m_f)
            st_sum = jnp.sum(m_f * taus_f)
            st_max = jnp.maximum(jnp.max(jnp.where(m_f > 0, taus_f, -1.0)), 0.0)
        elif mask is not None:
            m_f = mask.astype(jnp.float32)
            sent_global = jnp.sum(m_f)
            st_sum = jnp.sum(m_f * taus_f)
            st_max = jnp.maximum(jnp.max(jnp.where(m_f > 0, taus_f, -1.0)), 0.0)
        else:
            sent_global = jnp.float32(C)
            st_sum = jnp.sum(taus_f)
            st_max = jnp.max(taus_f) if D > 1 else jnp.zeros((), jnp.float32)
        st_mean = st_sum / jnp.maximum(sent_global, 1.0)

        # --- buffer accumulate, then apply iff >= K contributions buffered
        new_sum = tree_add(buf.delta_sum, upd_sum)
        new_weight = buf.weight + wsum
        new_count = buf.count + nlive
        new_stale_sum = buf.stale_sum + st_sum
        new_stale_max = jnp.maximum(buf.stale_max, st_max)
        applied = (new_count >= buf.k).astype(jnp.float32)
        denom = jnp.maximum(new_weight, 1.0)
        new_params = jax.tree_util.tree_map(
            lambda w, s: jnp.where(applied > 0, w + fed.server_lr * (s / denom), w),
            params,
            new_sum,
        )
        zero = jnp.zeros((), jnp.float32)
        new_buf = AsyncBuffer(
            delta_sum=jax.tree_util.tree_map(
                lambda s: jnp.where(applied > 0, jnp.zeros_like(s), s), new_sum
            ),
            weight=jnp.where(applied > 0, zero, new_weight),
            count=jnp.where(applied > 0, zero, new_count),
            k=buf.k,
            version=buf.version + applied.astype(jnp.int32),
            hist=hist,
            stale_sum=jnp.where(applied > 0, zero, new_stale_sum),
            stale_max=jnp.where(applied > 0, zero, new_stale_max),
            pending=applied,  # an apply schedules next tick's broadcast
        )

        # wire accounting: C2S per live uplink + the S2C broadcast on
        # broadcast ticks only (scalar gate; 1.0 * bits is exact)
        wire = WireStats(
            index_bits=wire4[0] + pending * wire_s2c.index_bits,
            value_bits=wire4[1] + pending * wire_s2c.value_bits,
            dense_bits=wire4[2] + pending * wire_s2c.dense_bits,
            saturated=wire4[3] + pending * wire_s2c.saturated,
        )
        metrics = {
            "clients": nlive,
            "checksum_failures": nfail,
            "uplink_bytes": (wire4[0] + wire4[1]) / 8.0,
            "downlink_bytes": pending * wire_s2c.total_bits / 8.0,
            "rel_volume": wire.rel_volume(),
            "staleness_mean": st_mean,
            "staleness_max": st_max,
            "staleness_hist": st_hist,
            "buffer_fill": new_count,
            "buffer_weight": new_weight,
            "applied": applied,
            "version": new_buf.version.astype(jnp.float32),
        }
        if pop_hist is not None:
            metrics["pop_hist"] = pop_hist
        if acc is not None:
            if pop_hist is not None:
                acc = acc.accumulate(
                    wire,
                    live_workers=nlive,
                    dropped_steps=jnp.asarray(nlive < C, jnp.float32),
                    checksum_failures=nfail,
                    staleness_hist=st_hist,
                    pop_hist=pop_hist,
                )
            else:
                acc = acc.accumulate(
                    wire,
                    live_workers=nlive,
                    dropped_steps=jnp.asarray(nlive < C, jnp.float32),
                    checksum_failures=nfail,
                    staleness_hist=st_hist,
                )
        return new_params, w_ref, bank, acc, rnd + 1, metrics, new_buf

    def _build_async(self, params):
        # donate the heavy carried state (params, w_ref, residual bank,
        # buffer): the synchronous driver's functional no-donation copy of
        # the [num_clients, ...] bank is the dominant fixed cost per round
        # at population scale, and the async tick is explicitly a stream —
        # state flows forward, nothing rereads the old tick's arrays
        pop = self.pop is not None
        if self.mesh is None:
            if pop:
                # the class-id vector is a trailing NON-donated operand
                # (index 7 — donate_argnums stays (0, 1, 2, 6)): it is
                # static host-carried state reread every tick
                def fn(params, w_ref, bank, acc, rnd, key, buf, classes):
                    return self._async_round_body(
                        params, w_ref, bank, acc, rnd, key, buf, 0,
                        classes_local=classes,
                    )
            else:
                def fn(params, w_ref, bank, acc, rnd, key, buf):
                    return self._async_round_body(
                        params, w_ref, bank, acc, rnd, key, buf, 0
                    )

            return jax.jit(fn, donate_argnums=(0, 1, 2, 6))

        axis = self.axis

        if pop:
            def spmd(params, w_ref, bank, acc, rnd, key, buf, classes):
                widx = jax.lax.axis_index(axis)
                return self._async_round_body(
                    params, w_ref, bank, acc, rnd, key, buf, widx,
                    classes_local=classes,
                )

            in_specs = (P(), P(), P(axis), P(), P(), P(), P(), P(axis))
        else:
            def spmd(params, w_ref, bank, acc, rnd, key, buf):
                widx = jax.lax.axis_index(axis)
                return self._async_round_body(
                    params, w_ref, bank, acc, rnd, key, buf, widx
                )

            in_specs = (P(), P(), P(axis), P(), P(), P(), P())

        fn = shard_map(
            spmd,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(), P(), P(axis), P(), P(), P(), P()),
            check_rep=False,
        )
        return jax.jit(fn, donate_argnums=(0, 1, 2, 6))

    # ------------------------------------------------------------------ #
    # multi-tenant serving: T stacked populations through the ONE tick —
    # the round body (sync or async) is vmapped over the tenant axis
    # INSIDE the shard_map, so codec tracing, cohort sampling and the
    # single fused psum amortize across tenants (the psum tuple operands
    # grow a leading [T]; collective count stays 1, independent of T).
    # ------------------------------------------------------------------ #

    def _build_mt(self, params):
        T = self.tenants
        asynchronous = self.fed_async

        def tick_fn(
            params, w_ref, bank, acc, rnds, key, buf,
            active, alpha, latency, cohort, tick, widx,
        ):
            # per-tenant key streams: tenant 0 replays the single-tenant
            # stream EXACTLY (bitwise T=1 degeneracy); every other slot
            # gets a fold_in-domain-separated stream
            tids = jnp.arange(T, dtype=jnp.uint32)
            folded = jax.vmap(lambda t: jax.random.fold_in(key, t))(tids)
            keys = jnp.where((tids == 0)[:, None], key[None, :], folded)

            def one(params_t, w_ref_t, bank_t, acc_t, rnd_t, key_t,
                    buf_t, act_t, alpha_t, lat_t, coh_t):
                if asynchronous:
                    (n_params, n_w_ref, n_bank, n_acc, n_rnd, metrics,
                     n_buf) = self._async_round_body(
                        params_t, w_ref_t, bank_t, acc_t, rnd_t, key_t,
                        buf_t, widx,
                        alpha=alpha_t, latency_row=lat_t, cohort=coh_t,
                    )
                else:
                    n_params, n_w_ref, n_bank, n_acc, n_rnd, metrics = (
                        self._round_body(
                            params_t, w_ref_t, bank_t, acc_t, rnd_t, key_t,
                            widx, cohort=coh_t,
                        )
                    )
                    n_buf = buf_t  # None
                # inactive slot: freeze every carried leaf by exact SELECT
                # (the pending-gate pattern generalized), zero its metrics

                def frz(new, old):
                    return jax.tree_util.tree_map(
                        lambda n, o: jnp.where(act_t, n, o), new, old
                    )

                n_params = frz(n_params, params_t)
                n_w_ref = frz(n_w_ref, w_ref_t)
                n_bank = frz(n_bank, bank_t)
                n_acc = frz(n_acc, acc_t)
                n_rnd = jnp.where(act_t, n_rnd, rnd_t)
                n_buf = frz(n_buf, buf_t)
                act_f = act_t.astype(jnp.float32)
                metrics = jax.tree_util.tree_map(
                    lambda m: m * act_f, metrics
                )
                return n_params, n_w_ref, n_bank, n_acc, n_rnd, metrics, n_buf

            (n_params, n_w_ref, n_bank, n_acc, n_rnds, metrics, n_buf) = (
                jax.vmap(one)(
                    params, w_ref, bank, acc, rnds, keys, buf,
                    active, alpha, latency, cohort,
                )
            )
            return (
                n_params, n_w_ref, n_bank, n_acc, n_rnds, metrics, n_buf,
                tick + 1,
            )

        donate = (0, 1, 2, 6) if asynchronous else ()
        if self.mesh is None:

            def fn(params, w_ref, bank, acc, rnds, key, buf,
                   active, alpha, latency, cohort, tick):
                return tick_fn(
                    params, w_ref, bank, acc, rnds, key, buf,
                    active, alpha, latency, cohort, tick, 0,
                )

            return jax.jit(fn, donate_argnums=donate)

        axis = self.axis

        def spmd(params, w_ref, bank, acc, rnds, key, buf,
                 active, alpha, latency, cohort, tick):
            widx = jax.lax.axis_index(axis)
            return tick_fn(
                params, w_ref, bank, acc, rnds, key, buf,
                active, alpha, latency, cohort, tick, widx,
            )

        fn = shard_map(
            spmd,
            mesh=self.mesh,
            # bank is [T, num_clients, ...]: tenant dim replicated, client
            # dim sharded — everything else replicated as before
            in_specs=(
                P(), P(), P(None, axis), P(), P(), P(), P(),
                P(), P(), P(), P(), P(),
            ),
            out_specs=(P(), P(), P(None, axis), P(), P(), P(), P(), P()),
            check_rep=False,
        )
        return jax.jit(fn, donate_argnums=donate)

    def sharded_round_fn(self) -> Callable:
        """The unjitted round callable (shard_map'd when a mesh is set) —
        what the analysis gate traces on an abstract mesh. Built lazily so
        trace-only callers never need `init` (which allocates the residual
        bank on real devices); the checksum/chaos uplink stage still needs
        `init` first, since the payload layout is derived there."""
        if self._round is None:
            if (self.checksum or self.chaos is not None) and self._layout is None:
                raise RuntimeError(
                    "call init(params) or build_layout(params_like) before "
                    "sharded_round_fn() when payload_checksum/chaos is "
                    "engaged — the uplink layout is built from param shapes"
                )
            if self.tenants >= 1:
                self._round = self._build_mt(None)
            else:
                self._round = (
                    self._build_async(None) if self.fed_async else self._build(None)
                )
        return self._round.__wrapped__  # the pre-jit callable

    # ------------------------------------------------------------------ #

    def step(self, state: FedSimState, key: jax.Array):
        """One federated round (or async ingest tick). Returns
        (new_state, device metrics dict). Host wall time per round is
        recorded for `summary()`. In async mode the input state's arrays
        are DONATED — keep only the returned state."""
        t0 = time.perf_counter()
        if isinstance(state, MultiTenantState):
            with spans.span("fedsim/mt-tick"):
                (params, w_ref, bank, acc, rnds, metrics, buf, tick) = (
                    self._round(
                        state.params, state.w_ref, state.residuals,
                        state.telemetry, state.round, key, state.buffer,
                        state.active, state.alpha, state.latency,
                        state.cohort, state.tick,
                    )
                )
            jax.block_until_ready(params)
            self._round_times.append(time.perf_counter() - t0)
            return (
                MultiTenantState(
                    params=params, w_ref=w_ref, residuals=bank, round=rnds,
                    telemetry=acc, buffer=buf, active=state.active,
                    alpha=state.alpha, latency=state.latency,
                    cohort=state.cohort, tick=tick,
                ),
                metrics,
            )
        # the class-id vector is static host-carried state: appended as a
        # trailing operand when the population plane is on, carried through
        # to the new state untouched
        extra = (state.classes,) if self.pop is not None else ()
        if state.buffer is not None:
            with spans.span("fedsim/tick"):
                params, w_ref, bank, acc, rnd, metrics, buf = self._round(
                    state.params, state.w_ref, state.residuals, state.telemetry,
                    state.round, key, state.buffer, *extra,
                )
            jax.block_until_ready(params)
            self._round_times.append(time.perf_counter() - t0)
            return (
                FedSimState(
                    params=params, w_ref=w_ref, residuals=bank, round=rnd,
                    telemetry=acc, buffer=buf, classes=state.classes,
                ),
                metrics,
            )
        with spans.span("fedsim/round"):
            params, w_ref, bank, acc, rnd, metrics = self._round(
                state.params, state.w_ref, state.residuals, state.telemetry,
                state.round, key, *extra,
            )
        jax.block_until_ready(params)
        self._round_times.append(time.perf_counter() - t0)
        new_state = FedSimState(
            params=params, w_ref=w_ref, residuals=bank, round=rnd,
            telemetry=acc, classes=state.classes,
        )
        return new_state, metrics

    def stream(self, state: FedSimState, key: jax.Array, num_ticks: int):
        """Dispatch `num_ticks` async ingest ticks back-to-back WITHOUT
        per-tick host synchronization — the "rounds to a stream" driver.
        Tick r uses `fold_in(key, r)` with r the state's round counter, so
        `stream(state, key, T)` lands on exactly the same state as T
        consecutive `step(state, fold_in(key, r))` calls (the per-tick
        program is identical; only the host dispatch pattern changes).
        Returns (final_state, per-tick metrics list, wall_seconds); the
        per-tick averages land in `self._round_times` for `summary()`."""
        if state.buffer is None:
            raise ValueError(
                "stream() drives the asynchronous buffered mode — build the "
                "FedSim with fed_async=True (state.buffer is None)"
            )
        mt = isinstance(state, MultiTenantState)
        # one host sync up front, none per tick; the MT tick key schedule
        # follows the GLOBAL tick counter (tenant rounds freeze with their
        # slot), which equals the round counter when tenant 0 never leaves
        # — the bitwise T=1 degeneracy contract
        r0 = int(state.tick) if mt else int(state.round)
        t0 = time.perf_counter()
        metrics_hist = []
        with spans.span("fedsim/stream"):
            for t in range(num_ticks):
                tick_key = jax.random.fold_in(key, r0 + t)
                if mt:
                    (params, w_ref, bank, acc, rnds, m, buf, tick) = (
                        self._round(
                            state.params, state.w_ref, state.residuals,
                            state.telemetry, state.round, tick_key,
                            state.buffer, state.active, state.alpha,
                            state.latency, state.cohort, state.tick,
                        )
                    )
                    state = MultiTenantState(
                        params=params, w_ref=w_ref, residuals=bank,
                        round=rnds, telemetry=acc, buffer=buf,
                        active=state.active, alpha=state.alpha,
                        latency=state.latency, cohort=state.cohort,
                        tick=tick,
                    )
                else:
                    extra = (
                        (state.classes,) if self.pop is not None else ()
                    )
                    params, w_ref, bank, acc, rnd, m, buf = self._round(
                        state.params, state.w_ref, state.residuals,
                        state.telemetry, state.round, tick_key, state.buffer,
                        *extra,
                    )
                    state = FedSimState(
                        params=params, w_ref=w_ref, residuals=bank, round=rnd,
                        telemetry=acc, buffer=buf, classes=state.classes,
                    )
                metrics_hist.append(m)
            jax.block_until_ready(state.params)
        wall = time.perf_counter() - t0
        if num_ticks > 0:
            self._round_times.extend([wall / num_ticks] * num_ticks)
        return state, metrics_hist, wall

    def summary(self, state: FedSimState) -> Dict[str, float]:
        """Host-side round-rate report: clients/sec and uplink volume, from
        the telemetry accumulators plus the recorded round wall times. The
        first recorded round is dropped when possible (it pays compile)."""
        mt = isinstance(state, MultiTenantState)
        out: Dict[str, float] = {
            "clients_per_round": float(self.fed.clients_per_round),
            "num_clients": float(self.fed.num_clients),
            "rounds": float(len(self._round_times)),
        }
        if mt:
            out["fed_tenants"] = float(self.tenants)
            out["active_tenants"] = float(jnp.sum(state.active))
        if self.pop is not None:
            out["pop_classes"] = float(self.pop.num_classes)
        times = self._round_times
        if len(times) > 1:
            times = times[1:]
        if times:
            per_round = sum(times) / len(times)
            out["round_time_s"] = per_round
            out["clients_per_sec"] = self.fed.clients_per_round / per_round
            if mt:
                # aggregate fleet throughput (the headline the MT tick is
                # for) next to the per-tenant rate
                out["clients_per_sec_per_tenant"] = out["clients_per_sec"]
                out["clients_per_sec"] *= max(out["active_tenants"], 1.0)
        if state.telemetry is not None:
            tele_acc = state.telemetry
            if mt:
                # per-tenant counters → fleet totals (the per-tenant rows
                # live in the step/stream metrics history)
                tele_acc = jax.tree_util.tree_map(
                    lambda x: jnp.sum(x, axis=0), tele_acc
                )
            tele = tele_acc.summary()
            steps = max(tele["steps"], 1.0)
            out.update(tele)
            # uplink: scarce-link bits net of the S2C broadcast is not
            # separable from the accumulators — report the per-round total
            out["uplink_bytes_per_round"] = tele["cumulative_total_bits"] / 8.0 / steps
        return out
