"""Fedsim CLI: small-cohort round smoke-check with churn, chaos and resume.

    python -m deepreduce_tpu.fedsim check --platform cpu --track_dir /tmp/x

`check` is the `make fedsim-check` body: a short client-sharded federated
run on the 8-device CPU mesh with FaultPlan churn AND wire corruption under
payload checksums, asserting that

- params stay finite and the model converges toward the linear teacher,
- churned cohort slots were recorded (live count < cohort on fault rounds),
- corrupted uplinks were caught by the checksum (counter incremented)
  instead of poisoning the server mean,
- a mid-run checkpoint restores bitwise: save after round R, keep running,
  then restore and replay — the replayed params must equal the
  uninterrupted run's exactly (the whole round is one deterministic jitted
  program of (state, key)),

and writes a tracking run dir (metrics.jsonl with per-round clients /
uplink_bytes) so `python -m deepreduce_tpu.telemetry summary` can render
the clients/sec and uplink-volume rows.
"""

from __future__ import annotations

import argparse
import json
import sys


def _build_cfg(**overrides):
    from deepreduce_tpu.config import DeepReduceConfig

    base = dict(
        deepreduce="index",
        index="bloom",
        bloom_blocked="mod",
        compress_ratio=0.25,
        fpr=0.01,
        memory="residual",
        min_compress_size=8,
        telemetry=True,
    )
    base.update(overrides)
    return DeepReduceConfig(**base)


def _run_check(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu import checkpoint, tracking
    from deepreduce_tpu.fedsim.round import FedConfig
    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem

    overrides = dict(
        fed=True,
        fed_num_clients=args.num_clients,
        fed_clients_per_round=args.clients_per_round,
        fed_local_steps=2,
        resilience=True,
        fault_plan="3@1,5@2:4",
        drop_rate=0.05,
        payload_checksum=True,
        chaos_corrupt_rate=0.2,
    )
    if args.use_async:
        # buffered async tick: K > 2 cohorts so the buffer fills across
        # ticks (the mid-run checkpoint lands mid-buffer), a 3-level
        # latency distribution so staleness counters are nonzero
        overrides.update(
            fed_async=True,
            fed_async_k=int(2.2 * args.clients_per_round),
            fed_async_alpha=0.5,
            fed_async_latency="0.5,0.3,0.2",
        )
    cfg = _build_cfg(**overrides)
    fed = cfg.fed_config()
    dim, batch = 32, 8
    params0, data_fn, loss_fn = synthetic_linear_problem(dim, batch, fed.local_steps)
    n_dev = min(args.num_workers, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))

    def build():
        fs = FedSim(
            loss_fn, cfg, fed, optax.sgd(0.1), data_fn, mesh=mesh, client_chunk=2
        )
        return fs, fs.init(params0)

    fs, state = build()
    key = jax.random.PRNGKey(args.seed)
    run = tracking.Run(
        args.track_dir,
        name="check",
        config={"fed": fed.__dict__, "codec": cfg.codec_params()},
        tags=["fedsim", "check"],
    )

    rounds_hist = []
    ckpt_path = f"{args.track_dir}/ckpt"
    mid = args.rounds // 2
    save_at = None
    saved_buffer_fill = None
    saved_stale_sum = None
    for r in range(args.rounds):
        state, m = fs.step(state, jax.random.fold_in(key, r))
        rec = {k: float(v) for k, v in m.items()}
        rounds_hist.append(rec)
        run.log({"round": r, **rec})
        if args.use_async:
            # save at the first mid-run tick where the buffer is MID-FILL
            # (partially filled, staleness counters nonzero) — the apply
            # cadence floats with churn, so a fixed tick could land right
            # on an apply's reset and checkpoint an empty buffer
            want_save = (
                save_at is None
                and r + 1 >= mid
                and float(state.buffer.count) > 0
                and float(state.buffer.stale_sum) > 0
            )
        else:
            want_save = r + 1 == mid
        if want_save:
            save_at = r + 1
            if state.buffer is not None:
                saved_buffer_fill = float(state.buffer.count)
                saved_stale_sum = float(state.buffer.stale_sum)
            checkpoint.save(ckpt_path, state, config=cfg)
    if save_at is None:
        save_at = args.rounds  # pathological; resume degenerates to a no-op

    # resume: restore the mid-run checkpoint into a FRESH driver and replay
    # the remaining rounds with the same keys — must land bitwise on the
    # uninterrupted run's params
    fs2, template = build()
    restored = checkpoint.restore(ckpt_path, template, config=cfg)
    state2 = restored
    for r in range(save_at, args.rounds):
        state2, _ = fs2.step(state2, jax.random.fold_in(key, r))
    resumed_equal = all(
        bool(jnp.all(a == b))
        for a, b in zip(
            jax.tree_util.tree_leaves(state.params),
            jax.tree_util.tree_leaves(state2.params),
        )
    )
    if state.buffer is not None:
        # async: the aggregation buffer (sums, counts, staleness, ring)
        # must also land bitwise — it IS part of the resumable state
        resumed_equal = resumed_equal and all(
            bool(jnp.all(a == b))
            for a, b in zip(
                jax.tree_util.tree_leaves(state.buffer),
                jax.tree_util.tree_leaves(state2.buffer),
            )
        )

    summary = fs.summary(state)
    run.finish(summary)

    w_true = jax.random.normal(jax.random.PRNGKey(42), (dim,))
    w_err = float(jnp.linalg.norm(state.params["w"] - w_true) / jnp.linalg.norm(w_true))
    C = fed.clients_per_round
    checks = {
        "params_finite": all(
            bool(jnp.all(jnp.isfinite(x)))
            for x in jax.tree_util.tree_leaves(state.params)
        ),
        "model_converging": w_err < 0.9,
        "churn_recorded": any(rec["clients"] < C for rec in rounds_hist),
        "checksum_failures_caught": sum(rec["checksum_failures"] for rec in rounds_hist)
        > 0.0,
        "uplink_accounted": all(rec["uplink_bytes"] > 0 for rec in rounds_hist),
        "resume_bitwise": resumed_equal,
    }
    if args.use_async:
        checks.update(
            {
                "staleness_observed": any(
                    rec.get("staleness_mean", 0.0) > 0 for rec in rounds_hist
                ),
                "buffer_applied": sum(
                    rec.get("applied", 0.0) for rec in rounds_hist
                )
                >= 1.0,
                "checkpoint_mid_buffer": bool(
                    saved_buffer_fill and saved_buffer_fill > 0
                    and saved_stale_sum and saved_stale_sum > 0
                ),
            }
        )
    report = {
        "ok": all(checks.values()),
        "checks": checks,
        "rounds": args.rounds,
        "w_rel_err": w_err,
        "clients_per_sec": summary.get("clients_per_sec"),
        "uplink_bytes_per_round": summary.get("uplink_bytes_per_round"),
        "checksum_failures": summary.get("checksum_failures"),
        "run_dir": str(run.dir),
        "config": {
            "fed_num_clients": fed.num_clients,
            "fed_clients_per_round": fed.clients_per_round,
            "fault_plan": cfg.fault_plan,
            "chaos_corrupt_rate": cfg.chaos_corrupt_rate,
        },
    }
    if args.use_async:
        st_means = [rec.get("staleness_mean", 0.0) for rec in rounds_hist]
        report["async"] = {
            "fed_async_k": cfg.fed_async_k,
            "fed_async_alpha": cfg.fed_async_alpha,
            "fed_async_latency": cfg.fed_async_latency,
            "staleness_mean": sum(st_means) / max(len(st_means), 1),
            "staleness_max": max(
                rec.get("staleness_max", 0.0) for rec in rounds_hist
            ),
            "applies": sum(rec.get("applied", 0.0) for rec in rounds_hist),
            "checkpoint_buffer_fill": saved_buffer_fill,
            "checkpoint_stale_sum": saved_stale_sum,
        }
    return report


def cmd_check(args) -> int:
    report = _run_check(args)
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m deepreduce_tpu.fedsim")
    ap.add_argument("--platform", type=str, default="",
                    help="pin the JAX platform (e.g. 'cpu' for the virtual "
                         "8-device mesh)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_check = sub.add_parser(
        "check", help="cohort round + churn + resume smoke-check (make fedsim-check)"
    )
    p_check.add_argument("--rounds", type=int, default=6)
    p_check.add_argument("--num_clients", type=int, default=256)
    p_check.add_argument("--clients_per_round", type=int, default=32)
    p_check.add_argument("--num_workers", type=int, default=8)
    p_check.add_argument("--seed", type=int, default=0)
    p_check.add_argument("--track_dir", type=str, default="/tmp/drtpu_fedsim_check")
    p_check.add_argument(
        "--async", dest="use_async", action="store_true",
        help="asynchronous buffered mode: staleness-weighted ingest ticks, "
             "K-threshold buffered applies, mid-buffer bitwise resume "
             "(make fedasync-check)")
    args = ap.parse_args(argv)
    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=max(2, args.num_workers))
    return cmd_check(args)


if __name__ == "__main__":
    sys.exit(main())
