"""Fedsim CLI: small-cohort round smoke-check with churn, chaos and resume.

    python -m deepreduce_tpu.fedsim check --platform cpu --track_dir /tmp/x

`check` is the `make fedsim-check` body: a short client-sharded federated
run on the 8-device CPU mesh with FaultPlan churn AND wire corruption under
payload checksums, asserting that

- params stay finite and the model converges toward the linear teacher,
- churned cohort slots were recorded (live count < cohort on fault rounds),
- corrupted uplinks were caught by the checksum (counter incremented)
  instead of poisoning the server mean,
- a mid-run checkpoint restores bitwise: save after round R, keep running,
  then restore and replay — the replayed params must equal the
  uninterrupted run's exactly (the whole round is one deterministic jitted
  program of (state, key)),

and writes a tracking run dir (metrics.jsonl with per-round clients /
uplink_bytes) so `python -m deepreduce_tpu.telemetry summary` can render
the clients/sec and uplink-volume rows.
"""

from __future__ import annotations

import argparse
import json
import sys


def _build_cfg(**overrides):
    from deepreduce_tpu.config import DeepReduceConfig

    base = dict(
        deepreduce="index",
        index="bloom",
        bloom_blocked="mod",
        compress_ratio=0.25,
        fpr=0.01,
        memory="residual",
        min_compress_size=8,
        telemetry=True,
    )
    base.update(overrides)
    return DeepReduceConfig(**base)


def _default_slo_spec(cfg):
    """The embedded churn+chaos smoke spec for `check --slo` without
    --slo_spec: targets the smoke MUST satisfy (it ends healthy), sized
    to the check's known geometry — chaos corrupts ~20% of uplinks
    against a 50% error budget, the 3-level latency draw keeps p95 under
    the distribution depth, and the buffer never holds more than a few
    cohorts between applies."""
    targets = {
        "min_clients_per_round": 1.0,
        "checksum_failure_budget": 0.5,
        "convergence_band": 2.0,
        "convergence_residency_min": 0.5,
    }
    if cfg.fed_async:
        from deepreduce_tpu.fedsim.round import parse_latency

        depth = len(parse_latency(cfg.fed_async_latency))
        targets["staleness_p95_max"] = float(depth)
        targets["buffer_fill_max"] = float(4 * cfg.fed_async_k)
    return {
        "version": 1,
        "window_ticks": 4,
        "fast_window_ticks": 2,
        "slow_window_ticks": 6,
        "hysteresis_ticks": 2,
        "targets": targets,
    }


def _slo_monitor(args, cfg, run_dir):
    """(monitor, spec) for `check --slo`, logging to RUN/health.jsonl."""
    from deepreduce_tpu.slo import HealthLog, HealthMonitor, SLOSpec

    if getattr(args, "slo_spec", ""):
        spec = SLOSpec.load(args.slo_spec)
    else:
        spec = SLOSpec.from_dict(_default_slo_spec(cfg))
    log = HealthLog(f"{run_dir}/health.jsonl")
    return HealthMonitor(spec, log=log), spec


def _slo_report(rec, w_rel_err):
    """The deterministic per-tick report the monitor consumes: only
    fields that are pure functions of (state, key) — never wall-clock —
    so the kill/resume replay regenerates them bitwise."""
    rep = {
        "clients": rec.get("clients"),
        "checksum_failures": rec.get("checksum_failures"),
        "buffer_fill": rec.get("buffer_fill"),
        "w_rel_err": w_rel_err,
    }
    hist = rec.get("staleness_hist")
    if isinstance(hist, list):
        rep["staleness_hist"] = hist
    pop = rec.get("pop_hist")
    if isinstance(pop, list):
        rep["pop_hist"] = pop
    return rep


def _run_check(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu import checkpoint, tracking
    from deepreduce_tpu.fedsim.round import FedConfig
    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem

    overrides = dict(
        fed=True,
        fed_num_clients=args.num_clients,
        fed_clients_per_round=args.clients_per_round,
        fed_local_steps=2,
        resilience=True,
        fault_plan="3@1,5@2:4",
        drop_rate=0.05,
        payload_checksum=True,
        chaos_corrupt_rate=0.2,
    )
    if args.use_async:
        # buffered async tick: K > 2 cohorts so the buffer fills across
        # ticks (the mid-run checkpoint lands mid-buffer), a 3-level
        # latency distribution so staleness counters are nonzero
        overrides.update(
            fed_async=True,
            fed_async_k=int(2.2 * args.clients_per_round),
            fed_async_alpha=0.5,
            fed_async_latency="0.5,0.3,0.2",
        )
    pop = bool(getattr(args, "population", False))
    if pop:
        # heterogeneous two-class smoke (make pop-check): planted label
        # skew on both classes plus per-class latency rows — which move
        # the staleness stats onto the psum'd transmit-level histogram —
        # and a 2x compute class; the exact per-class participation
        # histogram is asserted against the tick's accepted count below
        overrides.update(
            pop_spec=(
                '{"version": 1, "num_labels": 4, "label_shift": 0.05, '
                '"classes": ['
                '{"name": "fast", "weight": 3.0, "data_alpha": 2.0, '
                '"latency": "0.6,0.3,0.1"}, '
                '{"name": "slow", "weight": 1.0, "data_alpha": 0.2, '
                '"data_bias": 4.0, "latency": "0.2,0.5,0.3", '
                '"local_steps_mult": 2.0}]}'
            ),
        )
    cfg = _build_cfg(**overrides)
    fed = cfg.fed_config()
    dim, batch = 32, 8
    params0, data_fn, loss_fn = synthetic_linear_problem(dim, batch, fed.local_steps)
    n_dev = min(args.num_workers, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))

    def build():
        fs = FedSim(
            loss_fn, cfg, fed, optax.sgd(0.1), data_fn, mesh=mesh, client_chunk=2
        )
        return fs, fs.init(params0)

    fs, state = build()
    key = jax.random.PRNGKey(args.seed)
    run = tracking.Run(
        args.track_dir,
        name="check",
        config={"fed": fed.__dict__, "codec": cfg.codec_params()},
        tags=["fedsim", "check"],
    )

    w_true = jax.random.normal(jax.random.PRNGKey(42), (dim,))

    def _w_rel(params):
        return float(
            jnp.linalg.norm(params["w"] - w_true) / jnp.linalg.norm(w_true)
        )

    monitor = spec = None
    saved_slo_state = None
    slo_events_at_save = 0
    if args.slo:
        monitor, spec = _slo_monitor(args, cfg, run.dir)

    rounds_hist = []
    ckpt_path = f"{args.track_dir}/ckpt"
    mid = args.rounds // 2
    save_at = None
    saved_buffer_fill = None
    saved_stale_sum = None
    for r in range(args.rounds):
        state, m = fs.step(state, jax.random.fold_in(key, r))
        rec = {}
        for k, v in m.items():
            arr = np.asarray(v)
            # vector metrics (the async on-device staleness histogram) log
            # as lists; scalars stay plain floats
            rec[k] = (
                [float(x) for x in arr.reshape(-1)] if arr.ndim else float(arr)
            )
        rec["w_rel_err"] = _w_rel(state.params)
        rounds_hist.append(rec)
        run.log({"round": r, **rec})
        if monitor is not None:
            monitor.observe(r, _slo_report(rec, rec["w_rel_err"]))
        if args.use_async:
            # save at the first mid-run tick where the buffer is MID-FILL
            # (partially filled, staleness counters nonzero) — the apply
            # cadence floats with churn, so a fixed tick could land right
            # on an apply's reset and checkpoint an empty buffer
            want_save = (
                save_at is None
                and r + 1 >= mid
                and float(state.buffer.count) > 0
                and float(state.buffer.stale_sum) > 0
            )
        else:
            want_save = r + 1 == mid
        if want_save:
            save_at = r + 1
            if state.buffer is not None:
                saved_buffer_fill = float(state.buffer.count)
                saved_stale_sum = float(state.buffer.stale_sum)
            if monitor is not None:
                # the monitor state rides the checkpoint as a plain-JSON
                # sidecar: the resumed monitor must replay the health
                # event tail bitwise from the re-executed tick reports
                saved_slo_state = json.dumps(
                    monitor.state_dict(), sort_keys=True
                )
                slo_events_at_save = len(monitor.events)
                with open(f"{args.track_dir}/slo_state.json", "w") as f:
                    f.write(saved_slo_state)
            checkpoint.save(ckpt_path, state, config=cfg)
    if save_at is None:
        save_at = args.rounds  # pathological; resume degenerates to a no-op

    # resume: restore the mid-run checkpoint into a FRESH driver and replay
    # the remaining rounds with the same keys — must land bitwise on the
    # uninterrupted run's params
    fs2, template = build()
    restored = checkpoint.restore(ckpt_path, template, config=cfg)
    state2 = restored
    monitor2 = None
    if monitor is not None and saved_slo_state is not None:
        from deepreduce_tpu.slo import HealthMonitor

        monitor2 = HealthMonitor(spec)
        monitor2.load_state_dict(json.loads(saved_slo_state))
    for r in range(save_at, args.rounds):
        state2, m2 = fs2.step(state2, jax.random.fold_in(key, r))
        if monitor2 is not None:
            rec2 = {}
            for k, v in m2.items():
                arr = np.asarray(v)
                rec2[k] = (
                    [float(x) for x in arr.reshape(-1)]
                    if arr.ndim
                    else float(arr)
                )
            monitor2.observe(r, _slo_report(rec2, _w_rel(state2.params)))
    resumed_equal = all(
        bool(jnp.all(a == b))
        for a, b in zip(
            jax.tree_util.tree_leaves(state.params),
            jax.tree_util.tree_leaves(state2.params),
        )
    )
    if state.buffer is not None:
        # async: the aggregation buffer (sums, counts, staleness, ring)
        # must also land bitwise — it IS part of the resumable state
        resumed_equal = resumed_equal and all(
            bool(jnp.all(a == b))
            for a, b in zip(
                jax.tree_util.tree_leaves(state.buffer),
                jax.tree_util.tree_leaves(state2.buffer),
            )
        )
    if state.classes is not None:
        # population: the class-id vector restores bitwise too (it is a
        # deterministic function of (spec, N), but it rides the
        # checkpoint as a state leaf and must round-trip exactly)
        resumed_equal = resumed_equal and bool(
            jnp.all(state.classes == state2.classes)
        )

    summary = fs.summary(state)
    run.finish(summary)

    w_err = _w_rel(state.params)
    C = fed.clients_per_round
    checks = {
        "params_finite": all(
            bool(jnp.all(jnp.isfinite(x)))
            for x in jax.tree_util.tree_leaves(state.params)
        ),
        "model_converging": w_err < 0.9,
        "churn_recorded": any(rec["clients"] < C for rec in rounds_hist),
        "checksum_failures_caught": sum(rec["checksum_failures"] for rec in rounds_hist)
        > 0.0,
        "uplink_accounted": all(rec["uplink_bytes"] > 0 for rec in rounds_hist),
        "resume_bitwise": resumed_equal,
    }
    if args.use_async:
        hist_rows = [
            rec["staleness_hist"]
            for rec in rounds_hist
            if isinstance(rec.get("staleness_hist"), list)
        ]
        checks.update(
            {
                "staleness_observed": any(
                    rec.get("staleness_mean", 0.0) > 0 for rec in rounds_hist
                ),
                # the on-device histogram is EXACT: its mass each tick is
                # the tick's accepted-contribution count, bit for bit
                "staleness_hist_exact": bool(hist_rows)
                and all(
                    abs(sum(rec["staleness_hist"]) - rec["clients"]) < 1e-3
                    for rec in rounds_hist
                    if isinstance(rec.get("staleness_hist"), list)
                ),
                "buffer_applied": sum(
                    rec.get("applied", 0.0) for rec in rounds_hist
                )
                >= 1.0,
                "checkpoint_mid_buffer": bool(
                    saved_buffer_fill and saved_buffer_fill > 0
                    and saved_stale_sum and saved_stale_sum > 0
                ),
            }
        )
    if pop:
        pop_rows = [
            rec["pop_hist"]
            for rec in rounds_hist
            if isinstance(rec.get("pop_hist"), list)
        ]
        K = len(pop_rows[0]) if pop_rows else 0
        pop_total = [sum(r[k] for r in pop_rows) for k in range(K)]
        checks.update(
            {
                # the on-device per-class histogram is EXACT: its mass
                # each tick is the tick's accepted-contribution count
                "pop_hist_exact": bool(pop_rows)
                and all(
                    abs(sum(rec["pop_hist"]) - rec["clients"]) < 1e-3
                    for rec in rounds_hist
                    if isinstance(rec.get("pop_hist"), list)
                ),
                "pop_all_classes_served": bool(pop_total)
                and all(t > 0 for t in pop_total),
            }
        )
    if args.slo:
        from deepreduce_tpu.slo import HealthLog, validate_health_stream

        logged = HealthLog.read(f"{run.dir}/health.jsonl")
        try:
            validate_health_stream(logged)
            stream_valid = True
        except ValueError:
            stream_valid = False
        as_lines = lambda recs: [json.dumps(x, sort_keys=True) for x in recs]
        tail = as_lines(monitor.events[slo_events_at_save:])
        tail2 = (
            as_lines(monitor2.events[slo_events_at_save:])
            if monitor2 is not None
            else tail
        )
        checks.update(
            {
                # the churn+chaos smoke must END healthy: every target in
                # the embedded spec holds at the final tick
                "slo_end_healthy": monitor.healthy(),
                # health.jsonl passes the stream validator and matches the
                # in-memory trail record for record
                "slo_stream_valid": stream_valid
                and as_lines(logged) == as_lines(monitor.events),
                # the resumed monitor replays the post-checkpoint event
                # tail bitwise from the re-executed tick reports
                "slo_resume_bitwise": tail == tail2,
            }
        )
    report = {
        "ok": all(checks.values()),
        "checks": checks,
        "rounds": args.rounds,
        "w_rel_err": w_err,
        "clients_per_sec": summary.get("clients_per_sec"),
        "uplink_bytes_per_round": summary.get("uplink_bytes_per_round"),
        "checksum_failures": summary.get("checksum_failures"),
        "run_dir": str(run.dir),
        "config": {
            "fed_num_clients": fed.num_clients,
            "fed_clients_per_round": fed.clients_per_round,
            "fault_plan": cfg.fault_plan,
            "chaos_corrupt_rate": cfg.chaos_corrupt_rate,
        },
    }
    if args.use_async:
        from deepreduce_tpu.telemetry.device_metrics import hist_quantile

        st_means = [rec.get("staleness_mean", 0.0) for rec in rounds_hist]
        hist_rows = [
            rec["staleness_hist"]
            for rec in rounds_hist
            if isinstance(rec.get("staleness_hist"), list)
        ]
        hist_total = []
        if hist_rows:
            depth = max(len(h) for h in hist_rows)
            hist_total = [
                sum(h[d] for h in hist_rows if d < len(h)) for d in range(depth)
            ]
        report["async"] = {
            "staleness_hist_total": hist_total,
            "staleness_p50": hist_quantile(hist_total, 0.50),
            "staleness_p95": hist_quantile(hist_total, 0.95),
            "staleness_p99": hist_quantile(hist_total, 0.99),
            "fed_async_k": cfg.fed_async_k,
            "fed_async_alpha": cfg.fed_async_alpha,
            "fed_async_latency": cfg.fed_async_latency,
            "staleness_mean": sum(st_means) / max(len(st_means), 1),
            "staleness_max": max(
                rec.get("staleness_max", 0.0) for rec in rounds_hist
            ),
            "applies": sum(rec.get("applied", 0.0) for rec in rounds_hist),
            "checkpoint_buffer_fill": saved_buffer_fill,
            "checkpoint_stale_sum": saved_stale_sum,
        }
    if pop:
        grand = max(sum(pop_total), 1.0)
        report["population"] = {
            "pop_spec": json.loads(cfg.pop_spec),
            "pop_hist_total": pop_total,
            "pop_shares": [t / grand for t in pop_total],
        }
    if args.slo:
        report["slo"] = {
            "state": monitor.state_of(0),
            "events": len(monitor.events),
            "health_jsonl": f"{run.dir}/health.jsonl",
            "verdict": monitor.verdict(0),
            "spec": spec.to_dict(),
        }
    return report


def _mt_rec(m):
    """Flatten one MT tick's per-tenant metrics ([T] arrays) into a
    metrics.jsonl row: per-tenant lists under `*_t` keys next to scalar
    fleet aggregates under the original keys (sums for counts/bytes, max
    for staleness_max, means otherwise) so the single-tenant telemetry
    digests keep working on MT runs."""
    import numpy as np

    SUM = {
        "clients", "uplink_bytes", "downlink_bytes", "checksum_failures",
        "applied", "buffer_fill", "buffer_weight",
    }
    MAX = {"staleness_max", "version"}
    rec = {}
    for k, v in m.items():
        arr = np.asarray(v)
        if arr.ndim == 2:
            # per-tenant VECTOR metrics ([T, D] staleness histograms):
            # per-tenant rows under `*_t`, elementwise fleet sum under the
            # original key (histogram counts aggregate by addition)
            rec[k + "_t"] = [[float(x) for x in row] for row in arr]
            rec[k] = [float(s) for s in arr.sum(axis=0)]
            continue
        vals = [float(x) for x in arr.reshape(-1)]
        rec[k + "_t"] = vals
        if k in SUM:
            rec[k] = float(sum(vals))
        elif k in MAX:
            rec[k] = float(max(vals))
        else:
            rec[k] = float(sum(vals) / max(len(vals), 1))
    return rec


def _run_mt_check(args):
    """Multi-tenant smoke (make fedmt-check): T heterogeneous async
    populations through the one vmapped tick — join/leave via the active
    mask WITHOUT retrace, mid-fill checkpoint with tenants at different
    buffer levels, bitwise resume (replaying the same mask schedule), and
    a fail-fast restore across a tenant-geometry mismatch."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from deepreduce_tpu import checkpoint, tracking
    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem

    T = args.tenants
    C = args.clients_per_round
    # a deliberately heterogeneous fleet: alternating K (distinct fill
    # cadences -> the mid-fill checkpoint catches DIFFERENT levels),
    # alpha (including the exact-identity 0.0), latency depth, cohorts
    # odd tenants run HALF cohorts (below), so their K must stay reachable
    # within the run — ~1.3 cohorts per apply vs. ~2.2 for even tenants
    ks = ",".join(str(int((2.2 - 1.55 * (t % 2)) * C)) for t in range(T))
    alphas = ",".join("0" if t % 2 else "0.5" for t in range(T))
    lats = ";".join("0.5,0.3,0.2" if t % 2 == 0 else "0.6,0.4" for t in range(T))
    cohorts = [C if t % 2 == 0 else max(C // 2, 1) for t in range(T)]
    overrides = dict(
        fed=True,
        fed_num_clients=args.num_clients,
        fed_clients_per_round=C,
        fed_local_steps=2,
        resilience=True,
        fault_plan="3@1,5@2:4",
        drop_rate=0.05,
        payload_checksum=True,
        chaos_corrupt_rate=0.2,
        fed_async=True,
        fed_async_k=int(2.2 * C),
        fed_async_alpha=0.5,
        fed_async_latency="0.5,0.3,0.2",
        fed_tenants=T,
        fed_mt_k=ks,
        fed_mt_alpha=alphas,
        fed_mt_latency=lats,
        fed_mt_cohort=",".join(str(c) for c in cohorts),
    )
    cfg = _build_cfg(**overrides)
    fed = cfg.fed_config()
    dim, batch = 32, 8
    params0, data_fn, loss_fn = synthetic_linear_problem(dim, batch, fed.local_steps)
    n_dev = min(args.num_workers, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))

    def build():
        fs = FedSim(
            loss_fn, cfg, fed, optax.sgd(0.1), data_fn, mesh=mesh, client_chunk=2
        )
        return fs, fs.init(params0)

    fs, state = build()
    key = jax.random.PRNGKey(args.seed)
    run = tracking.Run(
        args.track_dir,
        name="mt-check",
        config={"fed": fed.__dict__, "fed_tenants": T, "codec": cfg.codec_params()},
        tags=["fedsim", "mt", "check"],
    )

    w_true = jax.random.normal(jax.random.PRNGKey(42), (dim,))
    monitor = spec = None
    if args.slo:
        monitor, spec = _slo_monitor(args, cfg, run.dir)

    # tenant T-1 leaves for two ticks near the end, then rejoins — the
    # resume replay repeats this schedule by round index
    leave = set(range(args.rounds - 3, args.rounds - 1)) if T > 1 else set()

    def mask_for(r):
        return [not (t == T - 1 and r in leave) for t in range(T)]

    rounds_hist = []
    ckpt_path = f"{args.track_dir}/ckpt"
    mid = args.rounds // 2
    save_at = None
    saved_fills = saved_stales = None
    cur_mask = [True] * T
    frozen_snap = None
    frozen_ok = True
    steady_cache = None
    for r in range(args.rounds):
        want = mask_for(r)
        if want != cur_mask:
            if frozen_snap is None and not all(want):
                frozen_snap = jax.tree_util.tree_map(
                    lambda x: np.asarray(x).copy(), state.params
                )
            state = fs.set_active(state, want)
            cur_mask = want
        state, m = fs.step(state, jax.random.fold_in(key, r))
        if frozen_snap is not None and not all(cur_mask):
            # the inactive slot's params must be frozen by exact SELECTs
            frozen_ok = frozen_ok and all(
                bool(np.array_equal(np.asarray(a)[T - 1], b[T - 1]))
                for a, b in zip(
                    jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(frozen_snap),
                )
            )
        if r == 1:
            # steady state: the 2nd step's input shardings are the tick's
            # own outputs (the 1st pays the init->steady recompile)
            steady_cache = fs._round._cache_size()
        rec = _mt_rec(m)
        # per-tenant convergence distance: feeds the SLO monitor's
        # convergence-band residency target (here and offline via
        # `telemetry slo` on the logged rows)
        rec["w_rel_err_t"] = [
            float(
                jnp.linalg.norm(state.params["w"][t] - w_true)
                / jnp.linalg.norm(w_true)
            )
            for t in range(T)
        ]
        rounds_hist.append(rec)
        run.log({"round": r, **rec})
        if monitor is not None:
            # one report per tenant slot: the per-tenant overrides in the
            # spec gate each tenant's own staleness tail / error budget
            for t in range(T):
                rep = {
                    "clients": rec["clients_t"][t],
                    "checksum_failures": rec["checksum_failures_t"][t],
                    "buffer_fill": rec["buffer_fill_t"][t],
                    "w_rel_err": rec["w_rel_err_t"][t],
                }
                hist_t = rec.get("staleness_hist_t")
                if hist_t:
                    rep["staleness_hist"] = hist_t[t]
                monitor.observe(r, rep, tenant=t)
        if save_at is None and r + 1 >= mid:
            fills = np.asarray(state.buffer.count)
            stales = np.asarray(state.buffer.stale_sum)
            # mid-fill with tenants at DIFFERENT levels, staleness nonzero
            if fills.min() > 0 and stales.min() > 0 and len(set(fills.tolist())) > 1:
                save_at = r + 1
                saved_fills = fills.tolist()
                saved_stales = stales.tolist()
                checkpoint.save(ckpt_path, state, config=cfg)
    no_retrace = (
        steady_cache is not None and fs._round._cache_size() == steady_cache
    )
    if save_at is None:
        save_at = args.rounds

    # bitwise resume: fresh driver, restore, replay the SAME mask schedule
    fs2, template = build()
    resumed_equal = False
    if save_at < args.rounds:
        state2 = checkpoint.restore(ckpt_path, template, config=cfg)
        cur2 = [bool(x) for x in np.asarray(state2.active)]
        for r in range(save_at, args.rounds):
            want = mask_for(r)
            if want != cur2:
                state2 = fs2.set_active(state2, want)
                cur2 = want
            state2, _ = fs2.step(state2, jax.random.fold_in(key, r))
        resumed_equal = all(
            bool(jnp.all(a == b))
            for a, b in zip(
                jax.tree_util.tree_leaves((state.params, state.buffer, state.residuals)),
                jax.tree_util.tree_leaves((state2.params, state2.buffer, state2.residuals)),
            )
        )

    # tenant-geometry fail-fast: restoring under a different T must raise
    # the dedicated mismatch error, not a deep orbax shape error
    t_mismatch_fast = False
    if save_at < args.rounds:
        cfg_bad = _build_cfg(**{**overrides, "fed_tenants": T + 1,
                                "fed_mt_cohort": "", "fed_mt_k": "",
                                "fed_mt_alpha": "", "fed_mt_latency": ""})
        try:
            checkpoint.restore(ckpt_path, template, config=cfg_bad)
        except ValueError as e:
            t_mismatch_fast = "tenant-geometry" in str(e)

    summary = fs.summary(state)
    run.finish(summary)

    w_errs = [
        float(jnp.linalg.norm(state.params["w"][t] - w_true) / jnp.linalg.norm(w_true))
        for t in range(T)
    ]
    checks = {
        "params_finite": all(
            bool(jnp.all(jnp.isfinite(x)))
            for x in jax.tree_util.tree_leaves(state.params)
        ),
        "model_converging": max(w_errs) < 0.9,
        "cohorts_respected": all(
            rec["clients_t"][t] <= cohorts[t] for rec in rounds_hist for t in range(T)
        ),
        "uplink_accounted": all(rec["uplink_bytes"] > 0 for rec in rounds_hist),
        "staleness_observed": any(rec["staleness_mean"] > 0 for rec in rounds_hist),
        "fleet_applied": sum(rec["applied"] for rec in rounds_hist) >= 1.0,
        "checkpoint_mid_fill_distinct": bool(
            saved_fills and min(saved_fills) > 0
            and len(set(saved_fills)) > 1
            and saved_stales and min(saved_stales) > 0
        ),
        "resume_bitwise": resumed_equal,
        "join_leave_no_retrace": no_retrace,
        "frozen_slot_bitwise": frozen_ok and frozen_snap is not None,
        "t_mismatch_fails_fast": t_mismatch_fast,
    }
    if args.slo:
        from deepreduce_tpu.slo import HealthLog, validate_health_stream

        logged = HealthLog.read(f"{run.dir}/health.jsonl")
        try:
            validate_health_stream(logged)
            stream_valid = True
        except ValueError:
            stream_valid = False
        checks.update(
            {
                "slo_end_healthy": monitor.healthy(),
                "slo_stream_valid": stream_valid,
            }
        )
    report = {
        "ok": all(checks.values()),
        "checks": checks,
        "rounds": args.rounds,
        "tenants": T,
        "w_rel_err_per_tenant": w_errs,
        "clients_per_sec": summary.get("clients_per_sec"),
        "clients_per_sec_per_tenant": summary.get("clients_per_sec_per_tenant"),
        "run_dir": str(run.dir),
        "config": {
            "fed_num_clients": fed.num_clients,
            "fed_clients_per_round": fed.clients_per_round,
            "fed_tenants": T,
            "fed_mt_k": ks,
            "fed_mt_alpha": alphas,
            "fed_mt_latency": lats,
            "fed_mt_cohort": overrides["fed_mt_cohort"],
        },
    }
    if args.slo:
        report["slo"] = {
            "states": {str(t): s for t, s in monitor.final_states().items()},
            "events": len(monitor.events),
            "health_jsonl": f"{run.dir}/health.jsonl",
            "verdicts": {str(t): monitor.verdict(t) for t in range(T)},
            "spec": spec.to_dict(),
        }
    return report


def cmd_check(args) -> int:
    report = _run_mt_check(args) if args.tenants >= 1 else _run_check(args)
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m deepreduce_tpu.fedsim")
    ap.add_argument("--platform", type=str, default="",
                    help="pin the JAX platform (e.g. 'cpu' for the virtual "
                         "8-device mesh)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_check = sub.add_parser(
        "check", help="cohort round + churn + resume smoke-check (make fedsim-check)"
    )
    p_check.add_argument("--rounds", type=int, default=6)
    p_check.add_argument("--num_clients", type=int, default=256)
    p_check.add_argument("--clients_per_round", type=int, default=32)
    p_check.add_argument("--num_workers", type=int, default=8)
    p_check.add_argument("--seed", type=int, default=0)
    p_check.add_argument("--track_dir", type=str, default="/tmp/drtpu_fedsim_check")
    p_check.add_argument(
        "--async", dest="use_async", action="store_true",
        help="asynchronous buffered mode: staleness-weighted ingest ticks, "
             "K-threshold buffered applies, mid-buffer bitwise resume "
             "(make fedasync-check)")
    p_check.add_argument(
        "--slo", action="store_true",
        help="run the SLO health monitor over the tick stream: writes "
             "RUN/health.jsonl, checkpoints the monitor state for the "
             "bitwise tail replay, and the check must END healthy "
             "(make slo-check)")
    p_check.add_argument(
        "--slo_spec", type=str, default="",
        help="SLOSpec JSON path for --slo; default: the embedded "
             "churn+chaos smoke spec")
    p_check.add_argument(
        "--population", action="store_true",
        help="heterogeneous-population smoke: skewed two-class spec with "
             "per-class latency rows through the async tick — churn, "
             "exact per-class participation histogram, mid-stream "
             "bitwise resume (make pop-check); implies --async")
    p_check.add_argument(
        "--tenants", type=int, default=0,
        help="multi-tenant smoke: T heterogeneous async populations "
             "through the one vmapped tick — join/leave without retrace, "
             "mid-fill multi-tenant bitwise resume, per-tenant telemetry "
             "rows (make fedmt-check)")
    args = ap.parse_args(argv)
    if getattr(args, "population", False):
        # the per-class latency rows (the tx-histogram path) only engage
        # on the async tick; the sync degeneracy is pinned by the tests
        args.use_async = True
    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=max(2, args.num_workers))
    return cmd_check(args)


if __name__ == "__main__":
    sys.exit(main())
