"""Population-scale federated simulation (see sim.py's module docstring).

Import surface:

- `FedConfig`, `cohort_updates`, `make_client_step` (round.py) — the round
  bodies `fedavg.FedAvg` delegates to.
- `TreeCodec` (codec_tree.py) — path-keyed per-leaf `TensorCodec` bank.
- `FedSim`, `FedSimState`, `synthetic_linear_problem` (sim.py) — the
  client-sharded population driver.
"""

from deepreduce_tpu.fedsim.codec_tree import TreeCodec, TreeSpec
from deepreduce_tpu.fedsim.round import (
    FedConfig,
    cohort_updates,
    make_async_client_step,
    make_client_step,
    parse_latency,
)
from deepreduce_tpu.fedsim.sim import (
    AsyncBuffer,
    FedSim,
    FedSimState,
    synthetic_linear_problem,
)

__all__ = [
    "AsyncBuffer",
    "FedConfig",
    "FedSim",
    "FedSimState",
    "TreeCodec",
    "TreeSpec",
    "cohort_updates",
    "make_async_client_step",
    "make_client_step",
    "parse_latency",
    "synthetic_linear_problem",
]
