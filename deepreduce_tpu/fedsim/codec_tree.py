"""Path-keyed pytree codec bank — one `TensorCodec` per (direction, leaf path).

`FedAvg` originally cached codecs by *flat leaf index* (`str(i)`), so two
pytrees with the same leaf shapes in swapped order would silently reuse each
other's codec names in telemetry, and two different-shape leaves landing on
the same index across calls would collide outright. `TreeCodec` keys the
cache by the treedef path (`jax.tree_util.keystr`), which is stable under
leaf reordering and self-describing in span/wire labels
(`c2s/['w']`, not `c2s/0`).

The encode/decode split (vs the fused `compress_tree`) exists for the
federated uplink: the fedsim round packs the encoded payloads into a flat
byte buffer (`comm.PayloadLayout`) so the resilience layer can checksum and
chaos-perturb the *wire image*, then decodes on the far side. PRNG keys are
still folded by flat leaf *position* (not path) so numerics are unchanged
from the pre-refactor `FedAvg._compress_tree`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.metrics import WireStats, combine
from deepreduce_tpu.wrappers import TensorCodec


@dataclasses.dataclass(frozen=True)
class TreeSpec:
    """Host-side skeleton of one flattened tree: enough to decode a payload
    list back into the original structure."""

    paths: Tuple[str, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    treedef: Any

    def unflatten(self, leaves: List[Any]) -> Any:
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class TreeCodec:
    """A directory of per-leaf `TensorCodec`s for one transfer direction."""

    def __init__(self, direction: str, cfg: DeepReduceConfig):
        self.direction = direction
        self.cfg = cfg
        self._codecs: Dict[str, TensorCodec] = {}

    def codec(self, path: str, shape) -> TensorCodec:
        shape = tuple(int(s) for s in shape)
        codec = self._codecs.get(path)
        if codec is None:
            codec = TensorCodec(shape, self.cfg, name=f"{self.direction}/{path}")
            self._codecs[path] = codec
        elif codec.shape != shape:
            raise ValueError(
                f"leaf path {path!r} previously had shape {codec.shape}, now "
                f"{shape} — the codec cache is keyed by treedef path, which "
                "must map to one static shape"
            )
        return codec

    def spec(self, tree: Any) -> TreeSpec:
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
        return TreeSpec(
            paths=tuple(jax.tree_util.keystr(p) for p, _ in leaves_with_path),
            shapes=tuple(tuple(leaf.shape) for _, leaf in leaves_with_path),
            treedef=treedef,
        )

    # ------------------------------------------------------------------ #

    def encode_tree(
        self, tree: Any, residual: Optional[Any], step, key
    ) -> Tuple[List[Any], List[jax.Array], TreeSpec]:
        """Compress `tree + residual` leaf-by-leaf. Returns the payload list
        (flatten order), the pre-compression leaves `leaf + residual` (what
        the sender must subtract the decode from to get its new residual),
        and the host-side `TreeSpec`."""
        spec = self.spec(tree)
        leaves = jax.tree_util.tree_leaves(tree)
        res_leaves = (
            jax.tree_util.tree_leaves(residual)
            if residual is not None
            else [None] * len(leaves)
        )
        payloads, comps = [], []
        for i, (path, leaf, r) in enumerate(zip(spec.paths, leaves, res_leaves)):
            codec = self.codec(path, leaf.shape)
            comp = leaf + r if r is not None else leaf
            k = jax.random.fold_in(key, i)
            payloads.append(codec.encode(comp, step=step, key=k))
            comps.append(comp)
        return payloads, comps, spec

    def decode_tree(self, payloads: List[Any], spec: TreeSpec, step) -> Any:
        out = [
            self.codec(path, shape).decode(p, step=step).reshape(shape)
            for path, shape, p in zip(spec.paths, spec.shapes, payloads)
        ]
        return spec.unflatten(out)

    def wire_tree(self, payloads: List[Any], spec: TreeSpec) -> WireStats:
        return combine(
            {
                path: self.codec(path, shape).wire_stats(p)
                for path, shape, p in zip(spec.paths, spec.shapes, payloads)
            }
        )

    # ------------------------------------------------------------------ #

    def compress_tree(
        self, tree: Any, residual: Optional[Any], step, key
    ) -> Tuple[Any, Optional[Any], WireStats]:
        """Fused encode+decode (the in-place simulation path `FedAvg` uses):
        returns (receiver's reconstruction, updated residual, wire bits)."""
        payloads, comps, spec = self.encode_tree(tree, residual, step, key)
        dec_leaves = [
            self.codec(path, shape).decode(p, step=step).reshape(shape)
            for path, shape, p in zip(spec.paths, spec.shapes, payloads)
        ]
        wire = self.wire_tree(payloads, spec)
        dec_tree = spec.unflatten(dec_leaves)
        new_residual = (
            spec.unflatten([c - d for c, d in zip(comps, dec_leaves)])
            if residual is not None
            else None
        )
        return dec_tree, new_residual, wire

    def payload_sds(self, tree_sds: Any, step=0) -> Tuple[List[Any], TreeSpec]:
        """Abstract payload structure (ShapeDtypeStructs) for a tree of that
        shape — what `comm.PayloadLayout` needs to build its static layout."""
        spec = self.spec(tree_sds)

        def _enc(leaves):
            key = jax.random.PRNGKey(0)
            payloads = []
            for i, (path, leaf) in enumerate(zip(spec.paths, leaves)):
                codec = self.codec(path, leaf.shape)
                payloads.append(
                    codec.encode(leaf, step=step, key=jax.random.fold_in(key, i))
                )
            return payloads

        leaves_sds = [
            jax.ShapeDtypeStruct(s.shape, s.dtype)
            for s in jax.tree_util.tree_leaves(tree_sds)
        ]
        return jax.eval_shape(_enc, leaves_sds), spec
